"""Enumerated framework errors (reference: exception/ShifuErrorCode.java)."""

from __future__ import annotations

import enum


class ErrorCode(enum.Enum):
    INVALID_MODEL_CONFIG = "invalid ModelConfig"
    INVALID_COLUMN_CONFIG = "invalid ColumnConfig"
    MODEL_CONFIG_NOT_FOUND = "ModelConfig.json not found; run `shifu new` first"
    COLUMN_CONFIG_NOT_FOUND = "ColumnConfig.json not found; run `shifu init` first"
    DATA_NOT_FOUND = "training data path not found"
    HEADER_NOT_FOUND = "header file not found"
    TARGET_NOT_FOUND = "target column not found in header"
    STATS_NOT_RUN = "column stats missing; run `shifu stats` first"
    NORM_NOT_RUN = "normalized data missing; run `shifu norm` first"
    MODEL_NOT_FOUND = "no trained model found; run `shifu train` first"
    EVAL_NOT_FOUND = "eval set not found in ModelConfig.evals"
    INVALID_ALGORITHM = "unsupported algorithm"
    INVALID_FILTER_EXPR = "invalid filter expression"
    GRID_CONFIG_INVALID = "invalid grid-search config"
    ILLEGAL_ARGUMENT = "illegal argument"


class ShifuError(Exception):
    def __init__(self, code: ErrorCode, detail: str = ""):
        self.code = code
        self.detail = detail
        msg = code.value if not detail else f"{code.value}: {detail}"
        super().__init__(msg)
