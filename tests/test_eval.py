"""Eval subsystem tests: metrics math (AUC, bucketing parity) and the
end-to-end eval processor (score -> confusion -> perf -> gain chart)."""

import json
import os

import numpy as np
import pytest

from shifu_tpu.eval.metrics import (
    area_under_curve,
    auc_from_sweep,
    confusion_sweep,
    evaluate_performance,
)


class TestConfusionSweep:
    def test_basic_counts(self):
        scores = np.array([0.9, 0.8, 0.3, 0.1])
        tags = np.array([1, 0, 1, 0])
        cs = confusion_sweep(scores, tags)
        np.testing.assert_array_equal(cs.tp, [1, 1, 2, 2])
        np.testing.assert_array_equal(cs.fp, [0, 1, 1, 2])
        assert cs.pos_total == 2 and cs.neg_total == 2

    def test_perfect_separation_auc(self):
        scores = np.array([0.9, 0.8, 0.2, 0.1])
        tags = np.array([1, 1, 0, 0])
        cs = confusion_sweep(scores, tags)
        assert auc_from_sweep(cs) == pytest.approx(1.0)

    def test_random_scores_auc_half(self):
        rng = np.random.default_rng(0)
        scores = rng.random(20_000)
        tags = (rng.random(20_000) < 0.3).astype(float)
        cs = confusion_sweep(scores, tags)
        assert auc_from_sweep(cs) == pytest.approx(0.5, abs=0.02)

    def test_weighted_auc_differs(self):
        scores = np.array([0.9, 0.8, 0.7, 0.1])
        tags = np.array([1, 0, 1, 0])
        w = np.array([1.0, 10.0, 1.0, 1.0])
        cs = confusion_sweep(scores, tags, w)
        assert auc_from_sweep(cs) != pytest.approx(auc_from_sweep(cs, weighted=True))

    def test_tied_scores_order_independent(self):
        """All-tied scores must give AUC 0.5 regardless of row order
        (tie blocks move through the sweep as a unit)."""
        scores = np.full(100, 0.5)
        tags = np.concatenate([np.ones(40), np.zeros(60)])
        for t in (tags, tags[::-1]):
            cs = confusion_sweep(scores, t)
            assert auc_from_sweep(cs) == pytest.approx(0.5, abs=1e-9)

    def test_multi_bucket_crossing_emits_all(self):
        """A dominant-weight record crossing several bucket boundaries at
        once must still emit every bucket row."""
        scores = np.array([0.9, 0.8, 0.7, 0.6])
        tags = np.array([1.0, 0, 0, 0])
        w = np.array([100.0, 1, 1, 1])
        perf = evaluate_performance(scores, tags, w, n_buckets=10)
        bins = [p["binNum"] for p in perf.weighted_pr]
        assert bins == list(range(11))  # 0 + all ten crossings

    def test_auc_known_value(self):
        # manual: ranks -> AUC = P(score_pos > score_neg)
        scores = np.array([0.9, 0.7, 0.6, 0.4, 0.2])
        tags = np.array([1, 0, 1, 0, 0])
        # pairs: (0.9 beats all 3 negs), (0.6 beats 0.4, 0.2) -> 5/6
        cs = confusion_sweep(scores, tags)
        assert auc_from_sweep(cs) == pytest.approx(5 / 6, abs=1e-6)


class TestPerformance:
    def test_bucket_lists_monotone(self):
        rng = np.random.default_rng(1)
        n = 5000
        tags = (rng.random(n) < 0.3).astype(float)
        scores = tags * 0.5 + rng.random(n) * 0.5
        perf = evaluate_performance(scores, tags, n_buckets=10)
        assert perf.area_under_roc > 0.7
        gains = perf.gains
        assert len(gains) >= 10
        # action rate and recall both increase down the gain table
        ar = [g["actionRate"] for g in gains]
        rc = [g["recall"] for g in gains]
        assert all(a2 >= a1 for a1, a2 in zip(ar, ar[1:]))
        assert all(r2 >= r1 for r1, r2 in zip(rc, rc[1:]))
        # first row parity: precision pinned to 1.0
        assert gains[0]["precision"] == 1.0

    def test_empty_input(self):
        perf = evaluate_performance(np.array([]), np.array([]))
        assert perf.area_under_roc == 0.0


class TestEvalProcessor:
    @pytest.fixture()
    def ready_root(self, tmp_path):
        from tests.helpers import make_model_set

        root = str(tmp_path / "ms")
        make_model_set(root, n_rows=500)
        from shifu_tpu.config.model_config import ModelConfig
        from shifu_tpu.processor.init import InitProcessor
        from shifu_tpu.processor.norm import NormProcessor
        from shifu_tpu.processor.stats import StatsProcessor
        from shifu_tpu.processor.train import TrainProcessor

        mc = ModelConfig.load(os.path.join(root, "ModelConfig.json"))
        mc.train.num_train_epochs = 30
        # point the default eval set at the training data
        mc.evals[0].data_set.data_path = mc.data_set.data_path
        mc.evals[0].data_set.header_path = mc.data_set.header_path
        mc.save(os.path.join(root, "ModelConfig.json"))
        assert InitProcessor(root).run() == 0
        assert StatsProcessor(root).run() == 0
        assert NormProcessor(root).run() == 0
        assert TrainProcessor(root).run() == 0
        return root

    def test_eval_run_full(self, ready_root):
        from shifu_tpu.processor.evaluate import EvalProcessor

        root = ready_root
        assert EvalProcessor(root, run_name="").run() == 0
        eval_dir = os.path.join(root, "evals", "Eval1")
        score_path = os.path.join(eval_dir, "EvalScore.csv")
        perf_path = os.path.join(eval_dir, "EvalPerformance.json")
        chart_path = os.path.join(eval_dir, "gainchart.html")
        assert os.path.isfile(score_path)
        assert os.path.isfile(perf_path)
        assert os.path.isfile(chart_path)
        assert os.path.isfile(os.path.join(eval_dir, "EvalConfusionMatrix.csv"))

        with open(perf_path) as fh:
            perf = json.load(fh)
        assert perf["areaUnderRoc"] > 0.9  # strongly separable synthetic data
        assert perf["gains"]

        import pandas as pd

        df = pd.read_csv(score_path, sep="|")
        assert {"tag", "weight", "mean", "model0"} <= set(df.columns)
        assert df["mean"].between(0, 1000).all()
        html = open(chart_path).read()
        assert "AUC" in html and "<svg" in html

    def test_eval_set_management(self, ready_root):
        from shifu_tpu.config.model_config import ModelConfig
        from shifu_tpu.processor.evaluate import EvalProcessor

        root = ready_root
        assert EvalProcessor(root, new_name="EvalX").run() == 0
        mc = ModelConfig.load(os.path.join(root, "ModelConfig.json"))
        assert mc.get_eval("EvalX") is not None
        assert EvalProcessor(root, delete_name="EvalX").run() == 0
        mc = ModelConfig.load(os.path.join(root, "ModelConfig.json"))
        assert mc.get_eval("EvalX") is None

    def test_eval_norm(self, ready_root):
        from shifu_tpu.processor.evaluate import EvalProcessor

        root = ready_root
        assert EvalProcessor(root, norm_name="").run() == 0
        out = os.path.join(root, "evals", "Eval1", "NormalizedData")
        assert os.path.isfile(os.path.join(out, "meta.json"))


def test_model_runner_batch_cache_survives_address_reuse(tmp_path):
    """ModelRunner's per-batch feature caches must invalidate by OBJECT
    identity held weakly, never by id(): in a streaming loop the freed
    previous chunk's address is routinely recycled for the next chunk,
    and an id()-keyed check silently scores the new rows with the OLD
    chunk's normalized features (a whole chunk of wrong scores,
    timing-dependent — caught live by the sharded eval chaos loop)."""
    import gc

    from shifu_tpu.data.reader import ColumnarData
    from shifu_tpu.eval.scorer import ModelRunner
    from shifu_tpu.models.nn import NNModelSpec, init_params

    cols = [f"c{i}" for i in range(3)]
    sizes = [3, 4, 1]
    specs = [{"name": c, "kind": "value", "outNames": [c],
              "mean": 0.0, "std": 1.0, "fill": 0.0, "zscore": True}
             for c in cols]
    path = str(tmp_path / "model0.nn")
    NNModelSpec(layer_sizes=sizes, activations=["tanh"],
                input_columns=cols, norm_specs=specs,
                params=init_params(sizes, seed=0)).save(path)
    runner = ModelRunner([path])

    def batch(vals):
        return ColumnarData(
            names=cols,
            raw={c: np.array([f"{v:.3f}" for v in vals], object)
                 for c in cols},
            n_rows=len(vals),
        )

    fresh = runner.score_raw(batch([2.0, -2.0])).mean.copy()
    # score another batch, drop it, then score the target batch — the
    # dead weakref must force a cache rebuild even if the allocator
    # hands the new batch the dead one's address
    d1 = batch([0.5, 0.25])
    runner.score_raw(d1)
    assert runner._cached_data_ref() is d1
    del d1
    gc.collect()
    assert runner._cached_data_ref() is None  # dead -> must invalidate
    again = runner.score_raw(batch([2.0, -2.0])).mean
    np.testing.assert_array_equal(again, fresh)


def test_eval_streaming_matches_in_memory(tmp_path):
    """Forced streaming eval writes the same score file as the in-memory
    path (chunks purify/tag/score independently)."""
    from tests.helpers import make_model_set

    root = str(tmp_path / "ms")
    make_model_set(root, n_rows=400)
    from shifu_tpu.config.model_config import ModelConfig
    from shifu_tpu.processor.evaluate import EvalProcessor
    from shifu_tpu.processor.init import InitProcessor
    from shifu_tpu.processor.norm import NormProcessor
    from shifu_tpu.processor.stats import StatsProcessor
    from shifu_tpu.processor.train import TrainProcessor
    from shifu_tpu.utils import environment

    assert InitProcessor(root).run() == 0
    assert StatsProcessor(root).run() == 0
    assert NormProcessor(root).run() == 0
    mc = ModelConfig.load(os.path.join(root, "ModelConfig.json"))
    mc.train.num_train_epochs = 20
    ev = mc.evals[0]
    ev.data_set.data_path = mc.data_set.data_path
    ev.data_set.header_path = mc.data_set.header_path
    ev.data_set.data_delimiter = "|"
    mc.save(os.path.join(root, "ModelConfig.json"))
    assert TrainProcessor(root).run() == 0

    assert EvalProcessor(root, score_name="Eval1").run() == 0
    import glob

    score_file = glob.glob(os.path.join(root, "**", "EvalScore*"),
                           recursive=True)[0]
    in_memory = open(score_file).read()

    environment.set_property("shifu.ingest.forceStreaming", "true")
    environment.set_property("shifu.ingest.chunkRows", "64")
    try:
        assert EvalProcessor(root, score_name="Eval1").run() == 0
    finally:
        environment.set_property("shifu.ingest.forceStreaming", "")
        environment.set_property("shifu.ingest.chunkRows",
                                 str(65536))
    streamed = open(score_file).read()
    assert streamed == in_memory


def test_perf_streamed_sweep_matches_in_memory(tmp_path):
    """Past the memory budget, the perf step accumulates exact
    per-distinct-score tallies; AUC/perf output must equal the in-memory
    sweep (the file carries 3 decimals, so the tally is exact)."""
    import json as _json

    from tests.helpers import make_model_set

    root = str(tmp_path / "ms")
    make_model_set(root, n_rows=400)
    from shifu_tpu.config.model_config import ModelConfig
    from shifu_tpu.processor.evaluate import EvalProcessor
    from shifu_tpu.processor.init import InitProcessor
    from shifu_tpu.processor.norm import NormProcessor
    from shifu_tpu.processor.stats import StatsProcessor
    from shifu_tpu.processor.train import TrainProcessor
    from shifu_tpu.utils import environment

    assert InitProcessor(root).run() == 0
    assert StatsProcessor(root).run() == 0
    assert NormProcessor(root).run() == 0
    mc = ModelConfig.load(os.path.join(root, "ModelConfig.json"))
    mc.train.num_train_epochs = 20
    ev = mc.evals[0]
    ev.data_set.data_path = mc.data_set.data_path
    ev.data_set.header_path = mc.data_set.header_path
    ev.data_set.data_delimiter = "|"
    mc.save(os.path.join(root, "ModelConfig.json"))
    assert TrainProcessor(root).run() == 0
    assert EvalProcessor(root, run_name="Eval1").run() == 0
    import glob

    perf_file = glob.glob(os.path.join(root, "**", "EvalPerformance.json"),
                          recursive=True)[0]
    with open(perf_file) as fh:
        in_memory = _json.load(fh)

    environment.set_property("shifu.ingest.memoryBudgetMB", "0")
    try:
        assert EvalProcessor(root, perf_name="Eval1").run() == 0
    finally:
        environment.set_property("shifu.ingest.memoryBudgetMB", "512")
    with open(perf_file) as fh:
        streamed = _json.load(fh)
    assert streamed["areaUnderRoc"] == in_memory["areaUnderRoc"]
    assert streamed["weightedAreaUnderRoc"] == in_memory["weightedAreaUnderRoc"]
    assert streamed["roc"] == in_memory["roc"]
    assert streamed["gains"] == in_memory["gains"]
