"""shifu_tpu.obs — unified observability: metrics, tracing, run ledger.

One process-global metrics registry + span tracer, reset at the start of
each lifecycle step (BasicProcessor.run) and snapshotted into that step's
run manifest. Library code records through the module-level accessors so a
reset (new step, bench scenario, test) transparently redirects recording:

    from shifu_tpu.obs import registry, span

    registry().counter("stats.rows_valid").inc(n)
    with span("stats.pass2", chunks=k):
        ...

Nested processor runs (combo invoking stats/norm/...) keep the outer step's
registry: only depth-0 begin_run() resets, every depth writes its own
manifest.
"""

from __future__ import annotations

from shifu_tpu.analysis.racetrack import tracked_lock
from shifu_tpu.obs import profile as _profile
from shifu_tpu.obs import reqtrace as _reqtrace
from shifu_tpu.obs.ledger import RunLedger, format_runs, list_runs
from shifu_tpu.obs.metrics import (
    MetricsRegistry,
    StageTimers,
    parse_prometheus,
)
from shifu_tpu.obs.profile import ProgramProfiler
from shifu_tpu.obs.tracing import Tracer

__all__ = [
    "MetricsRegistry",
    "ProgramProfiler",
    "RunLedger",
    "StageTimers",
    "Tracer",
    "begin_run",
    "end_run",
    "format_runs",
    "install_jax_probes",
    "list_runs",
    "parse_prometheus",
    "profiler",
    "registry",
    "reset",
    "span",
    "tracer",
]

_lock = tracked_lock("obs.scope")
_registry = MetricsRegistry()
_tracer = Tracer()
_run_depth = 0


def registry() -> MetricsRegistry:
    """The process-global registry (current step's scope)."""
    return _registry


def tracer() -> Tracer:
    """The process-global span tracer (current step's scope)."""
    return _tracer


def profiler() -> ProgramProfiler:
    """The process-global program profiler (current step's scope) —
    per-jit-program XLA cost accounting (obs/profile.py)."""
    return _profile.profiler()


def span(name: str, **attrs):
    """Open a span on the current global tracer (resolved at entry, so a
    registry/tracer reset between calls is transparent)."""
    return _tracer.span(name, **attrs)


def reset() -> None:
    """Fresh registry + tracer + profiler + request-trace scope (step
    boundaries, bench scenarios, tests). The profiler's program-cost
    cache survives — the compiled executables it mirrors do too."""
    global _registry, _tracer
    with _lock:
        _registry = MetricsRegistry()
        _tracer = Tracer()
        _profile.reset()
        _reqtrace.reset()


def begin_run() -> int:
    """Enter a step run; resets the registry/tracer at depth 0 only, so a
    composite processor's sub-steps accumulate into the outer scope.
    Returns the depth BEFORE entering (0 = outermost)."""
    global _run_depth
    with _lock:
        depth = _run_depth
        _run_depth += 1
    if depth == 0:
        reset()
    return depth


def end_run() -> None:
    global _run_depth
    with _lock:
        _run_depth = max(0, _run_depth - 1)


def install_jax_probes() -> bool:
    """Idempotently hook jax.monitoring compile events into the registry."""
    from shifu_tpu.obs.jaxprobe import install

    return install()
