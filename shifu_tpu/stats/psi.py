"""Population Stability Index per column, split by the PSI unit column.

Parity: the reference's PSI Pig job (PSI.pig, udf/PSICalculatorUDF.java,
driven by MapReducerStatsWorker.runPSI:594) — per-unit bin distributions per
column, PSI of each unit against the whole population, unitStats strings
written back into ColumnConfig.
"""

from __future__ import annotations

from typing import List

import numpy as np

from shifu_tpu.config import ColumnConfig
from shifu_tpu.data.reader import ColumnarData
from shifu_tpu.stats.binning import categorical_bin_index, numeric_bin_index
from shifu_tpu.stats.metrics import psi_metric


def compute_psi(
    data: ColumnarData, columns: List[ColumnConfig], psi_column: str
) -> None:
    """Fill column_stats.psi and unit_stats in place."""
    if psi_column not in data.raw:
        raise KeyError(f"psi column {psi_column} not in data")
    units = data.column(psi_column)
    unit_values = sorted({str(u) for u in units})
    unit_masks = [(units == u) for u in unit_values]

    for cc in columns:
        if cc.is_target() or cc.is_meta() or cc.is_weight():
            continue
        if cc.is_categorical():
            cats = cc.column_binning.bin_category
            if cats is None:
                continue
            idx = categorical_bin_index(
                data.column(cc.column_name), cats, data.missing_mask(cc.column_name)
            )
            n_slots = len(cats) + 1
        else:
            bounds = cc.column_binning.bin_boundary
            if not bounds:
                continue
            idx = numeric_bin_index(data.numeric(cc.column_name), bounds)
            n_slots = len(bounds) + 1
        overall = np.bincount(idx, minlength=n_slots).astype(np.float64)
        unit_psis = []
        unit_stats = []
        for u, m in zip(unit_values, unit_masks):
            dist = np.bincount(idx[m], minlength=n_slots).astype(np.float64)
            p = psi_metric(overall, dist)
            unit_psis.append(p)
            unit_stats.append(f"{u}:{p:.6f}")
        cc.column_stats.psi = float(np.mean(unit_psis)) if unit_psis else 0.0
        cc.column_stats.unit_stats = unit_stats
