"""Online PSI drift: per-column live-vs-training bin distributions.

Baseline: the training run's own bin distributions — `binCountPos +
binCountNeg` per ColumnConfig (length = bins + trailing missing slot, the
exact layout `stats` writes). Live side: every served micro-batch is
bin-coded against the same ColumnConfig bins and folded into a per-column
count accumulator; `psi_from_counts` (stats/psi.py) then gives each
column's PSI, so offline PSI and online drift share one definition.

Where the fold runs: inside the registry's already-fused scoring program.
The monitor contributes device constants (padded boundary tables, slot
offsets) and a traced body (`traced_fold`) the registry splices after the
forward pass — numeric columns searchsorted-bin on device from the raw
(pre-fill) values with an explicit missing mask, categorical/hybrid
columns ride the host bin codes the featurizer already computes for the
norm gather (shared `_bin_codes_for` cache: zero extra host parses). The
counts land in an f32 device window that stays resident across batches
(the PR-1/PR-8 windowed-fold idiom: no per-batch device->host sync) and
flushes into a host float64 fold every `WINDOW_FLUSH_ROWS` rows or on
demand — counts are exact at any stream length.

Degrade seam: when any column's PSI crosses `-Dshifu.loop.psiDegrade`
(default 0.2 — the classic "significant shift" PSI convention), the serve
health monitor flips to `degraded` (a routing de-prioritization, not an
ejection: scoring continues) and ONE `recommend-<seq>.json` manifest
lands in the run ledger naming the drifted columns — the machine-readable
retrain recommendation `shifu retrain`/`shifu promote` read back.

Metrics: loop.drift.rows, loop.drift.flushes, gauges
loop.drift.psi{column=}, loop.drift.max_psi, counter loop.drift.degraded.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from shifu_tpu.analysis.racetrack import tracked_lock
from shifu_tpu.config import ColumnConfig
from shifu_tpu.loop import psi_degrade_setting
from shifu_tpu.stats.psi import psi_from_counts
from shifu_tpu.utils.log import get_logger

log = get_logger(__name__)

# same f32-exactness bound as the ingest-side DeviceAccumulator: flush the
# device window to the f64 host fold before any slot count can reach 2^24
WINDOW_FLUSH_ROWS = 1 << 23


class _MonitoredColumn:
    __slots__ = ("name", "kind", "n_slots", "offset", "expected", "cc")

    def __init__(self, name: str, kind: str, n_slots: int, offset: int,
                 expected: np.ndarray, cc: ColumnConfig) -> None:
        self.name = name
        self.kind = kind          # "numeric" | "coded"
        self.n_slots = n_slots
        self.offset = offset
        self.expected = expected  # training bin counts, len == n_slots
        self.cc = cc


def monitorable_columns(column_configs: List[ColumnConfig]
                        ) -> List[ColumnConfig]:
    """Feature columns with both bins and a training distribution."""
    out = []
    for cc in column_configs:
        if cc.is_target() or cc.is_meta() or cc.is_weight():
            continue
        bn = cc.column_binning
        has_bins = (bn.bin_category is not None) or bool(bn.bin_boundary)
        if not has_bins or not bn.bin_count_pos or not bn.bin_count_neg:
            continue
        out.append(cc)
    return out


class DriftMonitor:
    """Per-column live bin-count fold + PSI verdicts for one model set."""

    def __init__(self, column_configs: List[ColumnConfig],
                 threshold: Optional[float] = None,
                 min_rows: Optional[int] = None) -> None:
        from shifu_tpu.loop import drift_min_rows_setting

        self.threshold = (psi_degrade_setting() if threshold is None
                          else float(threshold))
        # PSI over a handful of live rows is sampling noise, not a
        # shift: verdicts report `warming` (and never degrade) until the
        # fold has seen this many rows
        self.min_rows = (drift_min_rows_setting() if min_rows is None
                         else int(min_rows))
        self.cols: List[_MonitoredColumn] = []
        offset = 0
        for cc in monitorable_columns(column_configs):
            bn = cc.column_binning
            numeric = not (cc.is_categorical() or cc.is_hybrid())
            n_slots = (len(bn.bin_boundary) + 1 if numeric
                       else (len(bn.bin_boundary or []) if cc.is_hybrid()
                             else 0) + len(bn.bin_category or []) + 1)
            expected = (np.asarray(bn.bin_count_pos, dtype=np.float64)
                        + np.asarray(bn.bin_count_neg, dtype=np.float64))
            if expected.size != n_slots:
                # stats written under different binning than the config
                # now carries — refuse to compare apples to oranges
                log.warning("drift: column %s bin counts (%d) do not match "
                            "its bins (%d slots); not monitored",
                            cc.column_name, expected.size, n_slots)
                continue
            self.cols.append(_MonitoredColumn(
                cc.column_name, "numeric" if numeric else "coded",
                n_slots, offset, expected, cc))
            offset += n_slots
        self.total_slots = offset
        self.numeric_cols = [c for c in self.cols if c.kind == "numeric"]
        self.coded_cols = [c for c in self.cols if c.kind == "coded"]
        self._lock = tracked_lock("loop.drift")
        self._host = np.zeros(self.total_slots, dtype=np.float64)
        # f32 device windows keyed by (owner, device) — owner is the
        # folding replica's label, so each window has exactly ONE
        # worker thread folding into it even when replicas share a
        # device: the fleet shares ONE monitor, but each replica's
        # fused fold must read/write an array resident on ITS device —
        # the host f64 fold below is where the per-key windows merge.
        # _epochs[key] bumps every time a flush swaps that key's window
        # out: a fold whose BASE window was already merged must not be
        # re-adopted (its base would double-count), so note_window drops
        # it — one micro-batch's counts lost at a flush boundary is
        # statistical noise; double-counting the whole window is not.
        self._windows: Dict = {}
        self._epochs: Dict = {}
        self._window_rows = 0
        self._rows = 0
        self._degraded: List[str] = []
        # bumped by reset(): an in-flight _flush whose window was
        # swapped out before a promotion reset describes the OLD
        # version's traffic and must not merge into the clean slate
        self._gen = 0

    @property
    def enabled(self) -> bool:
        return self.total_slots > 0

    # ---- featurization (host half) ----
    def featurize(self, data, code_cache: Optional[dict] = None,
                  numeric_cache: Optional[dict] = None
                  ) -> Tuple[np.ndarray, np.ndarray]:
        """(raw numeric values [n, Cn] f32 with NaN=missing, bin codes
        [n, Cc] i32 for coded columns). Both halves share the registry
        featurizer's per-call caches (`_bin_codes_for` codes, the
        `numeric_cache` parse), so a column the norm plan already
        consumed is parsed exactly once per request; columns only the
        monitor watches fall back to ONE flattened pandas parse
        (per-column `data.numeric()` dispatch costs ~0.2 ms of fixed
        pandas overhead each, which on a hand-of-rows online batch
        dwarfs the fused program itself)."""
        from shifu_tpu.norm.normalizer import _bin_codes_for

        n = data.n_rows
        if self.numeric_cols:
            names = [c.name for c in self.numeric_cols]
            if numeric_cache is not None and all(
                    nm in numeric_cache for nm in names):
                vals = np.stack([numeric_cache[nm] for nm in names],
                                axis=1).astype(np.float32)
            else:
                vals = self._numeric_matrix(data)
        else:
            vals = np.zeros((n, 0), dtype=np.float32)
        if self.coded_cols:
            codes = np.stack(
                [_bin_codes_for(c.cc, data, code_cache)
                 for c in self.coded_cols],
                axis=1).astype(np.int32)
        else:
            codes = np.zeros((n, 0), dtype=np.int32)
        return vals, codes

    def _numeric_matrix(self, data) -> np.ndarray:
        """[n, Cn] f32, NaN = missing — the featurizer's exact parse
        (data.reader.flat_numeric_matrix, the ONE implementation both
        sides bin against) over all monitored numeric columns."""
        from shifu_tpu.data.reader import flat_numeric_matrix

        return flat_numeric_matrix(
            data, [c.name for c in self.numeric_cols]).astype(np.float32)

    # ---- traced half (spliced into the fused serve program) ----
    def device_consts(self) -> dict:
        """Static tensors the traced fold closes over."""
        import jax.numpy as jnp

        consts: dict = {
            "num_offsets": np.asarray(
                [c.offset for c in self.numeric_cols], np.int32),
            "cat_offsets": np.asarray(
                [c.offset for c in self.coded_cols], np.int32),
            "cat_clips": np.asarray(
                [c.n_slots - 1 for c in self.coded_cols], np.int32),
        }
        if self.numeric_cols:
            bmax = max(len(c.cc.column_binning.bin_boundary)
                       for c in self.numeric_cols)
            bounds = np.full((len(self.numeric_cols), bmax), np.inf,
                             dtype=np.float32)
            nbins = np.zeros(len(self.numeric_cols), dtype=np.int32)
            for k, c in enumerate(self.numeric_cols):
                b = np.asarray(c.cc.column_binning.bin_boundary,
                               dtype=np.float32)
                bounds[k, : b.size] = b
                nbins[k] = b.size
            consts["num_bounds"] = jnp.asarray(bounds)
            consts["num_nbins"] = jnp.asarray(nbins)
        return consts

    def traced_fold(self, consts, window, vals, codes, valid):
        """Traced: fold one padded batch into the window.

        vals  [n, Cn] f32 raw numeric, NaN = missing
        codes [n, Cc] i32 host bin codes (already include missing slots)
        valid [n]     f32 1.0 for real rows, 0.0 for bucket padding
        Returns window + this batch's per-slot counts. Numeric binning is
        numeric_bin_index's semantics traced: boundaries[i] <= v <
        boundaries[i+1], non-finite -> the trailing missing slot."""
        import jax.numpy as jnp

        slot_ids = []
        if self.numeric_cols:
            b = consts["num_bounds"]          # [Cn, Bmax], +inf padded
            nb = consts["num_nbins"]          # [Cn]
            # searchsorted per column via broadcast compare: the +inf
            # padding never counts, so sum(v >= bound) - 1 is the index
            ge = (vals[:, :, None] >= b[None, :, :]).sum(axis=2) - 1
            idx = jnp.clip(ge, 0, nb[None, :] - 1)
            idx = jnp.where(jnp.isfinite(vals), idx, nb[None, :])
            slot_ids.append(idx + consts["num_offsets"][None, :])
        if self.coded_cols:
            cc = jnp.clip(codes, 0, consts["cat_clips"][None, :])
            slot_ids.append(cc + consts["cat_offsets"][None, :])
        ids = jnp.concatenate(slot_ids, axis=1)          # [n, Cm]
        w = jnp.broadcast_to(valid[:, None], ids.shape)
        return window.at[ids.reshape(-1)].add(
            w.reshape(-1), mode="drop")

    # ---- window lifecycle ----
    def window(self, device=None, owner: Optional[str] = None):
        """(resident device window for (owner, device), generation
        token) — created on first use per key. Pass the token back to
        note_window: a fold that straddles a promotion reset() OR a
        concurrent flush (window read -> dispatch -> adopt) would
        otherwise reinstate counts the host fold already absorbed."""
        import jax

        key = (owner, device)
        with self._lock:
            win = self._windows.get(key)
            if win is None:
                win = jax.device_put(
                    np.zeros(self.total_slots, np.float32), device)
                self._windows[key] = win
            return win, (self._gen, self._epochs.get(key, 0))

    def note_window(self, new_window, rows: int,
                    gen=None, device=None,
                    owner: Optional[str] = None) -> None:
        """Adopt the post-fold window for (owner, device); flush ALL
        windows to the f64 host fold when the summed row budget is spent
        (ONE device->host sync per window per key). The sync itself
        happens OUTSIDE the lock (SH203): a health/metrics probe taking
        the lock must never queue behind a d2h transfer."""
        key = (owner, device)
        with self._lock:
            if gen is not None:
                want = (self._gen, self._epochs.get(key, 0))
                if (gen if isinstance(gen, tuple) else (gen, 0)) != want:
                    # reset() (a promotion — the fold counted the old
                    # version's traffic) or a concurrent _flush (the
                    # fold's BASE window is already in the host fold —
                    # adopting base+delta would double-count the base)
                    # landed between window() and here: drop the fold
                    return
            self._windows[key] = new_window
            self._window_rows += rows
            self._rows += rows
            need_flush = self._window_rows > WINDOW_FLUSH_ROWS
        if need_flush:
            self._flush()

    def reset(self) -> None:
        """Clean slate after a promotion acted on the drift: live counts,
        the device window, and the degraded-column memory all clear, so
        drift on the NEW version's traffic re-degrades and re-recommends
        instead of being swallowed by the already-seen set (the monitor
        is per-process; without this, the closed loop would close exactly
        once). The baseline stays — it is the training ColumnConfig."""
        with self._lock:
            self._host = np.zeros(self.total_slots, dtype=np.float64)
            self._windows = {}
            self._window_rows = 0
            self._rows = 0
            self._degraded = []
            self._gen += 1  # invalidate any flush already past its swap

    def fold_host(self, data, code_cache: Optional[dict] = None) -> None:
        """Host-side fold for non-fused registries (ModelRunner fallback).
        Values AND boundaries compare in float32 — the exact semantics of
        the traced device fold — so a column's live counts are identical
        whichever execution path a registry runs (pinned in
        test_loop.py); f64 boundaries would flip rows sitting within one
        f32 ulp of a bin edge."""
        if not self.enabled:
            return
        vals, codes = self.featurize(data, code_cache)
        counts = np.zeros(self.total_slots, dtype=np.float64)
        for k, c in enumerate(self.numeric_cols):
            from shifu_tpu.stats.binning import numeric_bin_index

            idx = numeric_bin_index(
                vals[:, k],
                np.asarray(c.cc.column_binning.bin_boundary, np.float32))
            np.add.at(counts, c.offset + idx, 1.0)
        for k, c in enumerate(self.coded_cols):
            idx = np.clip(codes[:, k], 0, c.n_slots - 1)
            np.add.at(counts, c.offset + idx, 1.0)
        with self._lock:
            self._host += counts
            self._rows += data.n_rows

    def _flush(self) -> None:
        """Swap-fetch-merge window flush: the device window is swapped
        for a fresh one UNDER the lock, the d2h sync runs OUTSIDE it
        (the lock is on the serve observer path — a blocked /metrics or
        health probe must never serialize behind a device transfer,
        SH203), and the fetched counts merge back under the lock.
        Concurrent flushes each own their swapped-out window, so counts
        are never lost or double-folded."""
        from shifu_tpu.obs import registry

        with self._lock:
            windows, rows = self._windows, self._window_rows
            if not windows or rows == 0:
                return
            # swap the whole window family out; fresh zeros lazily
            # re-create on each key's next window() call. Bumping each
            # key's epoch invalidates any fold in flight against the
            # swapped-out base (note_window drops it instead of
            # double-counting the base into the next flush).
            for key in windows:
                self._epochs[key] = self._epochs.get(key, 0) + 1
            self._windows = {}
            self._window_rows = 0
            gen = self._gen
        import jax

        counts = np.zeros(self.total_slots, dtype=np.float64)
        for win in windows.values():
            counts += np.asarray(jax.device_get(win), dtype=np.float64)
        with self._lock:
            if self._gen == gen:
                self._host += counts
            # else: reset() (a promotion) landed mid-flush — the
            # swapped windows counted the OLD version's traffic; merging
            # them would pollute the new version's fold, so drop them
        registry().counter("loop.drift.flushes").inc()

    # ---- verdicts ----
    def psi_by_column(self) -> Dict[str, float]:
        """Per-column PSI of the live fold vs the training distribution
        (forces a window flush — one d2h sync; call on a cadence, not per
        batch)."""
        self._flush()
        with self._lock:
            counts = self._host.copy()
        return {
            c.name: psi_from_counts(
                c.expected, counts[c.offset: c.offset + c.n_slots])
            for c in self.cols
        }

    def verdict(self) -> dict:
        """The drift summary manifests and /healthz embed; also exports
        loop.drift.* gauges."""
        from shifu_tpu.obs import registry

        psis = self.psi_by_column()
        with self._lock:
            rows = self._rows
        warming = rows < self.min_rows
        drifted = ([] if warming else
                   sorted(name for name, p in psis.items()
                          if p > self.threshold))
        reg = registry()
        for name, p in psis.items():
            reg.gauge("loop.drift.psi", column=name).set(p)
        max_psi = max(psis.values()) if psis else 0.0
        reg.gauge("loop.drift.max_psi").set(max_psi)
        reg.counter("loop.drift.rows").inc(0)  # materialize the key
        return {
            "rows": int(rows),
            "threshold": self.threshold,
            "minRows": self.min_rows,
            "maxPsi": max_psi,
            "psi": {k: round(v, 6) for k, v in sorted(psis.items())},
            "driftedColumns": drifted,
            "status": ("warming" if warming
                       else "drift" if drifted else "ok"),
        }

    def check_degrade(self, health=None, ledger_root: Optional[str] = None,
                      model_sha: str = "",
                      reporter: str = "") -> Optional[dict]:
        """Evaluate the degrade gate: on first breach flip /healthz to
        degraded and stamp ONE retrain recommendation into the ledger.
        Returns the verdict (None only when monitoring is disabled), so
        a cadenced caller pays exactly one window flush + PSI pass per
        check — never verdict() twice."""
        if not self.enabled:
            return None
        v = self.verdict()
        if v["status"] != "drift":
            return v
        from shifu_tpu.obs import registry

        with self._lock:
            new_cols = [c for c in v["driftedColumns"]
                        if c not in self._degraded]
            first = not self._degraded
            self._degraded.extend(new_cols)
        if not new_cols and not first:
            return v
        registry().counter("loop.drift.degraded").inc()
        reason = (f"psi drift > {self.threshold:g} on "
                  f"{','.join(v['driftedColumns'][:5])}")
        if health is not None:
            health.note_degraded(reason)
        if ledger_root and first:
            self._write_recommendation(ledger_root, v, model_sha, reporter)
        return v

    def _write_recommendation(self, root: str, verdict: dict,
                              model_sha: str, reporter: str = "") -> None:
        import sys
        import time

        from shifu_tpu import obs
        from shifu_tpu.obs.ledger import RunLedger

        try:
            ledger = RunLedger(root)
            seq = ledger.next_seq("recommend")
            path = ledger.write(
                "recommend", seq,
                status="ok", exit_status=0,
                started_at=time.time(), elapsed_seconds=0.0,
                argv=list(sys.argv), registry=obs.registry(),
                extra={"recommendation": {
                    "action": "retrain",
                    "reason": "psi-drift",
                    "modelSetSha": model_sha,
                    # which fleet process observed the drift — N serve
                    # processes share one ledger, so recommendations
                    # must be attributable (same id as its traffic
                    # chunks' writer and its lease)
                    "reporter": reporter,
                    "drift": verdict,
                }},
            )
            log.warning("drift degrade: retrain recommendation -> %s", path)
        except OSError as e:  # a broken ledger must not break serving
            log.warning("cannot write retrain recommendation: %s", e)
