"""Benchmark: NN training throughput vs a measured Encog-style CPU baseline.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The reference publishes no numbers (BASELINE.md), so the baseline is MEASURED
here: the same full-batch MLP train step (fwd + backprop + RPROP update,
double precision like Encog's FloatFlatNetwork path) implemented in numpy on
one core — what one reference Hadoop worker does per iteration — scaled by
the reference's nominal 100-worker cluster. vs_baseline > 1.0 means one TPU
chip out-trains the modeled 100-node Hadoop deployment.
"""

from __future__ import annotations

import json
import os
import time

# single-core baseline: pin BLAS threads BEFORE numpy loads
os.environ.setdefault("OPENBLAS_NUM_THREADS", "1")
os.environ.setdefault("OMP_NUM_THREADS", "1")
os.environ.setdefault("MKL_NUM_THREADS", "1")

import numpy as np

N_REFERENCE_WORKERS = 100  # north-star cluster size (BASELINE.md)


def numpy_worker_row_epochs_per_s(d: int = 30, h: int = 50, n: int = 20_000) -> float:
    """One Encog-worker-equivalent: full-batch fwd+backprop in float64."""
    rng = np.random.default_rng(0)
    x = rng.normal(size=(n, d))
    t = (rng.random(n) < 0.5).astype(np.float64)
    w1 = rng.normal(size=(d, h)) * 0.1
    b1 = np.zeros(h)
    w2 = rng.normal(size=(h, 1)) * 0.1
    b2 = np.zeros(1)

    def step():
        z1 = x @ w1 + b1
        a1 = np.tanh(z1)
        z2 = a1 @ w2 + b2
        p = 1.0 / (1.0 + np.exp(-z2[:, 0]))
        delta2 = ((t - p) * p * (1 - p))[:, None]
        g_w2 = a1.T @ delta2
        delta1 = (delta2 @ w2.T) * (1 - a1 * a1)
        g_w1 = x.T @ delta1
        return g_w1.sum() + g_w2.sum()

    step()  # warm caches
    reps, t0 = 3, time.perf_counter()
    for _ in range(reps):
        step()
    dt = (time.perf_counter() - t0) / reps
    return n / dt


def main() -> None:
    from shifu_tpu.train.nn_trainer import NNTrainConfig, train_nn

    rng = np.random.default_rng(0)
    n, d = 1_000_000, 30
    x = rng.normal(size=(n, d)).astype(np.float32)
    logits = x[:, 0] * 1.5 - x[:, 1] + 0.5 * x[:, 2] * x[:, 3]
    t = (logits + rng.normal(scale=0.5, size=n) > 0).astype(np.float32)
    w = np.ones(n, dtype=np.float32)

    epochs = 50
    cfg = NNTrainConfig(
        hidden_nodes=[50], activations=["tanh"], propagation="R",
        num_epochs=epochs, valid_set_rate=0.1, seed=1, mixed_precision=True,
    )

    # resident dataset: upload once, train from HBM (the reference's workers
    # likewise hold their shard in memory across iterations)
    import jax

    x_dev = jax.device_put(x)
    t_dev = jax.device_put(t)

    # warmup: compiles the program (epoch count is a traced arg, so the
    # 2-epoch warmup warms the full run)
    warm = NNTrainConfig(**{**cfg.__dict__, "num_epochs": 2})
    train_nn(x_dev, t_dev, w, warm)

    t0 = time.perf_counter()
    res = train_nn(x_dev, t_dev, w, cfg)
    dt = time.perf_counter() - t0

    throughput = n * res.iterations / dt
    baseline = numpy_worker_row_epochs_per_s(d=d) * N_REFERENCE_WORKERS
    print(json.dumps({
        "metric": "nn_train_row_epochs_per_s",
        "value": round(throughput, 1),
        "unit": "row-epochs/s",
        "vs_baseline": round(throughput / baseline, 4),
    }))


if __name__ == "__main__":
    main()
