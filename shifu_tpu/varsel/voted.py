"""Voted variable selection — the dvarsel genetic wrapper, vmapped.

Parity: core/dvarsel/VarSelMaster.java:39 + wrapper/CandidateGenerator.java —
a population of candidate variable subsets ("seeds") evolves over
generations: every seed is trained/validated, seeds sort by validation
error, the best INHERIT, the middle CROSS over, the worst MUTATE
(nextGeneration), and after the configured generations the best seed wins
the vote (voteBestSeed).

TPU-first shape: one generation = ONE vmapped program. Each candidate's
feature subset is a {0,1} mask over the feature axis applied to the first
dense layer (x @ (W1 * mask[:, None]) — masked features get zero forward
signal AND zero gradient), so P candidate models train simultaneously on
the shared row-sharded matrix instead of P Guagua worker fleets
(wrapper/ValidationConductor.java trains one Encog net per seed per
worker).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Tuple

import numpy as np

from shifu_tpu.utils.log import get_logger

log = get_logger(__name__)


@dataclass
class VotedConfig:
    """Knobs mirror CandidateGenerator's params (defaults follow
    Constants.java / dvarsel defaults where the reference defines them).

    The candidate-model architecture/hyperparams come from the MODEL's
    training config (ValidationConductor trains the CONFIGURED network per
    seed, core/dvarsel/wrapper/ValidationConductor.java — not a fixed
    surrogate); `from_model_config` wires them."""

    expect_var_count: int = 20  # EXPECT_VARIABLE_CNT (varSelect.wrapperNum)
    population_size: int = 30  # POPULATION_LIVE_SIZE
    generations: int = 5  # POPULATION_MULTIPLY_CNT
    cross_percent: int = 60  # HYBRID_PERCENT
    mutation_percent: int = 20  # MUTATION_PERCENT
    hidden_nodes: List[int] = field(default_factory=lambda: [10])
    activations: List[str] = field(default_factory=lambda: ["tanh"])
    epochs: int = 30
    learning_rate: float = 0.05
    valid_rate: float = 0.2
    seed: int = 0

    @classmethod
    def from_model_config(cls, mc, **overrides) -> "VotedConfig":
        """Candidates train the model's own architecture/params (reuse the
        NN trainer's wiring so NumHiddenNodes/ActivationFunc/LearningRate
        track the deliverable model exactly); epoch count is capped — the
        probe needs ranking fidelity, not a converged deliverable."""
        from shifu_tpu.train.nn_trainer import NNTrainConfig

        ncfg = NNTrainConfig.from_model_config(mc)
        return cls(
            hidden_nodes=list(ncfg.hidden_nodes) or [10],
            activations=list(ncfg.activations) or ["tanh"],
            epochs=min(int(ncfg.num_epochs), 50),
            learning_rate=float(ncfg.learning_rate),
            valid_rate=float(ncfg.valid_set_rate or 0.2),
            **overrides,
        )


_PROGRAMS: Dict[tuple, object] = {}


def _get_eval_program(d: int, hidden_nodes: tuple, activations: tuple,
                      epochs: int, lr: float):
    """Vmapped candidate evaluator over the CONFIGURED architecture:
    (flat0 [P, nw], masks [P, d], x, t, sig_tr, sig_va) -> valid_error
    [P]. The {0,1} mask multiplies the first dense layer, so masked
    features get zero forward signal AND zero gradient."""
    key = (d, tuple(hidden_nodes), tuple(activations), epochs, lr)
    prog = _PROGRAMS.get(key)
    if prog is not None:
        return prog
    import jax
    import jax.numpy as jnp

    from shifu_tpu.models.nn import activation_fn

    sizes = [d] + list(hidden_nodes) + [1]
    shapes = list(zip(sizes[:-1], sizes[1:]))
    n_total = sum(fi * fo + fo for fi, fo in shapes)
    acts = list(activations)

    def unflatten(flat):
        out, off = [], 0
        for (fi, fo) in shapes:
            w = flat[off:off + fi * fo].reshape(fi, fo)
            off += fi * fo
            b = flat[off:off + fo]
            off += fo
            out.append((w, b))
        return out

    def fwd(flat, mask, x):
        layers = unflatten(flat)
        h = x
        for i, (w, b) in enumerate(layers[:-1]):
            if i == 0:
                w = w * mask[:, None]
            h = activation_fn(acts[i % len(acts)] if acts else "tanh")(
                h @ w + b)
        w, b = layers[-1]
        if len(layers) == 1:
            w = w * mask[:, None]
        return 1.0 / (1.0 + jnp.exp(-(h @ w + b)[:, 0]))

    def loss(flat, mask, x, t, sig):
        p = fwd(flat, mask, x)
        return jnp.sum(sig * (t - p) ** 2)

    grad = jax.grad(loss)

    def train_one(flat0, mask, x, t, sig_tr, sig_va):
        def body(_, carry):
            flat, m, v, step = carry
            g = grad(flat, mask, x, t, sig_tr)
            # Adam (fixed betas; the candidate model is a probe, not a
            # deliverable — ValidationConductor trains a quick net per
            # seed too; the ARCHITECTURE is what must match the model)
            m2 = 0.9 * m + 0.1 * g
            v2 = 0.999 * v + 0.001 * g * g
            mh = m2 / (1.0 - 0.9 ** (step + 1.0))
            vh = v2 / (1.0 - 0.999 ** (step + 1.0))
            flat2 = flat - lr * mh / (jnp.sqrt(vh) + 1e-8)
            return flat2, m2, v2, step + 1.0

        carry = (flat0, jnp.zeros_like(flat0), jnp.zeros_like(flat0), 0.0)
        flat, _, _, _ = jax.lax.fori_loop(
            0, epochs, lambda i, c: body(i, c), carry)
        p = fwd(flat, mask, x)
        sq = (t - p) ** 2
        return jnp.sum(sig_va * sq) / jnp.maximum(jnp.sum(sig_va), 1.0)

    from shifu_tpu.obs import profile

    prog = profile.wrap(
        "varsel.vmap_train",
        jax.jit(jax.vmap(train_one, in_axes=(0, 0, None, None, None, None))),
        sync=True)
    _PROGRAMS[key] = (prog, n_total)
    return _PROGRAMS[key]


def _masks_from_seeds(seeds: List[List[int]], d: int) -> np.ndarray:
    masks = np.zeros((len(seeds), d), np.float32)
    for i, s in enumerate(seeds):
        masks[i, list(s)] = 1.0
    return masks


def _next_generation(seeds: List[List[int]], errors: np.ndarray,
                     cfg: VotedConfig, rng, d: int) -> List[List[int]]:
    """CandidateGenerator.nextGeneration: sort by error; best inherit,
    middle crossover (parents from the best pool), worst replaced by
    mutants."""
    order = np.argsort(errors)
    seeds = [seeds[i] for i in order]
    p = len(seeds)
    n_best = max(1, (100 - cfg.cross_percent - cfg.mutation_percent) * p // 100)
    n_cross = cfg.cross_percent * p // 100
    k = cfg.expect_var_count
    out = [list(s) for s in seeds[:n_best]]
    while len(out) < n_best + n_cross:
        a, b = rng.choice(n_best, size=2, replace=True)
        pool = sorted(set(seeds[a]) | set(seeds[b]))
        out.append(sorted(rng.choice(pool, size=min(k, len(pool)),
                                     replace=False).tolist()))
    while len(out) < p:
        out.append(sorted(rng.choice(d, size=min(k, d),
                                     replace=False).tolist()))
    return out


def voted_selection(
    feats: np.ndarray,
    tags: np.ndarray,
    weights: np.ndarray,
    cfg: VotedConfig,
) -> Tuple[List[int], np.ndarray]:
    """Run the GA; returns (best seed column indices, per-column vote
    frequency over the final population — diagnostic like the reference's
    worker vote tallies)."""
    import jax.numpy as jnp

    n, d = feats.shape
    rng = np.random.default_rng(cfg.seed)
    k = min(cfg.expect_var_count, d)
    seeds = [
        sorted(rng.choice(d, size=k, replace=False).tolist())
        for _ in range(cfg.population_size)
    ]
    valid = rng.random(n) < cfg.valid_rate
    sig_tr = (np.where(valid, 0.0, weights)).astype(np.float32)
    sig_va = (np.where(valid, weights, 0.0)).astype(np.float32)

    (prog, n_total) = _get_eval_program(
        d, tuple(cfg.hidden_nodes), tuple(cfg.activations), cfg.epochs,
        cfg.learning_rate)
    x = jnp.asarray(feats.astype(np.float32))
    t = jnp.asarray(tags.astype(np.float32))
    sig_tr_j = jnp.asarray(sig_tr)
    sig_va_j = jnp.asarray(sig_va)

    best_seed: List[int] = seeds[0]
    best_err = float("inf")
    errors = np.zeros(len(seeds))
    for gen in range(cfg.generations):
        flats = rng.normal(0, 0.1, size=(len(seeds), n_total)).astype(np.float32)
        masks = _masks_from_seeds(seeds, d)
        errors = np.asarray(prog(jnp.asarray(flats), jnp.asarray(masks),
                                 x, t, sig_tr_j, sig_va_j))
        gi = int(np.argmin(errors))
        if float(errors[gi]) < best_err:
            best_err = float(errors[gi])
            best_seed = list(seeds[gi])
        log.info("voted varsel generation %d/%d: best err %.6f "
                 "(global best %.6f)", gen + 1, cfg.generations,
                 float(errors[gi]), best_err)
        if gen + 1 < cfg.generations:
            seeds = _next_generation(seeds, errors, cfg, rng, d)

    votes = _masks_from_seeds(seeds, d).sum(axis=0) / max(len(seeds), 1)
    return sorted(best_seed), votes
