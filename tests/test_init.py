"""`shifu new` / `shifu init` behavior tests."""

import json
import os

from tests.helpers import make_model_set

from shifu_tpu.config import ColumnFlag, ColumnType, load_column_config_list
from shifu_tpu.processor.create import run_new
from shifu_tpu.processor.init import InitProcessor


def test_new_scaffolds_model_set(tmp_path):
    rc = run_new("MyModel", "GBT", root=str(tmp_path))
    assert rc == 0
    root = tmp_path / "MyModel"
    mc = json.loads((root / "ModelConfig.json").read_text())
    assert mc["basic"]["name"] == "MyModel"
    assert mc["train"]["algorithm"] == "GBT"
    assert mc["train"]["params"]["TreeNum"] == 100
    assert (root / "columns" / "meta.column.names").exists()
    # creating again fails gracefully
    assert run_new("MyModel", "GBT", root=str(tmp_path)) == 1


def test_init_builds_column_config(tmp_path):
    root = make_model_set(str(tmp_path / "ms"))
    proc = InitProcessor(root)
    assert proc.run() == 0
    cols = load_column_config_list(os.path.join(root, "ColumnConfig.json"))
    by_name = {c.column_name: c for c in cols}
    assert by_name["diagnosis"].column_flag == ColumnFlag.TARGET
    assert by_name["num_0"].column_type == ColumnType.N
    assert by_name["cat_0"].column_type == ColumnType.C  # auto-typed
    assert by_name["cat_0"].column_stats.distinct_count == 4
    assert all(c.column_num == i for i, c in enumerate(cols))
