"""Pallas histogram kernel vs the scatter reference (interpret mode on
CPU; on TPU the same kernel compiles via Mosaic — see ops/hist_pallas.py
for the measured comparison against the XLA lowering)."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from shifu_tpu.ops.hist_pallas import _chunk_runs, make_pallas_hist_fn
from shifu_tpu.train.tree_trainer import (  # noqa: E402
    _device_layout,
    _make_hist_fn,
    make_layout,
)


def _ref_hist(L, lay, codes, y, w, node, active, n_classes=0):
    la = _device_layout(lay, np.ones(len(lay.slots), bool))
    fn = jax.jit(_make_hist_fn(L, lay, allow_matmul=False,
                               n_classes=n_classes))
    return np.asarray(fn(jnp.asarray(codes), jnp.asarray(y),
                         jnp.asarray(w), jnp.asarray(node),
                         jnp.asarray(active), la.off, la.clip, la.seg_t,
                         la.pos_t))


def _pallas_hist(L, lay, codes, y, w, node, active, n_classes=0):
    fn = jax.jit(make_pallas_hist_fn(L, lay, n_classes=n_classes,
                                     interpret=True))
    return np.asarray(fn(jnp.asarray(codes), jnp.asarray(y),
                         jnp.asarray(w), jnp.asarray(node),
                         jnp.asarray(active)))


def _mixed_case(n=1500, seed=0):
    rng = np.random.default_rng(seed)
    # narrow numerics + a couple of categoricals + one wide categorical
    # that must split across T-chunks
    slots = [9] * 6 + [33, 17] + [1500]
    is_cat = [False] * 6 + [True] * 3
    codes = np.stack(
        [rng.integers(0, s, size=n) for s in slots], 1).astype(np.int32)
    y = rng.random(n).astype(np.float32)
    w = rng.integers(1, 4, size=n).astype(np.float32)
    return slots, is_cat, codes, y, w, rng


def test_pallas_matches_scatter_regression():
    slots, is_cat, codes, y, w, rng = _mixed_case()
    lay = make_layout(slots, is_cat)
    L = 8
    node = rng.integers(0, L, size=len(y)).astype(np.int32)
    active = rng.random(len(y)) < 0.9
    h_ref = _ref_hist(L, lay, codes, y, w, node, active)
    h_pl = _pallas_hist(L, lay, codes, y, w, node, active)
    # counts: integer weights sum exactly in f32 either way
    np.testing.assert_array_equal(h_ref[0], h_pl[0])
    # sums/sqsums: equal up to float summation order
    np.testing.assert_allclose(h_ref, h_pl, rtol=1e-5, atol=1e-3)


def test_pallas_matches_scatter_multiclass():
    slots, is_cat, codes, _y, w, rng = _mixed_case(seed=3)
    lay = make_layout(slots, is_cat)
    K, L = 4, 4
    cls = rng.integers(0, K, size=len(w)).astype(np.float32)
    node = rng.integers(0, L, size=len(w)).astype(np.int32)
    active = np.ones(len(w), bool)
    h_ref = _ref_hist(L, lay, codes, cls, w, node, active, n_classes=K)
    h_pl = _pallas_hist(L, lay, codes, cls, w, node, active, n_classes=K)
    np.testing.assert_array_equal(h_ref, h_pl)  # pure counts: exact


def test_chunk_runs_cover_layout():
    slots, is_cat, *_ = _mixed_case()
    lay = make_layout(slots, is_cat)
    chunks = _chunk_runs(lay)
    cols = 0
    for ch in chunks:
        assert ch["w"] == sum(
            (r[2] - r[1]) * r[3] if r[0] == "vec" else r[3] - r[2]
            for r in ch["runs"])
        cols += ch["w"]
    assert cols == lay.T
    # the wide categorical must have been split
    assert any(r[0] == "piece" for ch in chunks for r in ch["runs"])


def test_shaping_knobs_and_profiler_annotation():
    """-Dshifu.pallas.blk/.wmax override the VMEM shaping (the kernel-
    tuning sweep seam), the overridden kernel still matches the scatter
    reference exactly, and the chosen shaping lands in the profiler
    snapshot so every manifest records what produced its numbers."""
    from shifu_tpu import obs
    from shifu_tpu.ops.hist_pallas import blk_setting, wmax_setting
    from shifu_tpu.utils import environment

    slots, is_cat, codes, y, w, rng = _mixed_case(n=700)
    lay = make_layout(slots, is_cat)
    L = 4
    node = rng.integers(0, L, size=len(y)).astype(np.int32)
    active = rng.random(len(y)) < 0.9
    h_ref = _ref_hist(L, lay, codes, y, w, node, active)

    environment.set_property("shifu.pallas.blk", "128")
    environment.set_property("shifu.pallas.wmax", "256")
    obs.reset()
    try:
        assert blk_setting() == 128 and wmax_setting() == 256
        # the narrower wmax splits the flat T axis into more chunks
        assert len(_chunk_runs(lay)) > len(_chunk_runs(lay, target=1024))
        h_pl = _pallas_hist(L, lay, codes, y, w, node, active)
        np.testing.assert_array_equal(h_ref[0], h_pl[0])
        np.testing.assert_allclose(h_ref, h_pl, rtol=2e-5, atol=1e-4)
        ann = obs.profiler().snapshot()["annotations"]["ops.hist_pallas"]
        assert ann["blk"] == 128 and ann["wMax"] == 256
        assert ann["chunks"] == len(_chunk_runs(lay))
    finally:
        environment.set_property("shifu.pallas.blk", "")
        environment.set_property("shifu.pallas.wmax", "")
    assert blk_setting() == 512 and wmax_setting() == 1024


def test_bench_baseline_guards(tmp_path, monkeypatch):
    """bench.py refuses to silently clobber the calibrated pinned baseline
    and rejects config drift (review findings, round 5)."""
    import json
    import sys

    import bench

    fake = tmp_path / "BASELINE_MEASURED.json"
    monkeypatch.setattr(bench, "BASELINE_FILE", str(fake))
    # calibrated file: remeasure refuses without --force-remeasure
    json.dump({"calibrated": True, "configs": {}}, open(fake, "w"))
    monkeypatch.setattr(sys, "argv", ["bench.py", "--remeasure-baseline"])
    with pytest.raises(SystemExit, match="calibrated"):
        bench.load_or_measure_baseline(remeasure=True)
    # config drift: plain load errors with guidance
    with pytest.raises(SystemExit, match="different bench configs"):
        bench.load_or_measure_baseline()
    # missing file: clear instruction
    fake.unlink()
    with pytest.raises(SystemExit, match="must be checked in"):
        bench.load_or_measure_baseline()
