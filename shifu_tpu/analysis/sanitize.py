"""Runtime sanitizer harness: ``-Dshifu.sanitize=transfer,nan,recompile,race``.

The static pass (engine.py) catches what the AST can see; this harness
catches what only the runtime can — the ASan/TSan analog for a jit
pipeline. Four opt-in modes, combined freely:

  transfer   arms ``jax.transfer_guard("disallow")`` around *declared
             traced stages* (the ``transfer_free(...)`` seams in
             nn_trainer / streaming / data.pipeline). Explicit
             ``jax.device_put``/``device_get`` stay legal; any IMPLICIT
             host↔device transfer inside a seam raises, the trip is
             recorded, and the step fails like a sanitizer trap. The
             guard is scoped to seams, not whole steps, because host→
             device staging (chunk feeds, scalar operand creation) is
             legitimate *between* traced stages.
  nan        arms ``jax.debug_nans`` for the step (the checkify-style
             trap): the first NaN/Inf produced under jit raises
             FloatingPointError at the producing primitive.
  recompile  a watchdog on the obs/jaxprobe compile counters: each armed
             stage gets a compile budget (``shifu.sanitize.recompileBudget``,
             default 64); a breach is recorded and logged as a ledger
             warning — recompile storms are a perf bug, not a
             correctness trap, so the step still completes.
  race       lock instrumentation (analysis/racetrack.py): every
             ``tracked_lock(...)`` site constructed while armed records
             per-thread acquisition stacks; lock-order inversions and
             ``@guarded_by`` violations make the verdict unclean,
             long holds past ``shifu.sanitize.race.holdMs`` are
             reported (perf hazard, not gated). Arming is read at lock
             CONSTRUCTION time, so set ``-Dshifu.sanitize=race`` before
             building the serve/loop objects to be watched.

Verdicts: ``Sanitizer.verdict()`` returns a ``shifu.sanitize/1`` dict —
BasicProcessor.run() embeds it in the run-ledger manifest (success AND
failure), bench.py embeds it per scenario. Trip/breach counts also land
in the metrics registry (``sanitizer.*``), so `shifu runs` output and
Prometheus exports see them too.
"""

from __future__ import annotations

import contextlib
from typing import Iterable, List, Optional

from shifu_tpu.analysis.racetrack import tracked_lock
from shifu_tpu.utils import environment
from shifu_tpu.utils.log import get_logger

log = get_logger(__name__)

SCHEMA = "shifu.sanitize/1"
MODES = ("transfer", "nan", "recompile", "race")
DEFAULT_RECOMPILE_BUDGET = 64

_lock = tracked_lock("analysis.sanitize")
_current: Optional["Sanitizer"] = None


def modes_from_environment() -> List[str]:
    """Parse -Dshifu.sanitize=transfer,nan,recompile (also accepts
    'all'); unknown mode names raise so a typo cannot silently disarm
    the run."""
    raw = (environment.get_property("shifu.sanitize", "") or "").strip()
    if not raw:
        return []
    if raw.lower() == "all":
        return list(MODES)
    modes = [m.strip().lower() for m in raw.split(",") if m.strip()]
    unknown = [m for m in modes if m not in MODES]
    if unknown:
        raise ValueError(
            f"shifu.sanitize: unknown mode(s) {', '.join(unknown)} "
            f"(known: {', '.join(MODES)})")
    return modes


def recompile_budget() -> int:
    return environment.get_int("shifu.sanitize.recompileBudget",
                               DEFAULT_RECOMPILE_BUDGET)


def _is_transfer_error(e: BaseException) -> bool:
    return "transfer" in str(e).lower() and "isallowed" in str(e)


class Sanitizer:
    """One armed sanitizer scope (a lifecycle step or a bench scenario)."""

    def __init__(self, modes: Iterable[str],
                 budget: Optional[int] = None) -> None:
        self.modes = frozenset(modes)
        unknown = self.modes - set(MODES)
        if unknown:
            raise ValueError(f"unknown sanitizer mode(s): {sorted(unknown)}")
        self.budget = recompile_budget() if budget is None else budget
        self.transfer_trips = 0
        self.nan_trips = 0
        self.recompile_breaches = 0
        self.recompile_seconds = 0.0  # wall-clock of breached stages' compiles
        self.stages_armed = 0
        self.events: List[dict] = []
        # race-mode scope: the verdict reports the tracker's DELTA from
        # this sanitizer's construction (the tracker itself is
        # process-global, like the fault-injection counters)
        from shifu_tpu.analysis import racetrack

        self._race_mark = racetrack.tracker().mark()

    @property
    def active(self) -> bool:
        return bool(self.modes)

    # ---- recording (also mirrored into the metrics registry so ledger
    # tables/Prometheus see sanitizer activity without parsing verdicts)
    def _record(self, kind: str, stage: str, detail: str) -> None:
        self.events.append({"kind": kind, "stage": stage,
                            "detail": detail})
        from shifu_tpu.obs import registry

        registry().counter(f"sanitizer.{kind}").inc()

    def record_transfer_trip(self, stage: str, detail: str) -> None:
        self.transfer_trips += 1
        self._record("transfer.trips", stage, detail)
        log.warning("sanitizer[transfer] trip in %s: %s", stage,
                    detail[:200])

    def record_nan_trip(self, stage: str, detail: str) -> None:
        self.nan_trips += 1
        self._record("nan.trips", stage, detail)
        log.warning("sanitizer[nan] trap in %s: %s", stage, detail[:200])

    def record_recompile_breach(self, stage: str, compiles: float,
                                seconds: float = 0.0) -> None:
        self.recompile_breaches += 1
        self.recompile_seconds += seconds
        self._record("recompile.breaches", stage,
                     f"{compiles:.0f} compiles ({seconds:.2f}s wall-clock)"
                     f" > budget {self.budget}")
        log.warning(
            "sanitizer[recompile] budget breach in %s: %.0f compiles "
            "costing %.2fs wall-clock > budget %d "
            "(shifu.sanitize.recompileBudget)", stage, compiles, seconds,
            self.budget)

    # ---- arming
    @contextlib.contextmanager
    def armed(self, stage: str):
        """Arm the step-scoped modes around `stage`: debug_nans for the
        whole region, the recompile watchdog over its compile-counter
        delta. Transfer guarding happens at the finer transfer_free()
        seams inside. Exceptions propagate (sanitizer-trap semantics) —
        trips are recorded first, and the caller's ledger write still
        sees the verdict because it runs in its own finally."""
        if not self.active:
            yield
            return
        self.stages_armed += 1
        compiles0 = self._compile_count()
        seconds0 = self._compile_seconds()
        nan_cm = contextlib.nullcontext()
        if "nan" in self.modes:
            import jax

            nan_cm = jax.debug_nans(True)
        try:
            with nan_cm:
                yield
        except FloatingPointError as e:
            if "nan" in self.modes:
                self.record_nan_trip(stage, f"{type(e).__name__}: {e}")
            raise
        finally:
            if "recompile" in self.modes:
                delta = self._compile_count() - compiles0
                if delta > self.budget:
                    # the jaxprobe duration events make the breach
                    # actionable: N compiles AND the wall-clock they cost
                    self.record_recompile_breach(
                        stage, delta,
                        self._compile_seconds() - seconds0)

    @contextlib.contextmanager
    def transfer_free(self, stage: str):
        """Declare a region transfer-free. Under the `transfer` mode any
        implicit host↔device transfer inside raises (explicit
        device_put/device_get remain legal); the trip is recorded and
        the error propagates."""
        if "transfer" not in self.modes:
            yield
            return
        import jax

        try:
            with jax.transfer_guard("disallow"):
                yield
        except Exception as e:
            if _is_transfer_error(e):
                self.record_transfer_trip(stage, str(e))
            raise

    # ---- verdict
    def verdict(self) -> dict:
        from shifu_tpu.analysis import racetrack

        race_armed = "race" in self.modes
        race = {"armed": race_armed}
        race_dirty = 0
        if race_armed:
            race.update(racetrack.tracker().verdict(self._race_mark))
            # inversions + guard violations are correctness findings;
            # long holds are a perf hazard — reported, never gating
            # `clean` (the recompile-watchdog contract)
            race_dirty = race["inversions"] + race["guardViolations"]
        return {
            "schema": SCHEMA,
            "modes": sorted(self.modes),
            "stagesArmed": self.stages_armed,
            "transfer": {
                "armed": "transfer" in self.modes,
                "trips": self.transfer_trips,
            },
            "nan": {
                "armed": "nan" in self.modes,
                "trips": self.nan_trips,
            },
            "recompile": {
                "armed": "recompile" in self.modes,
                "budgetPerStage": self.budget,
                "breaches": self.recompile_breaches,
                "breachedCompileSeconds": round(self.recompile_seconds, 3),
            },
            "race": race,
            "events": self.events,
            "clean": not (self.transfer_trips or self.nan_trips
                          or self.recompile_breaches or race_dirty),
        }

    @staticmethod
    def _compile_count() -> float:
        from shifu_tpu import obs

        obs.install_jax_probes()
        return obs.registry().counter("jax.compiles").value

    @staticmethod
    def _compile_seconds() -> float:
        from shifu_tpu import obs

        obs.install_jax_probes()
        return obs.registry().timer("jax.compile").seconds


def from_environment() -> Sanitizer:
    return Sanitizer(modes_from_environment())


def current() -> Optional[Sanitizer]:
    return _current


@contextlib.contextmanager
def activate(san: Sanitizer):
    """Make `san` the process-current sanitizer so library seams
    (transfer_free below) find it without plumbing. Nested activation
    restores the previous one on exit."""
    global _current
    with _lock:
        prev, _current = _current, san
    try:
        yield san
    finally:
        with _lock:
            _current = prev


@contextlib.contextmanager
def transfer_free(stage: str):
    """Library-side seam: no-op unless a sanitizer with the `transfer`
    mode is active. Cheap enough for per-dispatch call sites (one global
    read when disarmed)."""
    san = _current
    if san is None or "transfer" not in san.modes:
        yield
        return
    with san.transfer_free(stage):
        yield
