"""`shifu retrain` — warm-start incremental training that closes the loop.

The reference Shifu retrains by re-running the whole one-shot pipeline;
this step turns serving traffic (or any new data drop) into an
INCREMENTAL run:

  1. **Source** — the serve-side traffic log (`loop/traffic.py`, rotating
     `|`-delimited chunk files under `.shifu/runs/traffic/`) or an
     explicit `--data` path. The log is read back through the ordinary
     `chunk_source` factory, so the retrain norm pass rides the identical
     ShardPlan/prefetch/checkpoint machinery as any training file.
  2. **Norm** — a full streaming norm pass over the new data into
     `tmp/retrain/` (NormalizedData + CleanedData), leaving the original
     training artifacts untouched. Resumable mid-stream
     (`retrain-norm-stream` checkpoint family; `shifu retrain --resume`).
  3. **Warm-start train** — NN/LR/WDL members initialize from the
     previous model's weights (the `isContinuous` seam); GBT appends
     `-Dshifu.loop.appendTrees` trees on the new chunks only (TreeNum is
     lifted to parent trees + append, so only the new trees train); RF
     has no warm-start and trains fresh on the new data. The result
     lands in a CANDIDATE dir (`models.candidate/` by default) — live
     `models/` is only replaced by `shifu promote`'s gated swap.
  4. **Provenance** — the retrain manifest records the full chain:
     parent model-set sha (+ per-model file shas), the data source and
     the exact traffic chunk files consumed, sectioned config shas
     (data / train / loop), and the candidate model-set sha. An
     incremental run is auditable from `.shifu/runs/` alone.

Chaos parity: the streamed trainer's epoch checkpoint carries a `loop`
identity section naming the warm-start parent, so `--resume` after a
mid-stream kill is bit-identical to an uninterrupted retrain — and a
checkpoint from a retrain against a DIFFERENT parent is rejected with
the diverged section named.
"""

from __future__ import annotations

import copy
import hashlib
import os
from typing import List, Optional

from shifu_tpu.config.model_config import Algorithm
from shifu_tpu.fs.listing import sorted_glob
from shifu_tpu.fs.pathfinder import PathFinder
from shifu_tpu.processor.basic import BasicProcessor
from shifu_tpu.processor.norm import NormProcessor
from shifu_tpu.processor.train import TrainProcessor
from shifu_tpu.utils.errors import ErrorCode, ShifuError
from shifu_tpu.utils.log import get_logger

log = get_logger(__name__)

DEFAULT_CANDIDATE_DIR = "models.candidate"


def _file_sha(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as fh:
        h.update(fh.read())
    return h.hexdigest()[:16]


class _RetrainPaths(PathFinder):
    """The retrain artifact layout: per-step tmp state under
    `tmp/retrain/` (so the original NormalizedData/CleanedData and train
    checkpoints survive untouched) and models written to the candidate
    dir instead of the live `models/`."""

    def __init__(self, root: str, models_dir: str) -> None:
        super().__init__(root)
        self._models = os.path.abspath(models_dir)

    def tmp_dir(self, step: Optional[str] = None) -> str:
        base = os.path.join(self.root, "tmp", "retrain")
        return os.path.join(base, step) if step else base

    def models_dir(self) -> str:
        return self._models


class _SubStep:
    """Mixin for the norm/train sub-steps: they run INSIDE the retrain
    observability envelope (run_step, not run — one manifest for the
    whole incremental run) with the retrain's prepared in-memory configs
    instead of re-loading from disk."""

    def _inject(self, paths: PathFinder, mc, ccs) -> None:
        self.paths = paths
        self._mc = mc
        self._ccs = ccs

    def setup(self, need_columns: bool = True) -> None:  # noqa: ARG002
        self.model_config = self._mc
        self.column_configs = self._ccs


class _RetrainNorm(_SubStep, NormProcessor):
    step = "retrain-norm"


class _RetrainTrain(_SubStep, TrainProcessor):
    step = "retrain-train"


class RetrainProcessor(BasicProcessor):
    step = "retrain"

    def __init__(self, root: str = ".", from_traffic: bool = False,
                 data_path: Optional[str] = None,
                 candidate_dir: Optional[str] = None,
                 append_trees: Optional[int] = None,
                 traffic_stream: str = "",
                 coresident: bool = False,
                 serve_url: Optional[str] = None) -> None:
        super().__init__(root)
        if from_traffic and data_path is not None:
            raise ShifuError(
                ErrorCode.ILLEGAL_ARGUMENT,
                "--from-traffic and --data are mutually exclusive — the "
                "run can stream ONE source; drop --from-traffic to "
                "retrain on the explicit path")
        self.from_traffic = from_traffic
        # model-zoo tenants log to per-set streams under
        # traffic/<set>/ (loop/traffic.py `stream`); --traffic-stream
        # selects one so per-tenant retrain never mixes another set's
        # rows
        self.traffic_stream = traffic_stream or ""
        if self.traffic_stream and data_path is not None:
            raise ShifuError(
                ErrorCode.ILLEGAL_ARGUMENT,
                "--traffic-stream retrains from the traffic log — it "
                "cannot combine with --data")
        self.data_path = data_path
        self.candidate_dir = os.path.abspath(
            candidate_dir
            if candidate_dir else os.path.join(self.root,
                                               DEFAULT_CANDIDATE_DIR))
        self.append_trees = append_trees
        # --coresident: run the warm-start NN/WDL train as a background
        # HBM-ledger tenant of the serving fleet (coresident/trainer.py)
        self.coresident = bool(coresident)
        self.serve_url = serve_url
        if serve_url and not coresident:
            raise ShifuError(
                ErrorCode.ILLEGAL_ARGUMENT,
                "--serve-url applies to --coresident retraining only "
                "(promotion has its own --serve-url on `shifu promote`)")

    # ---- source resolution ----
    def _resolve_source(self, mc):
        """(kind, names_override, traffic_chunks) — and mutates the
        in-memory ModelConfig copy's data_set to point at the stream.
        The traffic source is the FLEET UNION by default: every serve
        process's writer-scoped chunks under one ledger dir
        (shifu.loop.trafficScope narrows to one writer); the writers
        consumed land in the lineage manifest."""
        from shifu_tpu.loop.traffic import (
            META_FILE,
            chunk_writer,
            log_meta,
            traffic_dir,
            traffic_scope_setting,
        )

        ds = mc.data_set
        stream = self.traffic_stream
        meta_path = os.path.join(traffic_dir(self.root, stream),
                                 META_FILE)
        use_traffic = self.from_traffic or bool(stream) or (
            self.data_path is None and os.path.isfile(meta_path))
        if self.data_path is not None:
            ds.data_path = self.data_path
            return "data", None, None
        if not use_traffic:
            # no traffic log, no --data: retrain on whatever the config
            # points at (a new data drop in place)
            return "data", None, None
        try:
            meta, chunks = log_meta(self.root, stream)
        except FileNotFoundError as e:
            raise ShifuError(ErrorCode.DATA_NOT_FOUND, str(e))
        names = list(meta["columns"])
        target = ds.target_column_name
        if target not in names:
            raise ShifuError(
                ErrorCode.DATA_NOT_FOUND,
                f"traffic log carries no `{target}` column — retraining "
                f"needs label-joined traffic (serve from the model-set "
                f"root so the log keeps the target column)")
        scope = traffic_scope_setting()
        pattern = ("traffic-*.psv" if scope == "fleet"
                   else f"traffic-{scope}-*.psv")
        ds.data_path = os.path.join(traffic_dir(self.root, stream),
                                    pattern)
        ds.data_delimiter = meta.get("delimiter", "|")
        ds.header_path = None
        # the distinct serve processes whose chunks this run consumes —
        # provenance that the union really spanned the fleet
        self._traffic_writers = sorted(
            {chunk_writer(p) or "" for p in chunks})
        return "traffic", names, [os.path.basename(p) for p in chunks]

    # ---- warm-start seeding ----
    def _seed_candidate(self, parent_paths: List[str]) -> None:
        """Copy the parent model set into the candidate dir so the
        trainers' `isContinuous` seam warm-starts from it in place.
        Idempotent: a `--resume` re-copy writes the same bytes, and a
        mid-train kill never touched the copies (specs save at the
        end)."""
        import shutil

        os.makedirs(self.candidate_dir, exist_ok=True)
        for p in parent_paths:
            shutil.copy2(p, os.path.join(self.candidate_dir,
                                         os.path.basename(p)))
        # stale candidates from a previous retrain with MORE members must
        # not survive as phantom ensemble members
        keep = {os.path.basename(p) for p in parent_paths}
        for p in sorted_glob(os.path.join(self.candidate_dir, "model*")):
            if os.path.basename(p) not in keep:
                os.unlink(p)

    def run_step(self) -> None:
        from shifu_tpu.eval.scorer import find_model_paths
        from shifu_tpu.loop import append_trees_setting
        from shifu_tpu.resilience.checkpoint import sectioned_sha
        from shifu_tpu.serve.registry import model_set_sha

        self.setup()
        mc = self.model_config
        assert mc is not None
        alg = mc.train.algorithm

        if self.coresident and alg not in (Algorithm.NN, Algorithm.LR,
                                           Algorithm.WDL):
            raise ShifuError(
                ErrorCode.ILLEGAL_ARGUMENT,
                f"--coresident applies to the streamed NN/LR/WDL "
                f"retrainers; {alg.value} retrains in one pass without "
                f"a resident epoch loop to co-schedule")

        parent_dir = self.paths.models_dir()
        parent_paths = find_model_paths(parent_dir)
        if not parent_paths:
            raise ShifuError(
                ErrorCode.DATA_NOT_FOUND,
                f"no models under {parent_dir} — run `shifu train` "
                f"before `shifu retrain`")
        parent_sha = model_set_sha(parent_paths)
        parent_files = {os.path.basename(p): _file_sha(p)
                        for p in parent_paths}

        # the sub-steps run on a COPY: source/continuous/TreeNum
        # overrides are retrain-scoped, never saved back to disk
        sub_mc = copy.deepcopy(mc)
        kind, names_override, traffic_chunks = self._resolve_source(sub_mc)
        sub_mc.train.is_continuous = True

        append = (append_trees_setting() if self.append_trees is None
                  else int(self.append_trees))
        parent_trees = None
        if alg in (Algorithm.GBT, Algorithm.RF, Algorithm.DT):
            from shifu_tpu.models.tree import TreeModelSpec

            try:
                parent_trees = len(TreeModelSpec.load(parent_paths[0]).trees)
            except Exception as e:
                raise ShifuError(
                    ErrorCode.DATA_NOT_FOUND,
                    f"cannot read parent tree model {parent_paths[0]}: {e}")
            if alg == Algorithm.GBT:
                # append-only growth: the continuous path keeps the
                # parent's trees and trains ONLY the lifted remainder on
                # the new chunks
                params = dict(sub_mc.train.params or {})
                params["TreeNum"] = parent_trees + append
                sub_mc.train.params = params

        rpaths = _RetrainPaths(self.root, self.candidate_dir)
        log.info("retrain source=%s -> norm into %s, candidate %s "
                 "(parent %s: %d model(s)%s)",
                 kind, rpaths.tmp_dir(), self.candidate_dir, parent_sha,
                 len(parent_paths),
                 f", +{append} trees" if alg == Algorithm.GBT else "")

        # ---- phase 1: norm the new stream into tmp/retrain ----
        rn = _RetrainNorm(self.root, names_override=names_override)
        rn._inject(rpaths, sub_mc, self.column_configs)
        rn.run_step()
        from shifu_tpu.norm.dataset import read_meta

        norm_meta = read_meta(rpaths.normalized_data_dir())
        if not norm_meta.n_rows:
            raise ShifuError(
                ErrorCode.DATA_NOT_FOUND,
                "retrain source produced 0 labeled rows after "
                "purify/tag filtering — nothing to train on (unlabeled "
                "traffic logs cannot retrain; join labels first)")

        # ---- phase 2: warm-start train into the candidate dir ----
        self._seed_candidate(parent_paths)
        rt = _RetrainTrain(self.root)
        rt._inject(rpaths, sub_mc, self.column_configs)
        # the streamed trainer's checkpoint identity gains a `loop`
        # section: a snapshot from a retrain against a different parent
        # set must reject, naming the section
        rt.train_ident_extra = {"parentModelSetSha": parent_sha}
        ccfg = None
        if self.coresident:
            from shifu_tpu.coresident import CoresidentConfig

            # family_dir = repo root: the per-stage checkpoint family
            # lands under .shifu/runs/ckpt beside every other resumable
            # stream so `shifu runs --resumable` lists it
            ccfg = CoresidentConfig(
                serve_url=self.serve_url, family_dir=self.root,
                meta={"step": "retrain",
                      "parentModelSetSha": parent_sha}).resolve()
            rt.coresident_cfg = ccfg
            log.info("retrain --coresident: tenant %r as a background "
                     "HBM-ledger tenant (%s)", ccfg.tenant,
                     self.serve_url or "local grant")
        rt.run_step()

        candidate_paths = find_model_paths(self.candidate_dir)
        candidate_sha = model_set_sha(candidate_paths)

        # ---- provenance: the auditable chain in the retrain manifest ----
        _sha, sections = sectioned_sha({
            "data": {"kind": kind,
                     "dataPath": sub_mc.data_set.data_path,
                     "chunks": traffic_chunks},
            "train": {"algorithm": alg.value,
                      "params": sub_mc.train.params or {},
                      "baggingNum": sub_mc.train.bagging_num},
            "loop": {"parentModelSetSha": parent_sha,
                     "appendTrees": (append if alg == Algorithm.GBT
                                     else None)},
        })
        # serve -> train lineage: the request-trace ids stamped into the
        # traffic log tie this candidate back to the exact serving
        # evidence (`shifu trace --show <id>` on the serve ledger)
        lineage = None
        if kind == "traffic":
            from shifu_tpu.loop.traffic import trace_lineage

            try:
                lineage = trace_lineage(self.root,
                                        stream=self.traffic_stream)
            except (OSError, ValueError) as e:
                log.warning("retrain: cannot read trace lineage: %s", e)
        self.manifest_extra["retrain"] = {
            "source": {"kind": kind,
                       "dataPath": sub_mc.data_set.data_path,
                       "trafficChunks": traffic_chunks,
                       "trafficWriters": getattr(
                           self, "_traffic_writers", None),
                       "rows": int(norm_meta.n_rows)},
            "lineage": lineage,
            "parent": {"modelSetSha": parent_sha,
                       "modelsDir": parent_dir,
                       "models": parent_files,
                       "trees": parent_trees},
            "candidate": {"modelSetSha": candidate_sha,
                          "dir": self.candidate_dir,
                          "models": {os.path.basename(p): _file_sha(p)
                                     for p in candidate_paths}},
            "configShas": sections,
            "warmStart": {
                "algorithm": alg.value,
                "appendedTrees": (append if alg == Algorithm.GBT
                                  else None),
            },
            "coresident": ({
                "tenant": ccfg.tenant,
                "stages": ccfg.stages or None,
                "microbatches": ccfg.microbatches,
                "replicas": ccfg.replicas,
                "serveUrl": self.serve_url,
            } if ccfg is not None else None),
        }
        log.info("retrain done: candidate %s (%d model(s)) from parent %s "
                 "on %d new rows — promote with `shifu promote`",
                 candidate_sha, len(candidate_paths), parent_sha,
                 norm_meta.n_rows)
