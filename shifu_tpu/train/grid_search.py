"""Hyper-parameter grid/random search.

Parity: core/dtrain/gs/GridSearch.java:44 — a train param whose value is a
list becomes a grid dimension; for natively-list-valued keys
(ActivationFunc, NumHiddenNodes, FixedLayers, NumEmbedColumnIds) a grid
dimension is a list OF lists (GridSearch.java:171-185). Flattening is
cartesian over sorted keys; when the flattened count exceeds
`shifu.gridsearch.threshold` (default 30) a seeded random subset is used
(checkParamsThreshold, GridSearch.java:222-232). A grid config file
(train.gridConfigFile) holds one `k:v;k:v` composite per line.
"""

from __future__ import annotations

import itertools
import random
from typing import Any, Dict, List, Optional

from shifu_tpu.utils import environment

LIST_NATURED_KEYS = {
    "ActivationFunc",
    "NumHiddenNodes",
    "FixedLayers",
    "NumEmbedColumnIds",
}


def _is_hyper(key: str, value: Any) -> bool:
    if key in LIST_NATURED_KEYS:
        return (
            isinstance(value, list)
            and len(value) > 0
            and isinstance(value[0], list)
        )
    return isinstance(value, list)


def _parse_value(raw: str) -> Any:
    raw = raw.strip()
    if raw.startswith("[") and raw.endswith("]"):
        inner = raw[1:-1].strip()
        return [_parse_value(v) for v in inner.split(",")] if inner else []
    for cast in (int, float):
        try:
            return cast(raw)
        except ValueError:
            continue
    return raw


def parse_grid_file(path: str) -> List[Dict[str, Any]]:
    """One composite per line: `LearningRate:0.1;NumHiddenNodes:[30,20]`."""
    out = []
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            composite: Dict[str, Any] = {}
            for ele in line.split(";"):
                if ":" not in ele:
                    continue
                k, v = ele.split(":", 1)
                composite[k.strip()] = _parse_value(v)
            if composite:
                out.append(composite)
    return out


def flatten_params(
    params: Dict[str, Any],
    grid_config_file: Optional[str] = None,
    seed: int = 0,
) -> List[Dict[str, Any]]:
    """All trainer param composites. Length 1 means no grid search."""
    if grid_config_file:
        composites = parse_grid_file(grid_config_file)
        if composites:
            return composites

    keys = sorted(params.keys())
    hyper = [(k, params[k]) for k in keys if _is_hyper(k, params[k])]
    if not hyper:
        return [dict(params)]
    normal = {k: v for k, v in params.items() if not _is_hyper(k, v)}

    composites = []
    for combo in itertools.product(*(v for _, v in hyper)):
        m = dict(normal)
        for (k, _), v in zip(hyper, combo):
            m[k] = v
        composites.append(m)

    threshold = environment.get_int("shifu.gridsearch.threshold", 30)
    if len(composites) > threshold:
        rng = random.Random(seed)
        composites = rng.sample(composites, threshold)
    return composites
