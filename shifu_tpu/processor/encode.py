"""`shifu encode` — encode a dataset against the trained model.

Parity: core/processor/ModelDataEncodeProcessor.java + udf/EncodeDataUDF.java:
tree models emit the per-tree leaf index (tree-path encoding); other models
fall back to woe encoding of every candidate column.
Output: tmp/encode/EncodedData/part-00000 (tag|f0|f1|...).
"""

from __future__ import annotations

import os

import numpy as np

from shifu_tpu.data.purify import combined_mask
from shifu_tpu.data.reader import make_tags, read_columnar, read_header
from shifu_tpu.processor.basic import BasicProcessor
from shifu_tpu.utils.errors import ErrorCode, ShifuError
from shifu_tpu.utils.log import get_logger

log = get_logger(__name__)


class EncodeProcessor(BasicProcessor):
    step = "encode"

    def __init__(self, root: str = ".", dataset: str = None):
        super().__init__(root)
        self.dataset = dataset  # eval set name; None = training data

    def _load(self):
        mc = self.model_config
        ds = mc.data_set
        if self.dataset:
            ec = mc.get_eval(self.dataset)
            if ec is None:
                raise ShifuError(ErrorCode.INVALID_MODEL_CONFIG,
                                 f"eval set {self.dataset} not found")
            src = ec.data_set
            data_path = src.data_path or ds.data_path
            header_path = src.header_path or ds.header_path
            delim = src.data_delimiter or ds.data_delimiter
        else:
            data_path, header_path, delim = ds.data_path, ds.header_path, ds.data_delimiter
        names = (read_header(self.resolve(header_path), ds.header_delimiter)
                 if header_path else [c.column_name for c in self.column_configs])
        data = read_columnar(self.resolve(data_path), names, delimiter=delim,
                             missing_values=tuple(ds.missing_or_invalid_values))
        mask = combined_mask(ds.filter_expressions, data.raw, data.n_rows)
        data = data.select_rows(mask)
        tags = make_tags(data.column(ds.target_column_name), ds.pos_tags, ds.neg_tags)
        return data, tags

    def run_step(self) -> None:
        self.setup()
        from shifu_tpu.eval.scorer import find_model_paths, load_model
        from shifu_tpu.models.tree import TreeModelSpec

        data, tags = self._load()
        out_dir = self.paths.ensure(self.paths.tmp_dir("encode"))
        out = os.path.join(out_dir, "EncodedData")
        paths = find_model_paths(self.paths.models_dir())
        tree_specs = [load_model(p) for p in paths
                      if p.endswith((".gbt", ".rf"))]

        if tree_specs:
            feats, names = self._tree_path_encode(tree_specs[0], data)
        else:
            feats, names = self._woe_encode(data)

        with open(out, "w") as fh:
            fh.write("|".join(["tag"] + names) + "\n")
            for i in range(data.n_rows):
                fh.write("|".join([str(int(tags[i]))] +
                                  [f"{v:g}" for v in feats[i]]) + "\n")
        log.info("encoded %d rows x %d features -> %s",
                 data.n_rows, len(names), out)

    def _tree_path_encode(self, spec, data):
        """Per record per tree: index of the leaf reached
        (EncodeDataUDF tree-path encoding)."""
        import jax
        import jax.numpy as jnp

        ind = spec.independent()
        codes = jnp.asarray(ind.codes_from_raw(data))
        leaves = []
        for t in spec.trees:
            feature = jnp.asarray(t.feature)
            left_mask = jnp.asarray(t.left_mask)
            node = jnp.zeros(codes.shape[0], jnp.int32)
            for _ in range(t.depth):
                f = feature[node]
                is_leaf = f < 0
                code = jnp.take_along_axis(
                    codes, jnp.maximum(f, 0)[:, None], axis=1
                )[:, 0]
                goes_left = left_mask[node, jnp.clip(code, 0, left_mask.shape[1] - 1)]
                child = jnp.where(goes_left, 2 * node + 1, 2 * node + 2)
                node = jnp.where(is_leaf, node, child)
            leaves.append(np.asarray(node))
        feats = np.stack(leaves, axis=1)
        return feats, [f"tree_{k}" for k in range(len(spec.trees))]

    def _woe_encode(self, data):
        from shifu_tpu.config.model_config import NormType
        from shifu_tpu.norm.normalizer import apply_norm_plan, build_norm_plan

        mc = self.model_config
        orig = mc.normalize.norm_type
        mc.normalize.norm_type = NormType.WOE
        try:
            plan = build_norm_plan(mc, self.column_configs)
            feats = apply_norm_plan(plan, data)
            return feats, plan.out_names
        finally:
            mc.normalize.norm_type = orig
