"""Shared utilities: logging, environment, errors."""
