"""Sharded on-disk layout for normalized training data.

Replaces the reference's Pig-written text NormalizedData
(core/processor/NormalizeModelProcessor.java:183-252 + Normalize.pig): rows
become float32 .npy shards that memory-map straight into host RAM and feed
`jax.device_put` per mesh shard — no text re-parsing between norm and train.

Layout under PathFinder.normalized_data_dir():
    meta.json                 columns, n_rows, shard row counts, norm type
    features-SSSSS.npy        [rows_s, n_cols] float32
    tags-SSSSS.npy            [rows_s] int8   (1 pos / 0 neg)
    weights-SSSSS.npy         [rows_s] float32
and under cleaned_data_dir() (tree-model input, bin codes not z-scores):
    codes-SSSSS.npy           [rows_s, n_feat] int16
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass
from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np

from shifu_tpu.fs.listing import sorted_glob


@dataclass
class NormMeta:
    columns: List[str]
    n_rows: int
    shard_rows: List[int]
    norm_type: str = "ZSCALE"
    extra: Optional[dict] = None

    def to_json(self) -> dict:
        return {
            "columns": self.columns,
            "nRows": self.n_rows,
            "shardRows": self.shard_rows,
            "normType": self.norm_type,
            "extra": self.extra or {},
        }

    @classmethod
    def from_json(cls, d: dict) -> "NormMeta":
        return cls(
            columns=list(d["columns"]),
            n_rows=int(d["nRows"]),
            shard_rows=[int(x) for x in d["shardRows"]],
            norm_type=d.get("normType", "ZSCALE"),
            extra=d.get("extra") or {},
        )


def _write_meta(
    out_dir: str,
    columns: List[str],
    shard_rows: List[int],
    norm_type: str,
    extra: Optional[dict],
) -> NormMeta:
    meta = NormMeta(
        columns=columns,
        n_rows=int(sum(shard_rows)),
        shard_rows=shard_rows,
        norm_type=norm_type,
        extra=extra,
    )
    with open(os.path.join(out_dir, "meta.json"), "w") as fh:
        json.dump(meta.to_json(), fh, indent=2)
    return meta


class ShardWriter:
    """Incremental shard-at-a-time writer — the streaming norm path emits
    one shard per ingest chunk, so peak memory is one chunk regardless of
    dataset size (MemoryDiskFloatMLDataSet's memory envelope, done the
    streaming way)."""

    def __init__(
        self,
        out_dir: str,
        primary_prefix: str,
        primary_dtype,
        columns: List[str],
        norm_type: str,
        extra: Optional[dict] = None,
    ):
        os.makedirs(out_dir, exist_ok=True)
        self.out_dir = out_dir
        self.primary_prefix = primary_prefix
        self.primary_dtype = primary_dtype
        self.columns = columns
        self.norm_type = norm_type
        self.extra = extra
        self.shard_rows: List[int] = []

    def add(self, primary: np.ndarray, tags: np.ndarray, weights: np.ndarray):
        s = len(self.shard_rows)
        np.save(os.path.join(self.out_dir, f"{self.primary_prefix}-{s:05d}.npy"),
                primary.astype(self.primary_dtype, copy=False))
        np.save(os.path.join(self.out_dir, f"tags-{s:05d}.npy"),
                tags.astype(np.int8, copy=False))
        np.save(os.path.join(self.out_dir, f"weights-{s:05d}.npy"),
                weights.astype(np.float32, copy=False))
        self.shard_rows.append(primary.shape[0])

    def restore(self, shard_rows: List[int]) -> None:
        """Resume after preemption: trust the first len(shard_rows) shards
        on disk (the stream checkpoint recorded them as complete) and
        continue appending — the next add() overwrites any shard the
        killed run wrote past its last snapshot, torn or whole."""
        self.shard_rows = [int(r) for r in shard_rows]

    def close(self) -> NormMeta:
        if not self.shard_rows:
            # every chunk filtered empty: write one empty shard so loaders
            # get a clear zero-row dataset, not a missing-file crash
            n_cols = len(self.columns)
            self.add(
                np.zeros((0, n_cols), dtype=self.primary_dtype),
                np.zeros(0, dtype=np.int8),
                np.zeros(0, dtype=np.float32),
            )
        return _write_meta(self.out_dir, self.columns, self.shard_rows,
                           self.norm_type, self.extra)


class HostPartWriter:
    """Per-host stage of the pod-scale streaming norm (HostPlan,
    data/pipeline.py): each host appends its OWN chunks as part files
    keyed by GLOBAL chunk index —
        .part-<prefix>-CCCCCCCC.npy  (+ .part-tags- / .part-weights-)
    — and after the host barrier the merge host renames the fleet's
    union into the sequential single-process shard layout. The rename
    is a pure relabel ci -> rank(ci) over the sorted union, and np.save
    of an identical array produces identical bytes, so every shard AND
    the merged meta.json come out byte-identical to the 1-process run
    regardless of how many hosts streamed. Parts live in the final
    out_dir (the same shared filesystem the leases and hostsync parts
    ride), so the merge is H*K renames, not a copy."""

    def __init__(
        self,
        out_dir: str,
        primary_prefix: str,
        primary_dtype,
        columns: List[str],
        norm_type: str,
        extra: Optional[dict] = None,
    ):
        os.makedirs(out_dir, exist_ok=True)
        self.out_dir = out_dir
        self.primary_prefix = primary_prefix
        self.primary_dtype = primary_dtype
        self.columns = columns
        self.norm_type = norm_type
        self.extra = extra
        self.part_rows: Dict[int, int] = {}

    def _part(self, prefix: str, ci: int) -> str:
        return os.path.join(self.out_dir, f".part-{prefix}-{ci:08d}.npy")

    def add(self, ci: int, primary: np.ndarray, tags: np.ndarray,
            weights: np.ndarray) -> None:
        np.save(self._part(self.primary_prefix, ci),
                primary.astype(self.primary_dtype, copy=False))
        np.save(self._part("tags", ci), tags.astype(np.int8, copy=False))
        np.save(self._part("weights", ci),
                weights.astype(np.float32, copy=False))
        self.part_rows[int(ci)] = int(primary.shape[0])

    def restore(self, part_rows: Dict) -> None:
        """Resume after preemption: the stream checkpoint recorded these
        parts as complete; a chunk killed mid-np.save sits past the
        cursor and is reprocessed, overwriting any torn part in place."""
        self.part_rows = {int(k): int(v) for k, v in part_rows.items()}

    def merge(self, union_rows: Dict[int, int]) -> NormMeta:
        """Merge host only, after the barrier: rename the fleet-wide
        union of parts ({global ci: rows}, this host's included) into
        the sequential shard layout and write the merged meta.json."""
        shard_rows: List[int] = []
        for sid, ci in enumerate(sorted(union_rows)):
            for prefix in (self.primary_prefix, "tags", "weights"):
                os.replace(
                    self._part(prefix, ci),
                    os.path.join(self.out_dir, f"{prefix}-{sid:05d}.npy"))
            shard_rows.append(int(union_rows[ci]))
        if not shard_rows:
            # mirror ShardWriter.close(): one empty shard, never a
            # missing-file crash for loaders
            np.save(os.path.join(self.out_dir,
                                 f"{self.primary_prefix}-00000.npy"),
                    np.zeros((0, len(self.columns)),
                             dtype=self.primary_dtype))
            np.save(os.path.join(self.out_dir, "tags-00000.npy"),
                    np.zeros(0, dtype=np.int8))
            np.save(os.path.join(self.out_dir, "weights-00000.npy"),
                    np.zeros(0, dtype=np.float32))
            shard_rows.append(0)
        # every host has published its part list by now, so any .part-*
        # file not in the union is debris from a dead earlier run
        for leftover in sorted_glob(os.path.join(self.out_dir,
                                                 ".part-*.npy")):
            try:
                os.unlink(leftover)
            except OSError:
                pass
        return _write_meta(self.out_dir, self.columns, shard_rows,
                           self.norm_type, self.extra)


class ShuffleShardWriter:
    """External-shuffle shard writer — the streaming analog of the MR shuffle
    (core/shuffle/MapReduceShuffle.java:47, random-key re-partition).

    Pass 1 (add): each chunk's rows scatter to k bucket files under a
    deterministic random assignment. Pass 2 (close): each bucket is loaded,
    permuted, and written as a final .npy shard. Random bucket assignment +
    within-bucket permutation is a TRUE uniform global permutation, so a
    label- or time-sorted input is fully decorrelated across AND within
    shards — within-chunk shuffling alone leaves chunks globally ordered.
    Peak memory: one bucket (~n_rows/k rows).

    Determinism contract: two writers built with the same (seed, n_buckets)
    and fed add() calls in lockstep draw identical assignments and bucket
    permutations, so the feature and bin-code artifacts stay row-aligned.

    Bucket files are opened in append mode per write (no persistent handles,
    so k is not bounded by the fd ulimit), and close() permutes each bucket
    through block-wise memmap gathers, so peak anonymous memory stays at one
    block regardless of bucket size.
    """

    _CLOSE_BLOCK_ROWS = 65536

    def __init__(
        self,
        out_dir: str,
        primary_prefix: str,
        primary_dtype,
        columns: List[str],
        norm_type: str,
        n_buckets: int,
        seed: int = 0,
        extra: Optional[dict] = None,
    ):
        os.makedirs(out_dir, exist_ok=True)
        self.out_dir = out_dir
        self.primary_prefix = primary_prefix
        self.primary_dtype = np.dtype(primary_dtype)
        self.columns = columns
        self.norm_type = norm_type
        self.extra = extra
        self.seed = seed
        self.k = max(1, n_buckets)
        self._chunk_idx = 0
        self._bucket_rows = [0] * self.k
        for s in range(self.k):
            base = self._bucket_base(s)
            for suffix in (".primary.bin", ".tags.bin", ".weights.bin"):
                open(base + suffix, "wb").close()  # truncate leftovers

    def _bucket_base(self, s: int) -> str:
        return os.path.join(self.out_dir, f".bucket-{s:05d}")

    def add(self, primary: np.ndarray, tags: np.ndarray, weights: np.ndarray):
        n = primary.shape[0]
        # 5_555 domain-separates from _prepare_rows' sampling draws, which
        # use [seed, chunk_idx] — replaying that exact stream here would
        # re-interpret the words that decided row retention as bucket ids,
        # biasing kept rows toward low buckets (close() tags with 7_777)
        assign = np.random.default_rng(
            [self.seed, 5_555, self._chunk_idx]
        ).integers(self.k, size=n)
        self._chunk_idx += 1
        # single stable partition instead of one boolean scan per bucket
        order = np.argsort(assign, kind="stable")
        counts = np.bincount(assign, minlength=self.k)
        bounds = np.concatenate([[0], np.cumsum(counts)])
        p = np.ascontiguousarray(primary.astype(self.primary_dtype, copy=False)[order])
        t = np.ascontiguousarray(tags.astype(np.int8, copy=False)[order])
        w = np.ascontiguousarray(weights.astype(np.float32, copy=False)[order])
        for s in np.nonzero(counts)[0]:
            a, b = bounds[s], bounds[s + 1]
            base = self._bucket_base(s)
            with open(base + ".primary.bin", "ab") as fh:
                fh.write(p[a:b].tobytes())
            with open(base + ".tags.bin", "ab") as fh:
                fh.write(t[a:b].tobytes())
            with open(base + ".weights.bin", "ab") as fh:
                fh.write(w[a:b].tobytes())
            self._bucket_rows[s] += int(b - a)

    def _permute_to_npy(self, src: str, dtype, shape, perm, dst: str) -> None:
        if shape[0] == 0:
            np.save(dst, np.zeros(shape, dtype=dtype))
            return
        src_mm = np.memmap(src, dtype=dtype, mode="r", shape=shape)
        out = np.lib.format.open_memmap(dst, mode="w+", dtype=dtype, shape=shape)
        for a in range(0, shape[0], self._CLOSE_BLOCK_ROWS):
            b = min(a + self._CLOSE_BLOCK_ROWS, shape[0])
            out[a:b] = src_mm[perm[a:b]]
        out.flush()
        del out, src_mm

    def close(self) -> NormMeta:
        n_cols = len(self.columns)
        shard_rows: List[int] = []
        for s in range(self.k):
            base = self._bucket_base(s)
            rows = self._bucket_rows[s]
            perm = np.random.default_rng([self.seed, 7_777, s]).permutation(rows)
            sid = len(shard_rows)
            self._permute_to_npy(
                base + ".primary.bin", self.primary_dtype, (rows, n_cols),
                perm,
                os.path.join(self.out_dir, f"{self.primary_prefix}-{sid:05d}.npy"))
            self._permute_to_npy(
                base + ".tags.bin", np.int8, (rows,), perm,
                os.path.join(self.out_dir, f"tags-{sid:05d}.npy"))
            self._permute_to_npy(
                base + ".weights.bin", np.float32, (rows,), perm,
                os.path.join(self.out_dir, f"weights-{sid:05d}.npy"))
            shard_rows.append(rows)
            for suffix in (".primary.bin", ".tags.bin", ".weights.bin"):
                os.remove(base + suffix)
        return _write_meta(self.out_dir, self.columns, shard_rows,
                           self.norm_type, self.extra)


def _shard_slices(n_rows: int, n_shards: int) -> List[Tuple[int, int]]:
    base, rem = divmod(n_rows, n_shards)
    out, start = [], 0
    for s in range(n_shards):
        size = base + (1 if s < rem else 0)
        out.append((start, start + size))
        start += size
    return out


def _write_sharded(
    out_dir: str,
    primary_prefix: str,
    primary: np.ndarray,
    primary_dtype,
    tags: np.ndarray,
    weights: np.ndarray,
    columns: List[str],
    norm_type: str,
    n_shards: int,
    extra: Optional[dict],
) -> NormMeta:
    os.makedirs(out_dir, exist_ok=True)
    n = primary.shape[0]
    n_shards = max(1, min(n_shards, max(n, 1)))
    shard_rows = []
    for s, (a, b) in enumerate(_shard_slices(n, n_shards)):
        np.save(os.path.join(out_dir, f"{primary_prefix}-{s:05d}.npy"),
                primary[a:b].astype(primary_dtype, copy=False))
        np.save(os.path.join(out_dir, f"tags-{s:05d}.npy"),
                tags[a:b].astype(np.int8, copy=False))
        np.save(os.path.join(out_dir, f"weights-{s:05d}.npy"),
                weights[a:b].astype(np.float32, copy=False))
        shard_rows.append(b - a)
    return _write_meta(out_dir, columns, shard_rows, norm_type, extra)


def write_normalized(
    out_dir: str,
    features: np.ndarray,
    tags: np.ndarray,
    weights: np.ndarray,
    columns: List[str],
    norm_type: str = "ZSCALE",
    n_shards: int = 1,
    extra: Optional[dict] = None,
) -> NormMeta:
    return _write_sharded(out_dir, "features", features, np.float32, tags,
                          weights, columns, norm_type, n_shards, extra)


def write_codes(
    out_dir: str,
    codes: np.ndarray,
    tags: np.ndarray,
    weights: np.ndarray,
    columns: List[str],
    slots: List[int],
    n_shards: int = 1,
) -> NormMeta:
    """Tree-model input: int16 bin codes per feature + per-column slot counts.
    int16 covers the reference's 10k category cap; wider slots use int32."""
    code_dtype = np.int16 if (not slots or max(slots) < 2**15) else np.int32
    return _write_sharded(out_dir, "codes", codes, code_dtype, tags, weights,
                          columns, "CODES", n_shards, {"slots": slots})


def read_meta(data_dir: str) -> NormMeta:
    with open(os.path.join(data_dir, "meta.json")) as fh:
        return NormMeta.from_json(json.load(fh))


def _load_stack(data_dir: str, prefix: str, n_shards: int) -> np.ndarray:
    parts = [
        np.load(os.path.join(data_dir, f"{prefix}-{s:05d}.npy"), mmap_mode="r")
        for s in range(n_shards)
    ]
    return np.concatenate(parts, axis=0) if len(parts) > 1 else np.asarray(parts[0])


def load_normalized(
    data_dir: str,
) -> Tuple[NormMeta, np.ndarray, np.ndarray, np.ndarray]:
    """(meta, features[n, C] f32, tags[n] i8, weights[n] f32)."""
    meta = read_meta(data_dir)
    k = len(meta.shard_rows)
    feats = _load_stack(data_dir, "features", k)
    tags = _load_stack(data_dir, "tags", k)
    weights = _load_stack(data_dir, "weights", k)
    return meta, feats, tags, weights


def load_codes(
    data_dir: str,
) -> Tuple[NormMeta, np.ndarray, np.ndarray, np.ndarray]:
    """(meta, codes[n, C] i16, tags[n] i8, weights[n] f32)."""
    meta = read_meta(data_dir)
    k = len(meta.shard_rows)
    codes = _load_stack(data_dir, "codes", k)
    tags = _load_stack(data_dir, "tags", k)
    weights = _load_stack(data_dir, "weights", k)
    return meta, codes, tags, weights


def iter_shards(data_dir: str, prefix: str = "features") -> Iterator[np.ndarray]:
    meta = read_meta(data_dir)
    for s in range(len(meta.shard_rows)):
        yield np.load(os.path.join(data_dir, f"{prefix}-{s:05d}.npy"), mmap_mode="r")
