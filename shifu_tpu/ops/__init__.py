"""Compute kernels: jit-compiled aggregations shared by stats/train/eval."""
