"""Weight-update rules as pure jax functions over flat parameter vectors.

Parity with core/dtrain/Weight.java (the master-side update machinery copied
from Encog) and core/dtrain/nn/update/* — but expressed as (state, w, g) ->
(w', state') pure functions so the whole training loop stays inside one jit.

Convention inherited from Encog/the reference: `g` is the DESCENT direction
(accumulated -dE/dw summed over records, NOT averaged), so every rule does
`w += step(g)`. Propagation codes (train params "Propagation"):
    B  back propagation w/ momentum     Weight.updateWeightBP:246
    Q  quick propagation                Weight.updateWeightQBP:252
    M  manhattan                        Weight.updateWeightMHP:300
    R  resilient (RPROP+)               Weight.updateWeightRLP:313
Optimizer names (train params "Propagation" again, reference overloads it):
    ADAM / ADAGRAD / RMSPROP / MOMENTUM / NESTEROV   nn/update/*.java
Regularization (non-optimizer path, Weight.calculateWeights:194-221): L2
subtracts reg*w/numTrainSize from the step; L1 soft-thresholds the updated
weight by reg/numTrainSize (the reference's L1 branch replaces the weight
with the shrunk delta — an evident bug we do not reproduce).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Tuple

# RPROP constants (DTrainUtils.java:74-85, Weight.java:72-74)
POSITIVE_ETA = 1.2
NEGATIVE_ETA = 0.5
DELTA_MIN = 1e-6
DEFAULT_INITIAL_UPDATE = 0.1
DEFAULT_MAX_STEP = 50.0
ZERO_TOLERANCE = 1e-17
QPROP_DECAY = 1e-4
QPROP_OUTPUT_EPSILON = 0.35

UpdateFn = Callable[..., Tuple[Any, Dict[str, Any]]]


def _zeros_like(n, jnp):
    return jnp.zeros((n,), dtype=jnp.float32)


def make_updater(
    propagation: str,
    momentum: float = 0.5,
    reg: float = 0.0,
    reg_level: str = "NONE",
    adam_beta1: float = 0.9,
    adam_beta2: float = 0.999,
):
    """Returns (init_state(n_weights) -> state,
                apply(state, w, g, lr, iteration, num_train_size) -> (w', state')).

    lr and num_train_size are threaded per-call as traced values so one
    compiled program serves every learning-decay step and bagging-sample
    size (NNMaster.java:267 lr *= 1-learningDecay composes outside)."""
    import jax.numpy as jnp

    prop = (propagation or "Q").upper()

    def regularize(w, step, nts):
        """Apply the step plus L1/L2 regularization (Weight.java:199-218)."""
        if reg_level == "L2" and reg != 0.0:
            return w + step - reg * w / nts
        if reg_level == "L1" and reg != 0.0:
            shrink = reg / nts
            updated = w + step
            return jnp.sign(updated) * jnp.maximum(0.0, jnp.abs(updated) - shrink)
        return w + step

    def reg_gradient(w, g, nts):
        """Fold the penalty into the descent direction for the optimizer
        branches, the way the reference regularizes inside each layer's
        gradient (DenseLayer.java:193, WideDenseLayer.java:100,
        WideFieldLayer.java:104) so L2 works under every optimizer."""
        if reg_level == "L2" and reg != 0.0:
            return g - reg * w / nts
        if reg_level == "L1" and reg != 0.0:
            return g - reg * jnp.sign(w) / nts
        return g

    if prop == "B":

        def init(n):
            return {"last_delta": _zeros_like(n, jnp)}

        def apply(state, w, g, lr, it, nts):
            delta = g * lr + state["last_delta"] * momentum
            return regularize(w, delta, nts), {"last_delta": delta}

        return init, apply

    if prop == "M":

        def init(n):
            return {}

        def apply(state, w, g, lr, it, nts):
            step = jnp.where(
                jnp.abs(g) < ZERO_TOLERANCE, 0.0, jnp.sign(g) * lr
            )
            return regularize(w, step, nts), state

        return init, apply

    if prop == "Q":
        # Quickprop (Weight.updateWeightQBP:252-297). eps/shrink derive from
        # the construction-time lr and train size (Weight.java:146-147);
        # nts is traced so eps follows the actual sample size.

        def init(n):
            return {
                "last_delta": _zeros_like(n, jnp),
                "last_gradient": _zeros_like(n, jnp),
            }

        def apply(state, w, g, lr, it, nts):
            eps = QPROP_OUTPUT_EPSILON / jnp.maximum(nts, 1.0)
            shrink = lr / (1.0 + lr)
            d = state["last_delta"]
            s = -g + QPROP_DECAY * w
            p = -state["last_gradient"]
            quad = d * s / (p - s)
            lin = -eps * s
            step_neg = jnp.where(s > 0.0, lin, 0.0) + jnp.where(
                s >= shrink * p, lr * d, quad
            )
            step_pos = jnp.where(s < 0.0, lin, 0.0) + jnp.where(
                s <= shrink * p, lr * d, quad
            )
            next_step = jnp.where(
                d < 0.0, step_neg, jnp.where(d > 0.0, step_pos, lin)
            )
            return regularize(w, next_step, nts), {
                "last_delta": next_step,
                "last_gradient": g,
            }

        return init, apply

    if prop == "R":
        # RPROP+ (Weight.updateWeightRLP:313-343): per-weight adaptive step,
        # sign-change backtracking, last gradient zeroed after a reversal.
        def init(n):
            return {
                "update_values": jnp.full((n,), DEFAULT_INITIAL_UPDATE, jnp.float32),
                "last_gradient": _zeros_like(n, jnp),
                "last_delta": _zeros_like(n, jnp),
            }

        def apply(state, w, g, lr, it, nts):
            change = jnp.sign(g * state["last_gradient"])
            upd = state["update_values"]
            delta_pos = jnp.minimum(upd * POSITIVE_ETA, DEFAULT_MAX_STEP)
            delta_neg = jnp.maximum(upd * NEGATIVE_ETA, DELTA_MIN)
            new_upd = jnp.where(
                change > 0, delta_pos, jnp.where(change < 0, delta_neg, upd)
            )
            wchange = jnp.where(
                change > 0,
                jnp.sign(g) * delta_pos,
                jnp.where(change < 0, -state["last_delta"], jnp.sign(g) * upd),
            )
            new_last_g = jnp.where(change < 0, 0.0, g)
            return regularize(w, wchange, nts), {
                "update_values": new_upd,
                "last_gradient": new_last_g,
                "last_delta": wchange,
            }

        return init, apply

    if prop == "ADAM":

        def init(n):
            return {"m": _zeros_like(n, jnp), "v": _zeros_like(n, jnp)}

        def apply(state, w, g, lr, it, nts):
            g = reg_gradient(w, g, nts)
            m = adam_beta1 * state["m"] + (1 - adam_beta1) * g
            v = adam_beta2 * state["v"] + (1 - adam_beta2) * g * g
            it_f = jnp.maximum(it.astype(jnp.float32), 1.0)
            m_hat = m / (1 - adam_beta1**it_f)
            v_hat = v / (1 - adam_beta2**it_f)
            step = lr * m_hat / (jnp.sqrt(v_hat) + 1e-8)
            return w + step, {"m": m, "v": v}

        return init, apply

    if prop == "ADAGRAD":

        def init(n):
            return {"sum_sq": _zeros_like(n, jnp)}

        def apply(state, w, g, lr, it, nts):
            g = reg_gradient(w, g, nts)
            s = state["sum_sq"] + g * g
            step = lr * g / (jnp.sqrt(s) + 1e-8)
            return w + step, {"sum_sq": s}

        return init, apply

    if prop == "RMSPROP":

        def init(n):
            return {"cache": _zeros_like(n, jnp)}

        def apply(state, w, g, lr, it, nts):
            g = reg_gradient(w, g, nts)
            cache = 0.9 * state["cache"] + 0.1 * g * g
            step = lr * g / (jnp.sqrt(cache) + 1e-8)
            return w + step, {"cache": cache}

        return init, apply

    if prop == "MOMENTUM":

        def init(n):
            return {"v": _zeros_like(n, jnp)}

        def apply(state, w, g, lr, it, nts):
            g = reg_gradient(w, g, nts)
            v = momentum * state["v"] + lr * g
            return w + v, {"v": v}

        return init, apply

    if prop == "NESTEROV":

        def init(n):
            return {"v": _zeros_like(n, jnp)}

        def apply(state, w, g, lr, it, nts):
            g = reg_gradient(w, g, nts)
            v_prev = state["v"]
            v = momentum * v_prev - lr * (-g)  # g is descent dir: v = mom*v + lr*g
            w_new = w - momentum * v_prev + (1 + momentum) * v
            return w_new, {"v": v}

        return init, apply

    raise ValueError(f"unknown propagation/optimizer: {propagation}")
