from shifu_tpu.parallel.mesh import (  # noqa: F401
    data_mesh,
    pad_rows,
    shard_rows,
)
