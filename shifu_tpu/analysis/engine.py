"""`shifu check` — AST lint engine with a JAX-aware view of the package.

The reference kept a ~99k-LoC pipeline honest with JVM-era program
checkers (FindBugs et al.); the JAX rebuild's failure classes are
different — host↔device syncs inside traced code, recompile storms,
dtype drift — and no off-the-shelf linter sees them. This engine is the
project-owned replacement: plain-stdlib AST analysis (no jax import, so
the CI lint job runs it without an accelerator stack) over a whole
package at once, so rules can reason about *reachability from jit sites*
rather than single files.

Pieces:
  * ``Module``      — one parsed file: source, AST, parent links.
  * ``PackageContext`` — the cross-file view: every function def, a
    lightweight call graph seeded at trace roots (``@jax.jit`` /
    ``jax.jit(f)`` / ``shard_map`` / ``lax.scan`` bodies, ...), and the
    resulting *traced set*: defs whose bodies execute under a tracer.
  * ``Rule``        — id + default severity + ``check(module, ctx)``;
    rules self-register via ``@register`` (rules/jaxrules.py,
    rules/hygiene.py).
  * reporters       — human one-line-per-finding, and a JSON document
    (``shifu.check/1``) for the CI gate and tooling.

Suppression: a finding is suppressed by ``# shifu: noqa[RULE1,RULE2]``
(or a blanket ``# shifu: noqa``) on the flagged line. Policy (see
docs/ANALYSIS.md): every noqa carries a one-line justification.
Exit code: 1 iff any unsuppressed error-severity finding remains.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Set

SCHEMA = "shifu.check/1"

_NOQA_RE = re.compile(
    r"#\s*shifu:\s*noqa(?:\s*\[(?P<rules>[A-Za-z0-9_,\s]+)\])?")

# wrappers whose function argument is traced (decorator or call form)
TRACE_WRAPPERS = {
    "jit", "pjit", "pmap", "vmap", "grad", "value_and_grad",
    "shard_map", "shard_map_compat", "checkify",
}
# jax.lax control flow: these call their function operands under trace
TRACE_LAX = {"scan", "while_loop", "fori_loop", "cond", "switch", "map",
             "associative_scan", "custom_root", "custom_linear_solve"}


@dataclasses.dataclass
class Finding:
    rule: str
    severity: str  # "error" | "warning"
    path: str
    line: int
    col: int
    message: str
    suppressed: bool = False
    baselined: bool = False

    def sort_key(self):
        return (self.path, self.line, self.col, self.rule)

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)

    def baseline_key(self) -> str:
        """Content-addressed identity for `--baseline` matching: rule +
        path + message with every number normalized away, so a finding
        keeps its key while unrelated edits move it around the file.
        Line/col are deliberately excluded."""
        import hashlib

        norm = re.sub(r"\d+", "#", self.message)
        return hashlib.sha256(
            f"{self.rule}\x00{self.path}\x00{norm}".encode("utf-8")
        ).hexdigest()[:16]


# ---------------------------------------------------------------------------
# parsed file + package context
# ---------------------------------------------------------------------------


class Module:
    """One parsed source file with parent links and line access."""

    def __init__(self, path: str, source: str) -> None:
        self.path = path
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        self.parent: Dict[ast.AST, ast.AST] = {}
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                self.parent[child] = node

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    def ancestors(self, node: ast.AST) -> Iterator[ast.AST]:
        cur = self.parent.get(node)
        while cur is not None:
            yield cur
            cur = self.parent.get(cur)

    def enclosing_function(self, node: ast.AST):
        """Nearest enclosing def (the scope whose trace status governs
        `node`), or None at module level."""
        for anc in self.ancestors(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return anc
        return None

    def segment(self, node: ast.AST) -> str:
        try:
            return ast.get_source_segment(self.source, node) or ""
        except Exception:  # malformed positions on synthesized nodes
            return ""


def dotted_name(node: ast.AST) -> str:
    """Best-effort dotted name of a call target / attribute chain
    ("jax.lax.scan", "jnp.float64", "partial"); "" when not name-like."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _is_trace_wrapper(expr: ast.AST) -> bool:
    """Does this expression evaluate to a tracing transform? Matches bare
    names/attributes (jax.jit, shard_map) and partial(jax.jit, ...)."""
    name = dotted_name(expr)
    if name and name.split(".")[-1] in TRACE_WRAPPERS:
        return True
    if isinstance(expr, ast.Call):
        fn = dotted_name(expr.func)
        if fn.split(".")[-1] in TRACE_WRAPPERS:
            return True
        if fn.split(".")[-1] == "partial" and expr.args:
            return _is_trace_wrapper(expr.args[0])
    return False


def _wrapped_function_names(call: ast.Call) -> List[str]:
    """For a call to a tracing transform, the simple names of the function
    operands it traces (jax.jit(f), lax.while_loop(cond, body, ...))."""
    fn = dotted_name(call.func)
    tail = fn.split(".")[-1]
    out: List[str] = []
    if tail in TRACE_WRAPPERS:
        for arg in call.args[:1]:  # the transformed function
            out.extend(_name_operands(arg))
    elif tail in TRACE_LAX:
        # every positional that looks like a function reference: lax
        # control flow takes (cond, body) / (pred, true_fn, false_fn) /
        # (f, init, xs) shapes — names beyond the first few are operands,
        # but resolving a data operand to a def is harmless (it IS that
        # function being traced if the name matches a def)
        for arg in call.args:
            out.extend(_name_operands(arg))
    elif tail == "partial" and call.args and _is_trace_wrapper(call.args[0]):
        for arg in call.args[1:2]:
            out.extend(_name_operands(arg))
    return out


def _name_operands(node: ast.AST) -> List[str]:
    if isinstance(node, ast.Name):
        return [node.id]
    if isinstance(node, ast.Call):  # jax.jit(vmap(f)) / partial(f, ...)
        inner = dotted_name(node.func)
        out = []
        if inner.split(".")[-1] in TRACE_WRAPPERS | {"partial"}:
            for a in node.args:
                out.extend(_name_operands(a))
        return out
    return []


def decorator_traces(dec: ast.AST) -> bool:
    return _is_trace_wrapper(dec)


def local_bindings(fn: ast.AST) -> Set[str]:
    """Names bound inside this function body: params, assignment/loop/
    with/walrus targets, nested defs, imports. Used both for call-graph
    resolution (a locally-bound name shadows any same-named def) and by
    JX005 (mutating a local is not a side-effect hazard)."""
    out: Set[str] = set()
    a = fn.args
    for p in a.posonlyargs + a.args + a.kwonlyargs:
        out.add(p.arg)
    if a.vararg:
        out.add(a.vararg.arg)
    if a.kwarg:
        out.add(a.kwarg.arg)
    for node in ast.walk(fn):
        if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            targets = (node.targets
                       if isinstance(node, ast.Assign) else [node.target])
            for t in targets:
                for n in ast.walk(t):
                    if isinstance(n, ast.Name):
                        out.add(n.id)
        elif isinstance(node, (ast.For, ast.comprehension)):
            for n in ast.walk(node.target):
                if isinstance(n, ast.Name):
                    out.add(n.id)
        elif isinstance(node, ast.withitem) and node.optional_vars:
            for n in ast.walk(node.optional_vars):
                if isinstance(n, ast.Name):
                    out.add(n.id)
        elif isinstance(node, ast.NamedExpr):
            out.add(node.target.id)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)) and node is not fn:
            out.add(node.name)
        elif isinstance(node, (ast.Import, ast.ImportFrom)):
            for alias in node.names:
                out.add((alias.asname or alias.name).split(".")[0])
    return out


class PackageContext:
    """Cross-file view: defs, classes, trace roots, reachability.

    The call graph is deliberately lightweight (the issue's "lightweight
    intra-package call graph"): a traced function's *name references* are
    resolved module-locally first, then package-wide when the name is
    unique; `self.method()` resolves within the enclosing class. That is
    enough to follow the codebase's idiom (closures named after the defs
    they capture) without a full type analysis.
    """

    def __init__(self, modules: Sequence[Module]) -> None:
        self.modules = list(modules)
        # def name -> nodes, per module and package-wide
        self._defs_by_module: Dict[Module, Dict[str, List[ast.AST]]] = {}
        self._defs_global: Dict[str, List[ast.AST]] = {}
        self._module_of: Dict[ast.AST, Module] = {}
        self._class_methods: Dict[Module, Dict[str, List[ast.AST]]] = {}
        for m in self.modules:
            local: Dict[str, List[ast.AST]] = {}
            classes: Dict[str, List[ast.AST]] = {}
            for node in ast.walk(m.tree):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    local.setdefault(node.name, []).append(node)
                    self._defs_global.setdefault(node.name, []).append(node)
                    self._module_of[node] = m
                elif isinstance(node, ast.ClassDef):
                    classes[node.name] = [
                        c for c in node.body
                        if isinstance(c, (ast.FunctionDef,
                                          ast.AsyncFunctionDef))]
            self._defs_by_module[m] = local
            self._class_methods[m] = classes
        self.traced: Set[ast.AST] = set()
        self.traced_via: Dict[ast.AST, str] = {}
        self._mark_traced()

    # -- trace roots + propagation --
    def _mark_traced(self) -> None:
        work: List[ast.AST] = []

        def add(node: ast.AST, via: str) -> None:
            if node not in self.traced:
                self.traced.add(node)
                self.traced_via[node] = via
                work.append(node)

        for m in self.modules:
            local = self._defs_by_module[m]
            for node in ast.walk(m.tree):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    for dec in node.decorator_list:
                        if decorator_traces(dec):
                            add(node, f"@{dotted_name(dec) or 'jit'}")
                elif isinstance(node, ast.Call) and (
                        _is_trace_wrapper(node.func)
                        or dotted_name(node.func).split(".")[-1]
                        in TRACE_LAX):
                    for name in _wrapped_function_names(node):
                        for target in local.get(name, []):
                            add(target,
                                f"passed to {dotted_name(node.func)}")

        while work:
            fn = work.pop()
            m = self._module_of.get(fn)
            if m is None:
                continue
            via = f"called from traced `{getattr(fn, 'name', '?')}`"
            for target in self._referenced_defs(m, fn):
                add(target, via)

    def _referenced_defs(self, m: Module, fn: ast.AST) -> List[ast.AST]:
        """Defs this function's body references by name. A name bound
        LOCALLY in `fn` shadows same-named defs (a `key = fold_in(...)`
        variable must not mark an unrelated `def key`). Module-local defs
        resolve on any load (closures are named after the defs they
        capture); package-wide resolution is reserved for *called* names
        with a unique match — bare variable names like `depth`/`active`
        collide across files far too often."""
        local = self._defs_by_module[m]
        bound = local_bindings(fn)
        out: List[ast.AST] = []
        own_class = None
        for anc in m.ancestors(fn):
            if isinstance(anc, ast.ClassDef):
                own_class = anc.name
                break
        for node in ast.walk(fn):
            if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
                if node.id in bound:
                    continue
                hits = local.get(node.id)
                if not hits:
                    parent = m.parent.get(node)
                    called = (isinstance(parent, ast.Call)
                              and parent.func is node)
                    g = self._defs_global.get(node.id, [])
                    hits = g if called and len(g) == 1 else []
                out.extend(h for h in hits if h is not fn)
            elif (isinstance(node, ast.Call)
                  and isinstance(node.func, ast.Attribute)
                  and isinstance(node.func.value, ast.Name)
                  and node.func.value.id == "self" and own_class):
                for meth in self._class_methods[m].get(own_class, []):
                    if meth.name == node.func.attr and meth is not fn:
                        out.append(meth)
        return out

    # -- generic reachability (the traced-set machinery, reusable for
    # other root kinds: rules/concurrency.py seeds THREAD roots the way
    # _mark_traced seeds jit roots) --
    def reachable(self, roots: Dict[ast.AST, str]
                  ) -> Dict[ast.AST, str]:
        """Transitive closure of defs referenced from `roots` through
        the same conservative resolution the traced set uses. Returns
        {def_node: why}."""
        out: Dict[ast.AST, str] = {}
        work: List[ast.AST] = []
        for node, via in roots.items():
            if node not in out:
                out[node] = via
                work.append(node)
        while work:
            fn = work.pop()
            m = self._module_of.get(fn)
            if m is None:
                continue
            via = f"called from `{getattr(fn, 'name', '?')}`"
            for target in self._referenced_defs(m, fn):
                if target not in out:
                    out[target] = via
                    work.append(target)
        return out

    def module_of(self, fn: ast.AST) -> Optional[Module]:
        return self._module_of.get(fn)

    def defs_named(self, m: Module, name: str) -> List[ast.AST]:
        """Module-local defs with this simple name (for root seeding)."""
        return list(self._defs_by_module[m].get(name, []))

    def class_methods(self, m: Module, class_name: str) -> List[ast.AST]:
        return list(self._class_methods[m].get(class_name, []))

    # -- public queries --
    def node_traced(self, m: Module, node: ast.AST) -> bool:
        """True when `node` executes under a jax tracer: its nearest
        enclosing def is in the traced set."""
        fn = node if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef)
        ) else m.enclosing_function(node)
        return fn is not None and fn in self.traced

    def trace_reason(self, m: Module, node: ast.AST) -> str:
        fn = node if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef)
        ) else m.enclosing_function(node)
        if fn is None:
            return ""
        name = getattr(fn, "name", "?")
        return f"`{name}` is traced ({self.traced_via.get(fn, '?')})"

    def reference_closure(self, m: Module, fn: ast.AST) -> Set[str]:
        """All simple names transitively referenced from `fn` through
        module-local defs and classes (SH103's plumbing check)."""
        seen_defs: Set[ast.AST] = set()
        names: Set[str] = set()
        classes = self._class_methods[m]
        work = [fn]
        while work:
            cur = work.pop()
            if cur in seen_defs:
                continue
            seen_defs.add(cur)
            for node in ast.walk(cur):
                if isinstance(node, ast.Name) and isinstance(
                        node.ctx, ast.Load):
                    names.add(node.id)
                    for target in self._defs_by_module[m].get(node.id, []):
                        work.append(target)
                    for meth in classes.get(node.id, []):
                        work.append(meth)
                elif isinstance(node, ast.Attribute):
                    names.add(node.attr)
        return names


# ---------------------------------------------------------------------------
# rule registry
# ---------------------------------------------------------------------------


class Rule:
    id: str = ""
    severity: str = "error"
    summary: str = ""

    def check(self, module: Module,
              ctx: PackageContext) -> Iterator[Finding]:  # pragma: no cover
        raise NotImplementedError

    def finding(self, module: Module, node: ast.AST, message: str,
                severity: Optional[str] = None) -> Finding:
        return Finding(
            rule=self.id,
            severity=severity or self.severity,
            path=module.path,
            line=getattr(node, "lineno", 1),
            col=getattr(node, "col_offset", 0) + 1,
            message=message,
        )


_REGISTRY: Dict[str, Rule] = {}


def register(cls):
    rule = cls()
    assert rule.id and rule.id not in _REGISTRY, rule.id
    _REGISTRY[rule.id] = rule
    return cls


def all_rules() -> Dict[str, Rule]:
    # import for side effect: rule modules self-register
    from shifu_tpu.analysis.rules import (  # noqa: F401
        concurrency,
        hygiene,
        jaxrules,
        spmd,
    )

    return dict(_REGISTRY)


# ---------------------------------------------------------------------------
# running + reporting
# ---------------------------------------------------------------------------


def _iter_py_files(paths: Sequence[str]) -> Iterator[str]:
    for path in paths:
        if os.path.isfile(path):
            yield path
        elif os.path.isdir(path):
            for dirpath, dirnames, filenames in os.walk(path):
                dirnames[:] = sorted(
                    d for d in dirnames
                    if d != "__pycache__" and not d.startswith("."))
                for name in sorted(filenames):
                    if name.endswith(".py"):
                        yield os.path.join(dirpath, name)
        else:
            raise FileNotFoundError(path)


def _suppressed(module: Module, finding: Finding) -> bool:
    m = _NOQA_RE.search(module.line_text(finding.line))
    if not m:
        return False
    rules = m.group("rules")
    if rules is None:
        return True
    return finding.rule in {r.strip() for r in rules.split(",")}


def analyze(paths: Sequence[str],
            rule_ids: Optional[Iterable[str]] = None) -> List[Finding]:
    """Run the (selected) rules over every .py under `paths`. Findings
    come back sorted, with noqa'd ones marked suppressed (not dropped —
    reporters show suppression counts so a silent noqa sweep is visible
    in review)."""
    rules = all_rules()
    if rule_ids is not None:
        wanted = [r.strip() for r in rule_ids if r.strip()]
        unknown = [r for r in wanted if r not in rules]
        if unknown:
            raise ValueError(
                f"unknown rule(s): {', '.join(unknown)} "
                f"(known: {', '.join(sorted(rules))})")
        rules = {rid: rules[rid] for rid in wanted}

    modules: List[Module] = []
    findings: List[Finding] = []
    for path in _iter_py_files(paths):
        try:
            with open(path, encoding="utf-8") as fh:
                modules.append(Module(path, fh.read()))
        except (SyntaxError, UnicodeDecodeError, OSError) as e:
            findings.append(Finding(
                rule="PARSE", severity="error", path=path,
                line=getattr(e, "lineno", None) or 1, col=1,
                message=f"cannot analyze: {type(e).__name__}: {e}"))

    ctx = PackageContext(modules)
    for rule in rules.values():
        for module in modules:
            findings.extend(rule.check(module, ctx))
    for f in findings:
        for module in modules:
            if module.path == f.path:
                f.suppressed = _suppressed(module, f)
                break
    findings.sort(key=Finding.sort_key)
    return findings


def counts(findings: Sequence[Finding]) -> Dict[str, int]:
    out = {"error": 0, "warning": 0, "suppressed": 0, "baselined": 0}
    for f in findings:
        if f.suppressed:
            out["suppressed"] += 1
        elif f.baselined:
            out["baselined"] += 1
        else:
            out[f.severity] = out.get(f.severity, 0) + 1
    return out


def report_human(findings: Sequence[Finding]) -> str:
    lines = []
    for f in findings:
        if f.suppressed or f.baselined:
            continue
        lines.append(f"{f.path}:{f.line}:{f.col}: "
                     f"{f.rule} {f.severity}: {f.message}")
    c = counts(findings)
    lines.append(
        f"shifu check: {c['error']} error(s), {c['warning']} warning(s), "
        f"{c['suppressed']} suppressed, {c['baselined']} baselined")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# findings baseline: land a new rule family at `error` severity while the
# pre-existing findings burn down incrementally. Baselined findings are
# counted, reported, and excluded from the exit gate — the exact noqa
# contract, but owned by a reviewed file instead of inline pragmas.
# ---------------------------------------------------------------------------

BASELINE_SCHEMA = "shifu.baseline/1"


def write_baseline(findings: Sequence[Finding], path: str) -> int:
    """Write the sorted, content-addressed baseline of every unsuppressed
    finding; returns how many entries it recorded."""
    entries = sorted(
        {f.baseline_key(): {"key": f.baseline_key(), "rule": f.rule,
                            "path": f.path}
         for f in findings if not f.suppressed}.values(),
        key=lambda e: (e["rule"], e["path"], e["key"]))
    doc = {"schema": BASELINE_SCHEMA, "findings": entries}
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, indent=2, sort_keys=True)
        fh.write("\n")
    return len(entries)


def load_baseline(path: str) -> Set[str]:
    with open(path, encoding="utf-8") as fh:
        doc = json.load(fh)
    if doc.get("schema") != BASELINE_SCHEMA:
        raise ValueError(
            f"{path}: not a {BASELINE_SCHEMA} document "
            f"(schema={doc.get('schema')!r})")
    return {e["key"] for e in doc.get("findings", [])}


def apply_baseline(findings: Sequence[Finding], keys: Set[str]) -> None:
    """Mark known findings baselined (counted-not-dropped, like noqa).
    Suppressed findings stay suppressed — noqa wins the accounting."""
    for f in findings:
        if not f.suppressed and f.baseline_key() in keys:
            f.baselined = True


def report_json(findings: Sequence[Finding],
                rule_ids: Optional[Iterable[str]] = None) -> str:
    rules = all_rules()
    doc = {
        "schema": SCHEMA,
        "counts": counts(findings),
        "findings": [f.as_dict() for f in findings],
        "rules": {
            rid: {"severity": r.severity, "summary": r.summary}
            for rid, r in sorted(rules.items())
            if rule_ids is None or rid in set(rule_ids)
        },
    }
    return json.dumps(doc, indent=2, sort_keys=True)


SARIF_VERSION = "2.1.0"
SARIF_SCHEMA_URI = ("https://raw.githubusercontent.com/oasis-tcs/"
                    "sarif-spec/master/Schemata/sarif-schema-2.1.0.json")


def report_sarif(findings: Sequence[Finding],
                 rule_ids: Optional[Iterable[str]] = None) -> str:
    """Minimal SARIF 2.1.0 log (stdlib-only): one run, the selected rule
    catalog under tool.driver.rules, one result per unsuppressed and
    unbaselined finding. Suppressed/baselined findings are carried as
    results with a `suppressions` entry so viewers show them greyed-out
    rather than losing them (counted-not-dropped, same as the human and
    JSON reports)."""
    rules = all_rules()
    selected = sorted(rid for rid in rules
                      if rule_ids is None or rid in set(rule_ids))
    index = {rid: i for i, rid in enumerate(selected)}
    results = []
    for f in sorted(findings, key=Finding.sort_key):
        result = {
            "ruleId": f.rule,
            "ruleIndex": index.get(f.rule, -1),
            "level": f.severity,
            "message": {"text": f.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": f.path.replace(os.sep, "/")},
                    "region": {"startLine": f.line,
                               "startColumn": f.col + 1},
                },
            }],
        }
        if f.suppressed:
            result["suppressions"] = [{"kind": "inSource"}]
        elif f.baselined:
            result["suppressions"] = [{"kind": "external"}]
        results.append(result)
    doc = {
        "$schema": SARIF_SCHEMA_URI,
        "version": SARIF_VERSION,
        "runs": [{
            "tool": {"driver": {
                "name": "shifu check",
                "informationUri":
                    "https://github.com/shifu-tpu/shifu-tpu",
                "rules": [{
                    "id": rid,
                    "shortDescription": {"text": rules[rid].summary},
                    "defaultConfiguration":
                        {"level": rules[rid].severity},
                } for rid in selected],
            }},
            "results": results,
        }],
    }
    return json.dumps(doc, indent=2, sort_keys=True)


def run_check(paths: Sequence[str], rule_ids: Optional[List[str]] = None,
              as_json: bool = False, emit=print, fmt: Optional[str] = None,
              baseline: Optional[str] = None,
              write_baseline_to: Optional[str] = None) -> int:
    """CLI entry: analyze, report, exit 1 on unsuppressed (and
    unbaselined) errors. `fmt` is "human"/"json"/"sarif" (`as_json` is
    the pre-SARIF spelling of fmt="json" and loses to an explicit fmt);
    `baseline` marks known findings; `write_baseline_to` records the
    current findings and exits clean (the baseline IS the verdict)."""
    if rule_ids is not None:  # normalize ONCE so the JSON rules catalog
        # and the analyze() selection agree on e.g. "JX001, SH101"
        rule_ids = [r.strip() for r in rule_ids if r.strip()]
    if fmt is None:
        fmt = "json" if as_json else "human"
    if fmt not in ("human", "json", "sarif"):
        raise ValueError(f"unknown report format {fmt!r}")
    findings = analyze(paths, rule_ids)
    if write_baseline_to is not None:
        n = write_baseline(findings, write_baseline_to)
        emit(f"shifu check: wrote {n} baseline entr"
             f"{'y' if n == 1 else 'ies'} to {write_baseline_to}")
        return 0
    if baseline is not None:
        apply_baseline(findings, load_baseline(baseline))
    if fmt == "json":
        emit(report_json(findings, rule_ids))
    elif fmt == "sarif":
        emit(report_sarif(findings, rule_ids))
    else:
        emit(report_human(findings))
    return 1 if counts(findings)["error"] else 0
