"""Chaos soak: two real `shifu serve` processes on one model set, race
sanitizer armed, one SIGKILLed around a fleet-atomic promotion.

The satellite acceptance: the round ABORTS with every survivor rolled
back to active (a half-promoted fleet is impossible), the survivor
stays `ok`-serving and reports the dead peer's lease expiry within
2 x TTL, the expiry is counted, and a RE-RUN promote (now fencing only
the survivor) succeeds — manifests sha-consistent throughout.

The victim is SIGKILLed while its lease is still live, immediately
before the coordinator prepares the round — from the protocol's view
the death is mid-round (the prepare fences the fresh lease, the ack
never comes, the deadline aborts). Killing after the ack instead would
legitimately commit (a dead-but-acked peer restarts into the new models
dir), so this is the timing that must prove the abort path, and it is
deterministic."""

import json
import os
import signal
import subprocess
import sys
import time
import urllib.request

import numpy as np

TTL_MS = 1500


def _http(url, timeout=10):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return json.loads(r.read().decode())


def _write_model_set(models_dir, seed=0, bias=0.0):
    from shifu_tpu.models.nn import NNModelSpec, init_params

    os.makedirs(models_dir, exist_ok=True)
    cols = [f"c{i}" for i in range(4)]
    sizes = [len(cols), 3, 1]
    specs = [{"name": c, "kind": "value", "outNames": [c],
              "mean": 0.0, "std": 1.0, "fill": 0.0, "zscore": True}
             for c in cols]
    params = init_params(sizes, seed=seed)
    if bias:
        params[-1]["b"] = np.asarray(params[-1]["b"]) + bias
    NNModelSpec(layer_sizes=sizes, activations=["tanh"],
                input_columns=cols, norm_specs=specs, params=params,
                ).save(os.path.join(models_dir, "model0.nn"))
    return cols


def _spawn_server(root):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    proc = subprocess.Popen(
        [sys.executable, "-m", "shifu_tpu", "serve", "--port", "0",
         "--replicas", "1",
         f"-Dshifu.lease.ttlMs={TTL_MS}",
         "-Dshifu.sanitize=race"],
        cwd=root, env=env, stdout=subprocess.PIPE,
        stderr=open(os.path.join(root, f"peer-{time.time_ns()}.err"), "w"), text=True)
    line = ""
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline:
        line = proc.stdout.readline()
        if "listening on" in line:
            break
        if proc.poll() is not None:
            raise AssertionError(f"server died at startup: {line!r}")
    port = int(line.split(":")[-1].split()[0])
    return proc, port


def test_sigkill_mid_promotion_never_half_promotes(tmp_path):
    from shifu_tpu.loop.promote import run_promote
    from shifu_tpu.resilience import lease

    root = str(tmp_path)
    _write_model_set(os.path.join(root, "models"), seed=0)
    _write_model_set(os.path.join(root, "models.candidate"), seed=0,
                     bias=1e-3)
    victim = survivor = None
    try:
        victim, victim_port = _spawn_server(root)
        survivor, survivor_port = _spawn_server(root)
        # both processes hold live leases and see each other
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            h = _http(f"http://127.0.0.1:{survivor_port}/healthz")
            if (h.get("peers", {}).get("liveProcesses") == 2
                    and not h["peers"]["expiredProcesses"]):
                break
            time.sleep(0.1)
        else:
            raise AssertionError(f"peers never met: {h.get('peers')}")
        old_sha = h["sha"]
        assert len(lease.scan(root)) == 2

        # SIGKILL the victim: its lease stays live (renewed moments
        # ago), so the promote below fences a corpse — the ack never
        # comes and the round must abort with the survivor rolled back
        os.kill(victim.pid, signal.SIGKILL)
        victim.wait(10)
        rc = run_promote(root, os.path.join(root, "models.candidate"),
                         require_drift=False)
        assert rc == 1  # held: the round aborted

        # promote manifest: fleet mode, aborted round, sha-consistent
        # (the coordinator also leaves promote-<seq>.traces.json beside
        # the manifest — the round's trace spans, not a manifest)
        promotes = sorted(
            p for p in os.listdir(os.path.join(root, ".shifu", "runs"))
            if p.startswith("promote-")
            and not p.endswith(".traces.json"))
        m = json.load(open(os.path.join(root, ".shifu", "runs",
                                        promotes[-1])))["promote"]
        assert m["mode"] == "fleet"
        assert not m["decision"]["promote"]
        assert not m["round"]["committed"]
        assert "no ack" in m["round"]["reason"]

        # the survivor is NOT half-promoted: still serving the old sha,
        # still ok-scoring, its staged candidate rolled back
        h = _http(f"http://127.0.0.1:{survivor_port}/healthz")
        assert h["sha"] == old_sha
        # ...and within 2 x TTL it reports the dead peer's expiry as a
        # degrade reason, with the expiry counted on /metrics
        deadline = time.monotonic() + 2 * TTL_MS / 1000.0 + 5
        while time.monotonic() < deadline:
            h = _http(f"http://127.0.0.1:{survivor_port}/healthz")
            if h["peers"]["expiredProcesses"] == 1:
                break
            time.sleep(0.1)
        assert h["peers"]["expiredProcesses"] == 1, h["peers"]
        assert h["status"] == "degraded"
        assert "lease" in h["reason"] and "expired" in h["reason"]
        metrics = urllib.request.urlopen(
            f"http://127.0.0.1:{survivor_port}/metrics",
            timeout=10).read().decode()
        assert "peer_lease_expired_total 1" in metrics
        # the rollback: the survivor's verdict poll runs on its
        # heartbeat thread, so under load the unstage can land a few
        # beats after the abort record — poll for it
        deadline = time.monotonic() + 30
        while ("serve_swap_unstaged_total" not in metrics
               and time.monotonic() < deadline):
            time.sleep(0.1)
            metrics = urllib.request.urlopen(
                f"http://127.0.0.1:{survivor_port}/metrics",
                timeout=10).read().decode()
        assert "serve_swap_unstaged_total" in metrics  # rolled back

        # re-run: the corpse's lease has expired out of the fence set,
        # the survivor acks, the round commits, the dir swap lands
        rc = run_promote(root, os.path.join(root, "models.candidate"),
                         require_drift=False)
        assert rc == 0
        deadline = time.monotonic() + 30
        new_sha = old_sha
        while time.monotonic() < deadline:
            h = _http(f"http://127.0.0.1:{survivor_port}/healthz")
            new_sha = h["sha"]
            if new_sha != old_sha:
                break
            time.sleep(0.1)
        assert new_sha != old_sha
        promotes = sorted(
            p for p in os.listdir(os.path.join(root, ".shifu", "runs"))
            if p.startswith("promote-")
            and not p.endswith(".traces.json"))
        m2 = json.load(open(os.path.join(root, ".shifu", "runs",
                                         promotes[-1])))["promote"]
        assert m2["round"]["committed"]
        assert m2["swap"]["mode"] == "fleet"
        # the on-disk models dir now IS the promoted candidate: a
        # restarted process loads the same sha the survivor serves
        from shifu_tpu.loop.promote import _models_sha

        assert _models_sha(os.path.join(root, "models")) == new_sha

        # clean shutdown: the survivor's manifest carries a clean race
        # verdict (all new lease/peers/breaker locks are tracked) and
        # its lease is RELEASED, not expired
        survivor.send_signal(signal.SIGTERM)
        survivor.wait(60)
        survivor = None
        serve_manifests = sorted(
            p for p in os.listdir(os.path.join(root, ".shifu", "runs"))
            if p.startswith("serve-") and p.endswith(".json")
            and ".traces" not in p)
        sm = json.load(open(os.path.join(root, ".shifu", "runs",
                                         serve_manifests[-1])))
        race = sm["sanitizer"]["race"]
        assert race["armed"] and race["inversions"] == 0, race
        assert race["guardViolations"] == 0, race
        assert sm["peers"]["enabled"]
        live = [p for p in lease.scan(root) if not p["expired"]]
        assert live == []
    finally:
        for proc in (victim, survivor):
            if proc is not None and proc.poll() is None:
                proc.kill()
                proc.wait(10)
