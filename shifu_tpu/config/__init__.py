"""Configuration objects: ModelConfig / ColumnConfig and their validation.

JSON wire format is compatible with the reference's Jackson POJOs
(container/obj/ModelConfig.java:57, container/obj/ColumnConfig.java:35) so that
model sets created by the reference load verbatim.
"""

from shifu_tpu.config.model_config import (  # noqa: F401
    Algorithm,
    BinningMethod,
    EvalConfig,
    ModelBasicConf,
    ModelConfig,
    ModelNormalizeConf,
    ModelSourceDataConf,
    ModelStatsConf,
    ModelTrainConf,
    ModelVarSelectConf,
    NormType,
    RunMode,
)
from shifu_tpu.config.column_config import (  # noqa: F401
    ColumnBinning,
    ColumnConfig,
    ColumnFlag,
    ColumnStats,
    ColumnType,
    load_column_config_list,
    save_column_config_list,
)
