"""Column statistics engine: binning, KS/IV/WOE, correlation, PSI.

The reference computes these with two Hadoop jobs (Pig SPDT histogram pass +
UpdateBinningInfo MR pass, core/processor/stats/MapReducerStatsWorker.java:105).
Here: bin boundaries from exact columnar quantiles, then ONE jit-compiled
aggregation over a dense [rows, cols] bin-code matrix — shardable over the
device mesh with psum for the multi-chip path.
"""
