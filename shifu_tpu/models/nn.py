"""MLP model: functional forward pass, weight init, and the .nn model spec.

Replaces the reference's Encog network stack (core/dtrain/dataset/
BasicFloatNetwork + FloatFlatNetwork flat-weight forward,
DTrainUtils.generateNetwork:? network builder) and its two serializers
(PersistBasicFloatNetwork EGB, nn/BinaryNNSerializer.java:44). Model math is
pure jax over a {W_i, b_i} pytree; the on-disk spec is a self-describing
binary (JSON header + raw float32 weights) loadable by IndependentNNModel
with zero pipeline dependencies (parity target: nn/IndependentNNModel.java:58).

Supported activations (nn/Activation*.java + wdl/activation/*): sigmoid,
tanh, relu, leakyrelu, swish, ptanh (LeCun scaled tanh), linear, log,
gaussian.
"""

from __future__ import annotations

import io
import json
import os
import struct
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

MAGIC = b"STNN"
FORMAT_VERSION = 1


def activation_fn(name: str) -> Callable:
    import jax.numpy as jnp

    name = (name or "sigmoid").lower()
    if name in ("sigmoid", "logistic"):
        return lambda x: 1.0 / (1.0 + jnp.exp(-x))
    if name == "tanh":
        return jnp.tanh
    if name == "relu":
        return lambda x: jnp.maximum(x, 0.0)
    if name in ("leakyrelu", "leaky_relu"):
        return lambda x: jnp.where(x > 0, x, 0.01 * x)
    if name == "swish":
        return lambda x: x / (1.0 + jnp.exp(-x))
    if name == "ptanh":  # LeCun scaled tanh (ActivationPTANH)
        return lambda x: 1.7159 * jnp.tanh(x * 2.0 / 3.0)
    if name == "linear":
        return lambda x: x
    if name == "log":
        return lambda x: jnp.sign(x) * jnp.log1p(jnp.abs(x))
    if name == "gaussian":
        return lambda x: jnp.exp(-(x * x))
    raise ValueError(f"unknown activation: {name}")


def init_params(
    layer_sizes: Sequence[int],
    seed: int = 0,
    init: str = "xavier",
) -> List[Dict[str, np.ndarray]]:
    """[{W: [in, out], b: [out]}] — Xavier/He/Lecun/Gaussian randomizers
    (core/dtrain/random/*, 9 files)."""
    rng = np.random.default_rng(seed)
    params = []
    for fan_in, fan_out in zip(layer_sizes[:-1], layer_sizes[1:]):
        if init == "xavier":
            limit = np.sqrt(6.0 / (fan_in + fan_out))
            w = rng.uniform(-limit, limit, size=(fan_in, fan_out))
        elif init == "he":
            w = rng.normal(0.0, np.sqrt(2.0 / fan_in), size=(fan_in, fan_out))
        elif init == "lecun":
            w = rng.normal(0.0, np.sqrt(1.0 / fan_in), size=(fan_in, fan_out))
        else:  # gaussian
            w = rng.normal(0.0, 1.0, size=(fan_in, fan_out))
        params.append(
            {"W": w.astype(np.float32), "b": np.zeros(fan_out, dtype=np.float32)}
        )
    return params


def forward(params, x, activations: Sequence[str], out_activation: str = "sigmoid"):
    """x: [..., n_in] -> [..., n_out]. Hidden activations per layer; output
    layer sigmoid for binary regression-mode scoring (reference networks end
    in sigmoid — DTrainUtils.generateNetwork output ActivationSigmoid)."""
    h = x
    n_hidden = len(params) - 1
    for i in range(n_hidden):
        h = activation_fn(activations[i % len(activations)] if activations else "tanh")(
            h @ params[i]["W"] + params[i]["b"]
        )
    out = h @ params[-1]["W"] + params[-1]["b"]
    return activation_fn(out_activation)(out)


def flatten_params(params) -> Tuple[np.ndarray, List[Tuple[int, int]]]:
    """Pytree -> flat vector + layer shapes (Weight.java operates flat)."""
    chunks, shapes = [], []
    for layer in params:
        shapes.append(layer["W"].shape)
        chunks.append(np.asarray(layer["W"]).ravel())
        chunks.append(np.asarray(layer["b"]).ravel())
    return np.concatenate(chunks), shapes


def unflatten_params(flat: np.ndarray, shapes: List[Tuple[int, int]]):
    params, off = [], 0
    for (fi, fo) in shapes:
        w = flat[off : off + fi * fo].reshape(fi, fo)
        off += fi * fo
        b = flat[off : off + fo]
        off += fo
        params.append({"W": np.asarray(w), "b": np.asarray(b)})
    return params


# ---------------------------------------------------------------------------
# Model spec (.nn)
# ---------------------------------------------------------------------------


@dataclass
class NNModelSpec:
    """Self-contained scoring spec: columns + norm info + weights.

    The reference's BinaryNNSerializer embeds per-column stats (NNColumnStats)
    so IndependentNNModel can normalize raw input itself; we do the same via
    a JSON header carrying the per-column norm plan summary."""

    layer_sizes: List[int]
    activations: List[str]
    out_activation: str = "sigmoid"
    input_columns: List[str] = field(default_factory=list)
    norm_type: str = "ZSCALE"
    algorithm: str = "NN"
    loss: str = "squared"
    # per-input-column normalization tables, mirrored from the NormPlan so the
    # independent model can score RAW records: list of dicts
    #   {name, kind: value|table|onehot, fill, mean, std, cutoff, table,
    #    boundaries | categories}
    norm_specs: List[Dict[str, Any]] = field(default_factory=list)
    norm_cutoff: float = 4.0
    params: Optional[List[Dict[str, np.ndarray]]] = None
    train_error: Optional[float] = None
    valid_error: Optional[float] = None
    # multi-class: the ordered tag list (flattened posTags+negTags); output k
    # scores class_tags[k]. Empty = binary regression model.
    class_tags: List[str] = field(default_factory=list)

    @property
    def out_dim(self) -> int:
        return int(self.layer_sizes[-1]) if self.layer_sizes else 1

    def header(self) -> dict:
        return {
            "formatVersion": FORMAT_VERSION,
            "algorithm": self.algorithm,
            "layerSizes": self.layer_sizes,
            "activations": self.activations,
            "outActivation": self.out_activation,
            "inputColumns": self.input_columns,
            "normType": self.norm_type,
            "loss": self.loss,
            "normSpecs": self.norm_specs,
            "normCutoff": self.norm_cutoff,
            "trainError": self.train_error,
            "validError": self.valid_error,
            "classTags": self.class_tags,
        }

    def save(self, path: str) -> None:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        flat, shapes = flatten_params(self.params)
        head = self.header()
        head["layerShapes"] = [list(s) for s in shapes]
        head_bytes = json.dumps(head).encode("utf-8")
        with open(path, "wb") as fh:
            fh.write(MAGIC)
            fh.write(struct.pack("<I", len(head_bytes)))
            fh.write(head_bytes)
            fh.write(flat.astype("<f4").tobytes())

    @classmethod
    def load(cls, path: str) -> "NNModelSpec":
        with open(path, "rb") as fh:
            data = fh.read()
        if data[:4] != MAGIC:
            raise ValueError(f"{path}: not a shifu-tpu .nn model")
        (hlen,) = struct.unpack("<I", data[4:8])
        head = json.loads(data[8 : 8 + hlen].decode("utf-8"))
        flat = np.frombuffer(data[8 + hlen :], dtype="<f4")
        shapes = [tuple(s) for s in head["layerShapes"]]
        spec = cls(
            layer_sizes=head["layerSizes"],
            activations=head["activations"],
            out_activation=head.get("outActivation", "sigmoid"),
            input_columns=head.get("inputColumns", []),
            norm_type=head.get("normType", "ZSCALE"),
            algorithm=head.get("algorithm", "NN"),
            loss=head.get("loss", "squared"),
            norm_specs=head.get("normSpecs", []),
            norm_cutoff=float(head.get("normCutoff", 4.0)),
            train_error=head.get("trainError"),
            valid_error=head.get("validError"),
            class_tags=head.get("classTags", []),
        )
        spec.params = unflatten_params(flat.copy(), shapes)
        return spec


class IndependentNNModel:
    """Zero-dependency scorer over NORMALIZED input vectors; raw-record
    scoring happens through shifu_tpu.eval.scorer which owns the norm plan.
    Parity anchor: nn/IndependentNNModel.java:58."""

    def __init__(self, spec: NNModelSpec):
        self.spec = spec
        self._fwd = None  # jitted forward, created once per model

    @classmethod
    def load(cls, path: str) -> "IndependentNNModel":
        return cls(NNModelSpec.load(path))

    def compute(self, x: np.ndarray) -> np.ndarray:
        """x: [n, n_in] normalized features -> [n] score (first output)."""
        out = self.compute_all(x)
        return out[:, 0] if out.ndim == 2 else out

    def compute_all(self, x: np.ndarray) -> np.ndarray:
        """All output neurons: [n, n_out] — multi-class NATIVE models emit
        one score per class (IndependentNNModel.compute returns the full
        output vector in the reference too)."""
        h = np.asarray(x, dtype=np.float32)
        if self._fwd is None:
            import jax

            from shifu_tpu.obs import profile

            self._fwd = profile.wrap("nn.forward", jax.jit(
                lambda inp: forward(
                    self.spec.params, inp, self.spec.activations,
                    self.spec.out_activation,
                )
            ), sync=True)
        return np.asarray(self._fwd(h))
