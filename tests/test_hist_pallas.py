"""Fused Pallas histogram→split-scan kernel vs the XLA references
(interpret mode on CPU; on TPU the same kernels compile via Mosaic — see
ops/hist_pallas.py for the lane-aligned layout and precision policy).

Covers the PR-11 acceptance matrix: hist parity vs the scatter
reference, in-kernel split scan == the reference split_scan on
ragged/wide layouts (33/65-wide segments, multi-chunk wide features),
RF forest BIT-parity kernel on vs off (binary + NATIVE multiclass),
GBT tolerance parity level- and leaf-wise, int8-code/bf16-plane bounds,
histogram-subtraction composition (built ratio still <= 0.55), and the
-Dshifu.pallas.* knob surface.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from shifu_tpu.ops.hist_pallas import (  # noqa: E402
    _chunks,
    make_codes8_fn,
    make_fused_level_fn,
    make_pallas_hist_fn,
    pallas_active,
    wide_features,
)
from shifu_tpu.train.tree_trainer import (  # noqa: E402
    TreeTrainConfig,
    _device_layout,
    _make_hist_fn,
    _make_scan_fn,
    make_layout,
    train_trees,
)
from shifu_tpu.utils import environment


@pytest.fixture
def pallas_on():
    environment.set_property("shifu.pallas.mode", "on")
    try:
        yield
    finally:
        environment.set_property("shifu.pallas.mode", "")


def _set_mode(mode):
    environment.set_property("shifu.pallas.mode", mode)


def _ref_hist(L, lay, codes, y, w, node, active, n_classes=0):
    la = _device_layout(lay, np.ones(len(lay.slots), bool))
    fn = jax.jit(_make_hist_fn(L, lay, allow_matmul=False,
                               n_classes=n_classes))
    return np.asarray(fn(jnp.asarray(codes), jnp.asarray(y),
                         jnp.asarray(w), jnp.asarray(node),
                         jnp.asarray(active), la.off, la.clip, la.seg_t,
                         la.pos_t))


def _pallas_hist(L, lay, codes, y, w, node, active, n_classes=0,
                 low_precision=False):
    fn = jax.jit(make_pallas_hist_fn(L, lay, n_classes=n_classes,
                                     interpret=True,
                                     low_precision=low_precision))
    return np.asarray(fn(jnp.asarray(codes), jnp.asarray(y),
                         jnp.asarray(w), jnp.asarray(node),
                         jnp.asarray(active)))


def _mixed_case(n=1500, seed=0, full_range=False):
    rng = np.random.default_rng(seed)
    # narrow numerics + 33/65-wide categoricals (the Mosaic unaligned-
    # store shapes of the round-5 measured loss) + one wide categorical
    # that must split across lane-aligned chunks
    slots = [9] * 6 + [33, 65] + [1500]
    is_cat = [False] * 6 + [True] * 3
    hi = [s if full_range else s - 1 for s in slots]
    codes = np.stack(
        [rng.integers(0, h, size=n) for h in hi], 1).astype(np.int32)
    y = rng.random(n).astype(np.float32)
    w = rng.integers(1, 4, size=n).astype(np.float32)
    return slots, is_cat, codes, y, w, rng


# ---------------------------------------------------------------------------
# histogram parity
# ---------------------------------------------------------------------------


def test_pallas_matches_scatter_regression():
    slots, is_cat, codes, y, w, rng = _mixed_case()
    lay = make_layout(slots, is_cat)
    L = 8
    node = rng.integers(0, L, size=len(y)).astype(np.int32)
    active = rng.random(len(y)) < 0.9
    h_ref = _ref_hist(L, lay, codes, y, w, node, active)
    h_pl = _pallas_hist(L, lay, codes, y, w, node, active)
    # counts: integer weights sum exactly in f32 either way
    np.testing.assert_array_equal(h_ref[0], h_pl[0])
    # sums/sqsums: equal up to float summation order
    np.testing.assert_allclose(h_ref, h_pl, rtol=1e-5, atol=1e-3)


def test_pallas_matches_scatter_multiclass():
    slots, is_cat, codes, _y, w, rng = _mixed_case(seed=3)
    lay = make_layout(slots, is_cat)
    K, L = 4, 4
    cls = rng.integers(0, K, size=len(w)).astype(np.float32)
    node = rng.integers(0, L, size=len(w)).astype(np.int32)
    active = np.ones(len(w), bool)
    h_ref = _ref_hist(L, lay, codes, cls, w, node, active, n_classes=K)
    h_pl = _pallas_hist(L, lay, codes, cls, w, node, active, n_classes=K)
    np.testing.assert_array_equal(h_ref, h_pl)  # pure counts: exact


def test_bf16_plane_parity_bounds():
    """bf16 component planes: integer-weight COUNT plane stays exact
    (0/1-valued bf16 operands, f32 MXU accumulation); float moment
    planes land within bf16 rounding of the f32 reference."""
    slots, is_cat, codes, y, w, rng = _mixed_case(n=900, seed=5)
    lay = make_layout(slots, is_cat)
    L = 4
    node = rng.integers(0, L, size=len(y)).astype(np.int32)
    active = np.ones(len(y), bool)
    w1 = np.ones(len(y), np.float32)
    h_ref = _ref_hist(L, lay, codes, y, w1, node, active)
    h_pl = _pallas_hist(L, lay, codes, y, w1, node, active,
                        low_precision=True)
    np.testing.assert_array_equal(h_ref[0], h_pl[0])  # counts exact
    # moments: one bf16 rounding per plane value (~2^-8 relative)
    np.testing.assert_allclose(h_ref[1:], h_pl[1:], rtol=1e-2, atol=0.15)


# ---------------------------------------------------------------------------
# lane-aligned chunk layout
# ---------------------------------------------------------------------------


def test_chunks_cover_layout_lane_aligned():
    slots, is_cat, *_ = _mixed_case()
    lay = make_layout(slots, is_cat)
    chunks = _chunks(lay)
    kept = 0
    for ch in chunks:
        assert ch.w % 128 == 0
        for (_f, lo, hi, col0) in ch.pieces:
            assert col0 % 128 == 0  # every piece starts lane-aligned
        kept += len(ch.keep)
    assert kept == lay.T  # gaps dropped at compaction, contract unchanged
    # the 1500-wide categorical exceeds one chunk: handled by the
    # epilogue's XLA fallback, not the in-kernel scan
    assert wide_features(lay) == [8]
    # chunks whose features all fit 128 slots are int8-code eligible;
    # the 1500-wide feature's chunks are not
    assert chunks[0].narrow
    assert not any(ch.narrow for ch in chunks if 8 in
                   {f for (f, _lo, _hi, _c0) in ch.pieces})


def test_codes8_planes():
    slots, is_cat, codes, *_ = _mixed_case(n=300)
    lay = make_layout(slots, is_cat)
    codes8 = np.asarray(jax.jit(make_codes8_fn(lay))(jnp.asarray(codes)))
    assert codes8.dtype == np.int8
    # exact for <=128-slot features; wide columns are clamped (unused)
    np.testing.assert_array_equal(codes8[:, :8], codes[:, :8])
    assert codes8[:, 8].max() <= 127


# ---------------------------------------------------------------------------
# in-kernel split scan == reference split_scan
# ---------------------------------------------------------------------------


def _run_scan_pair(slots, is_cat, codes, y, w, L, impurity, n_classes=0,
                   min_inst=2, seed=7, wmax=None):
    rng = np.random.default_rng(seed)
    n = len(y)
    lay = make_layout(slots, is_cat)
    node = rng.integers(0, L, size=n).astype(np.int32)
    active = rng.random(n) < 0.95
    feat_ok = np.ones(len(slots), bool)
    fot = jnp.asarray(feat_ok[lay.seg_of_t])
    la = _device_layout(lay, feat_ok)
    if wmax is not None:
        environment.set_property("shifu.pallas.wmax", str(wmax))
    try:
        h_ref = jax.jit(_make_hist_fn(L, lay, allow_matmul=False,
                                      n_classes=n_classes))(
            jnp.asarray(codes), jnp.asarray(y), jnp.asarray(w),
            jnp.asarray(node), jnp.asarray(active), la.off, la.clip,
            la.seg_t, la.pos_t)
        scan = jax.jit(_make_scan_fn(L, lay.T, lay.s_max, impurity,
                                     min_inst, 0.0, n_classes))
        ref = scan(h_ref, fot, la.is_cat_t, la.seg_t, la.pos_t,
                   la.start_t, la.size_t, la.off, la.clip,
                   int(lay.slots[0]))
        fused = jax.jit(make_fused_level_fn(
            L, lay, impurity, min_inst, 0.0, n_classes=n_classes,
            interpret=True))
        hist, out = fused(jnp.asarray(codes), None, jnp.asarray(y),
                          jnp.asarray(w), jnp.asarray(node),
                          jnp.asarray(active), fot)
    finally:
        if wmax is not None:
            environment.set_property("shifu.pallas.wmax", "")
    return h_ref, hist, ref, out


def _assert_scan_equal(ref, out, exact_floats):
    names = ("feature", "cut_rank", "rank_flat", "leaf_value", "is_split",
             "best_gain", "left_mask", "node_cnt", "left_cnt")
    for nm, a, b in zip(names, ref, out):
        a, b = np.asarray(a), np.asarray(b)
        if nm in ("best_gain", "leaf_value", "node_cnt", "left_cnt"):
            if exact_floats:
                np.testing.assert_array_equal(a, b, err_msg=nm)
            else:
                np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-3,
                                           err_msg=nm)
        else:
            np.testing.assert_array_equal(a, b, err_msg=nm)


@pytest.mark.parametrize("impurity", ["variance", "friedmanmse",
                                      "entropy", "gini"])
def test_fused_scan_matches_reference_ragged(impurity):
    """All four impurities over the ragged 33/65-wide + multi-chunk-wide
    layout. Integer 0/1 labels x integer weights make every plane an
    exact integer sum, so even gains/leaves must be BIT-equal between
    the pairwise-rank kernel formulation and the lexsort reference."""
    slots, is_cat, codes, _y, w, rng = _mixed_case(n=1300, seed=11)
    y = (codes[:, 0] >= 4).astype(np.float32)  # 0/1: exact planes
    h_ref, hist, ref, out = _run_scan_pair(slots, is_cat, codes, y, w,
                                           L=4, impurity=impurity)
    np.testing.assert_array_equal(np.asarray(h_ref), np.asarray(hist))
    _assert_scan_equal(ref, out, exact_floats=True)


def test_fused_scan_matches_reference_float_labels():
    """GBT-shaped float labels: discrete outputs (feature, cut, ranks,
    masks, split flags) still match exactly; float stats within
    summation-order tolerance."""
    slots, is_cat, codes, y, w, _rng = _mixed_case(n=1300, seed=12)
    _h, _hist, ref, out = _run_scan_pair(slots, is_cat, codes, y, w,
                                         L=4, impurity="variance")
    _assert_scan_equal(ref, out, exact_floats=False)


def test_fused_scan_matches_reference_multiclass():
    slots, is_cat, codes, _y, w, rng = _mixed_case(n=1100, seed=13)
    K = 4
    cls = rng.integers(0, K, size=len(w)).astype(np.float32)
    _h, _hist, ref, out = _run_scan_pair(slots, is_cat, codes, cls, w,
                                         L=2, impurity="entropy",
                                         n_classes=K)
    _assert_scan_equal(ref, out, exact_floats=True)


def test_fused_scan_chunk_tail_never_splits_fitting_feature():
    """Regression (PR-11 review): a feature that FITS one chunk must
    never straddle a chunk tail — its in-kernel scan only sees its own
    chunk's columns, so a tail split would scan partial histograms
    while staying off the wide-feature XLA fallback. slots=[850, 300]
    at wmax 1024 is exactly that shape: f0 pads to 896, leaving 128
    columns of tail that must NOT receive a piece of f1."""
    rng = np.random.default_rng(21)
    slots = [850, 300]
    is_cat = [True, True]
    lay = make_layout(slots, is_cat)
    chunks = _chunks(lay, 1024)
    assert wide_features(lay, 1024) == []
    for ch in chunks:  # every piece covers its whole feature
        for (f, lo, hi, _c0) in ch.pieces:
            assert (lo, hi) == (0, slots[f])
    n = 1200
    codes = np.stack([rng.integers(0, s, size=n) for s in slots],
                     1).astype(np.int32)
    y = (codes[:, 1] >= 150).astype(np.float32)
    w = np.ones(n, np.float32)
    _h, _hist, ref, out = _run_scan_pair(slots, is_cat, codes, y, w,
                                         L=2, impurity="variance")
    _assert_scan_equal(ref, out, exact_floats=True)


def test_fused_scan_narrow_wmax_multichunk():
    """A small -Dshifu.pallas.wmax forces EVERY feature wider than one
    chunk onto the XLA fallback and splits the narrow ones across many
    chunks — the composed result must still equal the reference."""
    slots, is_cat, codes, _y, w, rng = _mixed_case(n=900, seed=14)
    y = (codes[:, 1] >= 5).astype(np.float32)
    lay = make_layout(slots, is_cat)
    assert wide_features(lay, 256) == [8]
    assert len(_chunks(lay, 256)) > len(_chunks(lay, 1024))
    _h, _hist, ref, out = _run_scan_pair(slots, is_cat, codes, y, w,
                                         L=2, impurity="variance",
                                         wmax=256)
    _assert_scan_equal(ref, out, exact_floats=True)


# ---------------------------------------------------------------------------
# end-to-end forest parity, kernel on vs off
# ---------------------------------------------------------------------------


def _forest_data(n=2500, seed=0):
    rng = np.random.default_rng(seed)
    slots = [17] * 5 + [33, 65]
    is_cat = [False] * 5 + [True] * 2
    codes = np.stack([rng.integers(0, s - 1, size=n) for s in slots],
                     1).astype(np.int32)
    y = ((codes[:, 0] >= 8).astype(np.int8)
         | (codes[:, 5] >= 20).astype(np.int8)).astype(np.float32)
    noise = rng.random(n) < 0.15
    y = np.where(noise, 1.0 - y, y).astype(np.float32)
    w = np.ones(n, np.float32)
    cols = [f"f{i}" for i in range(len(slots))]
    return codes, y, w, slots, is_cat, cols


def _run_mode(mode, codes, y, w, slots, is_cat, cols, cfg):
    _set_mode(mode)
    try:
        return train_trees(codes, y, w, slots, is_cat, cols, cfg)
    finally:
        _set_mode("")


def _assert_forests_bit_equal(a, b):
    assert len(a.spec.trees) == len(b.spec.trees)
    for t0, t1 in zip(a.spec.trees, b.spec.trees):
        np.testing.assert_array_equal(t0.feature, t1.feature)
        np.testing.assert_array_equal(t0.left_mask, t1.left_mask)
        np.testing.assert_array_equal(t0.leaf_value, t1.leaf_value)


def test_rf_bit_parity_fused_kernel_binary():
    """PR-3 gate under the fused kernel: RF integer-weight planes stay
    f32 and exact, so the forest is BIT-equal kernel on vs off —
    subtraction composition included (depth 4 engages the derive
    chain)."""
    codes, y, w, slots, is_cat, cols = _forest_data()
    cfg = TreeTrainConfig(algorithm="RF", tree_num=3, max_depth=4,
                          feature_subset_strategy="TWOTHIRDS", seed=3,
                          valid_set_rate=0.1)
    off = _run_mode("off", codes, y, w, slots, is_cat, cols, cfg)
    on = _run_mode("on", codes, y, w, slots, is_cat, cols, cfg)
    _assert_forests_bit_equal(off, on)
    assert off.valid_error == on.valid_error


def test_rf_bit_parity_fused_kernel_multiclass():
    codes, _y, w, slots, is_cat, cols = _forest_data(seed=4)
    rng = np.random.default_rng(9)
    y3 = np.clip(codes[:, 0] // 6 + rng.integers(0, 2, len(w)),
                 0, 2).astype(np.float32)
    cfg = TreeTrainConfig(algorithm="RF", tree_num=2, max_depth=3,
                          impurity="gini", n_classes=3, seed=5)
    off = _run_mode("off", codes, y3, w, slots, is_cat, cols, cfg)
    on = _run_mode("on", codes, y3, w, slots, is_cat, cols, cfg)
    _assert_forests_bit_equal(off, on)


def test_gbt_tolerance_parity_levelwise():
    """GBT under the kernel: bf16 planes + matvec summation order means
    tolerance parity, not bit parity — scores must stay close."""
    codes, y, w, slots, is_cat, cols = _forest_data(seed=6)
    cfg = TreeTrainConfig(algorithm="GBT", tree_num=4, max_depth=4,
                          learning_rate=0.3, seed=7, valid_set_rate=0.1)
    off = _run_mode("off", codes, y, w, slots, is_cat, cols, cfg)
    on = _run_mode("on", codes, y, w, slots, is_cat, cols, cfg)
    s_off = off.spec.independent().compute(codes)
    s_on = on.spec.independent().compute(codes)
    np.testing.assert_allclose(s_on, s_off, atol=0.03)


def test_gbt_tolerance_parity_leafwise():
    codes, y, w, slots, is_cat, cols = _forest_data(seed=8, n=1500)
    cfg = TreeTrainConfig(algorithm="GBT", tree_num=2, max_depth=6,
                          max_leaves=7, learning_rate=0.3, seed=9)
    off = _run_mode("off", codes, y, w, slots, is_cat, cols, cfg)
    on = _run_mode("on", codes, y, w, slots, is_cat, cols, cfg)
    s_off = off.spec.independent().compute(codes)
    s_on = on.spec.independent().compute(codes)
    np.testing.assert_allclose(s_on, s_off, atol=0.03)


def test_subtraction_composition_built_ratio(pallas_on):
    """Histogram subtraction composes with the fused kernel: the kernel
    grows only the smaller child, the sibling derives as parent − built,
    and the built-histogram counters keep the <= 0.55 acceptance ratio
    of the subtraction-off run."""
    from shifu_tpu import obs

    codes, y, w, slots, is_cat, cols = _forest_data(n=1200, seed=10)
    trees, depth = 2, 4
    cfg = TreeTrainConfig(algorithm="GBT", tree_num=trees,
                          max_depth=depth, seed=1)
    cfg_off = TreeTrainConfig(**{**cfg.__dict__, "hist_subtraction": False})

    def counters():
        snap = obs.registry().snapshot().get("counters", {})
        return {k.split(".")[-1]: v for k, v in snap.items()
                if k.startswith("tree.hist.")}

    obs.reset()
    train_trees(codes, y, w, slots, is_cat, cols, cfg)
    c_on = counters()
    obs.reset()
    train_trees(codes, y, w, slots, is_cat, cols, cfg_off)
    c_off = counters()
    leaves = 2 ** depth
    assert c_on["built"] == trees * (leaves // 2)
    assert c_on["derived"] == trees * (leaves // 2 - 1)
    assert c_on["built"] / c_off["built"] <= 0.55


# ---------------------------------------------------------------------------
# knob surface
# ---------------------------------------------------------------------------


def test_mode_knob_resolution():
    """auto = off on the CPU harness; on = forced with interpret mode;
    off = XLA. (On a TPU backend auto resolves to the compiled
    kernel.)"""
    try:
        _set_mode("auto")
        assert pallas_active() == (False, False)  # CPU harness
        _set_mode("off")
        assert pallas_active() == (False, False)
        _set_mode("on")
        assert pallas_active() == (True, True)  # interpret off-TPU
        _set_mode("bogus")
        assert pallas_active() == (False, False)  # falls back to auto
    finally:
        _set_mode("")


def test_shaping_knobs_and_profiler_annotation():
    """-Dshifu.pallas.blk/.wmax override the VMEM shaping (the kernel-
    tuning sweep seam), the overridden kernel still matches the scatter
    reference exactly, and the chosen shaping lands in the profiler
    snapshot so every manifest records what produced its numbers."""
    from shifu_tpu import obs
    from shifu_tpu.ops.hist_pallas import blk_setting, wmax_setting

    slots, is_cat, codes, y, w, rng = _mixed_case(n=700)
    lay = make_layout(slots, is_cat)
    L = 4
    node = rng.integers(0, L, size=len(y)).astype(np.int32)
    active = rng.random(len(y)) < 0.9
    h_ref = _ref_hist(L, lay, codes, y, w, node, active)

    environment.set_property("shifu.pallas.blk", "128")
    environment.set_property("shifu.pallas.wmax", "256")
    obs.reset()
    try:
        assert blk_setting() == 128 and wmax_setting() == 256
        # the narrower wmax splits the lane-aligned layout into more
        # chunks
        assert len(_chunks(lay)) > len(_chunks(lay, target=1024))
        h_pl = _pallas_hist(L, lay, codes, y, w, node, active)
        np.testing.assert_array_equal(h_ref[0], h_pl[0])
        np.testing.assert_allclose(h_ref, h_pl, rtol=2e-5, atol=1e-4)
        ann = obs.profiler().snapshot()["annotations"]["ops.hist_pallas"]
        assert ann["blk"] == 128 and ann["wMax"] == 256
        assert ann["chunks"] == len(_chunks(lay))
        assert ann["mode"] in ("auto", "on", "off")
    finally:
        environment.set_property("shifu.pallas.blk", "")
        environment.set_property("shifu.pallas.wmax", "")
    assert blk_setting() == 512 and wmax_setting() == 1024


def test_bench_baseline_guards(tmp_path, monkeypatch):
    """bench.py refuses to silently clobber the calibrated pinned baseline
    and rejects config drift (review findings, round 5)."""
    import json
    import sys

    import bench

    fake = tmp_path / "BASELINE_MEASURED.json"
    monkeypatch.setattr(bench, "BASELINE_FILE", str(fake))
    # calibrated file: remeasure refuses without --force-remeasure
    json.dump({"calibrated": True, "configs": {}}, open(fake, "w"))
    monkeypatch.setattr(sys, "argv", ["bench.py", "--remeasure-baseline"])
    with pytest.raises(SystemExit, match="calibrated"):
        bench.load_or_measure_baseline(remeasure=True)
    # config drift: plain load errors with guidance
    with pytest.raises(SystemExit, match="different bench configs"):
        bench.load_or_measure_baseline()
    # missing file: clear instruction
    fake.unlink()
    with pytest.raises(SystemExit, match="must be checked in"):
        bench.load_or_measure_baseline()
