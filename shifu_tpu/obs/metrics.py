"""Thread-safe metrics registry: counters, gauges, histograms, timers, series.

The reference's only run-level numbers are Hadoop job counters plus per-phase
wall-clock log lines (SURVEY §5); TensorFlow's summary/event system shows a
training stack needs a first-class metrics stream instead. This registry is
that stream for the TPU rebuild: every lifecycle step, the streaming pipeline,
the trainers and eval record into it, `BasicProcessor.run()` snapshots it into
the run manifest (obs/ledger.py), and the Prometheus/JSON exporters make the
same state scrapeable and diffable.

Kinds:
  Counter    monotonically increasing float (row counts, compile counts)
  Gauge      last-written value (AUC, column counts)
  Histogram  fixed-bucket distribution (value counts + sum/min/max)
  Timer      wall-clock accumulator: seconds + calls — the PR-1
             `utils/timing.StageTimers` absorbed as a first-class kind
             (StageTimers below is the multi-stage facade over it)
  Series     (step, value) time series (per-epoch loss curves)

Metric identity is (name, sorted labels); all kinds are safe to update from
the prefetch worker thread and the consumer thread concurrently.
"""

from __future__ import annotations

import bisect
import json
import re
import time
from contextlib import contextmanager
from typing import Dict, Iterator, List, Optional, Tuple

from shifu_tpu.analysis.racetrack import tracked_lock

DEFAULT_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                   1.0, 2.5, 5.0, 10.0, float("inf"))

LabelsKey = Tuple[Tuple[str, str], ...]


def _labels_key(labels: Dict[str, str]) -> LabelsKey:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _escape(v: str) -> str:
    # Prometheus exposition escaping for label values: \ and " (label
    # values come from user config — eval-set names — so this is load-bearing
    # for both valid scrape output and the lossless JSON round-trip)
    return v.replace("\\", "\\\\").replace('"', '\\"')


def _unescape(v: str) -> str:
    return v.replace('\\"', '"').replace("\\\\", "\\")


def _label_str(labels: LabelsKey) -> str:
    if not labels:
        return ""
    return "{" + ",".join(f'{k}="{_escape(v)}"' for k, v in labels) + "}"


def sanitize_name(name: str) -> str:
    """Prometheus metric names allow [a-zA-Z0-9_:] only."""
    return re.sub(r"[^a-zA-Z0-9_:]", "_", name)


class Counter:
    __slots__ = ("_lock", "_value")

    def __init__(self) -> None:
        self._lock = tracked_lock("obs.metrics.counter")
        self._value = 0.0

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Gauge:
    __slots__ = ("_lock", "_value")

    def __init__(self) -> None:
        self._lock = tracked_lock("obs.metrics.gauge")
        self._value = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Histogram:
    __slots__ = ("_lock", "buckets", "_counts", "_sum", "_count",
                 "_min", "_max", "_exemplars")

    def __init__(self, buckets: Tuple[float, ...] = DEFAULT_BUCKETS) -> None:
        self._lock = tracked_lock("obs.metrics.histogram")
        self.buckets = tuple(sorted(buckets))
        if self.buckets[-1] != float("inf"):
            self.buckets = self.buckets + (float("inf"),)
        self._counts = [0] * len(self.buckets)
        self._sum = 0.0
        self._count = 0
        self._min = float("inf")
        self._max = float("-inf")
        # per-bucket last (value, trace id): /metrics links a slow
        # bucket straight to a captured request trace (OpenMetrics
        # exemplar annotations on the _bucket samples)
        self._exemplars: Dict[int, Tuple[float, str]] = {}

    def observe(self, v: float, exemplar: Optional[str] = None) -> None:
        v = float(v)
        # first bucket with v <= bound, C-speed (the last bound is +inf,
        # so any non-NaN value lands in range) — observe runs per
        # request on the serve path, where a Python linear scan is
        # measurable. NaN (v != v) counts in NO bucket, matching the
        # old linear scan's no-match behavior (bisect would mis-place
        # it in bucket 0).
        i = bisect.bisect_left(self.buckets, v) if v == v else -1
        with self._lock:
            if i >= 0:
                self._counts[i] += 1
                if exemplar is not None:
                    self._exemplars[i] = (v, str(exemplar))
            self._sum += v
            self._count += 1
            self._min = min(self._min, v)
            self._max = max(self._max, v)

    def add_binned(self, counts, total: float, n: int,
                   vmin: float, vmax: float) -> None:
        """Bulk merge pre-binned observations under ONE lock acquisition.
        The caller binned with the same `v <= bucket` rule observe()
        uses (e.g. np.searchsorted(buckets, values, side="left")) into
        one count per bucket — the batch path for hot loops where a
        per-value observe() would serialize on the lock."""
        if n <= 0:
            return
        with self._lock:
            for i, c in enumerate(counts):
                if c:
                    self._counts[i] += int(c)
            self._sum += float(total)
            self._count += int(n)
            self._min = min(self._min, float(vmin))
            self._max = max(self._max, float(vmax))

    def as_dict(self) -> dict:
        with self._lock:
            out = {
                "buckets": ["inf" if b == float("inf") else b
                            for b in self.buckets],
                "counts": list(self._counts),
                "sum": self._sum,
                "count": self._count,
                "min": self._min if self._count else None,
                "max": self._max if self._count else None,
            }
            if self._exemplars:
                out["exemplars"] = {
                    str(i): [v, eid]
                    for i, (v, eid) in sorted(self._exemplars.items())}
            return out

    @classmethod
    def from_dict(cls, d: dict) -> "Histogram":
        """Rebuild a histogram from its as_dict() form (the snapshot/
        JSON shape) — the read half of the lossless round-trip."""
        buckets = tuple(float("inf") if b == "inf" else float(b)
                        for b in d["buckets"])
        hist = cls(buckets)
        with hist._lock:
            hist._counts = [int(c) for c in d["counts"]]
            hist._sum = float(d["sum"])
            hist._count = int(d["count"])
            hist._min = (float(d["min"]) if d.get("min") is not None
                         else float("inf"))
            hist._max = (float(d["max"]) if d.get("max") is not None
                         else float("-inf"))
            hist._exemplars = {
                int(i): (float(v), str(eid))
                for i, (v, eid) in (d.get("exemplars") or {}).items()}
        return hist

    def merge(self, other: "Histogram") -> None:
        """EXACT merge of another histogram into this one: per-bucket
        counts, sum, count, min/max all add/combine losslessly — the ONE
        way snapshots are ever folded together (fleet federation, shadow
        evidence, bench report folding), so merged == recomputed-from-raw
        holds by construction. Requires identical pinned bucket edges
        (the serve path's exponential edges are pinned for exactly this)
        and raises ValueError on any mismatch rather than resampling.

        Exemplars: an existing local exemplar wins (it is linkable in
        THIS process's trace evidence); empty slots adopt the other's."""
        if self.buckets != other.buckets:
            raise ValueError(
                f"histogram bucket edges differ: {self.buckets} vs "
                f"{other.buckets} — exact merge needs identical pinned "
                "edges")
        # sequential snapshot-then-apply (never nest the two same-named
        # tracked locks): other's state is copied out under its lock,
        # folded in under ours
        with other._lock:
            counts = list(other._counts)
            o_sum, o_count = other._sum, other._count
            o_min, o_max = other._min, other._max
            o_ex = dict(other._exemplars)
        with self._lock:
            for i, c in enumerate(counts):
                if c:
                    self._counts[i] += c
            self._sum += o_sum
            self._count += o_count
            self._min = min(self._min, o_min)
            self._max = max(self._max, o_max)
            for i, ex in o_ex.items():
                self._exemplars.setdefault(i, ex)

    def quantile(self, q: float) -> Optional[float]:
        with self._lock:
            return quantile_from_counts(self.buckets, self._counts, q)


def quantile_from_counts(buckets, counts, q: float) -> Optional[float]:
    """Bucket-interpolated quantile (the Prometheus histogram_quantile
    rule: linear within the target bucket, the lower edge of the first
    bucket as 0). Shared by Histogram.quantile, the fleet view and
    `shifu top` (which recovers counts from scraped `_bucket{le=}`
    cumulative samples). Returns None on an empty histogram; a quantile
    landing in the +inf overflow bucket reports that bucket's lower
    edge (the largest finite bound)."""
    total = sum(counts)
    if total <= 0:
        return None
    q = min(max(float(q), 0.0), 1.0)
    rank = q * total
    seen = 0.0
    for i, c in enumerate(counts):
        if not c:
            continue
        seen += c
        if seen >= rank:
            hi = buckets[i]
            lo = buckets[i - 1] if i else 0.0
            if hi == float("inf"):
                return float(lo)
            frac = 1.0 - (seen - rank) / c
            return float(lo + (hi - lo) * frac)
    return float(buckets[-2]) if len(buckets) > 1 else None


class Timer:
    """Wall-clock accumulator (seconds + call count) — the StageTimers kind."""

    __slots__ = ("_lock", "_seconds", "_calls")

    def __init__(self) -> None:
        self._lock = tracked_lock("obs.metrics.timer")
        self._seconds = 0.0
        self._calls = 0

    def add(self, seconds: float, calls: int = 1) -> None:
        with self._lock:
            self._seconds += seconds
            self._calls += calls

    @contextmanager
    def time(self) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.add(time.perf_counter() - t0)

    @property
    def seconds(self) -> float:
        with self._lock:
            return self._seconds

    @property
    def calls(self) -> int:
        with self._lock:
            return self._calls


class Series:
    """(step, value) time series — per-epoch loss curves and the like."""

    __slots__ = ("_lock", "_points")

    def __init__(self) -> None:
        self._lock = tracked_lock("obs.metrics.series")
        self._points: List[List[float]] = []

    def append(self, step: float, value: float) -> None:
        with self._lock:
            self._points.append([float(step), float(value)])

    @property
    def points(self) -> List[List[float]]:
        with self._lock:
            return [list(p) for p in self._points]

    @property
    def last(self) -> Optional[float]:
        with self._lock:
            return self._points[-1][1] if self._points else None


class MetricsRegistry:
    """Label-aware, thread-safe registry with Prometheus + JSON exporters."""

    def __init__(self) -> None:
        self._lock = tracked_lock("obs.metrics.registry")
        self._counters: Dict[Tuple[str, LabelsKey], Counter] = {}
        self._gauges: Dict[Tuple[str, LabelsKey], Gauge] = {}
        self._histograms: Dict[Tuple[str, LabelsKey], Histogram] = {}
        self._timers: Dict[Tuple[str, LabelsKey], Timer] = {}
        self._series: Dict[Tuple[str, LabelsKey], Series] = {}

    def _get(self, store: dict, name: str, labels: dict, factory):
        key = (name, _labels_key(labels))
        with self._lock:
            m = store.get(key)
            if m is None:
                m = factory()
                store[key] = m
            return m

    # ---- accessors (get-or-create) ----
    def counter(self, name: str, **labels) -> Counter:
        return self._get(self._counters, name, labels, Counter)

    def gauge(self, name: str, **labels) -> Gauge:
        return self._get(self._gauges, name, labels, Gauge)

    def histogram(self, name: str,
                  buckets: Tuple[float, ...] = DEFAULT_BUCKETS,
                  **labels) -> Histogram:
        return self._get(self._histograms, name, labels,
                         lambda: Histogram(buckets))

    def timer(self, name: str, **labels) -> Timer:
        return self._get(self._timers, name, labels, Timer)

    def series(self, name: str, **labels) -> Series:
        return self._get(self._series, name, labels, Series)

    def stage_timers(self, prefix: str) -> "StageTimers":
        """A StageTimers facade whose stages are registry timers named
        `prefix` with a `stage` label — streaming-pipeline timings recorded
        through it land in the run manifest, not just a log line."""
        return StageTimers(registry=self, prefix=prefix)

    # ---- snapshots ----
    def snapshot(self) -> dict:
        """Nested JSON-able view of the full registry state."""
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            histograms = dict(self._histograms)
            timers = dict(self._timers)
            series = dict(self._series)
        out: dict = {"counters": {}, "gauges": {}, "histograms": {},
                     "timers": {}, "series": {}}
        for (name, labels), c in sorted(counters.items()):
            out["counters"][name + _label_str(labels)] = c.value
        for (name, labels), g in sorted(gauges.items()):
            out["gauges"][name + _label_str(labels)] = g.value
        for (name, labels), h in sorted(histograms.items()):
            out["histograms"][name + _label_str(labels)] = h.as_dict()
        for (name, labels), t in sorted(timers.items()):
            out["timers"][name + _label_str(labels)] = {
                "seconds": t.seconds, "calls": t.calls}
        for (name, labels), s in sorted(series.items()):
            out["series"][name + _label_str(labels)] = s.points
        return out

    def is_empty(self) -> bool:
        with self._lock:
            return not (self._counters or self._gauges or self._histograms
                        or self._timers or self._series)

    # ---- JSON exporter (lossless round-trip via from_json) ----
    def to_json(self, indent: Optional[int] = None) -> str:
        return json.dumps(self.snapshot(), indent=indent, sort_keys=True)

    @classmethod
    def from_json(cls, text: str) -> "MetricsRegistry":
        snap = json.loads(text)
        reg = cls()
        for key, v in snap.get("counters", {}).items():
            name, labels = _parse_key(key)
            reg.counter(name, **labels).inc(v)
        for key, v in snap.get("gauges", {}).items():
            name, labels = _parse_key(key)
            reg.gauge(name, **labels).set(v)
        for key, h in snap.get("histograms", {}).items():
            name, labels = _parse_key(key)
            buckets = tuple(float("inf") if b == "inf" else float(b)
                            for b in h["buckets"])
            hist = reg.histogram(name, buckets=buckets, **labels)
            hist.merge(Histogram.from_dict(h))
        for key, t in snap.get("timers", {}).items():
            name, labels = _parse_key(key)
            reg.timer(name, **labels).add(t["seconds"], t["calls"])
        for key, pts in snap.get("series", {}).items():
            name, labels = _parse_key(key)
            s = reg.series(name, **labels)
            for step, value in pts:
                s.append(step, value)
        return reg

    # ---- Prometheus text exporter ----
    def flatten(self) -> Dict[str, float]:
        """Flat {prometheus_sample_name: value} — exactly the samples
        to_prometheus() emits (series are JSON-only; their last value is
        exported as a `<name>_last` gauge sample)."""
        flat: Dict[str, float] = {}
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            histograms = dict(self._histograms)
            timers = dict(self._timers)
            series = dict(self._series)
        for (name, labels), c in counters.items():
            flat[sanitize_name(name) + "_total" + _label_str(labels)] = c.value
        for (name, labels), g in gauges.items():
            flat[sanitize_name(name) + _label_str(labels)] = g.value
        for (name, labels), t in timers.items():
            base = sanitize_name(name)
            flat[base + "_seconds_total" + _label_str(labels)] = t.seconds
            flat[base + "_calls_total" + _label_str(labels)] = float(t.calls)
        for (name, labels), h in histograms.items():
            base = sanitize_name(name)
            d = h.as_dict()
            cum = 0
            for b, n in zip(d["buckets"], d["counts"]):
                cum += n
                le = "+Inf" if b == "inf" else repr(float(b))
                bl = _labels_key(dict(labels, le=le))
                flat[base + "_bucket" + _label_str(bl)] = float(cum)
            flat[base + "_sum" + _label_str(labels)] = d["sum"]
            flat[base + "_count" + _label_str(labels)] = float(d["count"])
        for (name, labels), s in series.items():
            last = s.last
            if last is not None:
                flat[sanitize_name(name) + "_last" + _label_str(labels)] = last
        return flat

    def _bucket_exemplars(self) -> Dict[str, Tuple[float, str]]:
        """{_bucket sample key: (value, trace id)} — same key shape as
        flatten(), so to_prometheus can annotate the matching lines."""
        with self._lock:
            histograms = dict(self._histograms)
        out: Dict[str, Tuple[float, str]] = {}
        for (name, labels), h in histograms.items():
            if not h._exemplars:  # bare emptiness peek (GIL-atomic):
                continue          # skip the second locked snapshot for
                                  # the common exemplar-less histogram
            base = sanitize_name(name)
            d = h.as_dict()
            for i, (v, eid) in (d.get("exemplars") or {}).items():
                b = d["buckets"][int(i)]
                le = "+Inf" if b == "inf" else repr(float(b))
                bl = _labels_key(dict(labels, le=le))
                out[base + "_bucket" + _label_str(bl)] = (float(v), eid)
        return out

    def to_prometheus(self) -> str:
        lines: List[str] = []
        types: Dict[str, str] = {}
        with self._lock:
            for (name, _), _c in self._counters.items():
                types[sanitize_name(name) + "_total"] = "counter"
            for (name, _), _g in self._gauges.items():
                types[sanitize_name(name)] = "gauge"
            for (name, _), _h in self._histograms.items():
                types[sanitize_name(name)] = "histogram"
        for base in sorted(types):
            lines.append(f"# TYPE {base} {types[base]}")
        flat = self.flatten()
        exemplars = self._bucket_exemplars()
        for sample in sorted(flat):
            line = f"{sample} {_fmt_value(flat[sample])}"
            ex = exemplars.get(sample)
            if ex is not None:
                # OpenMetrics exemplar: the slow bucket names the trace
                # id whose request landed in it (evidence, not a sample)
                v, eid = ex
                line += f' # {{trace_id="{_escape(eid)}"}} {_fmt_value(v)}'
            lines.append(line)
        return "\n".join(lines) + ("\n" if lines else "")


def _fmt_value(v: float) -> str:
    if v == float("inf"):
        return "+Inf"
    if v == float("-inf"):
        return "-Inf"
    return repr(float(v))


def _parse_key(key: str) -> Tuple[str, Dict[str, str]]:
    """Invert `name{a="b",...}` snapshot/sample keys (escape-aware)."""
    if "{" not in key:
        return key, {}
    name, rest = key.split("{", 1)
    rest = rest.rstrip("}")
    labels: Dict[str, str] = {}
    for k, v in re.findall(r'(\w+)="((?:[^"\\]|\\.)*)"', rest):
        labels[k] = _unescape(v)
    return name, labels


def parse_prometheus(text: str) -> Dict[str, float]:
    """Parse the exporter's text format back to {sample_name: value} —
    the round-trip counterpart of MetricsRegistry.flatten()."""
    out: Dict[str, float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        # exemplar annotations (` # {trace_id="..."} v`) are evidence
        # riding the sample line, not part of the sample value —
        # anchored at end-of-line so a label VALUE containing " # "
        # (label values only escape \ and ") can never be truncated
        line = re.sub(r' # \{[^{}]*\} \S+$', '', line)
        sample, _, value = line.rpartition(" ")
        if value == "+Inf":
            out[sample] = float("inf")
        elif value == "-Inf":
            out[sample] = float("-inf")
        else:
            out[sample] = float(value)
    return out


class StageTimers:
    """Named wall-clock accumulators (seconds + call counts).

    PR-1's standalone pipeline timers, now backed by registry Timer metrics:
    constructed with a registry (or via `MetricsRegistry.stage_timers`),
    each stage is the registry timer `prefix{stage=<stage>}` and the timings
    land in the run manifest; constructed bare (`StageTimers()`), it keeps
    the original self-contained behavior for library/test use.

    Thread-safe either way: the prefetch worker times parse/bincode while
    the consumer thread times device/sync against the same instance.
    """

    def __init__(self, registry: Optional[MetricsRegistry] = None,
                 prefix: str = "stage") -> None:
        self._registry = registry
        self._prefix = prefix
        self._lock = tracked_lock("obs.metrics.stage_timers")
        self._stages: Dict[str, Timer] = {}

    def _stage(self, stage: str) -> Timer:
        with self._lock:
            t = self._stages.get(stage)
            if t is None:
                if self._registry is not None:
                    t = self._registry.timer(self._prefix, stage=stage)
                else:
                    t = Timer()
                self._stages[stage] = t
            return t

    def add(self, stage: str, seconds: float, calls: int = 1) -> None:
        self._stage(stage).add(seconds, calls)

    @contextmanager
    def timer(self, stage: str) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.add(stage, time.perf_counter() - t0)

    def seconds(self, stage: str) -> float:
        return self._stage(stage).seconds

    def calls(self, stage: str) -> int:
        return self._stage(stage).calls

    def as_dict(self) -> Dict[str, Dict[str, float]]:
        with self._lock:
            stages = dict(self._stages)
        return {
            k: {"seconds": round(t.seconds, 4), "calls": t.calls}
            for k, t in stages.items()
        }

    def summary(self) -> str:
        """One log-friendly line: "parse 1.21s/12 | device 0.43s/12"."""
        with self._lock:
            stages = dict(self._stages)
        if not stages:
            return "(no stages timed)"
        return " | ".join(
            f"{k} {t.seconds:.2f}s/{t.calls}" for k, t in stages.items()
        )
