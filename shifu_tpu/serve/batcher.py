"""Dynamic micro-batching: coalesce concurrent requests into one dispatch.

Single-record dispatches waste the accelerator (a 1-row matmul costs the
same launch overhead as a 1024-row one); unbounded batching wastes the
client's latency budget. The batcher sits between the admission queue and
the fused registry program and closes each batch on whichever bound hits
first:

  * row cap       shifu.serve.maxBatchRows (default 1024)
  * wait deadline shifu.serve.maxWaitMs    (default 2.0 ms after the
                  batch's FIRST request arrives — a lone request never
                  waits longer than that for company)

Coalesced rows concatenate into one raw batch, score in one fused
dispatch (the registry pads to the power-of-two row bucket, so compile
count stays bounded whatever sizes traffic produces), and the result is
sliced back per request — padding rows belong to the registry, request
boundaries to the batcher, and neither leaks into the other.

One worker thread keeps ordering FIFO and the device queue depth at one
batch; requests resolve through a per-request event (`ScoreRequest.wait`).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, List, Optional, Sequence

import numpy as np

from shifu_tpu.data.reader import ColumnarData
from shifu_tpu.eval.scorer import ScoreResult
from shifu_tpu.serve.queue import AdmissionQueue
from shifu_tpu.utils import environment
from shifu_tpu.utils.log import get_logger

log = get_logger(__name__)

DEFAULT_MAX_BATCH_ROWS = 1024
DEFAULT_MAX_WAIT_MS = 2.0

# Exponential histogram edges, pinned (tests/test_serve.py). The metrics
# registry's DEFAULT_BUCKETS start at 5 ms — useless for a path whose p99
# is single-digit milliseconds: every observation landed in the first two
# buckets and the exported quantiles collapsed. Doubling edges from 100 µs
# give ~equal relative resolution from sub-ms latencies to multi-second
# stalls.
LATENCY_BUCKETS = tuple(0.0001 * 2 ** k for k in range(16)) + (float("inf"),)
# batch sizes are power-of-two-ish by construction (row buckets), so the
# edges are exact powers of two up to the 8192 cap ambit
BATCH_ROWS_BUCKETS = tuple(float(2 ** k) for k in range(14)) + (float("inf"),)


def max_batch_rows_setting() -> int:
    return environment.get_int("shifu.serve.maxBatchRows",
                               DEFAULT_MAX_BATCH_ROWS)


def max_wait_ms_setting() -> float:
    raw = environment.get_property("shifu.serve.maxWaitMs", "")
    try:
        return float(raw) if raw else DEFAULT_MAX_WAIT_MS
    except ValueError:
        return DEFAULT_MAX_WAIT_MS


class ScoreRequest:
    """One admitted request: a raw columnar slice plus its completion."""

    __slots__ = ("data", "n_rows", "enqueued_at", "_done", "result",
                 "error")

    def __init__(self, data: ColumnarData) -> None:
        self.data = data
        self.n_rows = data.n_rows
        self.enqueued_at = time.perf_counter()
        self._done = threading.Event()
        self.result: Optional[ScoreResult] = None
        self.error: Optional[BaseException] = None

    def resolve(self, result: ScoreResult) -> None:
        self.result = result
        self._done.set()

    def fail(self, error: BaseException) -> None:
        self.error = error
        self._done.set()

    def wait(self, timeout: Optional[float] = None) -> ScoreResult:
        if not self._done.wait(timeout):
            raise TimeoutError("score request did not complete in time")
        if self.error is not None:
            raise self.error
        return self.result


def _concat_batches(datas: Sequence[ColumnarData]) -> ColumnarData:
    if len(datas) == 1:
        return datas[0]
    names = datas[0].names
    raw = {
        name: np.concatenate([np.asarray(d.column(name), dtype=object)
                              for d in datas])
        for name in names
    }
    return ColumnarData(names=list(names), raw=raw,
                        n_rows=sum(d.n_rows for d in datas),
                        missing_values=datas[0].missing_values)


def _slice_result(res: ScoreResult, start: int, stop: int) -> ScoreResult:
    return ScoreResult(
        model_scores=res.model_scores[start:stop],
        mean=res.mean[start:stop],
        max=res.max[start:stop],
        min=res.min[start:stop],
        median=res.median[start:stop],
        model_names=res.model_names,
        model_widths=res.model_widths,
    )


class MicroBatcher:
    """Admission-queue consumer: coalesce -> score -> fan results out."""

    def __init__(self, score_fn: Callable[[ColumnarData], ScoreResult],
                 admission: AdmissionQueue,
                 max_batch_rows: Optional[int] = None,
                 max_wait_ms: Optional[float] = None) -> None:
        self.score_fn = score_fn
        self.admission = admission
        self.max_batch_rows = (max_batch_rows_setting()
                               if max_batch_rows is None
                               else int(max_batch_rows))
        self.max_wait_s = (max_wait_ms_setting()
                           if max_wait_ms is None
                           else float(max_wait_ms)) / 1000.0
        self._worker = threading.Thread(target=self._loop,
                                        name="shifu-serve-batcher",
                                        daemon=True)
        self._worker.start()

    def submit(self, data: ColumnarData) -> ScoreRequest:
        """Admit one request (raises queue.RejectedError on shed)."""
        req = ScoreRequest(data)
        self.admission.put(req)
        return req

    def join(self, timeout: Optional[float] = None) -> None:
        """Wait for drain: meaningful only after admission.close()."""
        self._worker.join(timeout)

    @property
    def draining(self) -> bool:
        return self.admission.closed and self._worker.is_alive()

    def _gather(self) -> Optional[List[ScoreRequest]]:
        """Block for the next request, then coalesce until the row cap or
        the max-wait deadline. None = queue closed and fully drained."""
        first = self.admission.get()
        if first is None:
            return None
        batch = [first]
        rows = first.n_rows
        deadline = time.perf_counter() + self.max_wait_s
        while rows < self.max_batch_rows:
            remaining = deadline - time.perf_counter()
            if remaining <= 0:
                break
            nxt = self.admission.get(timeout=remaining)
            if nxt is None:
                break
            batch.append(nxt)
            rows += nxt.n_rows
        return batch

    def _loop(self) -> None:
        from shifu_tpu.obs import registry

        while True:
            batch = self._gather()
            if batch is None:
                return
            reg = registry()
            rows = sum(r.n_rows for r in batch)
            reg.counter("serve.batches").inc()
            reg.histogram(
                "serve.batch.rows", buckets=BATCH_ROWS_BUCKETS,
            ).observe(rows)
            try:
                with reg.timer("serve.batch.score").time():
                    result = self.score_fn(_concat_batches(
                        [r.data for r in batch]))
            except BaseException as e:  # fan the failure out per request
                log.warning("serve batch of %d requests failed: %s",
                            len(batch), e)
                reg.counter("serve.batch.errors").inc()
                for r in batch:
                    r.fail(e)
                continue
            off = 0
            now = time.perf_counter()
            lat = reg.histogram("serve.latency_seconds",
                                buckets=LATENCY_BUCKETS)
            for r in batch:
                r.resolve(_slice_result(result, off, off + r.n_rows))
                off += r.n_rows
                lat.observe(now - r.enqueued_at)
            reg.counter("serve.requests").inc(len(batch))
            reg.counter("serve.records").inc(rows)
