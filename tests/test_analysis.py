"""`shifu check` + sanitizer harness: the ISSUE-4 acceptance contract.

Covers: seeded positive/negative fixtures for every rule (JX001-JX005,
SH101-SH103), noqa suppression, the shifu.check/1 JSON schema, the CLI
entry, the self-check (the shipped tree must be clean), the runtime
sanitizer's three modes, and the ledger integration (a sanitizer breach
shows up in the step manifest).
"""

import json
import os
import textwrap

import numpy as np
import pytest

from shifu_tpu.analysis.engine import analyze, report_json


def check_snippet(tmp_path, src, rules=None, name="snippet.py"):
    path = tmp_path / name
    path.write_text(textwrap.dedent(src))
    return analyze([str(path)], rule_ids=rules)


def rule_lines(findings, rule, suppressed=False):
    return [f.line for f in findings
            if f.rule == rule and f.suppressed == suppressed]


# ---------------------------------------------------------------------------
# engine: reporters, suppression, selection
# ---------------------------------------------------------------------------


class TestEngine:
    def test_json_reporter_schema(self, tmp_path):
        findings = check_snippet(tmp_path, """
            import jax

            @jax.jit
            def f(x):
                return float(x.sum())
        """)
        doc = json.loads(report_json(findings))
        assert doc["schema"] == "shifu.check/1"
        assert set(doc["counts"]) >= {"error", "warning", "suppressed"}
        assert doc["counts"]["error"] == 1
        (f,) = doc["findings"]
        assert {"rule", "severity", "path", "line", "col", "message",
                "suppressed"} <= set(f)
        assert f["rule"] == "JX001" and f["severity"] == "error"
        # the rule catalog rides along for tooling
        assert doc["rules"]["JX001"]["severity"] == "error"

    def test_noqa_suppression(self, tmp_path):
        findings = check_snippet(tmp_path, """
            import jax

            @jax.jit
            def f(x):
                a = float(x.sum())  # shifu: noqa[JX001] - test fixture
                b = float(x.max())  # shifu: noqa
                c = float(x.min())  # shifu: noqa[JX004] - wrong rule id
                return a + b + c
        """)
        assert rule_lines(findings, "JX001", suppressed=True) == [6, 7]
        assert rule_lines(findings, "JX001") == [8]  # wrong id ≠ suppressed

    def test_suppressed_errors_exit_zero(self, tmp_path):
        from shifu_tpu.analysis.engine import run_check

        path = tmp_path / "ok.py"
        path.write_text(textwrap.dedent("""
            import jax

            @jax.jit
            def f(x):
                return float(x.sum())  # shifu: noqa[JX001] - fixture
        """))
        emitted = []
        assert run_check([str(path)], emit=emitted.append) == 0
        assert "1 suppressed" in emitted[0]

    def test_rule_selection_and_unknown_rule(self, tmp_path):
        src = """
            import jax

            @jax.jit
            def f(x, flags=[]):
                return float(x.sum())
        """
        only_jx1 = check_snippet(tmp_path, src, rules=["JX001"])
        assert {f.rule for f in only_jx1} == {"JX001"}
        with pytest.raises(ValueError, match="unknown rule"):
            check_snippet(tmp_path, src, rules=["JX999"])

    def test_parse_error_is_a_finding(self, tmp_path):
        findings = check_snippet(tmp_path, "def broken(:\n")
        assert findings[0].rule == "PARSE"
        assert findings[0].severity == "error"

    def test_cli_check(self, tmp_path, capsys):
        from shifu_tpu import cli

        bad = tmp_path / "bad.py"
        bad.write_text("import jax\n\n@jax.jit\ndef f(x):\n"
                       "    return float(x.sum())\n")
        assert cli.main(["check", str(bad)]) == 1
        assert "JX001" in capsys.readouterr().out
        good = tmp_path / "good.py"
        good.write_text("def f():\n    return 1\n")
        assert cli.main(["check", "--json", str(good)]) == 0
        doc = json.loads(capsys.readouterr().out)
        assert doc["findings"] == []
        assert cli.main(["check", "--list-rules"]) == 0
        out = capsys.readouterr().out
        for rid in ("JX001", "JX002", "JX003", "JX004", "JX005",
                    "SH101", "SH102", "SH103"):
            assert rid in out


# ---------------------------------------------------------------------------
# JX rules: one positive + one negative fixture each
# ---------------------------------------------------------------------------


class TestJaxRules:
    def test_jx001_host_sync_reachable_from_jit(self, tmp_path):
        findings = check_snippet(tmp_path, """
            import jax
            import numpy as np

            def helper(x):                    # traced: called from step
                return np.asarray(x) + x.item()

            @jax.jit
            def step(x):
                return helper(x) + float(x.sum())

            def host_report(x):               # NOT traced: same calls ok
                return np.asarray(x), x.item(), float(x.sum())
        """)
        assert rule_lines(findings, "JX001") == [6, 6, 10]

    def test_jx001_call_form_and_lax_bodies(self, tmp_path):
        findings = check_snippet(tmp_path, """
            import jax

            def body(c, x):                   # traced via lax.scan below
                c.tolist()
                return c, x

            def run(xs):
                return jax.lax.scan(body, 0, xs)
        """)
        assert rule_lines(findings, "JX001") == [5]

    def test_jx001_negative_shapes_and_device_code(self, tmp_path):
        findings = check_snippet(tmp_path, """
            import jax
            import jax.numpy as jnp

            @jax.jit
            def f(x):
                n = int(x.shape[0])           # shapes are host ints
                k = len(x)
                return jnp.sum(x) * n * k
        """)
        assert rule_lines(findings, "JX001") == []

    def test_jx002_unhashable_static_and_omitted_static(self, tmp_path):
        findings = check_snippet(tmp_path, """
            import jax
            from functools import partial

            @partial(jax.jit, static_argnames=("cols",))
            def f(x, cols=[]):                # unhashable static default
                return x

            @jax.jit
            def g(x, training):
                if training:                  # tracer bool: omitted static
                    return x * 2
                return x

            @partial(jax.jit, static_argnames=("training",))
            def ok(x, training):
                if training:                  # declared static: fine
                    return x * 2
                return x
        """)
        # line 6: the unhashable default node; line 11: the `if training`
        assert rule_lines(findings, "JX002") == [6, 11]

    def test_jx002_positional_only_static_default(self, tmp_path):
        findings = check_snippet(tmp_path, """
            import jax
            from functools import partial

            @partial(jax.jit, static_argnames=("cols",))
            def f(x, /, cols=[]):
                return x
        """)
        assert rule_lines(findings, "JX002") == [6]

    def test_jx003_jit_in_loop(self, tmp_path):
        findings = check_snippet(tmp_path, """
            import jax
            from functools import partial

            def grow(levels):
                progs = []
                for d in range(levels):
                    progs.append(jax.jit(lambda v: v * d))
                while levels:
                    p = partial(jax.jit, donate_argnums=0)
                    levels -= 1
                return progs

            hoisted = jax.jit(lambda v: v + 1)   # module level: fine

            def cached(key, table):
                if key not in table:
                    table[key] = jax.jit(lambda v: v)  # not in a loop
                return table[key]
        """)
        assert rule_lines(findings, "JX003") == [8, 10]

    def test_jx004_float64_guard(self, tmp_path):
        findings = check_snippet(tmp_path, """
            import jax
            import jax.numpy as jnp
            import numpy as np

            acc64 = bool(jax.config.jax_enable_x64)

            bad = jnp.zeros(4, jnp.float64)
            good = jnp.zeros(4, jnp.float64 if acc64 else jnp.float32)
            host = np.zeros(4, np.float64)        # host f64: fine

            if jax.config.jax_enable_x64:
                also_good = jnp.ones(4, jnp.float64)
        """)
        assert rule_lines(findings, "JX004") == [8]

    def test_jx005_side_effects_under_jit(self, tmp_path):
        findings = check_snippet(tmp_path, """
            import jax

            history = []

            @jax.jit
            def step(x):
                print("step", x)              # trace-time only
                history.append(x)             # captured mutation
                local = []
                local.append(x)               # local build-up: fine
                return x

            def host_loop(xs):
                print("epoch", xs)            # host print: fine
                history.append(xs)
        """)
        assert rule_lines(findings, "JX005") == [8, 9]


# ---------------------------------------------------------------------------
# SH rules
# ---------------------------------------------------------------------------


class TestHygieneRules:
    def test_sh101_bare_blanket_and_justified(self, tmp_path):
        findings = check_snippet(tmp_path, """
            def f():
                try:
                    work()
                except:
                    return None
                try:
                    work()
                except Exception:
                    pass
                try:
                    work()
                except Exception:
                    return None
                try:
                    work()
                except Exception:  # probing optional dep: absence is fine
                    return None
                try:
                    work()
                except Exception:
                    raise
                try:
                    work()
                except ValueError:
                    return None
        """)
        errors = [f for f in findings if f.rule == "SH101"
                  and f.severity == "error"]
        warnings = [f for f in findings if f.rule == "SH101"
                    and f.severity == "warning"]
        assert [f.line for f in errors] == [5, 9]      # bare + swallow
        assert [f.line for f in warnings] == [13]      # unjustified blanket

    def test_sh101_pragma_only_comment_is_not_justification(self, tmp_path):
        findings = check_snippet(tmp_path, """
            def f():
                try:
                    work()
                except Exception:  # noqa: E722
                    return None
                try:
                    work()
                except Exception:  # type: ignore
                    return None
                try:
                    work()
                except Exception:  # pragma: no cover - dep may be absent
                    return None
        """)
        warnings = [f.line for f in findings if f.rule == "SH101"]
        # tool pragmas alone don't justify; pragma + prose does
        assert warnings == [5, 9]

    def test_sh102_mutable_defaults(self, tmp_path):
        findings = check_snippet(tmp_path, """
            def bad(x, acc=[], table={}, seen=set()):
                return x

            def good(x, acc=None, names=()):
                return x
        """)
        assert len(rule_lines(findings, "SH102")) == 3

    def test_sh103_streaming_plumbing(self, tmp_path):
        findings = check_snippet(tmp_path, """
            def train_foo_streamed(data_dir, cfg):
                for shard in open(data_dir):      # hand-rolled loop
                    pass

            def train_bar_streamed(data_dir, cfg, chunk_rows=65536):
                return data_dir                   # plumbed kwarg

            def compute_baz_streaming(mc, chunk_factory):
                return mc                         # factory param

            def train_qux_streamed(data_dir):
                feed = prefetch_iter(range(3))    # drives the pipeline
                return list(feed)

            def prefetch_iter(it):
                return it

            def should_stream(path):              # predicate: not an entry
                return False
        """)
        assert rule_lines(findings, "SH103") == [2]

    def test_sh103_chunk_loop_needs_shard_plan(self, tmp_path):
        """The sharded-lifecycle extension: an entry point that loops raw
        ingest chunks must go through the shard planner or declare
        single-shard intent — otherwise the next contributor quietly
        reintroduces an O(rows) path."""
        findings = check_snippet(tmp_path, """
            def score_all_streaming(path, names):
                for chunk in chunk_source(path, names)():  # no plan
                    consume(chunk)

            def fold_planned_streaming(path, names):
                plan = ShardPlan()
                for ci, chunk in enumerate(chunk_source(path, names)()):
                    fold(plan.shard_of(ci), chunk)

            def sweep_local_streaming(path, names):
                '''Tallies pre-reduced rows; deliberately single-shard.'''
                for chunk in chunk_source(path, names)():
                    tally(chunk)

            def chunk_source(path, names):
                return lambda: iter(())
        """)
        assert rule_lines(findings, "SH103") == [2]
        assert "ShardPlan" in findings[0].message

    def test_sh103_applies_to_methods(self, tmp_path):
        """Lifecycle entry points are processor METHODS — the rule must
        reach inside classes (the real `_score_streaming`/`_run_streaming`
        seams live there)."""
        findings = check_snippet(tmp_path, """
            class P:
                def _score_streaming(self, path):
                    for chunk in iter_columnar_chunks(path):
                        self.emit(chunk)
        """)
        assert rule_lines(findings, "SH103") == [3]


# ---------------------------------------------------------------------------
# concurrency rules (SH201-SH204) + knob catalog (SH105)
# ---------------------------------------------------------------------------


class TestConcurrencyRules:
    def test_sh201_unguarded_mutation_of_guarded_attr(self, tmp_path):
        findings = check_snippet(tmp_path, """
            import threading

            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._n = 0

                def bump(self):
                    with self._lock:
                        self._n += 1

                def read(self):
                    with self._lock:
                        return self._n

                def double(self):
                    with self._lock:
                        self._n *= 2

                def reset(self):
                    self._n = 0
        """, rules=["SH201"])
        (line,) = rule_lines(findings, "SH201")
        f = [x for x in findings if x.rule == "SH201"][0]
        assert "self._n" in f.message and "C._lock" in f.message
        assert "reset" in f.message

    def test_sh201_exemptions_init_guardedby_lockedname(self, tmp_path):
        findings = check_snippet(tmp_path, """
            import threading
            from shifu_tpu.analysis.racetrack import guarded_by

            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._n = 0              # construction: exempt

                def bump(self):
                    with self._lock:
                        self._n += 1

                def read(self):
                    with self._lock:
                        return self._n

                @guarded_by("_lock")
                def reset(self):
                    self._n = 0              # declared caller-holds

                def _clear_locked(self):
                    self._n = 0              # *_locked convention
        """, rules=["SH201"])
        assert rule_lines(findings, "SH201") == []

    def test_sh201_unlocked_attrs_not_inferred(self, tmp_path):
        # an attribute never accessed under the lock has no inferred
        # discipline — the rule must not invent one
        findings = check_snippet(tmp_path, """
            import threading

            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.free = 0

                def a(self):
                    self.free += 1

                def b(self):
                    self.free = 2
        """, rules=["SH201"])
        assert rule_lines(findings, "SH201") == []

    def test_sh201_thread_reachability_in_message(self, tmp_path):
        findings = check_snippet(tmp_path, """
            import threading

            class W:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._q = []
                    self._t = threading.Thread(target=self._run)

                def _run(self):
                    self._q.append(1)        # mutation on the worker

                def put(self, x):
                    with self._lock:
                        self._q.append(x)

                def drain(self):
                    with self._lock:
                        out, self._q = self._q, []
                    return out
        """, rules=["SH201"])
        (f,) = [x for x in findings if x.rule == "SH201"]
        assert "thread-reachable" in f.message
        assert "Thread(target=...)" in f.message

    def test_sh202_inverted_nesting_is_a_cycle(self, tmp_path):
        findings = check_snippet(tmp_path, """
            import threading

            _a = threading.Lock()
            _b = threading.Lock()

            def one():
                with _a:
                    with _b:
                        pass

            def two():
                with _b:
                    with _a:
                        pass
        """, rules=["SH202"])
        assert len(rule_lines(findings, "SH202")) == 2  # both edges
        f = [x for x in findings if x.rule == "SH202"][0]
        assert "._a" in f.message and "._b" in f.message

    def test_sh202_one_hop_through_a_call(self, tmp_path):
        findings = check_snippet(tmp_path, """
            import threading

            class C:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()

                def _take_a(self):
                    with self._a:
                        pass

                def forward(self):
                    with self._a:
                        with self._b:
                            pass

                def backward(self):
                    with self._b:
                        self._take_a()
        """, rules=["SH202"])
        assert len(rule_lines(findings, "SH202")) >= 1

    def test_sh202_consistent_order_is_clean(self, tmp_path):
        findings = check_snippet(tmp_path, """
            import threading

            _a = threading.Lock()
            _b = threading.Lock()

            def one():
                with _a:
                    with _b:
                        pass

            def two():
                with _a:
                    with _b:
                        pass
        """, rules=["SH202"])
        assert rule_lines(findings, "SH202") == []

    def test_sh203_blocking_under_lock(self, tmp_path):
        findings = check_snippet(tmp_path, """
            import threading
            import time

            import jax

            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._w = None
                    self._done = threading.Event()

                def flush_bad(self):
                    with self._lock:
                        return jax.device_get(self._w)

                def flush_good(self):
                    with self._lock:
                        w = self._w
                    return jax.device_get(w)

                def nap(self):
                    with self._lock:
                        time.sleep(0.5)

                def park(self):
                    with self._lock:
                        self._done.wait(1.0)
        """, rules=["SH203"])
        lines = rule_lines(findings, "SH203")
        assert len(lines) == 3
        msgs = " | ".join(f.message for f in findings
                          if f.rule == "SH203")
        assert "device" in msgs and "sleep" in msgs
        assert "waiting on" in msgs  # event wait while holding the lock

    def test_sh203_caller_holds_body_and_one_hop(self, tmp_path):
        findings = check_snippet(tmp_path, """
            import threading

            from shifu_tpu.resilience.checkpoint import atomic_write

            class T:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._buf = []

                def _rotate_locked(self):
                    atomic_write("p", b"x")   # runs under caller's lock

                def flush(self):
                    with self._lock:
                        self._rotate_locked()
        """, rules=["SH203"])
        # flagged in the caller-holds body AND at the locked call site
        assert len(rule_lines(findings, "SH203")) == 2

    def test_sh203_condition_wait_is_exempt(self, tmp_path):
        findings = check_snippet(tmp_path, """
            import threading

            class Q:
                def __init__(self):
                    self._cond = threading.Condition()
                    self._items = []

                def get(self):
                    with self._cond:
                        while not self._items:
                            self._cond.wait()
                        return self._items.pop()
        """, rules=["SH203"])
        assert rule_lines(findings, "SH203") == []

    def test_sh204_notify_and_wait_protocols(self, tmp_path):
        findings = check_snippet(tmp_path, """
            import threading

            class C:
                def __init__(self):
                    self._cond = threading.Condition()
                    self._done = threading.Event()
                    self._flag = False

                def wake_bad(self):
                    self._cond.notify_all()

                def wake_ok(self):
                    with self._cond:
                        self._cond.notify()

                def wait_noloop(self):
                    with self._cond:
                        self._cond.wait()

                def wait_ok(self):
                    with self._cond:
                        while not self._flag:
                            self._cond.wait()

                def park_bad(self):
                    self._done.wait()

                def park_ok(self):
                    return self._done.wait(1.0)
        """, rules=["SH204"])
        errors = [f for f in findings if f.rule == "SH204"
                  and f.severity == "error"]
        warnings = [f for f in findings if f.rule == "SH204"
                    and f.severity == "warning"]
        assert len(errors) == 1            # notify outside the lock
        assert "notify_all" in errors[0].message
        assert len(warnings) == 2          # no-loop wait + unbounded park
        msgs = " | ".join(w.message for w in warnings)
        assert "predicate loop" in msgs and "unbounded" in msgs


# ---------------------------------------------------------------------------
# JX3xx/SH3xx: SPMD & multi-host determinism rules
# ---------------------------------------------------------------------------


class TestSpmdRules:
    # -- JX301: collective under per-host control flow --
    def test_jx301_barrier_under_divergent_branch(self, tmp_path):
        findings = check_snippet(tmp_path, """
            import jax
            from shifu_tpu.parallel import hostsync

            def merge(root, plan, sha):
                if jax.process_index() == 0:
                    hostsync.await_parts(root, "stats", plan, sha)

            def reduce_local(x):
                idx = jax.process_index()
                if idx == 0:
                    return jax.lax.psum(x, "data")
                return x
        """, rules=["JX301"])
        lines = rule_lines(findings, "JX301")
        assert len(lines) == 2
        msgs = [f.message for f in findings if f.rule == "JX301"]
        assert any("await_parts" in m and "per-host branch" in m
                   for m in msgs)
        assert any("psum" in m for m in msgs)

    def test_jx301_indirect_through_helper(self, tmp_path):
        findings = check_snippet(tmp_path, """
            from shifu_tpu.parallel import hostsync

            def _publish(root, plan, sha):
                hostsync.publish_part(root, "stats", plan, sha)

            def run(root, plan, sha):
                host = plan.host_index
                if host == 0:
                    _publish(root, plan, sha)
        """, rules=["JX301"])
        (line,) = rule_lines(findings, "JX301")
        (f,) = [x for x in findings if x.rule == "JX301"]
        assert "_publish" in f.message and "per-host" in f.message

    def test_jx301_uniform_and_post_barrier_guards_clean(self, tmp_path):
        findings = check_snippet(tmp_path, """
            from shifu_tpu.parallel import hostsync

            def run(root, plan, sha, write_merged):
                # uniform predicate: every host takes the same branch
                if plan.n_hosts > 1:
                    hostsync.publish_part(root, "stats", plan, sha)
                    parts = hostsync.await_parts(root, "stats", plan, sha)
                    # leader-only work AFTER the barrier is the pattern
                    if plan.host_index == 0:
                        write_merged(parts)
        """, rules=["JX301"])
        assert rule_lines(findings, "JX301") == []

    # -- JX302: axis names must exist in the mesh spec --
    def test_jx302_axis_absent_from_mesh(self, tmp_path):
        findings = check_snippet(tmp_path, """
            import jax
            from jax.sharding import Mesh

            def body(x):
                return jax.lax.psum(x, "model")

            def dispatch(devs, x):
                mesh = Mesh(devs, ("data",))
                return shard_map_compat(body, mesh, x)
        """, rules=["JX302"])
        (f,) = [x for x in findings if x.rule == "JX302"]
        assert "'model'" in f.message and "['data']" in f.message

    def test_jx302_declared_axes_clean(self, tmp_path):
        findings = check_snippet(tmp_path, """
            import jax
            from jax.sharding import Mesh

            def body(x):
                return jax.lax.psum(x, "data")

            def dispatch(devs, x):
                mesh = Mesh(devs, ("dcn", "data"))
                return shard_map_compat(body, mesh, x)

            def dynamic(devs, x, axes):
                mesh = Mesh(devs, ("data",))
                # non-literal axis operand: skipped, never guessed
                return shard_map_compat(lambda v: jax.lax.psum(v, axes),
                                        mesh, x)
        """, rules=["JX302"])
        assert rule_lines(findings, "JX302") == []

    def test_jx302_mesh_through_producer_def(self, tmp_path):
        findings = check_snippet(tmp_path, """
            import jax
            from jax.sharding import Mesh

            def data_mesh(devs):
                return Mesh(devs, ("dcn", "data"))

            def body(x):
                return jax.lax.pmean(x, axis_name="model")

            def dispatch(devs, x):
                return shard_map_compat(body, data_mesh(devs), x)
        """, rules=["JX302"])
        (f,) = [x for x in findings if x.rule == "JX302"]
        assert "'model'" in f.message

    # -- SH301: unsorted listing / set walk --
    def test_sh301_unsorted_listing_and_set_walk(self, tmp_path):
        findings = check_snippet(tmp_path, """
            import glob
            import os

            def merge(d, fold):
                for p in glob.glob(os.path.join(d, "part-*")):
                    fold(p)
                for name in os.listdir(d):
                    fold(name)
                for col in {"a", "b", "c"}:
                    fold(col)
        """, rules=["SH301"])
        assert len(rule_lines(findings, "SH301")) == 3

    def test_sh301_sorted_and_order_free_clean(self, tmp_path):
        findings = check_snippet(tmp_path, """
            import glob
            import os
            from shifu_tpu.fs.listing import sorted_glob

            def merge(d, fold):
                for p in sorted(glob.glob(os.path.join(d, "part-*"))):
                    fold(p)
                for p in sorted_glob(os.path.join(d, "part-*")):
                    fold(p)
                n = len(os.listdir(d))                 # count only
                names = set(os.listdir(d))             # set algebra
                ok = "x" in os.listdir(d)              # membership
                stale = sorted(p for p in glob.glob(d + "/*")
                               if p.endswith(".tmp"))  # via genexp
                return n, names, ok, stale
        """, rules=["SH301"])
        assert rule_lines(findings, "SH301") == []

    # -- SH302: opposite barrier await orders --
    def test_sh302_opposite_orders_both_witnessed(self, tmp_path):
        findings = check_snippet(tmp_path, """
            from shifu_tpu.parallel import hostsync

            def path_a(root, plan, sha):
                hostsync.await_parts(root, "pass1", plan, sha)
                hostsync.await_parts(root, "pass2", plan, sha)

            def path_b(root, plan, sha):
                hostsync.await_parts(root, "pass2", plan, sha)
                hostsync.await_parts(root, "pass1", plan, sha)
        """, rules=["SH302"])
        msgs = [f.message for f in findings if f.rule == "SH302"]
        assert len(msgs) == 2      # one witness per direction
        assert any("'pass1' -> 'pass2'" in m for m in msgs)
        assert any("'pass2' -> 'pass1'" in m for m in msgs)
        # each witness points at the site of the OTHER direction
        assert all("snippet.py:" in m for m in msgs)

    def test_sh302_consistent_order_clean(self, tmp_path):
        findings = check_snippet(tmp_path, """
            from shifu_tpu.parallel import hostsync

            def pass1(root, plan, sha):
                hostsync.await_parts(root, "pass1", plan, sha)

            def both(root, plan, sha):
                pass1(root, plan, sha)
                hostsync.await_parts(root, "pass2", plan, sha)

            def again(root, plan, sha):
                hostsync.await_parts(root, "pass1", plan, sha)
                hostsync.await_parts(root, "pass2", plan, sha)
        """, rules=["SH302"])
        assert rule_lines(findings, "SH302") == []

    # -- SH303: nondeterminism in fingerprint computation --
    def test_sh303_wall_clock_and_randomness(self, tmp_path):
        findings = check_snippet(tmp_path, """
            import hashlib
            import json
            import time
            import uuid

            def config_sha(props):
                ident = {"props": props, "at": time.time(),
                         "run": uuid.uuid4().hex}
                return hashlib.sha256(
                    json.dumps(ident, sort_keys=True).encode()).hexdigest()
        """, rules=["SH303"])
        msgs = [f.message for f in findings if f.rule == "SH303"]
        assert len(msgs) == 2
        assert any("time.time" in m and "wall-clock" in m for m in msgs)
        assert any("uuid" in m and "randomness" in m for m in msgs)

    def test_sh303_reaches_through_call_graph(self, tmp_path):
        findings = check_snippet(tmp_path, """
            import random

            def _salt():
                return random.random()

            def stream_fingerprint(cols):
                return hash((tuple(cols), _salt()))
        """, rules=["SH303"])
        (f,) = [x for x in findings if x.rule == "SH303"]
        assert "random.random" in f.message and "_salt" in f.message

    def test_sh303_durations_and_nonfingerprints_clean(self, tmp_path):
        findings = check_snippet(tmp_path, """
            import time
            import uuid

            def config_sha_age(started):
                # durations are fine: monotonic is excluded by design
                return time.monotonic() - started

            def shadow_run_name():
                # not fingerprint-named ("shadow" must not match "sha")
                return uuid.uuid4().hex
        """, rules=["SH303"])
        assert rule_lines(findings, "SH303") == []


# ---------------------------------------------------------------------------
# --baseline / --write-baseline and the SARIF reporter
# ---------------------------------------------------------------------------


class TestBaselineAndSarif:
    SRC = """
        import glob

        def merge(d, fold):
            for p in glob.glob(d + "/part-*"):
                fold(p)
    """

    def test_baseline_round_trip(self, tmp_path):
        from shifu_tpu.analysis.engine import (
            apply_baseline, counts, load_baseline, write_baseline)

        findings = check_snippet(tmp_path, self.SRC)
        assert counts(findings)["error"] == 1
        base = tmp_path / "base.json"
        assert write_baseline(findings, str(base)) == 1
        doc = json.loads(base.read_text())
        assert doc["schema"] == "shifu.baseline/1"
        # counted-not-dropped: the finding stays, flagged baselined
        apply_baseline(findings, load_baseline(str(base)))
        c = counts(findings)
        assert c["error"] == 0 and c["baselined"] == 1
        assert findings[0].baselined is True

    def test_baseline_key_survives_line_moves(self, tmp_path):
        a = check_snippet(tmp_path, self.SRC, name="a.py")
        moved = ("\n\n\n# a comment pushing everything down\n"
                 + textwrap.dedent(self.SRC))
        b = check_snippet(tmp_path, moved, name="a.py")
        assert a[0].line != b[0].line
        assert a[0].baseline_key() == b[0].baseline_key()

    def test_baseline_rejects_foreign_schema(self, tmp_path):
        from shifu_tpu.analysis.engine import load_baseline

        p = tmp_path / "not-a-baseline.json"
        p.write_text(json.dumps({"schema": "shifu.check/1"}))
        with pytest.raises(ValueError, match="shifu.baseline/1"):
            load_baseline(str(p))

    def test_cli_baseline_gates_exit(self, tmp_path, capsys):
        from shifu_tpu.cli import main

        src = tmp_path / "bad.py"
        src.write_text(textwrap.dedent(self.SRC))
        base = str(tmp_path / "base.json")
        assert main(["check", str(src)]) == 1
        assert main(["check", "--write-baseline", base, str(src)]) == 0
        assert main(["check", "--baseline", base, str(src)]) == 0
        out = capsys.readouterr().out
        assert "1 baselined" in out
        # a NEW finding is not absorbed by the old baseline
        src.write_text(textwrap.dedent(self.SRC) + textwrap.dedent("""
            import os

            def walk(d, fold):
                for name in os.listdir(d):
                    fold(name)
        """))
        assert main(["check", "--baseline", base, str(src)]) == 1

    def test_sarif_round_trip(self, tmp_path, capsys):
        from shifu_tpu.cli import main

        src = tmp_path / "bad.py"
        src.write_text(textwrap.dedent(self.SRC))
        assert main(["check", "--format", "sarif", str(src)]) == 1
        doc = json.loads(capsys.readouterr().out)
        assert doc["version"] == "2.1.0"
        (run,) = doc["runs"]
        driver = run["tool"]["driver"]
        assert driver["name"] == "shifu check"
        ids = [r["id"] for r in driver["rules"]]
        assert ids == sorted(ids)
        assert {"JX301", "JX302", "SH301", "SH302", "SH303"} <= set(ids)
        (res,) = run["results"]
        assert res["ruleId"] == "SH301" and res["level"] == "error"
        assert driver["rules"][res["ruleIndex"]]["id"] == "SH301"
        loc = res["locations"][0]["physicalLocation"]
        assert loc["artifactLocation"]["uri"].endswith("bad.py")
        assert loc["region"]["startLine"] == 5
        assert loc["region"]["startColumn"] >= 1  # SARIF is 1-based

    def test_sarif_carries_suppressions(self, tmp_path):
        from shifu_tpu.analysis.engine import report_sarif

        findings = check_snippet(tmp_path, """
            import glob

            def merge(d, fold):
                for p in glob.glob(d + "/*"):  # shifu: noqa[SH301] - fixture
                    fold(p)
        """)
        doc = json.loads(report_sarif(findings))
        (res,) = doc["runs"][0]["results"]
        assert res["suppressions"] == [{"kind": "inSource"}]


class TestKnobCatalog:
    def test_sh105_undeclared_and_mistyped(self, tmp_path):
        findings = check_snippet(tmp_path, """
            from shifu_tpu.utils import environment

            def knobs():
                a = environment.get_int("shifu.serve.maxBatchRow", 1)
                b = environment.get_int("shifu.loop.logSample", 0)
                c = environment.get_float("shifu.loop.logSample", 0.0)
                d = environment.get_property("shifu.serve.maxWaitMs", "")
                e = environment.get_int("shifu.serve.maxBatchRows", 1024)
                return a, b, c, d, e
        """, rules=["SH105"])
        msgs = [f.message for f in findings if f.rule == "SH105"]
        assert len(msgs) == 2
        assert any("does not declare" in m for m in msgs)   # typo'd key
        assert any("declared as float" in m for m in msgs)  # get_int

    def test_sh105_dynamic_keys_and_constants(self, tmp_path):
        findings = check_snippet(tmp_path, """
            from shifu_tpu.utils import environment

            PROP = "shifu.faults"

            def read(seam):
                a = environment.get_property(PROP, "")
                b = environment.get_int(f"shifu.retry.{seam}.max", 3)
                c = environment.get_float(f"shifu.bogus.{seam}.x", 0.0)
                return a, b, c
        """, rules=["SH105"])
        msgs = [f.message for f in findings if f.rule == "SH105"]
        assert len(msgs) == 1
        assert "shifu.bogus.*.x" in msgs[0]

    def test_sh105_unread_declared_knob_flagged_in_catalog(self, tmp_path):
        # a fixture "catalog" (path ends analysis/knobs.py) declaring a
        # real knob that nothing in the fixture tree reads
        pkg = tmp_path / "analysis"
        pkg.mkdir()
        (pkg / "knobs.py").write_text(textwrap.dedent("""
            KNOBS = [_K("shifu.loop.appendTrees", "int", "10", "doc")]
        """))
        findings = analyze([str(tmp_path)], rule_ids=["SH105"])
        (f,) = [x for x in findings if x.rule == "SH105"]
        assert "nothing reads it" in f.message
        # ... and with a reader present, it is clean
        (tmp_path / "reader.py").write_text(textwrap.dedent("""
            from shifu_tpu.utils import environment

            def n():
                return environment.get_int("shifu.loop.appendTrees", 10)
        """))
        findings = analyze([str(tmp_path)], rule_ids=["SH105"])
        assert [x for x in findings if x.rule == "SH105"] == []

    def test_knobs_markdown_committed_file_is_fresh(self):
        """CI staleness gate: docs/KNOBS.md must equal the generated
        catalog (regenerate with `shifu check --knobs > docs/KNOBS.md`)."""
        from shifu_tpu.analysis.knobs import render_markdown

        import shifu_tpu

        repo = os.path.dirname(os.path.dirname(
            os.path.abspath(shifu_tpu.__file__)))
        path = os.path.join(repo, "docs", "KNOBS.md")
        with open(path) as fh:
            committed = fh.read()
        assert committed == render_markdown(), (
            "docs/KNOBS.md is stale — regenerate with "
            "`python -m shifu_tpu check --knobs > docs/KNOBS.md`")

    def test_knobs_cli_flag(self, tmp_path, capsys):
        from shifu_tpu.cli import main

        assert main(["check", "--knobs"]) == 0
        out = capsys.readouterr().out
        assert "shifu.sanitize.race.holdMs" in out
        assert out.startswith("# `-Dshifu.*` knob catalog")


# ---------------------------------------------------------------------------
# self-check: the shipped tree is clean (the at-merge acceptance bar)
# ---------------------------------------------------------------------------


class TestSelfCheck:
    def test_shifu_tpu_tree_is_clean(self):
        import shifu_tpu
        from shifu_tpu.analysis.engine import all_rules

        # the SPMD/multi-host family must be registered — the clean
        # sweep below is vacuous for rules that never ran
        assert {"JX301", "JX302", "SH301", "SH302",
                "SH303"} <= set(all_rules())
        pkg = os.path.dirname(os.path.abspath(shifu_tpu.__file__))
        findings = analyze([pkg])
        live = [f for f in findings if not f.suppressed]
        assert [f"{f.path}:{f.line} {f.rule} {f.message}"
                for f in live if f.severity == "error"] == []
        # warnings are not gated, but the tree ships warning-free too
        assert [f"{f.path}:{f.line} {f.rule}" for f in live] == []


# ---------------------------------------------------------------------------
# runtime sanitizer harness
# ---------------------------------------------------------------------------


class TestSanitizer:
    def test_mode_parsing(self):
        from shifu_tpu.analysis import sanitize
        from shifu_tpu.utils import environment

        environment.set_property("shifu.sanitize", "transfer, nan")
        try:
            assert sanitize.modes_from_environment() == ["transfer", "nan"]
            environment.set_property("shifu.sanitize", "all")
            assert set(sanitize.modes_from_environment()) == {
                "transfer", "nan", "recompile", "race", "divergence"}
            environment.set_property("shifu.sanitize", "transfr")
            with pytest.raises(ValueError, match="unknown mode"):
                sanitize.modes_from_environment()
        finally:
            environment.set_property("shifu.sanitize", "")
        assert sanitize.modes_from_environment() == []

    def test_transfer_trip_records_and_raises(self):
        import jax

        from shifu_tpu import obs
        from shifu_tpu.analysis import sanitize

        obs.reset()
        san = sanitize.Sanitizer(["transfer"])
        f = jax.jit(lambda a: a + 1)
        f(np.zeros(3, np.float32))  # warm (compile outside the guard)
        with pytest.raises(Exception, match="[Dd]isallowed"):
            with sanitize.activate(san), san.transfer_free("stage.x"):
                f(np.zeros(3, np.float32))  # implicit h2d
        v = san.verdict()
        assert v["transfer"] == {"armed": True, "trips": 1}
        assert v["clean"] is False
        assert v["events"][0]["kind"] == "transfer.trips"
        assert v["events"][0]["stage"] == "stage.x"
        assert obs.registry().counter("sanitizer.transfer.trips").value == 1

    def test_transfer_seam_allows_explicit_and_device_ops(self):
        import jax

        from shifu_tpu.analysis import sanitize

        san = sanitize.Sanitizer(["transfer"])
        f = jax.jit(lambda a: a * 2)
        x = jax.device_put(np.arange(4, dtype=np.float32))
        f(x)
        with sanitize.activate(san), san.transfer_free("stage.clean"):
            y = f(x)
            jax.device_get(y)  # explicit d2h stays legal
        assert san.verdict()["clean"] is True

    def test_transfer_free_noop_when_disarmed(self):
        import jax

        from shifu_tpu.analysis import sanitize

        # no active sanitizer: the library seam must not guard anything
        f = jax.jit(lambda a: a + 3)
        with sanitize.transfer_free("anywhere"):
            f(np.zeros(2, np.float32))  # implicit transfer, tolerated

    def test_nan_trap(self):
        import jax
        import jax.numpy as jnp

        from shifu_tpu import obs
        from shifu_tpu.analysis import sanitize

        obs.reset()
        san = sanitize.Sanitizer(["nan"])
        g = jax.jit(lambda a: jnp.log(a))
        with pytest.raises(FloatingPointError):
            with sanitize.activate(san), san.armed("train.step"):
                g(-np.ones(2, np.float32))
        v = san.verdict()
        assert v["nan"] == {"armed": True, "trips": 1}
        assert v["events"][0]["stage"] == "train.step"

    def test_recompile_breach_is_nonfatal(self):
        import jax

        from shifu_tpu import obs
        from shifu_tpu.analysis import sanitize

        obs.reset()
        san = sanitize.Sanitizer(["recompile"], budget=0)
        with sanitize.activate(san), san.armed("stage.compile"):
            jax.jit(lambda a: a - 7)(np.arange(5.0))  # fresh program
        v = san.verdict()
        assert v["recompile"]["breaches"] == 1
        assert v["recompile"]["budgetPerStage"] == 0
        assert v["clean"] is False
        assert (obs.registry()
                .counter("sanitizer.recompile.breaches").value == 1)

    def test_verdict_schema(self):
        from shifu_tpu.analysis import sanitize

        v = sanitize.Sanitizer(["transfer", "nan", "recompile"]).verdict()
        assert v["schema"] == "shifu.sanitize/1"
        assert set(v) == {"schema", "modes", "stagesArmed", "transfer",
                          "nan", "recompile", "race", "divergence",
                          "events", "clean"}
        assert v["race"] == {"armed": False}
        assert v["divergence"]["armed"] is False
        assert v["clean"] is True


class TestDivergenceSanitizer:
    """-Dshifu.sanitize=divergence: barrier stamps, the mismatch
    refusal, and the single-process fold-digest trail."""

    def test_stamp_seq_is_per_step_per_host(self):
        from shifu_tpu.analysis import sanitize

        san = sanitize.Sanitizer(["divergence"])
        s0 = san.barrier_stamp("stats", 0, "sha", ["a", "b"])
        s1 = san.barrier_stamp("stats", 1, "sha", ["a", "b"])
        # thread-hosts share the process-global sanitizer: each host
        # still gets seq 1 at its first barrier (keyed per (step, host))
        assert s0["seq"] == 1 and s1["seq"] == 1
        assert s0["digest"] == s1["digest"]
        assert san.barrier_stamp("stats", 0, "sha", ["a", "b"])["seq"] == 2
        assert san.barrier_stamp("other", 0, "sha", ["a", "b"])["seq"] == 1

    def test_stamp_digest_covers_sha_and_merge_key_order(self):
        from shifu_tpu.analysis import sanitize

        san = sanitize.Sanitizer(["divergence"])
        base = san.barrier_stamp("stats", 0, "sha", ["a", "b"])
        other_sha = san.barrier_stamp("stats", 1, "sha2", ["a", "b"])
        swapped = san.barrier_stamp("stats", 2, "sha", ["b", "a"])
        assert other_sha["digest"] != base["digest"]
        assert swapped["digest"] != base["digest"]

    def test_check_raises_named_verdict_on_mismatch(self):
        from shifu_tpu import obs
        from shifu_tpu.analysis import sanitize

        obs.reset()
        san = sanitize.Sanitizer(["divergence"])
        own = {"seq": 1, "digest": "aaaa"}
        with pytest.raises(sanitize.DivergenceError,
                           match="host 1 diverged .* digest mismatch"):
            san.check_barrier_stamps(
                "stats", 0, own, {0: own, 1: {"seq": 1, "digest": "bbbb"}})
        with pytest.raises(sanitize.DivergenceError,
                           match="not uniformly armed"):
            san.check_barrier_stamps("stats", 0, own, {0: own, 1: None})
        with pytest.raises(sanitize.DivergenceError,
                           match="out-of-order barrier sequence"):
            san.check_barrier_stamps(
                "stats", 0, own, {0: own, 1: {"seq": 2, "digest": "aaaa"}})
        v = san.verdict()
        assert v["divergence"]["trips"] == 3
        assert v["divergence"]["barriersChecked"] == 3
        assert v["clean"] is False
        assert any(e["kind"] == "divergence.trips" for e in v["events"])
        assert (obs.registry().counter("sanitizer.divergence.checks",
                                       step="stats").value == 3)

    def test_check_tolerates_matching_and_unarmed_self(self):
        from shifu_tpu.analysis import sanitize

        san = sanitize.Sanitizer(["divergence"])
        own = {"seq": 1, "digest": "aaaa"}
        san.check_barrier_stamps("stats", 0, own, {0: own, 1: dict(own)})
        # this host published unarmed: nothing to compare against
        san.check_barrier_stamps("stats", 0, None,
                                 {0: None, 1: {"seq": 9, "digest": "z"}})
        assert san.verdict()["clean"] is True

    def test_record_fold_digests_and_cap(self):
        from shifu_tpu.analysis import sanitize
        from shifu_tpu.utils import environment

        environment.set_property("shifu.sanitize.divergence.maxFolds", "2")
        try:
            san = sanitize.Sanitizer(["divergence"])
            for i in range(4):
                san.record_fold("pipeline.window",
                                [np.full(3, float(i))])
            d = san.verdict()["divergence"]
        finally:
            environment.set_property("shifu.sanitize.divergence.maxFolds",
                                     "")
        # folds past the cap still COUNT; only their digests are dropped
        assert d["foldsRecorded"] == 4
        assert [f["seq"] for f in d["foldDigests"]] == [1, 2]
        assert all(f["stage"] == "pipeline.window"
                   for f in d["foldDigests"])
        # same bytes -> same digest, different bytes -> different
        a = sanitize.Sanitizer(["divergence"])
        a.record_fold("s", [np.arange(4.0)])
        b = sanitize.Sanitizer(["divergence"])
        b.record_fold("s", [np.arange(4.0)])
        c = sanitize.Sanitizer(["divergence"])
        c.record_fold("s", [np.arange(4.0) + 1])
        da = a.verdict()["divergence"]["foldDigests"][0]["digest"]
        db = b.verdict()["divergence"]["foldDigests"][0]["digest"]
        dc = c.verdict()["divergence"]["foldDigests"][0]["digest"]
        assert da == db and da != dc

    def test_module_seams_noop_when_disarmed(self):
        from shifu_tpu.analysis import sanitize

        # no sanitizer active at all
        assert sanitize.barrier_stamp("s", 0, "sha", []) is None
        sanitize.check_barrier_stamps("s", 0, {"seq": 1, "digest": "x"},
                                      {1: None})
        sanitize.record_fold("s", [np.ones(1)])
        # active but divergence NOT in the mode set
        with sanitize.activate(sanitize.Sanitizer(["transfer"])):
            assert sanitize.barrier_stamp("s", 0, "sha", []) is None
        # active and armed: the seams delegate
        with sanitize.activate(sanitize.Sanitizer(["divergence"])) as san:
            stamp = sanitize.barrier_stamp("s", 0, "sha", ["k"])
            assert stamp is not None and stamp["seq"] == 1
            assert san.divergence_stamps == 1


# ---------------------------------------------------------------------------
# ledger integration: verdicts land in the step manifest
# ---------------------------------------------------------------------------


def _processor(root, step, body):
    from shifu_tpu.processor.basic import BasicProcessor

    class P(BasicProcessor):
        def run_step(self):
            body()

    P.step = step
    return P(root)


class TestLedgerIntegration:
    def test_recompile_breach_in_manifest(self, tmp_path):
        import jax

        from shifu_tpu.utils import environment

        environment.set_property("shifu.sanitize", "recompile")
        environment.set_property("shifu.sanitize.recompileBudget", "0")
        try:
            proc = _processor(
                str(tmp_path), "sanstep",
                lambda: jax.jit(lambda a: a + 11)(np.arange(3.0)))
            assert proc.run() == 0  # breach is a warning, not a trap
        finally:
            environment.set_property("shifu.sanitize", "")
            environment.set_property("shifu.sanitize.recompileBudget", "")
        m = json.load(open(os.path.join(
            str(tmp_path), ".shifu", "runs", "sanstep-1.json")))
        assert m["status"] == "ok"
        san = m["sanitizer"]
        assert san["schema"] == "shifu.sanitize/1"
        assert san["modes"] == ["recompile"]
        assert san["recompile"]["breaches"] >= 1
        assert san["clean"] is False
        assert any(e["kind"] == "recompile.breaches" for e in san["events"])
        # the counters mirror into the manifest's metrics snapshot too
        assert m["metrics"]["counters"]["sanitizer.recompile.breaches"] >= 1

    def test_nan_trap_fails_step_and_lands_in_manifest(self, tmp_path):
        import jax
        import jax.numpy as jnp

        from shifu_tpu.utils import environment

        def bad_step():
            jax.jit(lambda a: jnp.sqrt(a))(-np.ones(2, np.float32))

        environment.set_property("shifu.sanitize", "nan")
        try:
            with pytest.raises(FloatingPointError):
                _processor(str(tmp_path), "nanstep", bad_step).run()
        finally:
            environment.set_property("shifu.sanitize", "")
        m = json.load(open(os.path.join(
            str(tmp_path), ".shifu", "runs", "nanstep-1.json")))
        assert m["status"] == "failed"
        assert m["sanitizer"]["nan"]["trips"] == 1
        assert m["sanitizer"]["clean"] is False

    def test_unsanitized_step_has_no_verdict(self, tmp_path):
        proc = _processor(str(tmp_path), "plainstep", lambda: None)
        assert proc.run() == 0
        m = json.load(open(os.path.join(
            str(tmp_path), ".shifu", "runs", "plainstep-1.json")))
        assert "sanitizer" not in m

    def test_bad_sanitize_value_fails_before_run_depth_leaks(self, tmp_path):
        from shifu_tpu import obs
        from shifu_tpu.utils import environment

        environment.set_property("shifu.sanitize", "transer")  # typo
        try:
            with pytest.raises(ValueError, match="unknown mode"):
                _processor(str(tmp_path), "typostep", lambda: None).run()
        finally:
            environment.set_property("shifu.sanitize", "")
        # the obs run depth stayed balanced: later steps still get a
        # fresh registry each run (counter is per-run, not cumulative)
        def count():
            obs.registry().counter("depthprobe.n").inc()

        for _ in range(2):
            _processor(str(tmp_path), "depthprobe", count).run()
        m = json.load(open(os.path.join(
            str(tmp_path), ".shifu", "runs", "depthprobe-2.json")))
        assert m["metrics"]["counters"]["depthprobe.n"] == 1
