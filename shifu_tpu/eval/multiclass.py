"""Multi-class prediction + confusion matrix.

Parity: core/ConfusionMatrix.java:625
(computeConfusionMatixForMultipleClassification) and
util/MultiClsTagPredictor.java. Three prediction regimes:

  NATIVE NN    score columns are model-major blocks of K per-class scores
               ("1,2,3 4,5,6: 1,2,3 is model 0" — ConfusionMatrix.java:760);
               per-class scores average over models, argmax wins.
  ONEVSALL     one binary model per class -> K columns; class k is "positive"
               when score_k > (1 - prior_k) * scale (the im-balance threshold,
               ConfusionMatrix.java:708-744); among positives the class with
               the LARGEST prior wins; no positive -> the largest-prior class.
  NATIVE RF    per-tree class votes (ConfusionMatrix.java:683-697) — handled
               by the tree scorer emitting per-class vote fractions, then
               argmax here like NATIVE NN.

`priors` are the per-class training frequencies (the reference reads them
from the target column's binCountPos/binCountNeg written by stats); the norm
step records them in meta.json as classPriors.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np


def class_priors(tags: np.ndarray, n_classes: int) -> np.ndarray:
    """Per-class frequency ratios from integer class tags (invalid < 0
    excluded) — binRatio in ConfusionMatrix.java:645-653."""
    t = np.asarray(tags)
    t = t[(t >= 0) & (t < n_classes)]
    counts = np.bincount(t.astype(np.int64), minlength=n_classes).astype(np.float64)
    total = counts.sum()
    return counts / total if total > 0 else np.full(n_classes, 1.0 / n_classes)


def predict_native(scores: np.ndarray, n_classes: int) -> np.ndarray:
    """scores [n, M*K] model-major blocks -> predicted class [n] by argmax of
    the model-averaged per-class score (ConfusionMatrix.java:758-772)."""
    n, c = scores.shape
    if c % n_classes != 0:
        raise ValueError(
            f"{c} score columns are not a multiple of {n_classes} classes"
        )
    m = c // n_classes
    per_class = scores.reshape(n, m, n_classes).mean(axis=1)
    return np.argmax(per_class, axis=1).astype(np.int32)


def predict_one_vs_all(
    scores: np.ndarray,
    priors: np.ndarray,
    scale: float = 1000.0,
) -> np.ndarray:
    """scores [n, K] (model k = class k's binary model, 0..scale). Threshold
    class k at (1 - priors[k]) * scale; among positives pick the class with
    the highest prior; if none, the globally largest-prior class
    (ConfusionMatrix.java:708-744; K == 2 special case :697-706 picks class 0
    iff its score crosses the threshold)."""
    n, k = scores.shape
    priors = np.asarray(priors, np.float64)
    if k == 2 or k == 1:
        # binary: one model decides (only model 0 is consulted)
        pred = np.where(scores[:, 0] > (1.0 - priors[0]) * scale, 0, 1)
        return pred.astype(np.int32)
    thresh = (1.0 - priors) * scale  # [K]
    positive = scores > thresh[None, :]
    # among positives, the highest-prior class; tie-break = first max
    prior_if_pos = np.where(positive, priors[None, :], -1.0)
    best_pos = np.argmax(prior_if_pos, axis=1)
    any_pos = positive.any(axis=1)
    fallback = int(np.argmax(priors))
    return np.where(any_pos, best_pos, fallback).astype(np.int32)


def confusion_matrix_multi(
    tags: np.ndarray, pred: np.ndarray, n_classes: int
) -> np.ndarray:
    """[K, K] counts, rows = actual, cols = predicted
    (confusionMatrix[tagIndex][predictIndex] ConfusionMatrix.java:781)."""
    t = np.asarray(tags, np.int64)
    p = np.asarray(pred, np.int64)
    ok = (t >= 0) & (t < n_classes) & (p >= 0) & (p < n_classes)
    flat = t[ok] * n_classes + p[ok]
    return np.bincount(flat, minlength=n_classes * n_classes).reshape(
        n_classes, n_classes
    )


def confusion_matrix_text(
    matrix: np.ndarray, class_tags: Sequence[str]
) -> str:
    """writeToConfMatrixFile layout: header of predicted tags, one row per
    actual tag."""
    lines = ["\t".join([""] + [str(t) for t in class_tags])]
    for i, t in enumerate(class_tags):
        lines.append("\t".join([str(t)] + [str(int(v)) for v in matrix[i]]))
    return "\n".join(lines) + "\n"


def multiclass_accuracy(matrix: np.ndarray) -> float:
    total = matrix.sum()
    return float(np.trace(matrix) / total) if total > 0 else 0.0
