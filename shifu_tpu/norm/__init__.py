from shifu_tpu.norm.normalizer import (  # noqa: F401
    NormPlan,
    build_norm_plan,
    norm_columns,
    normalize_dataset,
)
