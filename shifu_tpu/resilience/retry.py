"""Bounded retry with exponential backoff + full jitter.

Wraps the transient seams — data-source / remote-source reads, the
prefetch worker's per-chunk transform, compiled-program dispatch on
non-deterministic runtime errors — in a budgeted retry loop. The policy
is the standard one for shared backends: exponential backoff so a
struggling source is not hammered, FULL jitter so a fleet of preempted
hosts resuming together does not thundering-herd it, and a hard attempt
budget so a persistent failure surfaces as the original exception
instead of an infinite stall.

Knobs (per-seam overrides take precedence over the globals)::

    shifu.retry.max            attempt budget, default 3 (1 = no retry)
    shifu.retry.baseMs         first backoff, default 25 ms
    shifu.retry.capMs          backoff ceiling, default 2000 ms
    shifu.retry.<seam>.max     e.g. -Dshifu.retry.io.max=5

Every attempt is ledgered: `retry.attempts{seam=}` counts re-tries,
`retry.recovered{seam=}` counts calls that eventually succeeded after
failing, `retry.exhausted{seam=}` counts budget exhaustions (the
original exception re-raises). Recovered injected faults additionally
count `fault.survived{seam=}` — the proof that chaos runs actually
exercise this path.
"""

from __future__ import annotations

import random
import time
from typing import Callable, Optional, Tuple, Type, TypeVar

from shifu_tpu.resilience.faults import InjectedFaultError, PreemptionError
from shifu_tpu.utils import environment
from shifu_tpu.utils.log import get_logger

log = get_logger(__name__)

T = TypeVar("T")

DEFAULT_MAX_ATTEMPTS = 3
DEFAULT_BASE_MS = 25.0
DEFAULT_CAP_MS = 2000.0

# Transient by default: injected faults (chaos harness) and the OS-level
# errors remote/flaky sources actually throw. PreemptionError is NEVER
# retryable — preemption means "die cleanly and resume", not "try again".
DEFAULT_TRANSIENT: Tuple[Type[BaseException], ...] = (
    InjectedFaultError, OSError, TimeoutError,
)


def max_attempts(seam: str) -> int:
    return max(1, environment.get_int(
        f"shifu.retry.{seam}.max",
        environment.get_int("shifu.retry.max", DEFAULT_MAX_ATTEMPTS)))


def backoff_ms(seam: str) -> Tuple[float, float]:
    base = environment.get_float(
        f"shifu.retry.{seam}.baseMs",
        environment.get_float("shifu.retry.baseMs", DEFAULT_BASE_MS))
    cap = environment.get_float(
        f"shifu.retry.{seam}.capMs",
        environment.get_float("shifu.retry.capMs", DEFAULT_CAP_MS))
    return max(base, 0.0), max(cap, base)


def backoff_window_ms(base_ms: float, cap_ms: float, attempt: int) -> float:
    """The exponentially growing, capped backoff window for attempt
    number `attempt` (1-based) — the one backoff formula in the repo.
    The retry loop AND the serve circuit breaker's open->half-open probe
    schedule both draw their jitter over it, so a fleet of breakers
    tripped by one shared-backend brownout does not probe it back down
    in lockstep."""
    return min(max(cap_ms, 0.0),
               max(base_ms, 0.0) * (2.0 ** (attempt - 1)))


def full_jitter_delay(base_ms: float, cap_ms: float, attempt: int,
                      rng: Optional[random.Random] = None) -> float:
    """Seconds to wait before attempt number `attempt` (1-based): FULL
    jitter over the backoff window."""
    window = backoff_window_ms(base_ms, cap_ms, attempt)
    draw = (rng or random).random()
    return (window * draw) / 1000.0


def backoff_delay(seam: str, attempt: int,
                  rng: Optional[random.Random] = None) -> float:
    """Seconds to sleep before retry number `attempt` (1-based), under
    the seam's configured base/cap."""
    base, cap = backoff_ms(seam)
    return full_jitter_delay(base, cap, attempt, rng=rng)


def retry_call(
    fn: Callable[[], T],
    seam: str,
    retryable: Tuple[Type[BaseException], ...] = DEFAULT_TRANSIENT,
    sleeper: Callable[[float], None] = time.sleep,
    rng: Optional[random.Random] = None,
) -> T:
    """Call `fn()` under the seam's retry budget. Non-retryable
    exceptions (including PreemptionError, always) propagate untouched;
    a retryable one re-raises only after the budget is exhausted."""
    budget = max_attempts(seam)
    from shifu_tpu.obs import registry

    reg = registry()
    failures = 0
    injected = 0
    while True:
        try:
            out = fn()
        except PreemptionError:
            raise
        except retryable as e:
            failures += 1
            if isinstance(e, InjectedFaultError):
                injected += 1
            if failures >= budget:
                reg.counter("retry.exhausted", seam=seam).inc()
                log.warning("%s: retry budget (%d) exhausted: %s",
                            seam, budget, e)
                raise
            reg.counter("retry.attempts", seam=seam).inc()
            delay = backoff_delay(seam, failures, rng=rng)
            log.debug("%s: attempt %d/%d failed (%s); retrying in %.0f ms",
                      seam, failures, budget, e, delay * 1000)
            # jittered, capped exponential backoff — never a fixed sleep
            sleeper(delay)
            continue
        if failures:
            reg.counter("retry.recovered", seam=seam).inc()
            if injected:
                from shifu_tpu.resilience import faults

                faults.survived(seam, injected)
        return out
