"""Reference (DevinWu/shifu) model-spec format compatibility.

Readers/writers for the reference's on-disk model formats so models trained
by either framework score identically in the other:

* :mod:`shifu_tpu.compat.encog`    — Encog EG text ``.nn`` (BasicNetwork)
* :mod:`shifu_tpu.compat.egb`      — BinaryNNSerializer gzip ``.nn``
* :mod:`shifu_tpu.compat.treespec` — BinaryDTSerializer ``.gbt``/``.rf`` + zip
* :mod:`shifu_tpu.compat.javaio`   — java.io.Data{Input,Output}Stream wire format
"""

from shifu_tpu.compat import egb, encog, javaio, treespec  # noqa: F401


def sniff_model_format(data: bytes) -> str:
    """Classify model-file bytes: 'eg-text', 'ref-binary' (gzip Java stream),
    'zip', or 'native' (our npz-style specs)."""
    if data[:6] == b"encog,":
        return "eg-text"
    if data[:2] == b"\x1f\x8b":
        return "ref-binary"
    if data[:2] == b"PK":
        return "zip"
    return "native"
