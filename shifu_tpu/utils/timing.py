"""Stage wall-clock timers for the streaming pipeline.

Overlap is invisible in one end-to-end number: a pipelined run and a serial
run produce the same log lines, just slower or faster. These counters make
the overlap observable without a profiler — each stage (parse / bincode /
device / sync) accumulates wall-clock seconds and a call count, and the
pipeline logs one summary line per run. When the per-stage times sum to
more than the elapsed wall-clock, the difference IS the overlap won.

Thread-safe: the prefetch worker times parse/bincode while the consumer
thread times device/sync against the same StageTimers instance.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import Dict, Iterator


class StageTimers:
    """Named wall-clock accumulators (seconds + call counts)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._seconds: Dict[str, float] = {}
        self._calls: Dict[str, int] = {}

    def add(self, stage: str, seconds: float, calls: int = 1) -> None:
        with self._lock:
            self._seconds[stage] = self._seconds.get(stage, 0.0) + seconds
            self._calls[stage] = self._calls.get(stage, 0) + calls

    @contextmanager
    def timer(self, stage: str) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.add(stage, time.perf_counter() - t0)

    def seconds(self, stage: str) -> float:
        with self._lock:
            return self._seconds.get(stage, 0.0)

    def calls(self, stage: str) -> int:
        with self._lock:
            return self._calls.get(stage, 0)

    def as_dict(self) -> Dict[str, Dict[str, float]]:
        with self._lock:
            return {
                k: {"seconds": round(self._seconds[k], 4),
                    "calls": self._calls.get(k, 0)}
                for k in self._seconds
            }

    def summary(self) -> str:
        """One log-friendly line: "parse 1.21s/12 | device 0.43s/12"."""
        with self._lock:
            if not self._seconds:
                return "(no stages timed)"
            return " | ".join(
                f"{k} {self._seconds[k]:.2f}s/{self._calls.get(k, 0)}"
                for k in self._seconds
            )
