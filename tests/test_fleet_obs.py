"""Fleet observability plane: on-disk metrics time-series
(obs/timeseries.py), cross-process federation (obs/fleetview.py),
per-tenant SLO burn, stitched promote-round traces (shifu trace
--fleet) and the pure `shifu top` renderer.

The acceptance pins live here: a window encode/apply round-trip is
lossless; the single Histogram.merge primitive produces bucket-exact ==
recomputed-from-raw results; the fleet merge sums counters bit-exact in
ANY fold order, keeps an expired peer's final counters while dropping
its gauges; per-tenant SLO burn isolates an antagonist tenant; and one
promotion round driven through real PeerRegistry heartbeat threads
yields coordinator + participant spans under ONE round trace id,
stitched into ONE Perfetto file with per-process track groups. All
jax-free."""

import json
import os
import time

import pytest

from shifu_tpu.utils import environment


class _Props:
    def __init__(self, **props):
        self.props = {k.replace("_", "."): v for k, v in props.items()}

    def __enter__(self):
        for k, v in self.props.items():
            environment.set_property(k, v)
        return self

    def __exit__(self, *exc):
        for k in self.props:
            environment.set_property(k, "")


def _wait_for(pred, timeout=10.0, interval=0.02, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {msg}")


# ---------------------------------------------------------------------------
# on-disk time-series: delta encoding + snapshotter chunk files
# ---------------------------------------------------------------------------


class TestTimeseriesEncoding:
    def test_window_roundtrip_is_lossless(self):
        from shifu_tpu.obs import timeseries
        from shifu_tpu.obs.metrics import MetricsRegistry

        reg = MetricsRegistry()
        reg.counter("serve.requests", format="json").inc(3)
        reg.counter("serve.slo.good", tenant="a").inc(10)
        reg.gauge("serve.queue.depth", tenant="a").set(2)
        reg.timer("stats").add(0.25, 4)
        reg.histogram("serve.stage_seconds", stage="device").observe(0.03)
        snap1 = reg.snapshot()

        full = timeseries.encode_window(None, snap1, 1.0)
        assert full["full"] is True
        assert timeseries.apply_window(None, full) == snap1

        reg.counter("serve.requests", format="json").inc(2)
        reg.gauge("serve.queue.depth", tenant="a").set(7)
        reg.histogram("serve.stage_seconds", stage="device").observe(0.5)
        snap2 = reg.snapshot()
        delta = timeseries.encode_window(snap1, snap2, 2.0)
        assert not delta.get("full")
        # the untouched counter is NOT re-shipped in the delta
        assert 'serve.slo.good{tenant="a"}' not in delta.get("counters", {})
        assert timeseries.apply_window(snap1, delta) == snap2

    def test_idle_delta_is_ts_only(self):
        from shifu_tpu.obs import timeseries
        from shifu_tpu.obs.metrics import MetricsRegistry

        reg = MetricsRegistry()
        reg.counter("serve.requests").inc(1)
        snap = reg.snapshot()
        assert timeseries.encode_window(snap, snap, 3.0) == {"ts": 3.0}


class TestMetricsSnapshotter:
    def _snapshotter(self, root, reg, **kw):
        from shifu_tpu.obs import timeseries

        kw.setdefault("snapshot_ms", 10_000)  # armed, ticked inline
        kw.setdefault("chunk_windows", 2)
        kw.setdefault("retain_chunks", 2)
        return timeseries.MetricsSnapshotter(
            str(root), "proc-a", lambda: reg, **kw)

    def test_rotation_retention_and_reconstruction(self, tmp_path):
        from shifu_tpu.obs import timeseries
        from shifu_tpu.obs.metrics import MetricsRegistry

        reg = MetricsRegistry()
        snap = self._snapshotter(tmp_path, reg)
        for _ in range(10):
            reg.counter("serve.requests").inc(1)
            snap.tick()
        root = str(tmp_path)
        # 10 windows at 2/chunk = 5 chunks, retention keeps the last 2
        assert len(timeseries.list_chunks(root, "proc-a")) == 2
        assert timeseries.list_process_dirs(root) \
            == [timeseries.obs_dir(root, "proc-a")]
        windows = timeseries.read_windows(root, "proc-a")
        counts = [w["metrics"]["counters"]["serve.requests"]
                  for w in windows]
        # retained chunks are self-contained: absolute values, in order
        assert counts == sorted(counts) and counts[-1] == 10.0
        last = timeseries.last_snapshot(root, "proc-a")
        assert last["metrics"] == reg.snapshot()

    def test_idle_ticks_write_nothing_new(self, tmp_path):
        from shifu_tpu.obs.metrics import MetricsRegistry

        reg = MetricsRegistry()
        snap = self._snapshotter(tmp_path, reg, chunk_windows=8)
        reg.counter("serve.requests").inc(1)
        snap.tick()
        snap.tick()  # nothing changed: no window, no rewrite
        snap.tick()
        assert snap.snapshot()["windows"] == 1

    def test_sigkill_leaves_last_windows_behind(self, tmp_path):
        """No clean shutdown ever runs — the ticked chunks alone must
        reconstruct the process's final counters (what the collector
        folds for an expired peer)."""
        from shifu_tpu.obs import timeseries
        from shifu_tpu.obs.metrics import MetricsRegistry

        reg = MetricsRegistry()
        snap = self._snapshotter(tmp_path, reg, chunk_windows=4)
        reg.counter("serve.slo.bad", tenant="a").inc(3)
        snap.tick()
        reg.counter("serve.slo.bad", tenant="a").inc(2)
        snap.tick()
        del snap  # SIGKILL stand-in: no stop(), no final flush
        last = timeseries.last_snapshot(str(tmp_path), "proc-a")
        assert last["metrics"]["counters"]['serve.slo.bad{tenant="a"}'] \
            == 5.0


# ---------------------------------------------------------------------------
# the single Histogram.merge primitive
# ---------------------------------------------------------------------------


class TestHistogramMerge:
    def test_merged_equals_recomputed_from_raw(self):
        from shifu_tpu.obs.metrics import Histogram

        edges = (0.01, 0.1, 1.0)
        # power-of-two fractions: float sums are exact in any order
        raw = [k / 64.0 for k in (1, 2, 3, 5, 6, 7, 9, 40, 64, 96, 100)]
        h1, h2, hall = Histogram(edges), Histogram(edges), Histogram(edges)
        for i, v in enumerate(raw):
            (h1 if i % 2 else h2).observe(v)
            hall.observe(v)
        h1.merge(h2)
        assert h1.as_dict() == hall.as_dict()
        for q in (0.5, 0.9, 0.99):
            assert h1.quantile(q) == hall.quantile(q)

    def test_edge_mismatch_raises(self):
        from shifu_tpu.obs.metrics import Histogram

        a, b = Histogram((0.1, 1.0)), Histogram((0.2, 1.0))
        b.observe(0.15)
        with pytest.raises(ValueError):
            a.merge(b)


# ---------------------------------------------------------------------------
# fleet federation: merge semantics + SLO summary (pure, no HTTP)
# ---------------------------------------------------------------------------


def _sample(lease_id, reg, live=True):
    return {"leaseId": lease_id, "live": live, "source": "test",
            "metrics": reg.snapshot(), "info": {}, "ageMs": 0.0}


def _process_registry(requests, queue_depth, stage_ms):
    from shifu_tpu.obs.metrics import MetricsRegistry

    reg = MetricsRegistry()
    reg.counter("serve.requests", format="json", replica="0").inc(requests)
    reg.gauge("serve.queue.depth", tenant="t1", replica="0") \
        .set(queue_depth)
    h = reg.histogram("serve.stage_seconds", stage="device", replica="0")
    for ms in stage_ms:
        h.observe(ms / 1e3)
    return reg


class TestFleetMerge:
    def test_counters_sum_bit_exact_in_any_fold_order(self):
        from shifu_tpu.obs import fleetview
        from shifu_tpu.obs.metrics import _parse_key

        a = _sample("proc-a", _process_registry(3, 2, [10, 20]))
        b = _sample("proc-b", _process_registry(4, 5, [30]))
        merged = fleetview.merge([a, b])
        # any peer answering /fleet/metrics renders the SAME text
        assert merged.to_prometheus() \
            == fleetview.merge([b, a]).to_prometheus()
        flat = merged.snapshot()
        assert flat["counters"][
            'serve.requests{format="json",replica="0"}'] == 7.0
        # gauges: one series per process + min/max/sum aggregates
        gauges = flat["gauges"]
        per_proc = {k: v for k, v in gauges.items()
                    if _parse_key(k)[0] == "serve.queue.depth"
                    and "process" in _parse_key(k)[1]}
        assert sorted(per_proc.values()) == [2.0, 5.0]
        assert gauges[
            'serve.queue.depth{agg="sum",replica="0",tenant="t1"}'] == 7.0
        assert gauges[
            'serve.queue.depth{agg="max",replica="0",tenant="t1"}'] == 5.0
        # histograms merged bucket-exact across processes
        hist = flat["histograms"][
            'serve.stage_seconds{replica="0",stage="device"}']
        assert hist["count"] == 3

    def test_expired_peer_keeps_counters_drops_gauges(self):
        from shifu_tpu.obs import fleetview

        live = _sample("proc-a", _process_registry(3, 2, [10]))
        dead = _sample("proc-b", _process_registry(9, 5, [30]), live=False)
        flat = fleetview.merge([live, dead]).snapshot()
        assert flat["counters"][
            'serve.requests{format="json",replica="0"}'] == 12.0
        assert not any("proc-b" in k for k in flat["gauges"])
        assert flat["gauges"]["fleet.processes.live"] == 1.0
        assert flat["gauges"]["fleet.processes.expired"] == 1.0

    def test_collect_reads_expired_peer_from_disk(self, tmp_path):
        """A SIGKILLed peer: stale lease file + the time-series windows
        it ticked while alive. collect() must surface its last counters
        from disk, marked expired."""
        from shifu_tpu.obs import fleetview, timeseries
        from shifu_tpu.obs.metrics import MetricsRegistry
        from shifu_tpu.resilience import lease

        root = str(tmp_path)
        reg = MetricsRegistry()
        reg.counter("serve.slo.bad", tenant="a").inc(4)
        snap = timeseries.MetricsSnapshotter(
            root, "dead-1", lambda: reg, snapshot_ms=10_000,
            chunk_windows=4, retain_chunks=2)
        snap.tick()
        os.makedirs(lease.peers_dir(root), exist_ok=True)
        with open(os.path.join(lease.peers_dir(root),
                               "dead-1" + lease.LEASE_SUFFIX), "w") as fh:
            json.dump({"schema": "shifu.lease/1", "leaseId": "dead-1",
                       "host": "h", "pid": 1, "token": "tok", "epoch": 1,
                       "ttlMs": 100.0, "renewedAt": time.time() - 60.0,
                       "info": {}}, fh)

        samples = fleetview.collect(root, self_id="me",
                                    self_snapshot=MetricsRegistry().snapshot)
        by_id = {s["leaseId"]: s for s in samples}
        assert by_id["me"]["live"] and by_id["me"]["source"] == "local"
        dead = by_id["dead-1"]
        assert not dead["live"] and dead["source"] == "disk"
        assert dead["metrics"]["counters"]['serve.slo.bad{tenant="a"}'] \
            == 4.0


class TestTenantSlo:
    def test_tenant_knobs_fall_back_to_fleet_wide(self):
        from shifu_tpu.serve import health

        with _Props(shifu_serve_sloMs="50", shifu_serve_sloTarget="0.99"):
            assert health.tenant_slo_ms("t9") == 50.0
            assert health.tenant_slo_target("t9") == 0.99
            with _Props(**{"shifu.serve.slo.t9.ms": "250",
                           "shifu.serve.slo.t9.target": "0.5"}):
                assert health.tenant_slo_ms("t9") == 250.0
                assert health.tenant_slo_target("t9") == 0.5
                # other tenants keep the fleet-wide objective
                assert health.tenant_slo_ms("other") == 50.0

    def test_burn_isolates_antagonist_tenant(self):
        from shifu_tpu.obs import fleetview
        from shifu_tpu.obs.metrics import MetricsRegistry

        reg = MetricsRegistry()
        reg.counter("serve.slo.good", tenant="quiet").inc(99)
        reg.counter("serve.slo.bad", tenant="quiet").inc(1)
        reg.counter("serve.slo.good", tenant="ant").inc(10)
        reg.counter("serve.slo.bad", tenant="ant").inc(90)
        with _Props(**{"shifu.serve.sloTarget": "0.99",
                       "shifu.serve.slo.ant.target": "0.5"}):
            out = fleetview.slo_summary(reg)
        ant, quiet = out["tenants"]["ant"], out["tenants"]["quiet"]
        # the antagonist burns against ITS OWN relaxed target...
        assert ant["bad"] == 90 and ant["target"] == 0.5
        assert ant["burn"] == pytest.approx(0.9 / 0.5)
        # ...and the quiet tenant's burn is untouched by the antagonist
        assert quiet["burn"] == pytest.approx(0.01 / 0.01)
        assert out["fleet"]["good"] == 109 and out["fleet"]["bad"] == 91
        gauges = reg.snapshot()["gauges"]
        assert gauges['fleet.slo.burn{tenant="ant"}'] \
            == pytest.approx(ant["burn"])


# ---------------------------------------------------------------------------
# stitched promote-round traces: one id across coordinator + participants
# ---------------------------------------------------------------------------


class _Participant:
    """A PeerRegistry wired to recording callbacks (the server
    stand-in, as in test_lease.py)."""

    def __init__(self, root, ttl_ms=2000, sha="cand-sha"):
        from shifu_tpu.serve.peers import PeerRegistry

        self.staged = []
        self.promoted = []

        def stage_cb(candidate_dir):
            self.staged.append(candidate_dir)
            return {"sha": sha}

        self.reg = PeerRegistry(root, stage_cb=stage_cb,
                                promote_cb=self.promoted.append,
                                unstage_cb=lambda: None, ttl_ms=ttl_ms)

    def close(self):
        self.reg.close()


class TestStitchedRoundTrace:
    def test_snapshot_json_serializable_mid_round(self, tmp_path):
        # the live RequestTrace rides PeerRegistry._round for the span
        # calls; the /healthz + manifest snapshot must render its ID,
        # not the object (a mid-round /healthz crashed on this once)
        from shifu_tpu.obs import reqtrace

        part = _Participant(str(tmp_path))
        try:
            tr = reqtrace.RequestTrace(trace_id="round-r1", sampled=True)
            with part.reg._lock:
                part.reg._round = {"round": "r1", "sha": "cand-sha",
                                   "deadline": time.time() + 5,
                                   "grace": 1.0, "trace": tr}
            snap = part.reg.snapshot()
            json.dumps(snap)
            assert snap["round"]["trace"] == "round-r1"
            with part.reg._lock:
                part.reg._round = None
        finally:
            part.close()

    def test_round_produces_one_stitched_perfetto_file(self, tmp_path):
        from shifu_tpu import obs
        from shifu_tpu.loop import promote
        from shifu_tpu.obs import reqtrace
        from shifu_tpu.obs.ledger import runs_dir

        obs.reset()
        root = str(tmp_path)
        parts = [_Participant(root), _Participant(root)]
        try:
            _wait_for(lambda: len(promote.live_peers(root)) == 2,
                      msg="both leases visible")
            res = promote.run_promotion_round(
                root, str(tmp_path / "cand"), "cand-sha",
                promote.live_peers(root))
            assert res["committed"]
            tid = res["trace"]
            assert tid == f"round-{res['round']}"
            _wait_for(lambda: all(p.promoted == ["cand-sha"]
                                  for p in parts), msg="commit applied")
            # coordinator + both participants offered sampled traces
            _wait_for(lambda: reqtrace.buffer().count >= 3,
                      msg="3 round traces retained")
        finally:
            for p in parts:
                p.close()

        summaries = reqtrace.buffer().traces()
        assert [s["id"] for s in summaries] == [tid] * 3
        by_role = {}
        for s in summaries:
            by_role.setdefault(s["attrs"]["role"], []).append(s)
        (coord,) = by_role["coordinator"]
        assert coord["attrs"]["outcome"] == "commit"
        for st in ("prepare", "acks", "fence", "commit"):
            assert st in coord["stages"]
        participants = by_role["participant"]
        assert len(participants) == 2
        for s in participants:
            assert s["attrs"]["outcome"] == "commit"
            for st in ("stage", "ack", "commit"):
                assert st in s["stages"]
        assert len({s["attrs"]["leaseId"] for s in participants}) == 2

        # split by role into per-"process" trace files, the shapes the
        # coordinator (promote-<seq>) and a serve peer (its own run
        # subdir) actually write, then stitch
        with reqtrace.buffer()._lock:
            traces = list(reqtrace.buffer()._ring)
        coord_buf = reqtrace.TraceBuffer(capacity=8, sample=1.0, slow_ms=0)
        part_buf = reqtrace.TraceBuffer(capacity=8, sample=1.0, slow_ms=0)
        for t in traces:
            buf = (coord_buf if t.attrs.get("role") == "coordinator"
                   else part_buf)
            buf.offer(t)
        runs = runs_dir(root)
        f1 = coord_buf.write_traces(os.path.join(runs,
                                                 "promote-1.traces.json"))
        f2 = part_buf.write_traces(os.path.join(runs, "proc-b",
                                                "serve-1.traces.json"))
        assert f1 and f2
        files = reqtrace.trace_files(root)
        assert set(files) == {f1, f2}  # the subdir file is found too

        out_path = os.path.join(runs, reqtrace.FLEET_TRACE_BASENAME)
        doc = reqtrace.stitch_trace_files(files, out_path)
        assert doc is not None and os.path.exists(out_path)
        assert doc["summary"]["stitched"] is True
        assert doc["summary"]["count"] == 3
        assert len(doc["summary"]["sources"]) == 2
        groups = [e for e in doc["traceEvents"]
                  if e.get("name") == "process_name"]
        assert len(groups) == 2
        # every span still carries the ONE round id, across both pids
        span_pids = {e["pid"] for e in doc["traceEvents"]
                     if e.get("args", {}).get("trace") == tid}
        assert span_pids == {1, 2}
        assert all(s["id"] == tid for s in doc["shifuTraces"])
        # the stitched export never re-globs itself
        assert set(reqtrace.trace_files(root)) == {f1, f2}


class TestTraceFileDiscovery:
    def test_any_run_or_process_dir_resolves(self, tmp_path):
        from shifu_tpu.obs import reqtrace
        from shifu_tpu.obs.ledger import runs_dir

        runs = runs_dir(str(tmp_path))
        os.makedirs(os.path.join(runs, "proc-x"), exist_ok=True)
        doc = {"schema": reqtrace.TRACES_SCHEMA, "traceEvents": [],
               "shifuTraces": [{"id": "t1", "totalMs": 1.0}]}
        top = os.path.join(runs, "serve-2.traces.json")
        sub = os.path.join(runs, "proc-x", "serve-1.traces.json")
        for p in (top, sub):
            with open(p, "w") as fh:
                json.dump(doc, fh)
        with open(os.path.join(runs, "fleet.traces.json"), "w") as fh:
            json.dump(doc, fh)  # stitched output: never listed
        files = reqtrace.trace_files(str(tmp_path))
        assert files == [top, sub]  # newest seq first, subdirs included


# ---------------------------------------------------------------------------
# `shifu top` renderer (pure — no server)
# ---------------------------------------------------------------------------


class TestTopRender:
    def test_group_gauge_skips_aggregate_series(self):
        from shifu_tpu.obs import top

        samples = {
            'serve_queue_depth{process="p1",tenant="a"}': 2.0,
            'serve_queue_depth{process="p2",tenant="a"}': 3.0,
            'serve_queue_depth{agg="sum",tenant="a"}': 5.0,
        }
        assert top._group_gauge(samples, "serve_queue_depth", "tenant") \
            == {"a": 5.0}

    def test_render_frame_pins_fleet_fields(self):
        from shifu_tpu.obs import fleetview, top
        from shifu_tpu.obs.metrics import parse_prometheus

        a = _sample("proc-a", _process_registry(30, 2, [10, 20, 30]))
        breg = _process_registry(12, 4, [40])
        breg.gauge("serve.breaker.open", replica="0").set(1.0)
        b = _sample("proc-b", breg)
        reg = fleetview.merge([a, b])
        with _Props(**{"shifu.serve.sloTarget": "0.99"}):
            slo = fleetview.slo_summary(reg)
        payload = {
            "liveProcesses": 2, "expiredProcesses": 1,
            "answeredBy": "proc-a", "slo": slo,
            "stages": fleetview.stage_quantiles(reg),
            "processes": [
                {"leaseId": "proc-a", "live": True, "source": "local",
                 "ageMs": 12.0, "info": {"status": "serving"}},
                {"leaseId": "proc-c", "live": False, "source": "disk",
                 "ageMs": 99000.0, "info": {}},
            ],
        }
        samples = parse_prometheus(reg.to_prometheus())
        assert top.total_requests(samples) == 42.0
        frame = top.render_frame(payload, samples, qps=12.5)
        assert "2 live / 1 expired" in frame
        assert "answered by proc-a" in frame
        assert "qps 12.5" in frame and "requests 42" in frame
        assert "device" in frame            # stage table row
        assert "t1" in frame                # tenant table row
        assert "1/1 OPEN" in frame          # proc-b's breaker, named
        assert "proc-c" in frame and "expired" in frame
