"""Operational configuration: the `shifuconfig` analog.

Three tiers, mirroring the reference (util/Environment.java:86-87 and
ShifuCLI.cleanArgs:430):
  1. `$SHIFU_TPU_HOME/conf/shifuconfig` then `/etc/shifuconfig` (key=value file)
  2. process environment variables prefixed SHIFU_
  3. `-Dk=v` CLI overrides (highest priority)
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

_props: Dict[str, str] = {}
_loaded = False


def _load_file(path: str) -> None:
    if not os.path.isfile(path):
        return
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            if "=" in line:
                k, v = line.split("=", 1)
                _props[k.strip()] = v.strip()


def _ensure_loaded() -> None:
    global _loaded
    if _loaded:
        return
    home = os.environ.get("SHIFU_TPU_HOME")
    if home:
        _load_file(os.path.join(home, "conf", "shifuconfig"))
    _load_file("/etc/shifuconfig")
    # tier 2: SHIFU_* env vars override config files (tier 3, -D, overrides both
    # via set_property)
    for k, v in os.environ.items():
        if k.startswith("SHIFU_") and k != "SHIFU_TPU_HOME":
            _props[k[len("SHIFU_"):].lower().replace("_", ".")] = v
    _loaded = True


def set_property(key: str, value: str) -> None:
    _ensure_loaded()
    _props[key] = str(value)


def get_property(key: str, default: Optional[str] = None) -> Optional[str]:
    _ensure_loaded()
    return _props.get(key, default)


def get_int(key: str, default: int) -> int:
    v = get_property(key)
    try:
        return int(v) if v is not None else default
    except ValueError:
        return default


def get_float(key: str, default: float) -> float:
    v = get_property(key)
    try:
        return float(v) if v is not None else default
    except ValueError:
        return default


def get_bool(key: str, default: bool) -> bool:
    """Empty string = unset (falls back to `default`), matching
    get_int/get_float — `set_property(k, "")` is the repo's only way to
    clear an override, and it must not silently pin False."""
    v = get_property(key)
    if v is None or not v.strip():
        return default
    return v.strip().lower() in ("1", "true", "yes", "on")


def all_properties() -> Dict[str, str]:
    _ensure_loaded()
    return dict(_props)
