"""`shifu init` — build the initial ColumnConfig list from the data header.

Parity: core/processor/InitModelProcessor.java:89 —
  1. parse the header (or first data row when headerPath is unset);
  2. assign column roles from the role files (meta/categorical/forceselect/
     forceremove) and targetColumnName/weightColumnName;
  3. auto-type detection: distinct counts + numeric-parse ratio decide
     numeric vs categorical (reference autotype MR job,
     core/autotype/AutoTypeDistinctCountMapper.java:45 — here an exact
     columnar pass instead of an HLL sketch).
"""

from __future__ import annotations

import json
import os
from typing import List, Optional, Set

import numpy as np

from shifu_tpu.config import ColumnConfig, ColumnFlag, ColumnType
from shifu_tpu.data.reader import read_header, strip_namespace
from shifu_tpu.processor.basic import BasicProcessor
from shifu_tpu.utils.errors import ErrorCode, ShifuError
from shifu_tpu.utils.log import get_logger

log = get_logger(__name__)

# cap rows scanned for auto-type detection; exact beyond this scale is wasted IO
AUTOTYPE_MAX_ROWS = 1_000_000


def _read_names_file(path: Optional[str], root: str) -> Set[str]:
    if not path:
        return set()
    full = path if os.path.isabs(path) else os.path.join(root, path)
    if not os.path.isfile(full):
        return set()
    names = set()
    with open(full) as fh:
        for line in fh:
            line = line.strip()
            if line and not line.startswith("#"):
                names.add(strip_namespace(line))
    return names


class InitProcessor(BasicProcessor):
    step = "init"

    def run_step(self) -> None:
        self.setup(need_columns=False)
        mc = self.model_config
        assert mc is not None
        ds = mc.data_set

        if ds.header_path:
            names = read_header(self.resolve(ds.header_path), ds.header_delimiter)
        else:
            # fall back to first data row as header (reference behavior when
            # headerPath empty: first line treated as header); data_path may
            # be a directory of part files
            from shifu_tpu.data.reader import _expand_paths

            first = _expand_paths(self.resolve(ds.data_path))[0]
            names = read_header(first, ds.data_delimiter)

        target = strip_namespace(ds.target_column_name)
        if target not in names:
            raise ShifuError(ErrorCode.TARGET_NOT_FOUND, target)

        meta_cols = _read_names_file(ds.meta_column_name_file, self.root)
        cate_cols = _read_names_file(ds.categorical_column_name_file, self.root)
        force_select = _read_names_file(
            mc.var_select.force_select_column_name_file, self.root
        )
        force_remove = _read_names_file(
            mc.var_select.force_remove_column_name_file, self.root
        )
        weight_col = strip_namespace(ds.weight_column_name or "")

        columns: List[ColumnConfig] = []
        for i, name in enumerate(names):
            cc = ColumnConfig(column_num=i, column_name=name)
            if name == target:
                cc.column_flag = ColumnFlag.TARGET
            elif name == weight_col and weight_col:
                cc.column_flag = ColumnFlag.WEIGHT
            elif name in meta_cols:
                cc.column_flag = ColumnFlag.META
            elif name in force_remove:
                cc.column_flag = ColumnFlag.FORCE_REMOVE
            elif name in force_select:
                cc.column_flag = ColumnFlag.FORCE_SELECT
                cc.final_select = True
            if name in cate_cols:
                cc.column_type = ColumnType.C
            columns.append(cc)

        self._auto_type(columns, names, cate_cols)
        self.column_configs = columns
        self.save_column_configs()
        log.info(
            "ColumnConfig.json initialized: %d columns (%d categorical, target=%s).",
            len(columns),
            sum(1 for c in columns if c.is_categorical()),
            target,
        )

    def _auto_type(
        self, columns: List[ColumnConfig], names: List[str], user_cate: Set[str]
    ) -> None:
        mc = self.model_config
        assert mc is not None
        ds = mc.data_set
        # streaming distinct-count sketches: the TPU-build analog of the
        # reference's HLL++ autotype MR job
        # (core/autotype/AutoTypeDistinctCountMapper.java:45) — bounded
        # memory regardless of dataset size or cardinality, sharded over
        # the lifecycle ShardPlan like every other streaming fold: each
        # row shard folds its own chunks into its own sketches, merged
        # once at the end (exact union for HLL registers / count sums)
        from shifu_tpu.data.pipeline import ShardPlan, prefetch_iter
        from shifu_tpu.data.stream import iter_columnar_chunks
        from shifu_tpu.stats.sketch import AutoTypeSketch

        candidates = [
            cc for cc in columns
            if not (cc.is_target() or cc.is_meta() or cc.is_weight())
        ]
        missing = tuple(ds.missing_or_invalid_values)
        plan = ShardPlan()
        shard_sketches = [
            {cc.column_name: AutoTypeSketch(missing) for cc in candidates}
            for _ in range(plan.n_shards)]
        # parse overlaps the sketch folds via the prefetch thread; only the
        # candidate columns are parsed at all — target/meta/weight (fat
        # padding fields included) never leave the CSV tokenizer
        for ci, chunk in prefetch_iter(enumerate(iter_columnar_chunks(
            self.resolve(ds.data_path),
            names,
            delimiter=ds.data_delimiter,
            missing_values=missing,
            max_rows=AUTOTYPE_MAX_ROWS,
            columns=[cc.column_name for cc in candidates],
        ))):
            s = plan.shard_of(ci)
            for cc in candidates:
                shard_sketches[s][cc.column_name].update(
                    chunk._series(cc.column_name))
            plan.record(s, chunk.n_rows, "init.autotype")
        sketches = shard_sketches[0]
        for s in range(1, plan.n_shards):
            for name, sk in sketches.items():
                sk.merge(shard_sketches[s][name])

        threshold = ds.auto_type_threshold
        count_info = {}
        for cc in columns:
            if cc.is_target() or cc.is_meta() or cc.is_weight():
                continue
            sk = sketches[cc.column_name]
            distinct = sk.distinct_count()
            cc.column_stats.distinct_count = int(distinct)
            num_ratio = sk.numeric_ratio()
            count_info[cc.column_name] = {
                "distinctCount": int(distinct),
                "numericRatio": round(float(num_ratio), 6),
            }
            if cc.column_name in user_cate:
                continue  # user decision wins
            if cc.column_type is None and ds.autoType and threshold > 0:
                if num_ratio < threshold / 100.0:
                    cc.column_type = ColumnType.C
                    log.info(
                        "Column %s auto-typed categorical (numeric ratio %.3f).",
                        cc.column_name,
                        num_ratio,
                    )
                else:
                    cc.column_type = ColumnType.N
            elif cc.column_type is None:
                cc.column_type = ColumnType.N
        out = self.paths.autotype_path()
        self.paths.ensure(os.path.dirname(out))
        with open(out, "w") as fh:
            json.dump(count_info, fh, indent=1)
