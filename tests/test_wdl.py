"""WDL tests: forward math, training convergence, TP-sharded embeddings on
the virtual mesh, spec roundtrip, and end-to-end processor + eval."""

import os

import numpy as np
import pytest

from shifu_tpu.models.wdl import (
    WDLModelSpec,
    flatten_wdl,
    init_wdl_params,
    unflatten_wdl,
    wdl_forward,
)
from shifu_tpu.train.wdl_trainer import WDLTrainConfig, train_wdl


def _make_data(n=1500, dn=4, seed=0):
    """Signal in dense col 0 and categorical field 0 (vocab 5)."""
    rng = np.random.default_rng(seed)
    dense = rng.normal(size=(n, dn)).astype(np.float32)
    codes = np.stack([
        rng.integers(0, 5, n), rng.integers(0, 3, n)
    ], axis=1).astype(np.int32)
    logits = dense[:, 0] * 1.5 + (codes[:, 0] >= 3) * 2.0 - 1.5
    t = (logits + rng.normal(scale=0.4, size=n) > 0).astype(np.float32)
    w = np.ones(n, np.float32)
    return dense, codes, t, w, [5, 3]


class TestForward:
    def test_flatten_roundtrip(self):
        p = init_wdl_params(3, [5, 4], 2, [8], seed=1)
        flat = flatten_wdl(p)
        p2 = unflatten_wdl(flat, p)
        np.testing.assert_allclose(p.embed[0], p2.embed[0])
        np.testing.assert_allclose(p.dense_layers[0]["W"], p2.dense_layers[0]["W"])
        np.testing.assert_allclose(p.bias, p2.bias)

    def test_forward_shape_and_range(self):
        import jax.numpy as jnp

        p = init_wdl_params(3, [5, 4], 2, [8], seed=1)
        dense = jnp.zeros((7, 3))
        codes = jnp.zeros((7, 2), jnp.int32)
        out = wdl_forward(p, dense, codes, ["relu"])
        assert out.shape == (7,)
        assert ((out >= 0) & (out <= 1)).all()

    def test_wide_tower_contributes(self):
        import jax.numpy as jnp

        p = init_wdl_params(1, [3], 2, [4], seed=1)
        p.wide[0] = np.asarray([0.0, 5.0, -5.0], np.float32)
        dense = jnp.zeros((3, 1))
        codes = jnp.asarray([[0], [1], [2]], jnp.int32)
        out = np.asarray(wdl_forward(p, dense, codes, ["relu"]))
        assert out[1] > out[0] > out[2]


class TestTrain:
    def test_learns_both_towers(self):
        dense, codes, t, w, vocab = _make_data()
        cfg = WDLTrainConfig(hidden=[16], activations=["relu"], embed_dim=4,
                             learning_rate=0.02, num_epochs=150,
                             valid_set_rate=0.2, seed=1)
        res = train_wdl(dense, codes, t, w, vocab, cfg)
        assert res.valid_error < 0.12

    def test_mesh_matches_single(self):
        from shifu_tpu.parallel.mesh import data_mesh

        dense, codes, t, w, vocab = _make_data(n=260)
        cfg = WDLTrainConfig(hidden=[8], embed_dim=2, optimizer="ADAM",
                             learning_rate=0.05, num_epochs=15,
                             valid_set_rate=0.25, seed=3)
        r1 = train_wdl(dense, codes, t, w, vocab, cfg)
        r2 = train_wdl(dense, codes, t, w, vocab, cfg, mesh=data_mesh())
        np.testing.assert_allclose(
            flatten_wdl(r1.params), flatten_wdl(r2.params), rtol=3e-3, atol=3e-4
        )

    def test_early_stop(self):
        dense, codes, t, w, vocab = _make_data(n=300)
        cfg = WDLTrainConfig(hidden=[8], embed_dim=2, learning_rate=0.1,
                             num_epochs=400, valid_set_rate=0.3,
                             early_stop_window=8, seed=5)
        res = train_wdl(dense, codes, t, w, vocab, cfg)
        assert res.iterations < 400


class TestSpec:
    def test_roundtrip_and_score(self, tmp_path):
        dense, codes, t, w, vocab = _make_data(n=400)
        cfg = WDLTrainConfig(hidden=[8], embed_dim=2, num_epochs=30, seed=7)
        res = train_wdl(dense, codes, t, w, vocab, cfg)
        spec = WDLModelSpec(
            hidden=[8], activations=["relu", "relu"], embed_dim=2,
            dense_columns=[f"n{i}" for i in range(4)],
            cat_columns=["c0", "c1"], vocab_sizes=vocab,
            categories=[["a", "b", "c", "d"], ["x", "y"]],
            params=res.params,
        )
        path = str(tmp_path / "model0.wdl")
        spec.save(path)
        loaded = WDLModelSpec.load(path)
        s1 = spec.independent().compute_parts(dense[:20], codes[:20])
        s2 = loaded.independent().compute_parts(dense[:20], codes[:20])
        np.testing.assert_allclose(s1, s2, atol=1e-6)


class TestProcessor:
    def test_end_to_end_wdl(self, tmp_path):
        from tests.helpers import make_model_set

        root = str(tmp_path / "ms")
        make_model_set(root, n_rows=500, algorithm="WDL")
        from shifu_tpu.config.model_config import ModelConfig
        from shifu_tpu.processor.evaluate import EvalProcessor
        from shifu_tpu.processor.init import InitProcessor
        from shifu_tpu.processor.norm import NormProcessor
        from shifu_tpu.processor.stats import StatsProcessor
        from shifu_tpu.processor.train import TrainProcessor

        mc = ModelConfig.load(os.path.join(root, "ModelConfig.json"))
        mc.train.num_train_epochs = 60
        mc.train.params["NumHiddenNodes"] = [16]
        mc.train.params["ActivationFunc"] = ["relu"]
        mc.train.params["LearningRate"] = 0.02
        mc.evals[0].data_set.data_path = mc.data_set.data_path
        mc.evals[0].data_set.header_path = mc.data_set.header_path
        mc.save(os.path.join(root, "ModelConfig.json"))
        assert InitProcessor(root).run() == 0
        assert StatsProcessor(root).run() == 0
        assert NormProcessor(root).run() == 0
        assert TrainProcessor(root).run() == 0
        model_path = os.path.join(root, "models", "model0.wdl")
        assert os.path.isfile(model_path)
        spec = WDLModelSpec.load(model_path)
        assert spec.cat_columns == ["cat_0", "cat_1"]
        assert len(spec.dense_columns) == 10

        assert EvalProcessor(root, run_name="").run() == 0
        import json

        with open(os.path.join(root, "evals", "Eval1",
                               "EvalPerformance.json")) as fh:
            perf = json.load(fh)
        assert perf["areaUnderRoc"] > 0.9


class TestWDLFirstClass:
    """WDL promoted to NN-equal treatment: vmapped bagging, grid search,
    k-fold, continuous training (TrainModelProcessor.java:768-945)."""

    def _pipeline_root(self, tmp_path, **train_kw):
        from tests.helpers import make_model_set

        root = str(tmp_path / "ms")
        make_model_set(root, n_rows=400, algorithm="WDL")
        from shifu_tpu.config.model_config import ModelConfig
        from shifu_tpu.processor.init import InitProcessor
        from shifu_tpu.processor.norm import NormProcessor
        from shifu_tpu.processor.stats import StatsProcessor

        assert InitProcessor(root).run() == 0
        assert StatsProcessor(root).run() == 0
        assert NormProcessor(root).run() == 0
        mc = ModelConfig.load(os.path.join(root, "ModelConfig.json"))
        mc.train.num_train_epochs = 25
        mc.train.params.update({"NumHiddenNodes": [16], "ActivationFunc": ["relu"],
                                "LearningRate": 0.01})
        for k, v in train_kw.items():
            if k == "params":
                mc.train.params.update(v)
            else:
                setattr(mc.train, k, v)
        mc.save(os.path.join(root, "ModelConfig.json"))
        return root

    def test_bagged_wdl(self, tmp_path):
        from shifu_tpu.processor.train import TrainProcessor

        root = self._pipeline_root(tmp_path, bagging_num=3)
        assert TrainProcessor(root).run() == 0
        from shifu_tpu.models.wdl import WDLModelSpec

        for i in range(3):
            path = os.path.join(root, "models", f"model{i}.wdl")
            assert os.path.isfile(path)
            spec = WDLModelSpec.load(path)
            assert spec.valid_error is not None
            assert os.path.isfile(
                os.path.join(root, "tmp", "train", f"progress_{i}.log"))
        # members differ (independent seeds/samples)
        a = WDLModelSpec.load(os.path.join(root, "models", "model0.wdl"))
        b = WDLModelSpec.load(os.path.join(root, "models", "model1.wdl"))
        assert not np.allclose(a.params.bias, b.params.bias) or not np.allclose(
            a.params.wide_dense, b.params.wide_dense)

    def test_wdl_grid_search(self, tmp_path):
        from shifu_tpu.processor.train import TrainProcessor

        root = self._pipeline_root(
            tmp_path, params={"LearningRate": [0.002, 0.01, 0.05]})
        assert TrainProcessor(root).run() == 0
        assert os.path.isfile(os.path.join(root, "models", "model0.wdl"))

    def test_wdl_k_fold(self, tmp_path):
        from shifu_tpu.processor.train import TrainProcessor

        root = self._pipeline_root(tmp_path, num_k_fold=3)
        assert TrainProcessor(root).run() == 0
        for i in range(3):
            assert os.path.isfile(
                os.path.join(root, "models", f"model{i}.wdl"))

    def test_wdl_continuous(self, tmp_path):
        from shifu_tpu.processor.train import TrainProcessor

        root = self._pipeline_root(tmp_path)
        assert TrainProcessor(root).run() == 0
        from shifu_tpu.models.wdl import WDLModelSpec

        first = WDLModelSpec.load(os.path.join(root, "models", "model0.wdl"))
        from shifu_tpu.config.model_config import ModelConfig

        mc = ModelConfig.load(os.path.join(root, "ModelConfig.json"))
        mc.train.is_continuous = True
        mc.train.num_train_epochs = 1  # barely moves off the loaded weights
        mc.save(os.path.join(root, "ModelConfig.json"))
        assert TrainProcessor(root).run() == 0
        second = WDLModelSpec.load(os.path.join(root, "models", "model0.wdl"))
        # resumed from the first model's weights, not re-initialized: one
        # epoch at lr=0.01 stays near the trained weights, while a fresh
        # Xavier init would differ wholesale
        from shifu_tpu.models.wdl import flatten_wdl, init_wdl_params

        f1 = flatten_wdl(first.params)
        f2 = flatten_wdl(second.params)
        fresh = flatten_wdl(init_wdl_params(
            len(first.dense_columns), first.vocab_sizes, first.embed_dim,
            first.hidden, seed=23))
        drift = float(np.linalg.norm(f2 - f1))
        scratch_dist = float(np.linalg.norm(fresh - f1))
        assert drift < 0.25 * scratch_dist, (drift, scratch_dist)


def test_wdl_streamed_training(tmp_path):
    """train.trainOnDisk=true streams WDL from the shard pairs and still
    learns (train/streaming_wdl.py)."""
    from tests.helpers import make_model_set

    root = str(tmp_path / "ms")
    make_model_set(root, n_rows=400, algorithm="WDL")
    from shifu_tpu.config.model_config import ModelConfig
    from shifu_tpu.processor.init import InitProcessor
    from shifu_tpu.processor.norm import NormProcessor
    from shifu_tpu.processor.stats import StatsProcessor
    from shifu_tpu.processor.train import TrainProcessor

    assert InitProcessor(root).run() == 0
    assert StatsProcessor(root).run() == 0
    assert NormProcessor(root).run() == 0
    mc = ModelConfig.load(os.path.join(root, "ModelConfig.json"))
    mc.train.num_train_epochs = 30
    mc.train.train_on_disk = True
    mc.train.params.update({"NumHiddenNodes": [16],
                            "ActivationFunc": ["relu"]})
    mc.save(os.path.join(root, "ModelConfig.json"))
    assert TrainProcessor(root).run() == 0

    from shifu_tpu.models.wdl import WDLModelSpec

    spec = WDLModelSpec.load(os.path.join(root, "models", "model0.wdl"))
    assert spec.valid_error is not None and spec.valid_error < 0.25
    assert os.path.isfile(os.path.join(root, "tmp", "train",
                                       "progress_0.log"))


def test_wdl_streamed_mesh_matches_single_device(tmp_path):
    """Streamed WDL composes with the mesh: row-sharded shard pairs, shard
    gradients psum'd — same trajectory as the single-device stream."""
    import numpy as np

    from shifu_tpu.norm.dataset import write_codes, write_normalized
    from shifu_tpu.parallel.mesh import data_mesh
    from shifu_tpu.train.streaming_wdl import train_wdl_streamed
    from shifu_tpu.train.wdl_trainer import WDLTrainConfig

    rng = np.random.default_rng(5)
    n, nd, nc, vocab = 1200, 4, 2, 6
    dense = rng.normal(size=(n, nd)).astype(np.float32)
    codes = rng.integers(0, vocab, size=(n, nc)).astype(np.int16)
    t = ((dense[:, 0] + (codes[:, 0] >= 3)) > 0.5).astype(np.int8)
    w = np.ones(n, np.float32)
    norm_dir = str(tmp_path / "NormalizedData")
    codes_dir = str(tmp_path / "CleanedData")
    cols = [f"d{i}" for i in range(nd)] + [f"c{i}" for i in range(nc)]
    write_normalized(norm_dir, np.concatenate(
        [dense, codes.astype(np.float32)], 1), t, w, cols, n_shards=3)
    write_codes(codes_dir, np.concatenate(
        [np.zeros((n, nd), np.int16), codes], 1), t, w, cols,
        [1] * nd + [vocab] * nc, n_shards=3)
    cfg = WDLTrainConfig(hidden=[8], activations=["relu"], embed_dim=4,
                         num_epochs=10, valid_set_rate=0.2, seed=3)
    num_idx = list(range(nd))
    cat_idx = [nd, nd + 1]
    single = train_wdl_streamed(norm_dir, codes_dir, num_idx, cat_idx,
                                [vocab] * nc, cfg)
    meshed = train_wdl_streamed(norm_dir, codes_dir, num_idx, cat_idx,
                                [vocab] * nc, cfg, mesh=data_mesh())
    assert meshed.iterations == single.iterations
    assert meshed.valid_error == pytest.approx(single.valid_error,
                                               abs=1e-4)
    np.testing.assert_allclose(meshed.params.embed[0],
                               single.params.embed[0], atol=1e-4)
