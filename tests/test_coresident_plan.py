"""Stage partitioning for the co-resident trainer
(shifu_tpu/coresident/plan.py): contiguous flat-vector slices, welded
prefixes, boundary widths, and budget-derived default stage counts.

The invariant everything else leans on: a stage IS a contiguous
`[lo, hi)` slice of the flat parameter vector, the slices tile the
vector exactly, and the elementwise updaters therefore make per-stage
updates concatenate bit-identically to full-vector updates (the
`stages=1` parity proof in test_coresident_parity.py rides this).
"""

import math

import numpy as np
import pytest

from shifu_tpu.coresident.plan import (
    default_stages,
    nn_plan,
    wdl_plan,
)


def _nn_shapes(sizes):
    return [(sizes[i], sizes[i + 1]) for i in range(len(sizes) - 1)]


class TestNNPlan:
    def test_slices_tile_the_flat_vector_exactly(self):
        shapes = _nn_shapes([12, 8, 6, 4, 1])
        total = sum(fi * fo + fo for fi, fo in shapes)
        for k in (1, 2, 3, 4):
            plan = nn_plan(shapes, k)
            assert plan.n_stages == k
            assert plan.stages[0].lo == 0
            assert plan.stages[-1].hi == total
            for a, b in zip(plan.stages, plan.stages[1:]):
                assert a.hi == b.lo  # contiguous, no gap, no overlap
            flat = np.arange(total, dtype=np.float32)
            pieces = plan.slices(flat)
            np.testing.assert_array_equal(np.concatenate(pieces), flat)

    def test_loss_head_lands_in_the_last_stage(self):
        shapes = _nn_shapes([10, 7, 5, 1])
        for k in (1, 2, 3):
            plan = nn_plan(shapes, k)
            assert plan.stages[-1].layer_hi == len(shapes)

    def test_boundary_widths_are_the_cut_layers_outputs(self):
        shapes = _nn_shapes([12, 8, 6, 4, 1])
        plan = nn_plan(shapes, 2)
        # K=2 over 4 layers cuts after layer 1 -> boundary width = 6
        assert plan.boundary_widths == [shapes[plan.stages[0].layer_hi
                                               - 1][1]]
        plan4 = nn_plan(shapes, 4)
        assert plan4.boundary_widths == [8, 6, 4]

    def test_more_stages_than_layers_rejected(self):
        shapes = _nn_shapes([6, 4, 1])
        with pytest.raises(ValueError, match="stages"):
            nn_plan(shapes, 3)
        with pytest.raises(ValueError, match="stages"):
            nn_plan(shapes, 0)


class TestWDLPlan:
    def _shapes(self, nd=4, nc=2, vocab=6, embed=4, hidden=(8, 5)):
        # models/wdl.wdl_arrays order: embed tables, wide tables,
        # wide_dense, (W, b) per dense layer, bias
        shapes = [(vocab, embed)] * nc + [(vocab, 1)] * nc + [(nd, 1)]
        widths = [nd + nc * embed] + list(hidden) + [1]
        for i in range(len(widths) - 1):
            shapes += [(widths[i], widths[i + 1]), (widths[i + 1],)]
        shapes += [(1,)]
        return shapes, nc

    def test_prefix_and_bias_welded_yet_contiguous(self):
        shapes, nc = self._shapes()
        total = sum(int(math.prod(s)) for s in shapes)
        for k in (1, 2, 3):
            plan = wdl_plan(shapes, nc, k)
            assert plan.stages[0].lo == 0       # embed/wide prefix
            assert plan.stages[-1].hi == total  # trailing bias
            for a, b in zip(plan.stages, plan.stages[1:]):
                assert a.hi == b.lo
            flat = np.arange(total, dtype=np.float32)
            np.testing.assert_array_equal(
                np.concatenate(plan.slices(flat)), flat)

    def test_boundary_carries_deep_width_plus_wide_logit(self):
        shapes, nc = self._shapes(hidden=(8, 5))
        plan = wdl_plan(shapes, nc, 2)
        # 3 dense layers cut 2|1: boundary after the 2nd dense layer
        # (width 5) + the wide logit column riding beside it
        assert plan.boundary_widths == [5 + 1]


class TestDefaultStages:
    def test_unbounded_grant_means_one_stage(self):
        assert default_stages(None, 10_000, 4) == 1
        assert default_stages(0, 10_000, 4) == 1

    def test_tight_budget_grows_k_and_caps_at_max(self):
        total = 1000 * 4  # bytes
        roomy = default_stages(100_000, total, 8, opt_leaves=1)
        assert roomy == 1
        tight = default_stages(total, total, 8, opt_leaves=1)
        assert tight == 3  # (2 + 1 leaf) x params / free
        assert default_stages(1, total, 8) == 8  # capped

    def test_resident_bytes_accounts_weights_opt_and_boundaries(self):
        shapes = _nn_shapes([12, 8, 1])
        plan = nn_plan(shapes, 2)
        s0 = plan.stages[0].n_params
        # stage 0: weights + 2 opt leaves + its outgoing boundary
        assert plan.resident_bytes(0, opt_leaves=2, mb_rows=16) == (
            s0 * 4 * 3 + plan.boundary_widths[0] * 16 * 4)
        s1 = plan.stages[1].n_params
        # last stage: only the incoming boundary
        assert plan.resident_bytes(1, opt_leaves=2, mb_rows=16) == (
            s1 * 4 * 3 + plan.boundary_widths[0] * 16 * 4)
