"""Wide & Deep model: functional forward + .wdl spec.

Parity target: core/dtrain/wdl/WideAndDeep.java:50 (forward :163) — dense
input layer + per-categorical-field embeddings feeding an MLP (deep), plus a
wide tower of per-field vocab weights and a linear dense part; combined
logits through sigmoid. The reference walks layer objects per record; here
the whole batch is embeddings-gather + matmuls in one jit program, with the
embedding tables shardable over a `model` mesh axis (tensor parallelism for
10k+-vocab fields — SURVEY §2.8 TP obligation).

Inputs: dense [n, Dn] float32 (z-scaled numerics) and codes [n, Dc] int32
(categorical bin indices incl. missing slot, from the CleanedData matrix).
"""

from __future__ import annotations

import io
import json
import os
import struct
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

MAGIC = b"STWD"
FORMAT_VERSION = 1


@dataclass
class WDLParams:
    """All arrays, grouped. Flattens to one vector for the update rules."""

    embed: List[np.ndarray]  # per cat field [vocab_f, E]
    wide: List[np.ndarray]  # per cat field [vocab_f]
    wide_dense: np.ndarray  # [Dn]
    dense_layers: List[Dict[str, np.ndarray]]  # deep MLP on [Dn + Dc*E]
    bias: np.ndarray  # [1]


def init_wdl_params(
    n_dense: int,
    vocab_sizes: List[int],
    embed_dim: int,
    hidden: List[int],
    seed: int = 0,
) -> WDLParams:
    rng = np.random.default_rng(seed)
    embed = [
        rng.normal(0, 0.05, size=(v, embed_dim)).astype(np.float32)
        for v in vocab_sizes
    ]
    wide = [np.zeros(v, dtype=np.float32) for v in vocab_sizes]
    deep_in = n_dense + len(vocab_sizes) * embed_dim
    sizes = [deep_in] + list(hidden) + [1]
    dense_layers = []
    for fi, fo in zip(sizes[:-1], sizes[1:]):
        limit = np.sqrt(6.0 / (fi + fo))
        dense_layers.append({
            "W": rng.uniform(-limit, limit, size=(fi, fo)).astype(np.float32),
            "b": np.zeros(fo, dtype=np.float32),
        })
    return WDLParams(
        embed=embed,
        wide=wide,
        wide_dense=np.zeros(n_dense, dtype=np.float32),
        dense_layers=dense_layers,
        bias=np.zeros(1, dtype=np.float32),
    )


def wdl_arrays(p: WDLParams) -> List[np.ndarray]:
    out = list(p.embed) + list(p.wide) + [p.wide_dense]
    for layer in p.dense_layers:
        out.extend([layer["W"], layer["b"]])
    out.append(p.bias)
    return out


def wdl_shapes(p: WDLParams) -> List[Tuple[int, ...]]:
    return [tuple(a.shape) for a in wdl_arrays(p)]


def flatten_wdl(p: WDLParams) -> np.ndarray:
    return np.concatenate([np.asarray(a).ravel() for a in wdl_arrays(p)])


def unflatten_wdl_from_shapes(flat, shapes, n_cat: int) -> WDLParams:
    """flat (np or jnp) -> WDLParams-like structure of same array type.
    Shape-only signature so jit closures need not retain parameter arrays."""
    parts, off = [], 0
    for shp in shapes:
        size = int(np.prod(shp))
        parts.append(flat[off : off + size].reshape(shp))
        off += size
    embed = parts[:n_cat]
    wide = parts[n_cat : 2 * n_cat]
    wide_dense = parts[2 * n_cat]
    rest = parts[2 * n_cat + 1 : -1]
    dense_layers = [
        {"W": rest[i], "b": rest[i + 1]} for i in range(0, len(rest), 2)
    ]
    return WDLParams(embed=embed, wide=wide, wide_dense=wide_dense,
                     dense_layers=dense_layers, bias=parts[-1])


def unflatten_wdl(flat, template: WDLParams) -> WDLParams:
    return unflatten_wdl_from_shapes(
        flat, wdl_shapes(template), len(template.embed)
    )


def wdl_forward(p: WDLParams, dense, codes, activations: List[str],
                logits_only: bool = False):
    """dense [n, Dn], codes [n, Dc] -> [n] probability (or raw logit)."""
    import jax.numpy as jnp

    from shifu_tpu.models.nn import activation_fn

    pieces = [dense]
    for f, table in enumerate(p.embed):
        tb = jnp.asarray(table)  # params may be host numpy (loaded spec)
        idx = jnp.clip(codes[:, f], 0, tb.shape[0] - 1)
        pieces.append(tb[idx])
    h = jnp.concatenate(pieces, axis=1)
    n_hidden = len(p.dense_layers) - 1
    for i in range(n_hidden):
        act = activation_fn(activations[i % len(activations)] if activations else "relu")
        h = act(h @ p.dense_layers[i]["W"] + p.dense_layers[i]["b"])
    deep_logit = (h @ p.dense_layers[-1]["W"] + p.dense_layers[-1]["b"])[:, 0]

    wide_logit = dense @ jnp.asarray(p.wide_dense)
    for f, table in enumerate(p.wide):
        tb = jnp.asarray(table)
        idx = jnp.clip(codes[:, f], 0, tb.shape[0] - 1)
        wide_logit = wide_logit + tb[idx]

    logit = deep_logit + wide_logit + jnp.asarray(p.bias)[0]
    if logits_only:
        return logit
    return 1.0 / (1.0 + jnp.exp(-logit))


@dataclass
class WDLModelSpec:
    hidden: List[int]
    activations: List[str]
    embed_dim: int
    dense_columns: List[str]
    cat_columns: List[str]
    vocab_sizes: List[int]
    # raw-record scoring info
    norm_specs: List[Dict[str, Any]] = field(default_factory=list)  # dense cols
    norm_cutoff: float = 4.0
    categories: List[List[str]] = field(default_factory=list)  # per cat col
    norm_type: str = "ZSCALE"
    algorithm: str = "WDL"
    params: Optional[WDLParams] = None
    train_error: Optional[float] = None
    valid_error: Optional[float] = None

    def save(self, path: str) -> None:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        arrays = wdl_arrays(self.params)
        head = {
            "formatVersion": FORMAT_VERSION,
            "algorithm": "WDL",
            "hidden": self.hidden,
            "activations": self.activations,
            "embedDim": self.embed_dim,
            "denseColumns": self.dense_columns,
            "catColumns": self.cat_columns,
            "vocabSizes": self.vocab_sizes,
            "normSpecs": self.norm_specs,
            "normCutoff": self.norm_cutoff,
            "categories": self.categories,
            "normType": self.norm_type,
            "trainError": self.train_error,
            "validError": self.valid_error,
            "shapes": [list(s) for s in wdl_shapes(self.params)],
        }
        head_bytes = json.dumps(head).encode("utf-8")
        buf = io.BytesIO()
        buf.write(MAGIC)
        buf.write(struct.pack("<I", len(head_bytes)))
        buf.write(head_bytes)
        buf.write(flatten_wdl(self.params).astype("<f4").tobytes())
        with open(path, "wb") as fh:
            fh.write(buf.getvalue())

    @classmethod
    def load(cls, path: str) -> "WDLModelSpec":
        with open(path, "rb") as fh:
            data = fh.read()
        if data[:4] != MAGIC:
            raise ValueError(f"{path}: not a shifu-tpu .wdl model")
        (hlen,) = struct.unpack("<I", data[4:8])
        head = json.loads(data[8 : 8 + hlen].decode("utf-8"))
        flat = np.frombuffer(data[8 + hlen :], dtype="<f4").copy()
        spec = cls(
            hidden=head["hidden"],
            activations=head["activations"],
            embed_dim=head["embedDim"],
            dense_columns=head["denseColumns"],
            cat_columns=head["catColumns"],
            vocab_sizes=head["vocabSizes"],
            norm_specs=head.get("normSpecs", []),
            norm_cutoff=float(head.get("normCutoff", 4.0)),
            categories=head.get("categories", []),
            norm_type=head.get("normType", "ZSCALE"),
            train_error=head.get("trainError"),
            valid_error=head.get("validError"),
        )
        template = init_wdl_params(
            len(spec.dense_columns), spec.vocab_sizes, spec.embed_dim,
            spec.hidden,
        )
        spec.params = unflatten_wdl(flat, template)
        return spec

    def independent(self) -> "IndependentWDLModel":
        return IndependentWDLModel(self)


class IndependentWDLModel:
    """Zero-dependency scorer (parity: wdl/IndependentWDLModel.java:46)."""

    def __init__(self, spec: WDLModelSpec):
        self.spec = spec
        self._fwd = None

    @classmethod
    def load(cls, path: str) -> "IndependentWDLModel":
        return cls(WDLModelSpec.load(path))

    def inputs_from_raw(self, data) -> Tuple[np.ndarray, np.ndarray]:
        """ColumnarData -> (dense [n, Dn], codes [n, Dc]) using the embedded
        norm plan (dense) and category lists."""
        from shifu_tpu.norm.normalizer import apply_norm_plan, plan_from_json
        from shifu_tpu.stats.binning import categorical_bin_index

        plan = plan_from_json({
            "normType": self.spec.norm_type,
            "cutoff": self.spec.norm_cutoff,
            "columns": self.spec.norm_specs,
        })
        dense = (
            apply_norm_plan(plan, data)
            if plan.specs
            else np.zeros((data.n_rows, 0), np.float32)
        )
        codes = np.zeros((data.n_rows, len(self.spec.cat_columns)), np.int32)
        for f, name in enumerate(self.spec.cat_columns):
            cats = self.spec.categories[f]
            miss = data.missing_mask(name)
            codes[:, f] = categorical_bin_index(data.column(name), cats, miss)
        return dense, codes

    def compute_parts(self, dense: np.ndarray, codes: np.ndarray) -> np.ndarray:
        import jax

        if self._fwd is None:
            spec = self.spec

            self._fwd = jax.jit(
                lambda d, c: wdl_forward(spec.params, d, c, spec.activations)
            )
        return np.asarray(
            self._fwd(np.asarray(dense, np.float32), np.asarray(codes, np.int32))
        )

    def compute_raw(self, data) -> np.ndarray:
        dense, codes = self.inputs_from_raw(data)
        return self.compute_parts(dense, codes)

    def compute(self, x) -> np.ndarray:  # ModelRunner protocol fallback
        raise NotImplementedError(
            "WDL scoring needs (dense, codes); use compute_parts/compute_raw"
        )
