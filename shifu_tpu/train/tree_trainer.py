"""GBT/RF histogram tree builder — level-wise, one fused scatter-add per level.

What DTMaster/DTWorker do across a Hadoop cluster (SURVEY §3.2: workers
accumulate per-node per-feature bin histograms via Impurity.featureUpdate
dt/DTWorker.java:851, master merges + picks best split per node
dt/DTMaster.java:274-360) happens here as one jit program per tree level:

    histogram    [L, F, S, 3] (cnt, sum, sqsum) built by ONE scatter-add over
                 the [n, F] code matrix — the Pallas-able hot op; XLA's TPU
                 scatter handles it. Row-sharded inputs all-reduce (psum) the
                 histogram when run on a mesh.
    split scan   ordered prefix sums per (node, feature): numeric bins keep
                 code order, categorical bins are sorted by label mean per
                 node (the reference sorts categories by mean response,
                 DTMaster split search); gain by impurity
                 (variance/friedmanmse: dt/Impurity.java:106,255;
                 entropy/gini via binary counts :368,553).
    node update  rows re-position via the chosen feature's goes-left bin mask.

GBT parity (dt/DTWorker.java:1470-1486): tree 0 weight 1.0, later trees
weight=learningRate; per-tree labels are -loss gradient (squared -> residual,
log -> y - sigmoid(pred)). RF: per-tree Poisson bagging + feature subset
(FeatureSubsetStrategy.java).
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from shifu_tpu.models.tree import DenseTree, TreeModelSpec
from shifu_tpu.utils.log import get_logger

log = get_logger(__name__)


@dataclass
class TreeTrainConfig:
    algorithm: str = "GBT"  # GBT | RF
    tree_num: int = 100
    max_depth: int = 6
    impurity: str = "variance"  # variance | friedmanmse | entropy | gini
    loss: str = "squared"  # squared | log (GBT label relabeling)
    learning_rate: float = 0.05
    min_instances_per_node: int = 5
    min_info_gain: float = 0.0
    feature_subset_strategy: str = "ALL"  # ALL/HALF/ONETHIRD/TWOTHIRDS/SQRT/LOG2/AUTO
    bagging_sample_rate: float = 1.0
    bagging_with_replacement: bool = True
    valid_set_rate: float = 0.1
    early_stop_rounds: int = 0  # GBT: stop when valid error worsens N rounds
    seed: int = 0

    @classmethod
    def from_model_config(cls, mc, trainer_id: int = 0) -> "TreeTrainConfig":
        t = mc.train
        alg = t.algorithm.value if hasattr(t.algorithm, "value") else str(t.algorithm)

        def g(key, default):
            v = t.get_param(key, default)
            return default if v is None else v

        alg = "RF" if alg in ("RF", "DT") else "GBT"
        return cls(
            algorithm=alg,
            tree_num=int(g("TreeNum", 100 if alg == "GBT" else 10)),
            max_depth=int(g("MaxDepth", 6 if alg == "GBT" else 10)),
            impurity=str(g("Impurity", "variance")).lower(),
            loss=str(g("Loss", "squared")).lower(),
            learning_rate=float(g("LearningRate", 0.05)),
            min_instances_per_node=int(g("MinInstancesPerNode", 5)),
            min_info_gain=float(g("MinInfoGain", 0.0)),
            feature_subset_strategy=str(
                g("FeatureSubsetStrategy", "ALL")
            ).upper(),
            bagging_sample_rate=float(t.bagging_sample_rate or 1.0),
            bagging_with_replacement=bool(t.bagging_with_replacement),
            valid_set_rate=float(t.valid_set_rate or 0.1),
            early_stop_rounds=int(g("EarlyStopRounds", 0)),
            seed=trainer_id * 977 + 13,
        )


def subset_count(strategy: str, n_features: int) -> int:
    s = strategy.upper()
    if s in ("ALL", ""):
        return n_features
    if s == "HALF":
        return max(1, n_features // 2)
    if s == "ONETHIRD":
        return max(1, n_features // 3)
    if s == "TWOTHIRDS":
        return max(1, (2 * n_features) // 3)
    if s == "QUARTER":
        return max(1, n_features // 4)
    if s in ("SQRT", "AUTO"):
        return max(1, int(math.sqrt(n_features)))
    if s == "LOG2":
        return max(1, int(math.log2(max(n_features, 2))))
    return n_features


# Cached per-level compiled programs keyed by static shape/hyperparams.
_LEVEL_PROGRAMS: Dict[tuple, object] = {}


def _get_level_program(L: int, F: int, S: int, impurity: str,
                       min_inst: int, min_gain: float):
    key = (L, F, S, impurity, min_inst, float(min_gain))
    prog = _LEVEL_PROGRAMS.get(key)
    if prog is not None:
        return prog

    import jax
    import jax.numpy as jnp

    @jax.jit
    def level_step(codes, labels, weights, node_local, active, is_cat, feat_ok):
        """One tree level over L nodes.

        codes [n, F] int32; labels/weights [n] f32; node_local [n] int32
        (0..L-1, position within level); active [n] bool; is_cat [F] bool;
        feat_ok [F] bool (feature-subset mask).

        Returns (feature [L], cut_rank [L], order [L, F, S], leaf_value [L],
        is_split [L]).
        """
        n = codes.shape[0]
        w = jnp.where(active, weights, 0.0)
        nl = jnp.where(active, node_local, 0)

        # ---- fused histogram: scatter-add of (w, w*y, w*y^2). One scatter
        # per component keeps the peak intermediate at [n, F] instead of
        # [n, F, 3]. Under a `data`-sharded mesh each device scatters its
        # row shard and XLA all-reduces the replicated histogram — the psum
        # that replaces DTMaster's NodeStats merge (DTMaster.java:297-310).
        flat = (nl[:, None] * F + jnp.arange(F)[None, :]) * S + codes
        comps = (w, w * labels, w * labels * labels)
        planes = [
            jnp.zeros((L * F * S,), jnp.float32)
            .at[flat]
            .add(jnp.broadcast_to(c[:, None], (n, F)))
            .reshape(L, F, S)
            for c in comps
        ]
        cnt, s1, s2 = planes

        # ---- bin ordering: numeric keeps code order, categorical sorts by
        # mean label (empty bins pushed right) ----
        mean = jnp.where(cnt > 0, s1 / jnp.maximum(cnt, 1e-12), jnp.inf)
        cat_order = jnp.argsort(mean, axis=-1)  # [L, F, S]
        num_order = jnp.broadcast_to(jnp.arange(S), (L, F, S))
        order = jnp.where(is_cat[None, :, None], cat_order, num_order)

        cnt_o = jnp.take_along_axis(cnt, order, axis=-1)
        s1_o = jnp.take_along_axis(s1, order, axis=-1)
        s2_o = jnp.take_along_axis(s2, order, axis=-1)
        lcnt = jnp.cumsum(cnt_o, axis=-1)
        ls1 = jnp.cumsum(s1_o, axis=-1)
        ls2 = jnp.cumsum(s2_o, axis=-1)
        tcnt, ts1, ts2 = lcnt[..., -1:], ls1[..., -1:], ls2[..., -1:]
        rcnt, rs1, rs2 = tcnt - lcnt, ts1 - ls1, ts2 - ls2

        def sse(c, s, q):  # sum squared error = impurity mass (variance)
            return q - s * s / jnp.maximum(c, 1e-12)

        def gini_mass(c, pos):
            neg = c - pos
            return c - (pos * pos + neg * neg) / jnp.maximum(c, 1e-12)

        def entropy_mass(c, pos):
            p = pos / jnp.maximum(c, 1e-12)
            q = 1.0 - p
            h = -(p * jnp.log2(jnp.maximum(p, 1e-12))
                  + q * jnp.log2(jnp.maximum(q, 1e-12)))
            return c * h

        if impurity in ("entropy",):
            gain = (entropy_mass(tcnt, ts1) - entropy_mass(lcnt, ls1)
                    - entropy_mass(rcnt, rs1))
        elif impurity in ("gini",):
            gain = gini_mass(tcnt, ts1) - gini_mass(lcnt, ls1) - gini_mass(rcnt, rs1)
        elif impurity == "friedmanmse":
            # FriedmanMSE (Impurity.java:255): (nl*nr)/(nl+nr) * (ml - mr)^2
            ml = ls1 / jnp.maximum(lcnt, 1e-12)
            mr = rs1 / jnp.maximum(rcnt, 1e-12)
            gain = lcnt * rcnt / jnp.maximum(tcnt, 1e-12) * (ml - mr) ** 2
        else:  # variance
            gain = sse(tcnt, ts1, ts2) - sse(lcnt, ls1, ls2) - sse(rcnt, rs1, rs2)

        valid = (
            (lcnt >= min_inst)
            & (rcnt >= min_inst)
            & (gain > min_gain)
            & feat_ok[None, :, None]
        )
        gain = jnp.where(valid, gain, -jnp.inf)

        # best cut per node over (F, S) — cut at ordered rank k means ordered
        # bins [0..k] go left (k = S-1 would send all left: invalid via rcnt)
        flat_gain = gain.reshape(L, F * S)
        best = jnp.argmax(flat_gain, axis=-1)
        best_gain = jnp.take_along_axis(flat_gain, best[:, None], axis=-1)[:, 0]
        best_feat = (best // S).astype(jnp.int32)
        best_rank = (best % S).astype(jnp.int32)
        is_split = jnp.isfinite(best_gain)

        node_cnt = tcnt[:, 0, 0]
        node_sum = ts1[:, 0, 0]
        leaf_value = node_sum / jnp.maximum(node_cnt, 1e-12)
        return best_feat, best_rank, order, leaf_value, is_split

    @jax.jit
    def finalize_level(bf, br, order, is_split, node_local, active, resting,
                       codes, base):
        """Build the level's goes-left masks, settle non-split rows, and
        reposition the rest — all on device, so the per-level Python loop
        never blocks on a host transfer (one sync per TREE, not per level;
        matters enormously over a tunneled TPU link)."""
        # inverse permutation of each node's best-feature bin order -> rank
        order_best = order[jnp.arange(L), bf]  # [L, S]
        rank = jnp.zeros((L, S), jnp.int32).at[
            jnp.arange(L)[:, None], order_best
        ].set(jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32), (L, S)))
        lm = (rank <= br[:, None]) & is_split[:, None]

        settled = active & ~is_split[node_local]
        resting2 = jnp.where(settled, base + node_local, resting)

        f = jnp.where(is_split, bf, 0)[node_local]
        code = jnp.take_along_axis(codes, f[:, None], axis=1)[:, 0]
        goes_left = lm[node_local, jnp.clip(code, 0, S - 1)]
        new_local = jnp.where(goes_left, 2 * node_local, 2 * node_local + 1)
        still = is_split[node_local] & active
        node_local2 = jnp.where(still, new_local, 0)
        feature_level = jnp.where(is_split, bf, -1)
        return lm, feature_level, resting2, node_local2, still

    _LEVEL_PROGRAMS[key] = (level_step, finalize_level)
    return _LEVEL_PROGRAMS[key]


def build_tree(
    codes,
    labels,
    weights,
    slots: np.ndarray,
    is_cat: np.ndarray,
    cfg: TreeTrainConfig,
    feat_ok: np.ndarray,
    mesh=None,
) -> Tuple[DenseTree, np.ndarray]:
    """One tree, level-wise. codes [n, F] int32 on device; labels/weights
    [n] f32 on device (weights already carry bagging significance). With a
    `mesh`, the row arrays must already be sharded over its `data` axis —
    per-level row state is created with the same sharding so every level
    runs SPMD with one histogram all-reduce.

    Returns (tree, resting [n] int32) — resting is the global node index each
    row ends at, so callers get per-row predictions without re-traversal
    (leaf_value[resting])."""
    import jax.numpy as jnp

    n, F = codes.shape
    S = int(slots.max())
    D = cfg.max_depth

    is_cat_j = jnp.asarray(is_cat)
    feat_ok_j = jnp.asarray(feat_ok)
    node_local = jnp.zeros(n, dtype=jnp.int32)
    active = jnp.ones(n, dtype=bool)
    resting = jnp.zeros(n, dtype=jnp.int32)
    if mesh is not None:
        from shifu_tpu.parallel.mesh import replicate, shard_rows

        node_local = shard_rows(node_local, mesh)
        active = shard_rows(active, mesh)
        resting = shard_rows(resting, mesh)
        is_cat_j = replicate(is_cat_j, mesh)
        feat_ok_j = replicate(feat_ok_j, mesh)

    feat_levels, mask_levels, leaf_levels = [], [], []
    for depth in range(D):
        L = 2**depth
        base = 2**depth - 1
        level_step, finalize_level = _get_level_program(
            L, F, S, cfg.impurity, cfg.min_instances_per_node, cfg.min_info_gain
        )
        bf, br, order, lv, is_split = level_step(
            codes, labels, weights, node_local, active, is_cat_j, feat_ok_j
        )
        lm, feature_level, resting, node_local, active = finalize_level(
            bf, br, order, is_split, node_local, active, resting, codes,
            jnp.int32(base),
        )
        feat_levels.append(feature_level)
        mask_levels.append(lm)
        leaf_levels.append(lv)

    # final level: leaf values for the deepest children + settle leftovers
    L2 = 2**D
    base2 = L2 - 1
    level_step2, _ = _get_level_program(
        L2, F, S, cfg.impurity, cfg.min_instances_per_node, cfg.min_info_gain
    )
    _, _, _, lv2, _ = level_step2(
        codes, labels, weights, node_local, active, is_cat_j, feat_ok_j
    )
    leaf_levels.append(lv2)
    feat_levels.append(jnp.full(L2, -1, jnp.int32))
    mask_levels.append(jnp.zeros((L2, S), bool))
    resting = jnp.where(active, base2 + node_local, resting)

    # ONE host sync for the whole tree
    import jax

    feature, left_mask, leaf_value = jax.device_get(
        (jnp.concatenate(feat_levels), jnp.concatenate(mask_levels, axis=0),
         jnp.concatenate(leaf_levels))
    )
    tree = DenseTree(
        feature=np.asarray(feature, np.int32),
        left_mask=np.asarray(left_mask, bool),
        leaf_value=np.asarray(leaf_value, np.float32),
        weight=1.0,
    )
    return tree, resting


@dataclass
class TreeTrainResult:
    spec: TreeModelSpec
    train_error: float
    valid_error: float


def train_trees(
    codes: np.ndarray,
    tags: np.ndarray,
    weights: np.ndarray,
    slots: List[int],
    is_cat: List[bool],
    columns: List[str],
    cfg: TreeTrainConfig,
    boundaries: Optional[List] = None,
    categories: Optional[List] = None,
    progress_cb=None,
    mesh=None,
) -> TreeTrainResult:
    """Full GBT/RF training run. `mesh` shards rows over its `data` axis
    (the TPU equivalent of DTWorker row shards); None = single device."""
    import jax
    import jax.numpy as jnp

    n, F = codes.shape
    n_orig = n  # rng draws always use the UNpadded count so the stream (and
    # therefore every tree) is identical with and without a mesh
    rng = np.random.default_rng(cfg.seed)
    valid_mask = rng.random(n) < cfg.valid_set_rate
    codes_np = codes.astype(np.int32)
    y_np = tags.astype(np.float32)
    base_w_np = np.where(valid_mask, 0.0, weights).astype(np.float32)
    real_np = np.ones(n, dtype=bool)
    if mesh is not None:
        from shifu_tpu.parallel.mesh import pad_rows, shard_rows

        row_put = lambda a: shard_rows(a, mesh)  # noqa: E731
        n_dev = mesh.devices.size
        (codes_np, y_np, base_w_np, valid_mask, real_np), _ = pad_rows(
            [codes_np, y_np, base_w_np, valid_mask, real_np], n_dev
        )
        n = codes_np.shape[0]
        codes_j = shard_rows(codes_np, mesh)
        y_j = shard_rows(y_np, mesh)
        vm_j = shard_rows(valid_mask, mesh)
        base_w_j = shard_rows(base_w_np, mesh)
        real_j = shard_rows(real_np, mesh)
    else:
        row_put = jnp.asarray
        codes_j = jnp.asarray(codes_np)
        y_j = jnp.asarray(y_np)
        vm_j = jnp.asarray(valid_mask)
        base_w_j = jnp.asarray(base_w_np)
        real_j = jnp.asarray(real_np)
    slots_np = np.asarray(slots, dtype=np.int32)
    is_cat_np = np.asarray(is_cat, dtype=bool)

    k_sub = subset_count(cfg.feature_subset_strategy, F)
    trees: List[DenseTree] = []
    lr = cfg.learning_rate
    is_gbt = cfg.algorithm == "GBT"
    log_loss = cfg.loss == "log"

    @jax.jit
    def errors_of(score):
        sq = (y_j - score) ** 2
        vsel = vm_j & real_j
        tsel = (~vm_j) & real_j
        v = jnp.sum(jnp.where(vsel, sq, 0.0)) / jnp.maximum(jnp.sum(vsel), 1.0)
        t = jnp.sum(jnp.where(tsel, sq, 0.0)) / jnp.maximum(jnp.sum(tsel), 1.0)
        return t, v

    pred = row_put(jnp.zeros(n, dtype=jnp.float32))  # GBT raw prediction F(x)
    valid_errors: List[float] = []
    bad_rounds = 0
    terr = verr = 0.0

    for k in range(cfg.tree_num):
        if cfg.algorithm == "RF":
            if cfg.bagging_with_replacement:
                bag = rng.poisson(cfg.bagging_sample_rate, size=n_orig)
            else:
                bag = rng.random(n_orig) < cfg.bagging_sample_rate
            bag = np.pad(bag.astype(np.float32), (0, n - n_orig))
            w_k = base_w_j * row_put(bag)
            labels_k = y_j
        else:  # GBT: fit the negative loss gradient
            w_k = base_w_j
            if log_loss:
                labels_k = y_j - 1.0 / (1.0 + jnp.exp(-pred))
            else:
                labels_k = y_j - pred

        feat_ok = np.zeros(F, dtype=bool)
        if k_sub >= F:
            feat_ok[:] = True
        else:
            feat_ok[rng.choice(F, size=k_sub, replace=False)] = True

        tree, resting = build_tree(
            codes_j, labels_k, w_k, slots_np, is_cat_np, cfg, feat_ok,
            mesh=mesh,
        )
        tree.weight = 1.0 if (is_gbt and k == 0) else (lr if is_gbt else 1.0)
        trees.append(tree)

        # per-row prediction straight from the build (no re-traversal)
        tree_pred = jnp.asarray(tree.leaf_value)[resting]
        if is_gbt:
            pred = pred + tree.weight * tree_pred
            score = (
                1.0 / (1.0 + jnp.exp(-pred)) if log_loss
                else jnp.clip(pred, 0.0, 1.0)
            )
        else:
            pred = tree_pred if k == 0 else (pred * k + tree_pred) / (k + 1)
            score = jnp.clip(pred, 0.0, 1.0)

        t_e, v_e = errors_of(score)
        terr, verr = float(t_e), float(v_e)  # one sync per tree
        valid_errors.append(verr)
        if progress_cb:
            progress_cb(k + 1, terr, verr)
        if cfg.early_stop_rounds and len(valid_errors) > 1:
            if verr > min(valid_errors):
                bad_rounds += 1
                if bad_rounds >= cfg.early_stop_rounds:
                    log.info("early stop after %d trees", k + 1)
                    break
            else:
                bad_rounds = 0

    spec = TreeModelSpec(
        algorithm=cfg.algorithm,
        trees=trees,
        input_columns=list(columns),
        slots=[int(s) for s in slots],
        boundaries=boundaries or [None] * F,
        categories=categories or [None] * F,
        loss=cfg.loss,
        learning_rate=lr,
        init_pred=0.0,
        convert_to_prob="SIGMOID" if cfg.loss == "log" else "RAW",
        train_error=terr,
        valid_error=valid_errors[-1] if valid_errors else None,
    )
    return TreeTrainResult(spec=spec, train_error=terr,
                           valid_error=valid_errors[-1] if valid_errors else 0.0)
