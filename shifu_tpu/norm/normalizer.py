"""Normalization: NormType kernel bank producing the dense training matrix.

Semantic parity with the reference's row-at-a-time dispatcher
(core/Normalizer.java:235-302 `normalize`, `fullNormalize`) and the Pig UDF
that drives it (udf/NormalizeUDF.java:256) — but organized TPU-first: instead
of per-record Java dispatch we precompute, per column, a lookup table over
bin slots plus z-scale parameters, then apply ONE fused jit gather+arithmetic
kernel over the whole [n_rows, n_cols] bin-code matrix. One-hot types expand
to multiple output columns via the same code matrix.

Norm types (container/obj/ModelNormalizeConf.java:33-46):
  ZSCALE/ZSCORE      numeric: clamp to mean±cutoff*std then (v-mean)/std
                     (Normalizer.computeZScore:771-787); categorical: value =
                     binPosRate[bin] (missing/unseen -> posrate of the missing
                     bin or mean, Normalizer.parseRawValue:520-577 +
                     fillDefaultValue:579-592), then the same z-score.
  OLD_ZSCALE/ZSCORE  same, but categorical stays raw posrate (no z-score,
                     Normalizer.zScoreNormalize isOld branch :446-452).
  WOE / WEIGHT_WOE   binCountWoe/binWeightedWoe lookup; missing -> last bin
                     (Normalizer.woeNormalize:618-648).
  WOE_ZSCORE/ZSCALE (+WEIGHT_) z-score of the woe value, with woe mean/std
                     computed from bin counts (calculateWoeMeanAndStdDev:728).
  HYBRID/WEIGHT_HYBRID  numeric -> z-score, categorical -> (weight) woe
                     (Normalizer.hybridNormalize:683-697).
  ONEHOT             one output column per bin slot incl. missing slot
                     (Normalizer.OneHotNormalize:380-391).
  ZSCALE_ONEHOT      numeric -> z-score, categorical -> one-hot (:393-409).
  DISCRETE_ZSCORE/ZSCALE  numeric value snapped to its bin's lower boundary
                     (bin0 -> min), then z-score (:455-487).
  ASIS_WOE/ASIS_PR   numeric raw (invalid -> mean); categorical -> woe /
                     posrate (:353-378).
  ZSCORE_INDEX/ZSCALE_INDEX  numeric z-score; categorical -> bin index float,
                     missing -> len(categories) (fullNormalize:305-334).
  WOE_INDEX          numeric woe; categorical index.
  WOE_ZSCALE_INDEX   numeric woe-zscore; categorical index.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from shifu_tpu.config import ColumnConfig
from shifu_tpu.config.model_config import (
    MissingValueFillType,
    ModelConfig,
    NormType,
)
from shifu_tpu.data.reader import ColumnarData
from shifu_tpu.stats.binning import categorical_bin_index, numeric_bin_index

STD_DEV_CUTOFF = 4.0  # Normalizer.STD_DEV_CUTOFF
MIN_STD = 1e-5  # Normalizer.computeZScore: stdDev > 0.00001 guard


def norm_columns(columns: List[ColumnConfig]) -> List[ColumnConfig]:
    """Columns emitted into the normalized matrix: final-selected if varsel has
    run, else every good candidate with stats (NormalizeUDF emits candidates
    pre-varsel, finalSelect post-varsel — udf/NormalizeUDF.java:167-199)."""
    selected = [c for c in columns if c.final_select and c.is_feature()]
    if selected:
        return selected
    return [
        c
        for c in columns
        if c.is_feature()
        and (
            c.column_binning.bin_boundary is not None
            or c.column_binning.bin_category is not None
        )
    ]


def _slots(cc: ColumnConfig) -> int:
    """Bin-slot count incl. the trailing missing slot."""
    if cc.is_categorical():
        return len(cc.column_binning.bin_category or []) + 1
    if cc.is_hybrid():
        return (len(cc.column_binning.bin_boundary or [float("-inf")])
                + len(cc.column_binning.bin_category or []) + 1)
    return len(cc.column_binning.bin_boundary or [float("-inf")]) + 1


def _zscore_params(cc: ColumnConfig) -> Tuple[float, float]:
    mean = cc.column_stats.mean or 0.0
    std = cc.column_stats.std_dev or 0.0
    return mean, std


def _woe_table(cc: ColumnConfig, weighted: bool) -> np.ndarray:
    woe = (
        cc.column_binning.bin_weighted_woe
        if weighted
        else cc.column_binning.bin_count_woe
    )
    s = _slots(cc)
    if not woe:
        return np.zeros(s, dtype=np.float64)
    t = np.asarray(woe, dtype=np.float64)
    if t.size < s:
        t = np.pad(t, (0, s - t.size), constant_values=t[-1] if t.size else 0.0)
    return t[:s]


def _posrate_table(cc: ColumnConfig) -> np.ndarray:
    pr = cc.column_binning.bin_pos_rate
    s = _slots(cc)
    if not pr:
        return np.zeros(s, dtype=np.float64)
    t = np.asarray([p if p is not None else 0.0 for p in pr], dtype=np.float64)
    if t.size < s:
        t = np.pad(t, (0, s - t.size), constant_values=0.0)
    return t[:s]


def woe_mean_std(cc: ColumnConfig, weighted: bool) -> Tuple[float, float]:
    """Normalizer.calculateWoeMeanAndStdDev:728-754 — count-weighted mean/std
    of the per-bin woe values (incl. missing bin), sample-variance denominator."""
    woe = _woe_table(cc, weighted)
    pos = np.asarray(cc.column_binning.bin_count_pos or [], dtype=np.float64)
    neg = np.asarray(cc.column_binning.bin_count_neg or [], dtype=np.float64)
    s = min(len(woe), len(pos), len(neg))
    if s == 0:
        return 0.0, 0.0
    cnt = pos[:s] + neg[:s]
    total = cnt.sum()
    if total <= 1:
        return 0.0, 0.0
    ssum = float((woe[:s] * cnt).sum())
    sq = float((woe[:s] * woe[:s] * cnt).sum())
    mean = ssum / total
    std = math.sqrt(abs((sq - ssum * ssum / total) / (total - 1)))
    return mean, std


def _cat_fill_value(cc: ColumnConfig, fill: MissingValueFillType) -> float:
    """Missing/unseen categorical value -> posrate of missing bin (POSRATE)
    or column mean (Normalizer.fillDefaultValue:579-592)."""
    if fill == MissingValueFillType.POSRATE:
        pr = _posrate_table(cc)
        return float(pr[-1]) if pr.size else 0.0
    return cc.column_stats.mean or 0.0


@dataclass
class ColumnNormSpec:
    """How one input column maps into the output matrix."""

    cc: ColumnConfig
    kind: str  # "value" | "table" | "onehot"
    out_names: List[str]
    # value kind: raw numeric value, missing -> fill, then affine+clamp
    fill: float = 0.0
    mean: float = 0.0
    std: float = 0.0
    zscore: bool = True
    # table kind: per-bin-slot lookup
    table: Optional[np.ndarray] = None

    @property
    def n_out(self) -> int:
        return len(self.out_names)


@dataclass
class NormPlan:
    specs: List[ColumnNormSpec]
    norm_type: NormType
    cutoff: float

    @property
    def out_names(self) -> List[str]:
        names: List[str] = []
        for s in self.specs:
            names.extend(s.out_names)
        return names

    @property
    def n_out(self) -> int:
        return sum(s.n_out for s in self.specs)

    @property
    def source_of(self) -> Dict[str, str]:
        """output column name -> source ColumnConfig name (one-hot style
        norms expand one source into several outputs)."""
        out: Dict[str, str] = {}
        for s in self.specs:
            for on in s.out_names:
                out[on] = s.cc.column_name
        return out


def _value_spec(
    cc: ColumnConfig, cutoff: float, fill: Optional[float] = None, zscore: bool = True
) -> ColumnNormSpec:
    mean, std = _zscore_params(cc)
    return ColumnNormSpec(
        cc=cc,
        kind="value",
        out_names=[cc.column_name],
        fill=mean if fill is None else fill,
        mean=mean,
        std=std,
        zscore=zscore,
    )


def _table_spec(cc: ColumnConfig, table: np.ndarray) -> ColumnNormSpec:
    return ColumnNormSpec(
        cc=cc, kind="table", out_names=[cc.column_name], table=table
    )


def _zscored_table(
    cc: ColumnConfig, table: np.ndarray, mean: float, std: float, cutoff: float
) -> np.ndarray:
    """Fold the z-score affine+clamp into the lookup table itself — tables are
    tiny, so pre-transforming them keeps the device kernel a pure gather."""
    lo, hi = mean - cutoff * std, mean + cutoff * std
    t = np.clip(table, lo, hi)
    if std > MIN_STD:
        return (t - mean) / std
    return np.zeros_like(t)


def _index_table(cc: ColumnConfig) -> np.ndarray:
    """Categorical bin index as float; missing slot -> len(categories)
    (fullNormalize index branches)."""
    return np.arange(_slots(cc), dtype=np.float64)


def build_column_spec(
    cc: ColumnConfig,
    norm_type: NormType,
    cutoff: float,
    fill: MissingValueFillType,
) -> ColumnNormSpec:
    nt = norm_type
    is_cat = cc.is_categorical()
    mean, std = _zscore_params(cc)

    if nt in (NormType.WOE, NormType.WEIGHT_WOE):
        return _table_spec(cc, _woe_table(cc, nt == NormType.WEIGHT_WOE))

    if nt in (
        NormType.WOE_ZSCORE,
        NormType.WOE_ZSCALE,
        NormType.WEIGHT_WOE_ZSCORE,
        NormType.WEIGHT_WOE_ZSCALE,
    ):
        weighted = nt.name.startswith("WEIGHT_")
        t = _woe_table(cc, weighted)
        wm, ws = woe_mean_std(cc, weighted)
        return _table_spec(cc, _zscored_table(cc, t, wm, ws, cutoff))

    if nt in (NormType.HYBRID, NormType.WEIGHT_HYBRID):
        # hybridNormalize (Normalizer.java:683): NUMERICAL columns z-score,
        # everything else (categorical AND hybrid-H) takes the woe path
        if is_cat or cc.is_hybrid():
            return _table_spec(cc, _woe_table(cc, nt == NormType.WEIGHT_HYBRID))
        return _value_spec(cc, cutoff)

    if nt == NormType.ONEHOT:
        s = _slots(cc)
        return ColumnNormSpec(
            cc=cc,
            kind="onehot",
            out_names=[f"{cc.column_name}_{i}" for i in range(s)],
        )

    if nt == NormType.ZSCALE_ONEHOT:
        if is_cat:
            s = _slots(cc)
            return ColumnNormSpec(
                cc=cc,
                kind="onehot",
                out_names=[f"{cc.column_name}_{i}" for i in range(s)],
            )
        return _value_spec(cc, cutoff)

    if nt in (NormType.DISCRETE_ZSCORE, NormType.DISCRETE_ZSCALE):
        if is_cat:
            t = _posrate_table(cc)
            t[-1] = _cat_fill_value(cc, fill)
            return _table_spec(cc, _zscored_table(cc, t, mean, std, cutoff))
        # numeric: value snapped to bin lower boundary; bin0 -> min; missing -> mean
        bounds = np.asarray(
            cc.column_binning.bin_boundary or [float("-inf")], dtype=np.float64
        )
        t = bounds.copy()
        t[0] = cc.column_stats.min if cc.column_stats.min is not None else 0.0
        t = np.append(t, mean)  # missing slot
        return _table_spec(cc, _zscored_table(cc, t, mean, std, cutoff))

    if nt in (NormType.ASIS_WOE, NormType.ASIS_PR):
        if is_cat:
            t = (
                _woe_table(cc, False)
                if nt == NormType.ASIS_WOE
                else _posrate_table(cc)
            )
            return _table_spec(cc, t)
        return _value_spec(cc, cutoff, zscore=False)

    if nt in (NormType.ZSCORE_INDEX, NormType.ZSCALE_INDEX):
        if is_cat:
            return _table_spec(cc, _index_table(cc))
        return _value_spec(cc, cutoff)

    if nt == NormType.WOE_INDEX:
        if is_cat:
            return _table_spec(cc, _index_table(cc))
        return _table_spec(cc, _woe_table(cc, False))

    if nt == NormType.WOE_ZSCALE_INDEX:
        if is_cat:
            return _table_spec(cc, _index_table(cc))
        t = _woe_table(cc, False)
        wm, ws = woe_mean_std(cc, False)
        return _table_spec(cc, _zscored_table(cc, t, wm, ws, cutoff))

    if nt in (NormType.OLD_ZSCALE, NormType.OLD_ZSCORE):
        if is_cat:
            t = _posrate_table(cc)
            t[-1] = _cat_fill_value(cc, fill)
            return _table_spec(cc, t)  # raw posrate, no z-score
        return _value_spec(cc, cutoff)

    # ZSCALE / ZSCORE / default
    if is_cat:
        t = _posrate_table(cc)
        t[-1] = _cat_fill_value(cc, fill)
        return _table_spec(cc, _zscored_table(cc, t, mean, std, cutoff))
    return _value_spec(cc, cutoff)


def build_norm_plan(
    mc: ModelConfig, columns: List[ColumnConfig]
) -> NormPlan:
    nt = mc.normalize.norm_type
    cutoff = mc.normalize.std_dev_cut_off
    # reference checkCutOff (Normalizer.java:708) rejects only null/NaN/Inf —
    # an explicit 0.0 is legal (clamps everything to the mean)
    if cutoff is None or not math.isfinite(cutoff):
        cutoff = STD_DEV_CUTOFF
    fill = mc.normalize.category_missing_norm_type
    specs = [
        build_column_spec(cc, nt, cutoff, fill) for cc in norm_columns(columns)
    ]
    return NormPlan(specs=specs, norm_type=nt, cutoff=cutoff)


# ---------------------------------------------------------------------------
# Vectorized application
# ---------------------------------------------------------------------------


def _bin_codes_for(
    cc: ColumnConfig, data: ColumnarData, cache: Optional[dict] = None
) -> np.ndarray:
    if cache is not None and cc.column_name in cache:
        return cache[cc.column_name]
    if cc.is_categorical():
        cats = cc.column_binning.bin_category or []
        out = categorical_bin_index(
            data.column(cc.column_name), cats, data.missing_mask(cc.column_name)
        )
    elif cc.is_hybrid():
        from shifu_tpu.stats.binning import hybrid_bin_index

        out = hybrid_bin_index(
            data.column(cc.column_name),
            cc.column_binning.bin_boundary or [float("-inf")],
            cc.column_binning.bin_category or [],
            data.missing_mask(cc.column_name),
        )
    else:
        bounds = cc.column_binning.bin_boundary or [float("-inf")]
        out = numeric_bin_index(data.numeric(cc.column_name), bounds)
    if cache is not None:
        cache[cc.column_name] = out
    return out


def bin_code_matrix(
    columns: Sequence[ColumnConfig],
    data: ColumnarData,
    cache: Optional[dict] = None,
) -> np.ndarray:
    """[n_rows, n_cols] int32 bin codes — the tree engine's native input
    (replaces the reference's CleanedData raw-column path,
    TrainModelProcessor.java:1366-1372: trees consume bin indices anyway via
    DTWorker bin-index columns). `cache` shares per-column codes with
    apply_norm_plan so the binning pass runs once per column."""
    n = data.n_rows
    out = np.zeros((n, len(columns)), dtype=np.int32)
    for j, cc in enumerate(columns):
        out[:, j] = _bin_codes_for(cc, data, cache)
    return out


def value_norm_traced(v, mean, std, zs, cutoff):
    """Traced body of the per-column z-score norm: clamp to mean±cutoff*std
    then (v-mean)/std, degenerate-std columns -> 0, non-zscore (ASIS)
    columns pass through UNclamped (asIsNormalize parity: only invalid
    values are touched, never clamped).

    This is THE value-norm semantics — the standalone jit kernel below and
    the serve registry's fused raw->score program both trace this one
    function, so offline norm, eval scoring and online serving cannot
    drift apart."""
    import jax.numpy as jnp

    lo = mean - cutoff * std
    hi = mean + cutoff * std
    clamped = jnp.clip(v, lo[None, :], hi[None, :])
    safe = jnp.where(std > MIN_STD, std, 1.0)
    z = jnp.where(
        std[None, :] > MIN_STD, (clamped - mean[None, :]) / safe[None, :], 0.0
    )
    return jnp.where(zs[None, :] > 0, z, v)


def table_norm_traced(codes, tables):
    """Traced body of the per-bin-slot lookup ([n, Ct] codes over padded
    [Ct, maxS] tables) — shared with the serve fused program like
    value_norm_traced above."""
    import jax.numpy as jnp

    return jnp.take_along_axis(
        tables.T, jnp.clip(codes, 0, tables.shape[1] - 1), axis=0
    )


def _make_kernels():
    import jax

    from shifu_tpu.obs import profile

    return (profile.wrap("norm.value_kernel", jax.jit(value_norm_traced)),
            profile.wrap("norm.table_kernel", jax.jit(table_norm_traced)))


def _value_kernel_jit(*args):
    global _KERNELS
    if _KERNELS is None:
        _KERNELS = _make_kernels()
    return _KERNELS[0](*args)


def _table_kernel_jit(*args):
    global _KERNELS
    if _KERNELS is None:
        _KERNELS = _make_kernels()
    return _KERNELS[1](*args)


_KERNELS = None


def apply_norm_plan(
    plan: NormPlan,
    data: ColumnarData,
    use_jax: bool = True,
    code_cache: Optional[dict] = None,
) -> np.ndarray:
    """Produce the dense normalized matrix [n_rows, plan.n_out] float32.

    Raises ValueError when the plan is empty (stats not run / all columns
    removed) instead of crashing in concatenate.
    """
    if not plan.specs:
        raise ValueError(
            "no columns to normalize — run `shifu stats` first or check "
            "column flags/finalSelect"
        )
    n = data.n_rows
    value_specs = [s for s in plan.specs if s.kind == "value"]
    table_specs = [s for s in plan.specs if s.kind == "table"]
    onehot_specs = [s for s in plan.specs if s.kind == "onehot"]

    pieces: dict = {}

    # ---- value columns: one [n, Cv] matrix, jit affine+clamp ----
    if value_specs:
        # missing-fill happens in float64 BEFORE the float32 cast so huge
        # finite raw values overflow to inf and get CLAMPED (reference
        # computeZScore clamps), not mistaken for missing and mean-filled
        vals64 = np.stack(
            [data.numeric(s.cc.column_name) for s in value_specs], axis=1
        )
        fill = np.asarray([s.fill for s in value_specs], dtype=np.float32)
        vals = np.where(
            np.isfinite(vals64), vals64, fill.astype(np.float64)[None, :]
        ).astype(np.float32)
        mean = np.asarray([s.mean for s in value_specs], dtype=np.float32)
        std = np.asarray([s.std for s in value_specs], dtype=np.float32)
        zs = np.asarray([1.0 if s.zscore else 0.0 for s in value_specs], np.float32)
        cutoff = np.float32(plan.cutoff)

        if use_jax:
            out_vals = np.asarray(
                _value_kernel_jit(vals, mean, std, zs, cutoff)
            )
        else:
            lo, hi = mean - cutoff * std, mean + cutoff * std
            clamped = np.clip(vals, lo[None, :], hi[None, :])
            safe = np.where(std > MIN_STD, std, 1.0)
            z = np.where(std[None, :] > MIN_STD, (clamped - mean[None, :]) / safe, 0.0)
            out_vals = np.where(zs[None, :] > 0, z, vals).astype(np.float32)
        for k, s in enumerate(value_specs):
            pieces[id(s)] = out_vals[:, k : k + 1]

    # ---- table columns: one [n, Ct] gather over padded tables ----
    if table_specs:
        codes = np.stack(
            [_bin_codes_for(s.cc, data, code_cache) for s in table_specs], axis=1
        )
        max_s = max(s.table.size for s in table_specs)
        tables = np.zeros((len(table_specs), max_s), dtype=np.float32)
        for k, s in enumerate(table_specs):
            tables[k, : s.table.size] = s.table
        if use_jax:
            out_tab = np.asarray(_table_kernel_jit(codes, tables))
        else:
            out_tab = np.take_along_axis(
                tables.T, np.clip(codes, 0, tables.shape[1] - 1), axis=0
            )
        for k, s in enumerate(table_specs):
            pieces[id(s)] = out_tab[:, k : k + 1]

    # ---- onehot columns: host expansion (sparse -> dense slots) ----
    for s in onehot_specs:
        codes = _bin_codes_for(s.cc, data, code_cache)
        width = s.n_out
        oh = np.zeros((n, width), dtype=np.float32)
        idx = np.clip(codes, 0, width - 1)
        oh[np.arange(n), idx] = 1.0
        pieces[id(s)] = oh

    return np.concatenate([pieces[id(s)] for s in plan.specs], axis=1)


def spec_to_json(s: ColumnNormSpec) -> dict:
    """Serializable summary of one column's norm mapping — embedded in model
    specs so independent scorers can normalize raw records (the reference
    embeds NNColumnStats in BinaryNNSerializer for the same reason)."""
    d: dict = {"name": s.cc.column_name, "kind": s.kind, "outNames": s.out_names}
    if s.kind == "value":
        d.update(fill=s.fill, mean=s.mean, std=s.std, zscore=s.zscore)
    elif s.kind == "table":
        d["table"] = [float(x) for x in s.table]
    if s.cc.is_categorical():
        d["categories"] = list(s.cc.column_binning.bin_category or [])
    elif s.cc.is_hybrid():
        d["hybrid"] = True
        d["categories"] = list(s.cc.column_binning.bin_category or [])
        d["boundaries"] = [float(b) for b in (s.cc.column_binning.bin_boundary or [])]
    else:
        d["boundaries"] = [float(b) for b in (s.cc.column_binning.bin_boundary or [])]
    return d


def plan_to_json(plan: NormPlan) -> dict:
    return {
        "normType": plan.norm_type.value,
        "cutoff": plan.cutoff,
        "columns": [spec_to_json(s) for s in plan.specs],
    }


def plan_from_json(d: dict) -> NormPlan:
    """Rebuild an applicable NormPlan from a model-embedded norm summary, so
    independent scorers normalize raw eval records without ColumnConfig."""
    from shifu_tpu.config.column_config import ColumnType

    specs = []
    for cd in d.get("columns", []):
        cc = ColumnConfig(column_name=cd["name"])
        if cd.get("hybrid"):
            cc.column_type = ColumnType.H
            cc.column_binning.bin_category = list(cd.get("categories", []))
            cc.column_binning.bin_boundary = [
                float(b) for b in cd.get("boundaries", [])
            ]
        elif "categories" in cd:
            cc.column_type = ColumnType.C
            cc.column_binning.bin_category = list(cd["categories"])
        else:
            cc.column_type = ColumnType.N
            cc.column_binning.bin_boundary = [float(b) for b in cd.get("boundaries", [])]
        kind = cd["kind"]
        spec = ColumnNormSpec(
            cc=cc,
            kind=kind,
            out_names=list(cd["outNames"]),
            fill=float(cd.get("fill", 0.0)),
            mean=float(cd.get("mean", 0.0)),
            std=float(cd.get("std", 0.0)),
            zscore=bool(cd.get("zscore", True)),
            table=np.asarray(cd["table"], dtype=np.float64)
            if cd.get("table") is not None
            else None,
        )
        specs.append(spec)
    nt = NormType.parse(d.get("normType", "ZSCALE"))
    return NormPlan(specs=specs, norm_type=nt, cutoff=float(d.get("cutoff", 4.0)))


def normalize_dataset(
    mc: ModelConfig,
    columns: List[ColumnConfig],
    data: ColumnarData,
    use_jax: bool = True,
) -> Tuple[np.ndarray, List[str]]:
    """Normalized matrix + output column names for a (purified) dataset."""
    plan = build_norm_plan(mc, columns)
    return apply_norm_plan(plan, data, use_jax=use_jax), plan.out_names
