"""Row filter expressions (`dataSet.filterExpressions`).

The reference evaluates Apache-JEXL expressions per row
(core/DataPurifier.java:37, udf/PurifyDataUDF.java:31). Here expressions are a
safe Python-expression subset compiled once and evaluated VECTORIZED over
numpy columns — each column name is bound to a ColumnVar that dispatches
comparisons numerically or lexically depending on the literal it meets, so
`column_4 > 10 and diagnosis == "M"` runs as array ops.

Supported: comparisons, and/or/not (rewritten to &, |, ~), arithmetic, and
`in` on literal lists (rewritten to isin).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from shifu_tpu.utils.errors import ErrorCode, ShifuError

_ALLOWED_NODES = (
    ast.Expression, ast.BoolOp, ast.BinOp, ast.UnaryOp, ast.Compare,
    ast.Name, ast.Load, ast.Constant, ast.And, ast.Or, ast.Not,
    ast.Add, ast.Sub, ast.Mult, ast.Div, ast.Mod, ast.USub, ast.UAdd,
    ast.Eq, ast.NotEq, ast.Lt, ast.LtE, ast.Gt, ast.GtE, ast.In, ast.NotIn,
    ast.List, ast.Tuple,
)
# Call/Attribute/BitAnd/BitOr/Invert appear only in the REWRITTEN tree (isin
# calls, &/|/~); user input is validated against the stricter set above first.


def _split_quoted(expr: str) -> List[Tuple[bool, str]]:
    """Split into (is_literal, text) segments so operator rewriting never
    touches the inside of quoted string literals."""
    out: List[Tuple[bool, str]] = []
    i, start = 0, 0
    while i < len(expr):
        ch = expr[i]
        if ch in ("'", '"'):
            if i > start:
                out.append((False, expr[start:i]))
            j = i + 1
            while j < len(expr) and expr[j] != ch:
                j += 1
            out.append((True, expr[i : min(j + 1, len(expr))]))
            i = j + 1
            start = i
        else:
            i += 1
    if start < len(expr):
        out.append((False, expr[start:]))
    return out


def _normalize_expr(expr: str) -> str:
    # JEXL-isms -> Python operators, outside string literals only.
    parts = []
    for is_lit, seg in _split_quoted(expr):
        if not is_lit:
            seg = (
                seg.replace("&&", " and ")
                .replace("||", " or ")
                .replace(" eq ", " == ")
                .replace(" ne ", " != ")
            )
        parts.append(seg)
    return "".join(parts)


class _Rewrite(ast.NodeTransformer):
    """and/or/not -> & / | / ~ (element-wise), `x in [..]` -> x.isin([..])."""

    def visit_BoolOp(self, node: ast.BoolOp):
        self.generic_visit(node)
        op = ast.BitAnd() if isinstance(node.op, ast.And) else ast.BitOr()
        out = node.values[0]
        for v in node.values[1:]:
            out = ast.BinOp(left=out, op=op, right=v)
        return out

    def visit_UnaryOp(self, node: ast.UnaryOp):
        self.generic_visit(node)
        if isinstance(node.op, ast.Not):
            return ast.UnaryOp(op=ast.Invert(), operand=node.operand)
        return node

    def visit_Compare(self, node: ast.Compare):
        self.generic_visit(node)
        if len(node.ops) == 1 and isinstance(node.ops[0], (ast.In, ast.NotIn)):
            call = ast.Call(
                func=ast.Attribute(value=node.left, attr="isin", ctx=ast.Load()),
                args=[node.comparators[0]],
                keywords=[],
            )
            if isinstance(node.ops[0], ast.NotIn):
                return ast.UnaryOp(op=ast.Invert(), operand=call)
            return call
        # chain a < b < c into (a < b) & (b < c)
        if len(node.ops) > 1:
            parts = []
            left = node.left
            for op, comp in zip(node.ops, node.comparators):
                parts.append(ast.Compare(left=left, ops=[op], comparators=[comp]))
                left = comp
            out = parts[0]
            for p in parts[1:]:
                out = ast.BinOp(left=out, op=ast.BitAnd(), right=p)
            return out
        return node


class ColumnVar:
    """A column bound into a filter expression: raw strings + lazy numeric
    view; comparisons pick the representation from the operand type."""

    def __init__(self, raw: np.ndarray):
        self._raw = raw
        self._num: Optional[np.ndarray] = None

    def _numeric(self) -> np.ndarray:
        if self._num is None:
            import pandas as pd

            self._num = pd.to_numeric(pd.Series(self._raw), errors="coerce").to_numpy(
                dtype=np.float64
            )
        return self._num

    def _strings(self) -> np.ndarray:
        return np.asarray([str(v).strip() for v in self._raw], dtype=object)

    def _pick(self, other) -> np.ndarray:
        if isinstance(other, (int, float, np.ndarray, ColumnVar)) and not isinstance(
            other, bool
        ):
            return self._numeric()
        return self._strings()

    @staticmethod
    def _rhs(other):
        return other._numeric() if isinstance(other, ColumnVar) else other

    def __gt__(self, other):
        return self._pick(other) > self._rhs(other)

    def __ge__(self, other):
        return self._pick(other) >= self._rhs(other)

    def __lt__(self, other):
        return self._pick(other) < self._rhs(other)

    def __le__(self, other):
        return self._pick(other) <= self._rhs(other)

    def __eq__(self, other):  # noqa: D105
        return self._pick(other) == self._rhs(other)

    def __ne__(self, other):  # noqa: D105
        return self._pick(other) != self._rhs(other)

    def __add__(self, other):
        return self._numeric() + self._rhs(other)

    def __radd__(self, other):
        return other + self._numeric()

    def __sub__(self, other):
        return self._numeric() - self._rhs(other)

    def __rsub__(self, other):
        return other - self._numeric()

    def __mul__(self, other):
        return self._numeric() * self._rhs(other)

    def __rmul__(self, other):
        return other * self._numeric()

    def __truediv__(self, other):
        return self._numeric() / self._rhs(other)

    def __rtruediv__(self, other):
        return other / self._numeric()

    def __mod__(self, other):
        return self._numeric() % self._rhs(other)

    def isin(self, values: Sequence) -> np.ndarray:
        vals = list(values)
        if vals and all(isinstance(v, (int, float)) and not isinstance(v, bool) for v in vals):
            return np.isin(self._numeric(), vals)
        return np.isin(self._strings(), [str(v) for v in vals])

    __hash__ = None  # type: ignore[assignment]


class DataPurifier:
    """Compile a filter expression once; apply to a column dict -> bool mask."""

    def __init__(self, expression: Optional[str]):
        self.expression = (expression or "").strip()
        self._code = None
        if self.expression:
            src = _normalize_expr(self.expression)
            try:
                tree = ast.parse(src, mode="eval")
            except SyntaxError as e:
                raise ShifuError(ErrorCode.INVALID_FILTER_EXPR, f"{expression}: {e}")
            for node in ast.walk(tree):
                if not isinstance(node, _ALLOWED_NODES):
                    raise ShifuError(
                        ErrorCode.INVALID_FILTER_EXPR,
                        f"{expression}: disallowed construct {type(node).__name__}",
                    )
            tree = ast.fix_missing_locations(_Rewrite().visit(tree))
            self._code = compile(tree, "<filter>", "eval")

    def is_noop(self) -> bool:
        return self._code is None

    def mask(self, columns: Dict[str, np.ndarray], n_rows: int) -> np.ndarray:
        """Evaluate to a boolean keep-mask of length n_rows."""
        if self._code is None:
            return np.ones(n_rows, dtype=bool)
        # bind ONLY the columns the expression references — `columns` may be
        # a lazy frame-backed mapping where touching a column materializes
        # it (data/reader.LazyColumns); iterating all of them would defeat
        # the bounded-memory ingest
        env = {
            name: ColumnVar(columns[name])
            for name in self._code.co_names
            if name in columns
        }
        try:
            out = eval(self._code, {"__builtins__": {}}, env)  # noqa: S307
        except Exception as e:
            raise ShifuError(ErrorCode.INVALID_FILTER_EXPR, f"{self.expression}: {e}")
        result = np.asarray(out)
        if result.shape == ():
            result = np.full(n_rows, bool(result))
        # NaN comparisons are False already; ensure boolean dtype
        return result.astype(bool)


def combined_mask(
    expressions: Optional[Union[str, Sequence[str]]],
    columns: Dict[str, np.ndarray],
    n_rows: int,
) -> np.ndarray:
    """Multiple expressions may be a list or ';'-separated — all must pass
    (the reference ANDs its filter-expression list)."""
    if not expressions:
        return np.ones(n_rows, dtype=bool)
    if isinstance(expressions, str):
        # split on ';' outside quoted literals only
        expr_list: List[str] = []
        buf = ""
        for is_lit, seg in _split_quoted(expressions):
            if is_lit:
                buf += seg
            else:
                chunks = seg.split(";")
                buf += chunks[0]
                for extra in chunks[1:]:
                    expr_list.append(buf)
                    buf = extra
        expr_list.append(buf)
    else:
        expr_list = list(expressions)
    mask = np.ones(n_rows, dtype=bool)
    for expr in expr_list:
        expr = expr.strip()
        if expr:
            mask &= DataPurifier(expr).mask(columns, n_rows)
    return mask
