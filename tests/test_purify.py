"""Vectorized filter-expression tests (DataPurifier parity)."""

import numpy as np
import pytest

from shifu_tpu.data.purify import DataPurifier, combined_mask
from shifu_tpu.utils.errors import ShifuError

COLS = {
    "a": np.array(["1", "20", "3", ""], dtype=object),
    "b": np.array(["0.5", "1.5", "2.5", "3.5"], dtype=object),
    "tag": np.array(["M", "B", "M", "B"], dtype=object),
}


def test_numeric_comparison_on_string_columns():
    mask = DataPurifier("a > 2").mask(COLS, 4)
    assert mask.tolist() == [False, True, True, False]  # '' -> NaN -> False


def test_jexl_and_or_rewrite():
    mask = DataPurifier("a > 1 && b < 2").mask(COLS, 4)
    assert mask.tolist() == [False, True, False, False]
    mask = DataPurifier("a > 10 || tag == 'M'").mask(COLS, 4)
    assert mask.tolist() == [True, True, True, False]


def test_not_and_in():
    mask = DataPurifier("not (tag == 'M')").mask(COLS, 4)
    assert mask.tolist() == [False, True, False, True]
    mask = DataPurifier("tag in ['M', 'X']").mask(COLS, 4)
    assert mask.tolist() == [True, False, True, False]
    mask = DataPurifier("a in [1, 3]").mask(COLS, 4)
    assert mask.tolist() == [True, False, True, False]


def test_arithmetic_and_chained_compare():
    mask = DataPurifier("a + b > 21").mask(COLS, 4)
    assert mask.tolist() == [False, True, False, False]
    mask = DataPurifier("1 < a < 4").mask(COLS, 4)
    assert mask.tolist() == [False, False, True, False]


def test_string_equality():
    mask = DataPurifier("tag == 'B'").mask(COLS, 4)
    assert mask.tolist() == [False, True, False, True]


def test_combined_mask_semicolon_and_list():
    mask = combined_mask("a > 1; tag == 'M'", COLS, 4)
    assert mask.tolist() == [False, False, True, False]
    mask = combined_mask(["a > 1", "tag == 'M'"], COLS, 4)
    assert mask.tolist() == [False, False, True, False]


def test_disallowed_constructs_rejected():
    with pytest.raises(ShifuError):
        DataPurifier("__import__('os')")
    with pytest.raises(ShifuError):
        DataPurifier("a > (lambda: 1)()")


def test_noop():
    assert DataPurifier("").is_noop()
    assert combined_mask(None, COLS, 4).all()


def test_quoted_literals_survive_rewrites():
    cols = {"note": np.array(["a;b", "M eq F", "A&&B", "x"], dtype=object)}
    assert DataPurifier("note == 'a;b'").mask(cols, 4).tolist() == [True, False, False, False]
    assert DataPurifier('note == "M eq F"').mask(cols, 4).tolist() == [False, True, False, False]
    assert DataPurifier('note == "A&&B"').mask(cols, 4).tolist() == [False, False, True, False]
    assert combined_mask("note == 'a;b'; note != 'zzz'", cols, 4).tolist() == [
        True, False, False, False]
