"""Closed-form column metrics from per-bin pos/neg counts.

Formula parity with the reference's core/ColumnStatsCalculator.java:24
(List<T> variant, the one UpdateBinningInfoReducer feeds):

    woe      = ln((sumP + EPS) / (sumN + EPS))
    woe_i    = ln((p_i + EPS) / (n_i + EPS)),  p_i = pos_i/sumP, n_i = neg_i/sumN
    iv       = sum_i (p_i - n_i) * woe_i
    ks       = 100 * max_i |cumP_i - cumN_i|

Vectorized over many columns at once in float64 numpy: inputs are padded
[n_cols, max_bins] arrays with a valid-bin mask. (The row-dimension reduction
— millions of rows down to per-bin counts — runs on-device in ops/binagg.py;
this final [cols x bins] step is tiny and needs f64 parity, so it stays on
host.)
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np

EPS = 1e-10


class ColumnMetrics(NamedTuple):
    ks: np.ndarray  # [n_cols]
    iv: np.ndarray  # [n_cols]
    woe: np.ndarray  # [n_cols]
    bin_woe: np.ndarray  # [n_cols, max_bins]
    valid: np.ndarray  # [n_cols] bool: sumP>0 and sumN>0


def column_metrics(
    pos: np.ndarray, neg: np.ndarray, mask: np.ndarray
) -> ColumnMetrics:
    """pos/neg: [n_cols, max_bins]; mask: same shape, 1 for real bins.

    Matches ColumnStatsCalculator.calculateColumnMetrics semantics; columns
    with an empty class (sumP==0 or sumN==0) are flagged invalid (the
    reference returns null there).
    """
    pos = np.asarray(pos, dtype=np.float64) * mask
    neg = np.asarray(neg, dtype=np.float64) * mask
    sum_p = pos.sum(axis=1, keepdims=True)
    sum_n = neg.sum(axis=1, keepdims=True)
    valid = (sum_p[:, 0] > 0) & (sum_n[:, 0] > 0)

    p = pos / np.maximum(sum_p, EPS)
    n = neg / np.maximum(sum_n, EPS)
    bin_woe = np.log((p + EPS) / (n + EPS)) * mask
    iv = ((p - n) * bin_woe).sum(axis=1)
    woe = np.log((sum_p[:, 0] + EPS) / (sum_n[:, 0] + EPS))

    cum_p = np.cumsum(p, axis=1)
    cum_n = np.cumsum(n, axis=1)
    ks = 100.0 * (np.abs(cum_p - cum_n) * mask).max(axis=1)
    return ColumnMetrics(ks=ks, iv=iv, woe=woe, bin_woe=bin_woe, valid=valid)


def psi_metric(
    expected: np.ndarray, actual: np.ndarray, eps: float = EPS
) -> float:
    """Population stability index between two bin distributions (counts)."""
    e = np.asarray(expected, dtype=np.float64)
    a = np.asarray(actual, dtype=np.float64)
    se, sa = e.sum(), a.sum()
    if se <= 0 or sa <= 0:
        return 0.0
    pe = e / se
    pa = a / sa
    return float(((pa - pe) * np.log((pa + eps) / (pe + eps))).sum())
