"""Larger-than-memory training: shards stream through the chip instead of
concatenating into one host array (MemoryDiskFloatMLDataSet parity,
train/streaming.py)."""

import os

import numpy as np
import pytest

from tests.helpers import make_model_set


def _write_shards(tmp_path, n=4000, d=12, n_shards=6, seed=3):
    from shifu_tpu.norm.dataset import write_normalized

    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, d)).astype(np.float32)
    logits = 1.5 * x[:, 0] - x[:, 1] + 0.8 * x[:, 2]
    t = (logits + rng.normal(scale=0.4, size=n) > 0).astype(np.int8)
    w = np.ones(n, dtype=np.float32)
    out = str(tmp_path / "NormalizedData")
    write_normalized(out, x, t, w, [f"c{i}" for i in range(d)],
                     n_shards=n_shards)
    return out, x, t


def test_streamed_training_learns(tmp_path):
    from shifu_tpu.train.nn_trainer import NNTrainConfig
    from shifu_tpu.train.streaming import train_nn_streamed

    data_dir, x, t = _write_shards(tmp_path)
    cfg = NNTrainConfig(hidden_nodes=[16], activations=["tanh"],
                        propagation="R", num_epochs=40, valid_set_rate=0.15,
                        seed=5)
    res = train_nn_streamed(data_dir, cfg)
    assert res.iterations == 40
    assert res.valid_error < 0.08, res.valid_error

    # the returned params score like an in-memory model
    from shifu_tpu.models.nn import forward
    import jax.numpy as jnp

    p = np.asarray(forward(res.params, jnp.asarray(x), ["tanh"]))[:, 0]
    acc = float(((p > 0.5).astype(int) == t).mean())
    assert acc > 0.9


def test_streamed_matches_inmemory_quality(tmp_path):
    """Streamed full-batch BSP = sum of shard gradients; quality must track
    the in-memory trainer on the same data (sampling streams differ, so
    compare errors, not bits)."""
    from shifu_tpu.norm.dataset import load_normalized
    from shifu_tpu.train.nn_trainer import NNTrainConfig, train_nn
    from shifu_tpu.train.streaming import train_nn_streamed

    data_dir, _, _ = _write_shards(tmp_path, n=3000, n_shards=5)
    cfg = NNTrainConfig(hidden_nodes=[12], activations=["tanh"],
                        propagation="R", num_epochs=40, valid_set_rate=0.15,
                        seed=9)
    streamed = train_nn_streamed(data_dir, cfg)
    _, feats, tags, weights = load_normalized(data_dir)
    mem = train_nn(np.asarray(feats, np.float32),
                   np.asarray(tags, np.float32),
                   np.asarray(weights, np.float32), cfg)
    assert abs(streamed.valid_error - mem.valid_error) < 0.05
    assert streamed.valid_error < 0.1 and mem.valid_error < 0.1


def test_streamed_early_stop_and_checkpoint(tmp_path):
    from shifu_tpu.train.nn_trainer import NNTrainConfig
    from shifu_tpu.train.streaming import train_nn_streamed

    data_dir, _, _ = _write_shards(tmp_path, n=1500, n_shards=3)
    ck = str(tmp_path / "ck.npy")
    seen = []
    cfg = NNTrainConfig(hidden_nodes=[8], activations=["tanh"],
                        propagation="R", num_epochs=200, valid_set_rate=0.2,
                        early_stop_window=5, seed=2,
                        checkpoint_every=10, checkpoint_path=ck,
                        progress_cb=lambda it, tr, va: seen.append(it))
    res = train_nn_streamed(data_dir, cfg)
    assert res.iterations < 200  # early stop fired
    assert os.path.isfile(ck)
    assert seen and seen == sorted(seen)


def test_processor_streams_when_forced(tmp_path):
    """train.trainOnDisk=true routes through the streamed trainer and still
    produces a loadable model + artifacts."""
    root = str(tmp_path / "ms")
    make_model_set(root, n_rows=400)
    from shifu_tpu.config.model_config import ModelConfig
    from shifu_tpu.processor.init import InitProcessor
    from shifu_tpu.processor.norm import NormProcessor
    from shifu_tpu.processor.stats import StatsProcessor
    from shifu_tpu.processor.train import TrainProcessor

    assert InitProcessor(root).run() == 0
    assert StatsProcessor(root).run() == 0
    assert NormProcessor(root).run() == 0
    mc = ModelConfig.load(os.path.join(root, "ModelConfig.json"))
    mc.train.num_train_epochs = 25
    mc.train.train_on_disk = True
    mc.save(os.path.join(root, "ModelConfig.json"))
    assert TrainProcessor(root).run() == 0

    from shifu_tpu.models.nn import NNModelSpec

    spec = NNModelSpec.load(os.path.join(root, "models", "model0.nn"))
    assert spec.valid_error is not None and spec.valid_error < 0.2
    assert os.path.isfile(os.path.join(root, "tmp", "train",
                                       "progress_0.log"))


def test_streaming_grid_search_runs(tmp_path):
    """Streamed grid search runs serial trials past the memory budget (was
    a hard error; the reference fans trials out over data of any size,
    TrainModelProcessor.java:768-945)."""
    root = str(tmp_path / "ms")
    make_model_set(root, n_rows=300)
    from shifu_tpu.config.model_config import ModelConfig
    from shifu_tpu.processor.init import InitProcessor
    from shifu_tpu.processor.norm import NormProcessor
    from shifu_tpu.processor.stats import StatsProcessor
    from shifu_tpu.processor.train import TrainProcessor

    assert InitProcessor(root).run() == 0
    assert StatsProcessor(root).run() == 0
    assert NormProcessor(root).run() == 0
    mc = ModelConfig.load(os.path.join(root, "ModelConfig.json"))
    mc.train.train_on_disk = True
    mc.train.num_train_epochs = 15
    mc.train.params["LearningRate"] = [0.05, 0.1]
    mc.save(os.path.join(root, "ModelConfig.json"))
    assert TrainProcessor(root).run() == 0
    from shifu_tpu.models.nn import NNModelSpec

    spec = NNModelSpec.load(os.path.join(root, "models", "model0.nn"))
    assert spec.valid_error is not None


def test_streaming_k_fold_runs(tmp_path):
    """Streamed k-fold: fold membership by global row index, folds run
    serially over the shard stream."""
    root = str(tmp_path / "ms")
    make_model_set(root, n_rows=300)
    from shifu_tpu.config.model_config import ModelConfig
    from shifu_tpu.processor.init import InitProcessor
    from shifu_tpu.processor.norm import NormProcessor
    from shifu_tpu.processor.stats import StatsProcessor
    from shifu_tpu.processor.train import TrainProcessor

    assert InitProcessor(root).run() == 0
    assert StatsProcessor(root).run() == 0
    assert NormProcessor(root).run() == 0
    mc = ModelConfig.load(os.path.join(root, "ModelConfig.json"))
    mc.train.train_on_disk = True
    mc.train.num_train_epochs = 15
    mc.train.num_k_fold = 3
    mc.save(os.path.join(root, "ModelConfig.json"))
    assert TrainProcessor(root).run() == 0
    for i in range(3):
        assert os.path.isfile(os.path.join(root, "models", f"model{i}.nn"))


def test_streamed_nn_mesh_matches_single_device(tmp_path):
    """Spill composes with the mesh: row-sharded shard gradients psum to
    the same training trajectory as the single-device stream."""
    from shifu_tpu.parallel.mesh import data_mesh
    from shifu_tpu.train.nn_trainer import NNTrainConfig
    from shifu_tpu.train.streaming import train_nn_streamed

    data_dir, _, _ = _write_shards(tmp_path, n=2000, n_shards=4)
    cfg = NNTrainConfig(hidden_nodes=[10], activations=["tanh"],
                        propagation="R", num_epochs=20, valid_set_rate=0.15,
                        seed=7)
    single = train_nn_streamed(data_dir, cfg)
    mesh = data_mesh()
    assert mesh.devices.size == 8
    meshed = train_nn_streamed(data_dir, cfg, mesh=mesh)
    assert meshed.iterations == single.iterations
    assert meshed.valid_error == pytest.approx(single.valid_error,
                                               abs=1e-4)
    for ps, pm in zip(single.params, meshed.params):
        np.testing.assert_allclose(ps["W"], pm["W"], atol=1e-4)


def test_should_stream_training_budget(tmp_path):
    from shifu_tpu.train.streaming import should_stream_training
    from shifu_tpu.utils import environment

    data_dir, _, _ = _write_shards(tmp_path, n=2000, d=8, n_shards=2)
    assert not should_stream_training(data_dir)
    assert should_stream_training(data_dir, force_attr=True)
    environment.set_property("shifu.train.memoryBudgetMB", "0")
    try:
        assert should_stream_training(data_dir)
    finally:
        environment.set_property("shifu.train.memoryBudgetMB",
                                 str(1024))


class TestStreamedTrees:
    """Larger-than-memory GBT/RF (train/streaming_tree.py)."""

    def _write_code_shards(self, tmp_path, n=3000, f=6, bins=8, shards=5,
                           seed=4):
        from shifu_tpu.norm.dataset import write_codes

        rng = np.random.default_rng(seed)
        codes = rng.integers(0, bins, size=(n, f)).astype(np.int16)
        y = ((codes[:, 0] >= 4) | (codes[:, 1] <= 2)).astype(np.int8)
        w = np.ones(n, np.float32)
        out = str(tmp_path / "CleanedData")
        write_codes(out, codes, y, w, [f"c{i}" for i in range(f)],
                    [bins] * f, n_shards=shards)
        return out, codes, y, w

    def test_streamed_matches_in_memory_forest(self, tmp_path):
        from shifu_tpu.train.streaming_tree import train_trees_streamed
        from shifu_tpu.train.tree_trainer import (
            TreeTrainConfig,
            train_trees,
        )

        out, codes, y, w, = self._write_code_shards(tmp_path)
        f = codes.shape[1]
        cfg = TreeTrainConfig(algorithm="GBT", tree_num=6, max_depth=4,
                              learning_rate=0.3, valid_set_rate=0.15,
                              seed=9, min_instances_per_node=2)
        cols = [f"c{i}" for i in range(f)]
        streamed = train_trees_streamed(out, [9] * f, [False] * f, cols, cfg)
        mem = train_trees(codes.astype(np.int32), y.astype(np.float32), w,
                          [9] * f, [False] * f, cols, cfg)
        assert len(streamed.spec.trees) == len(mem.spec.trees)
        for ts, tm in zip(streamed.spec.trees, mem.spec.trees):
            np.testing.assert_array_equal(ts.feature, tm.feature)
            np.testing.assert_array_equal(ts.left_mask, tm.left_mask)
            np.testing.assert_allclose(ts.leaf_value, tm.leaf_value,
                                       atol=1e-4)
        assert streamed.valid_error == pytest.approx(mem.valid_error,
                                                     abs=1e-5)

    def test_streamed_rf(self, tmp_path):
        from shifu_tpu.train.streaming_tree import train_trees_streamed
        from shifu_tpu.train.tree_trainer import TreeTrainConfig

        out, codes, y, _w = self._write_code_shards(tmp_path, seed=6)
        f = codes.shape[1]
        cfg = TreeTrainConfig(algorithm="RF", tree_num=5, max_depth=4,
                              feature_subset_strategy="TWOTHIRDS",
                              valid_set_rate=0.15, seed=3,
                              min_instances_per_node=2)
        res = train_trees_streamed(out, [9] * f, [False] * f,
                                   [f"c{i}" for i in range(f)], cfg)
        scores = res.spec.independent().compute(codes.astype(np.int32))
        acc = float(((scores > 0.5) == (y > 0.5)).mean())
        assert acc > 0.9, acc

    def test_processor_streams_trees_when_forced(self, tmp_path):
        from tests.helpers import make_model_set

        root = str(tmp_path / "ms")
        make_model_set(root, n_rows=400, algorithm="GBT")
        from shifu_tpu.config.model_config import ModelConfig
        from shifu_tpu.processor.init import InitProcessor
        from shifu_tpu.processor.norm import NormProcessor
        from shifu_tpu.processor.stats import StatsProcessor
        from shifu_tpu.processor.train import TrainProcessor

        assert InitProcessor(root).run() == 0
        assert StatsProcessor(root).run() == 0
        assert NormProcessor(root).run() == 0
        mc = ModelConfig.load(os.path.join(root, "ModelConfig.json"))
        mc.train.train_on_disk = True
        mc.train.params.update({"TreeNum": 6, "MaxDepth": 3})
        mc.save(os.path.join(root, "ModelConfig.json"))
        assert TrainProcessor(root).run() == 0

        from shifu_tpu.models.tree import TreeModelSpec

        spec = TreeModelSpec.load(os.path.join(root, "models", "model0.gbt"))
        assert len(spec.trees) == 6


def test_streamed_rf_native_multiclass(tmp_path):
    """NATIVE multi-class RF streams too: per-shard vote accumulation,
    forest identical to the in-memory trainer."""
    from shifu_tpu.norm.dataset import write_codes
    from shifu_tpu.train.streaming_tree import train_trees_streamed
    from shifu_tpu.train.tree_trainer import TreeTrainConfig, train_trees

    rng = np.random.default_rng(12)
    n, f, bins, K = 1800, 5, 8, 3
    codes = rng.integers(0, bins, size=(n, f)).astype(np.int16)
    y = ((codes[:, 0] >= 5).astype(int)
         + (codes[:, 1] >= 4).astype(int)).astype(np.int8)
    w = np.ones(n, np.float32)
    out = str(tmp_path / "CleanedData")
    cols = [f"c{i}" for i in range(f)]
    write_codes(out, codes, y, w, cols, [bins] * f, n_shards=4)

    cfg = TreeTrainConfig(algorithm="RF", tree_num=6, max_depth=4,
                          impurity="entropy", n_classes=K, seed=8,
                          min_instances_per_node=2,
                          feature_subset_strategy="TWOTHIRDS")
    streamed = train_trees_streamed(out, [bins] * f, [False] * f, cols, cfg)
    mem = train_trees(codes.astype(np.int32), y.astype(np.float32), w,
                      [bins] * f, [False] * f, cols, cfg)
    assert streamed.spec.n_classes == K
    for ts, tm in zip(streamed.spec.trees, mem.spec.trees):
        np.testing.assert_array_equal(ts.feature, tm.feature)
        np.testing.assert_allclose(ts.leaf_value, tm.leaf_value, atol=1e-5)
    assert streamed.valid_error == pytest.approx(mem.valid_error, abs=1e-6)
    votes = streamed.spec.independent().compute(codes.astype(np.int32))
    assert votes.shape == (n, K)
    acc = float((np.argmax(votes, 1) == y).mean())
    assert acc > 0.85, acc


def test_streamed_trees_mesh_matches_single_device(tmp_path):
    """Streamed tree building composes with the mesh: per-shard histograms
    psum over devices; counts are exact integers so the forest structure
    is identical to the single-device stream."""
    from shifu_tpu.norm.dataset import write_codes
    from shifu_tpu.parallel.mesh import data_mesh
    from shifu_tpu.train.streaming_tree import train_trees_streamed
    from shifu_tpu.train.tree_trainer import TreeTrainConfig

    rng = np.random.default_rng(21)
    n, f, bins = 2000, 5, 8
    codes = rng.integers(0, bins, size=(n, f)).astype(np.int16)
    y = ((codes[:, 0] >= 4) | (codes[:, 2] <= 1)).astype(np.int8)
    w = np.ones(n, np.float32)
    cols = [f"c{i}" for i in range(f)]
    out = str(tmp_path / "CleanedData")
    write_codes(out, codes, y, w, cols, [bins] * f, n_shards=3)
    cfg = TreeTrainConfig(algorithm="GBT", tree_num=4, max_depth=4,
                          learning_rate=0.3, valid_set_rate=0.15, seed=5,
                          min_instances_per_node=2)
    single = train_trees_streamed(out, [bins] * f, [False] * f, cols, cfg)
    meshed = train_trees_streamed(out, [bins] * f, [False] * f, cols, cfg,
                                  mesh=data_mesh())
    for ts, tm in zip(single.spec.trees, meshed.spec.trees):
        np.testing.assert_array_equal(ts.feature, tm.feature)
        np.testing.assert_array_equal(ts.left_mask, tm.left_mask)
        np.testing.assert_allclose(ts.leaf_value, tm.leaf_value, atol=1e-4)
    assert meshed.valid_error == pytest.approx(single.valid_error, abs=1e-5)


def test_streamed_leafwise_matches_in_memory(tmp_path):
    """MaxLeaves no longer degrades to level-wise on the streamed path
    (DTMaster.java:137 toSplitQueue works at any scale): the streamed
    leaf-wise forest matches build_tree_leafwise's."""
    from shifu_tpu.norm.dataset import write_codes
    from shifu_tpu.train.streaming_tree import train_trees_streamed
    from shifu_tpu.train.tree_trainer import TreeTrainConfig, train_trees

    rng = np.random.default_rng(17)
    n, f, bins = 1500, 5, 8
    codes = rng.integers(0, bins, size=(n, f)).astype(np.int16)
    y = ((codes[:, 0] + codes[:, 1]) >= 8).astype(np.int8)
    w = np.ones(n, np.float32)
    cols = [f"c{i}" for i in range(f)]
    out = str(tmp_path / "CleanedData")
    write_codes(out, codes, y, w, cols, [bins] * f, n_shards=4)
    cfg = TreeTrainConfig(algorithm="GBT", tree_num=3, max_depth=6,
                          max_leaves=7, learning_rate=0.3,
                          valid_set_rate=0.15, seed=11,
                          min_instances_per_node=2)
    streamed = train_trees_streamed(out, [bins] * f, [False] * f, cols, cfg)
    mem = train_trees(codes.astype(np.int32), y.astype(np.float32), w,
                      [bins] * f, [False] * f, cols, cfg)
    for ts, tm in zip(streamed.spec.trees, mem.spec.trees):
        # lopsided trees with explicit child pointers
        assert ts.left is not None and tm.left is not None
        np.testing.assert_array_equal(ts.feature, tm.feature)
        np.testing.assert_array_equal(ts.left, tm.left)
        np.testing.assert_array_equal(ts.right, tm.right)
        np.testing.assert_allclose(ts.leaf_value, tm.leaf_value, atol=1e-4)
    assert streamed.valid_error == pytest.approx(mem.valid_error, abs=1e-4)


def test_streamed_training_memory_bound(tmp_path):
    """THE streaming claim: peak host RSS stays bounded by a few shards
    while the dataset is much larger. Runs in a subprocess so earlier
    tests' high-water marks cannot mask a regression; an np.concatenate
    of the full matrix (~200 MB) would blow the assertion."""
    import subprocess
    import sys

    script = r"""
import os, resource, sys
import numpy as np
sys.path.insert(0, %(repo)r)
from shifu_tpu.utils.platform import force_platform
force_platform("cpu", n_devices=1)
from shifu_tpu.norm.dataset import write_normalized
from shifu_tpu.train.nn_trainer import NNTrainConfig
from shifu_tpu.train.streaming import train_nn_streamed

out = %(out)r
n, d, shards = 2_000_000, 25, 10   # ~200 MB of f32 features
rng = np.random.default_rng(0)
x = rng.normal(size=(n, d)).astype(np.float32)
t = (x[:, 0] > 0).astype(np.int8)
w = np.ones(n, np.float32)
write_normalized(out, x, t, w, [f"c{i}" for i in range(d)], n_shards=shards)
del x, t, w

cfg = NNTrainConfig(hidden_nodes=[8], activations=["tanh"],
                    propagation="R", num_epochs=2, valid_set_rate=0.1,
                    seed=1)
# warm the compile + one full epoch so every steady-state allocation exists
train_nn_streamed(out, NNTrainConfig(**{**cfg.__dict__, "num_epochs": 1}))
base_kb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
train_nn_streamed(out, cfg)
peak_kb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
growth_mb = (peak_kb - base_kb) / 1024.0
print(f"RSS growth {growth_mb:.1f} MB")
# budget: ~2 shard pairs (~40 MB) + slack; full concatenation adds ~200 MB
assert growth_mb < 120, f"streamed training RSS grew {growth_mb:.1f} MB"
print("MEMORY-BOUND-OK")
""" % {"repo": os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
       "out": str(tmp_path / "NormalizedData")}
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run([sys.executable, "-c", script],
                          capture_output=True, text=True, timeout=600,
                          env=env)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "MEMORY-BOUND-OK" in proc.stdout
