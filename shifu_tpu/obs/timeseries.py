"""On-disk metrics time-series: rotating delta-encoded snapshot chunks.

Every observability surface before this PR — /metrics, run manifests,
request traces — describes one process at one instant (scrape time or
shutdown). This module gives each serving process a durable TIME AXIS:
a low-overhead snapshotter thread periodically captures the process
MetricsRegistry and appends delta-encoded windows to atomic rotating
chunk files under ``.shifu/runs/obs/<leaseId>/`` — the traffic-log file
discipline (loop/traffic.py): whole files land via temp + os.replace,
sequence numbers only grow, and a ``_meta.json`` sidecar names the
schema. A SIGKILLed process therefore leaves its last windows behind
for the fleet collector (obs/fleetview.py) to fold — its final
counters survive the process — and bench/regression tooling gets real
per-window series instead of only shutdown manifests.

Encoding, per window:

  * the FIRST window of every chunk is a FULL registry snapshot, so
    each chunk file is self-contained — bounded retention can drop old
    chunks without breaking reconstruction;
  * later windows are DELTAS against the previous window: counters as
    increments, timers/gauges/histograms as changed-keys-only absolute
    values, series as newly appended points. An idle process writes
    near-empty windows.

The current chunk is atomically REWRITTEN on every tick (bounded by
``chunkWindows`` windows per file), so at most the in-flight tick is
lost to a kill; at ``chunkWindows`` the sequence rotates and chunks
older than ``retainChunks`` are deleted.

Knobs: ``-Dshifu.obs.snapshotMs`` (0 = off), ``-Dshifu.obs.
chunkWindows``, ``-Dshifu.obs.retainChunks``.
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
from typing import Callable, Dict, List, Optional

from shifu_tpu.analysis.racetrack import tracked_lock
from shifu_tpu.fs.listing import sorted_glob
from shifu_tpu.utils import environment
from shifu_tpu.utils.log import get_logger

log = get_logger(__name__)

OBS_SUBDIR = os.path.join(".shifu", "runs", "obs")
META_FILE = "_meta.json"
TIMESERIES_SCHEMA = "shifu.obs.timeseries/1"

_CHUNK_RE = re.compile(r"^obs-(\d+)\.json$")

DEFAULT_CHUNK_WINDOWS = 8
DEFAULT_RETAIN_CHUNKS = 16


def snapshot_ms_setting() -> float:
    """shifu.obs.snapshotMs — metrics time-series snapshot cadence for
    the on-disk per-process chunk files (0 disables the snapshotter)."""
    return environment.get_float("shifu.obs.snapshotMs", 0.0)


def chunk_windows_setting() -> int:
    """shifu.obs.chunkWindows — snapshot windows per rotating chunk
    file (the current chunk is atomically rewritten each tick)."""
    return environment.get_int("shifu.obs.chunkWindows",
                               DEFAULT_CHUNK_WINDOWS)


def retain_chunks_setting() -> int:
    """shifu.obs.retainChunks — rotated chunk files kept per process
    (older ones are deleted; each chunk is self-contained)."""
    return environment.get_int("shifu.obs.retainChunks",
                               DEFAULT_RETAIN_CHUNKS)


def obs_dir(root: str, lease_id: str) -> str:
    """One process's time-series dir: ``<root>/.shifu/runs/obs/<leaseId>``.
    The lease id (resilience/lease.py) is the fleet-wide process name,
    so the collector can join these dirs against the peer scan."""
    return os.path.join(os.path.abspath(root), OBS_SUBDIR, str(lease_id))


def list_process_dirs(root: str) -> List[str]:
    """Every process dir that ever snapshotted under this ledger."""
    base = os.path.join(os.path.abspath(root), OBS_SUBDIR)
    if not os.path.isdir(base):
        return []
    return [p for p in sorted_glob(os.path.join(base, "*"))
            if os.path.isdir(p)]


def list_chunks(root: str, lease_id: str) -> List[str]:
    """Chunk files in sequence (append) order."""
    out = []
    for path in sorted_glob(os.path.join(obs_dir(root, lease_id),
                                       "obs-*.json")):
        m = _CHUNK_RE.match(os.path.basename(path))
        if m:
            out.append((int(m.group(1)), path))
    return [p for _s, p in sorted(out)]


# ---- delta encoding ----
def _hist_changed(prev: Optional[dict], cur: dict) -> bool:
    return (prev is None or prev.get("count") != cur.get("count")
            or prev.get("sum") != cur.get("sum"))


def encode_window(prev: Optional[dict], cur: dict, ts: float) -> dict:
    """One window: full when `prev` is None, else the delta described in
    the module docstring. `prev`/`cur` are MetricsRegistry.snapshot()
    dicts; neither is mutated."""
    if prev is None:
        return {"ts": ts, "full": True, "metrics": cur}
    w: dict = {"ts": ts}
    counters = {k: v - prev.get("counters", {}).get(k, 0.0)
                for k, v in cur.get("counters", {}).items()
                if v != prev.get("counters", {}).get(k, 0.0)}
    gauges = {k: v for k, v in cur.get("gauges", {}).items()
              if v != prev.get("gauges", {}).get(k)}
    timers = {k: v for k, v in cur.get("timers", {}).items()
              if v != prev.get("timers", {}).get(k)}
    hists = {k: v for k, v in cur.get("histograms", {}).items()
             if _hist_changed(prev.get("histograms", {}).get(k), v)}
    series = {}
    for k, pts in cur.get("series", {}).items():
        seen = len(prev.get("series", {}).get(k, []))
        if len(pts) > seen:
            series[k] = pts[seen:]
    for key, val in (("counters", counters), ("gauges", gauges),
                     ("timers", timers), ("histograms", hists),
                     ("series", series)):
        if val:
            w[key] = val
    return w


def apply_window(base: Optional[dict], window: dict) -> dict:
    """Fold one window into a reconstructed absolute snapshot dict
    (returns a new dict; `base` is not mutated)."""
    if window.get("full"):
        return json.loads(json.dumps(window["metrics"]))
    out = json.loads(json.dumps(base)) if base else {
        "counters": {}, "gauges": {}, "histograms": {}, "timers": {},
        "series": {}}
    for k, dv in window.get("counters", {}).items():
        out["counters"][k] = out["counters"].get(k, 0.0) + dv
    for k, v in window.get("gauges", {}).items():
        out["gauges"][k] = v
    for k, v in window.get("timers", {}).items():
        out["timers"][k] = v
    for k, v in window.get("histograms", {}).items():
        out["histograms"][k] = v
    for k, pts in window.get("series", {}).items():
        out["series"][k] = out["series"].get(k, []) + pts
    return out


def read_windows(root: str, lease_id: str) -> List[dict]:
    """Reconstructed absolute snapshots, one per window, in time order:
    ``[{"ts": <unix>, "metrics": <snapshot dict>}, ...]``. Unreadable or
    torn files are skipped (the atomic-write discipline makes torn files
    impossible in practice, but a reader must never crash on a dir a
    killed process left behind)."""
    out: List[dict] = []
    for path in list_chunks(root, lease_id):
        try:
            with open(path) as fh:
                chunk = json.load(fh)
        except (OSError, ValueError):
            continue
        if chunk.get("schema") != TIMESERIES_SCHEMA:
            continue
        base: Optional[dict] = None
        for w in chunk.get("windows", []):
            base = apply_window(base, w)
            out.append({"ts": w.get("ts", 0.0), "metrics": base})
    return out


def last_snapshot(root: str, lease_id: str) -> Optional[dict]:
    """The final reconstructed window a process left behind — what the
    fleet collector folds for an EXPIRED peer (its last counters). Only
    the newest self-contained chunk needs reading."""
    chunks = list_chunks(root, lease_id)
    if not chunks:
        return None
    try:
        with open(chunks[-1]) as fh:
            chunk = json.load(fh)
    except (OSError, ValueError):
        return None
    if chunk.get("schema") != TIMESERIES_SCHEMA:
        return None
    base: Optional[dict] = None
    ts = 0.0
    for w in chunk.get("windows", []):
        base = apply_window(base, w)
        ts = w.get("ts", ts)
    if base is None:
        return None
    return {"ts": ts, "metrics": base}


class MetricsSnapshotter:
    """Background snapshot thread for one process's registry.

    ``registry_cb`` is called at every tick (the process obs registry is
    swappable — obs.reset() — so the snapshotter must re-resolve it).
    Disarmed (snapshotMs <= 0) it is a no-op object, the SloTracker
    pattern: construction is always safe, arming is the knob's job."""

    def __init__(self, root: str, lease_id: str,
                 registry_cb: Callable,
                 snapshot_ms: Optional[float] = None,
                 chunk_windows: Optional[int] = None,
                 retain_chunks: Optional[int] = None) -> None:
        self.root = os.path.abspath(root)
        self.lease_id = str(lease_id)
        self.dir = obs_dir(root, lease_id)
        self._registry_cb = registry_cb
        self.snapshot_ms = (snapshot_ms_setting() if snapshot_ms is None
                            else float(snapshot_ms))
        self.chunk_windows = max(1, chunk_windows_setting()
                                 if chunk_windows is None
                                 else int(chunk_windows))
        self.retain_chunks = max(1, retain_chunks_setting()
                                 if retain_chunks is None
                                 else int(retain_chunks))
        self.enabled = self.snapshot_ms > 0.0
        self._lock = tracked_lock("obs.timeseries")
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._prev: Optional[dict] = None
        self._windows: List[dict] = []
        self._seq = 1
        self._written = 0

    # ---- lifecycle ----
    def start(self) -> None:
        if not self.enabled or self._thread is not None:
            return
        os.makedirs(self.dir, exist_ok=True)
        self._write_meta()
        with self._lock:
            # the tick thread is not running yet, but _seq is otherwise
            # lock-guarded — keep the discipline uniform
            self._seq = self._next_seq()
        self._thread = threading.Thread(
            target=self._run, name=f"shifu-obs-snap-{self.lease_id}",
            daemon=True)
        self._thread.start()
        log.info("metrics snapshotter on: %s every %.0f ms "
                 "(%d windows/chunk, keep %d chunks)", self.dir,
                 self.snapshot_ms, self.chunk_windows, self.retain_chunks)

    def stop(self, timeout: Optional[float] = 5.0) -> None:
        """Flush a final window and stop the thread (a clean shutdown
        leaves the registry's terminal state as the last window)."""
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout)
            self._thread = None
        if self.enabled:
            self.tick()

    def _run(self) -> None:
        while not self._stop.wait(self.snapshot_ms / 1000.0):
            try:
                self.tick()
            except Exception as e:  # a disk hiccup must never kill the
                # serving process's snapshot cadence
                log.warning("metrics snapshot tick failed: %s", e)

    # ---- one window ----
    def tick(self) -> None:
        """Capture one window and atomically (re)write the current
        chunk. Also callable inline (tests, final flush)."""
        from shifu_tpu.resilience.checkpoint import atomic_write_json

        reg = self._registry_cb()
        if reg is None:
            return
        snap = reg.snapshot()
        now = time.time()
        with self._lock:
            if not self._windows:
                # chunk start: full window, self-contained file
                self._windows.append(encode_window(None, snap, now))
            else:
                w = encode_window(self._prev, snap, now)
                if len(w) == 1:  # ts only: nothing changed, skip the
                    return       # rewrite (idle process, idle disk)
                self._windows.append(w)
            self._prev = snap
            seq = self._seq
            windows = list(self._windows)
            rotated = len(self._windows) >= self.chunk_windows
            if rotated:
                self._seq += 1
                self._windows = []
                self._prev = None  # next chunk restarts full
            self._written += 1
        path = os.path.join(self.dir, f"obs-{seq:05d}.json")
        atomic_write_json(path, {
            "schema": TIMESERIES_SCHEMA,
            "leaseId": self.lease_id,
            "pid": os.getpid(),
            "seq": seq,
            "windows": windows,
        })
        if rotated:
            self._retire()

    def _retire(self) -> None:
        chunks = list_chunks(self.root, self.lease_id)
        for path in chunks[:-self.retain_chunks or None]:
            try:
                os.remove(path)
            except OSError:
                pass

    # ---- layout ----
    def _next_seq(self) -> int:
        highest = 0
        for path in list_chunks(self.root, self.lease_id):
            m = _CHUNK_RE.match(os.path.basename(path))
            if m:
                highest = max(highest, int(m.group(1)))
        return highest + 1

    def _write_meta(self) -> None:
        from shifu_tpu.resilience.checkpoint import atomic_write_json

        atomic_write_json(os.path.join(self.dir, META_FILE), {
            "schema": TIMESERIES_SCHEMA,
            "leaseId": self.lease_id,
            "pid": os.getpid(),
            "snapshotMs": self.snapshot_ms,
            "chunkWindows": self.chunk_windows,
            "retainChunks": self.retain_chunks,
        })

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "dir": self.dir,
                "enabled": self.enabled,
                "snapshotMs": self.snapshot_ms,
                "windows": self._written,
                "chunks": self._seq if self._written else 0,
            }
