"""Fused Pallas TPU kernel: bin-code gather → per-node histogram
accumulate → split gain scan, with low-precision planes.

The tree builder's hot op (dt/DTWorker.java:851 featureUpdate, fused by
SURVEY §7.5 into "the histogram kernel") is

    hist[c, l, t] = Σ_i comps[i, c] · (node[i] == l) · (code_t[i] == t)

followed immediately by the split gain scan over the [C, L, T] result.
The XLA lowering in tree_trainer materializes the [blk, T] (or, hoisted,
the full [n, T]) code one-hot M in HBM between the compare and the
matmul, and round-trips the histogram to HBM between the build dispatch
and the scan. This kernel keeps BOTH in VMEM:

    grid (row blocks)  — per-chunk VMEM-resident [L, W] accumulator per
                         component, revisited across the grid (init at
                         block 0, += afterwards)
    per block          — the chunk's code one-hot M is built by ONE
                         broadcast-compare over a LANE-ALIGNED padded
                         column layout (below); a dot per component
                         plane contracts the row axis on the MXU
    last block         — the split scan runs in-kernel on the resident
                         planes (pairwise-rank formulation, below) and
                         emits per-column gain/rank/left-count planes,
                         so the histogram never has to be re-read from
                         HBM by a second scan dispatch

Three measured-loss fixes over the round-5 kernel (which lost 10-25% to
the XLA lowering on v5e and shipped dark behind an env var):

1. LANE-ALIGNED COLUMN LAYOUT. The old kernel wrote each feature's
   one-hot segment at its raw flat-T offset with per-run slice stores;
   33/65-wide segments land mid-lane and Mosaic emits masked unaligned
   lane stores — the measured 10-25% loss. The rebuilt kernel pads every
   feature piece to the 128-lane boundary INSIDE the kernel layout
   (gaps are dead columns, masked out of the gain scan and dropped at
   the [C, L, T] compaction — the output contract is unchanged) and
   builds M with zero per-feature stores: a static selection matmul
   broadcasts each column's code (codes_f32 @ E, exact in f32), then one
   full-width compare against the static slot-position row writes the
   whole [blk, W] block aligned.

2. LOW-PRECISION PLANES. Bin codes travel int8 in HBM for chunks whose
   features all fit 128 slots (4x less code-read bandwidth than i32 —
   the kernel is bandwidth-bound on code reads; wide chunks stay i32).
   GBT gradient/hessian component planes travel bf16 with f32 MXU
   accumulation (`preferred_element_type`); RF planes stay f32 so
   integer-weight counts stay exact and PR-3's bit-parity gate holds
   bit-for-bit.

3. IN-KERNEL SPLIT SCAN. After the last grid step the kernel computes,
   per (node, candidate column), the cumulative left/right stats IN THE
   REFERENCE'S MEAN-SORTED ORDER without sorting: left(a) = Σ_b
   IND[b, a] · h[b] where IND[b, a] = [b's (sec, index) lex-≤ a's,
   same segment] — a [W, W] indicator built from one exact
   eye-transpose of the sec row plus static column metadata, applied as
   C matvecs on the MXU per node. rank(a) = Σ_b IND[b, a] − 1
   reproduces the lexsort rank exactly (stable ties included), so the
   emitted (gain, rank, left-count) planes are combinable with the XLA
   reference scan epilogue: argmax with the reference's ordered-position
   tie-break, rank_flat for row routing, the model-facing left mask.
   Features too wide for one chunk (> wmax padded columns) fall back to
   the XLA reference scan on just their columns of the compacted
   histogram — the kernel masks them out of its own scan.

Numerics: counts and integer-weight moments are exact under any
summation order (< 2^24), so RF forests are BIT-equal with the kernel
on vs off; GBT float planes differ only by summation association
(tolerance-tested), with bf16 comps adding one rounding at plane build.

Mode selection is the cataloged knob `-Dshifu.pallas.mode`:
  auto  (default) kernel on TPU backends, XLA elsewhere
  on    kernel everywhere — interpret mode off-TPU (CPU tests)
  off   XLA lowering everywhere
(The round-5 `SHIFU_PALLAS` env var is retired; docs/KNOBS.md has the
catalog row.)
"""

from __future__ import annotations

import functools
from typing import List, Optional

import numpy as np

_LANE = 128  # TPU lane width: every feature piece starts lane-aligned

# VMEM budget shaping: rows per grid step x max padded chunk columns.
# M [BLK, W] + the [W, W] scan indicator + C [L, W] planes must sit well
# under ~16 MB. Overridable per PROCESS (-Dshifu.pallas.blk /
# -Dshifu.pallas.wmax) so kernel-tuning rounds can sweep shapings
# without code edits — per process because the built kernels are cached
# (_build_call lru, tree_trainer's program cache): set the knobs at
# launch, one process per shaping, the way the bench sweep children do.
# The chosen values land in the profiler snapshot (obs.profile
# annotations, process-global so a later obs scope still reports them)
# so every manifest records which shaping produced its numbers.
_BLK = 512
_W_MAX = 1024
# the in-kernel scan's [W, W] indicator scratch is W^2 f32; past 1024
# padded columns it would blow the VMEM budget, so fused-scan chunking
# clamps to this even when -Dshifu.pallas.wmax asks for wider (hist-only
# chunks honor the raw knob)
_SCAN_W_CAP = 1024


def blk_setting() -> int:
    """shifu.pallas.blk — rows per grid step (default 512)."""
    from shifu_tpu.utils import environment

    return max(8, environment.get_int("shifu.pallas.blk", _BLK))


def wmax_setting() -> int:
    """shifu.pallas.wmax — max one-hot columns per VMEM chunk (1024)."""
    from shifu_tpu.utils import environment

    return max(_LANE, environment.get_int("shifu.pallas.wmax", _W_MAX))


def pallas_mode() -> str:
    """shifu.pallas.mode — auto | on | off (default auto)."""
    from shifu_tpu.utils import environment

    m = (environment.get_property("shifu.pallas.mode", "auto")
         or "auto").strip().lower()
    return m if m in ("auto", "on", "off") else "auto"


def _on_tpu() -> bool:
    try:
        import jax

        return jax.default_backend() in ("tpu", "axon")
    except Exception:  # jax backend probe failed: assume not a TPU
        return False


def pallas_active() -> tuple:
    """(enabled, interpret) for the current process.

    auto = the measured default: kernel on TPU, XLA elsewhere. on =
    forced everywhere, interpret mode off-TPU (the CPU test harness).
    off = XLA everywhere."""
    mode = pallas_mode()
    if mode == "off":
        return False, False
    if mode == "on":
        return True, not _on_tpu()
    return _on_tpu(), False


def _pad_lane(w: int) -> int:
    return -(-w // _LANE) * _LANE


class _Chunk:
    """One lane-aligned kernel chunk: a contiguous run of feature pieces,
    each padded to the 128-lane boundary, plus the static per-column
    metadata the kernel and the epilogue need."""

    __slots__ = ("pieces", "w", "f_lo", "f_hi", "pos", "feat_rel", "clip",
                 "seg", "size", "iscat", "scan_ok", "seg0", "t_idx",
                 "keep", "narrow", "start")

    def __init__(self, pieces, lay, whole):
        self.pieces = pieces
        self.f_lo = pieces[0][0]
        self.f_hi = pieces[-1][0] + 1
        w = pieces[-1][3] + _pad_lane(pieces[-1][2] - pieces[-1][1])
        self.w = w
        pos = np.full(w, -1, np.int32)
        feat_rel = np.zeros(w, np.int32)
        clip = np.zeros(w, np.int32)
        seg = np.full(w, -1, np.int32)
        size = np.ones(w, np.int32)
        iscat = np.zeros(w, np.int32)
        scan_ok = np.zeros(w, np.int32)
        seg0 = np.zeros(w, np.float32)
        t_idx = np.full(w, -1, np.int64)
        start = np.zeros(w, np.int32)
        for (f, lo, hi, col0) in pieces:
            cw = hi - lo
            sl = slice(col0, col0 + cw)
            pos[sl] = np.arange(lo, hi, dtype=np.int32)
            feat_rel[sl] = f - self.f_lo
            clip[sl] = int(lay.clip_max[f])
            seg[sl] = f
            size[sl] = int(lay.slots[f])
            iscat[sl] = int(bool(lay.is_cat_t[lay.off[f]]))
            scan_ok[sl] = int(whole[f])
            seg0[sl] = 1.0 if f == 0 else 0.0
            t_idx[sl] = np.arange(int(lay.off[f]) + lo,
                                  int(lay.off[f]) + hi, dtype=np.int64)
            start[sl] = int(lay.off[f])
        self.pos, self.feat_rel, self.clip = pos, feat_rel, clip
        self.seg, self.size, self.iscat = seg, size, iscat
        self.scan_ok, self.seg0, self.t_idx = scan_ok, seg0, t_idx
        self.start = start
        self.keep = np.nonzero(pos >= 0)[0].astype(np.int64)
        self.narrow = all(int(lay.slots[f]) <= _LANE
                          for (f, _lo, _hi, _c0) in pieces)


def _chunks(lay, target: Optional[int] = None) -> List[_Chunk]:
    """Split the flat T axis into lane-aligned chunks of <= target padded
    columns. Every feature piece starts at a 128-lane boundary; a feature
    wider than the target spans several pieces/chunks (and is then
    excluded from the in-kernel scan — the epilogue's XLA fallback owns
    it). Chunks cover whole features of [0, T) in order, so the caller
    can hand the kernel a contiguous column slice of the code matrix."""
    if target is None:
        target = wmax_setting()
    target = max(_LANE, (target // _LANE) * _LANE)
    slots = [int(s) for s in lay.slots]
    whole = [_pad_lane(s) <= target for s in slots]
    chunks: List[_Chunk] = []
    cur: List[tuple] = []
    cur_w = 0
    for f, s in enumerate(slots):
        lo = 0
        while lo < s:
            avail = target - cur_w
            # a chunk-fitting feature must NEVER straddle a chunk tail:
            # its in-kernel scan sees only its own chunk's columns, so a
            # split would scan partial histograms — start a fresh chunk
            # instead (only over-wide features split, and those are the
            # epilogue's XLA-fallback set)
            if avail < _LANE or (whole[f] and _pad_lane(s) > avail):
                chunks.append(_Chunk(cur, lay, whole))
                cur, cur_w = [], 0
                continue
            take = min(s - lo, avail)
            cur.append((f, lo, lo + take, cur_w))
            cur_w += _pad_lane(take)
            lo += take
    if cur:
        chunks.append(_Chunk(cur, lay, whole))
    return chunks


def wide_features(lay, target: Optional[int] = None) -> List[int]:
    """Features too wide for one chunk at this shaping — scanned by the
    XLA reference fallback instead of the in-kernel scan."""
    if target is None:
        target = wmax_setting()
    target = max(_LANE, (target // _LANE) * _LANE)
    return [f for f, s in enumerate(int(x) for x in lay.slots)
            if _pad_lane(s) > target]


@functools.lru_cache(maxsize=None)
def _build_call(lay_key: tuple, target: int, ci: int, L: int, C: int,
                blk: int, code_i8: bool, lowp: bool, scan_key,
                interpret: bool):
    """One chunk's pallas_call builder, cached per static configuration.

    Returns call(codes_chunk [n, nf], comps [n, C], node [n, 1],
    featok [1, W]) -> (C hist planes [L, W], + when scan_key:
    gain [L, W], rank [L, W], lcnt [L, W], tot0 [L, C]).

    scan_key = None (hist-only) or (impurity, min_inst, min_gain,
    n_classes)."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    from shifu_tpu.train.tree_trainer import make_layout

    lay = make_layout(list(lay_key[0]), list(lay_key[1]))
    ch = _chunks(lay, target)[ci]
    W = ch.w
    nf = ch.f_hi - ch.f_lo
    do_scan = scan_key is not None
    comp_dt = jnp.bfloat16 if lowp else jnp.float32
    m_dt = comp_dt
    if do_scan:
        impurity, min_inst, min_gain, n_classes = scan_key
        use_entropy = impurity == "entropy"

    # static column metadata rides in as [1, W] / [W, 1] inputs (vector
    # constants are inputs, not closure captures, in Mosaic)
    pos_np = ch.pos[None, :]
    clip_np = ch.clip[None, :]
    featrel_np = ch.feat_rel[None, :]
    seg_row_np = ch.seg[None, :]
    seg_col_np = ch.seg[:, None]
    iscat_np = ch.iscat[None, :]
    size_np = ch.size[None, :].astype(np.float32)
    seg0_np = ch.seg0[:, None]

    def kernel(*refs):
        (codes_ref, comps_ref, node_ref, featok_ref, pos_ref, clip_ref,
         featrel_ref) = refs[:7]
        k = 7
        if do_scan:
            (segr_ref, segc_ref, iscat_ref, size_ref, seg0_ref) = \
                refs[k:k + 5]
            k += 5
        hist_refs = refs[k:k + C]
        k += C
        if do_scan:
            gain_ref, rank_ref, lcnt_ref, tot0_ref = refs[k:k + 4]
            k += 4
        m_ref = refs[k]
        if do_scan:
            wsq_ref, sec_ref, secT_ref = refs[k + 1:k + 4]

        i = pl.program_id(0)
        grid_n = pl.num_programs(0)

        # ---- M build: selection matmul + one aligned full-width compare
        # (no per-feature stores — the round-5 measured loss) ----
        codes_f = codes_ref[...].astype(jnp.float32)  # [blk, nf]
        sel = (jax.lax.broadcasted_iota(jnp.int32, (nf, W), 0)
               == featrel_ref[...]).astype(jnp.float32)  # [nf, W]
        cb = jax.lax.dot_general(
            codes_f, sel, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)  # [blk, W]: code per col
        cb = jnp.clip(cb, 0.0, clip_ref[...].astype(jnp.float32))
        # gap columns carry pos -1: clipped codes are >= 0, so M is 0
        m_ref[...] = (cb == pos_ref[...].astype(jnp.float32)).astype(m_dt)

        comps = comps_ref[...]  # [blk, C]
        if L > 1:
            oh_node = (node_ref[...] == jax.lax.broadcasted_iota(
                jnp.int32, (blk, L), 1)).astype(comp_dt)
        M = m_ref[...]
        for c in range(C):
            A_c = (comps[:, c:c + 1] if L == 1
                   else comps[:, c:c + 1] * oh_node)  # [blk, L]
            contrib = jax.lax.dot_general(
                A_c, M, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)  # [L, W]

            @pl.when(i == 0)
            def _init(out_ref=hist_refs[c]):
                out_ref[...] = jnp.zeros_like(out_ref)

            hist_refs[c][...] += contrib

        if not do_scan:
            return

        # ---- fused split scan on the VMEM-resident planes (last step):
        # the reference's mean-sorted cumulative stats via the pairwise
        # lex-≤ indicator — no sort, all matmul/elementwise ----
        @pl.when(i == grid_n - 1)
        def _scan():
            eps = 1e-12
            # the reference keys empty category slots with +inf so they
            # sort last; the eye-transpose matmul would turn 0*inf into
            # NaN, so use a huge FINITE sentinel — same ordering, same
            # stable index tie-break among empties
            big = 3.0e38
            h = [hist_refs[c][...] for c in range(C)]  # [L, W] f32
            if n_classes >= 3:
                cnt = h[0]
                ex = jnp.zeros_like(cnt)
                for c in range(1, C):
                    cnt = cnt + h[c]
                for c in range(C):
                    ex = ex + float(c) * h[c]
                mean = jnp.where(cnt > 0, ex / jnp.maximum(cnt, eps), big)
            else:
                cnt, s1 = h[0], h[1]
                mean = jnp.where(cnt > 0, s1 / jnp.maximum(cnt, eps), big)
            posf = pos_ref[...].astype(jnp.float32)  # [1, W]
            sec_ref[...] = jnp.where(
                iscat_ref[...] != 0, mean,
                jnp.broadcast_to(posf, (L, W)))
            # exact data transpose via an in-kernel identity matmul:
            # secT[b, l] = sec[l, b] (1.0 * x sums with zeros — exact)
            wsq_ref[...] = (jax.lax.broadcasted_iota(jnp.int32, (W, W), 0)
                            == jax.lax.broadcasted_iota(
                                jnp.int32, (W, W), 1)).astype(jnp.float32)
            secT_ref[...] = jax.lax.dot_general(
                wsq_ref[...], sec_ref[...], (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)  # [W, L]

            seg_eq = segc_ref[...] == segr_ref[...]  # [W, W] static
            tie = (jax.lax.broadcasted_iota(jnp.int32, (W, W), 0)
                   <= jax.lax.broadcasted_iota(jnp.int32, (W, W), 1))
            fok = featok_ref[...]  # [1, W] f32, gaps/wide already 0
            sizef = size_ref[...]  # [1, W] f32
            gain_rows, rank_rows, lcnt_rows = [], [], []
            for l in range(L):
                sec_r = sec_ref[l:l + 1, :]    # [1, W]
                sec_c = secT_ref[:, l:l + 1]   # [W, 1]
                lt = sec_c < sec_r
                eq = sec_c == sec_r
                inc = lt | (eq & tie)          # lex-≤ on (sec, index)
                wsq_ref[...] = jnp.where(seg_eq & inc, 1.0, 0.0)
                ind = wsq_ref[...]
                left = [jax.lax.dot_general(
                    h[c][l:l + 1, :], ind, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32)
                    for c in range(C)]  # [1, W] each
                rank = jnp.sum(ind, axis=0, keepdims=True) - 1.0
                wsq_ref[...] = jnp.where(seg_eq & ~inc, 1.0, 0.0)
                indr = wsq_ref[...]
                right = [jax.lax.dot_general(
                    h[c][l:l + 1, :], indr, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32)
                    for c in range(C)]

                if n_classes >= 3:
                    lc = left[0]
                    rc = right[0]
                    for c in range(1, C):
                        lc = lc + left[c]
                        rc = rc + right[c]
                    tc = lc + rc

                    def mass(parts, total):
                        acc = None
                        for c in range(C):
                            p = parts[c] / jnp.maximum(total, eps)
                            if use_entropy:
                                t = -p * (jnp.log2(jnp.maximum(p, eps)))
                            else:
                                t = p * p
                            acc = t if acc is None else acc + t
                        if use_entropy:
                            return total * acc
                        return total * (1.0 - acc)

                    tot = [left[c] + right[c] for c in range(C)]
                    g = (mass(tot, tc) - mass(left, lc) - mass(right, rc))
                else:
                    lc, ls1, ls2 = left
                    rc, rs1, rs2 = right
                    tc, ts1, ts2 = lc + rc, ls1 + rs1, ls2 + rs2
                    if impurity == "entropy":
                        def emass(c_, p_):
                            pr = p_ / jnp.maximum(c_, eps)
                            q = 1.0 - pr
                            hh = -(pr * jnp.log2(jnp.maximum(pr, eps))
                                   + q * jnp.log2(jnp.maximum(q, eps)))
                            return c_ * hh

                        g = emass(tc, ts1) - emass(lc, ls1) - emass(rc,
                                                                    rs1)
                    elif impurity == "gini":
                        def gmass(c_, p_):
                            ng = c_ - p_
                            return c_ - (p_ * p_ + ng * ng) / jnp.maximum(
                                c_, eps)

                        g = gmass(tc, ts1) - gmass(lc, ls1) - gmass(rc,
                                                                    rs1)
                    elif impurity == "friedmanmse":
                        ml = ls1 / jnp.maximum(lc, eps)
                        mr = rs1 / jnp.maximum(rc, eps)
                        g = (lc * rc / jnp.maximum(tc, eps)
                             * (ml - mr) ** 2)
                    else:  # variance

                        def sse(c_, s_, q_):
                            return q_ - s_ * s_ / jnp.maximum(c_, eps)

                        g = (sse(tc, ts1, ts2) - sse(lc, ls1, ls2)
                             - sse(rc, rs1, rs2))

                valid = ((lc >= min_inst) & (rc >= min_inst)
                         & (g > min_gain) & (fok > 0)
                         & (rank < sizef - 1.0))
                gain_rows.append(jnp.where(valid, g, -jnp.inf))
                rank_rows.append(rank)
                lcnt_rows.append(lc)
            gain_ref[...] = jnp.concatenate(gain_rows, axis=0)
            rank_ref[...] = jnp.concatenate(rank_rows, axis=0)
            lcnt_ref[...] = jnp.concatenate(lcnt_rows, axis=0)
            # node totals = segment-0 column sums (the reference's
            # seg0-cumsum endpoint), summed across chunks outside
            tot_cols = [jax.lax.dot_general(
                hist_refs[c][...], seg0_ref[...], (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32) for c in range(C)]
            tot0_ref[...] = jnp.concatenate(tot_cols, axis=1)  # [L, C]

    def call(codes_chunk, comps, node2d, featok):
        import jax.numpy as jnp

        n = codes_chunk.shape[0]
        grid = n // blk
        code_dt = jnp.int8 if code_i8 else jnp.int32
        in_specs = [
            pl.BlockSpec((blk, nf), lambda i: (i, 0)),
            pl.BlockSpec((blk, C), lambda i: (i, 0)),
            pl.BlockSpec((blk, 1), lambda i: (i, 0)),
            pl.BlockSpec((1, W), lambda i: (0, 0)),
            pl.BlockSpec((1, W), lambda i: (0, 0)),
            pl.BlockSpec((1, W), lambda i: (0, 0)),
            pl.BlockSpec((1, W), lambda i: (0, 0)),
        ]
        args = [codes_chunk.astype(code_dt), comps, node2d,
                featok.astype(jnp.float32),
                jnp.asarray(pos_np), jnp.asarray(clip_np),
                jnp.asarray(featrel_np)]
        if do_scan:
            in_specs += [
                pl.BlockSpec((1, W), lambda i: (0, 0)),
                pl.BlockSpec((W, 1), lambda i: (0, 0)),
                pl.BlockSpec((1, W), lambda i: (0, 0)),
                pl.BlockSpec((1, W), lambda i: (0, 0)),
                pl.BlockSpec((W, 1), lambda i: (0, 0)),
            ]
            args += [jnp.asarray(seg_row_np), jnp.asarray(seg_col_np),
                     jnp.asarray(iscat_np), jnp.asarray(size_np),
                     jnp.asarray(seg0_np)]
        out_specs = [pl.BlockSpec((L, W), lambda i: (0, 0))
                     for _ in range(C)]
        out_shape = [jax.ShapeDtypeStruct((L, W), jnp.float32)
                     for _ in range(C)]
        if do_scan:
            out_specs += [pl.BlockSpec((L, W), lambda i: (0, 0))] * 3 \
                + [pl.BlockSpec((L, C), lambda i: (0, 0))]
            out_shape += [jax.ShapeDtypeStruct((L, W), jnp.float32)] * 3 \
                + [jax.ShapeDtypeStruct((L, C), jnp.float32)]
        scratch = [pltpu.VMEM((blk, W), m_dt)]
        if do_scan:
            scratch += [pltpu.VMEM((W, W), jnp.float32),
                        pltpu.VMEM((L, W), jnp.float32),
                        pltpu.VMEM((W, L), jnp.float32)]
        outs = pl.pallas_call(
            kernel,
            grid=(grid,),
            in_specs=in_specs,
            out_specs=out_specs,
            out_shape=out_shape,
            scratch_shapes=scratch,
            interpret=interpret,
        )(*args)
        return outs

    return call


def _comps_of(labels, weights, active, n_classes: int, dtype):
    """[n, C] component planes (shared semantics with tree_trainer's
    _make_comps_of): inactive rows zero out via the weight."""
    import jax.numpy as jnp

    w = jnp.where(active, weights, 0.0)
    if n_classes >= 3:
        cls = jnp.clip(labels.astype(jnp.int32), 0, n_classes - 1)
        cols = [w * (cls == c).astype(jnp.float32)
                for c in range(n_classes)]
    else:
        cols = [w, w * labels, w * labels * labels]
    return jnp.stack(cols, 1).astype(dtype)


def _pad_rows(arrs, blk):
    import jax.numpy as jnp

    n = arrs[0].shape[0]
    n_pad = -(-n // blk) * blk
    pad = n_pad - n
    return [jnp.pad(a, ((0, pad),) + ((0, 0),) * (a.ndim - 1))
            for a in arrs]


def _annotate(lay, chunks, L, do_scan, lowp, i8_chunks):
    from shifu_tpu.obs import profile as _profile

    _profile.annotate(
        "ops.hist_pallas", blk=blk_setting(), wMax=wmax_setting(),
        chunks=len(chunks), L=int(L), T=int(lay.T),
        paddedT=int(sum(c.w for c in chunks)), fusedScan=bool(do_scan),
        bf16Planes=bool(lowp), int8Chunks=int(i8_chunks),
        mode=pallas_mode())


def make_pallas_hist_fn(L: int, lay, n_classes: int = 0,
                        interpret: bool = False,
                        low_precision: bool = False):
    """Histogram-only kernel entry: traced fn (codes, labels, weights,
    node_slot, active) -> [C, L, T] matching tree_trainer's histogram
    contract (the hist-subtraction built-child, budget-batched,
    leaf-wise and streamed/shard_map call sites). `interpret=True` runs
    the kernels in pallas interpret mode (CPU tests)."""
    import jax.numpy as jnp

    C = n_classes if n_classes >= 3 else 3
    blk_max = blk_setting()
    target = wmax_setting()
    chunks = _chunks(lay, target)
    comp_dt = jnp.bfloat16 if low_precision else jnp.float32
    _annotate(lay, chunks, L, False, low_precision, 0)

    def hist_fn(codes, labels, weights, node_slot, active):
        n, F = codes.shape
        comps = _comps_of(labels, weights, active, n_classes, comp_dt)
        nl = jnp.where(active, jnp.clip(node_slot, 0, L - 1), 0)
        blk = min(blk_max, n)
        codes_p, comps_p, nl_p = _pad_rows([codes, comps, nl], blk)
        node2d = nl_p[:, None]
        parts = []
        for ci, ch in enumerate(chunks):
            call = _build_call(lay.key, target, ci, L, C, blk, False,
                               low_precision, None, interpret)
            featok = jnp.ones((1, ch.w), jnp.float32)
            outs = call(codes_p[:, ch.f_lo:ch.f_hi], comps_p, node2d,
                        featok)
            planes = jnp.stack(outs[:C])  # [C, L, W]
            parts.append(planes[:, :, jnp.asarray(ch.keep)])
        return (parts[0] if len(parts) == 1
                else jnp.concatenate(parts, axis=2))  # [C, L, T]

    return hist_fn


def make_codes8_fn(lay):
    """jit-able (codes [n, F] i32) -> [n, F] int8 low-bandwidth code
    planes: exact for every feature with <= 128 slots (the int8-eligible
    chunks); wide features keep reading the i32 matrix."""
    import jax.numpy as jnp

    cap = np.minimum(lay.clip_max, _LANE - 1).astype(np.int32)

    def build(codes):
        return jnp.clip(codes, 0, jnp.asarray(cap)[None, :]).astype(
            jnp.int8)

    return build


def make_fused_level_fn(L: int, lay, impurity: str, min_inst: int,
                        min_gain: float, n_classes: int = 0,
                        interpret: bool = False,
                        low_precision: bool = False):
    """Fused histogram + split-scan entry for one tree level.

    Traced fn (codes, codes8, labels, weights, node_slot, active,
    feat_ok_t) -> (hist [C, L, T], scan) where `scan` is the reference
    split_scan 9-tuple (feature, cut_rank, rank_flat, leaf_value,
    is_split, best_gain, left_mask, node_cnt, left_cnt) — drop-in for
    tree_trainer's per-level hist+scan pair. `codes8` may be None (i32
    codes everywhere); when given, int8-eligible chunks read it instead
    of the i32 matrix."""
    import jax.numpy as jnp

    C = n_classes if n_classes >= 3 else 3
    blk_max = blk_setting()
    target = min(wmax_setting(), _SCAN_W_CAP)
    chunks = _chunks(lay, target)
    wide = wide_features(lay, target)
    comp_dt = jnp.bfloat16 if low_precision else jnp.float32
    scan_key = (impurity, int(min_inst), float(min_gain), int(n_classes))
    T, s_max = lay.T, lay.s_max
    i8_chunks = sum(1 for ch in chunks if ch.narrow)
    _annotate(lay, chunks, L, True, low_precision, i8_chunks)

    # static epilogue maps over the padded column space
    start_all = np.concatenate([ch.start for ch in chunks])
    seg_all = np.concatenate([ch.seg for ch in chunks])
    keep_all = np.concatenate(
        [ch.keep + off for ch, off in zip(
            chunks, np.cumsum([0] + [c.w for c in chunks[:-1]]))])
    # XLA-fallback sub-layout for chunk-spanning wide features
    if wide:
        wide_cols = np.concatenate(
            [np.arange(int(lay.off[f]), int(lay.off[f]) + int(lay.slots[f]),
                       dtype=np.int64) for f in wide])
        w_slots = np.asarray([int(lay.slots[f]) for f in wide], np.int32)
        w_off = np.zeros(len(wide), np.int32)
        w_off[1:] = np.cumsum(w_slots[:-1])
        w_seg = np.repeat(np.arange(len(wide), dtype=np.int32), w_slots)
        w_pos = np.arange(int(w_slots.sum()), dtype=np.int32) - w_off[w_seg]
        w_start = w_off[w_seg]
        w_size = w_slots[w_seg]
        w_iscat = np.asarray(
            [bool(lay.is_cat_t[lay.off[f]]) for f in wide])[w_seg]
        w_clip = np.maximum(w_slots - 1, 0)
        w_smax = int(w_slots.max())
        wide_arr = np.asarray(wide, np.int32)
        from shifu_tpu.train.tree_trainer import _make_scan_fn

        wide_scan = _make_scan_fn(L, int(w_slots.sum()), w_smax, impurity,
                                  min_inst, min_gain, n_classes)
    off_c = np.asarray(lay.off)
    clip_c = np.asarray(lay.clip_max)

    def fused_fn(codes, codes8, labels, weights, node_slot, active,
                 feat_ok_t):
        n, F = codes.shape
        comps = _comps_of(labels, weights, active, n_classes, comp_dt)
        nl = jnp.where(active, jnp.clip(node_slot, 0, L - 1), 0)
        blk = min(blk_max, n)
        pads = _pad_rows(
            [codes, comps, nl] + ([codes8] if codes8 is not None else []),
            blk)
        codes_p, comps_p, nl_p = pads[:3]
        codes8_p = pads[3] if codes8 is not None else None
        node2d = nl_p[:, None]
        fok_f = feat_ok_t.astype(jnp.float32)

        hist_parts, gain_parts, rank_parts, lcnt_parts = [], [], [], []
        tot0 = None
        for ci, ch in enumerate(chunks):
            use_i8 = ch.narrow and codes8_p is not None
            src = codes8_p if use_i8 else codes_p
            call = _build_call(lay.key, target, ci, L, C, blk, use_i8,
                               low_precision, scan_key, interpret)
            # dynamic per-tree feature mask folded with the static
            # scannable/gap mask into one [1, W] plane
            t_clamp = np.where(ch.t_idx >= 0, ch.t_idx, 0)
            fok = (fok_f[jnp.asarray(t_clamp)]
                   * jnp.asarray((ch.scan_ok > 0)
                                 & (ch.pos >= 0), np.float32))[None, :]
            outs = call(src[:, ch.f_lo:ch.f_hi], comps_p, node2d, fok)
            planes = jnp.stack(outs[:C])
            hist_parts.append(planes[:, :, jnp.asarray(ch.keep)])
            gain_parts.append(outs[C])
            rank_parts.append(outs[C + 1])
            lcnt_parts.append(outs[C + 2])
            tot0 = outs[C + 3] if tot0 is None else tot0 + outs[C + 3]

        hist = (hist_parts[0] if len(hist_parts) == 1
                else jnp.concatenate(hist_parts, axis=2))  # [C, L, T]
        gain_all = jnp.concatenate(gain_parts, axis=1)  # [L, ΣW]
        rank_all = jnp.concatenate(rank_parts, axis=1)
        lcnt_all = jnp.concatenate(lcnt_parts, axis=1)

        # kernel-side best with the reference's ordered-position
        # tie-break: o = segment start + within-segment rank
        o_all = jnp.asarray(start_all, jnp.float32)[None, :] + rank_all
        gmax = jnp.max(gain_all, axis=-1)
        cand = gain_all == gmax[:, None]
        obest = jnp.min(jnp.where(cand, o_all, jnp.inf), axis=-1)
        best = jnp.argmax(cand & (o_all == obest[:, None]), axis=-1)
        pick = lambda a: jnp.take_along_axis(  # noqa: E731
            a, best[:, None], axis=-1)[:, 0]
        feature = jnp.asarray(seg_all)[best].astype(jnp.int32)
        cut_rank = pick(rank_all).astype(jnp.int32)
        left_cnt = pick(lcnt_all)
        best_gain = gmax

        # rank_flat over the ORIGINAL flat columns (row routing + mask)
        rank_flat = rank_all[:, jnp.asarray(keep_all)].astype(jnp.int32)

        if wide:
            sub = wide_scan(
                hist[:, :, jnp.asarray(wide_cols)],
                fok_f[jnp.asarray(wide_cols)] > 0,
                jnp.asarray(w_iscat), jnp.asarray(w_seg),
                jnp.asarray(w_pos), jnp.asarray(w_start),
                jnp.asarray(w_size), jnp.asarray(w_off),
                jnp.asarray(w_clip), int(w_slots[0]))
            (f_w, cut_w, rank_w, _lv, _sp, g_w, _lm, _nc, lc_w) = sub
            f_wg = jnp.asarray(wide_arr)[f_w]
            o_w = jnp.asarray(off_c)[f_wg].astype(jnp.float32) \
                + cut_w.astype(jnp.float32)
            take_w = (g_w > best_gain) | ((g_w == best_gain)
                                          & (o_w < obest))
            feature = jnp.where(take_w, f_wg, feature)
            cut_rank = jnp.where(take_w, cut_w, cut_rank)
            left_cnt = jnp.where(take_w, lc_w, left_cnt)
            best_gain = jnp.where(take_w, g_w, best_gain)
            rank_flat = rank_flat.at[:, jnp.asarray(wide_cols)].set(rank_w)

        is_split = jnp.isfinite(best_gain)

        # node stats from the segment-0 totals (summed across chunks)
        if n_classes >= 3:
            node_cnt = tot0.sum(axis=1)
            leaf_value = jnp.argmax(tot0, axis=1).astype(jnp.float32)
        else:
            node_cnt = tot0[:, 0]
            leaf_value = tot0[:, 1] / jnp.maximum(node_cnt, 1e-12)

        # model-facing mask over ORIGINAL codes [L, s_max] (reference
        # formula, from the merged rank_flat)
        s_range = jnp.arange(s_max, dtype=jnp.int32)
        f_clip = jnp.asarray(clip_c)[feature]
        s_idx = jnp.minimum(s_range[None, :], f_clip[:, None])
        flat_idx = jnp.asarray(off_c)[feature][:, None] + s_idx
        ranks = jnp.take_along_axis(rank_flat, flat_idx, axis=-1)
        left_mask = (
            (ranks <= cut_rank[:, None])
            & (s_range[None, :] <= f_clip[:, None])
            & is_split[:, None]
        )
        return hist, (feature, cut_rank, rank_flat, leaf_value, is_split,
                      best_gain, left_mask, node_cnt, left_cnt)

    return fused_fn
