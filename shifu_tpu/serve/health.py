"""Serve health state machine: ok | degraded | draining, with a reason.

/healthz used to be a liveness ping; under the self-healing serve path it
is the load balancer's routing signal, so it must distinguish three
states the supervisor actually produces:

  ok        scoring normally.
  degraded  still scoring, but a worker crash was survived recently —
            the state a router uses to de-prioritize (not eject) a
            replica. Clears back to `ok` after `ok_after` consecutive
            clean batches.
  draining  not accepting new work (shutdown in progress, or the worker
            restart budget is exhausted) — /healthz returns 503 so the
            balancer stops routing here while in-flight work finishes.

Transitions are monotone toward draining: once draining, crash/ok notes
cannot resurrect the replica (a drained server restarts, it does not
heal). Every transition lands in `serve.health.transitions{to=...}` so
the run-ledger manifest carries the replica's health history.
"""

from __future__ import annotations

from typing import Optional

from shifu_tpu.analysis.racetrack import guarded_by, tracked_lock

OK = "ok"
DEGRADED = "degraded"
DRAINING = "draining"

DEFAULT_OK_AFTER = 3


class HealthMonitor:
    """Thread-safe tri-state health with crash-recovery hysteresis.

    `labels` (typically {"replica": "<i>"}) ride the transition counter
    so a fleet's per-replica health histories stay separable in one
    metrics page; the fleet-level aggregation over these monitors lives
    in serve/fleet.py (`ReplicaFleet.health_snapshot`)."""

    def __init__(self, ok_after: int = DEFAULT_OK_AFTER,
                 labels: Optional[dict] = None) -> None:
        self._lock = tracked_lock("serve.health")
        self.labels = dict(labels or {})
        self._state = OK
        self._reason = ""
        self._ok_after = max(1, ok_after)
        self._ok_streak = 0
        self._crashes = 0
        self._sticky = False  # degrade that clean batches must NOT clear
        # the crash-caused degrade is tracked SEPARATELY from the sticky
        # (drift) one: the two can layer, and clearing the sticky overlay
        # must leave the crash degrade (and its hysteresis) underneath
        self._crash_degraded = False
        self._crash_reason = ""

    @guarded_by("_lock")
    def _transition(self, state: str, reason: str) -> None:
        # caller holds the lock (declared + race-checked via @guarded_by)
        if self._state == state:
            self._reason = reason
            return
        self._state = state
        self._reason = reason
        from shifu_tpu.obs import registry

        registry().counter("serve.health.transitions", to=state,
                           **self.labels).inc()

    def note_crash(self, reason: str) -> None:
        with self._lock:
            self._crashes += 1
            self._ok_streak = 0
            self._crash_degraded = True
            self._crash_reason = reason
            if self._state != DRAINING:
                self._transition(DEGRADED, reason)

    def note_degraded(self, reason: str) -> None:
        """Degrade WITHOUT counting a crash and WITHOUT the clean-batch
        hysteresis clearing it (the drift path: scoring is healthy, the
        MODEL is stale — only an operator action like `shifu promote`
        resolves it, via clear_degraded)."""
        with self._lock:
            self._sticky = True
            if self._state != DRAINING:
                self._transition(DEGRADED, reason)

    def clear_degraded(self) -> None:
        """Drop a sticky (non-crash) degrade — called after a hot-swap
        promoted a fresh model set. A crash-caused degrade is NOT
        cleared: scoring itself was failing, and only the clean-batch
        hysteresis (note_ok) may lift it — a promote must not route full
        traffic back onto a still-crashing replica."""
        with self._lock:
            was_sticky, self._sticky = self._sticky, False
            self._ok_streak = 0
            if self._state != DEGRADED or not was_sticky:
                return
            if self._crash_degraded:
                # the crash degrade layered UNDER the drift one survives:
                # scoring was failing, and only clean batches heal that
                self._reason = self._crash_reason
                return
            self._transition(OK, "")

    def note_ok(self) -> None:
        with self._lock:
            if self._state != DEGRADED or self._sticky:
                return
            self._ok_streak += 1
            if self._ok_streak >= self._ok_after:
                self._crash_degraded = False
                self._crash_reason = ""
                self._transition(OK, "")

    def set_draining(self, reason: str) -> None:
        with self._lock:
            self._transition(DRAINING, reason)

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    @property
    def reason(self) -> str:
        with self._lock:
            return self._reason

    @property
    def crashes(self) -> int:
        with self._lock:
            return self._crashes

    def snapshot(self) -> dict:
        with self._lock:
            return {"status": self._state, "reason": self._reason,
                    "workerCrashes": self._crashes}
