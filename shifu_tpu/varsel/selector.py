"""Variable selection: metric filters + sensitivity analysis.

Parity: core/VariableSelector.java:110 (selectByFilter: KS / IV / MIX
alternating / PARETO front), VarSelectModelProcessor auto-filter
(missing-rate / min-KS / min-IV / correlation thresholds) and the SE/ST
sensitivity wrapper (core/varselect/VarSelectMapper.java:66: score each
record with one column knocked out, rank columns by error delta).

TPU-first SE: the reference caches partial forward results per column
(CacheBasicFloatNetwork); here the knockout scan is one `lax.map` over
columns — each step zeroes a column (mean after z-scale) and reuses the same
compiled forward. O(C) forwards, all on device.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Set, Tuple

import numpy as np

from shifu_tpu.config import ColumnConfig
from shifu_tpu.utils.log import get_logger

log = get_logger(__name__)


def _usable(cc: ColumnConfig) -> bool:
    return (
        cc.is_feature()
        and not cc.is_force_select()
        and cc.column_stats.ks is not None
        and cc.column_stats.iv is not None
    )


def pareto_front_order(points: List[Tuple[float, float]]) -> List[int]:
    """Indices ordered by successive pareto fronts (maximize both dims), the
    reference's sortByPareto (VariableSelector.java:393)."""
    remaining = list(range(len(points)))
    out: List[int] = []
    while remaining:
        front = []
        for i in remaining:
            dominated = any(
                points[j][0] >= points[i][0]
                and points[j][1] >= points[i][1]
                and (points[j][0] > points[i][0] or points[j][1] > points[i][1])
                for j in remaining
                if j != i
            )
            if not dominated:
                front.append(i)
        # within a front, order by ks desc
        front.sort(key=lambda i: -points[i][0])
        out.extend(front)
        remaining = [i for i in remaining if i not in set(front)]
    return out


def select_by_filter(
    columns: List[ColumnConfig],
    filter_by: str,
    filter_num: int,
    filter_enable: bool = True,
) -> List[str]:
    """Set final_select in place; returns selected column names.

    Force-selected columns always count toward filter_num
    (VariableSelector.java:139-149)."""
    for c in columns:
        if not c.is_force_select():
            c.final_select = False

    selected: List[str] = []
    for c in columns:
        if c.is_force_select():
            c.final_select = True
            selected.append(c.column_name)

    if not filter_enable:
        return selected

    cands = [c for c in columns if _usable(c)]
    key = (filter_by or "KS").upper()
    if key == "IV":
        order = sorted(cands, key=lambda c: -(c.column_stats.iv or 0.0))
    elif key == "PARETO":
        pts = [(c.column_stats.ks or 0.0, c.column_stats.iv or 0.0) for c in cands]
        order = [cands[i] for i in pareto_front_order(pts)]
    elif key == "MIX":
        ks_sorted = sorted(cands, key=lambda c: -(c.column_stats.ks or 0.0))
        iv_sorted = sorted(cands, key=lambda c: -(c.column_stats.iv or 0.0))
        order, seen = [], set()
        for a, b in zip(ks_sorted, iv_sorted):
            for c in (a, b):
                if id(c) not in seen:
                    seen.add(id(c))
                    order.append(c)
    else:  # KS default
        order = sorted(cands, key=lambda c: -(c.column_stats.ks or 0.0))

    budget = max(0, filter_num - len(selected))
    for c in order[:budget]:
        c.final_select = True
        selected.append(c.column_name)
    return selected


@dataclass
class AutoFilterResult:
    removed: Dict[str, str]  # column -> reason


def auto_filter(
    columns: List[ColumnConfig],
    missing_rate_threshold: float = 0.98,
    min_ks: float = 0.0,
    min_iv: float = 0.0,
    correlation: Optional[np.ndarray] = None,
    correlation_names: Optional[List[str]] = None,
    correlation_threshold: float = 1.0,
) -> AutoFilterResult:
    """Flag obviously-bad candidates ForceRemove (VarSelectModelProcessor
    autoFilter: missing rate / minKs / minIv; correlation drop keeps the
    higher-IV member of each over-threshold pair)."""
    from shifu_tpu.config.column_config import ColumnFlag

    removed: Dict[str, str] = {}
    for c in columns:
        if not c.is_feature() or c.is_force_select():
            continue
        st = c.column_stats
        if (st.missing_percentage or 0.0) > missing_rate_threshold:
            removed[c.column_name] = (
                f"missing rate {st.missing_percentage:.3f} > {missing_rate_threshold}"
            )
        elif min_ks > 0 and st.ks is not None and st.ks < min_ks:
            removed[c.column_name] = f"ks {st.ks:.3f} < {min_ks}"
        elif min_iv > 0 and st.iv is not None and st.iv < min_iv:
            removed[c.column_name] = f"iv {st.iv:.3f} < {min_iv}"

    if (
        correlation is not None
        and correlation_names
        and correlation_threshold < 1.0
    ):
        by_name = {c.column_name: c for c in columns}
        n = len(correlation_names)
        for i in range(n):
            for j in range(i + 1, n):
                if abs(correlation[i, j]) < correlation_threshold:
                    continue
                a = by_name.get(correlation_names[i])
                b = by_name.get(correlation_names[j])
                if a is None or b is None:
                    continue
                if a.column_name in removed or b.column_name in removed:
                    continue
                drop = a if (a.column_stats.iv or 0) <= (b.column_stats.iv or 0) else b
                keep = b if drop is a else a
                if not drop.is_force_select():
                    removed[drop.column_name] = (
                        f"|corr|={abs(correlation[i, j]):.3f} with "
                        f"{keep.column_name} >= {correlation_threshold}"
                    )

    for c in columns:
        if c.column_name in removed:
            c.column_flag = ColumnFlag.FORCE_REMOVE
            c.final_select = False
    return AutoFilterResult(removed=removed)


def sensitivity_scores(
    params,
    activations: List[str],
    feats: np.ndarray,
    tags: np.ndarray,
    se_type: str = "SE",
) -> np.ndarray:
    """Per-column sensitivity: error increase when the column is knocked out
    to its mean (0 after z-scale). SE = mean squared delta of scores; ST =
    delta of MSE against labels (VarSelectMapper ColumnStatistics semantics).
    Returns [C] float — higher = more important."""
    import jax
    import jax.numpy as jnp

    from shifu_tpu.models.nn import forward

    x = jnp.asarray(feats, jnp.float32)
    t = jnp.asarray(tags, jnp.float32)
    col_means = jnp.mean(x, axis=0)

    def fwd(inp):
        return forward(params, inp, activations)[:, 0]

    base = fwd(x)
    base_mse = jnp.mean((t - base) ** 2)

    def knockout(j):
        xj = x.at[:, j].set(col_means[j])
        pj = fwd(xj)
        if se_type.upper() == "ST":
            return jnp.mean((t - pj) ** 2) - base_mse
        return jnp.mean((base - pj) ** 2)

    scores = jax.lax.map(knockout, jnp.arange(x.shape[1]))
    return np.asarray(scores)
