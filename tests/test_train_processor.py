"""End-to-end `shifu train` on a synthetic model set (NN + LR paths),
mirroring ShifuCLITest.java:102-210's init->stats->norm->train drive."""

import json
import os

import numpy as np
import pytest

from tests.helpers import make_model_set


@pytest.fixture()
def trained_root(tmp_path):
    root = str(tmp_path / "ms")
    make_model_set(root, n_rows=500)
    from shifu_tpu.processor.init import InitProcessor
    from shifu_tpu.processor.norm import NormProcessor
    from shifu_tpu.processor.stats import StatsProcessor

    assert InitProcessor(root).run() == 0
    assert StatsProcessor(root).run() == 0
    assert NormProcessor(root).run() == 0
    return root


def _set_train(root, **kw):
    from shifu_tpu.config.model_config import ModelConfig

    mc = ModelConfig.load(os.path.join(root, "ModelConfig.json"))
    for k, v in kw.items():
        setattr(mc.train, k, v)
    mc.save(os.path.join(root, "ModelConfig.json"))
    return mc


def test_train_nn_end_to_end(trained_root):
    root = trained_root
    _set_train(root, num_train_epochs=40)
    from shifu_tpu.processor.train import TrainProcessor

    assert TrainProcessor(root).run() == 0
    model_path = os.path.join(root, "models", "model0.nn")
    assert os.path.isfile(model_path)

    from shifu_tpu.models.nn import IndependentNNModel, NNModelSpec
    from shifu_tpu.norm.dataset import load_normalized

    spec = NNModelSpec.load(model_path)
    assert spec.algorithm == "NN"
    assert spec.norm_specs  # embedded norm plan for independent scoring
    assert spec.valid_error is not None and spec.valid_error < 0.15

    _, feats, tags, _ = load_normalized(
        os.path.join(root, "tmp", "norm", "NormalizedData")
    )
    scores = IndependentNNModel(spec).compute(np.asarray(feats))
    # model separates the classes: mean score of pos >> neg
    pos = scores[np.asarray(tags) == 1].mean()
    neg = scores[np.asarray(tags) == 0].mean()
    assert pos - neg > 0.4

    # progress + val error artifacts (NNOutput parity)
    assert os.path.isfile(os.path.join(root, "tmp", "train", "progress_0.log"))
    assert os.path.isfile(os.path.join(root, "tmp", "train", "val_error_0.txt"))


def test_train_lr_and_bagging(trained_root):
    root = trained_root
    mc = _set_train(root, num_train_epochs=30, bagging_num=2)
    mc.train.algorithm = type(mc.train.algorithm).LR
    mc.train.params = {"LearningRate": 0.3, "Propagation": "ADAM"}
    mc.save(os.path.join(root, "ModelConfig.json"))

    from shifu_tpu.processor.train import TrainProcessor

    assert TrainProcessor(root).run() == 0
    assert os.path.isfile(os.path.join(root, "models", "model0.lr"))
    assert os.path.isfile(os.path.join(root, "models", "model1.lr"))

    from shifu_tpu.models.nn import NNModelSpec

    spec = NNModelSpec.load(os.path.join(root, "models", "model0.lr"))
    assert spec.layer_sizes[1] == 1  # no hidden layer
    assert spec.loss == "log"


def test_train_continuous_resume(trained_root):
    root = trained_root
    _set_train(root, num_train_epochs=15)
    from shifu_tpu.processor.train import TrainProcessor

    assert TrainProcessor(root).run() == 0
    first = os.path.getmtime(os.path.join(root, "models", "model0.nn"))
    _set_train(root, num_train_epochs=15, is_continuous=True)
    assert TrainProcessor(root).run() == 0
    assert os.path.getmtime(os.path.join(root, "models", "model0.nn")) >= first


def test_grid_search_vmapped(trained_root):
    """Grid trials sharing a program signature run as ONE vmapped group;
    best params are written back (gs/GridSearch.java:44)."""
    root = trained_root
    mc = _set_train(root, num_train_epochs=20)
    mc.train.params = {
        "NumHiddenNodes": [8],
        "ActivationFunc": ["tanh"],
        "LearningRate": [0.02, 0.1, 0.3, 0.5],  # list value -> grid
        "Propagation": "Q",
    }
    mc.save(os.path.join(root, "ModelConfig.json"))
    from shifu_tpu.processor.train import TrainProcessor

    assert TrainProcessor(root).run() == 0
    assert os.path.isfile(os.path.join(root, "models", "model0.nn"))
    from shifu_tpu.config.model_config import ModelConfig

    best = ModelConfig.load(os.path.join(root, "ModelConfig.json"))
    # ModelConfig on disk keeps the grid; the in-memory best was trained
    assert isinstance(mc.train.params["LearningRate"], list)


def test_k_fold_vmapped(trained_root):
    """k-fold: one vmapped program, one model per fold with holdout error
    (TrainModelProcessor.java:947-969)."""
    root = trained_root
    _set_train(root, num_train_epochs=20, num_k_fold=3)
    from shifu_tpu.processor.train import TrainProcessor

    assert TrainProcessor(root).run() == 0
    for i in range(3):
        assert os.path.isfile(os.path.join(root, "models", f"model{i}.nn"))
