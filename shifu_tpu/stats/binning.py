"""Bin-boundary construction.

The reference builds numeric bins with a streaming SPDT histogram sketch
(core/binning/EqualPopulationBinning.java:34) because data only streams
through Pig mappers; here full columns are resident, so boundaries come from
EXACT (weighted) quantiles — strictly more accurate than the sketch, same
contract: boundary[0] = -inf, bin i covers [b[i], b[i+1]).

Methods (stats.binningMethod, container/obj/ModelStatsConf.java):
  EqualPositive / EqualNegative / EqualTotal — equal count of pos/neg/all rows
  per bin (quantiles over the respective subset); Weight* variants use the
  weight column as the mass. EqualInterval — equal-width bins over [min, max].

Categorical bins: distinct values ordered by descending frequency, capped at
``cate_max_num_bin`` (rare tail merged into the last real bin); missing is
always the extra final bin slot of the count arrays.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

from shifu_tpu.config.model_config import BinningMethod

NEG_INF = float("-inf")


def weighted_quantile_boundaries(
    values: np.ndarray, weights: Optional[np.ndarray], max_bins: int
) -> List[float]:
    """Boundaries so each bin holds ~equal mass. values must be finite."""
    if values.size == 0:
        return [NEG_INF]
    order = np.argsort(values, kind="stable")
    v = values[order]
    if weights is None:
        cum = np.arange(1, v.size + 1, dtype=np.float64)
    else:
        cum = np.cumsum(weights[order])
    total = cum[-1]
    if total <= 0:
        return [NEG_INF]
    boundaries = [NEG_INF]
    for k in range(1, max_bins):
        target = total * k / max_bins
        idx = int(np.searchsorted(cum, target, side="left"))
        idx = min(idx, v.size - 1)
        b = float(v[idx])
        if b > boundaries[-1]:
            boundaries.append(b)
    return boundaries


def equal_interval_boundaries(values: np.ndarray, max_bins: int) -> List[float]:
    if values.size == 0:
        return [NEG_INF]
    lo, hi = float(values.min()), float(values.max())
    if hi <= lo:
        return [NEG_INF]
    step = (hi - lo) / max_bins
    boundaries = [NEG_INF]
    for k in range(1, max_bins):
        boundaries.append(lo + k * step)
    return boundaries


def numeric_boundaries(
    values: np.ndarray,
    tags: np.ndarray,
    weights: np.ndarray,
    method: BinningMethod,
    max_bins: int,
) -> List[float]:
    """values: float64 with NaN for missing; tags: {1,0,-1}; returns bin
    boundaries starting at -inf."""
    finite = np.isfinite(values)
    v = values[finite]
    t = tags[finite]
    w = weights[finite]
    if method == BinningMethod.EQUAL_INTERVAL:
        return equal_interval_boundaries(v, max_bins)
    if method in (BinningMethod.EQUAL_POSITIVE, BinningMethod.WEIGHT_EQUAL_POSITIVE):
        sel = t == 1
    elif method in (BinningMethod.EQUAL_NEGATIVE, BinningMethod.WEIGHT_EQUAL_NEGATIVE):
        sel = t == 0
    else:  # EqualTotal / WeightEqualTotal
        sel = t >= 0
    use_weights = method in (
        BinningMethod.WEIGHT_EQUAL_POSITIVE,
        BinningMethod.WEIGHT_EQUAL_NEGATIVE,
        BinningMethod.WEIGHT_EQUAL_TOTAL,
    )
    subset = v[sel]
    if subset.size == 0:  # degenerate: fall back to all rows
        subset, sel = v, np.ones(v.size, dtype=bool)
    return weighted_quantile_boundaries(
        subset, w[sel] if use_weights else None, max_bins
    )


def categorical_bins(
    raw: np.ndarray,
    missing_mask: np.ndarray,
    max_categories: int,
) -> List[str]:
    """Distinct non-missing values by descending frequency, capped."""
    import pandas as pd

    ser = pd.Series(raw[~missing_mask]).str.strip()
    counts = ser.value_counts()
    cats = [str(c) for c in counts.index.tolist()]
    if max_categories and len(cats) > max_categories:
        cats = cats[:max_categories]
    return cats


def numeric_bin_index(values: np.ndarray, boundaries: Sequence[float]) -> np.ndarray:
    """Vectorized BinUtils.getNumericalBinIndex (util/BinUtils.java:74):
    bin i when boundaries[i] <= v < boundaries[i+1]; NaN -> missing bin
    (= len(boundaries), the last slot)."""
    b = np.asarray(boundaries, dtype=np.float64)
    idx = np.searchsorted(b, values, side="right") - 1
    idx = np.clip(idx, 0, len(b) - 1)
    missing = ~np.isfinite(values)
    idx = np.where(missing, len(b), idx)
    return idx.astype(np.int32)


def categorical_bin_index(
    raw: np.ndarray, categories: Sequence[str], missing_mask: np.ndarray
) -> np.ndarray:
    """Value -> category position; unseen/missing -> missing bin
    (= len(categories))."""
    import pandas as pd

    lookup = {c: i for i, c in enumerate(categories)}
    ser = pd.Series(raw).str.strip()
    idx = np.array(
        ser.map(lookup).fillna(len(categories)).to_numpy(dtype=np.int64)
    )  # copy: pandas may hand back a read-only buffer
    idx[missing_mask] = len(categories)
    return idx.astype(np.int32)


def hybrid_bin_index(
    raw: np.ndarray,
    boundaries: Sequence[float],
    categories: Sequence[str],
    missing_mask: np.ndarray,
) -> np.ndarray:
    """Hybrid (H) column bin index — Normalizer.java:622-638: try the
    categorical lookup first (hit -> |numeric bins| + category index), else
    parse as a number (numeric bin; unparseable -> the trailing missing slot
    at |numeric bins| + |categories|)."""
    import pandas as pd

    nb = len(boundaries)
    miss_slot = nb + len(categories)
    lookup = {c: i for i, c in enumerate(categories)}
    ser = pd.Series(raw).str.strip()
    cat_idx = ser.map(lookup)
    vals = pd.to_numeric(ser, errors="coerce").to_numpy(dtype=np.float64)
    num_idx = numeric_bin_index(vals, boundaries)
    out = np.where(
        cat_idx.notna().to_numpy(),
        nb + cat_idx.fillna(0).to_numpy(dtype=np.int64),
        # non-finite parses ("Infinity") are missing too, like
        # ColumnarData.numeric does for pure-numeric columns
        np.where(~np.isfinite(vals), miss_slot, num_idx),
    ).astype(np.int32)
    out[np.asarray(missing_mask)] = miss_slot
    return out
