"""`shifu serve` front end: stdlib HTTP JSONL server + in-process Scorer.

Endpoints (http.server.ThreadingHTTPServer — no new dependencies):

  POST /score    body is either {"records": [{col: value, ...}, ...]} or
                 JSONL (one record object per line). Response:
                 {"scores": [{"mean","max","min","median","models"}...]}.
                 Shed requests get HTTP 429 + Retry-After — an explicit
                 rejection, never a hung connection.
  GET  /healthz  liveness + registry identity (model-set sha, mode).
  GET  /metrics  the existing Prometheus exporter (obs/metrics.py) over
                 the live serve counters/histograms/gauges.

Embedding: `Scorer.score_batch(records)` is the same admission → batcher
→ fused-program path without HTTP — the bench harness and tests drive it
directly.

Shutdown (`ScoringServer.shutdown()` / SIGINT in the CLI): admission
closes first (new requests shed with reason=closed), the batcher drains
every admitted request, the HTTP listener stops, and a run-ledger
manifest (`.shifu/runs/serve-<seq>.json`) lands with the full metrics
snapshot — the serving analog of the per-step manifests every lifecycle
step writes.
"""

from __future__ import annotations

import json
import math
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import List, Optional, Sequence

from shifu_tpu.analysis.racetrack import tracked_lock
from shifu_tpu.eval.scorer import ScoreResult
from shifu_tpu.serve.batcher import MicroBatcher
from shifu_tpu.serve.fleet import ReplicaFleet, ScoringReplica
from shifu_tpu.serve.health import DRAINING
from shifu_tpu.serve import wire
from shifu_tpu.serve.queue import AdmissionQueue, RejectedError
from shifu_tpu.serve.registry import ModelRegistry
from shifu_tpu.serve.zoo import ColdStartError
from shifu_tpu.utils.errors import ShifuError
from shifu_tpu.utils.log import get_logger

log = get_logger(__name__)

DEFAULT_SCORE_TIMEOUT_S = 30.0

# Content-Types parsed as JSON/JSONL. "" (no header) stays JSON so bare
# clients keep working, and x-www-form-urlencoded is curl -d's default —
# every pre-wire client POSTs with it. Anything outside this set and the
# columnar type is a 415, not a guess.
_JSON_CONTENT_TYPES = frozenset({
    "", "application/json", "text/json", "application/jsonl",
    "application/x-ndjson", "text/plain",
    "application/x-www-form-urlencoded",
})


class Scorer:
    """In-process scoring API over the replica fleet's router.

    Two construction modes:

      Scorer(registry, admission=...)  — the embedding path: the given
          registry (plain ModelRegistry or SwappableRegistry — anything
          with `score_raw` + `input_columns`) becomes a ONE-replica
          fleet around the given admission queue. Behaviorally the
          pre-fleet Scorer: `.batcher`/`.admission`/`.health` read the
          same objects they always did.
      Scorer(fleet=ReplicaFleet(...))  — the server path: requests
          route across N per-device replicas by observed drain rate.

    `observer(data, result)` rides each replica batcher's
    post-resolution hook (traffic logging, shadow scoring, drift
    checks — the continuous-loop seams)."""

    def __init__(self, registry: Optional[ModelRegistry] = None,
                 admission: Optional[AdmissionQueue] = None,
                 max_batch_rows: Optional[int] = None,
                 max_wait_ms: Optional[float] = None,
                 max_restarts: Optional[int] = None,
                 deadline_ms: Optional[float] = None,
                 observer=None, extra_columns=None,
                 batching: Optional[str] = None,
                 fleet: Optional[ReplicaFleet] = None) -> None:
        if fleet is None:
            if registry is None:
                raise ValueError("Scorer needs a registry or a fleet")
            if observer is None:
                wrapped = None
            else:
                # single-replica compat: callers pass (data, result)
                def wrapped(_rep, data, result):
                    observer(data, result)
            fleet = ReplicaFleet([ScoringReplica(
                registry, index=0, admission=admission,
                max_batch_rows=max_batch_rows, max_wait_ms=max_wait_ms,
                max_restarts=max_restarts, deadline_ms=deadline_ms,
                batching=batching, observer=wrapped)])
        self.fleet = fleet
        self.registry = fleet.replicas[0].registry
        # label plumbing: extra raw columns (target/weight) that ride
        # through conversion and batching untouched by scoring, so the
        # traffic log can keep outcomes and `shifu retrain` can train on
        # the log directly (absent fields log as the missing token)
        self.extra_columns = [c for c in (extra_columns or [])
                              if c not in fleet.input_columns]
        # fleet-level health (sticky drift degrades, shutdown); replica
        # monitors aggregate into health_snapshot()
        self.health = fleet.health

    # single-replica accessors (the embedding/test surface; in a fleet
    # they read replica 0 — per-replica state lives on fleet.replicas)
    @property
    def admission(self) -> AdmissionQueue:
        return self.fleet.replicas[0].admission

    @property
    def batcher(self) -> MicroBatcher:
        return self.fleet.replicas[0].batcher

    def health_snapshot(self) -> dict:
        """Aggregate fleet health (one degraded replica = degraded fleet
        with the replica named; all draining = draining)."""
        return self.fleet.health_snapshot()

    def retry_after_seconds(self) -> float:
        """Fleet-wide Retry-After (total backlog / summed drain rates)."""
        return self.fleet.retry_after_seconds()

    def score_batch(self, records: Sequence[dict],
                    timeout: Optional[float] = DEFAULT_SCORE_TIMEOUT_S,
                    trace=None) -> ScoreResult:
        """Score raw records; blocks until the micro-batch containing
        them completes. Raises RejectedError on shed (429 analog).

        Tracing: with an explicit `trace` (the HTTP path) the CALLER
        finishes it; without one, a trace is created per request when
        tracing or SLO accounting is armed, and finished here — so
        in-process embeddings (bench, tests) get the same per-stage
        evidence the HTTP front end gets."""
        from shifu_tpu.obs import reqtrace

        own = None
        if trace is None:
            buf = reqtrace.buffer()
            if buf.active or self.fleet.slo.enabled:
                own = trace = reqtrace.RequestTrace(
                    sampled=buf.head_sampled())
        try:
            return self.fleet.score_batch(records, timeout=timeout,
                                          extra_columns=self.extra_columns,
                                          trace=trace)
        except Exception as e:
            if own is not None:
                own.annotate(status=type(e).__name__)
            raise
        finally:
            if own is not None:
                self.fleet.finish_trace(own)

    def close(self, timeout: Optional[float] = 30.0) -> None:
        """Stop admitting and drain every in-flight request fleet-wide."""
        self.fleet.close(timeout)


def _result_rows(res: ScoreResult) -> List[dict]:
    return [
        {
            "mean": round(float(res.mean[i]), 4),
            "max": round(float(res.max[i]), 4),
            "min": round(float(res.min[i]), 4),
            "median": round(float(res.median[i]), 4),
            "models": [round(float(v), 4) for v in res.model_scores[i]],
        }
        for i in range(len(res.mean))
    ]


def _parse_records(body: bytes) -> List[dict]:
    """JSON document or JSONL lines -> list of record dicts."""
    text = body.decode("utf-8")
    try:
        doc = json.loads(text)
    except ValueError:
        # JSONL: one record object per line
        records = []
        for line in text.splitlines():
            line = line.strip()
            if line:
                records.append(json.loads(line))
        return _all_objects(records)
    if isinstance(doc, list):
        return _all_objects(doc)
    if isinstance(doc, dict) and isinstance(doc.get("records"), list):
        return _all_objects(doc["records"])
    if isinstance(doc, dict):
        return [doc]  # a single bare record object
    raise ValueError("body must be a JSON record, a list of records, "
                     'a {"records": [...]} document, or JSONL lines')


def _all_objects(records: List) -> List[dict]:
    """Every record must be a JSON object — anything else is a 400, not
    an AttributeError dropping the connection mid-handler."""
    for r in records:
        if not isinstance(r, dict):
            raise ValueError(
                f"records must be JSON objects, got {type(r).__name__}")
    return records


class ScoringServer:
    """Registry + Scorer + HTTP listener + shutdown manifest, in one."""

    def __init__(self, root: str = ".",
                 models_dir: Optional[str] = None,
                 host: str = "127.0.0.1", port: int = 0,
                 queue_depth: Optional[int] = None,
                 max_batch_rows: Optional[int] = None,
                 max_wait_ms: Optional[float] = None,
                 replicas: Optional[int] = None,
                 batching: Optional[str] = None,
                 column_configs=None, model_config=None,
                 zoo: Optional[dict] = None) -> None:
        from shifu_tpu.loop import drift_check_batches_setting, \
            log_sample_setting
        from shifu_tpu.loop.drift import DriftMonitor
        from shifu_tpu.loop.traffic import TrafficLog, traffic_columns

        self.root = os.path.abspath(root)
        self._observe_lock = tracked_lock("serve.server.observe")
        self._observed_batches = 0
        self._last_drift_verdict: Optional[dict] = None
        self.zoo = None
        if zoo:
            # multi-tenant mode (serve/zoo.py): N model sets behind this
            # one server on a bounded HBM budget. Per-tenant drift
            # windows / traffic streams / shadow gates live in the zoo;
            # the DEFAULT (first-registered) tenant doubles as this
            # server's registry facade so the single-tenant surfaces
            # (/healthz identity, peers, manifests) keep working.
            from shifu_tpu.serve.zoo import ModelZoo

            self.zoo = ModelZoo(
                self.root, n_replicas=replicas,
                queue_depth=queue_depth,
                max_batch_rows=max_batch_rows,
                max_wait_ms=max_wait_ms, batching=batching)
            for name, set_path in zoo.items():
                self.zoo.register(name, set_path)
            default = self.zoo.default_tenant
            # the default tenant MUST fit (the server needs one resident
            # fleet); later tenants admit best-effort in registration
            # order and stay cold past the budget
            self.zoo.ensure_resident(default)
            for name in list(zoo):
                if name == default:
                    continue
                try:
                    # evict=False: pre-warming tenant N must not evict
                    # the tenants just admitted — only scored demand
                    # earns an eviction
                    self.zoo.ensure_resident(name, evict=False)
                except Exception as e:  # best-effort warm-up: past-
                    # budget tenants legitimately stay cold at startup
                    log.info("zoo: tenant %s stays cold at startup "
                             "(%s)", name, e)
            tenant = self.zoo._get(default)
            self.column_configs = tenant.column_configs
            self.model_config = tenant.model_config
            self.drift = None       # per-tenant, owned by the zoo
            self.traffic = None     # per-tenant streams, ditto
            self._registry = self.zoo.fleet_of(default)
            self._scorer = tenant.scorer
            self._drift_check_every = max(
                1, drift_check_batches_setting())
            self._finish_init(host, port)
            return
        # the loop seams read the model-set configs when the server runs
        # inside one (the CLI path); an explicit models_dir outside a
        # model set still serves, just without drift/label plumbing
        if column_configs is None or model_config is None:
            ccs, mc = self._load_configs()
            column_configs = column_configs or ccs
            model_config = model_config or mc
        self.column_configs = column_configs
        self.model_config = model_config
        self.drift = (DriftMonitor(column_configs)
                      if column_configs else None)
        if self.drift is not None and not self.drift.enabled:
            self.drift = None
        # the fleet: one SwappableRegistry + queue + batcher per device
        # (replicas=None reads -Dshifu.serve.replicas; default = all
        # local devices; 1 is the exact pre-fleet behavior). It is also
        # the registry facade this server reads (sha/model_names/warm/
        # stage/promote) — replica 0 is the canonical read.
        self._registry = ReplicaFleet.build(
            models_dir or os.path.join(self.root, "models"),
            n_replicas=replicas,
            column_configs=column_configs, model_config=model_config,
            drift=self.drift, queue_depth=queue_depth,
            max_batch_rows=max_batch_rows, max_wait_ms=max_wait_ms,
            batching=batching, observer=self._observe)
        input_columns = self.registry.input_columns
        # outcome columns (target/weight) ride the request conversion as
        # extra raw columns so label-joined traffic is retrainable
        # straight from the log
        label_cols = []
        if model_config is not None:
            for extra_col in (
                    model_config.data_set.target_column_name,
                    model_config.data_set.weight_column_name):
                if (extra_col and extra_col not in label_cols
                        and extra_col not in input_columns):
                    label_cols.append(extra_col)
        self.traffic: Optional[TrafficLog] = None
        if log_sample_setting() > 0.0:
            self.traffic = TrafficLog(self.root, traffic_columns(
                list(input_columns) + label_cols))
        self._drift_check_every = max(1, drift_check_batches_setting())
        self._scorer = Scorer(fleet=self.registry,
                              extra_columns=label_cols)
        self._finish_init(host, port)

    @property
    def registry(self):
        """The default serving fleet. In zoo mode the DEFAULT tenant's
        fleet is re-resolved on every read: budget pressure may have
        evicted and re-admitted the tenant since startup, and a stale
        reference to its torn-down fleet would misreport /admin/shadow,
        peer health and manifests (falls back to the last-known fleet
        while the tenant is cold)."""
        if self.zoo is not None:
            from shifu_tpu.serve import zoo as zoo_mod

            tenant = self.zoo._get(self.zoo.default_tenant)
            if (tenant.state == zoo_mod.RESIDENT
                    and tenant.fleet is not None):
                self._registry = tenant.fleet
                self._scorer = tenant.scorer
        return self._registry

    @property
    def scorer(self):
        """The default Scorer (re-resolved like `registry`)."""
        if self.zoo is not None:
            self.registry  # refresh both references
        return self._scorer

    def _finish_init(self, host: str, port: int) -> None:
        """Shared tail of construction: HTTP listener + heartbeat lease
        (built AFTER the listener so the advertised port is the bound
        one)."""
        self.started_at = time.time()
        self._serve_thread: Optional[threading.Thread] = None
        self._shutdown_lock = tracked_lock("serve.server.shutdown")
        self._shutdown_started = False
        self._shutdown_done = threading.Event()
        self.httpd = ThreadingHTTPServer((host, port),
                                         self._handler_class())
        self.httpd.daemon_threads = True
        # process heartbeat lease + peer view + fleet-promotion-round
        # participant (serve/peers.py): N serve processes on one model
        # set observe each other through `.shifu/runs/peers/`, and a
        # fleet-atomic `shifu promote` drives stage/promote/unstage on
        # every live process through these hooks. Built AFTER the HTTP
        # listener so the advertised port is the bound one; disabled by
        # -Dshifu.lease.ttlMs=0.
        from shifu_tpu.serve.peers import PeerRegistry

        self.peers = PeerRegistry(
            self.root,
            stage_cb=self.stage_candidate,
            promote_cb=self.promote_candidate,
            unstage_cb=self._unstage_default,
            info_cb=self._peer_info)
        # on-disk metrics time-series (obs/timeseries.py): the process
        # name is the lease id, so the fleet collector joins these dirs
        # against the peer scan — a SIGKILLed process leaves its last
        # windows (final counters) behind for survivors' /fleet views.
        # Armed by -Dshifu.obs.snapshotMs; a lease-less server (ttlMs=0)
        # still snapshots under a synthetic solo id.
        import socket

        from shifu_tpu.obs import registry as obs_registry
        from shifu_tpu.obs.timeseries import MetricsSnapshotter

        self.lease_id = (self.peers.lease.lease_id if self.peers.enabled
                         else f"{socket.gethostname()}-{os.getpid()}-solo")
        # fleet-shared traffic log: adopt the lease id as this process's
        # writer id so N replicas append to ONE ledger dir without ever
        # contending for a chunk sequence number; `shifu retrain
        # --from-traffic` reads the union across writers
        if self.traffic is not None:
            self.traffic.set_writer(self.lease_id)
        if self.zoo is not None:
            self.zoo.writer = self.lease_id
            for name in self.zoo.tenants():
                t = self.zoo._get(name)
                if t.traffic is not None:
                    t.traffic.set_writer(self.lease_id)
        self.obs_snap = MetricsSnapshotter(self.root, self.lease_id,
                                           registry_cb=obs_registry)
        self.obs_snap.start()

    def _unstage_default(self) -> None:
        """Aborted-round rollback: in zoo mode route through the ZOO so
        the ledger's shadow charge and the tenant's shadow_staged flag
        roll back with the device state (a bare fleet.unstage would
        leave the charge inflated and the tenant unevictable forever);
        single-tenant goes straight to the fleet — through the property,
        not a bound method, since the default fleet can be replaced by
        an evict/re-admit cycle."""
        if self.zoo is not None:
            self.zoo.unstage(self.zoo.default_tenant)
        else:
            self.registry.unstage()

    def _peer_info(self) -> dict:
        """The health summary renewed into this process's lease file —
        a peer scan is a cheap fleet-of-processes health view."""
        info = {
            "port": self.port,
            "status": (self.zoo.fleet_health_snapshot()["status"]
                       if self.zoo is not None
                       else self.scorer.health.state),
            "sha": self.registry.sha,
            "replicas": len(self.registry.replicas),
            "queueDepth": sum(len(r.admission)
                              for r in self.registry.replicas),
        }
        if self.traffic is not None and self.traffic.writer:
            # which traffic-log chunks are this process's — the peer
            # scan ties a lease to its slice of the fleet-shared log
            info["trafficWriter"] = self.traffic.writer
        return info

    # ---- continuous-loop seams ----
    def _load_configs(self):
        """Best-effort model-set configs from the serving root — the
        drift baseline (ColumnConfig bins + counts) and the traffic log's
        label columns come from here. Absent/corrupt configs degrade to
        plain serving, never to a failed startup. ONE loader for the
        single-tenant and zoo paths (serve/zoo.py owns it)."""
        from shifu_tpu.serve.zoo import load_set_configs

        return load_set_configs(self.root)

    def _observe(self, replica, data, result) -> None:
        """Per-replica post-resolution observer: traffic log + shadow
        scoring + cadenced drift verdict. Runs on THAT replica's worker
        thread AFTER every request in the batch is answered; the traffic
        log and drift window stay fleet-global (one log, one monitor)."""
        if self.traffic is not None:
            # the REPLICA's scored_sha, not the fleet sha: mid-roll, each
            # replica may serve a different version, and a promote
            # between the score and this observe must not re-attribute
            # the batch's logged rows (the drift recommendation below
            # DOES want the current active sha — it targets the set
            # being served)
            self.traffic.record(
                data, result,
                getattr(replica.registry, "scored_sha",
                        replica.registry.sha))
        replica.registry.observe(data, result)
        with self._observe_lock:
            self._observed_batches += 1
            check = (self.drift is not None
                     and self._observed_batches
                     % self._drift_check_every == 0)
        if check:
            # check_degrade returns the verdict it computed — one window
            # flush + PSI pass per cadence, not two; OUTSIDE the cadence
            # lock (it forces a d2h window flush, SH203)
            self._last_drift_verdict = self.drift.check_degrade(
                self.scorer.health, self.root,
                model_sha=self.registry.sha,
                reporter=getattr(self, "lease_id", ""))

    def stage_candidate(self, models_dir: str,
                        set_name: Optional[str] = None) -> dict:
        """Load + warm a candidate model set as the shadow version on
        EVERY replica (each onto its own device). In zoo mode the stage
        is per-tenant and STREAMED through the budget ledger
        (`set_name`; default tenant when omitted)."""
        if self.zoo is not None:
            return self.zoo.stage(set_name or self.zoo.default_tenant,
                                  models_dir)
        return self.registry.stage(models_dir,
                                   column_configs=self.column_configs,
                                   model_config=self.model_config,
                                   drift=self.drift)

    def promote_candidate(self, expected_sha: Optional[str] = None,
                          set_name: Optional[str] = None) -> dict:
        """ROLLING hot-swap: the fleet promotes one replica at a time
        (requests keep flowing on the others), and each replica step
        stamps a sha-bound `swap-<seq>.json` audit manifest — from/to
        shas plus that replica's own shadow evidence, so a rollout is
        reconstructible per replica from the ledger alone. Afterwards a
        sticky drift degrade clears — the recommendation was acted on —
        and the drift monitor resets so drift on the NEW version's
        traffic re-degrades and re-recommends instead of being swallowed
        by the old run's already-seen columns. `expected_sha` (from the
        gate evidence) must match the staged shadow on every replica, or
        the roll is refused before the first swap."""
        if self.zoo is not None:
            # per-tenant promote: the zoo also releases the old active
            # version's ledger charge and renames the shadow's
            return self.zoo.promote(
                set_name or self.zoo.default_tenant, expected_sha,
                step_cb=self._write_swap_manifest)
        swap = self.registry.promote(expected_sha,
                                     step_cb=self._write_swap_manifest)
        self.scorer.health.clear_degraded()
        if self.drift is not None:
            self.drift.reset()
        self._last_drift_verdict = None
        return swap

    def _fleet_for(self, set_name: Optional[str] = None):
        """The fleet that owns a request's trace/Retry-After surfaces:
        the named tenant's when resident, else the default registry (a
        shed cold-tenant request still gets a coherent answer)."""
        if self.zoo is None or not set_name:
            return self.registry
        try:
            return self.zoo.fleet_of(set_name)
        except (KeyError, ValueError):
            return self.registry

    def _write_swap_manifest(self, replica, step: dict) -> None:
        """One sha-bound audit manifest per replica promote step."""
        import sys

        from shifu_tpu import obs
        from shifu_tpu.obs.ledger import RunLedger

        ledger = RunLedger(self.root)
        seq = ledger.next_seq("swap")
        path = ledger.write(
            "swap", seq,
            status="ok", exit_status=0,
            started_at=time.time(), elapsed_seconds=0.0,
            argv=list(sys.argv), registry=obs.registry(),
            extra={"swap": dict(step,
                                fleetReplicas=len(self.registry.replicas))},
        )
        log.info("promote step (replica %s) manifest -> %s",
                 replica.name, path)

    # ---- HTTP ----
    @property
    def host(self) -> str:
        return self.httpd.server_address[0]

    @property
    def port(self) -> int:
        return self.httpd.server_address[1]

    def _handler_class(self):
        server = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):  # route to our logger
                log.debug("http: " + fmt, *args)

            def _reply(self, code: int, payload, content_type: str
                       = "application/json", extra_headers=None) -> None:
                body = (payload if isinstance(payload, bytes)
                        else json.dumps(payload).encode("utf-8"))
                self.send_response(code)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(body)))
                for k, v in (extra_headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                from shifu_tpu.obs import registry as obs_registry

                if self.path == "/healthz":
                    # aggregate fleet health: one degraded replica =
                    # degraded fleet with the replica named in `reason`
                    # and the per-replica states under `replicas`; ALL
                    # replicas draining (or fleet shutdown) = draining.
                    # Zoo mode aggregates over RESIDENT tenants instead
                    # — an evicted tenant's torn-down fleet must not
                    # 503 the whole process
                    if server.zoo is not None:
                        health = server.zoo.fleet_health_snapshot()
                    else:
                        health = server.scorer.health_snapshot()
                    # draining replies 503 so load balancers stop routing
                    # here; ok AND degraded stay 200 (degraded still
                    # scores — it is a de-prioritization hint, not an
                    # ejection)
                    code = 503 if health["status"] == DRAINING else 200
                    health.update({
                        "models": len(server.registry.model_names),
                        "sha": server.registry.sha,
                        "fused": server.registry.fused,
                        "replicaCount": len(server.registry.replicas),
                        "queueDepth": sum(
                            len(r.admission)
                            for r in server.registry.replicas),
                        "workerRestarts": sum(
                            r.batcher.restarts
                            for r in server.registry.replicas),
                        "uptimeSeconds": round(
                            time.time() - server.started_at, 1),
                    })
                    # drift summary from the CACHED cadence verdict — a
                    # health probe must never force a device sync
                    if server._last_drift_verdict is not None:
                        v = server._last_drift_verdict
                        health["drift"] = {
                            "status": v["status"],
                            "maxPsi": round(v["maxPsi"], 6),
                            "driftedColumns": v["driftedColumns"],
                            "threshold": v["threshold"],
                        }
                    # SLO burn rate rides /healthz: burning the error
                    # budget faster than sustainable is a degrade
                    # REASON (computed, not sticky — it clears the
                    # moment the window recovers)
                    slo = server.registry.slo
                    if slo.enabled:
                        snap = slo.snapshot()
                        health["slo"] = snap
                        if snap["burning"] and health["status"] == "ok":
                            health["status"] = "degraded"
                            health["reason"] = (
                                f"SLO burn rate {snap['burnRate']:.2f} "
                                f"(>{slo.slo_ms:g}ms beyond the "
                                f"{slo.target:g} objective)")
                    # fleet-of-processes view: every peer lease (live +
                    # expired with ages and last-renewed health info).
                    # An EXPIRED peer is a computed degrade reason —
                    # this process keeps serving, but the balancer and
                    # the operator see the process fleet lost a member
                    # (it clears if the peer's lease is swept or it
                    # comes back)
                    if server.peers.enabled:
                        health["peers"] = server.peers.snapshot()
                        expired = server.peers.expired_peers()
                        if expired and health["status"] == "ok":
                            health["status"] = "degraded"
                            health["reason"] = (
                                "peer lease(s) expired: "
                                + ", ".join(expired))
                    if server.zoo is not None:
                        # the zoo section: budget occupancy + per-tenant
                        # states, with an in-flight admission surfaced
                        # as a NON-STICKY cold_start degrade reason (it
                        # clears the moment the tenant lands resident)
                        z = server.zoo.health_snapshot()
                        health["zoo"] = z
                        if z["admitting"] and health["status"] == "ok":
                            health["status"] = "degraded"
                            health["reason"] = (
                                "cold_start: warming tenant(s) "
                                + ", ".join(z["admitting"]))
                    self._reply(code, health)
                    return
                if self.path == "/admin/traces":
                    from shifu_tpu.obs import reqtrace

                    buf = reqtrace.buffer()
                    self._reply(200, {
                        **buf.snapshot(),
                        "traces": buf.traces(),
                    })
                    return
                if self.path == "/metrics":
                    self._reply(
                        200,
                        obs_registry().to_prometheus().encode("utf-8"),
                        content_type="text/plain; version=0.0.4")
                    return
                if self.path == "/admin/metrics.json":
                    # the LOSSLESS snapshot (exact histogram state, not
                    # the rendered Prometheus text) — what a peer's
                    # fleet collector scrapes to merge bucket-exact
                    from shifu_tpu.obs import fleetview

                    self._reply(200, {
                        "schema": fleetview.METRICS_JSON_SCHEMA,
                        "leaseId": server.lease_id,
                        "pid": os.getpid(),
                        "ts": time.time(),
                        "metrics": obs_registry().snapshot(),
                    })
                    return
                if self.path in ("/fleet/metrics", "/fleet/healthz"):
                    # ANY peer answers for the fleet: scan leases, merge
                    # live peers' scraped snapshots + expired peers'
                    # final on-disk windows (obs/fleetview.py). Folded
                    # in sorted-leaseId order, so every process reports
                    # bit-identical merged counter totals.
                    from shifu_tpu.obs import fleetview

                    reg, payload = fleetview.fleet_view(
                        server.root, self_id=server.lease_id,
                        self_snapshot=lambda: obs_registry().snapshot())
                    if self.path == "/fleet/metrics":
                        self._reply(
                            200, reg.to_prometheus().encode("utf-8"),
                            content_type="text/plain; version=0.0.4")
                    else:
                        self._reply(200, payload)
                    return
                if (self.path == "/admin/shadow"
                        or self.path.startswith("/admin/shadow?")):
                    from urllib.parse import parse_qs, urlsplit

                    q = parse_qs(urlsplit(self.path).query)
                    set_name = (q.get("set") or [None])[0]
                    if set_name and server.zoo is None:
                        # match the POST plane: silently answering the
                        # single shadow for ?set= would let promote
                        # --set gate on the WRONG tenant's evidence
                        self._reply(409, {"error": "this server is "
                                                   "single-tenant "
                                                   "(no --zoo)"})
                        return
                    if set_name:
                        try:
                            fleet = server.zoo.fleet_of(set_name)
                        except (KeyError, ValueError) as e:
                            self._reply(404, {"error": str(e)})
                            return
                    else:
                        fleet = server.registry
                    self._reply(200, {
                        "active": fleet.sha,
                        "shadow": fleet.shadow_snapshot(),
                    })
                    return
                self._reply(404, {"error": f"unknown path {self.path}"})

            def do_POST(self):
                from shifu_tpu.obs import reqtrace

                if self.path in ("/admin/stage", "/admin/promote",
                                 "/admin/evict"):
                    self._do_admin()
                    return
                if self.path.startswith("/admin/coresident/"):
                    self._do_coresident()
                    return
                # /score (single-tenant, or the zoo's default set) and
                # /score/<set> (one tenant of the model zoo)
                set_name = None
                if self.path.startswith("/score/"):
                    set_name = self.path[len("/score/"):]
                elif self.path != "/score":
                    self._reply(404, {"error": f"unknown path {self.path}"})
                    return
                if server.zoo is not None:
                    set_name = set_name or server.zoo.default_tenant
                    if set_name not in server.zoo.tenants():
                        self._reply(404, {
                            "error": f"unknown model set {set_name!r}",
                            "sets": server.zoo.tenants()})
                        return
                elif set_name is not None:
                    self._reply(404, {
                        "error": "this server is single-tenant — "
                                 "POST /score (start with --zoo for "
                                 "per-set routes)"})
                    return
                # wire-format negotiation: the columnar binary protocol
                # (serve/wire.py) rides its own Content-Type; the JSON
                # family stays the default. Malformed bodies of either
                # kind are a 400 and unknown types a 415 — always a JSON
                # error body, never a 500 or a hung worker.
                ctype = (self.headers.get("Content-Type") or "")
                ctype = ctype.split(";", 1)[0].strip().lower()
                try:
                    length = int(self.headers.get("Content-Length", "0"))
                except ValueError:
                    self._reply(400, {"error": "bad Content-Length"})
                    return
                from shifu_tpu.obs import registry as obs_registry

                if ctype == wire.CONTENT_TYPE:
                    wire_fmt = "binary"
                    limit = wire.max_body_bytes()
                    if length > limit:
                        self._reply(400, {
                            "error": f"columnar body of {length} bytes "
                                     f"exceeds shifu.serve.wire.maxBodyMB "
                                     f"({limit} bytes)"})
                        return
                    body = self.rfile.read(length)
                    try:
                        records = wire.decode(body)
                    except wire.WireFormatError as e:
                        self._reply(400, {
                            "error": f"bad columnar body: {e}"})
                        return
                    n_rows = records.n_rows
                elif ctype in _JSON_CONTENT_TYPES:
                    wire_fmt = "json"
                    body = self.rfile.read(length)
                    try:
                        records = _parse_records(body)
                    except ValueError as e:
                        self._reply(400, {
                            "error": f"bad request body: {e}"})
                        return
                    n_rows = len(records)
                else:
                    self._reply(415, {
                        "error": f"unsupported Content-Type {ctype!r}",
                        "accepts": sorted(
                            t for t in _JSON_CONTENT_TYPES if t
                        ) + [wire.CONTENT_TYPE]})
                    return
                if not n_rows:
                    self._reply(400, {"error": "no records in body"})
                    return
                # the wire-format mix, by payload bytes — one counter
                # next to the format-labeled serve.requests split
                obs_registry().counter("serve.wire.bytes",
                                       format=wire_fmt).inc(len(body))
                # trace id contract: an inbound X-Shifu-Trace header is
                # honored (and FORCES retention — the caller asked for
                # this trace), otherwise one is generated under the
                # head-sampling/slow-capture policy; the id is echoed in
                # the response header either way
                hdr = reqtrace.clean_trace_id(
                    self.headers.get(reqtrace.TRACE_HEADER))
                buf = reqtrace.buffer()
                trace = None
                if (hdr or buf.active
                        or server.registry.slo.enabled):
                    trace = reqtrace.RequestTrace(
                        trace_id=hdr,
                        sampled=bool(hdr) or buf.head_sampled())
                fleet = server._fleet_for(set_name)
                try:
                    if server.zoo is not None:
                        res = server.zoo.score_batch(set_name, records,
                                                     trace=trace)
                    else:
                        res = server.scorer.score_batch(records,
                                                        trace=trace)
                except ColdStartError as e:
                    # cold-tenant compile stall: 429 NOW with a
                    # Retry-After from OBSERVED warm-up time — the
                    # admission queue never blocks behind the build
                    # (which proceeds in the background)
                    err_headers = {}
                    if trace is not None:
                        trace.annotate(status="cold_start",
                                       tenant=set_name)
                        # the cold tenant has NO fleet: offer the trace
                        # to the ring directly instead of feeding
                        # another tenant's stage histograms/SLO under
                        # the wrong tenant= label (the PR-13 "never
                        # fabricate a wrong series" rule)
                        trace.finish()
                        reqtrace.buffer().offer(trace)
                        err_headers[reqtrace.TRACE_HEADER] = trace.trace_id
                    err_headers["Retry-After"] = str(
                        int(math.ceil(e.retry_after_s)))
                    self._reply(429, {
                        "error": str(e), "reason": e.reason,
                        "set": set_name,
                        "retryAfterSeconds": round(e.retry_after_s, 3)},
                        extra_headers=err_headers)
                    return
                except RejectedError as e:
                    # the trace header echoes on ERROR replies too —
                    # correlating a shed/timeout with its server-side
                    # trace is exactly when the caller needs the link
                    err_headers = {}
                    if trace is not None:
                        trace.annotate(status="rejected", reason=e.reason)
                        fleet.finish_trace(trace)
                        err_headers[reqtrace.TRACE_HEADER] = trace.trace_id
                    # Retry-After from the FLEET drain rate (total
                    # backlog / summed per-replica drain rates, clamped)
                    # — the hint describes the fleet's capacity to
                    # absorb the retry, not one replica's. Per-tenant in
                    # a zoo: the tenant's own fleet answers.
                    hint = fleet.retry_after_seconds()
                    err_headers["Retry-After"] = str(int(math.ceil(hint)))
                    self._reply(429, {"error": str(e),
                                      "reason": e.reason,
                                      "retryAfterSeconds": round(hint, 3)},
                                extra_headers=err_headers)
                    return
                except TimeoutError as e:
                    err_headers = {}
                    if trace is not None:
                        trace.annotate(status="timeout")
                        fleet.finish_trace(trace)
                        err_headers[reqtrace.TRACE_HEADER] = trace.trace_id
                    self._reply(503, {"error": str(e)},
                                extra_headers=err_headers)
                    return
                # the tenant that actually scored (zoo) names its models
                fleet = server._fleet_for(set_name)
                doc = {"models": fleet.model_names,
                       "scores": None}
                if trace is None:
                    doc["scores"] = _result_rows(res)
                    self._reply(200, doc)
                    return
                # serialize is a measured stage: the response-row build
                # + JSON encode is host work the client waits on
                with trace.stage("serialize"):
                    doc["scores"] = _result_rows(res)
                    doc["trace"] = trace.trace_id
                    body = json.dumps(doc).encode("utf-8")
                fleet.finish_trace(trace)
                self._reply(200, body, extra_headers={
                    reqtrace.TRACE_HEADER: trace.trace_id})

            def _do_admin(self):
                """Rollout control plane: stage a candidate as the shadow
                version, promote the staged one (zero-downtime swap), or
                — zoo mode — evict a resident tenant. `shifu promote`
                drives stage/promote; `set` selects the tenant (zoo
                default when omitted)."""
                try:
                    length = int(self.headers.get("Content-Length", "0"))
                    body = self.rfile.read(length) if length else b"{}"
                    doc = json.loads(body.decode("utf-8") or "{}")
                except ValueError as e:
                    self._reply(400, {"error": f"bad request body: {e}"})
                    return
                set_name = doc.get("set") or None
                if set_name is not None and server.zoo is None:
                    self._reply(409, {"error": "this server is single-"
                                               "tenant (no --zoo)"})
                    return
                try:
                    if self.path == "/admin/evict":
                        if server.zoo is None:
                            self._reply(409, {"error": "eviction needs "
                                                       "zoo mode"})
                            return
                        if not set_name:
                            self._reply(400, {"error": "set required"})
                            return
                        server.zoo.evict(set_name, reason="admin")
                        self._reply(200, {
                            "evicted": set_name,
                            "zoo": server.zoo.health_snapshot()})
                        return
                    if self.path == "/admin/stage":
                        models_dir = doc.get("modelsDir")
                        if not models_dir:
                            self._reply(400,
                                        {"error": "modelsDir required"})
                            return
                        self._reply(200, {
                            "staged": server.stage_candidate(
                                models_dir, set_name=set_name)})
                        return
                    self._reply(200, server.promote_candidate(
                        doc.get("sha"), set_name=set_name))
                except KeyError as e:
                    self._reply(404, {"error": str(e)})
                except (ValueError, OSError) as e:
                    self._reply(409, {"error": str(e)})

            def _do_coresident(self):
                """The co-resident trainer's grant plane (HttpGrant,
                coresident/tenant.py): admit / charge / heartbeat /
                release against the zoo's HBM ledger as a
                `priority=background` tenant. A charge that does not
                fit answers 409 with the byte deficit — the trainer
                backs off; it NEVER evicts a serving tenant."""
                from shifu_tpu.serve.zoo import LedgerFullError

                if server.zoo is None:
                    self._reply(409, {"error": "co-resident training "
                                               "needs zoo mode"})
                    return
                action = self.path[len("/admin/coresident/"):]
                try:
                    length = int(self.headers.get("Content-Length", "0"))
                    body = self.rfile.read(length) if length else b"{}"
                    doc = json.loads(body.decode("utf-8") or "{}")
                except ValueError as e:
                    self._reply(400, {"error": f"bad request body: {e}"})
                    return
                tenant = doc.get("tenant") or ""
                try:
                    if action == "admit":
                        self._reply(200, server.zoo.admit_background(
                            tenant, meta=doc.get("meta") or {}))
                    elif action == "charge":
                        nbytes = int(doc.get("bytes", 0))
                        if nbytes >= 0:
                            server.zoo.background_acquire(tenant, nbytes)
                        else:
                            server.zoo.background_reduce(tenant, -nbytes)
                        self._reply(200, {"charged": nbytes})
                    elif action == "heartbeat":
                        evicted = server.zoo.background_heartbeat(
                            tenant, int(doc.get("epoch", -1)))
                        self._reply(200, {"evicted": evicted})
                    elif action == "release":
                        server.zoo.background_release(
                            tenant, final=bool(doc.get("final")))
                        self._reply(200, {"released": tenant})
                    else:
                        self._reply(404, {
                            "error": f"unknown coresident action "
                                     f"{action!r}"})
                except LedgerFullError as e:
                    self._reply(409, {"error": str(e),
                                      "deficit": e.deficit})
                except KeyError as e:
                    self._reply(404, {"error": str(e)})
                except (ValueError, ShifuError) as e:
                    self._reply(409, {"error": str(e)})

        return Handler

    # ---- lifecycle ----
    def start(self) -> "ScoringServer":
        self._serve_thread = threading.Thread(
            target=self.httpd.serve_forever, name="shifu-serve-http",
            daemon=True)
        self._serve_thread.start()
        log.info("shifu serve listening on %s:%d (%d models, sha %s)",
                 self.host, self.port, len(self.registry.model_names),
                 self.registry.sha)
        return self

    def serve_forever(self) -> None:
        """Foreground serving (the CLI path); returns after shutdown()."""
        self.start()
        # the foreground park IS the contract: shutdown() sets the event
        # in its finally on every path, including a mid-drain crash
        self._shutdown_done.wait()  # shifu: noqa[SH204] park by design

    def shutdown(self, drain_timeout: float = 30.0) -> Optional[str]:
        """Reject-new -> drain in-flight -> stop HTTP -> write manifest.
        Returns the manifest path (None for every caller but the first —
        the started-flag swap is atomic, so a double SIGINT during a long
        drain cannot run shutdown twice or write duplicate manifests)."""
        with self._shutdown_lock:
            if self._shutdown_started:
                return None
            self._shutdown_started = True
        try:
            # release the heartbeat lease FIRST: a draining process must
            # leave the fleet cleanly (file removed), not expire into a
            # survivor's degrade reason
            self.peers.close()
            if self.zoo is not None:
                # drains EVERY resident tenant (incl. the default fleet
                # the scorer wraps) and flushes per-tenant traffic
                self.zoo.close(drain_timeout)
            else:
                self.scorer.close(drain_timeout)
            self.httpd.shutdown()
            self.httpd.server_close()
            if self._serve_thread is not None:
                self._serve_thread.join(5.0)
            if self.traffic is not None:
                # buffered rows become a final (short) chunk — nothing
                # logged is ever lost to shutdown
                self.traffic.close()
            # final time-series window AFTER the drain: the last chunk
            # carries the terminal counter state a fleet survivor (or a
            # post-mortem) reads for this process
            self.obs_snap.stop()
            return self._write_manifest()
        finally:
            # whatever happens above, serve_forever() must unblock — a
            # shutdown that dies mid-drain must not leave the CLI parked
            # forever on a listener that is already closed
            self._shutdown_done.set()

    def _write_manifest(self) -> Optional[str]:
        import sys

        from shifu_tpu import obs
        from shifu_tpu.obs.ledger import RunLedger

        ledger = RunLedger(self.root)
        try:
            try:
                profile_snap = obs.profiler().snapshot()
            except Exception as pe:  # pragma: no cover - defensive
                log.warning("cannot snapshot profiler: %s", pe)
                profile_snap = None
            extra = {"serve": self.registry.snapshot()}
            if self.zoo is not None:
                # budget ledger + per-tenant detail: evictions, cold
                # starts and peak occupancy are reconstructible from the
                # shutdown manifest alone
                extra["zoo"] = self.zoo.snapshot()
            from shifu_tpu.analysis import sanitize

            san = sanitize.current()
            if san is not None and san.active:
                # the serving analog of BasicProcessor.run's embed: the
                # shutdown manifest carries the shifu.sanitize/1 verdict
                # (incl. the race tracker's inversions/guard violations
                # under -Dshifu.sanitize=race) for the whole serve run
                extra["sanitizer"] = san.verdict()
            if self.drift is not None:
                # final flush: the shutdown manifest carries the full
                # per-column PSI state of everything this replica served
                extra["drift"] = self.drift.verdict()
            if self.traffic is not None:
                extra["traffic"] = self.traffic.snapshot()
            if self.registry.slo.enabled:
                extra["slo"] = self.registry.slo.snapshot()
            if self.peers.enabled:
                # last peer view before the lease released: the manifest
                # records what the process fleet looked like at drain
                extra["peers"] = self.peers.snapshot()
            if self.obs_snap.enabled:
                extra["obsTimeseries"] = self.obs_snap.snapshot()
            seq = ledger.next_seq("serve")
            # retained request traces serialize as a Perfetto-loadable
            # file next to the manifest; the manifest carries the
            # summary `shifu trace` / `shifu runs --traces` read
            from shifu_tpu.obs import reqtrace

            buf = reqtrace.buffer()
            if buf.active or buf.count:
                traces_path = os.path.join(
                    ledger.dir, f"serve-{seq}.traces.json")
                written = buf.write_traces(traces_path)
                extra["traces"] = dict(
                    buf.snapshot(),
                    path=(os.path.relpath(written, self.root)
                          if written else None))
            path = ledger.write(
                "serve", seq,
                status="ok",
                exit_status=0,
                started_at=self.started_at,
                elapsed_seconds=time.time() - self.started_at,
                argv=list(sys.argv),
                registry=obs.registry(),
                tracer=obs.tracer(),
                profile=profile_snap,
                extra=extra,
            )
            log.info("serve manifest -> %s", path)
            return path
        except OSError as e:  # a broken ledger must not mask shutdown
            log.warning("cannot write serve manifest: %s", e)
            return None
