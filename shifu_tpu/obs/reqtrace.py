"""Request-scoped distributed tracing for the serving fleet.

One end-to-end latency histogram cannot explain a p99: with N replicas
and continuous batching, tail latency is a routing/coalescing/convoy
question, and answering it takes per-request, per-stage evidence
(TensorFlow's timeline tooling exists for exactly this reason —
aggregate counters can't attribute a tail). Every admitted request gets
a `RequestTrace`:

  * a **trace id** — honoring an inbound `X-Shifu-Trace` header (the
    caller's distributed-tracing context), else generated — echoed in
    the response header and stamped into the traffic log so
    `shifu retrain`/`shifu promote` manifests carry serve→train→promote
    lineage;
  * a **per-stage timeline** over the whole serve path:

      featurize   raw record parse + host featurize + device_put
      route       drain-aware router placement + admission
      queue       admission queue wait (enqueue → worker pop)
      coalesce    micro-batch bucket wait (pop → dispatch)
      device      fused-program dispatch wall-clock
      d2h         result device_get
      serialize   response row build + JSON encode (HTTP path)

Retention is bounded and two-policy (`TraceBuffer`): **head sampling**
(`-Dshifu.trace.sample`, a deterministic stride like the shadow
sampler) keeps a representative slice, and **tail capture** keeps every
request slower than `-Dshifu.trace.slowMs` regardless of the sample —
the slow ones are the evidence. The ring holds at most
`-Dshifu.trace.maxTraces` traces; overflow drops the oldest and counts
`serve.trace.dropped`, so serve memory stays bounded at any uptime.

Stage durations also feed the `serve.stage_seconds{stage=,replica=}`
histograms (serve/fleet.py `finish_trace`), whose bucket samples carry
OpenMetrics exemplar trace ids — /metrics links straight to a captured
trace. Batcher bucket records (`note_batch`) witness which requests
shared a dispatch: the convoy evidence. Everything serializes as a
Chrome-trace/Perfetto-loadable JSON file next to the serve manifest
(`serve-<seq>.traces.json`), which `shifu trace` reads back.

Stage capture is thread-local (`capture_stages`/`note_stage`): the
micro-batcher opens a capture around the fused dispatch, the registry
notes featurize/device/d2h into it, and the batcher fans the captured
batch-level stages out to every request that rode the bucket — no
signature changes through the SwappableRegistry indirection.
"""

from __future__ import annotations

import itertools
import json
import os
import random
import re
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Dict, List, Optional, Tuple

from shifu_tpu.analysis.racetrack import tracked_lock
from shifu_tpu.fs.listing import sorted_glob
from shifu_tpu.utils import environment

TRACES_SCHEMA = "shifu.traces/1"
STAGES = ("featurize", "route", "queue", "coalesce", "device", "d2h",
          "serialize")
TRACE_HEADER = "X-Shifu-Trace"

DEFAULT_TRACE_SAMPLE = 0.05
DEFAULT_SLOW_MS = 100.0
DEFAULT_MAX_TRACES = 512

_ID_RE = re.compile(r"[^A-Za-z0-9_.:-]")
_FILE_RE = re.compile(r"^(?P<step>.+)-(?P<seq>\d+)\.traces\.json$")


def trace_sample_setting() -> float:
    """shifu.trace.sample — head-sampling fraction of requests whose
    traces are retained (0 = only the slow tail is captured)."""
    return environment.get_float("shifu.trace.sample", DEFAULT_TRACE_SAMPLE)


def trace_slow_ms_setting() -> float:
    """shifu.trace.slowMs — tail capture: every request slower than this
    is retained regardless of head sampling (0 disables)."""
    return environment.get_float("shifu.trace.slowMs", DEFAULT_SLOW_MS)


def trace_max_traces_setting() -> int:
    """shifu.trace.maxTraces — retained-trace ring capacity."""
    return environment.get_int("shifu.trace.maxTraces", DEFAULT_MAX_TRACES)


# id generation runs once per request on the serve hot path, so it must
# not release the GIL: uuid4/os.urandom is a syscall per call, and a GIL
# release point in a 16-thread handler pool costs switch-interval-scale
# convoy waits (measured ~1.5 ms on p50). A process-seeded Mersenne
# prefix + monotone sequence is unique without ever leaving Python.
_ID_RAND = random.Random()  # seeded from urandom ONCE at import
_ID_PREFIX = f"{os.getpid() & 0xFFFF:04x}{_ID_RAND.getrandbits(16):04x}"
_ID_SEQ = itertools.count(1)


def new_trace_id() -> str:
    return f"{_ID_PREFIX}{next(_ID_SEQ) & 0xFFFFFFFF:08x}"


def clean_trace_id(raw: Optional[str]) -> Optional[str]:
    """Sanitize an inbound header id: it lands in metrics exemplars, the
    traffic log and file names, so it must stay token-shaped."""
    if not raw:
        return None
    cleaned = _ID_RE.sub("_", raw.strip())[:64]
    return cleaned or None


class RequestTrace:
    """One request's id + per-stage timeline. Stages are appended by the
    handler thread (featurize/route/serialize) and the replica's batcher
    worker (queue/coalesce/device/d2h) — never concurrently on the
    request's happy path, because the handler blocks on the request
    event between its stages and the worker's."""

    __slots__ = ("trace_id", "sampled", "started_unix", "_t0", "timeline",
                 "attrs", "total_seconds")

    def __init__(self, trace_id: Optional[str] = None,
                 sampled: bool = False) -> None:
        self.trace_id = clean_trace_id(trace_id) or new_trace_id()
        self.sampled = bool(sampled)
        self.started_unix = time.time()
        self._t0 = time.perf_counter()
        self.timeline: List[Tuple[str, float, float]] = []
        self.attrs: Dict[str, object] = {}
        self.total_seconds: Optional[float] = None

    def add_stage(self, stage: str, seconds: float,
                  t0: Optional[float] = None) -> None:
        """Record one stage duration; `t0` is the stage's absolute
        perf_counter start (defaults to now - seconds)."""
        if t0 is None:
            t0 = time.perf_counter() - seconds
        self.timeline.append((stage, t0 - self._t0, float(seconds)))

    @contextmanager
    def stage(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.add_stage(name, time.perf_counter() - t0, t0)

    def annotate(self, **attrs) -> None:
        self.attrs.update(attrs)

    def finish(self) -> float:
        """Close the trace (idempotent); returns total seconds."""
        if self.total_seconds is None:
            self.total_seconds = time.perf_counter() - self._t0
        return self.total_seconds

    def stage_totals(self) -> Dict[str, float]:
        """Summed seconds per stage (a stage split across components —
        e.g. featurize in the front end AND in the registry — sums)."""
        out: Dict[str, float] = {}
        for stage, _off, dur in list(self.timeline):
            out[stage] = out.get(stage, 0.0) + dur
        return out

    def summary(self) -> dict:
        total = self.finish()
        return {
            "id": self.trace_id,
            "sampled": self.sampled,
            "startedUnix": round(self.started_unix, 3),
            "totalMs": round(total * 1e3, 3),
            "stages": {k: round(v * 1e3, 3)
                       for k, v in self.stage_totals().items()},
            "timeline": [[stage, round(off * 1e3, 3), round(dur * 1e3, 3)]
                         for stage, off, dur in list(self.timeline)],
            "attrs": dict(self.attrs),
        }


# ---------------------------------------------------------------------------
# thread-local batch stage capture (batcher worker <-> registry seam)
# ---------------------------------------------------------------------------

_tl = threading.local()


class StageCapture:
    """One dispatch's captured batch-level evidence: stage durations
    (fanned out to every request that rode the bucket) plus attributes
    like the model-set sha that scored the batch (version lineage —
    attributable across a mid-roll promote)."""

    __slots__ = ("stages", "attrs")

    def __init__(self) -> None:
        self.stages: List[Tuple[str, float, Optional[float]]] = []
        self.attrs: Dict[str, object] = {}


@contextmanager
def capture_stages(enabled: bool = True):
    """Collect `note_stage`/`note_attr` calls on THIS thread — the
    batcher wraps the fused dispatch so the registry's featurize/device/
    d2h notes land here and fan out to every request in the bucket."""
    if not enabled:
        yield None
        return
    prev = getattr(_tl, "capture", None)
    cap = StageCapture()
    _tl.capture = cap
    try:
        yield cap
    finally:
        _tl.capture = prev


def note_stage(stage: str, seconds: float,
               t0: Optional[float] = None) -> None:
    """Record a stage duration into the active capture (no-op without
    one — the un-traced hot path pays one thread-local read)."""
    cap = getattr(_tl, "capture", None)
    if cap is not None:
        cap.stages.append((stage, float(seconds), t0))


def note_attr(**attrs) -> None:
    """Attach batch-level attributes (e.g. scoredSha) to the active
    capture; they annotate every request in the bucket."""
    cap = getattr(_tl, "capture", None)
    if cap is not None:
        cap.attrs.update(attrs)


# ---------------------------------------------------------------------------
# bounded retained-trace ring
# ---------------------------------------------------------------------------


class TraceBuffer:
    """Ring of retained traces + batch (convoy) records, memory-bounded.

    Head sampling uses the deterministic every-k-th stride the shadow
    sampler uses (k = round(1/sample)); tail capture retains anything
    slower than `slow_ms`. Overflow drops the OLDEST retained trace and
    counts `serve.trace.dropped` — the ring never grows."""

    def __init__(self, capacity: Optional[int] = None,
                 sample: Optional[float] = None,
                 slow_ms: Optional[float] = None) -> None:
        self.capacity = max(1, (trace_max_traces_setting()
                                if capacity is None else int(capacity)))
        self.sample = (trace_sample_setting() if sample is None
                       else float(sample))
        self.slow_ms = (trace_slow_ms_setting() if slow_ms is None
                        else float(slow_ms))
        self._lock = tracked_lock("obs.reqtrace")
        self._ring: deque = deque(maxlen=self.capacity)
        self._batches: deque = deque(maxlen=self.capacity)
        # lock-free request tick (itertools.count is C-atomic): the
        # stride draw runs once per admitted request on the hot path,
        # where a shared lock would serialize handler threads
        self._tick = itertools.count()
        self._stride = max(1, int(round(1.0 / max(self.sample, 1e-6))))
        # offered counts lock-free (itertools.count): the common case —
        # an unretained trace — must not take the ring lock at all, or
        # a batch's worth of handler threads convoy on it per dispatch
        self._offered = itertools.count()
        self._offered_n = 0
        self._dropped = 0

    @property
    def active(self) -> bool:
        return self.sample > 0.0 or self.slow_ms > 0.0

    def head_sampled(self) -> bool:
        """Deterministic stride draw for the next request."""
        if self.sample <= 0.0:
            return False
        if self.sample >= 1.0:
            return True
        return next(self._tick) % self._stride == 0

    def offer(self, trace: RequestTrace) -> bool:
        """Finish + maybe retain a trace; True when it entered the ring
        (the caller attaches metric exemplars only for retained ids)."""
        total_ms = trace.finish() * 1e3
        keep = trace.sampled or (self.slow_ms > 0.0
                                 and total_ms >= self.slow_ms)
        self._offered_n = next(self._offered) + 1
        if not keep:
            return False
        overflow = False
        with self._lock:
            if len(self._ring) == self._ring.maxlen:
                self._dropped += 1
                overflow = True
            self._ring.append(trace)
        if overflow:
            from shifu_tpu.obs import registry

            registry().counter("serve.trace.dropped").inc()
        return True

    def note_batch(self, replica: str, trace_ids: List[str],
                   requests: int, rows: int, started_unix: float,
                   dur_s: float) -> None:
        """Record one micro-batch bucket: which traces shared a dispatch
        (the convoy witness in the exported trace file)."""
        with self._lock:
            self._batches.append({
                "replica": str(replica),
                "traces": list(trace_ids),
                "requests": int(requests),
                "rows": int(rows),
                "startedUnix": float(started_unix),
                "durMs": round(dur_s * 1e3, 3),
            })

    # ---- read side ----
    @property
    def count(self) -> int:
        with self._lock:
            return len(self._ring)

    def traces(self, last: Optional[int] = None) -> List[dict]:
        """Retained trace summaries, newest first."""
        with self._lock:
            kept = list(self._ring)
        out = [t.summary() for t in reversed(kept)]
        return out[:last] if last is not None else out

    def slowest(self, n: int = 10, stage: Optional[str] = None
                ) -> List[dict]:
        """Top-n summaries by total ms (or by one stage's ms)."""
        return slowest_summaries(self.traces(), n, stage=stage)

    def get(self, trace_id: str) -> Optional[dict]:
        with self._lock:
            kept = list(self._ring)
        for t in reversed(kept):
            if t.trace_id == trace_id:
                return t.summary()
        return None

    def snapshot(self) -> dict:
        with self._lock:
            kept = list(self._ring)
            offered, dropped = self._offered_n, self._dropped
        slowest_t = max(kept, key=lambda t: t.finish(), default=None)
        return {
            "count": len(kept),
            "offered": offered,
            "dropped": dropped,
            "sample": self.sample,
            "slowMs": self.slow_ms,
            "capacity": self.capacity,
            "slowestMs": (round(slowest_t.finish() * 1e3, 3)
                          if slowest_t is not None else None),
            "slowestId": (slowest_t.trace_id
                          if slowest_t is not None else None),
        }

    # ---- Chrome-trace / Perfetto export ----
    def to_chrome_trace(self) -> dict:
        """Perfetto-loadable JSON object: one track (tid) per retained
        request, stage X-events on a shared unix-µs timebase, plus one
        batcher track per replica whose bucket spans list the trace ids
        that coalesced together."""
        with self._lock:
            kept = list(self._ring)
            batches = list(self._batches)
        pid = os.getpid()
        events: List[dict] = []
        for i, t in enumerate(kept):
            tid = i + 1
            events.append({"name": "thread_name", "ph": "M", "pid": pid,
                           "tid": tid,
                           "args": {"name": f"req {t.trace_id}"}})
            base_us = t.started_unix * 1e6
            total = t.finish()
            events.append({
                "name": "request", "ph": "X", "ts": base_us,
                "dur": total * 1e6, "pid": pid, "tid": tid,
                "args": {"trace": t.trace_id,
                         **{k: _jsonable(v) for k, v in t.attrs.items()}},
            })
            for stage, off, dur in list(t.timeline):
                events.append({
                    "name": stage, "ph": "X", "ts": base_us + off * 1e6,
                    "dur": dur * 1e6, "pid": pid, "tid": tid,
                    "args": {"trace": t.trace_id, "parent": "request"},
                })
        replicas = sorted({b["replica"] for b in batches})
        for r in replicas:
            tid = 100_000 + replicas.index(r)
            events.append({"name": "thread_name", "ph": "M", "pid": pid,
                           "tid": tid,
                           "args": {"name": f"batcher replica {r}"}})
        for b in batches:
            tid = 100_000 + replicas.index(b["replica"])
            events.append({
                "name": f"batch[{b['requests']}]", "ph": "X",
                "ts": b["startedUnix"] * 1e6, "dur": b["durMs"] * 1e3,
                "pid": pid, "tid": tid,
                "args": {"traces": b["traces"], "rows": b["rows"],
                         "replica": b["replica"]},
            })
        events.sort(key=lambda e: e.get("ts", 0.0))
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def write_traces(self, path: str) -> Optional[str]:
        """Write the Perfetto-loadable trace file (plus the summaries
        `shifu trace` reads back); None when nothing was retained."""
        doc = self.to_chrome_trace()
        with self._lock:
            empty = not self._ring
        if empty:
            return None
        doc["schema"] = TRACES_SCHEMA
        doc["shifuTraces"] = self.traces()
        doc["summary"] = self.snapshot()
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(doc, fh)
        os.replace(tmp, path)
        return path


# ---------------------------------------------------------------------------
# process-global buffer (obs.reset() scope, like registry()/tracer())
# ---------------------------------------------------------------------------

_buffer: Optional[TraceBuffer] = None
_buffer_lock = tracked_lock("obs.reqtrace.scope")


def buffer() -> TraceBuffer:
    """The process-global request-trace ring; created lazily so the
    knobs bind AFTER -D parsing, re-read on obs.reset(). Double-checked:
    the steady-state read is lock-free (this runs per request on the
    serve hot path)."""
    global _buffer
    buf = _buffer
    if buf is not None:
        return buf
    with _buffer_lock:
        if _buffer is None:
            _buffer = TraceBuffer()
        return _buffer


def reset() -> None:
    global _buffer
    with _buffer_lock:
        _buffer = None


# ---------------------------------------------------------------------------
# ledger read side (`shifu trace` — jax-free)
# ---------------------------------------------------------------------------


def trace_files(root: str = ".") -> List[str]:
    """`<step>-<seq>.traces.json` files under <root>/.shifu/runs — the
    top-level ledger dir AND any per-run/per-process subdirectory one
    level down (a fleet member may ledger under its own dir) — newest
    (highest seq, then mtime) first, so `shifu trace --show <id>` and
    `--fleet` accept ids from ANY run or process, not just the newest
    serve run's file."""
    from shifu_tpu.obs.ledger import runs_dir

    out = []
    base = runs_dir(root)
    for pattern in ("*.traces.json", os.path.join("*", "*.traces.json")):
        for path in sorted_glob(os.path.join(base, pattern)):
            m = _FILE_RE.match(os.path.basename(path))
            if m:
                out.append((int(m.group("seq")),
                            os.path.getmtime(path), path))
    return [p for _s, _t, p in sorted(out, reverse=True)]


FLEET_TRACE_BASENAME = "fleet.traces.json"  # no -<seq>: never re-globbed


def stitch_trace_files(paths: List[str], out_path: str) -> Optional[dict]:
    """Merge many shifu.traces/1 exports (one per process/run) into ONE
    Perfetto-loadable document: each source file becomes its own track
    group (pids remapped per file, `process_name` metadata from the file
    stem), with every span kept on the shared unix-µs timebase — so a
    promote round's coordinator and participant spans, which share the
    round trace id, line up across processes in one view. Returns the
    stitched doc (None when no source file was readable); unreadable or
    non-trace files are skipped, not fatal."""
    events: List[dict] = []
    summaries: List[dict] = []
    sources: List[dict] = []
    for path in paths:
        try:
            doc = load_trace_file(path)
        except (OSError, ValueError):
            continue
        pid = len(sources) + 1
        label = os.path.basename(path)
        if label.endswith(".traces.json"):
            label = label[: -len(".traces.json")]
        events.append({"name": "process_name", "ph": "M", "pid": pid,
                       "args": {"name": label}})
        for e in doc.get("traceEvents", []):
            e = dict(e)
            e["pid"] = pid
            events.append(e)
        for s in doc.get("shifuTraces", []):
            s = dict(s)
            s["file"] = label
            summaries.append(s)
        sources.append({"path": path, "label": label,
                        "traces": len(doc.get("shifuTraces", []))})
    if not sources:
        return None
    events.sort(key=lambda e: e.get("ts", 0.0))
    out = {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "schema": TRACES_SCHEMA,
        "shifuTraces": summaries,
        "summary": {"count": len(summaries), "stitched": True,
                    "sources": sources},
    }
    os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
    tmp = out_path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(out, fh)
    os.replace(tmp, out_path)
    return out


def load_trace_file(path: str) -> dict:
    with open(path) as fh:
        doc = json.load(fh)
    if doc.get("schema") != TRACES_SCHEMA:
        raise ValueError(f"{path} is not a {TRACES_SCHEMA} file")
    return doc


def slowest_summaries(summaries: List[dict], n: int,
                      stage: Optional[str] = None) -> List[dict]:
    """Top-n by total ms, or by one stage's summed ms (requests that
    never entered the stage rank last)."""
    if stage is not None:
        def key(s):
            return s.get("stages", {}).get(stage, -1.0)
    else:
        def key(s):
            return s.get("totalMs", 0.0)
    return sorted(summaries, key=key, reverse=True)[:max(0, n)]


def dominant_stage(summary: dict) -> str:
    stages = summary.get("stages") or {}
    if not stages:
        return "-"
    return max(stages.items(), key=lambda kv: kv[1])[0]


def format_trace_table(summaries: List[dict]) -> str:
    """Human table for `shifu trace` listings."""
    if not summaries:
        return "(no traces captured — serve with -Dshifu.trace.sample>0 " \
               "or send an X-Shifu-Trace header)"
    header = (f"{'TRACE':<18} {'TOTAL ms':>9} {'DOMINANT':<10} "
              f"{'REPLICA':>7} STAGES (ms)")
    lines = [header]
    for s in summaries:
        stages = s.get("stages") or {}
        stage_str = " ".join(
            f"{k}={stages[k]:.2f}" for k in STAGES if k in stages)
        lines.append(
            f"{s.get('id', '?'):<18} {s.get('totalMs', 0.0):>9.2f} "
            f"{dominant_stage(s):<10} "
            f"{str(s.get('attrs', {}).get('replica', '-')):>7} "
            f"{stage_str}")
    return "\n".join(lines)


def format_trace_detail(summary: dict, path: Optional[str] = None) -> str:
    """Full per-stage timeline for `shifu trace --show <id>`."""
    lines = [f"trace {summary.get('id')}  total "
             f"{summary.get('totalMs', 0.0):.3f} ms"
             + (f"  ({path})" if path else "")]
    for k, v in sorted((summary.get("attrs") or {}).items()):
        lines.append(f"  {k}: {v}")
    lines.append(f"  {'STAGE':<10} {'AT ms':>10} {'DUR ms':>10}")
    for stage, off, dur in summary.get("timeline") or []:
        lines.append(f"  {stage:<10} {off:>10.3f} {dur:>10.3f}")
    if path:
        lines.append(f"open {path} in Perfetto (ui.perfetto.dev) for the "
                     "batch-convoy view")
    return "\n".join(lines)


def _jsonable(v):
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    return str(v)
