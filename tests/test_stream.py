"""Streaming bounded-memory ingest tests.

The contract (reference MemoryDiskFloatMLDataSet + shifuconfig memory
envelope): the pipeline must complete on datasets far larger than the
configured memory budget, with peak allocation under the budget, and the
streaming results must agree with the in-RAM path.
"""

import json
import os
import tracemalloc

import numpy as np
import pytest

from shifu_tpu.utils import environment
from tests.helpers import make_model_set


def _set_props(**kv):
    for k, v in kv.items():
        environment.set_property(k, str(v))


def _clear_props(*keys):
    for k in keys:
        environment.set_property(k, "")


class TestChunkedReader:
    def test_chunks_concatenate_to_whole_read(self, tmp_path):
        from shifu_tpu.data.reader import read_columnar
        from shifu_tpu.data.stream import iter_columnar_chunks
        from tests.helpers import make_binary_dataset, write_dataset

        names, rows, _ = make_binary_dataset(n_rows=500)
        data_path, _ = write_dataset(str(tmp_path / "d"), names, rows)
        whole = read_columnar(data_path, names)
        chunks = list(iter_columnar_chunks(data_path, names, chunk_rows=128))
        assert len(chunks) == 4
        assert sum(c.n_rows for c in chunks) == whole.n_rows
        got = np.concatenate([c.column("num_0") for c in chunks])
        np.testing.assert_array_equal(got, whole.column("num_0"))

    def test_parquet_chunks(self, tmp_path):
        import pandas as pd

        from shifu_tpu.data.stream import iter_columnar_chunks

        df = pd.DataFrame({
            "a": [str(i) for i in range(300)],
            "b": ["x"] * 300,
        })
        p = str(tmp_path / "part.parquet")
        df.to_parquet(p)
        chunks = list(iter_columnar_chunks(p, ["a", "b"], chunk_rows=100))
        assert sum(c.n_rows for c in chunks) == 300
        assert chunks[0].column("a")[0] == "0"


class TestStreamingStats:
    def test_streaming_matches_exact_within_tolerance(self, tmp_path):
        from shifu_tpu.config import load_column_config_list
        from shifu_tpu.processor.init import InitProcessor
        from shifu_tpu.processor.stats import StatsProcessor

        root = str(tmp_path / "ms")
        make_model_set(root, n_rows=3000)
        assert InitProcessor(root).run() == 0
        assert StatsProcessor(root).run() == 0
        exact = load_column_config_list(os.path.join(root, "ColumnConfig.json"))

        _set_props(**{"shifu.ingest.forceStreaming": "true",
                      "shifu.ingest.chunkRows": "512"})
        try:
            assert StatsProcessor(root).run() == 0
        finally:
            _clear_props("shifu.ingest.forceStreaming",
                         "shifu.ingest.chunkRows")
        stream = load_column_config_list(os.path.join(root, "ColumnConfig.json"))

        for e, s in zip(exact, stream):
            if e.column_stats.ks is None:
                continue
            assert s.column_stats.ks == pytest.approx(e.column_stats.ks,
                                                      abs=2.0), e.column_name
            assert s.column_stats.iv == pytest.approx(e.column_stats.iv,
                                                      rel=0.2, abs=0.05)
            assert s.column_stats.mean == pytest.approx(e.column_stats.mean,
                                                        rel=1e-5, abs=1e-6)
            assert s.column_stats.std_dev == pytest.approx(
                e.column_stats.std_dev, rel=1e-4, abs=1e-6)
            assert s.column_stats.total_count == e.column_stats.total_count
            assert s.column_stats.missing_count == e.column_stats.missing_count
            if e.is_categorical():
                # exact parity for categoricals: counts, not sketches
                assert (s.column_binning.bin_category
                        == e.column_binning.bin_category)
                assert (s.column_binning.bin_count_pos
                        == e.column_binning.bin_count_pos)


class TestStreamingNorm:
    def test_streaming_norm_identical_given_same_bins(self, tmp_path):
        from shifu_tpu.norm.dataset import load_codes, load_normalized
        from shifu_tpu.processor.init import InitProcessor
        from shifu_tpu.processor.norm import NormProcessor
        from shifu_tpu.processor.stats import StatsProcessor

        root = str(tmp_path / "ms")
        make_model_set(root, n_rows=1500)
        assert InitProcessor(root).run() == 0
        assert StatsProcessor(root).run() == 0
        assert NormProcessor(root).run() == 0
        m1, f1, t1, w1 = load_normalized(
            os.path.join(root, "tmp", "norm", "NormalizedData"))
        _, c1, _, _ = load_codes(
            os.path.join(root, "tmp", "norm", "CleanedData"))

        _set_props(**{"shifu.ingest.forceStreaming": "true",
                      "shifu.ingest.chunkRows": "256"})
        try:
            assert NormProcessor(root).run() == 0
        finally:
            _clear_props("shifu.ingest.forceStreaming",
                         "shifu.ingest.chunkRows")
        m2, f2, t2, w2 = load_normalized(
            os.path.join(root, "tmp", "norm", "NormalizedData"))
        _, c2, _, _ = load_codes(
            os.path.join(root, "tmp", "norm", "CleanedData"))

        assert m2.columns == m1.columns
        assert len(m2.shard_rows) >= 5  # one shard per chunk
        np.testing.assert_allclose(np.asarray(f2), np.asarray(f1), atol=1e-6)
        np.testing.assert_array_equal(np.asarray(t2), np.asarray(t1))
        np.testing.assert_allclose(np.asarray(w2), np.asarray(w1), atol=1e-6)
        np.testing.assert_array_equal(np.asarray(c2), np.asarray(c1))
        assert (m2.extra or {}).get("sourceOf")


@pytest.mark.slow
class TestBoundedMemoryPipeline:
    """init -> stats -> norm -> train on a dataset ~4x the memory budget,
    asserting tracked peak allocation stays under the budget."""

    BUDGET_MB = 10

    def _generate_big(self, root: str) -> str:
        """~40 MB CSV written incrementally: 8 informative numerics + one
        fat text column (padding that an in-RAM object-array read would
        hold resident at ~10x file cost)."""
        from shifu_tpu.config.model_config import Algorithm, new_model_config

        data_dir = os.path.join(root, "data")
        os.makedirs(data_dir, exist_ok=True)
        names = ["target"] + [f"f{i}" for i in range(8)] + ["pad"]
        with open(os.path.join(data_dir, "header.txt"), "w") as fh:
            fh.write("|".join(names))
        rng = np.random.default_rng(0)
        n, block = 70_000, 5_000
        pad = "z" * 500
        with open(os.path.join(data_dir, "data.txt"), "w") as fh:
            for start in range(0, n, block):
                x = rng.normal(size=(block, 8))
                y = (1.5 * x[:, 0] - x[:, 1] > 0).astype(int)
                lines = []
                for i in range(block):
                    fields = [str(y[i])] + [f"{v:.5f}" for v in x[i]] + [pad]
                    lines.append("|".join(fields))
                fh.write("\n".join(lines) + "\n")

        with open(os.path.join(root, "meta.names"), "w") as fh:
            fh.write("pad\n")
        mc = new_model_config("BigModel", Algorithm.NN)
        mc.data_set.data_path = os.path.join(data_dir, "data.txt")
        mc.data_set.header_path = os.path.join(data_dir, "header.txt")
        mc.data_set.data_delimiter = "|"
        mc.data_set.header_delimiter = "|"
        mc.data_set.target_column_name = "target"
        mc.data_set.pos_tags = ["1"]
        mc.data_set.neg_tags = ["0"]
        mc.data_set.meta_column_name_file = os.path.join(root, "meta.names")
        mc.train.num_train_epochs = 3
        mc.save(os.path.join(root, "ModelConfig.json"))
        return os.path.join(data_dir, "data.txt")

    def test_pipeline_under_budget(self, tmp_path):
        from shifu_tpu.data.stream import dataset_size_bytes
        from shifu_tpu.processor.init import InitProcessor
        from shifu_tpu.processor.norm import NormProcessor
        from shifu_tpu.processor.stats import StatsProcessor
        from shifu_tpu.processor.train import TrainProcessor
        from shifu_tpu.varsel.selector import select_by_filter

        root = str(tmp_path / "big")
        os.makedirs(root)
        data_path = self._generate_big(root)
        budget = self.BUDGET_MB * 1024 * 1024
        assert dataset_size_bytes(data_path) >= 3.5 * budget

        _set_props(**{
            "shifu.ingest.memoryBudgetMB": str(self.BUDGET_MB),
            "shifu.ingest.chunkRows": "8192",
        })
        # warm jax/pandas before measuring so one-time import/compile
        # allocations don't count against the ingest budget
        import jax.numpy as jnp

        (jnp.zeros((8, 8)) @ jnp.zeros((8, 8))).block_until_ready()
        tracemalloc.start()
        try:
            assert InitProcessor(root).run() == 0
            assert StatsProcessor(root).run() == 0
            assert NormProcessor(root).run() == 0
            _, peak_ingest = tracemalloc.get_traced_memory()
            assert TrainProcessor(root).run() == 0
            _, peak_total = tracemalloc.get_traced_memory()
        finally:
            tracemalloc.stop()
            _clear_props("shifu.ingest.memoryBudgetMB",
                         "shifu.ingest.chunkRows")

        assert peak_ingest < budget, (
            f"ingest peak {peak_ingest/1e6:.1f} MB over "
            f"{budget/1e6:.0f} MB budget"
        )
        # training holds the dense f32 matrix (HBM-resident design) — still
        # far under the raw dataset size
        assert peak_total < budget + 16 * 1024 * 1024
        assert os.path.isfile(os.path.join(root, "models", "model0.nn"))
