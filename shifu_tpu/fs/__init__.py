"""Filesystem layer: canonical artifact path layout + IO helpers."""

from shifu_tpu.fs.listing import sorted_glob, sorted_listdir  # noqa: F401
from shifu_tpu.fs.pathfinder import PathFinder  # noqa: F401
