"""`shifu combo` — ensemble-of-algorithms workflow.

Parity: core/processor/ComboModelProcessor.java:45 + combo/* — NEW declares
the algorithm list (last = assembler), INIT scaffolds one sub-model-set dir
per member, RUN trains members then joins their training-data scores into
the assembler's training set (combo/PigDataJoin equivalent) and trains the
assembler, EVAL scores the eval set through members -> assembler.
"""

from __future__ import annotations

import json
import os
import shutil
from typing import List, Optional

import numpy as np

from shifu_tpu.config.model_config import Algorithm, ModelConfig
from shifu_tpu.processor.basic import BasicProcessor
from shifu_tpu.utils.errors import ErrorCode, ShifuError
from shifu_tpu.utils.log import get_logger

log = get_logger(__name__)

COMBO_SPEC = "ComboTrain.json"


class ComboProcessor(BasicProcessor):
    step = "combo"

    def __init__(self, root: str = ".", new_algs: Optional[str] = None,
                 do_init: bool = False, do_run: bool = False,
                 do_eval: bool = False):
        super().__init__(root)
        self.new_algs = new_algs
        self.do_init = do_init
        self.do_run = do_run
        self.do_eval = do_eval

    @classmethod
    def from_args(cls, args) -> "ComboProcessor":
        return cls(new_algs=args.new_algs, do_init=args.do_init,
                   do_run=args.do_run, do_eval=args.do_eval)

    # ---- spec ----
    def _spec_path(self) -> str:
        return os.path.join(self.root, COMBO_SPEC)

    def _load_spec(self) -> dict:
        if not os.path.isfile(self._spec_path()):
            raise ShifuError(ErrorCode.INVALID_MODEL_CONFIG,
                             "no ComboTrain.json — run `shifu combo -new ...`")
        with open(self._spec_path()) as fh:
            return json.load(fh)

    def _member_dir(self, i: int, alg: str) -> str:
        return os.path.join(self.root, f"sub_{i}_{alg}")

    def _assembler_dir(self, alg: str) -> str:
        return os.path.join(self.root, f"assembler_{alg}")

    def run_step(self) -> None:
        if self.new_algs:
            algs = [a.strip().upper() for a in self.new_algs.split(",") if a.strip()]
            if len(algs) < 2:
                raise ShifuError(ErrorCode.INVALID_MODEL_CONFIG,
                                 "combo needs >= 2 algorithms (last = assembler)")
            with open(self._spec_path(), "w") as fh:
                json.dump({"members": algs[:-1], "assembler": algs[-1]}, fh,
                          indent=2)
            log.info("combo spec: members=%s assembler=%s", algs[:-1], algs[-1])
            return

        spec = self._load_spec()
        if self.do_init:
            self._init(spec)
        if self.do_run:
            self._run(spec)
        if self.do_eval:
            self._eval(spec)
        if not (self.do_init or self.do_run or self.do_eval):
            log.info("combo spec: %s", spec)

    # ---- steps ----
    def _init(self, spec: dict) -> None:
        self.setup(need_columns=False)
        from shifu_tpu.config.model_config import default_train_params

        for i, alg in enumerate(spec["members"]):
            d = self._member_dir(i, alg)
            os.makedirs(d, exist_ok=True)
            mc = ModelConfig.load(self.paths.model_config_path())
            mc.basic.name = f"{mc.basic.name}_sub{i}_{alg}"
            mc.train.algorithm = Algorithm.parse(alg)
            mc.train.params = default_train_params(mc.train.algorithm)
            # data paths resolve relative to the member dir
            mc.data_set.data_path = os.path.relpath(
                self.resolve(mc.data_set.data_path), d)
            if mc.data_set.header_path:
                mc.data_set.header_path = os.path.relpath(
                    self.resolve(mc.data_set.header_path), d)
            mc.save(os.path.join(d, "ModelConfig.json"))
            log.info("member %d (%s) -> %s", i, alg, d)

    def _run_pipeline(self, d: str, steps=("init", "stats", "norm", "train")) -> None:
        from shifu_tpu.processor.init import InitProcessor
        from shifu_tpu.processor.norm import NormProcessor
        from shifu_tpu.processor.stats import StatsProcessor
        from shifu_tpu.processor.train import TrainProcessor

        mapping = {
            "init": InitProcessor, "stats": StatsProcessor,
            "norm": NormProcessor, "train": TrainProcessor,
        }
        for s in steps:
            assert mapping[s](d).run() == 0

    def _member_scores(self, spec: dict, data) -> np.ndarray:
        """[n, n_members] mean scores of each member on a raw dataset."""
        from shifu_tpu.eval.scorer import ModelRunner, find_model_paths

        cols = []
        for i, alg in enumerate(spec["members"]):
            d = self._member_dir(i, alg)
            paths = find_model_paths(os.path.join(d, "models"))
            runner = ModelRunner(paths)
            cols.append(runner.score_raw(data).mean)
        return np.stack(cols, axis=1)

    def _load_raw(self):
        from shifu_tpu.data.purify import combined_mask
        from shifu_tpu.data.reader import make_tags, read_columnar, read_header

        mc = self.model_config
        ds = mc.data_set
        names = read_header(self.resolve(ds.header_path), ds.header_delimiter)
        data = read_columnar(self.resolve(ds.data_path), names,
                             delimiter=ds.data_delimiter,
                             missing_values=tuple(ds.missing_or_invalid_values))
        mask = combined_mask(ds.filter_expressions, data.raw, data.n_rows)
        data = data.select_rows(mask)
        tags = make_tags(data.column(ds.target_column_name), ds.pos_tags,
                         ds.neg_tags)
        return data, tags

    def _run(self, spec: dict) -> None:
        self.setup(need_columns=False)
        for i, alg in enumerate(spec["members"]):
            log.info("=== combo member %d: %s ===", i, alg)
            self._run_pipeline(self._member_dir(i, alg))

        # assembler training set: tag | member scores (combo/DataMerger)
        data, tags = self._load_raw()
        scores = self._member_scores(spec, data)
        alg = spec["assembler"]
        d = self._assembler_dir(alg)
        os.makedirs(os.path.join(d, "data"), exist_ok=True)
        names = [f"score_{i}" for i in range(scores.shape[1])]
        with open(os.path.join(d, "data", "header.txt"), "w") as fh:
            fh.write("|".join(["tag"] + names) + "\n")
        with open(os.path.join(d, "data", "data.txt"), "w") as fh:
            for i in range(scores.shape[0]):
                if tags[i] < 0:
                    continue
                fh.write("|".join([str(int(tags[i]))] +
                                  [f"{v:.4f}" for v in scores[i]]) + "\n")

        from shifu_tpu.config.model_config import default_train_params, new_model_config

        amc = new_model_config(f"{self.model_config.basic.name}_assembler",
                               Algorithm.parse(alg))
        amc.data_set.data_path = "data/data.txt"
        amc.data_set.header_path = "data/header.txt"
        amc.data_set.target_column_name = "tag"
        amc.data_set.pos_tags = ["1"]
        amc.data_set.neg_tags = ["0"]
        amc.train.params = default_train_params(amc.train.algorithm)
        amc.save(os.path.join(d, "ModelConfig.json"))
        log.info("=== combo assembler: %s ===", alg)
        self._run_pipeline(d)
        log.info("combo run complete.")

    def _eval(self, spec: dict) -> None:
        self.setup(need_columns=False)
        from shifu_tpu.data.reader import ColumnarData
        from shifu_tpu.eval.metrics import evaluate_performance
        from shifu_tpu.eval.scorer import ModelRunner, find_model_paths

        mc = self.model_config
        if not mc.evals:
            raise ShifuError(ErrorCode.INVALID_MODEL_CONFIG, "no eval sets")
        data, tags = self._load_raw()  # eval on training source by default
        scores = self._member_scores(spec, data)
        names = [f"score_{i}" for i in range(scores.shape[1])]
        sdata = ColumnarData(
            names=names,
            raw={n: np.asarray([f"{v:.4f}" for v in scores[:, i]], object)
                 for i, n in enumerate(names)},
            n_rows=scores.shape[0],
        )
        alg = spec["assembler"]
        paths = find_model_paths(os.path.join(self._assembler_dir(alg), "models"))
        runner = ModelRunner(paths)
        final = runner.score_raw(sdata).mean
        keep = tags >= 0
        perf = evaluate_performance(final[keep], tags[keep].astype(float))
        out_dir = self.paths.ensure(os.path.join(self.root, "evals", "Combo"))
        with open(os.path.join(out_dir, "EvalPerformance.json"), "w") as fh:
            json.dump(perf.to_json(), fh, indent=2)
        log.info("combo eval AUC %.6f -> %s", perf.area_under_roc, out_dir)
