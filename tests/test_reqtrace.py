"""Request-scoped tracing (obs/reqtrace.py) + per-stage tail attribution:
the RequestTrace/TraceBuffer layer, the serve-path stage timeline
(featurize/route/queue/coalesce/device/d2h/serialize), head-sampling +
slow-tail retention bounds, stage histograms with trace-id exemplars,
the X-Shifu-Trace header contract, SLO burn accounting, traffic-log
lineage, the `shifu trace` CLI, the span-tracer event ring, and the
concurrent-thread Chrome-trace export (per-thread tracks + parenting
must survive spans opened on router/batcher/prefetch threads).

The acceptance pin lives in TestSlowFeaturizeAttribution: a deliberately
slowed featurize path must show up IN THE TRACES as the dominant stage,
and `shifu trace --slowest --stage featurize` must surface it.
"""

import json
import os
import threading
import time
import urllib.request

import numpy as np
import pytest

from shifu_tpu.utils import environment


class _Props:
    def __init__(self, **props):
        self.props = {k.replace("_", "."): v for k, v in props.items()}

    def __enter__(self):
        for k, v in self.props.items():
            environment.set_property(k, v)
        from shifu_tpu import obs

        obs.reset()  # buffers/tracers re-read knobs at construction
        return self

    def __exit__(self, *exc):
        for k in self.props:
            environment.set_property(k, "")
        from shifu_tpu import obs

        obs.reset()


@pytest.fixture(scope="module")
def models_dir(tmp_path_factory):
    """Tiny 2-bag NN set written directly (tracing mechanics don't need
    trained weights)."""
    from shifu_tpu.models.nn import NNModelSpec, init_params

    d = str(tmp_path_factory.mktemp("trace_models"))
    cols = [f"c{i}" for i in range(5)]
    sizes = [len(cols), 4, 1]
    for b in range(2):
        specs = [{"name": c, "kind": "value", "outNames": [c],
                  "mean": 0.1 * i, "std": 1.0, "fill": 0.0, "zscore": True}
                 for i, c in enumerate(cols)]
        NNModelSpec(layer_sizes=sizes, activations=["tanh"],
                    input_columns=cols, norm_specs=specs,
                    params=init_params(sizes, seed=b),
                    ).save(os.path.join(d, f"model{b}.nn"))
    return d


def _scorer(models_dir, **kw):
    from shifu_tpu.serve.queue import AdmissionQueue
    from shifu_tpu.serve.registry import ModelRegistry
    from shifu_tpu.serve.server import Scorer

    reg = ModelRegistry(models_dir)
    sc = Scorer(reg, AdmissionQueue(64), **kw)
    reg.warm([1, 4])
    return sc


def _rec(i=0):
    return {f"c{k}": f"{0.1 * (i + k):.3f}" for k in range(5)}


# ---------------------------------------------------------------------------
# RequestTrace + TraceBuffer mechanics
# ---------------------------------------------------------------------------


class TestRequestTrace:
    def test_stages_totals_and_summary(self):
        from shifu_tpu.obs.reqtrace import RequestTrace

        t = RequestTrace(sampled=True)
        t.add_stage("featurize", 0.002)
        t.add_stage("featurize", 0.001)  # components of one stage SUM
        with t.stage("device"):
            time.sleep(0.001)
        t.annotate(replica="3", rows=7)
        total = t.finish()
        assert total >= 0.001
        tot = t.stage_totals()
        assert tot["featurize"] == pytest.approx(0.003)
        assert tot["device"] >= 0.001
        s = t.summary()
        assert s["id"] == t.trace_id
        assert s["stages"]["featurize"] == pytest.approx(3.0, abs=0.01)
        assert s["attrs"] == {"replica": "3", "rows": 7}
        assert [e[0] for e in s["timeline"]] == ["featurize", "featurize",
                                                 "device"]
        # finish is idempotent — a second call keeps the first total
        assert t.finish() == total

    def test_trace_ids_unique_and_header_sanitized(self):
        from shifu_tpu.obs.reqtrace import RequestTrace, clean_trace_id

        ids = {RequestTrace().trace_id for _ in range(500)}
        assert len(ids) == 500
        assert clean_trace_id("  ok-id_1.2:3 ") == "ok-id_1.2:3"
        assert clean_trace_id('evil"id\nwith|stuff') == "evil_id_with_stuff"
        assert clean_trace_id("x" * 200) == "x" * 64
        assert clean_trace_id("") is None
        assert clean_trace_id(None) is None

    def test_head_sampling_stride_and_slow_capture(self):
        from shifu_tpu.obs.reqtrace import RequestTrace, TraceBuffer

        buf = TraceBuffer(capacity=100, sample=0.25, slow_ms=0)
        draws = [buf.head_sampled() for _ in range(100)]
        assert sum(draws) == 25  # deterministic every-4th stride
        # slow capture keeps an unsampled trace that crossed slowMs
        buf = TraceBuffer(capacity=10, sample=0.0, slow_ms=5.0)
        fast = RequestTrace(sampled=False)
        fast.total_seconds = 0.001
        assert buf.offer(fast) is False
        slow = RequestTrace(sampled=False)
        slow.total_seconds = 0.050
        assert buf.offer(slow) is True
        assert buf.count == 1
        assert buf.get(slow.trace_id)["id"] == slow.trace_id
        assert buf.snapshot()["offered"] == 2

    def test_ring_bound_and_drop_counter(self):
        from shifu_tpu import obs
        from shifu_tpu.obs.reqtrace import RequestTrace, TraceBuffer

        obs.reset()
        buf = TraceBuffer(capacity=4, sample=1.0, slow_ms=0)
        traces = [RequestTrace(sampled=True) for _ in range(7)]
        for t in traces:
            t.total_seconds = 0.001
            buf.offer(t)
        assert buf.count == 4  # bounded
        snap = buf.snapshot()
        assert snap["dropped"] == 3
        kept_ids = {s["id"] for s in buf.traces()}
        assert kept_ids == {t.trace_id for t in traces[3:]}  # newest kept
        c = obs.registry().snapshot()["counters"]
        assert c.get("serve.trace.dropped") == 3.0

    def test_slowest_ranking_by_total_and_stage(self):
        from shifu_tpu.obs.reqtrace import slowest_summaries

        sums = [
            {"id": "a", "totalMs": 10.0, "stages": {"featurize": 9.0}},
            {"id": "b", "totalMs": 30.0, "stages": {"device": 29.0}},
            {"id": "c", "totalMs": 20.0, "stages": {"featurize": 1.0}},
        ]
        assert [s["id"] for s in slowest_summaries(sums, 2)] == ["b", "c"]
        by_feat = slowest_summaries(sums, 3, stage="featurize")
        assert [s["id"] for s in by_feat] == ["a", "c", "b"]


# ---------------------------------------------------------------------------
# end-to-end: the serve path produces full stage timelines
# ---------------------------------------------------------------------------


class TestServePathTracing:
    def test_stages_convoy_and_exemplars(self, models_dir):
        from shifu_tpu import obs
        from shifu_tpu.obs import reqtrace

        with _Props(shifu_trace_sample="1.0", shifu_trace_slowMs="0"):
            sc = _scorer(models_dir)
            n_threads = 4

            def client(ti):
                for k in range(3):
                    sc.score_batch([_rec(ti + k)])

            threads = [threading.Thread(target=client, args=(ti,))
                       for ti in range(n_threads)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            sc.close()
            buf = reqtrace.buffer()
            assert buf.count == 12
            sha = sc.registry.sha
            for s in buf.traces():
                assert set(s["stages"]) >= {"featurize", "route", "queue",
                                            "coalesce", "device", "d2h"}
                assert s["attrs"]["replica"] == "0"
                # version lineage: the trace names the sha that scored it
                assert s["attrs"]["scoredSha"] == sha
            # convoy witness: batch records name the coalesced traces
            ct = buf.to_chrome_trace()
            batch_events = [e for e in ct["traceEvents"]
                            if e["name"].startswith("batch[")]
            assert batch_events
            witnessed = {tid for e in batch_events
                         for tid in e["args"]["traces"]}
            assert witnessed == {s["id"] for s in buf.traces()}
            # per-request tracks: one metadata thread-name per trace
            names = [e for e in ct["traceEvents"]
                     if e.get("name") == "thread_name"]
            assert len([e for e in names
                        if e["args"]["name"].startswith("req ")]) == 12
            # stage histograms with exemplar ids on /metrics
            prom = obs.registry().to_prometheus()
            assert "serve_stage_seconds_bucket" in prom
            assert "trace_id=" in prom
            from shifu_tpu.obs.metrics import parse_prometheus

            assert parse_prometheus(prom) == obs.registry().flatten()

    def test_unsampled_requests_not_retained_but_measured(self, models_dir):
        from shifu_tpu import obs
        from shifu_tpu.obs import reqtrace

        with _Props(shifu_trace_sample="0", shifu_trace_slowMs="60000"):
            sc = _scorer(models_dir)
            for i in range(5):
                sc.score_batch([_rec(i)])
            sc.close()
            assert reqtrace.buffer().count == 0  # nothing retained...
            snap = obs.registry().snapshot()
            hists = [k for k in snap["histograms"]
                     if k.startswith("serve.stage_seconds")]
            assert hists  # ...but every request fed the stage histograms
            key = [k for k in hists if 'stage="device"' in k][0]
            assert snap["histograms"][key]["count"] == 5

    def test_tracing_off_is_off(self, models_dir):
        from shifu_tpu.obs import reqtrace

        with _Props(shifu_trace_sample="0", shifu_trace_slowMs="0"):
            sc = _scorer(models_dir)
            sc.score_batch([_rec()])
            sc.close()
            buf = reqtrace.buffer()
            assert not buf.active
            assert buf.count == 0
            assert buf.snapshot()["offered"] == 0


# ---------------------------------------------------------------------------
# acceptance: a slowed featurize path is correctly attributed
# ---------------------------------------------------------------------------


class TestSlowFeaturizeAttribution:
    def test_slow_featurize_dominates_and_cli_surfaces_it(
            self, models_dir, tmp_path, monkeypatch, capsys):
        from shifu_tpu.obs import reqtrace
        from shifu_tpu.serve import registry as registry_mod

        slow_call = registry_mod._PlanFeaturizer.__call__

        def slowed(self, data, code_cache=None, numeric_cache=None):
            time.sleep(0.04)  # the deliberately slowed host featurize
            return slow_call(self, data, code_cache, numeric_cache)

        monkeypatch.setattr(registry_mod._PlanFeaturizer, "__call__",
                            slowed)
        with _Props(shifu_trace_sample="1.0", shifu_trace_slowMs="0"):
            sc = _scorer(models_dir)
            for i in range(4):
                sc.score_batch([_rec(i)])
            sc.close()
            buf = reqtrace.buffer()
            summaries = buf.traces()
            assert len(summaries) >= 4
            for s in summaries:
                stages = s["stages"]
                # featurize dominates every other stage in every trace
                others = max(v for k, v in stages.items()
                             if k != "featurize")
                assert stages["featurize"] >= 40.0  # the injected 40 ms
                assert stages["featurize"] > others
            # --slowest --stage featurize surfaces them via the ledger
            # file exactly as `shifu trace` reads it
            path = os.path.join(str(tmp_path), ".shifu", "runs",
                                "serve-1.traces.json")
            assert buf.write_traces(path) == path
            monkeypatch.chdir(tmp_path)
            from shifu_tpu.cli import main

            assert main(["trace", "--slowest", "2",
                         "--stage", "featurize", "--json"]) == 0
            doc = json.loads(capsys.readouterr().out)
            assert len(doc["traces"]) == 2
            top = doc["traces"][0]
            assert top["stages"]["featurize"] >= 40.0
            # human table names featurize as the dominant stage
            assert main(["trace", "--slowest", "2",
                         "--stage", "featurize"]) == 0
            out = capsys.readouterr().out
            assert "featurize" in out
            # --show renders the per-stage timeline for the slowest id
            assert main(["trace", "--show", top["id"]]) == 0
            assert "featurize" in capsys.readouterr().out


# ---------------------------------------------------------------------------
# HTTP contract: X-Shifu-Trace honored, echoed, retained, logged
# ---------------------------------------------------------------------------


class TestHttpTraceContract:
    def test_header_forces_retention_and_lineage(self, tmp_path):
        from shifu_tpu.models.nn import NNModelSpec, init_params
        from shifu_tpu.obs import reqtrace
        from shifu_tpu.serve.server import ScoringServer

        root = str(tmp_path)
        cols = [f"c{i}" for i in range(4)]
        sizes = [4, 3, 1]
        specs = [{"name": c, "kind": "value", "outNames": [c],
                  "mean": 0.0, "std": 1.0, "fill": 0.0, "zscore": True}
                 for c in cols]
        os.makedirs(os.path.join(root, "models"))
        NNModelSpec(layer_sizes=sizes, activations=["tanh"],
                    input_columns=cols, norm_specs=specs,
                    params=init_params(sizes, seed=0),
                    ).save(os.path.join(root, "models", "model0.nn"))
        with _Props(shifu_trace_sample="0", shifu_trace_slowMs="0",
                    shifu_loop_logSample="1.0",
                    shifu_serve_sloMs="60000"):
            server = ScoringServer(root=root, port=0)
            server.registry.warm([1])
            server.start()
            try:
                url = f"http://127.0.0.1:{server.port}"
                body = json.dumps(
                    {"records": [{c: "0.5" for c in cols}]}).encode()
                req = urllib.request.Request(
                    f"{url}/score", data=body,
                    headers={"Content-Type": "application/json",
                             "X-Shifu-Trace": "pin-trace-7"})
                with urllib.request.urlopen(req, timeout=60) as r:
                    doc = json.loads(r.read().decode())
                    assert r.headers.get("X-Shifu-Trace") == "pin-trace-7"
                assert doc["trace"] == "pin-trace-7"
                # headerless request under sample=0: measured (SLO armed)
                # but NOT retained
                req2 = urllib.request.Request(
                    f"{url}/score", data=body,
                    headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(req2, timeout=60) as r:
                    assert "trace" in json.loads(r.read().decode())
                with urllib.request.urlopen(f"{url}/admin/traces",
                                            timeout=10) as r:
                    at = json.loads(r.read().decode())
                assert at["count"] == 1
                assert at["traces"][0]["id"] == "pin-trace-7"
                assert set(at["traces"][0]["stages"]) >= {
                    "featurize", "route", "queue", "coalesce", "device",
                    "d2h", "serialize"}
                # SLO sections: healthz + gauge armed, both requests good
                with urllib.request.urlopen(f"{url}/healthz",
                                            timeout=10) as r:
                    h = json.loads(r.read().decode())
                assert h["slo"]["good"] == 2 and not h["slo"]["burning"]
                # shed path: the error reply still echoes the trace
                # header (correlating a 429 with its server-side trace
                # is when the link matters most), the forced-retention
                # trace is captured with status=rejected, and the shed
                # counts BAD against the SLO despite being fast
                server.scorer.fleet.close(5)
                req3 = urllib.request.Request(
                    f"{url}/score", data=body,
                    headers={"Content-Type": "application/json",
                             "X-Shifu-Trace": "pin-shed-1"})
                with pytest.raises(urllib.error.HTTPError) as ei:
                    urllib.request.urlopen(req3, timeout=60)
                assert ei.value.code == 429
                assert ei.value.headers.get(
                    "X-Shifu-Trace") == "pin-shed-1"
            finally:
                manifest = server.shutdown()
            m = json.load(open(manifest))
            assert m["traces"]["count"] == 2
            assert m["slo"] == dict(m["slo"], good=2, bad=1)
            tdoc = json.load(open(
                os.path.join(root, m["traces"]["path"])))
            assert tdoc["schema"] == reqtrace.TRACES_SCHEMA
            by_id = {s["id"]: s for s in tdoc["shifuTraces"]}
            assert set(by_id) == {"pin-trace-7", "pin-shed-1"}
            shed = by_id["pin-shed-1"]
            assert shed["attrs"]["status"] == "rejected"
            assert shed["attrs"].get("replica") is None  # never placed
            # traffic-log lineage: the row carries the trace id and
            # trace_lineage() reads it back
            from shifu_tpu.loop.traffic import trace_lineage

            lin = trace_lineage(root)
            assert lin["tracedRows"] >= 1
            assert "pin-trace-7" in lin["sampleTraceIds"]
            assert lin["rows"] == 2  # the headerless row logs empty


# ---------------------------------------------------------------------------
# SLO tracker
# ---------------------------------------------------------------------------


class TestSloTracker:
    def test_disabled_by_default(self):
        from shifu_tpu.serve.health import SloTracker

        t = SloTracker()
        assert not t.enabled
        t.observe(10.0)  # no-op, no counters
        assert t.burn_rate() == 0.0

    def test_good_bad_and_burn_rate(self):
        from shifu_tpu import obs
        from shifu_tpu.serve.health import SloTracker

        obs.reset()
        t = SloTracker(slo_ms=50.0, target=0.9)
        for _ in range(8):
            t.observe(0.010)   # good
        for _ in range(2):
            t.observe(0.200)   # bad
        c = obs.registry().snapshot()["counters"]
        assert c["serve.slo.good"] == 8.0
        assert c["serve.slo.bad"] == 2.0
        # bad fraction 0.2 over budget 0.1 -> burn rate 2.0
        assert t.burn_rate() == pytest.approx(2.0)
        snap = t.snapshot()
        assert snap["burning"] and snap["burnRate"] == pytest.approx(2.0)
        assert obs.registry().snapshot()["gauges"][
            "serve.slo.burn_rate"] == pytest.approx(2.0)

    def test_window_recovery(self):
        from shifu_tpu.serve.health import SloTracker

        t = SloTracker(slo_ms=50.0, target=0.9, window_s=0.05)
        t.observe(0.200)  # bad
        assert t.burn_rate() > 1.0
        time.sleep(0.08)  # the bad request ages out of the window
        assert t.burn_rate() == 0.0

    def test_failed_requests_count_bad_regardless_of_latency(
            self, models_dir):
        """A shed/failed request got NO score: it must burn SLO budget
        even though it completed in sub-millisecond time — otherwise a
        fleet shedding 90% of its traffic with fast 429s reads as
        healthy on exactly the overload the SLO exists to catch."""
        from shifu_tpu import obs
        from shifu_tpu.obs.reqtrace import RequestTrace
        from shifu_tpu.serve.health import SloTracker

        obs.reset()
        t = SloTracker(slo_ms=50.0, target=0.9)
        t.observe(0.001, ok=False)  # fast but failed
        c = obs.registry().snapshot()["counters"]
        assert c.get("serve.slo.bad") == 1.0
        assert "serve.slo.good" not in c
        # fleet seam: a trace carrying a `status` attr (the error
        # paths' marker) counts bad through finish_trace
        with _Props(shifu_serve_sloMs="60000", shifu_trace_sample="0",
                    shifu_trace_slowMs="0"):
            sc = _scorer(models_dir)
            tr = RequestTrace(sampled=False)
            tr.annotate(status="rejected")
            sc.fleet.finish_trace(tr)
            c = obs.registry().snapshot()["counters"]
            assert c.get("serve.slo.bad") == 1.0
            sc.close()

    def test_unrouted_trace_stage_label(self, models_dir):
        """A trace that never reached a replica labels its stage
        samples replica="unrouted", never an empty replica="" series."""
        from shifu_tpu import obs
        from shifu_tpu.obs.reqtrace import RequestTrace

        with _Props(shifu_trace_sample="1.0", shifu_trace_slowMs="0"):
            sc = _scorer(models_dir)
            tr = RequestTrace(sampled=True)
            tr.add_stage("featurize", 0.001)
            tr.annotate(status="rejected")
            sc.fleet.finish_trace(tr)
            sc.close()
            hists = obs.registry().snapshot()["histograms"]
            assert any('replica="unrouted"' in k for k in hists), hists
            assert not any('replica=""' in k for k in hists)


# ---------------------------------------------------------------------------
# span tracer: bounded ring + concurrent-thread Chrome export
# ---------------------------------------------------------------------------


class TestTracerRing:
    def test_max_events_ring_and_drop_counter(self):
        from shifu_tpu import obs
        from shifu_tpu.obs.tracing import Tracer

        obs.reset()
        tr = Tracer(max_events=4)
        for i in range(7):
            with tr.span(f"s{i}"):
                pass
        events = tr.events
        assert len(events) == 4
        assert [e["name"] for e in events] == ["s3", "s4", "s5", "s6"]
        assert tr.dropped == 3
        c = obs.registry().snapshot()["counters"]
        assert c.get("trace.dropped") == 3.0

    def test_max_events_knob(self):
        from shifu_tpu.obs.tracing import Tracer

        with _Props(shifu_trace_maxEvents="2"):
            tr = Tracer()
            assert tr.max_events == 2
            for i in range(3):
                with tr.span(f"s{i}"):
                    pass
            assert len(tr.events) == 2

    def test_concurrent_thread_export_round_trips(self, tmp_path):
        """Satellite pin: spans opened on router, batcher-worker and
        prefetch threads round-trip through the Chrome-trace export with
        correct per-thread tracks and parenting, and the exported file
        is valid Perfetto JSON."""
        from shifu_tpu.obs.tracing import Tracer

        tr = Tracer()
        barrier = threading.Barrier(3)
        tids = {}

        def worker(name):
            barrier.wait()
            with tr.span(f"{name}.outer", role=name):
                with tr.span(f"{name}.inner"):
                    time.sleep(0.002)
            tids[name] = threading.get_ident()

        threads = [threading.Thread(target=worker, args=(n,))
                   for n in ("router", "batcher-worker", "prefetch")]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        path = str(tmp_path / "spans.trace.json")
        assert tr.save(path) == path
        doc = json.load(open(path))  # valid JSON by construction
        assert set(doc) >= {"traceEvents", "displayTimeUnit"}
        events = doc["traceEvents"]
        assert len(events) == 6
        for e in events:  # Perfetto complete-event schema
            assert set(e) >= {"name", "ph", "ts", "dur", "pid", "tid",
                              "args"}
            assert e["ph"] == "X"
        for name in ("router", "batcher-worker", "prefetch"):
            mine = [e for e in events if e["name"].startswith(name)]
            assert len(mine) == 2
            # both spans recorded on THAT thread's track
            assert {e["tid"] for e in mine} == {tids[name]}
            inner = [e for e in mine if e["name"].endswith(".inner")][0]
            outer = [e for e in mine if e["name"].endswith(".outer")][0]
            # parenting: inner names its parent path; outer is a root
            assert inner["args"]["parent"] == f"{name}.outer"
            assert "parent" not in outer["args"]
            assert outer["args"]["role"] == name
            # the inner span nests temporally inside the outer one
            assert outer["ts"] <= inner["ts"]
            assert (inner["ts"] + inner["dur"]
                    <= outer["ts"] + outer["dur"] + 50)  # 50 µs slack


# ---------------------------------------------------------------------------
# exemplars: JSON + Prometheus round-trips
# ---------------------------------------------------------------------------


class TestExemplars:
    def test_exemplar_round_trips(self):
        from shifu_tpu.obs import MetricsRegistry, parse_prometheus

        reg = MetricsRegistry()
        h = reg.histogram("lat", buckets=(0.01, 0.1, 1.0), stage="device")
        h.observe(0.005, exemplar="tr-fast")
        h.observe(0.5, exemplar="tr-slow")
        h.observe(0.6)  # exemplar-less observe keeps the last id
        d = h.as_dict()
        assert d["exemplars"]["0"] == [0.005, "tr-fast"]
        assert d["exemplars"]["2"] == [0.5, "tr-slow"]
        prom = reg.to_prometheus()
        slow_line = [ln for ln in prom.splitlines()
                     if 'le="1.0"' in ln][0]
        assert '# {trace_id="tr-slow"} 0.5' in slow_line
        # the annotation never breaks the parser round-trip
        assert parse_prometheus(prom) == reg.flatten()
        # ...and the JSON round-trip is still lossless, exemplars incl.
        clone = MetricsRegistry.from_json(reg.to_json())
        assert clone.snapshot() == reg.snapshot()
        assert clone.to_prometheus() == prom

    def test_nan_observe_counts_no_bucket(self):
        """The bisect rewrite must keep the old linear scan's NaN
        semantics: a NaN observation lands in NO bucket (bisect alone
        would mis-place it in bucket 0)."""
        from shifu_tpu.obs.metrics import Histogram

        h = Histogram(buckets=(0.01, 1.0))
        h.observe(float("nan"))
        d = h.as_dict()
        assert sum(d["counts"]) == 0
        assert d["count"] == 1  # still counted in the totals

    def test_label_value_with_exemplar_lookalike_parses(self):
        """A user-supplied label value containing ' # ' (eval-set
        names escape only backslash and quote) must survive the
        exemplar strip — the strip anchors on the end-of-line exemplar
        shape, never a bare ' # '."""
        from shifu_tpu.obs import MetricsRegistry, parse_prometheus

        reg = MetricsRegistry()
        reg.counter("evals", set="a # b").inc(3)
        h = reg.histogram("lat", buckets=(1.0,), set="x # {y} z")
        h.observe(0.5, exemplar="tr-1")
        prom = reg.to_prometheus()
        assert parse_prometheus(prom) == reg.flatten()


# ---------------------------------------------------------------------------
# lineage: promote reads the retrain manifest's trace evidence
# ---------------------------------------------------------------------------


class TestPromoteLineage:
    def test_retrain_lineage_matches_candidate_sha(self, tmp_path):
        from shifu_tpu.loop.promote import retrain_lineage

        runs = tmp_path / ".shifu" / "runs"
        runs.mkdir(parents=True)
        for seq, cand in ((1, "aaaa"), (2, "bbbb")):
            (runs / f"retrain-{seq}.json").write_text(json.dumps({
                "step": "retrain", "seq": seq, "startedAtUnix": float(seq),
                "retrain": {
                    "parent": {"modelSetSha": "pppp"},
                    "candidate": {"modelSetSha": cand},
                    "source": {"kind": "traffic"},
                    "lineage": {"traceColumn": "shifu_trace",
                                "tracedRows": seq,
                                "sampleTraceIds": [f"t-{seq}"]},
                }}))
        lin = retrain_lineage(str(tmp_path), "aaaa")
        assert lin["candidateModelSetSha"] == "aaaa"
        assert lin["retrainManifest"] == "retrain-1.json"
        assert lin["traffic"]["sampleTraceIds"] == ["t-1"]
        # unknown sha: newest retrain wins
        lin = retrain_lineage(str(tmp_path), None)
        assert lin["candidateModelSetSha"] == "bbbb"
        # no match at all
        assert retrain_lineage(str(tmp_path), "cccc") is None


# ---------------------------------------------------------------------------
# ledger surfaces: runs --traces column
# ---------------------------------------------------------------------------


class TestRunsTracesColumn:
    def test_traces_column(self):
        from shifu_tpu.obs.ledger import format_runs

        manifests = [
            {"step": "serve", "seq": 1, "status": "ok",
             "elapsedSeconds": 1.0, "startedAt": "2026-08-04T00:00:00",
             "metrics": {},
             "traces": {"count": 3, "slowestMs": 12.5}},
            {"step": "train", "seq": 2, "status": "ok",
             "elapsedSeconds": 2.0, "startedAt": "2026-08-04T00:00:01",
             "metrics": {}},
        ]
        out = format_runs(manifests, show_traces=True)
        assert "TRACES" in out.splitlines()[0]
        assert "3@12.5ms" in out
        assert " - " in out  # trace-less runs show a dash
        plain = format_runs(manifests)
        assert "TRACES" not in plain
