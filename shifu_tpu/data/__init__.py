"""Columnar data ingest: header parsing, chunked CSV reads, row filtering."""

from shifu_tpu.data.reader import ColumnarData, read_header, read_columnar  # noqa: F401
