"""Sharded on-disk layout for normalized training data.

Replaces the reference's Pig-written text NormalizedData
(core/processor/NormalizeModelProcessor.java:183-252 + Normalize.pig): rows
become float32 .npy shards that memory-map straight into host RAM and feed
`jax.device_put` per mesh shard — no text re-parsing between norm and train.

Layout under PathFinder.normalized_data_dir():
    meta.json                 columns, n_rows, shard row counts, norm type
    features-SSSSS.npy        [rows_s, n_cols] float32
    tags-SSSSS.npy            [rows_s] int8   (1 pos / 0 neg)
    weights-SSSSS.npy         [rows_s] float32
and under cleaned_data_dir() (tree-model input, bin codes not z-scores):
    codes-SSSSS.npy           [rows_s, n_feat] int16
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass
from typing import Iterator, List, Optional, Tuple

import numpy as np


@dataclass
class NormMeta:
    columns: List[str]
    n_rows: int
    shard_rows: List[int]
    norm_type: str = "ZSCALE"
    extra: Optional[dict] = None

    def to_json(self) -> dict:
        return {
            "columns": self.columns,
            "nRows": self.n_rows,
            "shardRows": self.shard_rows,
            "normType": self.norm_type,
            "extra": self.extra or {},
        }

    @classmethod
    def from_json(cls, d: dict) -> "NormMeta":
        return cls(
            columns=list(d["columns"]),
            n_rows=int(d["nRows"]),
            shard_rows=[int(x) for x in d["shardRows"]],
            norm_type=d.get("normType", "ZSCALE"),
            extra=d.get("extra") or {},
        )


class ShardWriter:
    """Incremental shard-at-a-time writer — the streaming norm path emits
    one shard per ingest chunk, so peak memory is one chunk regardless of
    dataset size (MemoryDiskFloatMLDataSet's memory envelope, done the
    streaming way)."""

    def __init__(
        self,
        out_dir: str,
        primary_prefix: str,
        primary_dtype,
        columns: List[str],
        norm_type: str,
        extra: Optional[dict] = None,
    ):
        os.makedirs(out_dir, exist_ok=True)
        self.out_dir = out_dir
        self.primary_prefix = primary_prefix
        self.primary_dtype = primary_dtype
        self.columns = columns
        self.norm_type = norm_type
        self.extra = extra
        self.shard_rows: List[int] = []

    def add(self, primary: np.ndarray, tags: np.ndarray, weights: np.ndarray):
        s = len(self.shard_rows)
        np.save(os.path.join(self.out_dir, f"{self.primary_prefix}-{s:05d}.npy"),
                primary.astype(self.primary_dtype, copy=False))
        np.save(os.path.join(self.out_dir, f"tags-{s:05d}.npy"),
                tags.astype(np.int8, copy=False))
        np.save(os.path.join(self.out_dir, f"weights-{s:05d}.npy"),
                weights.astype(np.float32, copy=False))
        self.shard_rows.append(primary.shape[0])

    def close(self) -> NormMeta:
        if not self.shard_rows:
            # every chunk filtered empty: write one empty shard so loaders
            # get a clear zero-row dataset, not a missing-file crash
            n_cols = len(self.columns)
            self.add(
                np.zeros((0, n_cols), dtype=self.primary_dtype),
                np.zeros(0, dtype=np.int8),
                np.zeros(0, dtype=np.float32),
            )
        meta = NormMeta(
            columns=self.columns,
            n_rows=int(sum(self.shard_rows)),
            shard_rows=self.shard_rows,
            norm_type=self.norm_type,
            extra=self.extra,
        )
        with open(os.path.join(self.out_dir, "meta.json"), "w") as fh:
            json.dump(meta.to_json(), fh, indent=2)
        return meta


def _shard_slices(n_rows: int, n_shards: int) -> List[Tuple[int, int]]:
    base, rem = divmod(n_rows, n_shards)
    out, start = [], 0
    for s in range(n_shards):
        size = base + (1 if s < rem else 0)
        out.append((start, start + size))
        start += size
    return out


def _write_sharded(
    out_dir: str,
    primary_prefix: str,
    primary: np.ndarray,
    primary_dtype,
    tags: np.ndarray,
    weights: np.ndarray,
    columns: List[str],
    norm_type: str,
    n_shards: int,
    extra: Optional[dict],
) -> NormMeta:
    os.makedirs(out_dir, exist_ok=True)
    n = primary.shape[0]
    n_shards = max(1, min(n_shards, max(n, 1)))
    shard_rows = []
    for s, (a, b) in enumerate(_shard_slices(n, n_shards)):
        np.save(os.path.join(out_dir, f"{primary_prefix}-{s:05d}.npy"),
                primary[a:b].astype(primary_dtype, copy=False))
        np.save(os.path.join(out_dir, f"tags-{s:05d}.npy"),
                tags[a:b].astype(np.int8, copy=False))
        np.save(os.path.join(out_dir, f"weights-{s:05d}.npy"),
                weights[a:b].astype(np.float32, copy=False))
        shard_rows.append(b - a)
    meta = NormMeta(columns=columns, n_rows=n, shard_rows=shard_rows,
                    norm_type=norm_type, extra=extra)
    with open(os.path.join(out_dir, "meta.json"), "w") as fh:
        json.dump(meta.to_json(), fh, indent=2)
    return meta


def write_normalized(
    out_dir: str,
    features: np.ndarray,
    tags: np.ndarray,
    weights: np.ndarray,
    columns: List[str],
    norm_type: str = "ZSCALE",
    n_shards: int = 1,
    extra: Optional[dict] = None,
) -> NormMeta:
    return _write_sharded(out_dir, "features", features, np.float32, tags,
                          weights, columns, norm_type, n_shards, extra)


def write_codes(
    out_dir: str,
    codes: np.ndarray,
    tags: np.ndarray,
    weights: np.ndarray,
    columns: List[str],
    slots: List[int],
    n_shards: int = 1,
) -> NormMeta:
    """Tree-model input: int16 bin codes per feature + per-column slot counts.
    int16 covers the reference's 10k category cap; wider slots use int32."""
    code_dtype = np.int16 if (not slots or max(slots) < 2**15) else np.int32
    return _write_sharded(out_dir, "codes", codes, code_dtype, tags, weights,
                          columns, "CODES", n_shards, {"slots": slots})


def read_meta(data_dir: str) -> NormMeta:
    with open(os.path.join(data_dir, "meta.json")) as fh:
        return NormMeta.from_json(json.load(fh))


def _load_stack(data_dir: str, prefix: str, n_shards: int) -> np.ndarray:
    parts = [
        np.load(os.path.join(data_dir, f"{prefix}-{s:05d}.npy"), mmap_mode="r")
        for s in range(n_shards)
    ]
    return np.concatenate(parts, axis=0) if len(parts) > 1 else np.asarray(parts[0])


def load_normalized(
    data_dir: str,
) -> Tuple[NormMeta, np.ndarray, np.ndarray, np.ndarray]:
    """(meta, features[n, C] f32, tags[n] i8, weights[n] f32)."""
    meta = read_meta(data_dir)
    k = len(meta.shard_rows)
    feats = _load_stack(data_dir, "features", k)
    tags = _load_stack(data_dir, "tags", k)
    weights = _load_stack(data_dir, "weights", k)
    return meta, feats, tags, weights


def load_codes(
    data_dir: str,
) -> Tuple[NormMeta, np.ndarray, np.ndarray, np.ndarray]:
    """(meta, codes[n, C] i16, tags[n] i8, weights[n] f32)."""
    meta = read_meta(data_dir)
    k = len(meta.shard_rows)
    codes = _load_stack(data_dir, "codes", k)
    tags = _load_stack(data_dir, "tags", k)
    weights = _load_stack(data_dir, "weights", k)
    return meta, codes, tags, weights


def iter_shards(data_dir: str, prefix: str = "features") -> Iterator[np.ndarray]:
    meta = read_meta(data_dir)
    for s in range(len(meta.shard_rows)):
        yield np.load(os.path.join(data_dir, f"{prefix}-{s:05d}.npy"), mmap_mode="r")
