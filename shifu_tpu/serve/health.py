"""Serve health state machine: ok | degraded | draining, with a reason.

/healthz used to be a liveness ping; under the self-healing serve path it
is the load balancer's routing signal, so it must distinguish three
states the supervisor actually produces:

  ok        scoring normally.
  degraded  still scoring, but a worker crash was survived recently —
            the state a router uses to de-prioritize (not eject) a
            replica. Clears back to `ok` after `ok_after` consecutive
            clean batches.
  draining  not accepting new work (shutdown in progress, or the worker
            restart budget is exhausted) — /healthz returns 503 so the
            balancer stops routing here while in-flight work finishes.

Transitions are monotone toward draining: once draining, crash/ok notes
cannot resurrect the replica (a drained server restarts, it does not
heal). Every transition lands in `serve.health.transitions{to=...}` so
the run-ledger manifest carries the replica's health history.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Optional

from shifu_tpu.analysis.racetrack import guarded_by, tracked_lock
from shifu_tpu.utils import environment

OK = "ok"
DEGRADED = "degraded"
DRAINING = "draining"

DEFAULT_OK_AFTER = 3

DEFAULT_SLO_TARGET = 0.99
DEFAULT_SLO_WINDOW_S = 60.0
# rolling-window event bound: at 4096 requests the window estimate is
# already statistical, and the deque stays O(KB) at any uptime
SLO_WINDOW_EVENTS = 4096


def slo_ms_setting() -> float:
    """shifu.serve.sloMs — per-request latency SLO threshold in ms
    (0 = SLO accounting off)."""
    return environment.get_float("shifu.serve.sloMs", 0.0)


def slo_target_setting() -> float:
    """shifu.serve.sloTarget — the objective: the fraction of requests
    that must meet sloMs (burn rate is measured against 1 - target)."""
    return environment.get_float("shifu.serve.sloTarget",
                                 DEFAULT_SLO_TARGET)


class SloTracker:
    """Good/bad SLO accounting + burn rate over a rolling window.

    A request is GOOD when its end-to-end latency meets
    `-Dshifu.serve.sloMs`; good/bad land in the `serve.slo.good` /
    `serve.slo.bad` counters. `burn_rate()` is the classic SRE number:
    the bad fraction over the rolling window divided by the error
    budget (1 - target) — 1.0 means the budget burns exactly at the
    sustainable rate, above it /healthz carries an SLO reason."""

    def __init__(self, slo_ms: Optional[float] = None,
                 target: Optional[float] = None,
                 window_s: float = DEFAULT_SLO_WINDOW_S) -> None:
        self.slo_ms = slo_ms_setting() if slo_ms is None else float(slo_ms)
        target = slo_target_setting() if target is None else float(target)
        self.target = min(max(target, 0.0), 0.9999)
        self.window_s = float(window_s)
        self._lock = tracked_lock("serve.slo")
        self._events: deque = deque(maxlen=SLO_WINDOW_EVENTS)
        self._good = 0
        self._bad = 0

    @property
    def enabled(self) -> bool:
        return self.slo_ms > 0.0

    def observe(self, latency_s: float, ok: Optional[bool] = None) -> None:
        """Count one request. `ok=None` applies the latency test;
        `ok=False` forces a bad count — shed (429) and failed requests
        got NO score, which must burn budget rather than dilute the
        window as sub-millisecond "good" outcomes."""
        if not self.enabled:
            return
        from shifu_tpu.obs import registry

        if ok is None:
            ok = latency_s * 1e3 <= self.slo_ms
        with self._lock:
            self._events.append((time.perf_counter(), ok))
            if ok:
                self._good += 1
            else:
                self._bad += 1
        registry().counter("serve.slo.good" if ok else "serve.slo.bad").inc()

    def burn_rate(self, now: Optional[float] = None) -> float:
        """Bad fraction over the rolling window / (1 - target); exported
        as the `serve.slo.burn_rate` gauge on every read."""
        if not self.enabled:
            return 0.0
        from shifu_tpu.obs import registry

        if now is None:
            now = time.perf_counter()
        with self._lock:
            recent = [ok for t, ok in self._events
                      if now - t <= self.window_s]
        if not recent:
            rate = 0.0
        else:
            bad = sum(1 for ok in recent if not ok)
            rate = (bad / len(recent)) / max(1e-9, 1.0 - self.target)
        registry().gauge("serve.slo.burn_rate").set(rate)
        return rate

    def snapshot(self) -> dict:
        rate = self.burn_rate()
        with self._lock:
            return {
                "sloMs": self.slo_ms,
                "target": self.target,
                "windowSeconds": self.window_s,
                "good": self._good,
                "bad": self._bad,
                "burnRate": round(rate, 4),
                "burning": rate > 1.0,
            }


class HealthMonitor:
    """Thread-safe tri-state health with crash-recovery hysteresis.

    `labels` (typically {"replica": "<i>"}) ride the transition counter
    so a fleet's per-replica health histories stay separable in one
    metrics page; the fleet-level aggregation over these monitors lives
    in serve/fleet.py (`ReplicaFleet.health_snapshot`)."""

    def __init__(self, ok_after: int = DEFAULT_OK_AFTER,
                 labels: Optional[dict] = None) -> None:
        self._lock = tracked_lock("serve.health")
        self.labels = dict(labels or {})
        self._state = OK
        self._reason = ""
        self._ok_after = max(1, ok_after)
        self._ok_streak = 0
        self._crashes = 0
        self._sticky = False  # degrade that clean batches must NOT clear
        # the crash-caused degrade is tracked SEPARATELY from the sticky
        # (drift) one: the two can layer, and clearing the sticky overlay
        # must leave the crash degrade (and its hysteresis) underneath
        self._crash_degraded = False
        self._crash_reason = ""

    @guarded_by("_lock")
    def _transition(self, state: str, reason: str) -> None:
        # caller holds the lock (declared + race-checked via @guarded_by)
        if self._state == state:
            self._reason = reason
            return
        self._state = state
        self._reason = reason
        from shifu_tpu.obs import registry

        registry().counter("serve.health.transitions", to=state,
                           **self.labels).inc()

    def note_crash(self, reason: str) -> None:
        with self._lock:
            self._crashes += 1
            self._ok_streak = 0
            self._crash_degraded = True
            self._crash_reason = reason
            if self._state != DRAINING:
                self._transition(DEGRADED, reason)

    def note_degraded(self, reason: str) -> None:
        """Degrade WITHOUT counting a crash and WITHOUT the clean-batch
        hysteresis clearing it (the drift path: scoring is healthy, the
        MODEL is stale — only an operator action like `shifu promote`
        resolves it, via clear_degraded)."""
        with self._lock:
            self._sticky = True
            if self._state != DRAINING:
                self._transition(DEGRADED, reason)

    def clear_degraded(self) -> None:
        """Drop a sticky (non-crash) degrade — called after a hot-swap
        promoted a fresh model set. A crash-caused degrade is NOT
        cleared: scoring itself was failing, and only the clean-batch
        hysteresis (note_ok) may lift it — a promote must not route full
        traffic back onto a still-crashing replica."""
        with self._lock:
            was_sticky, self._sticky = self._sticky, False
            self._ok_streak = 0
            if self._state != DEGRADED or not was_sticky:
                return
            if self._crash_degraded:
                # the crash degrade layered UNDER the drift one survives:
                # scoring was failing, and only clean batches heal that
                self._reason = self._crash_reason
                return
            self._transition(OK, "")

    def note_ok(self) -> None:
        with self._lock:
            if self._state != DEGRADED or self._sticky:
                return
            self._ok_streak += 1
            if self._ok_streak >= self._ok_after:
                self._crash_degraded = False
                self._crash_reason = ""
                self._transition(OK, "")

    def set_draining(self, reason: str) -> None:
        with self._lock:
            self._transition(DRAINING, reason)

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    @property
    def reason(self) -> str:
        with self._lock:
            return self._reason

    @property
    def crashes(self) -> int:
        with self._lock:
            return self._crashes

    def snapshot(self) -> dict:
        with self._lock:
            return {"status": self._state, "reason": self._reason,
                    "workerCrashes": self._crashes}
