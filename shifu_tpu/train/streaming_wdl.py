"""Larger-than-memory WDL: stream dense + code shards per epoch.

Completes the streaming trio (NN: train/streaming.py, GBT/RF:
train/streaming_tree.py): the WDL epoch gradient is the sum of per-shard
gradients over (dense numeric slice, categorical code slice) pairs — the
NormalizedData and CleanedData shards are row-aligned because `shifu norm`
writes them in one pass. Full-batch BSP semantics match train_wdl exactly;
peak host memory is one (dense, codes) shard pair.
"""

from __future__ import annotations

import math
import os
from typing import List, Optional

import numpy as np

from shifu_tpu.models.wdl import (
    WDLParams,
    flatten_wdl,
    init_wdl_params,
    unflatten_wdl,
    unflatten_wdl_from_shapes,
    wdl_forward,
    wdl_shapes,
)
from shifu_tpu.norm.dataset import read_meta
from shifu_tpu.obs import profile
from shifu_tpu.train.updaters import make_updater
from shifu_tpu.train.wdl_trainer import WDLTrainConfig, WDLTrainResult
from shifu_tpu.utils.log import get_logger

log = get_logger(__name__)

_PROGRAMS: dict = {}


class WDLShardFeed:
    """Row-aligned (dense, codes) shard pairs, padded to one static shape;
    per-shard sampling masks drawn once like the NN ShardFeed."""

    def __init__(self, norm_dir: str, codes_dir: str, num_idx: List[int],
                 cat_idx: List[int], cfg: WDLTrainConfig, mesh=None):
        from shifu_tpu.train.nn_trainer import split_and_sample

        self.norm_dir = norm_dir
        self.codes_dir = codes_dir
        self.num_idx = list(num_idx)
        self.cat_idx = list(cat_idx)
        self.meta = read_meta(norm_dir)
        cmeta = read_meta(codes_dir)
        if cmeta.shard_rows != self.meta.shard_rows:
            raise ValueError(
                "NormalizedData and CleanedData shards are not row-aligned "
                "— re-run `shifu norm`"
            )
        self.n_shards = len(self.meta.shard_rows)
        self.pad_rows = max(self.meta.shard_rows) if self.meta.shard_rows else 0
        self.mesh = mesh
        if mesh is not None and self.pad_rows:
            from shifu_tpu.parallel.mesh import round_up_rows

            self.pad_rows = round_up_rows(self.pad_rows, mesh)
        self._sig = []
        for s, rows in enumerate(self.meta.shard_rows):
            cfg_s = WDLTrainConfig(
                **{**cfg.__dict__, "seed": cfg.seed * 100_003 + s}
            )
            sig, valid = split_and_sample(rows, cfg_s)
            w = np.load(os.path.join(norm_dir, f"weights-{s:05d}.npy"),
                        mmap_mode="r")
            self._sig.append((
                (sig * np.asarray(w)).astype(np.float32),
                (valid.astype(np.float32) * np.asarray(w)).astype(np.float32),
            ))
        self.n_train_size = float(
            max(sum(float((st > 0).sum()) for st, _ in self._sig), 1.0)
        )

    def _padded(self, a, pad, two_d=False):
        if pad == 0:
            return a
        return (np.pad(a, ((0, pad), (0, 0))) if two_d
                else np.pad(a, (0, pad)))

    def _load_host(self, s: int):
        """Disk read + column slice + pad on the prefetch thread."""
        rows = self.meta.shard_rows[s]
        pad = self.pad_rows - rows
        dense = np.asarray(np.load(
            os.path.join(self.norm_dir, f"features-{s:05d}.npy"),
            mmap_mode="r")[:, self.num_idx], np.float32)
        codes = np.asarray(np.load(
            os.path.join(self.codes_dir, f"codes-{s:05d}.npy"),
            mmap_mode="r")[:, self.cat_idx], np.int32)
        t = np.asarray(np.load(
            os.path.join(self.norm_dir, f"tags-{s:05d}.npy"),
            mmap_mode="r"), np.float32)
        sig_t, sig_v = self._sig[s]
        return (
            self._padded(dense, pad, True),
            self._padded(codes, pad, True),
            self._padded(t, pad),
            self._padded(sig_t, pad),
            self._padded(sig_v, pad),
        )

    def __iter__(self):
        # like the NN ShardFeed: shard s+1 loads on the prefetch thread
        # while shard s computes; the async device_put on consume keeps the
        # host->device copy under the caller's compute
        import jax

        from shifu_tpu.data.pipeline import prefetch_iter

        if self.mesh is not None:
            from shifu_tpu.parallel.mesh import shard_rows

            def put(a):
                return shard_rows(a, self.mesh)
        else:
            put = jax.device_put
        for arrs in prefetch_iter(range(self.n_shards),
                                  transform=self._load_host):
            yield tuple(put(a) for a in arrs)


def _get_shard_program(cfg: WDLTrainConfig, template: WDLParams):
    import jax
    import jax.numpy as jnp

    shapes = wdl_shapes(template)
    n_cat = len(template.embed)
    key = ("wdl-shard", tuple(shapes), n_cat, tuple(cfg.activations))
    prog = _PROGRAMS.get(key)
    if prog is not None:
        return prog

    def loss_fn(flat, dense, codes, t, sig):
        p = unflatten_wdl_from_shapes(flat, shapes, n_cat)
        prob = wdl_forward(p, dense, codes, cfg.activations)
        eps = 1e-7
        pc = jnp.clip(prob, eps, 1 - eps)
        ll = -(t * jnp.log(pc) + (1 - t) * jnp.log(1 - pc))
        return jnp.sum(sig * ll), prob

    grad_fn = jax.grad(loss_fn, has_aux=True)

    @jax.jit
    def shard_grad(flat, dense, codes, t, sig_t, sig_v):
        g_neg, prob = grad_fn(flat, dense, codes, t, sig_t)
        sq = (t - prob) ** 2
        tr_w = jnp.sum(sig_t)
        va_w = jnp.sum(sig_v)
        tr = jnp.sum(sig_t * sq)
        va = jnp.sum(sig_v * sq)
        return -g_neg, tr, va, tr_w, va_w

    _PROGRAMS[key] = shard_grad
    return shard_grad


def _wdl_stream_sha(cfg: WDLTrainConfig, feed: "WDLShardFeed",
                    num_idx: List[int], cat_idx: List[int],
                    vocab_sizes: List[int]) -> str:
    """Checkpoint-compatibility identity (hyperparams + shard layout +
    column split) — see train/streaming.py:_stream_train_sha."""
    from shifu_tpu.resilience.checkpoint import config_sha

    return config_sha({**{k: v for k, v in cfg.__dict__.items()
                          if not callable(v) and k != "progress_cb"},
                       "shardRows": list(feed.meta.shard_rows),
                       "numIdx": list(num_idx), "catIdx": list(cat_idx),
                       "vocab": list(vocab_sizes)})


def train_wdl_streamed(
    norm_dir: str,
    codes_dir: str,
    num_idx: List[int],
    cat_idx: List[int],
    vocab_sizes: List[int],
    cfg: WDLTrainConfig,
    init_flat: Optional[np.ndarray] = None,
    mesh=None,
    resume: bool = False,
) -> WDLTrainResult:
    """With a `mesh`, shards stream row-sharded over the `data` axis and
    XLA all-reduces each shard gradient — disk spill composes with the
    device mesh (AbstractNNWorker.java:485-494 runs the same spill inside
    every distributed worker)."""
    import jax.numpy as jnp

    feed = WDLShardFeed(norm_dir, codes_dir, num_idx, cat_idx, cfg,
                        mesh=mesh)
    template = init_wdl_params(
        len(num_idx), vocab_sizes, cfg.embed_dim, cfg.hidden, seed=cfg.seed
    )
    flat0 = flatten_wdl(template)
    if init_flat is not None and init_flat.size == flat0.size:
        flat0 = init_flat.astype(np.float32)

    shard_grad = _get_shard_program(cfg, template)
    init_state, apply_update = make_updater(
        cfg.optimizer if cfg.optimizer != "GD" else "B",
        momentum=0.0,
        reg=cfg.l2_reg,
        reg_level="L2" if cfg.l2_reg else "NONE",
    )
    flat = jnp.asarray(flat0)
    opt = init_state(flat0.size)
    nts = jnp.float32(feed.n_train_size)

    best_val = math.inf
    best_flat = np.asarray(flat)
    bad = 0
    tr_e = va_e = 0.0
    it_done = 0
    start_epoch = 0

    # preemption safety: full-state epoch checkpoint + bit-identical
    # resume, mirroring train/streaming.py (see the NN path for why the
    # snapshot includes optimizer leaves and best-weights bookkeeping)
    from jax import tree_util as jtu

    from shifu_tpu.resilience import checkpoint as ckpt_mod
    from shifu_tpu.resilience import faults

    ck = None
    if cfg.checkpoint_path and cfg.checkpoint_every:
        ck = ckpt_mod.StreamCheckpoint(
            cfg.checkpoint_path + ".state" + ckpt_mod.CKPT_SUFFIX,
            _wdl_stream_sha(cfg, feed, num_idx, cat_idx, vocab_sizes),
            every=0)
        if resume:
            loaded = ck.load()
            if loaded is not None:
                _ci, arrays, meta, _blob = loaded
                start_epoch = it_done = int(meta["epoch"])
                flat = jnp.asarray(arrays["flat"])
                leaves, treedef = jtu.tree_flatten(opt)
                opt = jtu.tree_unflatten(
                    treedef, [jnp.asarray(arrays[f"opt{i}"])
                              for i in range(len(leaves))])
                best_flat = np.asarray(arrays["bestFlat"])
                best_val = float(meta["bestVal"])
                bad = int(meta["bad"])
                tr_e, va_e = float(meta["trE"]), float(meta["vaE"])
                faults.survived("preempt")
                log.info("resuming streamed WDL at epoch %d", start_epoch)

    if mesh is not None:
        from shifu_tpu.parallel.mesh import replicate

        flat = replicate(flat, mesh)
        opt = replicate(opt, mesh)

    for it in range(start_epoch, cfg.num_epochs):
        faults.fault_point("epoch")
        g_sum = tr_sum = va_sum = tr_w = va_w = None
        for (dense, codes, t, sig_t, sig_v) in feed:
            g, trs, vas, trw, vaw = profile.dispatch(
                "wdl.shard_grad", shard_grad, flat, dense, codes, t,
                sig_t, sig_v, sync=False)
            if g_sum is None:
                g_sum, tr_sum, va_sum, tr_w, va_w = g, trs, vas, trw, vaw
            else:
                g_sum = g_sum + g
                tr_sum, va_sum = tr_sum + trs, va_sum + vas
                tr_w, va_w = tr_w + trw, va_w + vaw
        tr_e = float(tr_sum / jnp.maximum(tr_w, 1.0))
        va_e = float(va_sum / jnp.maximum(va_w, 1.0))
        if va_e < best_val:
            best_val = va_e
            best_flat = np.asarray(flat)
            bad = 0
        else:
            bad += 1
        flat, opt = apply_update(opt, flat, g_sum,
                                 jnp.float32(cfg.learning_rate),
                                 jnp.int32(it + 1), nts)
        it_done = it + 1
        if cfg.checkpoint_every and it_done % cfg.checkpoint_every == 0:
            if cfg.progress_cb:
                cfg.progress_cb(it_done, tr_e, va_e)
            if ck is not None:
                leaves, _ = jtu.tree_flatten(opt)
                arrays = {"flat": np.asarray(flat),
                          "bestFlat": np.asarray(best_flat)}
                arrays.update({f"opt{i}": np.asarray(leaf)
                               for i, leaf in enumerate(leaves)})
                ck.save(it_done, arrays=arrays, meta={
                    "epoch": it_done, "bestVal": best_val, "bad": bad,
                    "trE": tr_e, "vaE": va_e})
                ckpt_mod.atomic_save_npy(cfg.checkpoint_path,
                                         np.asarray(flat))
        if cfg.early_stop_window and bad >= cfg.early_stop_window:
            log.info("streamed WDL early stop at epoch %d", it_done)
            break

    if ck is not None:
        ck.clear()  # completed: nothing left to resume
    use_best = cfg.valid_set_rate > 0 and math.isfinite(best_val)
    chosen = best_flat if use_best else np.asarray(flat)
    params = unflatten_wdl(chosen, template)
    params = WDLParams(
        embed=[np.asarray(a) for a in params.embed],
        wide=[np.asarray(a) for a in params.wide],
        wide_dense=np.asarray(params.wide_dense),
        dense_layers=[{k: np.asarray(v) for k, v in l.items()}
                      for l in params.dense_layers],
        bias=np.asarray(params.bias),
    )
    log.info("streamed WDL done: %d epochs over %d shards, train %.6f "
             "valid %.6f", it_done, feed.n_shards, tr_e,
             best_val if use_best else va_e)
    return WDLTrainResult(
        params=params, train_error=tr_e,
        valid_error=best_val if use_best else va_e,
        iterations=it_done,
    )
