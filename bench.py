"""Benchmark: TPU training throughput vs a PINNED measured CPU baseline.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...extras}.

The reference publishes no numbers (BASELINE.md), so the baseline is
MEASURED: the same full-batch MLP train step (fwd + backprop, double
precision like Encog's path) in single-core numpy — what one reference
Hadoop worker does per iteration — scaled by the reference's nominal
100-worker cluster. vs_baseline > 1.0 means one TPU chip out-trains the
modeled 100-node Hadoop deployment.

Round-2 verdict fixes:
  * the baseline denominator is pinned in BASELINE_MEASURED.json (median of
    10 reps, measured once and checked in) — a fresh 3-rep measurement per
    run swung 3.5x and made vs_baseline meaningless. Re-measure explicitly
    with `python bench.py --remeasure-baseline`.
  * the TPU number is the median of N timed reps with the spread reported —
    single-shot timings on the shared/tunneled chip swung ~30%.
  * a compute-dense config (d=256, hidden 512/256) reports achieved GFLOP/s
    alongside the bandwidth-bound headline config.
  * the GBT histogram builder is benched too (row-trees/s).
"""

from __future__ import annotations

import json
import os
import statistics
import sys
import time

# single-core baseline: pin BLAS threads BEFORE numpy loads
os.environ.setdefault("OPENBLAS_NUM_THREADS", "1")
os.environ.setdefault("OMP_NUM_THREADS", "1")
os.environ.setdefault("MKL_NUM_THREADS", "1")

import numpy as np

N_REFERENCE_WORKERS = 100  # north-star cluster size (BASELINE.md)
BASELINE_FILE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "BASELINE_MEASURED.json")

SMALL = dict(d=30, hidden=[50], n=1_000_000, epochs=50)
DENSE = dict(d=256, hidden=[512, 256], n=250_000, epochs=20)


def _mlp_flops_per_row_epoch(d: int, hidden: list) -> float:
    """fwd+bwd ~= 3x the forward matmul cost; 2 flops per MAC."""
    sizes = [d] + list(hidden) + [1]
    macs = sum(a * b for a, b in zip(sizes[:-1], sizes[1:]))
    return 6.0 * macs


def numpy_worker_row_epochs_per_s(d: int, hidden: list, n: int = 20_000,
                                  reps: int = 10) -> float:
    """One Encog-worker-equivalent: full-batch fwd+backprop in float64.
    Median of `reps` to damp scheduler noise."""
    rng = np.random.default_rng(0)
    x = rng.normal(size=(n, d))
    t = (rng.random(n) < 0.5).astype(np.float64)
    sizes = [d] + list(hidden) + [1]
    ws = [rng.normal(size=(a, b)) * 0.1 for a, b in zip(sizes[:-1], sizes[1:])]
    bs = [np.zeros(b) for b in sizes[1:]]

    def step():
        hs = [x]
        for w, b in zip(ws[:-1], bs[:-1]):
            hs.append(np.tanh(hs[-1] @ w + b))
        z = hs[-1] @ ws[-1] + bs[-1]
        p = 1.0 / (1.0 + np.exp(-z[:, 0]))
        delta = ((t - p) * p * (1 - p))[:, None]
        acc = 0.0
        for li in range(len(ws) - 1, -1, -1):
            acc += (hs[li].T @ delta).sum()
            if li:
                delta = (delta @ ws[li].T) * (1 - hs[li] * hs[li])
        return acc

    step()  # warm caches
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        step()
        times.append(time.perf_counter() - t0)
    return n / statistics.median(times)


def load_or_measure_baseline(remeasure: bool = False) -> dict:
    configs = {"small": SMALL, "dense": DENSE}
    if not remeasure:
        if not os.path.isfile(BASELINE_FILE):
            # re-measuring silently would reintroduce the unstable-denominator
            # problem this file exists to fix
            raise SystemExit(
                f"{BASELINE_FILE} missing — it must be checked in; run "
                "`python bench.py --remeasure-baseline` once to regenerate")
        with open(BASELINE_FILE) as fh:
            base = json.load(fh)
        if base.get("configs") != configs:
            raise SystemExit(
                "BASELINE_MEASURED.json was measured for different bench "
                "configs — rerun `python bench.py --remeasure-baseline`")
        return base
    base = {
        "configs": configs,
        "note": ("single-core f64 numpy MLP fwd+bwd row-epochs/s per "
                 "reference worker; median of 10 reps; pinned so "
                 "vs_baseline is stable across runs"),
        "n_reference_workers": N_REFERENCE_WORKERS,
        "small_row_epochs_per_s": round(
            numpy_worker_row_epochs_per_s(SMALL["d"], SMALL["hidden"]), 1),
        "dense_row_epochs_per_s": round(
            numpy_worker_row_epochs_per_s(DENSE["d"], DENSE["hidden"],
                                          n=5_000), 1),
    }
    with open(BASELINE_FILE, "w") as fh:
        json.dump(base, fh, indent=2)
    return base


def _median_timed(fn, reps: int):
    """Median wall-clock of reps calls (fn must block until done)."""
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        times.append(time.perf_counter() - t0)
    return statistics.median(times), min(times), max(times)


def bench_nn(spec: dict, mixed_precision: bool, reps: int):
    import jax

    from shifu_tpu.train.nn_trainer import NNTrainConfig, train_nn

    rng = np.random.default_rng(0)
    n, d = spec["n"], spec["d"]
    x = rng.normal(size=(n, d)).astype(np.float32)
    logits = x[:, 0] * 1.5 - x[:, 1] + 0.5 * x[:, 2] * x[:, 3]
    t = (logits + rng.normal(scale=0.5, size=n) > 0).astype(np.float32)
    w = np.ones(n, dtype=np.float32)
    cfg = NNTrainConfig(
        hidden_nodes=list(spec["hidden"]),
        activations=["tanh"] * len(spec["hidden"]),
        propagation="R", num_epochs=spec["epochs"], valid_set_rate=0.1,
        seed=1, mixed_precision=mixed_precision,
    )
    x_dev = jax.device_put(x)
    t_dev = jax.device_put(t)
    # warmup compiles the program (epoch count is traced, so 2 epochs warm
    # the full run)
    warm = NNTrainConfig(**{**cfg.__dict__, "num_epochs": 2})
    train_nn(x_dev, t_dev, w, warm)
    med, lo, hi = _median_timed(lambda: train_nn(x_dev, t_dev, w, cfg), reps)
    row_epochs = n * spec["epochs"]
    return {
        "row_epochs_per_s": row_epochs / med,
        "spread": [round(row_epochs / hi, 1), round(row_epochs / lo, 1)],
        "gflops": row_epochs * _mlp_flops_per_row_epoch(d, spec["hidden"])
        / med / 1e9,
    }


def bench_gbt(reps: int):
    from shifu_tpu.train.tree_trainer import TreeTrainConfig, train_trees

    rng = np.random.default_rng(0)
    n, F, bins, trees = 1_000_000, 50, 32, 8
    codes = rng.integers(0, bins, size=(n, F)).astype(np.int16)
    y = (codes[:, 0] + codes[:, 1] + rng.integers(0, bins, size=n)
         > 1.5 * bins).astype(np.int8)
    w = np.ones(n, dtype=np.float32)
    slots = [bins + 1] * F
    cfg = TreeTrainConfig(algorithm="GBT", tree_num=trees, max_depth=6,
                          learning_rate=0.1, valid_set_rate=0.1, seed=3)
    cols = [f"f{i}" for i in range(F)]

    def run():
        train_trees(codes, y, w, slots, [False] * F, cols, cfg)

    run()  # warmup/compile
    med, lo, hi = _median_timed(run, reps)
    return {
        "row_trees_per_s": n * trees / med,
        "spread": [round(n * trees / hi, 1), round(n * trees / lo, 1)],
    }


def main() -> None:
    remeasure = "--remeasure-baseline" in sys.argv
    base = load_or_measure_baseline(remeasure)

    small = bench_nn(SMALL, mixed_precision=True, reps=5)
    dense = bench_nn(DENSE, mixed_precision=True, reps=3)
    gbt = bench_gbt(reps=3)

    denom = base["small_row_epochs_per_s"] * base["n_reference_workers"]
    dense_denom = base["dense_row_epochs_per_s"] * base["n_reference_workers"]
    print(json.dumps({
        "metric": "nn_train_row_epochs_per_s",
        "value": round(small["row_epochs_per_s"], 1),
        "unit": "row-epochs/s",
        "vs_baseline": round(small["row_epochs_per_s"] / denom, 4),
        "spread": small["spread"],
        "baseline_pinned": True,
        "dense": {
            "row_epochs_per_s": round(dense["row_epochs_per_s"], 1),
            "achieved_gflops": round(dense["gflops"], 1),
            "vs_baseline": round(dense["row_epochs_per_s"] / dense_denom, 4),
            "spread": dense["spread"],
        },
        "gbt": {
            "row_trees_per_s": round(gbt["row_trees_per_s"], 1),
            "spread": gbt["spread"],
        },
    }))


if __name__ == "__main__":
    main()
