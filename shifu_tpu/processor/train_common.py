"""Shared trainer-orchestration helpers for the NN and WDL processors.

The progress-line format is a CONTRACT (the reference's NNOutput progress
files are tailed by TailThread and parsed by downstream tooling,
TrainModelProcessor.java:1862) — it must exist in exactly one place.

Both writers ALSO record the per-epoch errors as registry time series
(train.train_error / train.valid_error labeled by trainer), so the run
manifest carries the full loss curve, not just the tail of a progress file.
"""

from __future__ import annotations

from typing import Callable, List


def progress_line(trainer_id: int, epoch: int, train_err: float,
                  valid_err: float) -> str:
    return (f"Trainer {trainer_id} Epoch #{epoch} "
            f"Train Error:{train_err:.8f} Validation Error:{valid_err:.8f}\n")


def record_epoch(trainer_id: int, epoch: int, train_err: float,
                 valid_err: float) -> None:
    """Per-epoch loss point -> registry series (resolved at call time so a
    step-boundary registry reset redirects recording transparently)."""
    from shifu_tpu.obs import registry

    reg = registry()
    reg.series("train.train_error", trainer=trainer_id).append(
        epoch, train_err)
    reg.series("train.valid_error", trainer=trainer_id).append(
        epoch, valid_err)


def progress_writer(path: str, trainer_id: int = 0,
                    echo: bool = True) -> Callable:
    """Single-trainer progress callback: (epoch, train_err, valid_err).
    `echo` mirrors the line to the console (the reference TailThread tails
    progress files to the console for interactive runs)."""
    from shifu_tpu.utils.log import get_logger

    log = get_logger(__name__)

    def cb(it, tr, va):
        with open(path, "a") as fh:
            fh.write(progress_line(trainer_id, it, tr, va))
        record_epoch(trainer_id, it, tr, va)
        if echo:
            log.info("trainer %d epoch %d train %.6f valid %.6f",
                     trainer_id, it, tr, va)

    return cb


def member_progress_writer(paths: List[str]) -> Callable:
    """Vmapped-member progress callback: ((member, epoch), tr, va)."""

    def cb(member_it, tr, va):
        i, it = member_it
        with open(paths[i], "a") as fh:
            fh.write(progress_line(i, it, tr, va))
        record_epoch(i, it, tr, va)

    return cb
