"""`shifu train` for GBT/RF — consumes the CleanedData bin codes.

Parity: TrainModelProcessor tree path (input = CleanedDataPath, not norm —
TrainModelProcessor.java:1366-1372) + DT param wiring (prepareDTParams:1312).
"""

from __future__ import annotations

import os

import numpy as np

from shifu_tpu.norm.dataset import load_codes
from shifu_tpu.utils.errors import ErrorCode, ShifuError
from shifu_tpu.utils.log import get_logger

log = get_logger(__name__)


def _pallas_fingerprint() -> str:
    """Resolved kernel lowering for the checkpoint fingerprint: what the
    process would actually run, not the raw knob string."""
    from shifu_tpu.ops.hist_pallas import pallas_active

    enabled, interpret = pallas_active()
    if not enabled:
        return "xla"
    return "pallas-interpret" if interpret else "pallas"


def train_tree_models(proc, alg) -> None:
    """proc: TrainProcessor (already set up)."""
    from shifu_tpu.norm.normalizer import norm_columns
    from shifu_tpu.train.tree_trainer import TreeTrainConfig, train_trees

    mc = proc.model_config
    codes_dir = proc.paths.cleaned_data_dir()
    if not os.path.isdir(codes_dir):
        raise ShifuError(
            ErrorCode.DATA_NOT_FOUND, f"{codes_dir} — run `shifu norm` first"
        )
    from shifu_tpu.train.streaming import should_stream_training

    stream = should_stream_training(codes_dir,
                                    force_attr=bool(mc.train.train_on_disk))
    if stream:
        # larger-than-memory: only tags materialize (tiny); the code
        # shards stream per level (train/streaming_tree.py)
        from shifu_tpu.norm.dataset import read_meta

        meta = read_meta(codes_dir)
        tags = np.concatenate([
            np.load(os.path.join(codes_dir, f"tags-{s:05d}.npy"))
            for s in range(len(meta.shard_rows))
        ]).astype(np.float32)
        codes = None
    else:
        meta, codes, tags, weights = load_codes(codes_dir)
        codes = np.asarray(codes, dtype=np.int32)
        tags = np.asarray(tags, dtype=np.float32)
        weights = np.asarray(weights, dtype=np.float32)
    slots = [int(s) for s in meta.extra["slots"]]

    cols = norm_columns(proc.column_configs)
    by_name = {c.column_name: c for c in cols}
    is_cat, boundaries, categories = [], [], []
    for name in meta.columns:
        cc = by_name.get(name)
        if cc is None:
            raise ShifuError(
                ErrorCode.DATA_NOT_FOUND,
                f"CleanedData column {name} is no longer selected in "
                f"ColumnConfig.json — re-run `shifu norm`",
            )
        # hybrid columns split like categoricals (their combined bin axis is
        # not totally ordered, so mean-sorted subset splits apply) but keep
        # BOTH binning tables so raw-record scoring can rebuild hybrid codes
        cat = cc.is_categorical() or cc.is_hybrid()
        is_cat.append(cat)
        boundaries.append(
            list(cc.column_binning.bin_boundary or [])
            if (not cc.is_categorical()) else None
        )
        categories.append(
            list(cc.column_binning.bin_category or []) if cat else None
        )

    suffix = proc._model_suffix(alg)
    proc.paths.ensure(proc.paths.models_dir())
    proc.paths.ensure(proc.paths.train_dir())
    bagging = max(1, int(mc.train.bagging_num or 1))

    # multi-class: ONEVSALL trains one binary forest per class (member k's
    # target is tag==k; eval thresholds per-class scores); NATIVE is
    # RF-only — per-class histogram counts, majority-vote leaves, per-tree
    # class votes at eval (TrainModelProcessor.java:341-349: "Only GBT and
    # RF and NN support OneVsAll", NATIVE "is supported in NN/RF").
    one_vs_all_tags = None
    if mc.is_multi_classification():
        if mc.train.is_one_vs_all():
            n_classes = len(mc.tags())
            if bagging not in (1, n_classes):
                log.warning("'train:baggingNum' overridden to %d for "
                            "ONEVSALL", n_classes)
            bagging = n_classes
            one_vs_all_tags = [
                (tags == k).astype(np.float32) for k in range(n_classes)
            ]
        elif alg.value not in ("RF", "DT"):
            raise ShifuError(
                ErrorCode.INVALID_MODEL_CONFIG,
                "NATIVE multi-class tree training is RF-only; use "
                "train.multiClassifyMethod=ONEVSALL for GBT "
                "(TrainModelProcessor.java:341-349)",
            )
        # RF NATIVE: tags stay class indices; TreeTrainConfig picks up
        # n_classes from the ModelConfig

    # row-shard the code matrix over every available chip (DTWorker shard
    # equivalent); histogram merge is the jit-inserted all-reduce
    import jax

    from shifu_tpu.parallel.mesh import data_mesh

    mesh = data_mesh() if len(jax.devices()) > 1 else None

    from shifu_tpu.models.tree import TreeModelSpec

    for i in range(bagging):
        cfg = TreeTrainConfig.from_model_config(mc, trainer_id=i)
        progress_path = proc.paths.progress_path(i)

        def progress(k, tr, va, _p=progress_path, _i=i):
            from shifu_tpu.processor.train_common import record_epoch

            record_epoch(_i, k, tr, va)  # per-tree series -> run manifest
            if k % 10 == 0 or k == 1:
                with open(_p, "a") as fh:
                    fh.write(f"Trainer {_i} Tree #{k} Train Error:{tr:.8f} "
                             f"Validation Error:{va:.8f}\n")
                log.info("trainer %d tree %d train %.6f valid %.6f",
                         _i, k, tr, va)

        # ---- per-tree checkpoint + resume (DTMaster.doCheckPoint:637,
        # recovery :284-291): a killed run restarts from the last
        # checkpointed tree, bit-equal thanks to per-tree RNG streams ----
        ck_dir = proc.paths.ensure(proc.paths.checkpoint_dir(i))
        ck_path = os.path.join(ck_dir, "trees.ckpt")
        ck_state_path = ck_path + ".json"
        ck_every = max(1, int(mc.train.get_param("CheckpointInterval", 10)))
        # full hyperparameter fingerprint: a leftover checkpoint from a
        # differently-configured run must NOT be silently grafted onto
        # this one (bit-equal resume is only meaningful for the same cfg)
        # data identity: a checkpoint built on a different binning (re-run
        # stats/norm) must not be grafted onto incompatible codes
        import hashlib
        import json as _json

        data_sig = hashlib.sha1(_json.dumps(
            [list(meta.columns), [int(s) for s in slots], boundaries,
             categories], sort_keys=True, default=str
        ).encode()).hexdigest()
        fingerprint = {
            "algorithm": cfg.algorithm, "loss": cfg.loss,
            "maxDepth": cfg.max_depth, "maxLeaves": cfg.max_leaves,
            "impurity": cfg.impurity, "learningRate": cfg.learning_rate,
            "dropoutRate": cfg.dropout_rate,
            "minInstancesPerNode": cfg.min_instances_per_node,
            "minInfoGain": cfg.min_info_gain,
            "featureSubsetStrategy": cfg.feature_subset_strategy,
            "baggingSampleRate": cfg.bagging_sample_rate,
            "baggingWithReplacement": cfg.bagging_with_replacement,
            "validSetRate": cfg.valid_set_rate, "seed": cfg.seed,
            "nClasses": cfg.n_classes,
            # lowering-affecting knobs: bit-equal resume only holds when
            # the resumed run picks the SAME histogram lowering (the
            # subtraction plan + node-batch budget are cfg-static, so
            # fingerprinting them records-and-replays the choice)
            "histSubtraction": cfg.hist_subtraction,
            "maxStatsMemoryMB": cfg.max_stats_memory_mb,
            # the Pallas fused kernel associates float sums differently
            # than the XLA lowering (and bf16 GBT planes round at build),
            # so a resume must replay under the SAME kernel choice
            "pallasLowering": _pallas_fingerprint(),
            "oneVsAll": bool(mc.train.is_one_vs_all()),
            "dataSignature": data_sig,
        }
        init_trees = None
        init_val_errors = None
        if os.path.isfile(ck_path):
            import json as _json

            try:
                ck_spec = TreeModelSpec.load(ck_path)
                state = {}
                if os.path.isfile(ck_state_path):
                    with open(ck_state_path) as fh:
                        state = _json.load(fh)
                if state.get("fingerprint") != fingerprint:
                    log.warning("checkpoint %s was built with different "
                                "hyperparameters; starting fresh", ck_path)
                elif len(ck_spec.trees) < cfg.tree_num:
                    init_trees = ck_spec.trees
                    init_val_errors = state.get("validErrors")
                    log.info("resuming trainer %d from checkpoint: %d trees",
                             i, len(init_trees))
            except Exception as e:  # corrupt checkpoint: fresh start
                log.warning("cannot resume from %s (%s)", ck_path, e)

        # ---- isContinuous: GBT keeps adding trees up to TreeNum
        # (TrainModelProcessor.java:1166-1184); RF starts from scratch ----
        if init_trees is None and mc.train.is_continuous:
            model_path = proc.paths.model_path(i, suffix)
            if cfg.algorithm != "GBT":
                log.warning("RF doesn't support continuous training")
            elif os.path.isfile(model_path):
                try:
                    old = TreeModelSpec.load(model_path)
                    if old.loss != cfg.loss:
                        log.warning("Loss changed, continuous training "
                                    "disabled; starting from scratch")
                    elif len(old.trees) >= cfg.tree_num:
                        log.info("model %d already has %d >= TreeNum trees; "
                                 "skipping", i, len(old.trees))
                        continue
                    else:
                        init_trees = old.trees
                        log.info("continuous training: model %d grows from "
                                 "%d trees", i, len(init_trees))
                except Exception as e:  # corrupt model: fresh start, logged
                    log.warning("cannot continue from %s (%s)", model_path, e)

        def checkpoint(k, trees_now, val_errs, _ck=ck_path,
                       _state=ck_state_path, _every=ck_every,
                       _fp=fingerprint):
            if k % _every == 0:
                from shifu_tpu.resilience.checkpoint import atomic_write_json

                TreeModelSpec(
                    algorithm=cfg.algorithm, trees=list(trees_now),
                    input_columns=list(meta.columns),
                    slots=[int(s) for s in slots],
                    boundaries=boundaries, categories=categories,
                    loss=cfg.loss, learning_rate=cfg.learning_rate,
                ).save(_ck)
                # atomic: a kill between the spec write and this state
                # write already falls back to fresh-start (fingerprint
                # check), but a TORN state file must never crash resume
                atomic_write_json(_state, {"fingerprint": _fp,
                                           "validErrors": list(val_errs)})

        tags_i = one_vs_all_tags[i] if one_vs_all_tags is not None else tags
        if stream:
            from shifu_tpu.train.streaming_tree import train_trees_streamed

            if (mc.train.is_continuous
                    and os.path.isfile(proc.paths.model_path(i, suffix))):
                raise ShifuError(
                    ErrorCode.INVALID_MODEL_CONFIG,
                    "isContinuous would overwrite the existing model: "
                    "continuous training is not streamed yet — raise "
                    "-Dshifu.train.memoryBudgetMB or disable "
                    "train.trainOnDisk",
                )
            if init_trees is not None:
                log.warning("streamed tree training starts fresh — "
                            "checkpoint resume needs the in-memory trainer")
            result = train_trees_streamed(
                codes_dir, slots, is_cat, meta.columns, cfg,
                tags_override=(one_vs_all_tags[i]
                               if one_vs_all_tags is not None else None),
                boundaries=boundaries, categories=categories,
                progress_cb=progress, mesh=mesh,
            )
        else:
            result = train_trees(
                codes, tags_i, weights, slots, is_cat, meta.columns, cfg,
                boundaries=boundaries, categories=categories,
                progress_cb=progress, mesh=mesh, init_trees=init_trees,
                init_valid_errors=init_val_errors, checkpoint_cb=checkpoint,
            )
        path = proc.paths.model_path(i, suffix)
        result.spec.save(path)
        for leftover in (ck_path, ck_state_path):
            if os.path.isfile(leftover):
                os.remove(leftover)  # completed: checkpoint no longer needed
        with open(proc.paths.val_error_path(i), "w") as fh:
            fh.write(f"{result.valid_error}\n")
        log.info("model %d (%s, %d trees) -> %s (valid err %.6f)",
                 i, cfg.algorithm, len(result.spec.trees), path,
                 result.valid_error)
