"""Histogram subtraction (build the smaller child, derive the sibling).

Contracts, per the train.params.treeHistSubtraction knob (default on):
  * RF histograms are integer sums in f32 (integer Poisson bag weights x
    0/1 labels), so derived = parent - built is EXACT and RF forests are
    BIT-EQUAL subtraction-on vs -off — binary and NATIVE multi-class,
    in-memory and streamed.
  * GBT moment planes carry float residuals: subtraction re-associates
    f32 summation, so GBT forests are TOLERANCE-equal (scores; a
    knife-edge zero-gain deep node may legitimately flip split/no-split,
    which the f64 accumulator chain removes when jax x64 is enabled).
  * A level-wise tree of depth D derives 2^(D-1) - 1 = leaves/2 - 1
    node-histograms (`tree.hist.derived`), builds 2^(D-1)
    (`tree.hist.built`) — vs 2^D - 1 built with subtraction off.
  * When the retained parent + child batch exceed the MaxStatsMemoryMB
    node-plane budget, the level falls back to a full rebuild and counts
    `tree.hist.fallback_rebuilds`; results must not change.
"""

import numpy as np
import pytest

from shifu_tpu import obs
from shifu_tpu.train.tree_trainer import (
    TreeTrainConfig,
    _node_batch_size,
    _sub_level_fits,
    make_layout,
    train_trees,
)


def _make_data(n=2500, f=5, bins=16, seed=0):
    rng = np.random.default_rng(seed)
    codes = rng.integers(0, bins, size=(n, f)).astype(np.int32)
    y = ((codes[:, 0] + codes[:, 1] + rng.integers(0, 8, n))
         > bins + 2).astype(np.float32)
    w = np.ones(n, np.float32)
    return codes, y, w, [bins] * f


def _cfg_off(cfg):
    return TreeTrainConfig(**{**cfg.__dict__, "hist_subtraction": False})


def _assert_forests_bit_equal(a, b):
    assert len(a.spec.trees) == len(b.spec.trees)
    for ta, tb in zip(a.spec.trees, b.spec.trees):
        np.testing.assert_array_equal(ta.feature, tb.feature)
        np.testing.assert_array_equal(ta.left_mask, tb.left_mask)
        np.testing.assert_allclose(ta.leaf_value, tb.leaf_value, atol=0)


def _hist_counters():
    snap = obs.registry().snapshot().get("counters", {})
    return {k.split(".")[-1]: v for k, v in snap.items()
            if k.startswith("tree.hist.")}


def test_rf_binary_bit_parity():
    """Integer count/moment planes subtract exactly: identical forests."""
    codes, y, w, slots = _make_data()
    cols = [f"c{i}" for i in range(len(slots))]
    cfg = TreeTrainConfig(algorithm="RF", tree_num=4, max_depth=4, seed=3,
                          feature_subset_strategy="TWOTHIRDS")
    on = train_trees(codes, y, w, slots, [False] * len(slots), cols, cfg)
    off = train_trees(codes, y, w, slots, [False] * len(slots), cols,
                      _cfg_off(cfg))
    _assert_forests_bit_equal(on, off)


def test_rf_multiclass_bit_parity():
    """NATIVE multi-class count planes are pure counts: exact too."""
    codes, y, w, slots = _make_data()
    y3 = (codes[:, 0] // 6).astype(np.float32)
    cols = [f"c{i}" for i in range(len(slots))]
    cfg = TreeTrainConfig(algorithm="RF", tree_num=3, max_depth=3, seed=2,
                          impurity="gini", n_classes=3)
    on = train_trees(codes, y3, w, slots, [False] * len(slots), cols, cfg)
    off = train_trees(codes, y3, w, slots, [False] * len(slots), cols,
                      _cfg_off(cfg))
    _assert_forests_bit_equal(on, off)


def test_gbt_tolerance_parity():
    """GBT derived moments re-associate f32: scores equal to tolerance."""
    codes, y, w, slots = _make_data()
    cols = [f"c{i}" for i in range(len(slots))]
    cfg = TreeTrainConfig(algorithm="GBT", tree_num=5, max_depth=4,
                          learning_rate=0.2, seed=3)
    on = train_trees(codes, y, w, slots, [False] * len(slots), cols, cfg)
    off = train_trees(codes, y, w, slots, [False] * len(slots), cols,
                      _cfg_off(cfg))
    s_on = on.spec.independent().compute(codes)
    s_off = off.spec.independent().compute(codes)
    np.testing.assert_allclose(s_on, s_off, atol=1e-3)
    assert on.valid_error == pytest.approx(off.valid_error, abs=1e-4)


def test_gbt_leafwise_tolerance_parity():
    """Leaf-wise growth derives the second frontier child from the
    retained parent histogram; scores must match the rebuild-both run."""
    codes, y, w, slots = _make_data()
    cols = [f"c{i}" for i in range(len(slots))]
    cfg = TreeTrainConfig(algorithm="GBT", tree_num=3, max_depth=6,
                          max_leaves=7, learning_rate=0.3, seed=5)
    on = train_trees(codes, y, w, slots, [False] * len(slots), cols, cfg)
    off = train_trees(codes, y, w, slots, [False] * len(slots), cols,
                      _cfg_off(cfg))
    s_on = on.spec.independent().compute(codes)
    s_off = off.spec.independent().compute(codes)
    np.testing.assert_allclose(s_on, s_off, atol=1e-3)


def test_counters_levelwise():
    """Per level-wise tree of depth D: derived = 2^(D-1) - 1 = leaves/2 - 1
    histograms, built = 2^(D-1); subtraction-off builds all 2^D - 1."""
    codes, y, w, slots = _make_data(n=1200)
    cols = [f"c{i}" for i in range(len(slots))]
    trees, depth = 3, 4
    cfg = TreeTrainConfig(algorithm="GBT", tree_num=trees, max_depth=depth,
                          seed=1)
    obs.reset()
    train_trees(codes, y, w, slots, [False] * len(slots), cols, cfg)
    c_on = _hist_counters()
    leaves = 2 ** depth
    assert c_on["derived"] == trees * (leaves // 2 - 1)
    assert c_on["built"] == trees * (leaves // 2)
    assert "fallback_rebuilds" not in c_on

    obs.reset()
    train_trees(codes, y, w, slots, [False] * len(slots), cols,
                _cfg_off(cfg))
    c_off = _hist_counters()
    assert c_off["built"] == trees * (leaves - 1)
    assert "derived" not in c_off
    # the acceptance ratio: subtraction builds ~half the node-histograms
    assert c_on["built"] / c_off["built"] <= 0.55


def test_counters_leafwise():
    """Each leaf-wise split sweeps ONE child histogram and derives the
    sibling: built = 1 root + n_splits, derived = n_splits."""
    codes, y, w, slots = _make_data(n=1200)
    cols = [f"c{i}" for i in range(len(slots))]
    cfg = TreeTrainConfig(algorithm="GBT", tree_num=1, max_depth=6,
                          max_leaves=6, seed=2)
    obs.reset()
    res = train_trees(codes, y, w, slots, [False] * len(slots), cols, cfg)
    c = _hist_counters()
    n_splits = int((res.spec.trees[0].feature >= 0).sum())
    assert n_splits >= 1
    assert c["derived"] == n_splits
    assert c["built"] == 1 + n_splits


def test_budget_pressure_fallback():
    """A wide layout under a tiny MaxStatsMemoryMB forces the batched path
    and the full-rebuild fallback; results must be unchanged and the
    fallback counted."""
    rng = np.random.default_rng(0)
    n = 1500
    slots = [4000, 16, 16]
    codes = np.stack([rng.integers(0, 4000, n), rng.integers(0, 16, n),
                      rng.integers(0, 16, n)], 1).astype(np.int32)
    y = ((codes[:, 1] + codes[:, 2] + rng.integers(0, 8, n))
         > 18).astype(np.float32)
    w = np.ones(n, np.float32)
    cols = [f"c{i}" for i in range(3)]
    depth = 5
    cfg = TreeTrainConfig(algorithm="GBT", tree_num=2, max_depth=depth,
                          seed=1, max_stats_memory_mb=1,
                          min_instances_per_node=2)
    lay = make_layout(slots, [False] * 3)
    cap = _node_batch_size(lay.T, cfg.max_stats_memory_mb)
    assert 2 ** depth > cap  # pins the host-driven batched path
    # the plan must be mixed: shallow levels subtract, deep levels fall back
    fits = [_sub_level_fits(2 ** d, cap, False) for d in range(1, depth + 1)]
    assert any(fits) and not all(fits)

    obs.reset()
    on = train_trees(codes, y, w, slots, [False] * 3, cols, cfg)
    c = _hist_counters()
    assert c["fallback_rebuilds"] >= 1
    assert c["derived"] >= 1
    off = train_trees(codes, y, w, slots, [False] * 3, cols, _cfg_off(cfg))
    s_on = on.spec.independent().compute(codes)
    s_off = off.spec.independent().compute(codes)
    np.testing.assert_allclose(s_on, s_off, atol=1e-3)


def test_streamed_levelwise_counters_and_rf_bit_parity(tmp_path):
    """The streamed level-wise grower derives every level >= 1 including
    the final leaf level; RF stays bit-equal across the knob."""
    from shifu_tpu.norm.dataset import write_codes
    from shifu_tpu.train.streaming_tree import train_trees_streamed

    rng = np.random.default_rng(0)
    n, f, bins = 2000, 5, 8
    codes = rng.integers(0, bins, size=(n, f)).astype(np.int32)
    y = ((codes[:, 0] + codes[:, 1] + rng.integers(0, 4, n))
         > 9).astype(np.float32)
    w = np.ones(n, np.float32)
    cols = [f"c{i}" for i in range(f)]
    out = str(tmp_path / "codes")
    write_codes(out, codes, y, w, cols, [bins] * f, n_shards=3)

    trees, depth = 2, 3
    cfg = TreeTrainConfig(algorithm="RF", tree_num=trees, max_depth=depth,
                          seed=3)
    obs.reset()
    on = train_trees_streamed(out, [bins] * f, [False] * f, cols, cfg)
    c = _hist_counters()
    # levels 1..D derive (incl. the final leaf level): 2^D - 1 per tree
    assert c["derived"] == trees * (2 ** depth - 1)
    assert c["built"] == trees * (2 ** depth)
    off = train_trees_streamed(out, [bins] * f, [False] * f, cols,
                               _cfg_off(cfg))
    _assert_forests_bit_equal(on, off)
