"""Runtime race sanitizer: ``-Dshifu.sanitize=race`` lock instrumentation.

The static concurrency pass (rules/concurrency.py) catches what the AST
can see — inconsistent nesting written in one file, blocking calls
syntactically inside a ``with lock:``. This module catches what only the
real thread interleavings can: the TSan analog for the host-side
coordination layer that PRs 5/7/9 grew (micro-batcher, traffic log,
drift monitor, hot-swap registry, prefetch workers).

Three instruments, all opt-in behind the ``race`` sanitizer mode:

  * ``tracked_lock(name)`` — the factory every ``self._lock =
    threading.Lock()`` site in the repo now calls. Unarmed it returns a
    **plain** ``threading.Lock`` (zero overhead — pinned in
    tests/test_racetrack.py and measured in the ``serve_latency``
    bench); armed it returns a ``TrackedLock`` that records, per
    thread, the stack of held locks with their acquisition sites, and
    on every nested acquisition adds an edge to a process-global
    lock-order graph. Two sites acquiring the same pair of lock *names*
    in opposite orders is a potential deadlock whether or not this run
    interleaved into one — the inversion is flagged the moment the
    second order is witnessed, with both witness sites in the verdict.
  * **long-hold detection** — a lock held longer than
    ``shifu.sanitize.race.holdMs`` (default 250) is recorded with its
    acquisition site. Long holds are the serve p99 killers (a device
    sync or file write under a lock every scoring thread needs);
    they're *reported*, not gated — ``clean`` stays true, matching the
    recompile watchdog's perf-bug-not-correctness-trap contract.
  * ``@guarded_by("_lock")`` — a method-level declaration that the
    named lock attribute must be held by the calling thread on entry
    (the repo's "caller holds the lock" docstring convention, made
    checkable). Unarmed the decorator returns the function untouched at
    call time beyond one flag read; armed, a violation is recorded with
    the lock name, attribute and method — recorded, and the verdict
    goes unclean, but the call proceeds (a sanitizer finding must not
    turn a survivable interleaving into an outage mid-serve).

Verdicts ride the existing ``shifu.sanitize/1`` ledger section:
``Sanitizer.verdict()`` (analysis/sanitize.py) embeds the tracker's
delta since the sanitizer was built, so every run manifest — lifecycle
steps, serve shutdown, bench scenarios — reports inversions /
guard violations / long holds exactly like transfer trips and
recompile breaches. CI's chaos/serve/loop smokes run with ``race``
armed and assert the sections clean (docs/ANALYSIS.md).
"""

from __future__ import annotations

import functools
import sys
import threading
import time
from typing import Dict, List, Optional, Tuple

from shifu_tpu.utils import environment
from shifu_tpu.utils.log import get_logger

log = get_logger(__name__)

DEFAULT_HOLD_MS = 250.0
# bounded event buffers: a pathological armed run must not grow without
# limit; counts keep incrementing past the cap, details stop
MAX_EVENTS = 100


def hold_ms_setting() -> float:
    """shifu.sanitize.race.holdMs — lock-hold duration (ms) above which
    an armed run records a long-hold event (0 disables)."""
    return environment.get_float("shifu.sanitize.race.holdMs",
                                 DEFAULT_HOLD_MS)


_forced: Optional[bool] = None  # test override (arm()/disarm())


def arm(on: bool = True) -> None:
    """Force arming on/off for this process (tests). ``arm(None)``
    restores environment-driven behavior."""
    global _forced
    _forced = on


def race_armed() -> bool:
    """Is the race mode armed? True when forced via arm(), else when
    -Dshifu.sanitize includes ``race`` (or ``all``). Checked at lock
    CONSTRUCTION time — arm the environment before building the objects
    whose locks you want tracked."""
    if _forced is not None:
        return _forced
    raw = (environment.get_property("shifu.sanitize", "") or "").lower()
    if not raw.strip():
        return False
    modes = {m.strip() for m in raw.split(",")}
    return "race" in modes or "all" in modes


_OWN_FILE = __file__


def _caller_site() -> str:
    """file:line of the nearest caller outside this module — the
    acquisition site the verdict quotes. One frame walk, no stack
    format: cheap enough for per-acquire use while armed."""
    f = sys._getframe(1)
    while f is not None and f.f_code.co_filename == _OWN_FILE:
        f = f.f_back
    if f is None:  # pragma: no cover - interpreter teardown
        return "?"
    path = f.f_code.co_filename
    short = path.split("shifu_tpu", 1)[-1] if "shifu_tpu" in path else path
    return f"{short}:{f.f_lineno} in {f.f_code.co_name}"


class RaceTracker:
    """Process-global witness state: per-thread held-lock stacks, the
    lock-order edge graph, and the three event classes."""

    def __init__(self) -> None:
        # plain Lock on purpose: the tracker must never track itself
        self._mu = threading.Lock()
        self._tls = threading.local()
        # (held_name, acquired_name) -> first witness "siteA -> siteB"
        self._edges: Dict[Tuple[str, str], str] = {}
        self.acquisitions = 0
        # counts are monotonic and NEVER capped — only the detail lists
        # stop growing (inversion details dedup per lock pair, the
        # others cap at MAX_EVENTS), so a delta-scoped verdict taken
        # late still reports every violation on its watch
        self.inversions: List[dict] = []
        self.inversion_count = 0
        self.long_holds: List[dict] = []
        self.long_hold_count = 0
        self.guard_violations: List[dict] = []
        self.guard_violation_count = 0

    # ---- per-thread held stack ----
    def _held(self) -> list:
        h = getattr(self._tls, "held", None)
        if h is None:
            h = []
            self._tls.held = h
        return h

    def held_names(self) -> List[str]:
        return [name for (_lk, name, _site, _t0) in self._held()]

    def holds(self, lock: "TrackedLock") -> bool:
        return any(lk is lock for (lk, _n, _s, _t) in self._held())

    # ---- witness recording ----
    def note_acquire(self, lock: "TrackedLock", site: str) -> None:
        held = self._held()
        b = lock.name
        inverted = 0
        with self._mu:
            self.acquisitions += 1
            for (_lk, a, asite, _t0) in held:
                if a == b:
                    # two same-named instances nested (e.g. two labeled
                    # metric locks): no order exists between instances
                    # of one name class, so no edge
                    continue
                edge = f"{asite} -> {site}"
                self._edges.setdefault((a, b), edge)
                rev = self._edges.get((b, a))
                if rev is not None:
                    # EVERY witnessed reversal counts (a sanitizer
                    # scoped after the first occurrence must still see
                    # a repeat on its watch); the detail dedups per pair
                    self.inversion_count += 1
                    inverted += 1
                    if not any(set(iv["locks"]) == {a, b}
                               for iv in self.inversions):
                        self.inversions.append({
                            "locks": sorted((a, b)),
                            "order": {f"{a} -> {b}": edge,
                                      f"{b} -> {a}": rev},
                            "thread": threading.current_thread().name,
                        })
        held.append((lock, b, site, time.perf_counter()))
        # the registry mirror acquires TRACKED metric locks, which
        # re-enter note_acquire -> self._mu: it must run after release
        for _ in range(inverted):
            self._count("inversions")

    def note_release(self, lock: "TrackedLock") -> None:
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] is lock:
                _lk, name, site, t0 = held.pop(i)
                budget = hold_ms_setting()
                if budget > 0:
                    ms = (time.perf_counter() - t0) * 1e3
                    if ms > budget:
                        with self._mu:
                            self.long_hold_count += 1
                            if len(self.long_holds) < MAX_EVENTS:
                                self.long_holds.append({
                                    "lock": name,
                                    "heldMs": round(ms, 2),
                                    "site": site,
                                    "thread":
                                        threading.current_thread().name,
                                })
                        self._count("long_holds")  # outside _mu: the
                        # mirror acquires tracked metric locks
                return
        # release of a lock this thread never tracked (acquired before
        # arming): nothing to unwind

    def note_guard_violation(self, lock_name: str, attr: str,
                             method: str) -> None:
        with self._mu:
            self.guard_violation_count += 1
            if len(self.guard_violations) < MAX_EVENTS:
                self.guard_violations.append({
                    "lock": lock_name,
                    "attr": attr,
                    "method": method,
                    "held": self.held_names(),
                    "thread": threading.current_thread().name,
                })
        self._count("guard_violations")  # outside _mu (tracked locks)

    def _count(self, kind: str) -> None:
        # mirrored into the metrics registry (like sanitizer.* trips) so
        # /metrics and ledger counter tables see race activity without
        # parsing verdicts; lazy import keeps this module jax/obs-free
        # until a violation actually happens
        try:
            from shifu_tpu.obs import registry

            registry().counter(f"sanitizer.race.{kind}").inc()
        except Exception as e:  # a broken registry must not break the tracker
            log.debug("race tracker: cannot mirror %s counter: %s",
                      kind, e)

    # ---- verdict plumbing (delta-scoped, like fault counters) ----
    def mark(self) -> Tuple[int, int, int, int]:
        with self._mu:
            return (self.inversion_count, self.long_hold_count,
                    self.guard_violation_count, self.acquisitions)

    def verdict(self, mark: Optional[Tuple[int, int, int, int]] = None
                ) -> dict:
        i0, h0, g0, a0 = mark or (0, 0, 0, 0)
        with self._mu:
            # counts come from the uncapped counters; event details
            # past MAX_EVENTS were dropped, so a mark taken after the
            # cap slices an empty detail delta while the count delta
            # still reports every violation
            return {
                "acquisitions": self.acquisitions - a0,
                "inversions": self.inversion_count - i0,
                "inversionEvents": [
                    dict(e) for e in self.inversions[
                        min(i0, len(self.inversions)):]],
                "guardViolations": self.guard_violation_count - g0,
                "guardViolationEvents": [
                    dict(e) for e in self.guard_violations[
                        min(g0, len(self.guard_violations)):]],
                "holdMsBudget": hold_ms_setting(),
                "longHolds": self.long_hold_count - h0,
                "longHoldEvents": [
                    dict(e) for e in self.long_holds[
                        min(h0, len(self.long_holds)):]],
            }

    def reset(self) -> None:
        """Tests only: a fresh graph + event lists (held stacks are
        per-thread and drain naturally)."""
        with self._mu:
            self._edges.clear()
            self.inversions = []
            self.inversion_count = 0
            self.long_holds = []
            self.long_hold_count = 0
            self.guard_violations = []
            self.guard_violation_count = 0
            self.acquisitions = 0


_TRACKER = RaceTracker()


def tracker() -> RaceTracker:
    return _TRACKER


class TrackedLock:
    """threading.Lock with acquisition witnessing (armed mode only)."""

    __slots__ = ("name", "_lock")

    def __init__(self, name: str) -> None:
        self.name = name
        self._lock = threading.Lock()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._lock.acquire(blocking, timeout)
        if ok:
            _TRACKER.note_acquire(self, _caller_site())
        return ok

    def release(self) -> None:
        _TRACKER.note_release(self)
        self._lock.release()

    def locked(self) -> bool:
        return self._lock.locked()

    def __enter__(self) -> "TrackedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> bool:
        self.release()
        return False

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"TrackedLock({self.name!r}, locked={self.locked()})"


def tracked_lock(name: str):
    """The lock factory every ``_lock`` site uses: a plain
    ``threading.Lock`` when the race mode is unarmed (zero overhead —
    the common case), a ``TrackedLock`` carrying `name` when armed.
    `name` identifies the lock *class* (e.g. ``"loop.traffic"``), not
    the instance: the lock-order graph is over name classes, which is
    exactly the granularity a deadlock argument needs."""
    if race_armed():
        return TrackedLock(name)
    return threading.Lock()


def guarded_by(lock_attr: str):
    """Declare that a method may only run with ``self.<lock_attr>``
    held by the calling thread (the "caller holds the lock" docstring
    convention, made checkable). Unarmed: one flag read per call.
    Armed: a violation is recorded in the tracker (and the sanitizer
    verdict goes unclean) but the call proceeds — sanitizer findings
    report, they don't convert survivable interleavings into outages.

    The static pass reads the decorator too: a ``@guarded_by``-declared
    method is exempt from SH201's with-lock requirement (its callers
    carry the obligation)."""

    def deco(fn):
        qual = getattr(fn, "__qualname__", fn.__name__)

        @functools.wraps(fn)
        def wrapper(self, *args, **kwargs):
            if race_armed():
                lock = getattr(self, lock_attr, None)
                if isinstance(lock, TrackedLock):
                    if not _TRACKER.holds(lock):
                        _TRACKER.note_guard_violation(
                            lock.name, lock_attr, qual)
                elif lock is not None and hasattr(lock, "locked"):
                    # plain lock (constructed before arming): the best
                    # checkable claim is "held by someone"
                    if not lock.locked():
                        _TRACKER.note_guard_violation(
                            f"<untracked {lock_attr}>", lock_attr, qual)
            return fn(self, *args, **kwargs)

        wrapper.__shifu_guarded_by__ = lock_attr
        return wrapper

    return deco
