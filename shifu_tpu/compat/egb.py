"""Reference binary NN model-spec compatibility (BinaryNNSerializer format).

Byte-compatible reader/writer for the gzip stream written by
core/dtrain/nn/BinaryNNSerializer.java:46 and loaded by
nn/IndependentNNModel.loadFromStream (IndependentNNModel.java:540):

    int NN_FORMAT_VERSION(=1); string normType; int nStats;
    NNColumnStats[nStats] (nn/NNColumnStats.java write());
    int nMap; (int columnNum, int index)[nMap];
    int nNetworks; PersistBasicFloatNetwork[n]
    (core/dtrain/dataset/PersistBasicFloatNetwork.saveNetwork:280).

Scoring normalizes RAW values internally per normType exactly like
IndependentNNModel.convertDataMapToDoubleArray (:262), then forwards the
Encog flat network (vectorized here, see compat/encog.py).
"""

from __future__ import annotations

import gzip
import io
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

from shifu_tpu.compat.encog import EncogNetwork
from shifu_tpu.compat.javaio import JavaDataInput, JavaDataOutput

NN_FORMAT_VERSION = 1  # CommonConstants.NN_FORMAT_VERSION
COLUMN_TYPE_BYTES = {"A": 0, "N": 1, "C": 2, "H": 3}  # ColumnType.java:19
COLUMN_TYPE_NAMES = {v: k for k, v in COLUMN_TYPE_BYTES.items()}
DEFAULT_CUTOFF = 4.0  # Normalizer.STD_DEV_CUTOFF


@dataclass
class RefNNColumnStats:
    """Mirror of nn/NNColumnStats.java (write/readFields)."""

    column_num: int
    column_name: str
    column_type: str  # N | C | H | A
    cutoff: float = DEFAULT_CUTOFF
    mean: float = 0.0
    stddev: float = 1.0
    woe_mean: float = 0.0
    woe_stddev: float = 1.0
    woe_wgt_mean: float = 0.0
    woe_wgt_stddev: float = 1.0
    bin_boundaries: List[float] = field(default_factory=list)
    bin_categories: List[str] = field(default_factory=list)
    bin_pos_rates: List[float] = field(default_factory=list)
    bin_count_woes: List[float] = field(default_factory=list)
    bin_weight_woes: List[float] = field(default_factory=list)

    def write(self, do: JavaDataOutput) -> None:
        do.write_int(self.column_num)
        do.write_string(self.column_name)
        do.write_byte(COLUMN_TYPE_BYTES[self.column_type])
        for v in (self.cutoff, self.mean, self.stddev, self.woe_mean,
                  self.woe_stddev, self.woe_wgt_mean, self.woe_wgt_stddev):
            do.write_double(float(v))
        do.write_double_array(self.bin_boundaries)
        do.write_int(len(self.bin_categories))
        for cat in self.bin_categories:
            do.write_string(cat)
        do.write_double_array(self.bin_pos_rates)
        do.write_double_array(self.bin_count_woes)
        do.write_double_array(self.bin_weight_woes)

    @classmethod
    def read(cls, di: JavaDataInput) -> "RefNNColumnStats":
        cs = cls(column_num=di.read_int(), column_name=di.read_string(),
                 column_type=COLUMN_TYPE_NAMES[di.read_byte()])
        (cs.cutoff, cs.mean, cs.stddev, cs.woe_mean, cs.woe_stddev,
         cs.woe_wgt_mean, cs.woe_wgt_stddev) = (di.read_double() for _ in range(7))
        cs.bin_boundaries = di.read_double_array()
        cs.bin_categories = [di.read_string() for _ in range(di.read_int())]
        cs.bin_pos_rates = di.read_double_array()
        cs.bin_count_woes = di.read_double_array()
        cs.bin_weight_woes = di.read_double_array()
        return cs


def read_float_network(di: JavaDataInput) -> EncogNetwork:
    """PersistBasicFloatNetwork.readNetwork (:199) stream image."""
    props = {di.read_string(): di.read_string() for _ in range(di.read_int())}
    di.read_int()  # beginTraining
    di.read_double()  # connectionLimit
    di.read_int_array()  # contextTargetOffset
    di.read_int_array()  # contextTargetSize
    di.read_int()  # endTraining
    di.read_boolean()  # hasContext
    di.read_int()  # inputCount
    layer_counts = di.read_int_array()
    layer_feed = di.read_int_array()
    di.read_int_array()  # layerContextCount
    di.read_int_array()  # layerIndex
    di.read_double_array()  # layerOutput snapshot
    di.read_int()  # outputCount
    di.read_int_array()  # weightIndex
    weights = np.array(di.read_double_array(), dtype=np.float64)
    bias_act = di.read_double_array()
    n_act = di.read_int()
    acts, act_params = [], []
    for _ in range(n_act):
        acts.append(di.read_string())
        act_params.append(di.read_double_array())
    feature_set = [di.read_int() for _ in range(di.read_int())]
    return EncogNetwork(
        layer_counts=layer_counts, layer_feed_counts=layer_feed, weights=weights,
        activations=acts, activation_params=act_params, bias_activation=bias_act,
        properties=props, feature_set=feature_set,
    )


def write_float_network(do: JavaDataOutput, net: EncogNetwork) -> None:
    """PersistBasicFloatNetwork.saveNetwork (:280) stream image."""
    do.write_int(len(net.properties))
    for k, v in net.properties.items():
        do.write_string(k)
        do.write_string(v)
    n = len(net.layer_counts)
    do.write_int(0)  # beginTraining
    do.write_double(0.0)  # connectionLimit
    do.write_int_array([0] * n)  # contextTargetOffset
    do.write_int_array([0] * n)  # contextTargetSize
    do.write_int(n - 1)  # endTraining
    do.write_boolean(False)  # hasContext
    do.write_int(net.input_count)
    do.write_int_array(net.layer_counts)
    do.write_int_array(net.layer_feed_counts)
    do.write_int_array([0] * n)  # layerContextCount
    do.write_int_array(net.layer_index)
    do.write_double_array(net.default_layer_output())
    do.write_int(net.output_count)
    do.write_int_array(net.weight_index)
    do.write_double_array(list(net.weights))
    do.write_double_array(net.bias_activation)
    do.write_int(len(net.activations))
    for name, params in zip(net.activations, net.activation_params):
        do.write_string(name)
        do.write_double_array(params)
    do.write_int(len(net.feature_set))
    for f in net.feature_set:
        do.write_int(f)


@dataclass
class RefNNModel:
    """In-memory image of the reference IndependentNNModel."""

    norm_type: str
    column_stats: List[RefNNColumnStats]
    column_mapping: Dict[int, int]  # columnNum -> input index
    networks: List[EncogNetwork]
    version: int = NN_FORMAT_VERSION

    def _stats_by_num(self) -> Dict[int, RefNNColumnStats]:
        return {cs.column_num: cs for cs in self.column_stats}

    # -- normalization (parity IndependentNNModel.java:262-540) -------------
    def _zscore(self, v: float, mean: float, std: float, cutoff: float) -> float:
        if std < 1e-12:
            std = 1e-12
        z = (v - mean) / std
        return float(np.clip(z, -cutoff, cutoff))

    def _numeric_bin(self, bounds: List[float], v: Optional[float]) -> int:
        if v is None or np.isnan(v):
            return -1
        idx = 0
        for i, b in enumerate(bounds):
            if v >= b:
                idx = i
            else:
                break
        return idx

    def _norm_one(self, cs: RefNNColumnStats, obj) -> float:
        nt = self.norm_type.upper()
        is_weighted = nt.startswith("WEIGHT_")
        base = nt[len("WEIGHT_"):] if is_weighted else nt

        def parse_num():
            try:
                v = float(obj)
                return None if np.isnan(v) else v
            except (TypeError, ValueError):
                return None

        if cs.column_type == "C":
            cat_idx = {c: i for i, c in enumerate(cs.bin_categories)}
            key = "" if obj is None else str(obj)
            j = cat_idx.get(key, len(cs.bin_categories) - 1 if "" in cat_idx else -1)
            if j < 0:
                j = len(cs.bin_pos_rates) - 1  # missing bin is last
            if base in ("WOE", "HYBRID"):
                woes = cs.bin_weight_woes if is_weighted else cs.bin_count_woes
                return woes[j]
            if base in ("WOE_ZSCORE", "WOE_ZSCALE"):
                woes = cs.bin_weight_woes if is_weighted else cs.bin_count_woes
                mean = cs.woe_wgt_mean if is_weighted else cs.woe_mean
                std = cs.woe_wgt_stddev if is_weighted else cs.woe_stddev
                return self._zscore(woes[j], mean, std, cs.cutoff)
            pos_rate = cs.bin_pos_rates[j]
            if base in ("OLD_ZSCALE", "OLD_ZSCORE"):
                return pos_rate
            return self._zscore(pos_rate, cs.mean, cs.stddev, cs.cutoff)
        # numeric / hybrid
        if base in ("WOE", "WOE_ZSCORE", "WOE_ZSCALE"):
            v = parse_num()
            j = self._numeric_bin(cs.bin_boundaries, v)
            woes = cs.bin_weight_woes if is_weighted else cs.bin_count_woes
            woe = woes[j] if j >= 0 else woes[-1]
            if base == "WOE":
                return woe
            mean = cs.woe_wgt_mean if is_weighted else cs.woe_mean
            std = cs.woe_wgt_stddev if is_weighted else cs.woe_stddev
            return self._zscore(woe, mean, std, cs.cutoff)
        v = parse_num()
        if v is None:
            v = cs.mean
        return self._zscore(v, cs.mean, cs.stddev, cs.cutoff)

    def normalize_rows(self, rows: List[Dict[str, object]]) -> np.ndarray:
        """Raw (columnName -> value) maps -> normalized [n, inputs]."""
        stats = self._stats_by_num()
        data = np.zeros((len(rows), len(self.column_mapping)), dtype=np.float64)
        for col_num, idx in self.column_mapping.items():
            cs = stats.get(col_num)
            if cs is None:
                continue
            for i, row in enumerate(rows):
                data[i, idx] = self._norm_one(cs, row.get(cs.column_name))
        return data

    def compute(self, data: np.ndarray) -> np.ndarray:
        """Normalized [n, inputs] -> averaged network output [n]
        (parity IndependentNNModel.compute:211)."""
        outs = [net.compute(data) for net in self.networks]
        stacked = np.stack([o if o.ndim == 1 else o[:, 0] for o in outs], axis=0)
        return stacked.mean(axis=0)

    def compute_raw(self, rows: List[Dict[str, object]]) -> np.ndarray:
        return self.compute(self.normalize_rows(rows))


def read_nn_model(data: bytes) -> RefNNModel:
    """Parse BinaryNNSerializer .nn bytes (gzip-sniffing)."""
    if data[:2] == b"\x1f\x8b":
        data = gzip.decompress(data)
    di = JavaDataInput(io.BytesIO(data))
    version = di.read_int()
    norm_type = di.read_string()
    stats = [RefNNColumnStats.read(di) for _ in range(di.read_int())]
    mapping = {di.read_int(): di.read_int() for _ in range(di.read_int())}
    networks = [read_float_network(di) for _ in range(di.read_int())]
    return RefNNModel(norm_type, stats, mapping, networks, version)


def write_nn_model(model: RefNNModel, compress: bool = True) -> bytes:
    """Serialize to the BinaryNNSerializer stream (gzip by default)."""
    raw = io.BytesIO()
    do = JavaDataOutput(raw)
    do.write_int(NN_FORMAT_VERSION)
    do.write_string(model.norm_type)
    do.write_int(len(model.column_stats))
    for cs in model.column_stats:
        cs.write(do)
    do.write_int(len(model.column_mapping))
    for col, idx in model.column_mapping.items():
        do.write_int(col)
        do.write_int(idx)
    do.write_int(len(model.networks))
    for net in model.networks:
        write_float_network(do, net)
    payload = raw.getvalue()
    return gzip.compress(payload) if compress else payload


def woe_mean_stddev(woes: List[float], pos: List[int], neg: List[int]):
    """Parity Normalizer.calculateWoeMeanAndStdDev (Normalizer.java:758):
    count-weighted mean/std over bins."""
    counts = np.array([p + n for p, n in zip(pos, neg)], dtype=np.float64)
    woes_a = np.array(woes, dtype=np.float64)
    total = counts.sum()
    if total <= 1:
        return 0.0, 1.0
    s = float((woes_a * counts).sum())
    sq = float((woes_a * woes_a * counts).sum())
    mean = s / total
    std = float(np.sqrt(abs((sq - s * s / total) / (total - 1))))
    return mean, std
