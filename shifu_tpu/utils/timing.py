"""Stage wall-clock timers — compatibility seam over shifu_tpu.obs.

PR 1 introduced StageTimers here as a standalone ad-hoc accumulator; PR 2
absorbed it into the unified metrics registry (shifu_tpu/obs/metrics.py) as
the Timer kind, with StageTimers kept as the multi-stage facade. Importing
from this module keeps working; registry-backed construction
(`MetricsRegistry.stage_timers(prefix)`) additionally lands the timings in
the step's run manifest.
"""

from __future__ import annotations

from shifu_tpu.obs.metrics import StageTimers

__all__ = ["StageTimers"]
