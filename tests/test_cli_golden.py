"""Full-CLI golden over the reference's cancer-judgement tutorial set.

The reference's strongest e2e anchor is ShifuCLITest.java:102-210:
init -> stats -> norm -> varsel -> train -> eval over
DataStore/DataSet1 with the checked-in ModelStore/ModelSet1 ModelConfig.
The reference test asserts step artifacts exist; it checks in no eval
numbers, so the AUC pin here is a floor on the well-known WDBC task
(the reference's own bundled EG models score ~0.97+ on EvalSet1, see
tests/test_compat.py golden scoring)."""

import json
import os

import pytest

REF = "/root/reference/src/test/resources/example/cancer-judgement"
DATA = f"{REF}/DataStore/DataSet1"
EVAL = f"{REF}/DataStore/EvalSet1"
MS1 = f"{REF}/ModelStore/ModelSet1"

needs_reference_data = pytest.mark.skipif(
    not os.path.isdir(DATA), reason="reference tutorial data not present")


def test_serve_subcommand_in_cli():
    """`shifu serve` is part of the command table: parser accepts the
    online-scoring knobs and `--help` exits 0 like every subcommand."""
    from shifu_tpu.cli import build_parser

    parser = build_parser()
    args = parser.parse_args([
        "serve", "--port", "0", "--queue-depth", "8",
        "--max-batch-rows", "64", "--max-wait-ms", "1.5",
        "--warm", "1,16", "--models-dir", "m",
    ])
    assert args.command == "serve"
    assert args.port == 0 and args.queue_depth == 8
    assert args.max_batch_rows == 64 and args.max_wait_ms == 1.5
    assert args.warm == "1,16" and args.models_dir == "m"

    with pytest.raises(SystemExit) as exc:
        parser.parse_args(["serve", "--help"])
    assert exc.value.code == 0


def test_serve_help_text_mentions_endpoints(capsys):
    from shifu_tpu.cli import build_parser

    with pytest.raises(SystemExit):
        build_parser().parse_args(["--help"])
    out = capsys.readouterr().out
    assert "serve" in out and "scoring" in out


@needs_reference_data
def test_full_cli_golden_cancer_judgement(tmp_path):
    root = str(tmp_path / "CancerJudgement")
    os.makedirs(root)
    # the reference's own ModelConfig, with paths resolved to the read-only
    # DataStore and a test-sized epoch budget (the net/params stay as
    # checked in: 2x45 Sigmoid, baggingNum 5)
    mc = json.load(open(os.path.join(MS1, "ModelConfig.json")))
    mc["basic"]["name"] = "CancerJudgement"
    mc["dataSet"]["dataPath"] = DATA + "/part-00"
    mc["dataSet"]["headerPath"] = DATA + "/.pig_header"
    mc["train"]["numTrainEpochs"] = 60
    mc["evals"] = mc["evals"][:1]
    ev = mc["evals"][0]
    ev["dataSet"]["dataPath"] = EVAL + "/part-00"
    ev["dataSet"]["headerPath"] = EVAL + "/.pig_header"
    ev["dataSet"]["targetColumnName"] = mc["dataSet"]["targetColumnName"]
    ev["dataSet"]["posTags"] = mc["dataSet"]["posTags"]
    ev["dataSet"]["negTags"] = mc["dataSet"]["negTags"]
    json.dump(mc, open(os.path.join(root, "ModelConfig.json"), "w"),
              indent=2)

    from shifu_tpu.processor.evaluate import EvalProcessor
    from shifu_tpu.processor.init import InitProcessor
    from shifu_tpu.processor.norm import NormProcessor
    from shifu_tpu.processor.stats import StatsProcessor
    from shifu_tpu.processor.train import TrainProcessor
    from shifu_tpu.processor.varsel import VarSelProcessor

    assert InitProcessor(root).run() == 0
    assert os.path.isfile(os.path.join(root, "ColumnConfig.json"))
    assert StatsProcessor(root).run() == 0
    cc = json.load(open(os.path.join(root, "ColumnConfig.json")))
    stats_cols = [c for c in cc if c.get("columnStats", {}).get("ks")]
    assert len(stats_cols) >= 20  # WDBC has 30 informative columns
    assert NormProcessor(root).run() == 0
    assert os.path.isdir(os.path.join(root, "tmp", "norm",
                                      "NormalizedData"))
    assert VarSelProcessor(root).run() == 0
    assert TrainProcessor(root).run() == 0
    models = sorted(os.listdir(os.path.join(root, "models")))
    assert len(models) == 5, models  # baggingNum=5, one file per member

    assert EvalProcessor(root, run_name="").run() == 0
    perf = json.load(open(os.path.join(root, "evals", "EvalA",
                                       "EvalPerformance.json")))
    auc = float(perf["areaUnderRoc"])
    # WDBC floor: the reference's bundled EG models reach ~0.97 on this
    # eval set; the freshly trained bagged net must land in that regime
    assert auc > 0.96, auc
