"""Stats engine tests: metrics parity, binning, end-to-end stats step."""

import json
import math
import os

import numpy as np
import pytest

from tests.helpers import make_model_set

from shifu_tpu.config import load_column_config_list
from shifu_tpu.processor.init import InitProcessor
from shifu_tpu.processor.stats import StatsProcessor
from shifu_tpu.stats.binning import (
    numeric_bin_index,
    numeric_boundaries,
    weighted_quantile_boundaries,
)
from shifu_tpu.stats.metrics import column_metrics, psi_metric


def test_metrics_match_reference_fixture_numbers():
    """Numbers from the reference's cancer-judgement ColumnConfig.json for
    column_4 (binCountNeg/binCountPos -> ks/iv/woe/binCountWoe)."""
    neg = [111, 52, 19, 11, 5, 6, 7, 5, 8, 11]
    pos = [12, 12, 13, 12, 12, 12, 12, 12, 12, 11]
    cm = column_metrics(
        np.asarray([pos], dtype=np.float64),
        np.asarray([neg], dtype=np.float64),
        np.ones((1, 10)),
    )
    assert bool(cm.valid[0])
    assert float(cm.ks[0]) == pytest.approx(49.361702127659576, rel=1e-6)
    assert float(cm.iv[0]) == pytest.approx(1.254393655186373, rel=1e-6)
    assert float(cm.woe[0]) == pytest.approx(-0.6720937713617051, rel=1e-6)
    assert float(cm.bin_woe[0, 0]) == pytest.approx(-1.5525297793739326, rel=1e-6)
    assert float(cm.bin_woe[0, 9]) == pytest.approx(0.6720937703166583, rel=1e-6)


def test_metrics_invalid_when_one_class_empty():
    cm = column_metrics(
        np.zeros((1, 4), dtype=np.float64),
        np.ones((1, 4), dtype=np.float64),
        np.ones((1, 4)),
    )
    assert not bool(cm.valid[0])


def test_quantile_boundaries_equal_mass():
    v = np.arange(1000, dtype=np.float64)
    b = weighted_quantile_boundaries(v, None, 10)
    assert b[0] == -math.inf
    assert len(b) == 10
    idx = numeric_bin_index(v, b)
    counts = np.bincount(idx, minlength=11)[:10]
    assert counts.min() >= 90 and counts.max() <= 110


def test_bin_index_missing_goes_last():
    b = [-math.inf, 1.0, 2.0]
    v = np.array([0.5, 1.0, 1.5, 2.5, np.nan])
    idx = numeric_bin_index(v, b)
    assert idx.tolist() == [0, 1, 1, 2, 3]


def test_equal_positive_binning_uses_positive_rows():
    rng = np.random.default_rng(0)
    vals = np.concatenate([rng.normal(0, 1, 900), rng.normal(5, 1, 100)])
    tags = np.concatenate([np.zeros(900, np.int32), np.ones(100, np.int32)])
    w = np.ones(1000)
    from shifu_tpu.config.model_config import BinningMethod

    b = numeric_boundaries(vals, tags, w, BinningMethod.EQUAL_POSITIVE, 5)
    # positives center on 5: all interior boundaries should sit near there
    assert all(x > 2.0 for x in b[1:])


def test_psi_metric_zero_for_identical():
    assert psi_metric([10, 20, 30], [100, 200, 300]) == pytest.approx(0.0, abs=1e-9)
    assert psi_metric([10, 20, 30], [30, 20, 10]) > 0.1


@pytest.fixture(scope="module")
def stats_model_set(tmp_path_factory):
    root = make_model_set(str(tmp_path_factory.mktemp("ms")))
    assert InitProcessor(root).run() == 0
    assert StatsProcessor(root, correlation=True).run() == 0
    return root


def test_stats_end_to_end(stats_model_set):
    root = stats_model_set
    cols = load_column_config_list(os.path.join(root, "ColumnConfig.json"))
    by_name = {c.column_name: c for c in cols}

    num0 = by_name["num_0"]  # informative numeric column
    assert num0.column_stats.mean is not None
    assert num0.column_stats.std_dev is not None
    assert num0.column_stats.ks is not None and num0.column_stats.ks > 5
    assert num0.column_stats.iv is not None and num0.column_stats.iv > 0.05
    assert num0.column_binning.bin_boundary[0] == -math.inf
    # missing bin included: counts length == boundaries + 1
    assert len(num0.column_binning.bin_count_pos) == len(
        num0.column_binning.bin_boundary
    ) + 1
    assert num0.column_stats.missing_count > 0  # helper injects ~2% missing

    cat0 = by_name["cat_0"]  # informative categorical column
    assert cat0.column_binning.bin_category is not None
    assert len(cat0.column_binning.bin_count_pos) == len(
        cat0.column_binning.bin_category
    ) + 1
    assert cat0.column_stats.ks is not None and cat0.column_stats.ks > 5
    # pos rate ordering encodes the signal: 'red' is the most positive color
    cats = cat0.column_binning.bin_category
    rates = cat0.column_binning.bin_pos_rate
    assert rates[cats.index("red")] > rates[cats.index("green")]

    # uninformative column has low KS
    num1 = by_name["num_1"]
    assert num1.column_stats.ks < num0.column_stats.ks

    # totals conserved: pos+neg counts sum to total rows
    tot = sum(num0.column_binning.bin_count_pos) + sum(
        num0.column_binning.bin_count_neg
    )
    assert tot == num0.column_stats.total_count


def test_correlation_artifact(stats_model_set):
    root = stats_model_set
    from shifu_tpu.stats.correlation import load_correlation_csv

    corr, names = load_correlation_csv(
        os.path.join(root, "tmp", "stats", "correlation.csv")
    )
    assert len(names) == corr.shape[0] == corr.shape[1]
    assert np.allclose(np.diag(corr), 1.0, atol=1e-2)  # f32 matmul precision
    assert np.all(np.abs(corr) <= 1.0 + 1e-2)


def test_sharded_binagg_matches_single_device():
    """8-virtual-device row-sharded aggregation == single-device result
    (the multi-chip stats path)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh

    from shifu_tpu.ops.binagg import bin_aggregate_jit, bin_aggregate_sharded

    rng = np.random.default_rng(1)
    n, c = 512, 6
    slots = [5, 5, 5, 5, 5, 5]
    codes = rng.integers(0, 5, size=(n, c)).astype(np.int32)
    offsets = np.array([0, 5, 10, 15, 20, 25], dtype=np.int32)
    tags = rng.integers(0, 2, size=n).astype(np.int32)
    weights = rng.random(n).astype(np.float32)
    values = rng.normal(size=(n, 3)).astype(np.float32)
    values[rng.random((n, 3)) < 0.1] = np.nan

    single = bin_aggregate_jit(
        jnp.asarray(codes), jnp.asarray(offsets), 30, jnp.asarray(tags),
        jnp.asarray(weights), jnp.asarray(values),
    )
    devices = jax.devices()
    assert len(devices) == 8, "conftest must force 8 virtual devices"
    mesh = Mesh(np.array(devices), ("data",))
    sharded = bin_aggregate_sharded(
        mesh, jnp.asarray(codes), jnp.asarray(offsets), 30,
        jnp.asarray(tags), jnp.asarray(weights), jnp.asarray(values),
    )
    np.testing.assert_allclose(np.asarray(single.pos), np.asarray(sharded.pos), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(single.wpos), np.asarray(sharded.wpos), rtol=1e-4)
    np.testing.assert_allclose(np.asarray(single.vsum), np.asarray(sharded.vsum), rtol=1e-4)
    np.testing.assert_allclose(np.asarray(single.vmin), np.asarray(sharded.vmin), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(single.vmissing), np.asarray(sharded.vmissing), rtol=1e-5)


class TestRebin:
    def test_rebin_reduces_bins_preserving_iv(self, tmp_path):
        from tests.helpers import make_model_set
        from shifu_tpu.config import load_column_config_list
        from shifu_tpu.processor.init import InitProcessor
        from shifu_tpu.processor.stats import StatsProcessor
        from shifu_tpu.utils import environment
        import os

        root = str(tmp_path / "ms")
        make_model_set(root, n_rows=500)
        assert InitProcessor(root).run() == 0
        assert StatsProcessor(root).run() == 0
        before = load_column_config_list(os.path.join(root, "ColumnConfig.json"))
        iv_before = {c.column_name: c.column_stats.iv for c in before
                     if c.column_stats.iv}

        environment.set_property("shifu.rebin.maxNumBin", "4")
        try:
            assert StatsProcessor(root, rebin=True).run() == 0
        finally:
            environment.set_property("shifu.rebin.maxNumBin", "")
        after = load_column_config_list(os.path.join(root, "ColumnConfig.json"))
        rebinned = [c for c in after
                    if not c.is_categorical() and c.column_binning.bin_boundary]
        assert any(len(c.column_binning.bin_boundary) <= 4 for c in rebinned)
        for c in after:
            iv0 = iv_before.get(c.column_name)
            if iv0 and c.column_stats.iv:
                assert c.column_stats.iv >= iv0 * 0.5  # IV largely preserved

    def test_rebin_refreshes_weighted_woe(self):
        # ADVICE r1: merged bins must get a consistent bin_weighted_woe (same
        # length as the merged count arrays) and fresh ks/weighted stats.
        from shifu_tpu.config.column_config import ColumnConfig, ColumnType
        from shifu_tpu.stats.rebin import rebin_column

        cc = ColumnConfig(column_num=1, column_name="x",
                          column_type=ColumnType.N)
        bn = cc.column_binning
        bn.bin_boundary = [-np.inf, 0.0, 1.0, 2.0, 3.0, 4.0, 5.0, 6.0]
        bn.bin_count_pos = [5, 8, 12, 20, 30, 45, 60, 80, 3]
        bn.bin_count_neg = [80, 60, 45, 30, 20, 12, 8, 5, 2]
        bn.bin_weighted_pos = [2 * p for p in bn.bin_count_pos]
        bn.bin_weighted_neg = [2 * n for n in bn.bin_count_neg]
        bn.length = 8
        assert rebin_column(cc, target_bins=4)
        n_bins = len(bn.bin_boundary) + 1  # + missing slot
        assert len(bn.bin_count_woe) == n_bins
        assert len(bn.bin_weighted_woe) == n_bins
        assert cc.column_stats.ks is not None and cc.column_stats.ks > 0
        assert cc.column_stats.weighted_iv is not None
        # weights are a uniform 2x scale, so weighted woe == count woe
        np.testing.assert_allclose(
            bn.bin_weighted_woe, bn.bin_count_woe, atol=1e-9
        )
