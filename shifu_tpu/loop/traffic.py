"""Append-only serve-side traffic log: rotating chunk files in the ledger.

Every scored micro-batch can leave a row-sampled trace of (raw features,
mean score, model-set sha, unix timestamp) under
`<root>/.shifu/runs/traffic/traffic-[<writer>-]<seq>.psv`. The log is
FLEET-SHARED: each serve process appends under its own lease-derived
writer id with its own monotone sequence, and consumers (`shifu retrain
--from-traffic`) read the union across writers — N replicas, one
training stream. Design constraints, in order:

  * **Append-only + torn-write-proof.** A chunk file appears atomically
    (resilience.checkpoint.atomic_write: temp + os.replace) when its row
    buffer fills — a killed server leaves only whole chunk files, never a
    half row. Files are never rewritten; the sequence number only grows
    (across server restarts too).
  * **Just another stream.** The files are plain `|`-delimited text plus
    a `_meta.json` sidecar naming the columns, so `traffic_source()`
    hands back the same `chunk_source` factory every lifecycle step
    consumes — `shifu retrain` reads logged traffic through the identical
    ShardPlan/prefetch machinery as any training file, and the underscore
    sidecar is invisible to the data-file scan.
  * **Sampled.** `-Dshifu.loop.logSample` (0..1) row-samples with a
    deterministic per-batch RNG, so a replayed stream logs the same rows.

Label plumbing: the log's schema is the caller's `columns` list — the
serve wiring passes the registry input columns PLUS the ModelConfig's
target/weight columns when it can see a ModelConfig, so records that
carry outcomes (label-joined traffic) keep them and `shifu retrain` can
train on the log directly; records without them log the empty missing
token and the retrain norm pass drops those rows like any unlabeled row.

Metrics: loop.traffic.rows / loop.traffic.sampled_out /
loop.traffic.chunks, all in the serve shutdown manifest.
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
from typing import List, Optional, Tuple

import numpy as np

from shifu_tpu.analysis.racetrack import guarded_by, tracked_lock
from shifu_tpu.fs.listing import sorted_glob
from shifu_tpu.loop import log_chunk_rows_setting, log_sample_setting
from shifu_tpu.utils.log import get_logger

log = get_logger(__name__)

TRAFFIC_SUBDIR = os.path.join(".shifu", "runs", "traffic")
DELIMITER = "|"
META_FILE = "_meta.json"
# scores/sha/trace/timestamp ride as ordinary columns; retrain treats
# them as meta (never features) because they are not in ColumnConfig.
# TRACE_COLUMN is the request-trace id (obs/reqtrace.py) of the request
# that produced the row — the serve -> retrain -> promote lineage key.
SCORE_COLUMN = "shifu_score_mean"
SHA_COLUMN = "shifu_model_sha"
TRACE_COLUMN = "shifu_trace"
TS_COLUMN = "shifu_ts"
# count of meta columns appended after the feature columns, in order
META_COLUMNS = (SCORE_COLUMN, SHA_COLUMN, TRACE_COLUMN, TS_COLUMN)

# chunk names carry an optional WRITER id: a fleet of serve processes
# appends to the same ledger dir as `traffic-<writer>-<seq>.psv`, each
# writer owning its own monotone sequence — no cross-process seq race,
# and readers union the writers. Legacy single-process chunks
# (`traffic-<seq>.psv`, writer group empty) stay readable. Writer ids
# are sanitized to [A-Za-z0-9_] and never all-digits (writer_id()), so
# the name grammar is unambiguous.
_CHUNK_RE = re.compile(r"^traffic-(?:([A-Za-z0-9_]+)-)?(\d+)\.psv$")


def writer_id(value: str) -> str:
    """Sanitize a lease id (resilience/lease.py: host-pid-token) into a
    chunk-name-safe writer id. All-digit/empty results get a 'w' prefix
    so a writer id can never parse as a bare sequence number."""
    cleaned = re.sub(r"[^A-Za-z0-9_]", "_", value or "")
    if not cleaned or cleaned[0].isdigit():
        cleaned = "w" + cleaned  # never digit-led: can't parse as a seq
    return cleaned


def traffic_scope_setting() -> str:
    """shifu.loop.trafficScope — which writers' chunks consumers read:
    'fleet' (default) unions every serve process's log; a specific
    writer id restricts to that process's chunks (replay/debug)."""
    from shifu_tpu.utils import environment

    v = environment.get_property("shifu.loop.trafficScope", "fleet")
    return (v or "fleet").strip()


def traffic_dir(root: str, stream: str = "") -> str:
    """Traffic-log dir; `stream` (a zoo tenant name) keeps each model
    set's logged traffic a SEPARATE stream under the shared ledger —
    per-tenant retrain must never mix another tenant's rows."""
    base = os.path.join(os.path.abspath(root), TRAFFIC_SUBDIR)
    return os.path.join(base, stream) if stream else base


def traffic_columns(base_columns: List[str]) -> List[str]:
    return list(base_columns) + list(META_COLUMNS)


def list_chunks(root: str, stream: str = "",
                scope: Optional[str] = None) -> List[str]:
    """Chunk files in (sequence, writer) order — the fleet union by
    default (`scope` falls back to shifu.loop.trafficScope), or one
    writer's own append order when a writer id is given."""
    scope = traffic_scope_setting() if scope is None else scope
    out = []
    for path in sorted_glob(os.path.join(traffic_dir(root, stream),
                                         "traffic-*.psv")):
        m = _CHUNK_RE.match(os.path.basename(path))
        if not m:
            continue
        writer = m.group(1) or ""
        if scope != "fleet" and writer != scope:
            continue
        out.append(((int(m.group(2)), writer), path))
    return [p for _k, p in sorted(out)]


def chunk_writer(path: str) -> Optional[str]:
    """Writer id a chunk file belongs to ('' for legacy unnamed chunks,
    None when the name is not a traffic chunk at all)."""
    m = _CHUNK_RE.match(os.path.basename(path))
    return (m.group(1) or "") if m else None


def list_writers(root: str, stream: str = "") -> List[str]:
    """Distinct writer ids with chunks on disk (legacy unnamed chunks
    report as '') — the retrain lineage manifest's evidence that the
    union spanned the fleet."""
    writers = set()
    for path in sorted_glob(os.path.join(traffic_dir(root, stream),
                                         "traffic-*.psv")):
        m = _CHUNK_RE.match(os.path.basename(path))
        if m:
            writers.add(m.group(1) or "")
    return sorted(writers)


def _sanitize(value: str) -> str:
    """Field hygiene: the log is `|`-delimited text, so the delimiter and
    newlines inside a raw value must not corrupt row framing."""
    if DELIMITER in value or "\n" in value or "\r" in value:
        return (value.replace(DELIMITER, ";")
                .replace("\n", " ").replace("\r", " "))
    return value


class TrafficLog:
    """Thread-safe rotating chunk writer for one serving process."""

    def __init__(self, root: str, columns: List[str],
                 sample: Optional[float] = None,
                 chunk_rows: Optional[int] = None,
                 seed: int = 0, stream: str = "",
                 writer: str = "") -> None:
        self.root = os.path.abspath(root)
        self.stream = stream
        self.dir = traffic_dir(root, stream)
        self.writer = writer_id(writer) if writer else ""
        self.columns = list(columns)
        self.sample = (log_sample_setting() if sample is None
                       else float(sample))
        self.chunk_rows = (log_chunk_rows_setting() if chunk_rows is None
                           else int(chunk_rows))
        self.seed = int(seed)
        self._lock = tracked_lock("loop.traffic")
        self._buffer: List[str] = []
        self._batches = 0
        self._chunks = 0  # chunks THIS process wrote (seq counts restarts)
        self._retire_mismatched_schema()
        self._seq = self._next_seq()
        # chunk writes happen outside self._lock (SH203) but must LAND
        # in sequence order: a reader (retrain --from-traffic against a
        # live server) globs the dir sorted by seq and would silently
        # skip chunk N's rows if N+1's smaller write raced onto disk
        # first. Concurrent rotators serialize among themselves on this
        # condition; recorders never touch it.
        self._write_cond = threading.Condition()
        self._next_write = self._seq
        self._write_meta()

    def _retire_mismatched_schema(self) -> None:
        """A restart with a DIFFERENT column schema must not rewrite
        _meta.json over chunks framed with the old one — every old row
        would parse misaligned into the new columns and retrain on
        garbage. The old log moves wholesale to a `superseded-<n>` subdir
        (nothing is destroyed; readers only glob the active dir)."""
        meta_path = os.path.join(self.dir, META_FILE)
        if not os.path.isfile(meta_path):
            return
        try:
            with open(meta_path) as fh:
                old = json.load(fh)
        except (OSError, ValueError):
            old = None  # unreadable meta: retire it with the chunks
        if old is not None and list(old.get("columns", [])) == self.columns:
            return
        n = 1
        while os.path.isdir(os.path.join(self.dir, f"superseded-{n}")):
            n += 1
        retired = os.path.join(self.dir, f"superseded-{n}")
        os.makedirs(retired)
        moved = 0
        for path in (sorted_glob(os.path.join(self.dir, "traffic-*.psv"))
                     + [meta_path]):
            if os.path.isfile(path):
                os.replace(path,
                           os.path.join(retired, os.path.basename(path)))
                moved += 1
        log.warning("traffic log schema changed (%s -> %s columns): "
                    "retired %d old file(s) to %s",
                    len(old.get("columns", [])) if old else "?",
                    len(self.columns), moved, retired)

    # ---- layout ----
    def set_writer(self, writer: str) -> None:
        """Adopt a fleet writer id (the serve lease id) — called once
        the lease exists, before traffic flows. The sequence restarts
        from this WRITER'S own highest chunk, so N processes on one
        ledger never contend for a sequence number."""
        with self._lock:
            self.writer = writer_id(writer)
            self._seq = self._next_seq()
            with self._write_cond:
                self._next_write = self._seq

    def _chunk_path(self, seq: int) -> str:
        name = (f"traffic-{self.writer}-{seq:05d}.psv" if self.writer
                else f"traffic-{seq:05d}.psv")
        return os.path.join(self.dir, name)

    def _next_seq(self) -> int:
        """Highest sequence among THIS writer's chunks + 1 (legacy
        unnamed chunks when no writer is set) — restarts keep the
        writer's own sequence monotone."""
        highest = 0
        for path in sorted_glob(os.path.join(self.dir, "traffic-*.psv")):
            m = _CHUNK_RE.match(os.path.basename(path))
            if m and (m.group(1) or "") == self.writer:
                highest = max(highest, int(m.group(2)))
        return highest + 1

    def _write_meta(self) -> None:
        from shifu_tpu.resilience.checkpoint import atomic_write_json

        atomic_write_json(os.path.join(self.dir, META_FILE), {
            "schema": "shifu.traffic/1",
            "columns": self.columns,
            "delimiter": DELIMITER,
            "sample": self.sample,
            "chunkRows": self.chunk_rows,
        })

    # ---- write side ----
    def record(self, data, result, sha: str) -> int:
        """Log one scored batch (a ColumnarData + its ScoreResult); returns
        the number of rows actually logged after sampling."""
        from shifu_tpu.obs import registry

        if self.sample <= 0.0:
            return 0
        n = data.n_rows
        with self._lock:
            self._batches += 1
            if self.sample >= 1.0:
                keep = np.arange(n)
            else:
                # deterministic per-batch draw: a replayed stream logs the
                # same rows, and restarts never re-use a stream position
                rng = np.random.default_rng([self.seed, self._batches])
                keep = np.nonzero(rng.random(n) < self.sample)[0]
            reg = registry()
            reg.counter("loop.traffic.rows").inc(len(keep))
            reg.counter("loop.traffic.sampled_out").inc(n - len(keep))
            if not len(keep):
                return 0
            ts = f"{time.time():.3f}"
            cols = [np.asarray(data.column(c), dtype=object)
                    if c in data.raw else None
                    for c in self.columns[:-len(META_COLUMNS)]]
            mean = result.mean
            # per-row request-trace ids (set by the batcher before the
            # observer runs) — rows from un-traced requests log empty
            trace_ids = getattr(data, "trace_ids", None)
            for i in keep:
                fields = [
                    _sanitize("" if col is None else str(col[i]))
                    for col in cols
                ]
                fields.append(f"{float(mean[i]):.4f}")
                fields.append(sha)
                fields.append(_sanitize(str(trace_ids[i]))
                              if trace_ids is not None
                              and i < len(trace_ids) else "")
                fields.append(ts)
                self._buffer.append(DELIMITER.join(fields))
            pending = (self._swap_chunk()
                       if len(self._buffer) >= self.chunk_rows else None)
            kept = len(keep)
        # the file write happens OUTSIDE the lock (SH203): the scoring
        # worker's next record() must never queue behind disk I/O. The
        # swap assigned this chunk its sequence number under the lock,
        # so row order across files is preserved whatever order the
        # writes land in.
        if pending is not None:
            self._write_chunk(*pending)
        return kept

    @guarded_by("_lock")
    def _swap_chunk(self) -> Optional[Tuple[int, str, List[str]]]:
        """Take the buffered rows + their chunk seq/path out of the
        shared state (caller holds the lock); the caller writes them
        outside via _write_chunk, which lands files in seq order."""
        if not self._buffer:
            return None
        seq = self._seq
        path = self._chunk_path(seq)
        rows, self._buffer = self._buffer, []
        self._seq += 1
        self._chunks += 1
        return seq, path, rows

    def _write_chunk(self, seq: int, path: str, rows: List[str]) -> None:
        from shifu_tpu.obs import registry
        from shifu_tpu.resilience.checkpoint import atomic_write

        # land in seq order: a reader globbing the dir mid-write must
        # never see chunk N+1 without N (it would silently skip N's
        # rows). Only concurrent rotators queue here, never recorders.
        with self._write_cond:
            while self._next_write != seq:
                self._write_cond.wait(1.0)
        try:
            atomic_write(path, ("\n".join(rows) + "\n").encode("utf-8"))
        finally:
            # bump even on a failed write so later chunks are not
            # wedged behind a disk error forever
            with self._write_cond:
                self._next_write = seq + 1
                self._write_cond.notify_all()
        registry().counter("loop.traffic.chunks").inc()
        log.debug("traffic chunk %s (%d rows)", path, len(rows))

    def flush(self) -> None:
        """Persist any buffered rows as a (possibly short) chunk."""
        with self._lock:
            pending = self._swap_chunk()
        if pending is not None:
            self._write_chunk(*pending)

    def close(self) -> None:
        self.flush()

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "dir": self.dir,
                "writer": self.writer,
                "sample": self.sample,
                "chunks": self._chunks,
                "bufferedRows": len(self._buffer),
            }


def log_meta(root: str, stream: str = "",
             scope: Optional[str] = None) -> Tuple[dict, List[str]]:
    """(parsed _meta.json, chunk paths) of the traffic log under `root`'s
    ledger — THE validation for every consumer (traffic_source, `shifu
    retrain`), so the operator guidance stays in one place. Raises
    FileNotFoundError when nothing was ever logged or no chunk has
    rotated out yet."""
    meta_path = os.path.join(traffic_dir(root, stream), META_FILE)
    if not os.path.isfile(meta_path):
        raise FileNotFoundError(
            f"no traffic log under {traffic_dir(root, stream)} — serve "
            f"with --traffic-log (or -Dshifu.loop.logSample>0) first")
    with open(meta_path) as fh:
        meta = json.load(fh)
    chunks = list_chunks(root, stream, scope=scope)
    if not chunks:
        raise FileNotFoundError(
            f"traffic log {traffic_dir(root, stream)} has no chunk "
            "files yet")
    return meta, chunks


def trace_lineage(root: str, limit: int = 8,
                  stream: str = "") -> Optional[dict]:
    """Serve -> train lineage evidence from the traffic log: how many
    logged rows carry a request-trace id (obs/reqtrace.py) and a sample
    of the ids, so retrain/promote manifests can point back at the
    exact serving evidence. A single-shard whole-log scan — the log's
    chunk files are small and this runs once per retrain, not on any
    hot path. None when the log has no trace column (pre-trace logs)."""
    try:
        meta, chunks = log_meta(root, stream)
    except FileNotFoundError:
        return None
    columns = list(meta.get("columns", []))
    if TRACE_COLUMN not in columns:
        return None
    idx = columns.index(TRACE_COLUMN)
    delim = meta.get("delimiter", DELIMITER)
    traced = 0
    total = 0
    sample: List[str] = []
    seen = set()
    for path in chunks:
        try:
            with open(path) as fh:
                for line in fh:
                    line = line.rstrip("\n")
                    if not line:
                        continue
                    total += 1
                    fields = line.split(delim)
                    tid = fields[idx] if idx < len(fields) else ""
                    if tid:
                        traced += 1
                        if tid not in seen and len(sample) < limit:
                            seen.add(tid)
                            sample.append(tid)
        except OSError:
            continue
    return {
        "traceColumn": TRACE_COLUMN,
        "rows": total,
        "tracedRows": traced,
        "sampleTraceIds": sample,
    }


def traffic_source(root: str, chunk_rows: Optional[int] = None,
                   columns: Optional[List[str]] = None,
                   missing_values=None,
                   stream: str = "",
                   scope: Optional[str] = None) -> Tuple[object, List[str]]:
    """(chunk_source factory, column names) over the logged traffic — the
    seam that makes the log just another input stream. The fleet UNION
    by default (every writer's chunks; shifu.loop.trafficScope / `scope`
    narrows to one writer). Raises FileNotFoundError when nothing was
    ever logged."""
    from shifu_tpu.data.reader import DEFAULT_MISSING
    from shifu_tpu.data.stream import chunk_source

    scope = traffic_scope_setting() if scope is None else scope
    meta, _ = log_meta(root, stream, scope=scope)
    names = list(meta["columns"])
    pattern = ("traffic-*.psv" if scope == "fleet"
               else f"traffic-{scope}-*.psv" if scope
               else "traffic-[0-9]*.psv")
    factory = chunk_source(
        os.path.join(traffic_dir(root, stream), pattern),
        names,
        delimiter=meta.get("delimiter", DELIMITER),
        missing_values=(tuple(missing_values) if missing_values
                        else DEFAULT_MISSING),
        chunk_rows=chunk_rows,
        columns=columns,
    )
    return factory, names
