"""BasicProcessor: shared step setup/teardown.

Contract parity with core/processor/BasicModelProcessor.java:57 — load both
configs from the working directory, validate via the inspector for the current
step, expose save helpers, and resolve data paths relative to the model-set
root."""

from __future__ import annotations

import os
import time
from typing import List, Optional

from shifu_tpu.config import (
    ColumnConfig,
    ModelConfig,
    load_column_config_list,
    save_column_config_list,
)
from shifu_tpu.config.inspector import probe
from shifu_tpu.fs.pathfinder import PathFinder
from shifu_tpu.utils.errors import ErrorCode, ShifuError
from shifu_tpu.utils.log import get_logger

log = get_logger(__name__)


class BasicProcessor:
    step: str = ""

    def __init__(self, root: str = "."):
        self.root = os.path.abspath(root)
        self.paths = PathFinder(self.root)
        self.model_config: Optional[ModelConfig] = None
        self.column_configs: List[ColumnConfig] = []
        # run_step() may stash step-specific manifest sections here (the
        # retrain provenance chain rides this seam); run() merges it into
        # the run-ledger manifest, success or failure
        self.manifest_extra: dict = {}

    # ---- lifecycle ----
    def setup(self, need_columns: bool = True) -> None:
        mc_path = self.paths.model_config_path()
        if not os.path.isfile(mc_path):
            raise ShifuError(ErrorCode.MODEL_CONFIG_NOT_FOUND, mc_path)
        self.model_config = ModelConfig.load(mc_path)
        result = probe(self.model_config, self.step, base_dir=self.root)
        if not result.status:
            raise ShifuError(
                ErrorCode.INVALID_MODEL_CONFIG, "; ".join(result.causes)
            )
        if need_columns:
            cc_path = self.paths.column_config_path()
            if not os.path.isfile(cc_path):
                raise ShifuError(ErrorCode.COLUMN_CONFIG_NOT_FOUND, cc_path)
            self.column_configs = load_column_config_list(cc_path)

    def save_column_configs(self) -> None:
        save_column_config_list(self.paths.column_config_path(), self.column_configs)

    def save_model_config(self) -> None:
        assert self.model_config is not None
        self.model_config.save(self.paths.model_config_path())

    def resolve(self, path: str) -> str:
        """Paths in configs are relative to the model-set root; scheme-ful
        URIs (hdfs://, s3://, memory://...) pass through untouched — the
        SourceType seam (fs/source.py) owns them."""
        from shifu_tpu.fs.source import is_remote

        if is_remote(path) or os.path.isabs(path):
            return path
        return os.path.normpath(os.path.join(self.root, path))

    # ---- run wrapper: ledger manifest, metrics/tracing scope, profiling ----
    def run(self) -> int:
        """Run the step inside the observability envelope: a fresh
        metrics/tracing/profiler scope (outermost run only), a root span,
        optional deep XLA capture (-Dshifu.profile=xla traces the step
        with jax.profiler into the ledger dir and links the Perfetto
        trace from the manifest; -Dshifu.profile=<dir> keeps the
        explicit-directory behavior), and — success OR failure — a
        sequence-numbered run manifest under
        <root>/.shifu/runs/<step>-<seq>.json carrying the registry
        snapshot, the per-program cost/roofline `profile` section
        (obs/profile.py), trace path, config hashes and exit status
        (obs/ledger.py). Exceptions re-raise after the manifest lands.

        -Dshifu.sanitize=transfer,nan,recompile additionally arms the
        runtime sanitizer harness (analysis/sanitize.py) for the step;
        its verdict (guard trips, nan traps, recompile-budget breaches)
        is embedded in the manifest, success or failure."""
        import sys

        from shifu_tpu import obs
        from shifu_tpu.analysis import sanitize
        from shifu_tpu.obs.ledger import RunLedger
        from shifu_tpu.resilience import faults

        obs.install_jax_probes()
        # parse the sanitizer config BEFORE begin_run: a bad
        # -Dshifu.sanitize value raises here, while the obs run depth is
        # still balanced (a raise between begin_run and its finally would
        # disable the per-step registry reset for the whole process)
        san = sanitize.from_environment()
        # fresh fault-injection counters per step (-Dshifu.faults), and
        # SIGTERM -> PreemptionError so a real preemption unwinds through
        # this frame and still writes its failure manifest below
        faults.reset()
        restore_sigterm = faults.install_preemption_handler()
        obs.begin_run()
        t0 = time.time()
        status, error = "ok", None
        profile_dir = None
        try:  # everything after begin_run pairs with end_run in finally —
            # a leaked run depth would disable the per-step registry reset
            # for the rest of the process
            ledger = RunLedger(self.root)
            seq = ledger.next_seq(self.step)
            log.info("Step %s starts.", self.step)
            profile_dir = self._profile_dir(ledger, seq)
            try:
                with obs.span(f"step.{self.step}", seq=seq), \
                        sanitize.activate(san), \
                        san.armed(f"step.{self.step}"):
                    if profile_dir:
                        # deep capture: wrap the step in a jax.profiler
                        # trace (the TPU answer to the reference's
                        # per-phase wall-clock logging + JMap
                        # introspection, SURVEY §5); inspect with
                        # TensorBoard/xprof/Perfetto
                        import jax

                        os.makedirs(profile_dir, exist_ok=True)
                        with jax.profiler.trace(profile_dir):
                            self.run_step()
                        log.info("profiler trace -> %s", profile_dir)
                    else:
                        self.run_step()
            except BaseException as e:
                status, error = "failed", f"{type(e).__name__}: {e}"
                raise
            finally:
                elapsed = time.time() - t0
                reg = obs.registry()
                reg.gauge("step.columns_configured").set(
                    len(self.column_configs))
                reg.timer("step.elapsed", step=self.step).add(elapsed)
                extra = {}
                if profile_dir:
                    extra["profileDir"] = profile_dir
                    trace_file = self._find_xla_trace(profile_dir)
                    if trace_file:
                        extra["perfettoTrace"] = trace_file
                if san.active:
                    extra["sanitizer"] = san.verdict()
                if self.manifest_extra:
                    extra.update(self.manifest_extra)
                try:
                    profile_snap = obs.profiler().snapshot()
                except Exception as pe:  # pragma: no cover - defensive
                    log.warning("cannot snapshot profiler: %s", pe)
                    profile_snap = None
                try:
                    path = ledger.write(
                        self.step, seq,
                        status=status,
                        exit_status=0 if status == "ok" else 1,
                        started_at=t0,
                        elapsed_seconds=elapsed,
                        argv=list(sys.argv),
                        registry=reg,
                        tracer=obs.tracer(),
                        profile=profile_snap,
                        error=error,
                        extra=extra or None,
                    )
                    log.info("run manifest -> %s", path)
                except Exception as we:  # a broken ledger must not mask
                    log.warning("cannot write run manifest: %s", we)
                log.info("Step %s finished in %.1f s.", self.step, elapsed)
        finally:
            obs.end_run()
            if restore_sigterm is not None:
                restore_sigterm()
        return 0

    def _profile_dir(self, ledger=None, seq=None):
        from shifu_tpu.utils import environment

        d = environment.get_property("shifu.profile", "")
        if not d:
            return None
        if d.strip().lower() == "xla" and ledger is not None:
            # -Dshifu.profile=xla: deep capture lands beside the run's
            # manifest, so `shifu profile` output and the Perfetto trace
            # share one ledger entry
            return os.path.join(ledger.dir, f"{self.step}-{seq}-xla")
        return os.path.join(self.resolve(d), self.step)

    @staticmethod
    def _find_xla_trace(profile_dir: str):
        """Newest Perfetto/Chrome trace file the jax profiler wrote under
        `profile_dir` (plugins/profile/<ts>/*.trace.json.gz), if any."""
        import glob

        hits = sorted(
            glob.glob(os.path.join(profile_dir, "**", "*.trace.json.gz"),
                      recursive=True)
            + glob.glob(os.path.join(profile_dir, "**", "*.trace.json"),
                        recursive=True),
            key=os.path.getmtime,
        )
        return hits[-1] if hits else None

    def run_step(self) -> None:  # pragma: no cover - abstract
        raise NotImplementedError

    # ---- helpers shared by steps ----
    def target_column(self) -> str:
        assert self.model_config is not None
        return self.model_config.data_set.target_column_name

    def selected_columns(self) -> List[ColumnConfig]:
        return [c for c in self.column_configs if c.final_select]

    def candidate_columns(self) -> List[ColumnConfig]:
        """Columns eligible as features (not target/meta/weight/force-remove)."""
        return [c for c in self.column_configs if c.is_feature()]
