"""GBT/RF tree engine tests: split correctness on hand-built data, GBT
residual fitting, RF voting, serialization roundtrip, categorical subset
splits, and the end-to-end tree train processor."""

import os

import numpy as np
import pytest

from shifu_tpu.models.tree import DenseTree, TreeModelSpec
from shifu_tpu.train.tree_trainer import (
    TreeTrainConfig,
    build_tree,
    subset_count,
    train_trees,
)


def _codes_1feat(values, slots=4):
    return np.asarray(values, dtype=np.int32).reshape(-1, 1), [slots]


class TestBuildTree:
    def test_perfect_numeric_split(self):
        """y = 1 iff code >= 2: one split should separate exactly."""
        import jax.numpy as jnp

        codes, slots = _codes_1feat([0, 0, 1, 1, 2, 2, 3, 3] * 10)
        y = (codes[:, 0] >= 2).astype(np.float32)
        w = np.ones(len(y), dtype=np.float32)
        cfg = TreeTrainConfig(max_depth=2, min_instances_per_node=1)
        tree, resting = build_tree(
            jnp.asarray(codes), jnp.asarray(y), jnp.asarray(w),
            np.asarray(slots), np.asarray([False]), cfg, np.asarray([True]),
        )
        assert tree.feature[0] == 0
        # bins 0,1 left; 2,3 right
        assert tree.left_mask[0, :2].all() and not tree.left_mask[0, 2:4].any()
        pred = tree.leaf_value[resting]
        np.testing.assert_allclose(pred, y, atol=1e-5)

    def test_categorical_subset_split(self):
        """Categorical where bins {0, 2} are positive: mean-sorted subset
        split must put them on one side despite non-contiguous codes."""
        import jax.numpy as jnp

        rng = np.random.default_rng(0)
        codes = rng.integers(0, 4, size=(400, 1)).astype(np.int32)
        y = np.isin(codes[:, 0], [0, 2]).astype(np.float32)
        w = np.ones(len(y), dtype=np.float32)
        cfg = TreeTrainConfig(max_depth=1, min_instances_per_node=1)
        tree, resting = build_tree(
            jnp.asarray(codes), jnp.asarray(y), jnp.asarray(w),
            np.asarray([4]), np.asarray([True]), cfg, np.asarray([True]),
        )
        pred = tree.leaf_value[resting]
        np.testing.assert_allclose(pred, y, atol=1e-5)
        left_set = set(np.nonzero(tree.left_mask[0])[0].tolist())
        assert left_set in ({0, 2}, {1, 3})

    def test_min_instances_blocks_split(self):
        import jax.numpy as jnp

        codes, slots = _codes_1feat([0, 1, 2, 3])
        y = np.asarray([0, 0, 1, 1], np.float32)
        w = np.ones(4, np.float32)
        cfg = TreeTrainConfig(max_depth=2, min_instances_per_node=10)
        tree, resting = build_tree(
            jnp.asarray(codes), jnp.asarray(y), jnp.asarray(w),
            np.asarray(slots), np.asarray([False]), cfg, np.asarray([True]),
        )
        assert tree.feature[0] == -1  # no split possible
        assert (resting == 0).all()
        assert tree.leaf_value[0] == pytest.approx(0.5)


def _make_data(n=2000, f=8, seed=0):
    rng = np.random.default_rng(seed)
    slots = [8] * f
    codes = rng.integers(0, 8, size=(n, f)).astype(np.int32)
    logits = (codes[:, 0] >= 4) * 2.0 + (codes[:, 1] <= 2) * 1.0 - 1.5
    y = (logits + rng.normal(scale=0.5, size=n) > 0).astype(np.float32)
    w = np.ones(n, dtype=np.float32)
    return codes, y, w, slots


class TestTrainTrees:
    def test_gbt_learns(self):
        codes, y, w, slots = _make_data()
        cfg = TreeTrainConfig(algorithm="GBT", tree_num=20, max_depth=3,
                              learning_rate=0.3, valid_set_rate=0.2, seed=1)
        res = train_trees(codes, y, w, slots, [False] * 8,
                          [f"c{i}" for i in range(8)], cfg)
        assert len(res.spec.trees) == 20
        assert res.valid_error < 0.12

        scores = res.spec.independent().compute(codes)
        auc_num = ((scores[y == 1][:, None] > scores[y == 0][None, :]).mean())
        assert auc_num > 0.85

    def test_rf_learns(self):
        codes, y, w, slots = _make_data()
        cfg = TreeTrainConfig(algorithm="RF", tree_num=10, max_depth=5,
                              feature_subset_strategy="TWOTHIRDS",
                              valid_set_rate=0.2, seed=2)
        res = train_trees(codes, y, w, slots, [False] * 8,
                          [f"c{i}" for i in range(8)], cfg)
        scores = res.spec.independent().compute(codes)
        assert res.valid_error < 0.15
        assert scores.min() >= 0 and scores.max() <= 1

    def test_gbt_log_loss(self):
        codes, y, w, slots = _make_data()
        cfg = TreeTrainConfig(algorithm="GBT", tree_num=15, max_depth=3,
                              loss="log", learning_rate=0.3, seed=3)
        res = train_trees(codes, y, w, slots, [False] * 8,
                          [f"c{i}" for i in range(8)], cfg)
        scores = res.spec.independent().compute(codes)
        assert ((scores > 0.5) == (y > 0.5)).mean() > 0.85

    def test_early_stop(self):
        codes, y, w, slots = _make_data(n=400)
        cfg = TreeTrainConfig(algorithm="GBT", tree_num=100, max_depth=3,
                              learning_rate=0.5, early_stop_rounds=3,
                              valid_set_rate=0.3, seed=4)
        res = train_trees(codes, y, w, slots, [False] * 8,
                          [f"c{i}" for i in range(8)], cfg)
        assert len(res.spec.trees) < 100

    def test_impurities_all_run(self):
        codes, y, w, slots = _make_data(n=500)
        for imp in ("variance", "friedmanmse", "entropy", "gini"):
            cfg = TreeTrainConfig(algorithm="RF", tree_num=2, max_depth=3,
                                  impurity=imp, seed=5)
            res = train_trees(codes, y, w, slots, [False] * 8,
                              [f"c{i}" for i in range(8)], cfg)
            assert np.isfinite(res.valid_error), imp

    def test_subset_count(self):
        assert subset_count("ALL", 100) == 100
        assert subset_count("HALF", 100) == 50
        assert subset_count("SQRT", 100) == 10
        assert subset_count("LOG2", 64) == 6
        assert subset_count("TWOTHIRDS", 9) == 6


class TestTreeSpec:
    def test_roundtrip(self, tmp_path):
        codes, y, w, slots = _make_data(n=500)
        cfg = TreeTrainConfig(algorithm="GBT", tree_num=5, max_depth=3, seed=6)
        res = train_trees(codes, y, w, slots, [False] * 8,
                          [f"c{i}" for i in range(8)], cfg)
        path = str(tmp_path / "model0.gbt")
        res.spec.save(path)
        loaded = TreeModelSpec.load(path)
        assert len(loaded.trees) == 5
        assert loaded.algorithm == "GBT"
        s1 = res.spec.independent().compute(codes[:50])
        s2 = loaded.independent().compute(codes[:50])
        np.testing.assert_allclose(s1, s2, atol=1e-6)

    def test_raw_record_scoring(self, tmp_path):
        """codes_from_raw bins raw values with embedded boundaries."""
        from shifu_tpu.data.reader import ColumnarData

        tree = DenseTree(
            feature=np.asarray([0, -1, -1], np.int32),
            left_mask=np.asarray([[1, 1, 0, 0]] * 3, bool),
            leaf_value=np.asarray([0.5, 0.1, 0.9], np.float32),
        )
        spec = TreeModelSpec(
            algorithm="RF", trees=[tree], input_columns=["x"], slots=[4],
            boundaries=[[-np.inf, 1.0, 2.0]], categories=[None],
        )
        data = ColumnarData(
            names=["x"],
            raw={"x": np.asarray(["0.5", "1.5", "5.0", "?"], object)},
            n_rows=4,
        )
        codes = spec.independent().codes_from_raw(data)
        np.testing.assert_array_equal(codes[:, 0], [0, 1, 2, 3])
        scores = spec.independent().compute(codes)
        np.testing.assert_allclose(scores, [0.1, 0.1, 0.9, 0.9], atol=1e-6)


class TestTreeProcessor:
    def test_end_to_end_gbt(self, tmp_path):
        from tests.helpers import make_model_set

        root = str(tmp_path / "ms")
        make_model_set(root, n_rows=400, algorithm="GBT")
        from shifu_tpu.config.model_config import ModelConfig
        from shifu_tpu.processor.init import InitProcessor
        from shifu_tpu.processor.norm import NormProcessor
        from shifu_tpu.processor.stats import StatsProcessor
        from shifu_tpu.processor.train import TrainProcessor

        mc = ModelConfig.load(os.path.join(root, "ModelConfig.json"))
        mc.train.params["TreeNum"] = 10
        mc.train.params["MaxDepth"] = 4
        mc.save(os.path.join(root, "ModelConfig.json"))
        assert InitProcessor(root).run() == 0
        assert StatsProcessor(root).run() == 0
        assert NormProcessor(root).run() == 0
        assert TrainProcessor(root).run() == 0
        model_path = os.path.join(root, "models", "model0.gbt")
        assert os.path.isfile(model_path)

        spec = TreeModelSpec.load(model_path)
        assert spec.valid_error is not None

        # eval with the tree model via the standard eval path
        from shifu_tpu.processor.evaluate import EvalProcessor

        mc = ModelConfig.load(os.path.join(root, "ModelConfig.json"))
        mc.evals[0].data_set.data_path = mc.data_set.data_path
        mc.evals[0].data_set.header_path = mc.data_set.header_path
        mc.save(os.path.join(root, "ModelConfig.json"))
        assert EvalProcessor(root, run_name="").run() == 0
        import json

        with open(os.path.join(root, "evals", "Eval1",
                               "EvalPerformance.json")) as fh:
            perf = json.load(fh)
        assert perf["areaUnderRoc"] > 0.85


class TestMeshParallelTrees:
    """The multi-chip contract (DTMaster.java:297-310 histogram merge →
    psum): an 8-device row-sharded build must produce the SAME forest as
    the single-device build."""

    def test_8_device_tree_equals_1_device_tree(self):
        from shifu_tpu.parallel.mesh import data_mesh

        rng = np.random.default_rng(7)
        n, F, S = 1003, 10, 12  # row count NOT divisible by 8 (pad path)
        codes = rng.integers(0, S, size=(n, F)).astype(np.int32)
        y = (codes[:, 0] + codes[:, 1]
             + rng.normal(scale=2, size=n) > S).astype(np.float32)
        w = np.ones(n, np.float32)
        slots = [S] * F
        is_cat = [False] * (F - 2) + [True, True]
        cols = [f"c{i}" for i in range(F)]

        for alg in ("GBT", "RF"):
            cfg = TreeTrainConfig(algorithm=alg, tree_num=4, max_depth=4,
                                  seed=3)
            r1 = train_trees(codes, y, w, slots, is_cat, cols, cfg)
            r8 = train_trees(codes, y, w, slots, is_cat, cols, cfg,
                             mesh=data_mesh(8))
            assert len(r1.spec.trees) == len(r8.spec.trees)
            for t1, t8 in zip(r1.spec.trees, r8.spec.trees):
                np.testing.assert_array_equal(t1.feature, t8.feature)
                np.testing.assert_array_equal(t1.left_mask, t8.left_mask)
                np.testing.assert_allclose(t1.leaf_value, t8.leaf_value,
                                           atol=1e-4)
            assert abs(r1.valid_error - r8.valid_error) < 1e-4, alg


def test_hoisted_m_matches_rebuild_path():
    """The forest-hoisted code one-hot (bf16 M, one build per run) must
    produce the same forest as the per-level rebuild path — counts are
    exact either way; -Dshifu.train.histCacheBudgetMB=0 disables the
    hoist."""
    import numpy as np

    from shifu_tpu.train.tree_trainer import TreeTrainConfig, train_trees
    from shifu_tpu.utils import environment

    rng = np.random.default_rng(9)
    n, f, bins = 1500, 6, 8
    codes = rng.integers(0, bins, size=(n, f)).astype(np.int32)
    y = ((codes[:, 0] >= 4) | (codes[:, 1] <= 2)).astype(np.float32)
    w = np.ones(n, np.float32)
    cols = [f"c{i}" for i in range(f)]
    cfg = TreeTrainConfig(algorithm="GBT", tree_num=4, max_depth=4,
                          learning_rate=0.3, valid_set_rate=0.15, seed=6,
                          min_instances_per_node=2)
    hoisted = train_trees(codes, y, w, [bins] * f, [False] * f, cols, cfg)
    environment.set_property("shifu.train.histCacheBudgetMB", "0")
    try:
        rebuilt = train_trees(codes, y, w, [bins] * f, [False] * f, cols,
                              cfg)
    finally:
        environment.set_property("shifu.train.histCacheBudgetMB", "4096")
    for th, tr in zip(hoisted.spec.trees, rebuilt.spec.trees):
        np.testing.assert_array_equal(th.feature, tr.feature)
        np.testing.assert_array_equal(th.left_mask, tr.left_mask)
        np.testing.assert_allclose(th.leaf_value, tr.leaf_value, atol=1e-4)
    assert hoisted.valid_error == pytest.approx(rebuilt.valid_error,
                                                abs=1e-5)
