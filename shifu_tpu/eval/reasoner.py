"""Reason codes — which variables drove a record's score.

Parity: core/Reasoner.java + udf/CalculateReasonCodeUDF.java. For every
final-selected column with a posttrain binAvgScore, the record's bin average
score IS its contribution proxy (Reasoner.ScoreDiffObject.scoreDiff =
binAvgScore[binNum]); the top-N columns by that score, mapped through the
reason-code dictionary, are the record's reasons.

Vectorized: one bin-index pass per column, one [n, C] gather, one
argsort — the per-record loop of the reference becomes three
device-friendly array ops.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional, Sequence

import numpy as np


def load_reason_code_map(path: str) -> Dict[str, str]:
    """column name -> reason code. JSON object, or lines of `column,code`.
    Local path or any fs/source.py scheme (hdfs://, s3://...)."""
    from shifu_tpu.fs.source import open_source

    with open_source(path, "rb") as fh:
        text = fh.read().decode("utf-8")
    try:
        data = json.loads(text)
        if isinstance(data, dict):
            return {str(k): str(v) for k, v in data.items()}
    except json.JSONDecodeError:
        pass
    out: Dict[str, str] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        parts = line.split(",", 1)
        if len(parts) == 2:
            out[parts[0].strip()] = parts[1].strip()
    return out


class Reasoner:
    """Batch reason-code calculator over raw records."""

    def __init__(self, column_configs, reason_code_map: Optional[Dict[str, str]] = None,
                 num_top_variables: int = 5):
        self.reason_code_map = reason_code_map or {}
        self.num_top = num_top_variables
        # eligible: final-selected columns that posttrain scored
        # (Reasoner skips columns without binAvgScore)
        self.columns = [
            cc for cc in column_configs
            if cc.final_select and (cc.column_binning.bin_avg_score or [])
        ]

    def score_diffs(self, data) -> np.ndarray:
        """[n, C] binAvgScore of each record's bin per eligible column."""
        from shifu_tpu.norm.normalizer import _bin_codes_for

        n = data.n_rows
        out = np.zeros((n, len(self.columns)), np.float64)
        for j, cc in enumerate(self.columns):
            table = np.asarray(
                [float(v) for v in cc.column_binning.bin_avg_score],
                np.float64,
            )
            codes = np.clip(_bin_codes_for(cc, data), 0, len(table) - 1)
            out[:, j] = table[codes]
        return out

    def reason_codes(self, data) -> List[List[str]]:
        """Per-record top-N reason codes, deduplicated in rank order
        (Reasoner.calculateReasonCodes sort + reasonCodeMap lookup)."""
        if not self.columns:
            return [[] for _ in range(data.n_rows)]
        diffs = self.score_diffs(data)
        order = np.argsort(-diffs, axis=1, kind="stable")
        names = [cc.column_name for cc in self.columns]
        top = min(self.num_top, len(self.columns))
        out: List[List[str]] = []
        for i in range(diffs.shape[0]):
            reasons: List[str] = []
            for j in order[i, :top]:
                code = self.reason_code_map.get(names[j], names[j])
                if code not in reasons:
                    reasons.append(code)
            out.append(reasons)
        return out
