"""Logging setup shared by the CLI and library."""

from __future__ import annotations

import logging
import sys


def get_logger(name: str) -> logging.Logger:
    return logging.getLogger(name)


def configure(verbose: bool = False) -> None:
    """Idempotent and effective on REPEATED calls: bare logging.basicConfig
    silently no-ops once the root logger has handlers, so a second
    configure(verbose=True) (e.g. `-v` after a library call already
    configured logging) used to change nothing. force=True replaces the
    root handlers so the latest call always wins."""
    level = logging.DEBUG if verbose else logging.INFO
    logging.basicConfig(
        level=level,
        stream=sys.stderr,
        format="%(asctime)s %(levelname)-5s %(name)s - %(message)s",
        datefmt="%Y-%m-%d %H:%M:%S",
        force=True,
    )
    # JAX compilation chatter stays at WARNING unless verbose; verbose
    # restores inheritance so a later non-verbose configure can be undone.
    logging.getLogger("jax").setLevel(
        logging.NOTSET if verbose else logging.WARNING)
