"""Co-resident trainer knobs + run configuration.

Every operational choice is a ``-Dshifu.coresident.*`` knob (declared in
analysis/knobs.py, SH105-checked) so the trainer can be tuned from the
same surface as the serving fleet it rides on.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from shifu_tpu.utils import environment

DEFAULT_WAIT_MS = 30000.0


def stages_setting() -> int:
    """shifu.coresident.stages — pipeline stage count K (0 = choose from
    the ledger grant's free budget, see plan.default_stages)."""
    return environment.get_int("shifu.coresident.stages", 0)


def microbatches_setting() -> int:
    """shifu.coresident.microbatches — GPipe microbatches per shard
    filling the pipeline (1 = whole shard at once)."""
    return environment.get_int("shifu.coresident.microbatches", 1)


def wait_ms_setting() -> float:
    """shifu.coresident.waitMs — how long an evicted trainer polls for
    re-admission before giving up with EvictedError."""
    return environment.get_float("shifu.coresident.waitMs",
                                 DEFAULT_WAIT_MS)


def throttle_ms_setting() -> float:
    """shifu.coresident.throttleMs — host sleep between epochs: the
    background tenant yields the devices to serving traffic (0 = run
    flat out)."""
    return environment.get_float("shifu.coresident.throttleMs", 0.0)


def tenant_setting() -> str:
    """shifu.coresident.tenant — the ledger tenant name the trainer
    registers under (the `/admin`-visible identity)."""
    return environment.get_property("shifu.coresident.tenant",
                                    "retrain") or "retrain"


def replicas_setting() -> int:
    """shifu.coresident.replicas — data-parallel pipeline replicas; the
    per-stage gradients all-reduce through parallel/mesh.fleet_reduce
    when > 1."""
    return environment.get_int("shifu.coresident.replicas", 1)


@dataclass
class CoresidentConfig:
    """One co-resident training run's shape. Field defaults of 0/""
    mean "read the knob" — resolve() pins them so the checkpoint
    identity hashes concrete values."""

    stages: int = 0           # 0 = from the grant (plan.default_stages)
    microbatches: int = 0     # 0 = knob (default 1)
    replicas: int = 0         # 0 = knob (default 1)
    tenant: str = ""          # "" = knob (default "retrain")
    serve_url: Optional[str] = None
    wait_ms: float = -1.0     # < 0 = knob
    throttle_ms: float = -1.0  # < 0 = knob
    family_dir: str = "."     # checkpoint-family root (.shifu/runs/ckpt)
    meta: dict = field(default_factory=dict)

    def resolve(self) -> "CoresidentConfig":
        if not self.stages:
            self.stages = max(0, stages_setting())
        if not self.microbatches:
            self.microbatches = max(1, microbatches_setting())
        if not self.replicas:
            self.replicas = max(1, replicas_setting())
        if not self.tenant:
            self.tenant = tenant_setting()
        if self.wait_ms < 0:
            self.wait_ms = max(0.0, wait_ms_setting())
        if self.throttle_ms < 0:
            self.throttle_ms = max(0.0, throttle_ms_setting())
        return self
