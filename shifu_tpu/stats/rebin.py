"""`shifu stats -rebin` — IV-driven dynamic re-binning.

Parity: core/binning/ColumnConfigDynamicBinning.java (DIB path of
StatsModelProcessor): merge adjacent bins of an already-statted column,
greedily combining the pair with the most similar WOE until the target bin
count is reached (or IV loss would exceed the keep ratio). Works off the
existing bin counts — no data re-read.
"""

from __future__ import annotations

import math
from typing import List

import numpy as np

from shifu_tpu.config import ColumnConfig
from shifu_tpu.utils.log import get_logger

log = get_logger(__name__)


def _woe(pos, neg, pos_total, neg_total) -> float:
    eps = 1e-10
    return math.log(
        max(pos / max(pos_total, eps), eps) / max(neg / max(neg_total, eps), eps)
    )


def _iv(pos_list, neg_list, pos_total, neg_total) -> float:
    total = 0.0
    eps = 1e-10
    for p, n in zip(pos_list, neg_list):
        pr = max(p / max(pos_total, eps), eps)
        nr = max(n / max(neg_total, eps), eps)
        total += (pr - nr) * math.log(pr / nr)
    return total


def rebin_column(cc: ColumnConfig, target_bins: int, iv_keep_ratio: float = 0.95) -> bool:
    """Merge adjacent numeric bins in place. Returns True if changed.
    The trailing missing bin never merges."""
    bn = cc.column_binning
    if cc.is_categorical() or not bn.bin_boundary or not bn.bin_count_pos:
        return False
    # real bins exclude the trailing missing slot
    n_real = len(bn.bin_boundary)
    pos = [float(x) for x in bn.bin_count_pos[:n_real]]
    neg = [float(x) for x in bn.bin_count_neg[:n_real]]
    wpos = [float(x) for x in (bn.bin_weighted_pos or pos)[:n_real]]
    wneg = [float(x) for x in (bn.bin_weighted_neg or neg)[:n_real]]
    bounds = list(bn.bin_boundary)
    pos_total = sum(pos) + float(bn.bin_count_pos[-1])
    neg_total = sum(neg) + float(bn.bin_count_neg[-1])
    orig_iv = _iv(pos, neg, pos_total, neg_total)

    changed = False
    while len(bounds) > max(target_bins, 2):
        woes = [_woe(p, n, pos_total, neg_total) for p, n in zip(pos, neg)]
        diffs = [abs(woes[i + 1] - woes[i]) for i in range(len(woes) - 1)]
        k = diffs.index(min(diffs))
        merged_pos = pos[: k] + [pos[k] + pos[k + 1]] + pos[k + 2 :]
        merged_neg = neg[: k] + [neg[k] + neg[k + 1]] + neg[k + 2 :]
        new_iv = _iv(merged_pos, merged_neg, pos_total, neg_total)
        if orig_iv > 0 and new_iv < orig_iv * iv_keep_ratio:
            break
        pos, neg = merged_pos, merged_neg
        wpos = wpos[: k] + [wpos[k] + wpos[k + 1]] + wpos[k + 2 :]
        wneg = wneg[: k] + [wneg[k] + wneg[k + 1]] + wneg[k + 2 :]
        bounds.pop(k + 1)  # bin k absorbs bin k+1
        changed = True

    if not changed:
        return False
    miss_pos = float(bn.bin_count_pos[-1])
    miss_neg = float(bn.bin_count_neg[-1])
    miss_wpos = float((bn.bin_weighted_pos or [miss_pos])[-1])
    miss_wneg = float((bn.bin_weighted_neg or [miss_neg])[-1])
    bn.bin_boundary = bounds
    bn.length = len(bounds)
    bn.bin_count_pos = [int(x) for x in pos] + [int(miss_pos)]
    bn.bin_count_neg = [int(x) for x in neg] + [int(miss_neg)]
    bn.bin_weighted_pos = wpos + [miss_wpos]
    bn.bin_weighted_neg = wneg + [miss_wneg]
    all_pos = pos + [miss_pos]
    all_neg = neg + [miss_neg]
    all_wpos = wpos + [miss_wpos]
    all_wneg = wneg + [miss_wneg]
    bn.bin_pos_rate = [
        p / max(p + n, 1e-10) for p, n in zip(all_pos, all_neg)
    ]
    # Recompute count AND weighted woe/iv/ks from the merged bins so
    # downstream WEIGHT_WOE/WEIGHT_HYBRID norms read fresh tables
    # (ColumnConfigDynamicBinning recomputes both in the reference).
    from shifu_tpu.stats.metrics import column_metrics

    mask = np.ones((1, len(all_pos)))
    cm = column_metrics(np.asarray([all_pos]), np.asarray([all_neg]), mask)
    wm = column_metrics(np.asarray([all_wpos]), np.asarray([all_wneg]), mask)
    bn.bin_count_woe = [float(x) for x in cm.bin_woe[0]]
    bn.bin_weighted_woe = [float(x) for x in wm.bin_woe[0]]
    st = cc.column_stats
    # same guard as the stats engine (engine.py writes metrics only for
    # valid columns): a column with an empty class gets no ks/iv, not noise
    if cm.valid[0]:
        st.iv = float(cm.iv[0])
        st.ks = float(cm.ks[0])
        st.woe = float(cm.woe[0])
    if wm.valid[0]:
        st.weighted_iv = float(wm.iv[0])
        st.weighted_ks = float(wm.ks[0])
        st.weighted_woe = float(wm.woe[0])
    return True


def rebin_columns(
    columns: List[ColumnConfig], target_bins: int, iv_keep_ratio: float = 0.95
) -> int:
    n = 0
    for cc in columns:
        if cc.final_select or not any(c.final_select for c in columns):
            if rebin_column(cc, target_bins, iv_keep_ratio):
                n += 1
    return n
