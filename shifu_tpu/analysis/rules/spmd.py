"""JX3xx/SH3xx rules: SPMD & multi-host determinism.

The reference kept its BSP workers in lockstep with Hadoop masters and
ZooKeeper barriers; this repo replaced that machinery with SPMD
collectives (ops/binagg.py, parallel/mesh.py), filesystem barriers
(parallel/hostsync.py) and a byte-identical-artifact contract enforced
by runtime parity pins. The failure classes that come back are the
MapReduce-era coordination bugs in JAX clothing:

  * a collective or hostsync barrier guarded by a per-host predicate —
    only SOME processes arrive, the pod deadlocks until the host-wait
    timeout (JX301);
  * a collective naming an axis the mesh at the dispatch site does not
    carry — an XLA lowering error at best, a silently wrong reduce at
    worst (JX302);
  * an unsorted directory listing / set walk feeding an artifact writer
    or merge — bytes differ per host and the parity contract breaks
    (SH301);
  * two hostsync barriers awaited in opposite orders on different call
    paths — the cross-process deadlock SH202 catches for in-process
    locks (SH302);
  * wall-clock or randomness folded into a content fingerprint — the
    sha no longer names the content, resume/dedup silently break
    (SH303).

Like the JX0xx/SH2xx families, these ride the PackageContext call graph
(traced set, ``reachable`` closure) and the noqa/JSON/CI machinery. The
runtime counterpart is ``-Dshifu.sanitize=divergence``
(analysis/sanitize.py + parallel/hostsync.py): what the AST cannot see —
actually divergent merge inputs between live hosts — the barrier stamps
witness at the real exchange.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, List, Optional, Set, Tuple

from shifu_tpu.analysis.engine import (
    Module,
    PackageContext,
    Rule,
    dotted_name,
    local_bindings,
    register,
)

# ---------------------------------------------------------------------------
# shared vocabulary
# ---------------------------------------------------------------------------

# calls every participating host must reach together: jax collectives
# that lower to cross-device communication, the repo's own reduce
# entry points, and the filesystem barrier verbs.
_COLLECTIVE_TAILS = {
    "psum", "pmean", "pmin", "pmax", "all_gather", "all_to_all",
    "ppermute", "pshuffle",
}
_BARRIER_TAILS = _COLLECTIVE_TAILS | {
    "window_reduce", "fleet_reduce", "shard_map", "shard_map_compat",
    "publish_part", "await_parts",
}

# predicates that differ per host/process. n_hosts/n_shards are uniform
# across the fleet and deliberately NOT here — `if plan.n_hosts > 1:`
# takes the same branch everywhere.
_DIVERGENT_RE = re.compile(
    r"process_index|host_index|hostIndex|host_idx|is_leader")

# def names that compute content fingerprints (SH303 roots). Matched on
# `_`-split tokens so `shadow_snapshot` does not match `sha`.
_FINGERPRINT_TOKENS = {"sha", "digest", "fingerprint", "checksum"}

# wall-clock / randomness sources that must never reach fingerprint
# input.  time.monotonic/perf_counter are for durations and excluded —
# a duration in a fingerprint is its own bug but not this rule's.
_NONDET_CALLS = {
    "time.time": "wall-clock", "time.time_ns": "wall-clock",
    "datetime.now": "wall-clock", "datetime.utcnow": "wall-clock",
    "date.today": "wall-clock",
    "os.urandom": "randomness", "uuid.uuid1": "randomness",
    "uuid.uuid4": "randomness", "uuid1": "randomness",
    "uuid4": "randomness",
}
_NONDET_ROOTS = {"random": "randomness", "secrets": "randomness"}

# listing calls whose filesystem order is arbitrary (SH301)
_LISTING_TAILS = {"listdir": "os.listdir", "glob": "glob.glob",
                  "iglob": "glob.iglob", "scandir": "os.scandir",
                  "iterdir": "Path.iterdir"}
# consumers for which ordering is immaterial
_ORDER_FREE_WRAPPERS = {"sorted", "set", "frozenset", "len", "sum",
                        "min", "max", "any", "all", "sorted_glob",
                        "sorted_listdir", "Counter"}


def _fingerprint_named(name: str) -> bool:
    return bool(_FINGERPRINT_TOKENS
                & set(re.split(r"[_\d]+", name.lower())))


def _is_call_to(node: ast.AST, tails: Set[str]) -> Optional[str]:
    if isinstance(node, ast.Call):
        name = dotted_name(node.func)
        if name.split(".")[-1] in tails:
            return name
    return None


def _const_strs(node: ast.AST) -> Set[str]:
    """All string constants anywhere under `node` (axis specs come as
    "data", ("dcn", "data"), P("data", None), ...)."""
    return {n.value for n in ast.walk(node)
            if isinstance(n, ast.Constant) and isinstance(n.value, str)}


# ---------------------------------------------------------------------------
# package-wide SPMD facts (cached on the PackageContext like
# rules/concurrency.py's _Analysis)
# ---------------------------------------------------------------------------


class _SpmdAnalysis:
    def __init__(self, ctx: PackageContext) -> None:
        self.ctx = ctx
        # defs whose bodies (transitively) reach a collective/barrier
        # call — computed as a fixpoint over direct-call seeds so JX301
        # can flag `f()` under a divergent branch when f() barriers
        # three calls down.
        self.barrier_defs: Dict[ast.AST, str] = {}
        self._seed_barrier_defs()
        self._propagate_barrier_defs()
        # axis vocabularies: def node -> literal axis names of every
        # Mesh(...) it (transitively) constructs; "" when none found.
        self._mesh_axes_cache: Dict[ast.AST, Set[str]] = {}

    # -- barrier-containing defs (JX301) --
    def _seed_barrier_defs(self) -> None:
        for m in self.ctx.modules:
            for node in ast.walk(m.tree):
                if not isinstance(node, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                    continue
                for sub in ast.walk(node):
                    if m.enclosing_function(sub) is not node:
                        continue
                    name = _is_call_to(sub, _BARRIER_TAILS)
                    if name:
                        self.barrier_defs.setdefault(
                            node, f"calls `{name}` at line {sub.lineno}")
                        break

    def _propagate_barrier_defs(self) -> None:
        """Fixpoint: a def that references a barrier-containing def is
        barrier-containing (callers must still arrive together)."""
        changed = True
        while changed:
            changed = False
            for m in self.ctx.modules:
                for node in ast.walk(m.tree):
                    if not isinstance(node, (ast.FunctionDef,
                                             ast.AsyncFunctionDef)):
                        continue
                    if node in self.barrier_defs:
                        continue
                    for target in self.ctx._referenced_defs(m, node):
                        why = self.barrier_defs.get(target)
                        if why is not None:
                            self.barrier_defs[node] = (
                                f"calls `{getattr(target, 'name', '?')}` "
                                f"which reaches a collective")
                            changed = True
                            break

    # -- axis vocabulary resolution (JX302) --
    def mesh_axes_of_def(self, fn: ast.AST) -> Set[str]:
        """Literal axis names of every Mesh(...) constructed in `fn` or
        in defs it references (data_mesh -> {"dcn","data","model"}).
        Empty set = unresolvable, caller must skip."""
        cached = self._mesh_axes_cache.get(fn)
        if cached is not None:
            return cached
        axes: Set[str] = set()
        seen: Set[ast.AST] = set()
        work = [fn]
        while work:
            cur = work.pop()
            if cur in seen:
                continue
            seen.add(cur)
            m = self.ctx.module_of(cur)
            if m is None:
                continue
            for node in ast.walk(cur):
                if _is_call_to(node, {"Mesh"}):
                    for arg in list(node.args[1:]) + [
                            kw.value for kw in node.keywords]:
                        axes |= _const_strs(arg)
            work.extend(self.ctx._referenced_defs(m, cur))
        self._mesh_axes_cache[fn] = axes
        return axes

    def resolve_mesh_axes(self, m: Module, site: ast.AST,
                          mesh_expr: ast.AST) -> Set[str]:
        """Axis names the mesh at a shard_map call site carries, when
        statically resolvable; empty set when not."""
        # literal Mesh(devices, ("dcn", "data")) at the site
        if _is_call_to(mesh_expr, {"Mesh"}):
            out: Set[str] = set()
            for arg in list(mesh_expr.args[1:]) + [
                    kw.value for kw in mesh_expr.keywords]:
                out |= _const_strs(arg)
            return out
        # call to a resolvable mesh-producing def
        if isinstance(mesh_expr, ast.Call):
            for d in self._resolve_name(m, site, mesh_expr.func):
                return self.mesh_axes_of_def(d)
            return set()
        # a name bound in the enclosing function: mesh = lifecycle_mesh()
        if isinstance(mesh_expr, ast.Name):
            fn = m.enclosing_function(site)
            if fn is None:
                return set()
            for node in ast.walk(fn):
                if (isinstance(node, ast.Assign) and len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Name)
                        and node.targets[0].id == mesh_expr.id):
                    return self.resolve_mesh_axes(m, site, node.value)
        return set()

    def _resolve_name(self, m: Module, site: ast.AST,
                      func: ast.AST) -> List[ast.AST]:
        """Defs a call target resolves to: module-local first, then a
        unique package-wide match (the PackageContext convention)."""
        tail = dotted_name(func).split(".")[-1]
        if not tail:
            return []
        hits = self.ctx.defs_named(m, tail)
        if hits:
            return hits
        g = self.ctx._defs_global.get(tail, [])
        return g if len(g) == 1 else []


def _spmd(ctx: PackageContext) -> _SpmdAnalysis:
    cached = getattr(ctx, "_spmd_analysis", None)
    if cached is None:
        cached = _SpmdAnalysis(ctx)
        ctx._spmd_analysis = cached
    return cached


def _divergent_test(m: Module, fn: Optional[ast.AST],
                    test: ast.AST) -> Optional[str]:
    """Why this branch predicate differs per host, or None. Matches the
    per-host vocabulary in the test source itself, or a name the
    enclosing function bound from a per-host expression."""
    seg = m.segment(test)
    hit = _DIVERGENT_RE.search(seg)
    if hit:
        return f"`{hit.group(0)}` in the predicate"
    if fn is None:
        return None
    names = {n.id for n in ast.walk(test)
             if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)}
    if not names:
        return None
    for node in ast.walk(fn):
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id in names):
            hit = _DIVERGENT_RE.search(m.segment(node.value))
            if hit:
                return (f"`{node.targets[0].id}` is per-host "
                        f"(`{hit.group(0)}`, line {node.lineno})")
    return None


@register
class DivergentCollective(Rule):
    """JX301 — collective/barrier reachable under per-host control flow.

    Every host must arrive at a psum / window_reduce / fleet_reduce /
    shard_map dispatch / hostsync publish-await together; a branch
    conditioned on process_index()/host_index means only SOME do — the
    rest deadlock until the host-wait timeout.

    bad:  if plan.host_index == 0:
              hostsync.await_parts(root, "stats", plan, sha)  # peers
              # never publish/await -> leader times out
    good: every host publishes and awaits; leader-ONLY work (writing the
          merged artifact) goes after the barrier, guarded alone:
              parts = hostsync.await_parts(root, "stats", plan, sha)
              if plan.host_index == 0:
                  write_merged(parts)
    """

    id = "JX301"
    severity = "error"
    summary = ("collective or hostsync barrier under a branch "
               "conditioned on process_index()/host_index — only some "
               "hosts arrive (deadlock)")

    def check(self, module: Module,
              ctx: PackageContext) -> Iterator["Finding"]:
        an = _spmd(ctx)
        for branch in ast.walk(module.tree):
            if not isinstance(branch, (ast.If, ast.While)):
                continue
            fn = module.enclosing_function(branch)
            why = _divergent_test(module, fn, branch.test)
            if why is None:
                continue
            test_nodes = set(ast.walk(branch.test))
            for sub in ast.walk(branch):
                if sub in test_nodes or not isinstance(sub, ast.Call):
                    continue
                name = _is_call_to(sub, _BARRIER_TAILS)
                if name:
                    yield self.finding(
                        module, sub,
                        f"`{name}` under a per-host branch at line "
                        f"{branch.lineno} ({why}) — every host must "
                        f"reach this barrier; hoist it out and guard "
                        f"only the leader-local work")
                    continue
                for callee in an._resolve_name(module, branch, sub.func):
                    reason = an.barrier_defs.get(callee)
                    if reason:
                        yield self.finding(
                            module, sub,
                            f"`{dotted_name(sub.func)}` {reason}, and "
                            f"is called under a per-host branch at "
                            f"line {branch.lineno} ({why}) — only some "
                            f"hosts would arrive at that barrier")
                        break


@register
class AxisNameDiscipline(Rule):
    """JX302 — collective axis names must exist in the mesh at the
    shard_map call site.

    bad:  mesh = Mesh(devs, ("data",))
          shard_map_compat(body, mesh=mesh, ...)   # body does
          ...jax.lax.psum(x, "model")              # no "model" axis
    good: name only axes the mesh spec carries — thread row_axes(mesh)
          into the body instead of hard-coding, as ops/binagg.py does.

    Interprocedural: the body def is resolved through the package call
    graph; the mesh operand resolves through literal Mesh(...) specs and
    mesh-producing defs (data_mesh, lifecycle_mesh). Unresolvable axis
    operands (variables) and unresolvable meshes are skipped, not
    guessed.
    """

    id = "JX302"
    severity = "error"
    summary = ("collective inside shard_map names an axis absent from "
               "the mesh spec at the dispatch site")

    _AXIS_KWARGS = {"axis_name", "axis", "axis_names"}

    def check(self, module: Module,
              ctx: PackageContext) -> Iterator["Finding"]:
        an = _spmd(ctx)
        for site in ast.walk(module.tree):
            if not isinstance(site, ast.Call):
                continue
            if not _is_call_to(site, {"shard_map", "shard_map_compat"}):
                continue
            mesh_expr = None
            for kw in site.keywords:
                if kw.arg == "mesh":
                    mesh_expr = kw.value
            if mesh_expr is None and len(site.args) >= 2:
                mesh_expr = site.args[1]
            if mesh_expr is None:
                continue
            declared = an.resolve_mesh_axes(module, site, mesh_expr)
            if not declared:
                continue  # unresolvable mesh: do not guess
            for body_m, call, axis in self._used_axes(module, an, site):
                if axis not in declared:
                    yield self.finding(
                        body_m, call,
                        f"`{dotted_name(call.func)}` names axis "
                        f"'{axis}' but the mesh at the shard_map site "
                        f"({module.path}:{site.lineno}) declares "
                        f"{sorted(declared)} — name only mesh axes "
                        f"(thread row_axes(mesh) instead of "
                        f"hard-coding)")

    def _used_axes(self, module: Module, an: _SpmdAnalysis,
                   site: ast.Call):
        """(module, collective call, literal axis) triples inside the
        function the shard_map site wraps, following module-local
        references."""
        bodies: List[Tuple[Module, ast.AST]] = []
        if site.args:
            arg = site.args[0]
            if isinstance(arg, ast.Lambda):
                bodies.append((module, arg))
            else:
                for d in an._resolve_name(module, site, arg):
                    m = an.ctx.module_of(d)
                    if m is not None:
                        bodies.append((m, d))
        seen: Set[ast.AST] = set()
        while bodies:
            m, fn = bodies.pop()
            if fn in seen:
                continue
            seen.add(fn)
            for node in ast.walk(fn):
                name = _is_call_to(node, _COLLECTIVE_TAILS
                                   | {"axis_index", "pbroadcast"})
                if name:
                    for axis in self._axis_operand(node):
                        yield m, node, axis
                elif isinstance(node, ast.Call):
                    for d in an._resolve_name(m, fn, node.func):
                        dm = an.ctx.module_of(d)
                        if dm is not None:
                            bodies.append((dm, d))

    def _axis_operand(self, call: ast.Call) -> Set[str]:
        for kw in call.keywords:
            if kw.arg in self._AXIS_KWARGS:
                return _const_strs(kw.value)
        if len(call.args) >= 2:
            return _const_strs(call.args[1])
        if len(call.args) == 1 and _is_call_to(
                call, {"axis_index"}):
            return _const_strs(call.args[0])
        return set()


@register
class UnsortedMergeOrder(Rule):
    """SH301 — arbitrary-order iteration where order reaches bytes.

    Filesystem listings (os.listdir, glob) come back in readdir order —
    different per host, per filesystem, per run; set iteration order is
    hash-seed dependent. Any of these feeding an artifact writer, a
    hostsync merge, or a fingerprint breaks the byte-identical contract
    between hosts (and between a run and its resume).

    bad:  for path in glob.glob(os.path.join(d, "part-*")):
              merge(path)                       # readdir order
    good: for path in fs.sorted_glob(os.path.join(d, "part-*")):
              merge(path)                       # one shared helper
    Order-insensitive consumption (set(...), len(...), membership,
    set.update) is recognized and not flagged.
    """

    id = "SH301"
    severity = "error"
    summary = ("unsorted os.listdir/glob/set iteration — arbitrary "
               "order where deterministic bytes are required; wrap in "
               "sorted() / fs.sorted_glob")

    def check(self, module: Module,
              ctx: PackageContext) -> Iterator["Finding"]:
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Call):
                tail = _is_call_to(node, set(_LISTING_TAILS))
                if not tail:
                    continue
                if not self._order_insensitive(module, node):
                    yield self.finding(
                        module, node,
                        f"`{tail}` returns entries in arbitrary "
                        f"filesystem order — wrap in sorted() (or use "
                        f"the shared fs.sorted_glob/sorted_listdir "
                        f"helpers) before the order can reach "
                        f"artifact bytes")
            elif isinstance(node, (ast.For, ast.comprehension)):
                it = node.iter
                if self._is_set_expr(it) and not \
                        self._order_insensitive(module, it):
                    yield self.finding(
                        module, it,
                        "iterating a set — order is hash-seed "
                        "dependent and differs across hosts; iterate "
                        "sorted(...) when the order can reach "
                        "artifact bytes")

    @staticmethod
    def _is_set_expr(node: ast.AST) -> bool:
        return isinstance(node, (ast.Set, ast.SetComp)) or bool(
            _is_call_to(node, {"set", "frozenset"}))

    @staticmethod
    def _order_insensitive(module: Module, node: ast.AST) -> bool:
        """Is this listing consumed in a way where order cannot matter?
        Checked lexically up the expression spine of the statement."""
        child = node
        for anc in module.ancestors(node):
            if isinstance(anc, ast.Call):
                name = dotted_name(anc.func)
                tail = name.split(".")[-1]
                if anc.func is child:
                    return False  # the listing IS the callee
                if tail in _ORDER_FREE_WRAPPERS:
                    return True
                if tail in ("update", "union", "intersection",
                            "difference", "rmtree"):
                    return True  # set algebra / recursive delete
            elif isinstance(anc, ast.Compare) and any(
                    isinstance(op, (ast.In, ast.NotIn))
                    for op in anc.ops):
                return True  # membership test
            elif isinstance(anc, ast.SetComp):
                return True  # result is a set: order cannot survive
            elif isinstance(anc, (ast.comprehension, ast.ListComp,
                                  ast.GeneratorExp)):
                pass  # element order maps 1:1 — judged by the consumer
            elif isinstance(anc, ast.stmt):
                return False
            child = anc
        return False


@register
class BarrierOrderCycle(Rule):
    """SH302 — hostsync barriers awaited in opposite orders.

    The cross-process analog of SH202's lock-order graph: host A awaits
    step "x" then "y" while host B's code path awaits "y" then "x" —
    each is parked at a barrier the other has not published yet, and
    both time out. One global barrier order per run, like one global
    lock order per process.

    bad:  def path_a(...):
              hostsync.await_parts(root, "stats-pass1", ...)
              hostsync.await_parts(root, "stats-pass2", ...)
          def path_b(...):
              hostsync.await_parts(root, "stats-pass2", ...)
              hostsync.await_parts(root, "stats-pass1", ...)
    good: every code path awaits the steps in one documented order
          (pass1 before pass2, init before stats before norm).
    """

    id = "SH302"
    severity = "error"
    summary = ("two hostsync barrier steps awaited in opposite orders "
               "on different call paths (cross-host deadlock)")

    def check(self, module: Module,
              ctx: PackageContext) -> Iterator["Finding"]:
        edges = self._edges(ctx)
        cycles = self._cycle_edges(ctx, edges)
        for (a, b), (m, site) in sorted(
                edges.items(), key=lambda kv: (kv[1][0].path,
                                               kv[1][1].lineno)):
            if m is not module or (a, b) not in cycles:
                continue
            others = [f"{om.path}:{osite.lineno}"
                      for (x, y), (om, osite) in sorted(
                          edges.items(), key=lambda kv: kv[0])
                      if (x, y) != (a, b) and {x, y} == {a, b}]
            yield self.finding(
                module, site,
                f"barrier order '{a}' -> '{b}' here is reversed "
                f"elsewhere ({'; '.join(others) or 'see graph'}) — "
                f"hosts taking different paths deadlock; fix ONE "
                f"global await order for these steps")

    # step-name extraction: await_parts(root, "step", ...) or step="..."
    @staticmethod
    def _step_of(call: ast.Call) -> Optional[str]:
        for kw in call.keywords:
            if kw.arg == "step" and isinstance(kw.value, ast.Constant) \
                    and isinstance(kw.value.value, str):
                return kw.value.value
        if len(call.args) >= 2 and isinstance(call.args[1], ast.Constant) \
                and isinstance(call.args[1].value, str):
            return call.args[1].value
        return None

    def _await_seq(self, an: _SpmdAnalysis, m: Module,
                   fn: ast.AST, depth: int = 1
                   ) -> List[Tuple[str, Module, ast.AST]]:
        """Static steps awaited by `fn`, in source order, following
        resolvable calls one hop (the SH202 convention)."""
        out: List[Tuple[str, Module, ast.AST]] = []
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            if m.enclosing_function(node) is not fn:
                continue
            if _is_call_to(node, {"await_parts"}):
                step = self._step_of(node)
                if step is not None:
                    out.append((step, m, node))
            elif depth > 0:
                for callee in an._resolve_name(m, fn, node.func):
                    cm = an.ctx.module_of(callee)
                    if cm is not None:
                        for (s, _sm, _sn) in self._await_seq(
                                an, cm, callee, depth - 1):
                            out.append((s, m, node))
        out.sort(key=lambda t: (t[2].lineno, t[2].col_offset))
        return out

    def _edges(self, ctx: PackageContext
               ) -> Dict[Tuple[str, str], Tuple[Module, ast.AST]]:
        cached = getattr(ctx, "_spmd_barrier_edges", None)
        if cached is not None:
            return cached
        an = _spmd(ctx)
        edges: Dict[Tuple[str, str], Tuple[Module, ast.AST]] = {}
        for m in ctx.modules:
            for node in ast.walk(m.tree):
                if not isinstance(node, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                    continue
                seq = self._await_seq(an, m, node)
                for i in range(len(seq)):
                    for j in range(i + 1, len(seq)):
                        a, b = seq[i][0], seq[j][0]
                        if a != b:
                            edges.setdefault((a, b),
                                             (seq[j][1], seq[j][2]))
        ctx._spmd_barrier_edges = edges
        return edges

    @staticmethod
    def _cycle_edges(ctx: PackageContext, edges) -> Set[Tuple[str, str]]:
        cached = getattr(ctx, "_spmd_barrier_cycles", None)
        if cached is not None:
            return cached
        adj: Dict[str, Set[str]] = {}
        for (a, b) in edges:
            adj.setdefault(a, set()).add(b)

        def reaches(src: str, dst: str) -> bool:
            seen, work = set(), [src]
            while work:
                cur = work.pop()
                if cur == dst:
                    return True
                if cur in seen:
                    continue
                seen.add(cur)
                work.extend(adj.get(cur, ()))
            return False

        out = {(a, b) for (a, b) in edges if reaches(b, a)}
        ctx._spmd_barrier_cycles = out
        return out


@register
class NondeterministicFingerprint(Rule):
    """SH303 — wall-clock/randomness reaching a content fingerprint.

    A config sha / stream sha / file digest names CONTENT: two runs (or
    two hosts) hashing the same content must get the same name, or
    resume matching, hostsync part identity, dedup and the parity pins
    all silently break. time.time/uuid4/random in the hash input makes
    every fingerprint unique.

    bad:  def _stream_config_sha(...):
              ident = {..., "run": uuid.uuid4().hex}   # never matches
              return config_sha(ident)
    good: fingerprint only the content and config; timestamps belong in
          the run LEDGER (manifest), never the identity.
    """

    id = "SH303"
    severity = "error"
    summary = ("wall-clock or randomness (time.time, uuid4, random, "
               "os.urandom) inside a fingerprint/sha/digest "
               "computation")

    def check(self, module: Module,
              ctx: PackageContext) -> Iterator["Finding"]:
        closure = self._closure(ctx)
        for fn, via in closure.items():
            m = ctx.module_of(fn)
            if m is not module:
                continue
            bound = local_bindings(fn)
            for node in ast.walk(fn):
                if m.enclosing_function(node) is not fn:
                    continue
                if not isinstance(node, ast.Call):
                    continue
                name = dotted_name(node.func)
                kind = _NONDET_CALLS.get(name) or _NONDET_CALLS.get(
                    name.split(".")[-1] if name.split(".")[-1]
                    in ("uuid1", "uuid4") else name)
                root = name.split(".")[0]
                if kind is None and root in _NONDET_ROOTS \
                        and root not in bound and "." in name:
                    kind = _NONDET_ROOTS[root]
                if kind:
                    yield self.finding(
                        m, node,
                        f"`{name}` is {kind} inside fingerprint "
                        f"computation `{fn.name}` ({via}) — the sha "
                        f"must name the content; move run metadata to "
                        f"the manifest")

    @staticmethod
    def _closure(ctx: PackageContext) -> Dict[ast.AST, str]:
        cached = getattr(ctx, "_spmd_fingerprint_closure", None)
        if cached is not None:
            return cached
        roots: Dict[ast.AST, str] = {}
        for m in ctx.modules:
            for node in ast.walk(m.tree):
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)) \
                        and _fingerprint_named(node.name):
                    roots.setdefault(node, "fingerprint-named def")
        out = ctx.reachable(roots)
        ctx._spmd_fingerprint_closure = out
        return out
