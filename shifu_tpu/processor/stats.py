"""`shifu stats` — compute per-column statistics and binning.

Parity: core/processor/StatsModelProcessor.java:116 (SPDTI executor path) +
optional -correlation / -psi / -rebin flags.
"""

from __future__ import annotations

import os

from shifu_tpu.data.reader import read_columnar, read_header
from shifu_tpu.processor.basic import BasicProcessor
from shifu_tpu.utils.log import get_logger

log = get_logger(__name__)


class StatsProcessor(BasicProcessor):
    step = "stats"

    def __init__(
        self,
        root: str = ".",
        correlation: bool = False,
        psi: bool = False,
        rebin: bool = False,
        host_plan=None,
    ):
        super().__init__(root)
        self.correlation = correlation
        self.psi = psi
        self.rebin = rebin
        # explicit HostPlan override for in-process multi-host drivers
        # (tests/bench); production processes read the lifecycle knobs
        self.host_plan = host_plan

    def _load_data(self):
        mc = self.model_config
        assert mc is not None
        ds = mc.data_set
        if ds.header_path:
            names = read_header(self.resolve(ds.header_path), ds.header_delimiter)
        else:
            names = [c.column_name for c in self.column_configs]
        return read_columnar(
            self.resolve(ds.data_path),
            names,
            delimiter=ds.data_delimiter,
            missing_values=tuple(ds.missing_or_invalid_values),
        )

    def _streaming_columns(self, names):
        """Columns the streaming stats passes actually read: target +
        weight + every stats candidate. Meta/padding columns never leave
        the CSV tokenizer — the bounded-memory envelope depends on it.
        Returns None (parse everything) when filter expressions are set,
        since those may reference any column."""
        mc = self.model_config
        if mc.data_set.filter_expressions:
            return None
        needed = {
            c.column_name for c in self.column_configs
            if not (c.is_meta() or c.is_weight())
        }
        needed.add(mc.data_set.target_column_name)
        if mc.data_set.weight_column_name:
            needed.add(mc.data_set.weight_column_name)
        if self.psi and (mc.stats.psi_column_name or "").strip():
            # the PSI unit column is often a meta column — keep it parsed
            needed.add(mc.stats.psi_column_name.strip())
        return [n for n in names if n in needed]

    def run_step(self) -> None:
        self.setup()
        mc = self.model_config
        assert mc is not None

        if self.rebin:
            # -rebin re-derives bins from the EXISTING stats (DIB path,
            # StatsModelProcessor DynamicBinning) — no data re-read
            from shifu_tpu.stats.rebin import rebin_columns
            from shifu_tpu.utils import environment

            target = environment.get_int("shifu.rebin.maxNumBin",
                                         mc.stats.max_num_bin)
            n = rebin_columns(self.column_configs, target)
            self.save_column_configs()
            log.info("rebin done: %d columns re-binned to <= %d bins.",
                     n, target)
            return

        from shifu_tpu.data.pipeline import HostPlan
        from shifu_tpu.data.stream import should_stream

        hp = self.host_plan if self.host_plan is not None else HostPlan()
        ds = mc.data_set
        streaming = should_stream(self.resolve(ds.data_path))
        if hp.active and not streaming:
            raise ValueError(
                "-Dshifu.lifecycle.hosts > 1 requires the streaming stats "
                "path (dataset under the memory budget loads in one "
                "process) — drop the hosts knob or lower "
                "shifu.stream.memoryBudgetMb")
        if hp.active and (self.correlation or self.psi):
            raise ValueError(
                "-correlation/-psi are not multi-host capable: the "
                "correlation moments share one shift derived from the "
                "globally first chunk, which no single host owns — run "
                "the extra pass on one process (the stats pass itself "
                "can stay multi-host)")
        if streaming:
            # bounded-memory path: two chunked passes, sketch-based bins
            from shifu_tpu.data.stream import chunk_source
            from shifu_tpu.stats.engine import compute_stats_streaming

            if ds.header_path:
                names = read_header(self.resolve(ds.header_path),
                                    ds.header_delimiter)
            else:
                names = [c.column_name for c in self.column_configs]
            factory = chunk_source(
                self.resolve(ds.data_path),
                names,
                delimiter=ds.data_delimiter,
                missing_values=tuple(ds.missing_or_invalid_values),
                columns=self._streaming_columns(names),
            )
            log.info("dataset exceeds the ingest memory budget; "
                     "streaming stats in chunks")
            from shifu_tpu.resilience.checkpoint import resume_requested

            compute_stats_streaming(mc, self.column_configs, factory,
                                    checkpoint_root=self.root,
                                    resume=resume_requested(),
                                    host_plan=hp)
            data = None
        else:
            data = self._load_data()

            from shifu_tpu.stats.engine import compute_stats

            compute_stats(mc, self.column_configs, data)

        if self.correlation or self.psi:
            self.paths.ensure(self.paths.tmp_dir("stats"))
        psi_col = (mc.stats.psi_column_name or "").strip()
        if self.psi and not psi_col:
            log.warning("-psi requested but stats.psiColumnName is empty; skipped")

        if streaming and (self.correlation or (self.psi and psi_col)):
            # one more chunked pass accumulating both artifacts, divided
            # over the lifecycle ShardPlan like the stats folds: chunk ci
            # feeds shard ci % S's own accumulators, shards merge in shard
            # order at the end — S per-shard PSI counts sum exactly (f64
            # integer counts) and the correlation moments share ONE shift
            # (derived from the globally first chunk), so the merged
            # result is byte-identical to the S=1 fold on integral data
            from shifu_tpu.data.pipeline import ShardPlan, prefetch_iter
            from shifu_tpu.stats.correlation import (
                StreamingCorrelation,
                save_correlation_csv,
            )
            from shifu_tpu.stats.psi import PsiAccumulator

            plan = ShardPlan()
            S = plan.n_shards
            corr_accs = psi_accs = None
            if self.psi and psi_col:
                psi_accs = [PsiAccumulator(self.column_configs, psi_col)
                            for _ in range(S)]
            shift = None
            # parse rides on the prefetch thread while this thread folds
            # the per-shard correlation/PSI accumulators
            for ci, chunk in prefetch_iter(enumerate(factory())):
                if self.correlation and corr_accs is None:
                    shift = StreamingCorrelation.shift_of(
                        chunk, self.column_configs)
                    corr_accs = [StreamingCorrelation(shift=shift)
                                 for _ in range(S)]
                shard = plan.shard_of(ci)
                if corr_accs is not None:
                    corr_accs[shard].update(chunk, self.column_configs)
                if psi_accs is not None:
                    psi_accs[shard].update(chunk)
                plan.record(shard, chunk.n_rows, "corrpsi")
            if corr_accs is not None:
                corr_acc = corr_accs[0]
                for other in corr_accs[1:]:
                    corr_acc.merge(other)
                corr, names = corr_acc.finalize()
                save_correlation_csv(self.paths.correlation_path(), corr, names)
                log.info("correlation matrix (%d x %d) -> %s [%d shards]",
                         len(names), len(names),
                         self.paths.correlation_path(), S)
            if psi_accs is not None:
                psi_acc = psi_accs[0]
                for other in psi_accs[1:]:
                    psi_acc.merge(other)
                psi_acc.finalize()
                log.info("PSI computed against unit column %s [%d shards]",
                         psi_col, S)
        else:
            if self.correlation:
                from shifu_tpu.stats.correlation import (
                    column_correlation,
                    save_correlation_csv,
                )

                corr, names = column_correlation(data, self.column_configs)
                save_correlation_csv(self.paths.correlation_path(), corr, names)
                log.info(
                    "correlation matrix (%d x %d) -> %s",
                    len(names), len(names), self.paths.correlation_path(),
                )
            if self.psi and psi_col:
                from shifu_tpu.stats.psi import compute_psi

                compute_psi(data, self.column_configs, psi_col)
                log.info("PSI computed against unit column %s", psi_col)

        if hp.active and not hp.is_merge_host:
            # every host computed the identical merged stats (the barrier
            # all-gathers sketches and folds), but exactly one process
            # writes ColumnConfig.json — artifact writes must not race
            log.info("stats computed on host %d/%d; merge host writes "
                     "ColumnConfig.json", hp.host_index, hp.n_hosts)
            return
        self.save_column_configs()
        n_binned = sum(1 for c in self.column_configs if c.column_binning.length)
        log.info("stats written for %d columns.", n_binned)
