"""Test configuration: force a virtual 8-device CPU mesh BEFORE jax loads.

Multi-chip sharding logic is exercised the way the reference exercises its
BSP protocol without a cluster (core/dtrain/DTrainTest.java:44 simulates N
workers in-process): same pure step functions, N virtual devices.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from shifu_tpu.utils.platform import force_platform  # noqa: E402

force_platform("cpu", n_devices=8)
