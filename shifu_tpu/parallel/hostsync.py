"""Host part exchange: the merge fabric of the pod-scale data plane.

A HostPlan (data/pipeline.py) hands every process its own chunk-file
slice; this module is how the per-host partial results come back
together. Each host publishes its partial (named numpy arrays + JSON
meta + an optional pickled blob, e.g. pass-1 sketches) as ONE atomic
npz under the model set's run ledger:

    <root>/.shifu/runs/hosts/<step>/part-h000.npz

and `await_parts` blocks until every host's part for the same stream
identity (the caller's config sha) is present, returning them in
SORTED-HOST order — the deterministic merge order that keeps
multi-process artifacts byte-identical to the 1-process run. The
filesystem is the exchange medium on purpose: it is the same shared
ledger the PR-14 leases and the PR-17 metrics time-series already ride,
it needs no sockets or rendezvous address, and `atomic_write` makes a
mid-publish kill invisible (the previous complete part, or none, never
a torn one).

Parts are keyed by the caller's config sha, so an awaiting host ignores
(keeps waiting past) parts left by a run with different chunk geometry
or columns. Parts from a previous run of the IDENTICAL config are
indistinguishable by design — the fold is deterministic, so a stale
part equals the part its host is about to republish. A fresh (non
resumed) run still calls `clear_part` before streaming so a crashed
half-fleet never leaves one-run-old state behind longer than necessary.

Metrics: host.parts_published, host.parts_merged, host.await_seconds.
"""

from __future__ import annotations

import io
import json
import os
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from shifu_tpu.analysis import sanitize
from shifu_tpu.utils import environment
from shifu_tpu.utils.log import get_logger

log = get_logger(__name__)

META_KEY = "__meta__"
BLOB_KEY = "__blob__"

HOSTS_SUBDIR = os.path.join(".shifu", "runs", "hosts")

DEFAULT_WAIT_MS = 600_000

Part = Tuple[Dict[str, np.ndarray], dict, Optional[bytes]]


def host_wait_ms_setting() -> float:
    """shifu.lifecycle.hostWaitMs — how long a host waits for its peers'
    parts at a merge barrier before failing loudly (a dead peer must
    surface as an error, not a hang)."""
    return environment.get_float("shifu.lifecycle.hostWaitMs",
                                 DEFAULT_WAIT_MS)


def parts_dir(root: str, step: str) -> str:
    return os.path.join(os.path.abspath(root), HOSTS_SUBDIR, step)


def part_path(root: str, step: str, host_index: int) -> str:
    return os.path.join(parts_dir(root, step), f"part-h{host_index:03d}.npz")


def publish_part(root: str, step: str, host_plan, sha: str,
                 arrays: Optional[Dict[str, np.ndarray]] = None,
                 meta: Optional[dict] = None,
                 blob: Optional[bytes] = None) -> str:
    """Atomically publish this host's partial for `step`."""
    from shifu_tpu.obs import registry
    from shifu_tpu.resilience.checkpoint import atomic_write

    payload: Dict[str, np.ndarray] = {}
    for k, v in (arrays or {}).items():
        assert not k.startswith("__"), k
        payload[k] = np.asarray(v)
    header = {
        "host": host_plan.host_index,
        "hosts": host_plan.n_hosts,
        "configSha": sha,
        "meta": meta or {},
    }
    # -Dshifu.sanitize=divergence: stamp the part with a monotone
    # per-(step, host) sequence id and a digest of (config sha, step,
    # call-site, merge-key ORDER) — awaiting peers refuse to merge a
    # part whose stamp disagrees with their own (analysis/sanitize.py)
    stamp = sanitize.barrier_stamp(
        step, host_plan.host_index, sha,
        list(arrays or ()) + list(meta or ()))
    if stamp is not None:
        header["sanitize"] = stamp
    payload[META_KEY] = np.frombuffer(
        json.dumps(header, sort_keys=True).encode("utf-8"), dtype=np.uint8)
    if blob is not None:
        payload[BLOB_KEY] = np.frombuffer(blob, dtype=np.uint8)
    buf = io.BytesIO()
    np.savez(buf, **payload)
    path = atomic_write(part_path(root, step, host_plan.host_index),
                        buf.getvalue())
    registry().counter("host.parts_published", step=step,
                       host=str(host_plan.host_index)).inc()
    return path


def clear_part(root: str, step: str, host_plan) -> None:
    """Remove this host's OWN previous part (fresh runs call this before
    streaming; other hosts' parts are their live state)."""
    try:
        os.unlink(part_path(root, step, host_plan.host_index))
    except OSError:  # never published / already cleared
        pass


def _read_part(path: str, sha: str, n_hosts: int):
    """(arrays, header, blob) when the part is complete and belongs to
    this stream (sha + host count match), else None — corrupt or foreign
    parts read as 'not arrived yet' and the barrier keeps waiting for
    the owner to republish."""
    try:
        with np.load(path) as z:
            header = json.loads(bytes(z[META_KEY].tobytes()).decode())
            arrays = {k: z[k] for k in z.files
                      if k not in (META_KEY, BLOB_KEY)}
            blob = z[BLOB_KEY].tobytes() if BLOB_KEY in z.files else None
    except Exception:  # torn/in-flight part: reads as "not arrived yet"
        return None
    if header.get("configSha") != sha or header.get("hosts") != n_hosts:
        return None
    return arrays, header, blob


def await_parts(root: str, step: str, host_plan, sha: str,
                timeout_ms: Optional[float] = None,
                poll_s: float = 0.05) -> List[Part]:
    """Block until every host's part for (`step`, `sha`) exists; return
    [(arrays, meta, blob)] in sorted-host order 0..H-1 — the merge order
    the byte-parity contract fixes. Raises TimeoutError when a peer
    never publishes (its process died before the barrier): a hang here
    would be indistinguishable from progress."""
    from shifu_tpu.obs import registry

    H = host_plan.n_hosts
    timeout_ms = host_wait_ms_setting() if timeout_ms is None else timeout_ms
    deadline = time.monotonic() + timeout_ms / 1000.0
    parts: Dict[int, tuple] = {}
    t0 = time.monotonic()
    while True:
        for h in range(H):
            if h in parts:
                continue
            got = _read_part(part_path(root, step, h), sha, H)
            if got is not None:
                parts[h] = got
        if len(parts) == H:
            break
        if time.monotonic() >= deadline:
            missing = sorted(set(range(H)) - set(parts))
            raise TimeoutError(
                f"host barrier '{step}' timed out after {timeout_ms:.0f}ms"
                f" waiting for host part(s) {missing} under"
                f" {parts_dir(root, step)} — peer process(es) dead or"
                " launched with a different config"
                " (-Dshifu.lifecycle.hostWaitMs raises the wait)")
        time.sleep(poll_s)
    # divergence sanitizer: refuse (DivergenceError) to hand back a
    # merge set whose peer stamps disagree with this host's own stamp
    own = host_plan.host_index
    sanitize.check_barrier_stamps(
        step, own,
        parts[own][1].get("sanitize") if own in parts else None,
        {h: hdr.get("sanitize") for h, (_a, hdr, _b) in parts.items()})
    reg = registry()
    reg.timer("host.await_seconds", step=step).add(time.monotonic() - t0)
    reg.counter("host.parts_merged", step=step).inc(H)
    return [(a, hdr.get("meta", {}), b)
            for a, hdr, b in (parts[h] for h in range(H))]
