"""SH2xx rules: thread-safety for the host-side coordination layer.

The reference outsourced coordination to Hadoop MR + ZooKeeper; this
repo pulled it in-process (prefetch workers, the micro-batcher,
ThreadingHTTPServer handlers, traffic-log rotation, shadow scoring, the
drift monitor, the hot-swap registry) and grew ~100 ad-hoc ``_lock``
sites whose discipline was only ever checked by hand — PR 9's review
pass alone fixed several latent races. These rules make thread safety a
checked property the way JX001–JX005 made trace safety one:

  * thread roots are seeded like jit roots: ``threading.Thread(target=
    ...)`` operands, HTTP handler methods, signal/atexit handlers —
    then propagated through the package call graph, so "thread-
    reachable" is a computed fact, not a guess;
  * lock discipline is *inferred* per class: an attribute predominantly
    accessed under ``with self._lock`` is treated as guarded by it, and
    the exceptions are the findings.

SH201  thread-reachable mutation of a guarded attribute without the lock
SH202  inconsistent nested-lock acquisition order (static cycle in the
       lock-order graph = potential deadlock)
SH203  blocking work while holding a lock (device sync, file I/O,
       sleep/join, waiting on an event) — the serve p99 killers
SH204  Event/Condition misuse (notify outside its lock, wait outside a
       predicate loop, unbounded Event.wait)

The runtime counterpart is ``-Dshifu.sanitize=race``
(analysis/racetrack.py): what these rules prove impossible statically,
the tracked-lock instrumentation witnesses at the real interleavings.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, Iterator, List, Optional, Set, Tuple

from shifu_tpu.analysis.engine import (
    Module,
    PackageContext,
    Rule,
    dotted_name,
    register,
)

# constructors that make an attribute lock-like. Condition guards state
# exactly like a lock (it wraps one); Event is signaling, not guarding.
_LOCK_CTORS = {"Lock", "RLock", "tracked_lock"}
_COND_CTORS = {"Condition"}
_EVENT_CTORS = {"Event"}

_MUTATORS = {"append", "extend", "insert", "update", "setdefault", "add",
             "remove", "discard", "clear", "pop", "popleft", "popitem",
             "appendleft"}

_CALLER_HOLDS_RE = re.compile(r"caller\s+holds\s+the\s+lock",
                              re.IGNORECASE)

_HANDLER_BASES = {"BaseHTTPRequestHandler", "SimpleHTTPRequestHandler",
                  "ThreadingHTTPServer", "HTTPServer"}


def _ctor_kind(value: ast.AST) -> Optional[str]:
    """'lock' | 'cond' | 'event' when `value` constructs one."""
    if not isinstance(value, ast.Call):
        return None
    tail = dotted_name(value.func).split(".")[-1]
    if tail in _LOCK_CTORS:
        return "lock"
    if tail in _COND_CTORS:
        return "cond"
    if tail in _EVENT_CTORS:
        return "event"
    return None


def _self_attr(node: ast.AST) -> Optional[str]:
    """'attr' for a `self.attr` expression, else None."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _guarded_by_decorator(fn: ast.AST) -> Optional[str]:
    """The lock attr named by a @guarded_by("_lock") decorator."""
    for dec in getattr(fn, "decorator_list", []):
        if (isinstance(dec, ast.Call)
                and dotted_name(dec.func).split(".")[-1] == "guarded_by"
                and dec.args and isinstance(dec.args[0], ast.Constant)):
            return str(dec.args[0].value)
    return None


def _caller_holds(fn: ast.AST, module: Module) -> bool:
    """The repo's caller-holds conventions: a `*_locked` name suffix, a
    @guarded_by declaration, or a 'caller holds the lock' line in the
    def's source (docstring or comment)."""
    name = getattr(fn, "name", "")
    if name.endswith("_locked"):
        return True
    if _guarded_by_decorator(fn) is not None:
        return True
    return bool(_CALLER_HOLDS_RE.search(module.segment(fn)))


class _ClassLocks:
    """Lock/cond/event attributes of one class + its access ledger."""

    def __init__(self, module: Module, node: ast.ClassDef) -> None:
        self.module = module
        self.node = node
        self.name = node.name
        self.guards: Dict[str, str] = {}   # attr -> "lock" | "cond"
        self.events: Set[str] = set()
        for sub in ast.walk(node):
            attr = None
            if isinstance(sub, ast.Assign) and len(sub.targets) == 1:
                attr = _self_attr(sub.targets[0])
                value = sub.value
            elif isinstance(sub, ast.AnnAssign) and sub.value is not None:
                attr = _self_attr(sub.target)
                value = sub.value
            else:
                continue
            if attr is None:
                continue
            kind = _ctor_kind(value)
            if kind in ("lock", "cond"):
                self.guards[attr] = kind
            elif kind == "event":
                self.events.add(attr)


def _module_locks(module: Module) -> Set[str]:
    """Module-level lock/cond names (`_lock = threading.Lock()`)."""
    out: Set[str] = set()
    for node in module.tree.body:
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and _ctor_kind(node.value) in ("lock", "cond")):
            out.add(node.targets[0].id)
    return out


def _short(path: str) -> str:
    base = os.path.basename(path)
    return base[:-3] if base.endswith(".py") else base


class _Analysis:
    """Package-wide concurrency facts, computed once per PackageContext
    and shared by SH201–SH204 (cached on the ctx instance the way the
    traced set is precomputed for the JX rules)."""

    def __init__(self, ctx: PackageContext) -> None:
        self.ctx = ctx
        self.classes: Dict[ast.ClassDef, _ClassLocks] = {}
        self.module_locks: Dict[Module, Set[str]] = {}
        for m in ctx.modules:
            self.module_locks[m] = _module_locks(m)
            for node in ast.walk(m.tree):
                if isinstance(node, ast.ClassDef):
                    self.classes[node] = _ClassLocks(m, node)
        self.thread_reach = ctx.reachable(self._thread_roots())
        # lock-order graph: (a, b) -> (module, witness node, detail)
        self.edges: Dict[Tuple[str, str],
                         Tuple[Module, ast.AST, str]] = {}
        for m in ctx.modules:
            self._collect_edges(m)

    # ---- thread roots (seeded like jit roots) ----
    def _thread_roots(self) -> Dict[ast.AST, str]:
        roots: Dict[ast.AST, str] = {}

        def add_named(m: Module, site: ast.AST, expr: ast.AST,
                      via: str) -> None:
            if isinstance(expr, ast.Name):
                for d in self.ctx.defs_named(m, expr.id):
                    roots.setdefault(d, via)
            else:
                attr = _self_attr(expr)
                if attr:
                    cls = None
                    for anc in m.ancestors(site):
                        if isinstance(anc, ast.ClassDef):
                            cls = anc.name
                            break
                    if cls:
                        for meth in self.ctx.class_methods(m, cls):
                            if meth.name == attr:
                                roots.setdefault(meth, via)

        for m in self.ctx.modules:
            for node in ast.walk(m.tree):
                if isinstance(node, ast.Call):
                    name = dotted_name(node.func)
                    tail = name.split(".")[-1]
                    if tail in ("Thread", "Timer"):
                        for kw in node.keywords:
                            if kw.arg in ("target", "function"):
                                add_named(m, node, kw.value,
                                          f"{tail}(target=...)")
                    elif name.endswith("signal.signal") and \
                            len(node.args) >= 2:
                        add_named(m, node, node.args[1],
                                  "signal handler")
                    elif name.endswith("atexit.register") and node.args:
                        add_named(m, node, node.args[0],
                                  "atexit handler")
                elif isinstance(node, ast.ClassDef):
                    bases = {dotted_name(b).split(".")[-1]
                             for b in node.bases}
                    if bases & _HANDLER_BASES:
                        for sub in node.body:
                            if isinstance(sub, (ast.FunctionDef,
                                                ast.AsyncFunctionDef)):
                                roots.setdefault(
                                    sub, f"HTTP handler method of "
                                         f"`{node.name}`")
        return roots

    # ---- lock identity + with-subject resolution ----
    def lock_id(self, m: Module, scope_node: ast.AST,
                expr: ast.AST) -> Optional[str]:
        """Stable name of the lock a `with <expr>:` acquires, or None
        when `expr` is not a known lock/cond."""
        attr = _self_attr(expr)
        if attr is not None:
            for anc in m.ancestors(scope_node):
                if isinstance(anc, ast.ClassDef):
                    info = self.classes.get(anc)
                    if info and attr in info.guards:
                        return f"{info.name}.{attr}"
                    return None
            return None
        if isinstance(expr, ast.Name) and \
                expr.id in self.module_locks.get(m, set()):
            return f"{_short(m.path)}.{expr.id}"
        return None

    def held_locks(self, m: Module, node: ast.AST) -> List[str]:
        """Lock ids of every enclosing `with` guarding `node`,
        innermost last."""
        out: List[str] = []
        for anc in m.ancestors(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                break  # a nested def runs later, outside these withs
            if isinstance(anc, ast.With):
                for item in anc.items:
                    lid = self.lock_id(m, anc, item.context_expr)
                    if lid:
                        out.append(lid)
        out.reverse()
        return out

    # ---- lock-order edges (SH202) ----
    def _with_locks_of_def(self, m: Module, fn: ast.AST) -> List[str]:
        """Locks a def acquires directly in its own body (for the
        one-hop edge: `with A:` body calls f(), f acquires B)."""
        out: List[str] = []
        for node in ast.walk(fn):
            if isinstance(node, ast.With):
                for item in node.items:
                    lid = self.lock_id(m, node, item.context_expr)
                    if lid:
                        out.append(lid)
        return out

    def _collect_edges(self, m: Module) -> None:
        for node in ast.walk(m.tree):
            if not isinstance(node, ast.With):
                continue
            outer = [self.lock_id(m, node, it.context_expr)
                     for it in node.items]
            outer = [o for o in outer if o]
            if not outer:
                continue
            for sub in ast.walk(node):
                if sub is node:
                    continue
                if isinstance(sub, ast.With):
                    for it in sub.items:
                        inner = self.lock_id(m, sub, it.context_expr)
                        if inner:
                            for o in outer:
                                self._edge(m, sub, o, inner, "nested with")
                elif isinstance(sub, ast.Call):
                    # one hop: a call made while holding the lock, to a
                    # def we can resolve, that itself acquires locks
                    for callee in self._resolve_call(m, node, sub):
                        cm = self.ctx.module_of(callee) or m
                        for inner in self._with_locks_of_def(cm, callee):
                            for o in outer:
                                self._edge(
                                    m, sub, o, inner,
                                    f"via call to "
                                    f"`{getattr(callee, 'name', '?')}`")

    def _resolve_call(self, m: Module, scope: ast.AST,
                      call: ast.Call) -> List[ast.AST]:
        fn = call.func
        if isinstance(fn, ast.Name):
            return self.ctx.defs_named(m, fn.id)
        attr = _self_attr(fn) if isinstance(fn, ast.Attribute) else None
        if attr:
            for anc in m.ancestors(scope):
                if isinstance(anc, ast.ClassDef):
                    return [meth for meth
                            in self.ctx.class_methods(m, anc.name)
                            if meth.name == attr]
        return []

    def _edge(self, m: Module, site: ast.AST, a: str, b: str,
              how: str) -> None:
        if a == b:
            return
        self.edges.setdefault(
            (a, b), (m, site, f"{m.path}:{site.lineno} ({how})"))

    def cycle_edges(self) -> Dict[Tuple[str, str], List[str]]:
        """Edges that sit on a cycle -> the cycle's lock names.
        Memoized: the edge set is complete after __init__, and SH202
        consults this once per module plus once per finding."""
        cached = getattr(self, "_cycle_edges", None)
        if cached is not None:
            return cached
        adj: Dict[str, Set[str]] = {}
        for (a, b) in self.edges:
            adj.setdefault(a, set()).add(b)

        def reaches(src: str, dst: str) -> bool:
            seen, work = set(), [src]
            while work:
                cur = work.pop()
                if cur == dst:
                    return True
                if cur in seen:
                    continue
                seen.add(cur)
                work.extend(adj.get(cur, ()))
            return False

        out: Dict[Tuple[str, str], List[str]] = {}
        for (a, b) in self.edges:
            if reaches(b, a):
                out[(a, b)] = sorted({a, b})
        self._cycle_edges = out
        return out


def _analysis(ctx: PackageContext) -> _Analysis:
    cached = getattr(ctx, "_concurrency_analysis", None)
    if cached is None:
        cached = _Analysis(ctx)
        ctx._concurrency_analysis = cached
    return cached


def _enclosing_method(module: Module, cls: ast.ClassDef,
                      node: ast.AST) -> Optional[ast.AST]:
    """Nearest enclosing def that is (transitively) inside `cls`."""
    for anc in module.ancestors(node):
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return anc
        if anc is cls:
            return None
    return None


def _is_mutation(module: Module, node: ast.Attribute) -> Optional[str]:
    """How `self.attr` is mutated here: 'assigned', 'augmented',
    'item-assigned', 'deleted', '.<m>() mutated' — None for reads."""
    if isinstance(node.ctx, ast.Store):
        return "assigned"
    if isinstance(node.ctx, ast.Del):
        return "deleted"
    parent = module.parent.get(node)
    if isinstance(parent, ast.AugAssign) and parent.target is node:
        return "augmented"
    if (isinstance(parent, ast.Subscript) and parent.value is node
            and isinstance(parent.ctx, (ast.Store, ast.Del))):
        return "item-assigned"
    if (isinstance(parent, ast.Attribute)
            and parent.attr in _MUTATORS):
        gp = module.parent.get(parent)
        if isinstance(gp, ast.Call) and gp.func is parent:
            return f".{parent.attr}() mutated"
    return None


@register
class GuardedStateMutation(Rule):
    """SH201 — mutation of a lock-guarded attribute without the lock.

    The guard is INFERRED: an attribute of a lock-owning class that is
    predominantly (>= 75%, >= 2 sites) accessed under `with
    self._lock:` outside __init__ is treated as guarded by that lock.

    bad:  class C:
              def __init__(self): self._lock = Lock(); self._n = 0
              def bump(self):
                  with self._lock: self._n += 1
              def reset(self): self._n = 0      # unguarded mutation
    good: take the lock, or declare the convention checkably:
          @guarded_by("_lock") (analysis/racetrack.py) on a method whose
          callers hold the lock (also enforced at runtime under
          -Dshifu.sanitize=race).
    """

    id = "SH201"
    severity = "error"
    summary = ("mutation of an inferred lock-guarded attribute outside "
               "the lock (non-__init__, thread-shared class)")

    MIN_GUARDED = 2
    MIN_FRACTION = 0.75

    def check(self, module: Module,
              ctx: PackageContext) -> Iterator["Finding"]:
        an = _analysis(ctx)
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            info = an.classes.get(node)
            if not info or not info.guards:
                continue
            yield from self._check_class(module, an, info)

    def _check_class(self, module: Module, an: _Analysis,
                     info: _ClassLocks) -> Iterator["Finding"]:
        # access ledger: attr -> [(guarding lock id or None, mutation
        # kind or None, node, method)]
        ledger: Dict[str, List] = {}
        for node in ast.walk(info.node):
            if not isinstance(node, ast.Attribute):
                continue
            attr = _self_attr(node)
            if (attr is None or attr in info.guards
                    or attr in info.events):
                continue
            method = _enclosing_method(module, info.node, node)
            if method is None:
                continue
            mname = getattr(method, "name", "")
            if mname in ("__init__", "__new__", "__post_init__"):
                continue
            held = an.held_locks(module, node)
            own = [h for h in held
                   if h.startswith(info.name + ".")]
            guard = own[-1] if own else None
            if guard is None and _caller_holds(method, module):
                dec = _guarded_by_decorator(method)
                guard = (f"{info.name}.{dec}" if dec
                         else f"{info.name}.(caller-held)")
            ledger.setdefault(attr, []).append(
                (guard, _is_mutation(module, node), node, method))
        for attr, accesses in sorted(ledger.items()):
            guarded = [a for a in accesses if a[0] is not None]
            if len(guarded) < self.MIN_GUARDED:
                continue
            if len(guarded) / len(accesses) < self.MIN_FRACTION:
                continue
            locks = sorted({g for (g, _mu, _n, _m) in guarded
                            if not g.endswith("(caller-held)")})
            lock = locks[0] if locks else f"{info.name}._lock"
            for (guard, mutation, node, method) in accesses:
                if guard is not None or mutation is None:
                    continue
                reach = an.thread_reach.get(method)
                via = (f"; `{method.name}` is thread-reachable "
                       f"({reach})" if reach else "")
                yield self.finding(
                    module, node,
                    f"`self.{attr}` ({mutation} in `{method.name}`) is "
                    f"guarded by `{lock}` at {len(guarded)}/"
                    f"{len(accesses)} access sites but mutated here "
                    f"without it — take the lock or declare "
                    f"@guarded_by{via}")


@register
class LockOrderCycle(Rule):
    """SH202 — inconsistent nested-lock acquisition order.

    bad:  def a(self):
              with self._alock:
                  with self._block: ...
          def b(self):
              with self._block:
                  with self._alock: ...   # reverse order: deadlock
    good: one global acquisition order (document it where the locks are
          constructed), or restructure so the second lock is taken
          after the first is released.
    """

    id = "SH202"
    severity = "error"
    summary = ("static lock-order graph has a cycle — two sites nest "
               "the same locks in opposite orders (potential deadlock)")

    def check(self, module: Module,
              ctx: PackageContext) -> Iterator["Finding"]:
        an = _analysis(ctx)
        for (a, b), names in sorted(an.cycle_edges().items()):
            m, site, detail = an.edges[(a, b)]
            if m is not module:
                continue
            others = [an.edges[e][2] for e in an.cycle_edges()
                      if e != (a, b) and set(e) <= set(names)]
            yield self.finding(
                module, site,
                f"lock order `{a}` -> `{b}` here closes a cycle over "
                f"{{{', '.join(names)}}} (other direction: "
                f"{'; '.join(others) or 'see graph'}) — pick ONE "
                f"global order for these locks")


# blocking-call detection for SH203
# tails that block regardless of receiver; tails needing a receiver/
# root check (os.replace, time.sleep, np.save, .join) have dedicated
# branches in _blocking_reason and must NOT be added here
_BLOCKING_TAILS = {
    "device_get": "a device->host sync",
    "block_until_ready": "a device sync",
    "dispatch": "a compiled-program dispatch",
    "urlopen": "network I/O",
    "atomic_write": "file I/O",
    "atomic_write_json": "file I/O",
    "atomic_save_npy": "file I/O",
}
_OS_IO = {"replace", "rename", "fsync"}


def _blocking_reason(call: ast.Call) -> Optional[str]:
    name = dotted_name(call.func)
    parts = name.split(".")
    tail = parts[-1]
    root = parts[0]
    if tail in _OS_IO:
        return "file I/O" if root == "os" else None
    if tail == "sleep":
        return "a sleep" if root in ("time", "sleep") else None
    if tail in ("save", "load") and root in ("np", "numpy"):
        return "file I/O"
    if root == "subprocess":
        return "a subprocess"
    if tail == "open" and len(parts) == 1:
        return "file I/O"
    if tail == "join" and isinstance(call.func, ast.Attribute):
        # thread join (0 args, or a single numeric timeout) — NOT
        # str.join, whose one argument is an iterable
        if not call.args and not call.keywords:
            return "a thread join"
        if (len(call.args) == 1 and isinstance(call.args[0], ast.Constant)
                and isinstance(call.args[0].value, (int, float))):
            return "a thread join"
        return None
    return _BLOCKING_TAILS.get(tail)


@register
class BlockingUnderLock(Rule):
    """SH203 — blocking work while holding a lock.

    Every thread that needs the lock now queues behind a device sync /
    file write / sleep — on the serve path this is the p99 killer the
    drift-flush and traffic-rotation fixes in this PR removed.

    bad:  with self._lock:
              counts = jax.device_get(self._window)   # d2h under lock
    good: swap the shared state out under the lock, do the blocking
          work outside, merge back under the lock (loop/drift.py
          `_flush`, loop/traffic.py `_write_chunk`).
    """

    id = "SH203"
    severity = "error"
    summary = ("blocking call (device sync, file/socket I/O, sleep, "
               "thread join, event wait) inside a `with lock:` body")

    def check(self, module: Module,
              ctx: PackageContext) -> Iterator["Finding"]:
        an = _analysis(ctx)
        # a caller-holds method (`*_locked` / @guarded_by / "caller
        # holds the lock") runs its WHOLE body under the caller's lock —
        # scan it like a with-body
        for node in ast.walk(module.tree):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            if not _caller_holds(node, module):
                continue
            dec = _guarded_by_decorator(node)
            held = [dec or "(caller-held lock)"]
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call) and \
                        module.enclosing_function(sub) is node:
                    reason = _blocking_reason(sub)
                    if reason:
                        yield self.finding(
                            module, sub,
                            f"`{dotted_name(sub.func) or '<call>'}` is "
                            f"{reason} inside caller-holds method "
                            f"`{node.name}` (runs under `{held[0]}`) — "
                            f"move the blocking work outside the "
                            f"locked region")
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.With):
                continue
            outer = [an.lock_id(module, node, it.context_expr)
                     for it in node.items]
            outer = [o for o in outer if o]
            if not outer:
                continue
            for sub in ast.walk(node):
                if not isinstance(sub, ast.Call):
                    continue
                # a nested def's body runs later, not under this with
                skip = False
                for anc in module.ancestors(sub):
                    if anc is node:
                        break
                    if isinstance(anc, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        skip = True
                        break
                if skip:
                    continue
                yield from self._check_call(module, an, node, outer, sub)

    def _check_call(self, module: Module, an: _Analysis,
                    with_node: ast.With, outer: List[str],
                    call: ast.Call) -> Iterator["Finding"]:
        reason = _blocking_reason(call)
        if reason:
            yield self.finding(
                module, call,
                f"`{dotted_name(call.func) or '<call>'}` is {reason} "
                f"inside `with {outer[-1]}:` — every thread needing "
                f"the lock now waits on it; move the blocking work "
                f"outside (swap state out under the lock)")
            return
        # waiting on an event/condition OTHER than the held lock while
        # holding it: the setter may need this very lock (lost wakeup)
        if (isinstance(call.func, ast.Attribute)
                and call.func.attr in ("wait", "wait_for")):
            subject = an.lock_id(module, with_node, call.func.value)
            if subject is None or subject not in outer:
                recv = dotted_name(call.func.value) or "<event>"
                if self._receiver_waitable(module, an, with_node,
                                           call.func.value):
                    yield self.finding(
                        module, call,
                        f"waiting on `{recv}` while holding "
                        f"`{outer[-1]}` — the setter may need the held "
                        f"lock (deadlock/lost wakeup); wait outside "
                        f"the lock")
            return
        # one hop: a resolvable callee that blocks directly (including
        # caller-holds methods — their bodies run under THIS lock)
        for callee in an._resolve_call(module, with_node, call):
            for sub in ast.walk(callee):
                if isinstance(sub, ast.Call):
                    r = _blocking_reason(sub)
                    if r:
                        yield self.finding(
                            module, call,
                            f"`{getattr(callee, 'name', '?')}()` does "
                            f"{r} (line {sub.lineno}) and is called "
                            f"inside `with {outer[-1]}:` — hoist the "
                            f"blocking work out of the locked region")
                        break
            else:
                continue
            break

    @staticmethod
    def _receiver_waitable(module: Module, an: _Analysis,
                           scope: ast.AST, expr: ast.AST) -> bool:
        """Is the wait() receiver a known Event/Condition (class attr or
        local constructed from threading.Event/Condition)? Unknown
        receivers are skipped — `.wait()` on arbitrary objects (futures,
        subprocesses) has its own semantics."""
        attr = _self_attr(expr)
        if attr is not None:
            for anc in module.ancestors(scope):
                if isinstance(anc, ast.ClassDef):
                    info = an.classes.get(anc)
                    return bool(info) and (attr in info.events
                                           or attr in info.guards)
        if isinstance(expr, ast.Name):
            fn = module.enclosing_function(scope)
            if fn is not None:
                for n in ast.walk(fn):
                    if (isinstance(n, ast.Assign)
                            and len(n.targets) == 1
                            and isinstance(n.targets[0], ast.Name)
                            and n.targets[0].id == expr.id
                            and _ctor_kind(n.value) in ("event", "cond")):
                        return True
        return False


@register
class EventConditionMisuse(Rule):
    """SH204 — Event/Condition protocol violations.

    bad:  self._cond.notify()            # outside `with self._cond:` —
                                         # RuntimeError at runtime
    bad:  with self._cond:
              self._cond.wait()          # no predicate loop: spurious
                                         # wakeups proceed on stale state
    bad:  self._done.wait()              # unbounded: a dead setter
                                         # parks this thread forever
    good: notify under the condition; wait in a `while not pred:` loop;
          give Event.wait a timeout (or justify the park inline).
    """

    id = "SH204"
    severity = "error"
    summary = ("notify outside the condition's lock (error) / cond.wait "
               "without a predicate loop or unbounded Event.wait "
               "(warning)")

    def check(self, module: Module,
              ctx: PackageContext) -> Iterator["Finding"]:
        an = _analysis(ctx)
        for node in ast.walk(module.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)):
                continue
            attr = node.func.attr
            if attr not in ("notify", "notify_all", "wait", "wait_for"):
                continue
            recv = node.func.value
            kind = self._receiver_kind(module, an, node, recv)
            if kind is None:
                continue
            recv_name = dotted_name(recv) or "<sync>"
            if attr in ("notify", "notify_all"):
                if kind != "cond":
                    continue
                if not self._inside_with_of(module, an, node, recv):
                    yield self.finding(
                        module, node,
                        f"`{recv_name}.{attr}()` outside `with "
                        f"{recv_name}:` — raises RuntimeError('cannot "
                        f"notify on un-acquired lock') at runtime")
            elif kind == "cond" and attr == "wait":
                if not self._inside_with_of(module, an, node, recv):
                    yield self.finding(
                        module, node,
                        f"`{recv_name}.wait()` outside `with "
                        f"{recv_name}:` — raises RuntimeError at "
                        f"runtime")
                elif not self._in_loop(module, node):
                    yield self.finding(
                        module, node,
                        f"`{recv_name}.wait()` without a predicate "
                        f"loop — spurious wakeups and stolen wakeups "
                        f"proceed on stale state; use `while not "
                        f"<predicate>: wait()`", severity="warning")
            elif kind == "event" and attr == "wait":
                if not node.args and not node.keywords:
                    yield self.finding(
                        module, node,
                        f"unbounded `{recv_name}.wait()` — if the "
                        f"setter thread died this parks forever; pass "
                        f"a timeout and re-check, or justify the park "
                        f"inline", severity="warning")

    @staticmethod
    def _receiver_kind(module: Module, an: _Analysis, node: ast.AST,
                       recv: ast.AST) -> Optional[str]:
        attr = _self_attr(recv)
        if attr is not None:
            for anc in module.ancestors(node):
                if isinstance(anc, ast.ClassDef):
                    info = an.classes.get(anc)
                    if info is None:
                        return None
                    if attr in info.events:
                        return "event"
                    if info.guards.get(attr) == "cond":
                        return "cond"
                    return None
            return None
        if isinstance(recv, ast.Name):
            fn = module.enclosing_function(node)
            scope = [fn] if fn is not None else []
            for s in scope:
                for n in ast.walk(s):
                    if (isinstance(n, ast.Assign) and len(n.targets) == 1
                            and isinstance(n.targets[0], ast.Name)
                            and n.targets[0].id == recv.id):
                        k = _ctor_kind(n.value)
                        if k == "event":
                            return "event"
                        if k == "cond":
                            return "cond"
        return None

    @staticmethod
    def _inside_with_of(module: Module, an: _Analysis, node: ast.AST,
                        recv: ast.AST) -> bool:
        want = ast.dump(recv)
        for anc in module.ancestors(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return False
            if isinstance(anc, ast.With):
                for item in anc.items:
                    if ast.dump(item.context_expr) == want:
                        return True
        return False

    @staticmethod
    def _in_loop(module: Module, node: ast.AST) -> bool:
        for anc in module.ancestors(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return False
            if isinstance(anc, (ast.While, ast.For)):
                return True
        return False
