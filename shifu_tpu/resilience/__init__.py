"""shifu_tpu.resilience — preemption-safe lifecycle plumbing.

The reference system inherited fault tolerance from its substrate: Guagua
BSP runs inside a Hadoop MapReduce job, so failed workers are retried by
MR and coordinated through ZooKeeper (PAPER.md layer map L3). The TPU
rebuild dropped that substrate, so this package rebuilds the guarantees
as a library, threaded through every long-running path:

  faults.py      deterministic, seeded fault injection at the real seams
                 (-Dshifu.faults=io:p=0.01:seed=7,preempt@chunk=40,...).
                 The same harness CI and the chaos-parity tests drive, so
                 recovery is proven, not assumed.
  retry.py       bounded retry with exponential backoff + full jitter
                 around transient seams (-Dshifu.retry.*); every attempt
                 is ledgered as retry.* metrics.
  checkpoint.py  atomic file writes (temp + os.replace) and mid-stream
                 checkpoint/resume for the chunked fold paths: a
                 preempted host resumes from (chunk_index, fold_state)
                 instead of row zero, bit-identical to an uninterrupted
                 run.
  lease.py       process heartbeat leases (the ZooKeeper-ephemeral-node
                 analog on the shared .shifu/runs root): N serve
                 processes on one model set observe each other's
                 liveness through atomic lease files — the membership
                 layer the fleet-atomic promotion rounds fence against.

All three record into the obs metrics registry, so every injected fault,
retry attempt and checkpoint write lands in the run-ledger manifest.
"""

from shifu_tpu.resilience.checkpoint import (
    StreamCheckpoint,
    atomic_save_npy,
    atomic_write,
    atomic_write_json,
)
from shifu_tpu.resilience.faults import (
    FaultPlan,
    FaultSpecError,
    InjectedFaultError,
    PreemptionError,
    fault_point,
    plan_active,
)
from shifu_tpu.resilience.lease import ProcessLease
from shifu_tpu.resilience.retry import retry_call

__all__ = [
    "FaultPlan",
    "FaultSpecError",
    "InjectedFaultError",
    "PreemptionError",
    "ProcessLease",
    "StreamCheckpoint",
    "atomic_save_npy",
    "atomic_write",
    "atomic_write_json",
    "fault_point",
    "plan_active",
    "retry_call",
]
