"""`shifu test` — dry-run data/filter validation on N sample records.

Parity: core/processor/ShifuTestProcessor.java:33 — parse the first N
records, apply the filter expression, report pass/fail counts and tag
coverage so config errors surface before long jobs.
"""

from __future__ import annotations

from shifu_tpu.data.purify import combined_mask
from shifu_tpu.data.reader import make_tags, read_columnar, read_header
from shifu_tpu.processor.basic import BasicProcessor
from shifu_tpu.utils.log import get_logger

log = get_logger(__name__)


class TestDataProcessor(BasicProcessor):
    step = "test"

    def __init__(self, root: str = ".", n: int = 100):
        super().__init__(root)
        self.n = n

    def run_step(self) -> None:
        self.setup(need_columns=False)
        mc = self.model_config
        ds = mc.data_set
        names = read_header(self.resolve(ds.header_path), ds.header_delimiter)
        data = read_columnar(
            self.resolve(ds.data_path), names, delimiter=ds.data_delimiter,
            missing_values=tuple(ds.missing_or_invalid_values),
            max_rows=self.n,
        )
        log.info("read %d records, %d columns.", data.n_rows, len(names))
        if ds.target_column_name not in names:
            log.error("target column %s NOT in header!", ds.target_column_name)
            return
        mask = combined_mask(ds.filter_expressions, data.raw, data.n_rows)
        log.info("filter `%s`: %d of %d records pass.",
                 ds.filter_expressions or "(none)", int(mask.sum()), data.n_rows)
        tags = make_tags(data.column(ds.target_column_name)[mask],
                         ds.pos_tags, ds.neg_tags)
        n_pos = int((tags == 1).sum())
        n_neg = int((tags == 0).sum())
        n_bad = int((tags == -1).sum())
        log.info("tags: %d positive, %d negative, %d invalid.",
                 n_pos, n_neg, n_bad)
        if n_bad:
            log.warning("%d records have tags outside posTags/negTags!", n_bad)
        if n_pos == 0:
            log.warning("no positive records in the sample — check posTags.")
