"""Bin aggregation: the TPU-native replacement for the UpdateBinningInfo MR
job (core/binning/UpdateBinningInfoMapper.java:71 / Reducer.java:57).

One scatter-add over a flat (column, bin) index space produces every
per-column per-bin count in a single fused XLA program; the multi-chip path
wraps the same function in shard_map over the row axis and psums the
aggregates — the analog of the reference's mapper-side partial sums merged in
one reducer.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P


class BinAggregates(NamedTuple):
    """Flat (column-offset + bin) histograms + per-numeric-column moments."""

    pos: jax.Array  # [total_slots] positive counts
    neg: jax.Array  # [total_slots] negative counts
    wpos: jax.Array  # [total_slots] weighted positive
    wneg: jax.Array  # [total_slots] weighted negative
    vsum: jax.Array  # [n_numeric] sum of non-missing values
    vsumsq: jax.Array  # [n_numeric] sum of squares
    vmin: jax.Array  # [n_numeric]
    vmax: jax.Array  # [n_numeric]
    vcount: jax.Array  # [n_numeric] non-missing count
    vmissing: jax.Array  # [n_numeric] missing count (valid-tag rows)


def bin_aggregate(
    codes: jax.Array,  # [n, C] int32, per-column bin index (missing = last slot)
    col_offsets: jax.Array,  # [C] int32 prefix offsets into the flat slot space
    total_slots: int,
    tags: jax.Array,  # [n] int32 {1 pos, 0 neg, -1 invalid}
    weights: jax.Array,  # [n] float32
    values: jax.Array,  # [n, Cn] float32 numeric matrix, NaN = missing
) -> BinAggregates:
    valid = tags >= 0
    posm = (tags == 1) & valid
    negm = (tags == 0) & valid

    flat = (codes + col_offsets[None, :]).reshape(-1)  # [n*C]
    n, c = codes.shape

    def scatter(row_mask, row_weight):
        contrib = jnp.where(row_mask, row_weight, 0.0).astype(jnp.float32)
        tiled = jnp.repeat(contrib, c)  # row value for every column slot
        return jnp.zeros(total_slots, jnp.float32).at[flat].add(tiled)

    ones = jnp.ones_like(weights)
    pos = scatter(posm, ones)
    neg = scatter(negm, ones)
    wpos = scatter(posm, weights)
    wneg = scatter(negm, weights)

    missing = jnp.isnan(values)
    vvalid = (~missing) & valid[:, None]
    v0 = jnp.where(vvalid, values, 0.0)
    vsum = v0.sum(axis=0)
    vsumsq = (v0 * v0).sum(axis=0)
    vmin = jnp.where(vvalid, values, jnp.inf).min(axis=0)
    vmax = jnp.where(vvalid, values, -jnp.inf).max(axis=0)
    vcount = vvalid.sum(axis=0).astype(jnp.float32)
    vmissing = (missing & valid[:, None]).sum(axis=0).astype(jnp.float32)
    return BinAggregates(pos, neg, wpos, wneg, vsum, vsumsq, vmin, vmax, vcount, vmissing)


bin_aggregate_jit = jax.jit(bin_aggregate, static_argnames=("total_slots",))

# profiled seam for the stats engine (in-RAM pass 2 + streamed chunks):
# same program, with per-dispatch FLOPs/bytes accounting in the obs scope.
# Async — streamed chunks fold into the DeviceAccumulator without a
# per-chunk wait. `bin_aggregate_jit` itself stays raw for direct/test use
# (tests probe its _cache_size underneath this wrapper).
from shifu_tpu.obs.profile import wrap as _profile_wrap  # noqa: E402

bin_aggregate_profiled = _profile_wrap(
    "stats.bin_aggregate", bin_aggregate_jit, sync=False,
    static_argnums=(2,), static_argnames=("total_slots",))


# ---------------------------------------------------------------------------
# sharded window fold / reduce — the lifecycle map/reduce programs
# ---------------------------------------------------------------------------
#
# The streaming folds keep one f32 BinAggregates WINDOW per row shard,
# stacked on a leading [S] axis sharded over the lifecycle mesh
# (parallel/mesh.py). Three programs close the DrJAX map_fn/reduce shape:
#
#   sharded_window_fold   the map: each shard bin-aggregates ITS chunk
#                         locally and folds it into ITS window — one
#                         shard_map dispatch folds up to S chunks, no
#                         cross-shard traffic at all.
#   masked_window_add     fold ONE precomputed aggregate into one shard's
#                         window (the degenerate/manual path — same
#                         program family, a size-S mask instead of a map).
#   window_reduce         the reduce: ONE psum over the row axes (pmin/
#                         pmax for the extrema) replaces S per-shard host
#                         pulls — on a multi-slice mesh the (dcn, data)
#                         axis order makes XLA lower it as a tree, heavy
#                         within-slice over ICI, one partial across DCN.
#
# Identity elements (0 for sums, +/-inf for min/max) make window init a
# plain elementwise combine, so a window that never saw a chunk
# contributes nothing to the reduce.

_WINDOW_PROGRAMS: dict = {}

_MIN_FIELD, _MAX_FIELD = 6, 7  # vmin / vmax positions in BinAggregates


def _combine_aggs(win: BinAggregates, part: BinAggregates) -> BinAggregates:
    out = [w + p for w, p in zip(win, part)]
    out[_MIN_FIELD] = jnp.minimum(win.vmin, part.vmin)
    out[_MAX_FIELD] = jnp.maximum(win.vmax, part.vmax)
    return BinAggregates(*out)


def _row_spec(axes, ndim: int):
    return P(axes if len(axes) > 1 else axes[0], *([None] * (ndim - 1)))


def _mesh_key(mesh) -> tuple:
    return (tuple(mesh.axis_names), tuple(mesh.devices.shape))


def _shard_index(mesh, axes):
    """Linear row-shard index of the executing device inside shard_map."""
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    idx = jax.lax.axis_index(axes[0])
    for a in axes[1:]:
        idx = idx * sizes[a] + jax.lax.axis_index(a)
    return idx


def window_specs(mesh):
    """(sharded, replicated) PartitionSpec pytrees for a stacked [S, ...]
    BinAggregates window."""
    from shifu_tpu.parallel.mesh import row_axes

    axes = row_axes(mesh)
    sharded = BinAggregates(*([_row_spec(axes, 2)] * 10))
    replicated = BinAggregates(*([P(None, None)] * 10))
    return sharded, replicated


def window_init(mesh, total_slots: int, n_numeric: int) -> BinAggregates:
    """Fresh stacked window: zeros for every sum, +/-inf for the extrema,
    placed sharded over the mesh's row axes (one slice per shard)."""
    from jax.sharding import NamedSharding

    from shifu_tpu.parallel.mesh import row_shard_count

    import numpy as np

    S = row_shard_count(mesh)
    sharded, _ = window_specs(mesh)
    host = BinAggregates(
        pos=np.zeros((S, total_slots), np.float32),
        neg=np.zeros((S, total_slots), np.float32),
        wpos=np.zeros((S, total_slots), np.float32),
        wneg=np.zeros((S, total_slots), np.float32),
        vsum=np.zeros((S, n_numeric), np.float32),
        vsumsq=np.zeros((S, n_numeric), np.float32),
        vmin=np.full((S, n_numeric), np.inf, np.float32),
        vmax=np.full((S, n_numeric), -np.inf, np.float32),
        vcount=np.zeros((S, n_numeric), np.float32),
        vmissing=np.zeros((S, n_numeric), np.float32),
    )
    return BinAggregates(*[
        jax.device_put(a, NamedSharding(mesh, s))
        for a, s in zip(host, sharded)])


def sharded_window_fold(mesh, total_slots: int):
    """Jitted map program: (windows [S, ...], codes [S, n, C], offsets [C],
    tags [S, n], weights [S, n], values [S, n, Cn]) -> windows'. Each
    shard aggregates its own row block and folds it into its own window —
    compiled once per (mesh, total_slots, row bucket)."""
    from shifu_tpu.parallel.mesh import row_axes, shard_map_compat

    key = ("fold", _mesh_key(mesh), int(total_slots))
    prog = _WINDOW_PROGRAMS.get(key)
    if prog is not None:
        return prog
    axes = row_axes(mesh)
    sharded, _ = window_specs(mesh)

    def local(win, codes, offsets, tags, weights, values):
        agg = bin_aggregate(codes[0], offsets, total_slots, tags[0],
                            weights[0], values[0])
        return _combine_aggs(win, BinAggregates(*[a[None] for a in agg]))

    prog = jax.jit(shard_map_compat(
        local, mesh=mesh,
        in_specs=(sharded, _row_spec(axes, 3), P(None), _row_spec(axes, 2),
                  _row_spec(axes, 2), _row_spec(axes, 3)),
        out_specs=sharded))
    _WINDOW_PROGRAMS[key] = prog
    return prog


def masked_window_add(mesh):
    """Jitted program folding ONE replicated BinAggregates into the window
    of shard `sid` (identity elements elsewhere) — the precomputed-
    aggregate entry point DeviceAccumulator.add uses."""
    from shifu_tpu.parallel.mesh import row_axes, shard_map_compat

    key = ("add", _mesh_key(mesh))
    prog = _WINDOW_PROGRAMS.get(key)
    if prog is not None:
        return prog
    axes = row_axes(mesh)
    sharded = window_specs(mesh)[0]

    def local(win, agg, sid):
        mine = _shard_index(mesh, axes) == sid
        part = [jnp.where(mine, a, jnp.zeros_like(a))[None] for a in agg]
        part[_MIN_FIELD] = jnp.where(mine, agg.vmin,
                                     jnp.full_like(agg.vmin, jnp.inf))[None]
        part[_MAX_FIELD] = jnp.where(mine, agg.vmax,
                                     jnp.full_like(agg.vmax,
                                                   -jnp.inf))[None]
        return _combine_aggs(win, BinAggregates(*part))

    prog = jax.jit(shard_map_compat(
        local, mesh=mesh,
        in_specs=(sharded, BinAggregates(*([P(None)] * 10)), P()),
        out_specs=sharded))
    _WINDOW_PROGRAMS[key] = prog
    return prog


def window_reduce(mesh):
    """Jitted reduce program: psum (pmin/pmax for extrema) of the stacked
    [S, ...] windows over the mesh's row axes — ONE collective tree
    closes the whole window, so the host pulls ONE replicated result
    instead of S per-shard windows.

    On a multi-slice (dcn, data) mesh the reduce is EXPLICITLY
    hierarchical (unless -Dshifu.reduce.topology=flat): stage 1 psums
    the heavy [S, ...] windows within each slice over ICI, stage 2 moves
    exactly ONE per-slice partial across DCN — the In-Network-Aggregation
    shallow-tree shape, spelled out instead of left to the joint-psum
    lowering. A single-axis mesh keeps the flat one-stage psum (the
    1-slice degenerate case). Either way the reduce is still one
    collective dispatch and the caller still pays one d2h sync per
    window."""
    from shifu_tpu.parallel.mesh import (
        hierarchical_reduce,
        row_axes,
        shard_map_compat,
    )

    staged = hierarchical_reduce(mesh)
    key = ("reduce", _mesh_key(mesh), staged)
    prog = _WINDOW_PROGRAMS.get(key)
    if prog is not None:
        return prog
    axes = row_axes(mesh)
    sharded, replicated = window_specs(mesh)

    if staged:
        ici = tuple(a for a in axes if a != "dcn")

        def stage2(op, x):
            return op(op(x, ici), "dcn")

        def local(win):
            out = [stage2(jax.lax.psum, w) for w in win]
            out[_MIN_FIELD] = stage2(jax.lax.pmin, win.vmin)
            out[_MAX_FIELD] = stage2(jax.lax.pmax, win.vmax)
            return BinAggregates(*out)
    else:
        def local(win):
            out = [jax.lax.psum(w, axes) for w in win]
            out[_MIN_FIELD] = jax.lax.pmin(win.vmin, axes)
            out[_MAX_FIELD] = jax.lax.pmax(win.vmax, axes)
            return BinAggregates(*out)

    prog = jax.jit(shard_map_compat(
        local, mesh=mesh, in_specs=(sharded,), out_specs=replicated))
    _WINDOW_PROGRAMS[key] = prog
    return prog


def bin_aggregate_sharded(
    mesh: Mesh,
    codes: jax.Array,
    col_offsets: jax.Array,
    total_slots: int,
    tags: jax.Array,
    weights: jax.Array,
    values: jax.Array,
    axis: str = "data",
) -> BinAggregates:
    """Row-sharded SPMD variant: each device aggregates its row shard, then a
    single psum merges — gradients-of-histograms over ICI instead of
    ZooKeeper-merged Bytables."""

    def local(codes, tags, weights, values):
        agg = bin_aggregate(codes, col_offsets, total_slots, tags, weights, values)
        psum = lambda x: jax.lax.psum(x, axis)  # noqa: E731
        return BinAggregates(
            pos=psum(agg.pos),
            neg=psum(agg.neg),
            wpos=psum(agg.wpos),
            wneg=psum(agg.wneg),
            vsum=psum(agg.vsum),
            vsumsq=psum(agg.vsumsq),
            vmin=jax.lax.pmin(agg.vmin, axis),
            vmax=jax.lax.pmax(agg.vmax, axis),
            vcount=psum(agg.vcount),
            vmissing=psum(agg.vmissing),
        )

    from shifu_tpu.parallel.mesh import shard_map_compat

    fn = shard_map_compat(
        local,
        mesh=mesh,
        in_specs=(P(axis, None), P(axis), P(axis), P(axis, None)),
        out_specs=BinAggregates(*([P()] * 10)),
        check=True,  # keep the replication check this call always had
    )
    return fn(codes, tags, weights, values)
