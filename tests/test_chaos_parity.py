"""Chaos parity: every resumable streaming path, killed mid-stream by the
deterministic fault injector and resumed, must produce BIT-IDENTICAL
results to an uninterrupted run — and a real SIGTERM mid-train must leave
a failure manifest (the PR-2 ledger contract) and resume to the pinned
final weights.

These are the acceptance tests for the preemption-safe lifecycle: the
recovery machinery is exercised by actual injected kills, never assumed.
"""

import filecmp
import glob
import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from shifu_tpu.resilience import checkpoint as ckpt_mod
from shifu_tpu.resilience import faults
from shifu_tpu.resilience.faults import FaultPlan, PreemptionError
from shifu_tpu.utils import environment
from tests.helpers import make_model_set

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class _StreamEnv:
    """Streaming knobs for one test, restored on exit."""

    def __init__(self, **props):
        self.props = props

    def __enter__(self):
        for k, v in self.props.items():
            environment.set_property(k, v)
        return self

    def __exit__(self, *exc):
        for k in self.props:
            environment.set_property(k, "")


# ---------------------------------------------------------------------------
# streaming stats
# ---------------------------------------------------------------------------


def _stats_stream_setup(tmp_path, n=420, chunk_rows=64):
    from shifu_tpu.config import ColumnConfig, ColumnType
    from shifu_tpu.config.column_config import ColumnFlag
    from shifu_tpu.config.model_config import Algorithm, new_model_config
    from shifu_tpu.data.stream import chunk_source

    rng = np.random.default_rng(0)
    y = (rng.random(n) < 0.35).astype(int)
    num = rng.normal(loc=y[:, None] * 0.7, size=(n, 4))
    cats = np.array(["aa", "bb", "cc"])[rng.integers(0, 3, size=n)]
    names = ["target", "n0", "n1", "n2", "n3", "c0"]
    data_path = os.path.join(str(tmp_path), "data.txt")
    with open(data_path, "w") as fh:
        for i in range(n):
            fh.write("|".join([str(y[i])]
                              + [f"{v:.5f}" for v in num[i]]
                              + [cats[i]]) + "\n")

    mc = new_model_config("ChaosStats", Algorithm.NN)
    mc.data_set.target_column_name = "target"
    mc.data_set.pos_tags = ["1"]
    mc.data_set.neg_tags = ["0"]

    def fresh_cols():
        cols = [ColumnConfig(column_num=0, column_name="target",
                             column_flag=ColumnFlag.TARGET)]
        for j in range(4):
            cols.append(ColumnConfig(column_num=1 + j,
                                     column_name=f"n{j}",
                                     column_type=ColumnType.N))
        cols.append(ColumnConfig(column_num=5, column_name="c0",
                                 column_type=ColumnType.C))
        return cols

    factory = chunk_source(data_path, names, delimiter="|",
                           chunk_rows=chunk_rows)
    return mc, fresh_cols, factory


def _cols_json(cols):
    from shifu_tpu.config.column_config import save_column_config_list

    import tempfile

    with tempfile.NamedTemporaryFile("r", suffix=".json") as fh:
        save_column_config_list(fh.name, cols)
        return open(fh.name).read()


@pytest.mark.parametrize("preempt_at, label", [
    (4, "pass1"),    # 420/64 -> 7 chunks/pass: event 4 dies in pass 1
    (10, "pass2"),   # events 8..14 are pass 2
])
def test_streaming_stats_preempt_resume_bit_identical(
        tmp_path, preempt_at, label):
    from shifu_tpu.stats.engine import compute_stats_streaming

    mc, fresh_cols, factory = _stats_stream_setup(tmp_path)
    root = str(tmp_path / f"root-{label}")

    clean = fresh_cols()
    compute_stats_streaming(mc, clean, factory)

    chaos = fresh_cols()
    with _StreamEnv(**{"shifu.ckpt.everyChunks": "1"}):
        with faults.activate(FaultPlan.parse(f"preempt@chunk={preempt_at}")):
            with pytest.raises(PreemptionError):
                compute_stats_streaming(mc, chaos, factory,
                                        checkpoint_root=root)
        # the snapshot family the kill left behind is listable /
        # resumable: slot files per row shard + the shared commit pointer
        from shifu_tpu.parallel.mesh import lifecycle_shards

        S = lifecycle_shards()
        names = {e["name"] for e in ckpt_mod.list_resumable(root)}
        assert "stats-stream-shared" in names
        for s in range(S):
            assert any(n.startswith(f"stats-stream-shard{s:05d}-")
                       for n in names), (s, sorted(names))
        resumed = fresh_cols()
        compute_stats_streaming(mc, resumed, factory,
                                checkpoint_root=root, resume=True)

    # bit-identical: every stat, bin boundary, WOE table, count
    assert _cols_json(resumed) == _cols_json(clean)
    # completed stream cleared its checkpoint
    assert ckpt_mod.list_resumable(root) == []


def test_streaming_stats_checkpoint_off_no_files(tmp_path):
    from shifu_tpu.stats.engine import compute_stats_streaming

    mc, fresh_cols, factory = _stats_stream_setup(tmp_path, n=200)
    root = str(tmp_path / "root-off")
    with _StreamEnv(**{"shifu.ckpt.stream": "false"}):
        compute_stats_streaming(mc, fresh_cols(), factory,
                                checkpoint_root=root)
    assert not os.path.isdir(ckpt_mod.ckpt_dir(root)) \
        or not os.listdir(ckpt_mod.ckpt_dir(root))


# ---------------------------------------------------------------------------
# streaming norm
# ---------------------------------------------------------------------------


def _artifact_files(root):
    from shifu_tpu.fs.pathfinder import PathFinder

    paths = PathFinder(root)
    out = {}
    for d in (paths.normalized_data_dir(), paths.cleaned_data_dir()):
        for f in sorted(glob.glob(os.path.join(d, "*"))):
            out[os.path.relpath(f, root)] = f
    return out


def test_streaming_norm_preempt_resume_bit_identical(tmp_path):
    from shifu_tpu.processor.init import InitProcessor
    from shifu_tpu.processor.norm import NormProcessor
    from shifu_tpu.processor.stats import StatsProcessor

    roots = {}
    for name in ("clean", "chaos"):
        root = str(tmp_path / name)
        make_model_set(root, n_rows=300, seed=7)
        assert InitProcessor(root).run() == 0
        assert StatsProcessor(root).run() == 0
        roots[name] = root

    with _StreamEnv(**{"shifu.ingest.forceStreaming": "true",
                       "shifu.ingest.chunkRows": "48",
                       "shifu.ckpt.everyChunks": "1"}):
        assert NormProcessor(roots["clean"]).run() == 0

        with faults.activate(FaultPlan.parse("preempt@chunk=3")):
            with pytest.raises(PreemptionError):
                NormProcessor(roots["chaos"]).run()
        # the kill still produced a failure manifest (ledger contract)
        manifest = json.load(open(os.path.join(
            roots["chaos"], ".shifu", "runs", "norm-1.json")))
        assert manifest["status"] == "failed"
        assert "PreemptionError" in manifest["error"]
        # ... and recorded the injected fault in the metrics snapshot
        counters = manifest["metrics"]["counters"]
        assert counters.get('fault.injected{seam="preempt"}') == 1.0
        # a resumable snapshot family must exist (one file per row shard
        # + the shared writer state) — otherwise the "resume" below
        # would be a vacuous from-scratch rerun
        base = ckpt_mod.ckpt_base(roots["chaos"], "norm", "stream")
        ck_file = base + "-shared" + ckpt_mod.CKPT_SUFFIX
        assert os.path.isfile(ck_file)
        assert glob.glob(base + "-shard00000-*" + ckpt_mod.CKPT_SUFFIX)

        with _StreamEnv(**{"shifu.resume": "true"}):
            assert NormProcessor(roots["chaos"]).run() == 0
        # the resumed run actually LOADED the whole snapshot family —
        # one file per row shard plus the shared state — and cleared it
        from shifu_tpu.parallel.mesh import lifecycle_shards

        resumed = json.load(open(os.path.join(
            roots["chaos"], ".shifu", "runs", "norm-2.json")))
        assert resumed["metrics"]["counters"].get("ckpt.resumes") == \
            float(lifecycle_shards() + 1)
        assert not os.path.isfile(ck_file)

    clean_files = _artifact_files(roots["clean"])
    chaos_files = _artifact_files(roots["chaos"])
    assert set(clean_files) == set(chaos_files)
    for rel in clean_files:
        assert filecmp.cmp(clean_files[rel], chaos_files[rel],
                           shallow=False), f"{rel} differs after resume"


def test_sharded_norm_preempt_resume_matches_1shard(tmp_path):
    """ISSUE-8 chaos parity for the sharded lifecycle: preempt the
    8-shard streaming norm mid-stream, --resume from the per-shard
    checkpoint family, and the NormalizedData/CleanedData artifacts are
    byte-identical BOTH to an uninterrupted sharded run AND to the
    1-shard degenerate run."""
    from shifu_tpu.processor.init import InitProcessor
    from shifu_tpu.processor.norm import NormProcessor
    from shifu_tpu.processor.stats import StatsProcessor

    roots = {}
    for name in ("sharded", "oneshard", "chaos"):
        root = str(tmp_path / name)
        make_model_set(root, n_rows=300, seed=7)
        assert InitProcessor(root).run() == 0
        assert StatsProcessor(root).run() == 0
        roots[name] = root

    with _StreamEnv(**{"shifu.ingest.forceStreaming": "true",
                       "shifu.ingest.chunkRows": "48",
                       "shifu.ckpt.everyChunks": "1"}):
        assert NormProcessor(roots["sharded"]).run() == 0
        with _StreamEnv(**{"shifu.lifecycle.shards": "1"}):
            assert NormProcessor(roots["oneshard"]).run() == 0

        with faults.activate(FaultPlan.parse("preempt@chunk=3")):
            with pytest.raises(PreemptionError):
                NormProcessor(roots["chaos"]).run()
        # the per-shard family survived the kill — every shard can
        # resume from its own cursor
        entries = ckpt_mod.list_resumable(roots["chaos"])
        names = [e["name"] for e in entries]
        assert any(n.startswith("norm-stream-shard00000-") for n in names)
        assert "norm-stream-shared" in names
        with _StreamEnv(**{"shifu.resume": "true"}):
            assert NormProcessor(roots["chaos"]).run() == 0

    sharded = _artifact_files(roots["sharded"])
    oneshard = _artifact_files(roots["oneshard"])
    chaos = _artifact_files(roots["chaos"])
    assert set(sharded) == set(chaos) == set(oneshard)
    for rel in sharded:
        assert filecmp.cmp(sharded[rel], chaos[rel], shallow=False), \
            f"{rel}: resumed sharded run differs from uninterrupted"
        assert filecmp.cmp(sharded[rel], oneshard[rel], shallow=False), \
            f"{rel}: sharded run differs from the 1-shard degenerate"


# ---------------------------------------------------------------------------
# streaming eval
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def trained_root(tmp_path_factory):
    from shifu_tpu.processor.init import InitProcessor
    from shifu_tpu.processor.norm import NormProcessor
    from shifu_tpu.processor.stats import StatsProcessor
    from shifu_tpu.processor.train import TrainProcessor

    root = str(tmp_path_factory.mktemp("chaos_eval"))
    make_model_set(root, n_rows=300, seed=7)
    mcp = os.path.join(root, "ModelConfig.json")
    mc = json.load(open(mcp))
    mc["train"]["numTrainEpochs"] = 15
    ev = mc["evals"][0]
    ev["dataSet"]["dataPath"] = mc["dataSet"]["dataPath"]
    ev["dataSet"]["headerPath"] = mc["dataSet"]["headerPath"]
    ev["dataSet"]["dataDelimiter"] = "|"
    json.dump(mc, open(mcp, "w"), indent=2)
    assert InitProcessor(root).run() == 0
    assert StatsProcessor(root).run() == 0
    assert NormProcessor(root).run() == 0
    assert TrainProcessor(root).run() == 0
    return root


def test_streaming_eval_preempt_resume_bit_identical(trained_root):
    from shifu_tpu.processor.evaluate import EvalProcessor

    root = trained_root
    with _StreamEnv(**{"shifu.ingest.forceStreaming": "true",
                       "shifu.ingest.chunkRows": "48",
                       "shifu.ckpt.everyChunks": "1"}):
        assert EvalProcessor(root, score_name="Eval1").run() == 0
        score_file = glob.glob(os.path.join(root, "**", "EvalScore*"),
                               recursive=True)[0]
        clean = open(score_file).read()

        with faults.activate(FaultPlan.parse("preempt@chunk=3")):
            with pytest.raises(PreemptionError):
                EvalProcessor(root, score_name="Eval1").run()
        partial = open(score_file).read()
        assert partial != clean  # the kill really landed mid-file
        ck_file = (ckpt_mod.ckpt_base(root, "eval", "score-Eval1")
                   + "-shared" + ckpt_mod.CKPT_SUFFIX)
        assert os.path.isfile(ck_file)  # resume has something to load

        with _StreamEnv(**{"shifu.resume": "true"}):
            assert EvalProcessor(root, score_name="Eval1").run() == 0
        assert not os.path.isfile(ck_file)  # loaded and cleared
    assert open(score_file).read() == clean


# ---------------------------------------------------------------------------
# streamed NN trainer
# ---------------------------------------------------------------------------


def test_streamed_nn_preempt_resume_bit_identical(tmp_path):
    from shifu_tpu.models.nn import flatten_params
    from shifu_tpu.norm.dataset import write_normalized
    from shifu_tpu.train.nn_trainer import NNTrainConfig
    from shifu_tpu.train.streaming import train_nn_streamed

    rng = np.random.default_rng(0)
    n, d = 600, 8
    x = rng.normal(size=(n, d)).astype(np.float32)
    t = (x[:, 0] - x[:, 1] > 0).astype(np.float32)
    w = np.ones(n, np.float32)
    data_dir = str(tmp_path / "norm")
    write_normalized(data_dir, x, t, w, [f"c{i}" for i in range(d)],
                     n_shards=3)

    def cfg(ck_path):
        return NNTrainConfig(hidden_nodes=[6], activations=["tanh"],
                             propagation="R", num_epochs=9,
                             valid_set_rate=0.2, seed=3,
                             checkpoint_every=2, checkpoint_path=ck_path)

    clean = train_nn_streamed(data_dir, cfg(str(tmp_path / "a.npy")))

    ck_path = str(tmp_path / "b.npy")
    with faults.activate(FaultPlan.parse("preempt@epoch=6")):
        with pytest.raises(PreemptionError):
            train_nn_streamed(data_dir, cfg(ck_path))
    # the state snapshot survived the kill, the weights file is whole
    assert os.path.isfile(ck_path + ".state" + ckpt_mod.CKPT_SUFFIX)
    np.load(ck_path)  # readable, not torn
    resumed = train_nn_streamed(data_dir, cfg(ck_path), resume=True)

    flat_clean, _ = flatten_params(clean.params)
    flat_resumed, _ = flatten_params(resumed.params)
    np.testing.assert_array_equal(flat_clean, flat_resumed)
    assert resumed.valid_error == clean.valid_error
    assert resumed.iterations == clean.iterations
    # completed: the resumable state is gone
    assert not os.path.isfile(ck_path + ".state" + ckpt_mod.CKPT_SUFFIX)


def test_streamed_nn_checkpoint_rejected_on_config_change(tmp_path):
    """A leftover snapshot from a DIFFERENT hyperparameter set must not
    be grafted on: resume starts fresh (sha mismatch), same result as a
    clean run."""
    from shifu_tpu.models.nn import flatten_params
    from shifu_tpu.norm.dataset import write_normalized
    from shifu_tpu.train.nn_trainer import NNTrainConfig
    from shifu_tpu.train.streaming import train_nn_streamed

    rng = np.random.default_rng(1)
    n, d = 300, 6
    x = rng.normal(size=(n, d)).astype(np.float32)
    t = (x[:, 0] > 0).astype(np.float32)
    data_dir = str(tmp_path / "norm")
    write_normalized(data_dir, x, t, np.ones(n, np.float32),
                     [f"c{i}" for i in range(d)], n_shards=2)
    ck_path = str(tmp_path / "w.npy")

    def cfg(lr):
        return NNTrainConfig(hidden_nodes=[4], activations=["tanh"],
                             propagation="R", num_epochs=6,
                             valid_set_rate=0.2, seed=3,
                             learning_rate=lr,
                             checkpoint_every=2, checkpoint_path=ck_path)

    with faults.activate(FaultPlan.parse("preempt@epoch=5")):
        with pytest.raises(PreemptionError):
            train_nn_streamed(data_dir, cfg(0.1))
    # resume under a CHANGED learning rate: snapshot must be rejected
    resumed = train_nn_streamed(data_dir, cfg(0.2), resume=True)
    clean = train_nn_streamed(data_dir, NNTrainConfig(
        hidden_nodes=[4], activations=["tanh"], propagation="R",
        num_epochs=6, valid_set_rate=0.2, seed=3, learning_rate=0.2))
    a, _ = flatten_params(resumed.params)
    b, _ = flatten_params(clean.params)
    np.testing.assert_array_equal(a, b)


# ---------------------------------------------------------------------------
# real SIGTERM mid-train (subprocess lifecycle)
# ---------------------------------------------------------------------------


def _run_lifecycle_until_train(root):
    from shifu_tpu.processor.init import InitProcessor
    from shifu_tpu.processor.norm import NormProcessor
    from shifu_tpu.processor.stats import StatsProcessor

    make_model_set(root, n_rows=240, seed=7)
    mcp = os.path.join(root, "ModelConfig.json")
    mc = json.load(open(mcp))
    mc["train"]["numTrainEpochs"] = 400
    mc["train"]["epochsPerIteration"] = 2  # checkpoint every 2 epochs
    json.dump(mc, open(mcp, "w"), indent=2)
    assert InitProcessor(root).run() == 0
    assert StatsProcessor(root).run() == 0
    assert NormProcessor(root).run() == 0


def _train_cmd(extra=()):
    return ([sys.executable, "-m", "shifu_tpu", "train",
             "-Dshifu.train.forceStreaming=true"] + list(extra))


def _child_env():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    return env


def test_sigterm_mid_train_manifest_and_pinned_resume(tmp_path):
    """Satellite: a subprocess lifecycle run killed by SIGTERM between
    checkpoint segments still writes a failure manifest, and
    `shifu train --resume` finishes with weights bit-identical to an
    uninterrupted run."""
    root_kill = str(tmp_path / "killed")
    root_ref = str(tmp_path / "reference")
    _run_lifecycle_until_train(root_kill)
    _run_lifecycle_until_train(root_ref)

    state_file = os.path.join(root_kill, "tmp", "train", "checkpoint_0",
                              "weights.npy.state" + ckpt_mod.CKPT_SUFFIX)
    proc = subprocess.Popen(_train_cmd(), cwd=root_kill, env=_child_env(),
                            stdout=subprocess.DEVNULL,
                            stderr=subprocess.DEVNULL)
    try:
        # SIGTERM as soon as the first mid-train snapshot lands — i.e.
        # BETWEEN checkpoint segments, the torn-state window
        deadline = time.time() + 120
        while not os.path.isfile(state_file):
            assert proc.poll() is None, \
                "train finished before SIGTERM could land — raise epochs"
            assert time.time() < deadline, "no checkpoint appeared"
            time.sleep(0.05)
        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=120)
    finally:
        if proc.poll() is None:
            proc.kill()
    assert rc != 0

    # failure manifest (PR-2 ledger contract) landed with the preemption
    manifest = json.load(open(os.path.join(
        root_kill, ".shifu", "runs", "train-1.json")))
    assert manifest["status"] == "failed"
    assert "PreemptionError" in manifest["error"]
    # the mid-train snapshot the kill left behind is intact AND visible
    # to `shifu runs --resumable` (trainer snapshots live under
    # tmp/train/checkpoint_*, not .shifu/runs/ckpt)
    assert os.path.isfile(state_file)
    assert any(e["name"] == "train-checkpoint_0"
               for e in ckpt_mod.list_resumable(root_kill))

    # resume the killed run; run the reference uninterrupted
    rc = subprocess.run(_train_cmd(["--resume"]), cwd=root_kill,
                        env=_child_env(), timeout=600).returncode
    assert rc == 0
    rc = subprocess.run(_train_cmd(), cwd=root_ref, env=_child_env(),
                        timeout=600).returncode
    assert rc == 0

    from shifu_tpu.models.nn import NNModelSpec, flatten_params

    killed = NNModelSpec.load(
        os.path.join(root_kill, "models", "model0.nn"))
    ref = NNModelSpec.load(os.path.join(root_ref, "models", "model0.nn"))
    a, _ = flatten_params(killed.params)
    b, _ = flatten_params(ref.params)
    np.testing.assert_array_equal(a, b)


# ---------------------------------------------------------------------------
# pod-scale data plane: kill one host, resume, byte-identical artifacts
# ---------------------------------------------------------------------------


def test_multi_host_kill_one_host_resume_byte_identical(tmp_path):
    """ISSUE-18 chaos acceptance: one host of a 2-process streamed-stats
    fleet is preempted mid-pass-1 (before its merge barrier, so no peer
    is left hanging), then the WHOLE fleet runs with `resume` — the dead
    host picks up its own per-host cursor slice — and the merged
    ColumnConfig is byte-identical to the uninterrupted 1-process run."""
    from shifu_tpu.data.pipeline import HostPlan
    from shifu_tpu.stats.engine import compute_stats_streaming
    from tests.test_sharded_lifecycle import (
        _integral_stats_setup,
        _run_hosts,
    )

    mc, fresh_cols, factory, K = _integral_stats_setup(tmp_path)
    clean = fresh_cols()
    compute_stats_streaming(mc, clean, factory)
    ref = _cols_json(clean)

    root = str(tmp_path / "fleet")
    # host 1 runs ALONE and dies on its 3rd owned chunk — mid-pass-1,
    # strictly before publishing its part (it owns ceil(K/2) > 3 chunks)
    assert -(-K // 2) > 3
    with _StreamEnv(**{"shifu.ckpt.everyChunks": "1",
                       "shifu.lifecycle.hostWaitMs": "60000"}):
        with faults.activate(FaultPlan.parse("preempt@chunk=3")):
            with pytest.raises(PreemptionError):
                compute_stats_streaming(
                    mc, fresh_cols(), factory, checkpoint_root=root,
                    host_plan=HostPlan(n_hosts=2, host_index=1))
        # the kill left host 1's OWN per-host family, resumable
        names = {e["name"] for e in ckpt_mod.list_resumable(root)}
        assert "stats-stream-h001-shared" in names, sorted(names)
        assert not any(n.startswith("stats-stream-h000") for n in names)

        # full fleet, concurrent, resume=True: host 1 resumes its cursor
        # slice, host 0 (no family) starts fresh
        cols = {h: fresh_cols() for h in (0, 1)}
        _run_hosts(lambda h: compute_stats_streaming(
            mc, cols[h], factory, checkpoint_root=root, resume=True,
            host_plan=HostPlan(n_hosts=2, host_index=h)))

    assert _cols_json(cols[0]) == _cols_json(cols[1]) == ref
    # completed hosts cleared their checkpoint families
    assert ckpt_mod.list_resumable(root) == []
