"""Overlapped streaming pipeline: background chunk prefetch feeding
shape-bucketed jit consumers.

The serial chunked paths ran parse -> host bin-code -> device aggregate ->
device->host sync strictly in sequence, one chunk at a time, so the device
idled during every parse and the host idled during every device step. This
module supplies the three pieces every chunked consumer shares (streaming
stats, streaming norm, the NN/WDL/tree shard feeds, chunked scoring):

  * ``prefetch_iter`` — a bounded-queue background producer. ONE worker
    thread pulls the source iterator and applies the host-side transform
    (CSV parse, bin-coding, shard load) while the consumer's device work
    runs; up to ``shifu.ingest.prefetchChunks`` (default 2) transformed
    chunks sit ready in the queue. A single thread plus a FIFO queue keeps
    chunk order — and therefore every accumulated result — bit-identical
    to the serial path; ``prefetchChunks=0`` degrades to a plain inline
    loop for debugging.
  * ``bucket_rows`` — power-of-two row buckets, so padded chunk shapes
    take O(log max_chunk_rows) distinct values and jit consumers compile
    a bounded set of programs regardless of the chunk-size sequence (the
    old running-max padding recompiled every time a larger chunk arrived).
  * ``ShardPlan`` — the deterministic chunk -> row-shard assignment the
    whole lifecycle shares (round-robin on the chunk index), so every
    streaming fold divides work O(rows/shards) over the mesh and every
    shard can prefetch exactly its own slice.
  * ``DeviceAccumulator`` — keeps one f32 BinAggregates window PER ROW
    SHARD resident on the lifecycle mesh across chunks (the fold is a
    shard_map program: each shard aggregates its own chunk locally), so
    the only device->host transfer in a streamed aggregation is one
    psum-tree-reduced window flush instead of a full sync per chunk —
    and instead of one pull per shard.
"""

from __future__ import annotations

import queue
import threading
from typing import (
    Any,
    Callable,
    Iterable,
    Iterator,
    List,
    Optional,
    Sequence,
    Tuple,
)

import numpy as np

from shifu_tpu.utils import environment
from shifu_tpu.utils.timing import StageTimers

DEFAULT_PREFETCH_CHUNKS = 2

# Smallest row bucket: chunks below this all pad to one shape, so tiny
# ragged tails don't each compile their own program.
MIN_ROW_BUCKET = 256


def prefetch_chunks_setting() -> int:
    """shifu.ingest.prefetchChunks — queue depth of the background
    prefetcher (0 = serial inline execution)."""
    return environment.get_int("shifu.ingest.prefetchChunks",
                               DEFAULT_PREFETCH_CHUNKS)


def bucket_rows(n: int, minimum: int = MIN_ROW_BUCKET) -> int:
    """Smallest power of two >= n (floored at `minimum`).

    Padding chunks to bucketed row counts bounds the set of shapes a jit
    consumer ever sees at O(log max_chunk_rows), whatever the chunk-size
    sequence; padding waste is < 2x compute on the padded rows, which carry
    zero weight/invalid tags and change no result."""
    if n <= minimum:
        return minimum
    return 1 << int(n - 1).bit_length()


def prefetch_iter(
    source: Iterable[Any],
    depth: Optional[int] = None,
    transform: Optional[Callable[[Any], Any]] = None,
    timers: Optional[StageTimers] = None,
    stage: str = "parse",
) -> Iterator[Any]:
    """Iterate `source` with the pull + `transform` running on a background
    thread, keeping up to `depth` transformed items ready.

    `depth` defaults to shifu.ingest.prefetchChunks; depth <= 0 runs the
    identical pull/transform inline (serial fallback). `timers`, when
    given, accumulates the source-pull wall-clock under `stage` (the
    transform times its own stages so none is double-counted) — time the
    consumer does NOT wait for once the queue is warm. Up to depth + 2
    items are in flight: the queue, one finished item in a blocked worker,
    one in the consumer.

    Guarantees: items arrive in source order (one worker, FIFO queue);
    worker exceptions re-raise in the consumer at the failing position;
    abandoning the iterator (break / close) stops the worker promptly.
    """
    if depth is None:
        depth = prefetch_chunks_setting()

    def _produce(it: Iterator[Any]):
        from shifu_tpu.resilience import faults

        # guarded like profile.dispatch's device seam: the unfaulted hot
        # path pays one property lookup per chunk, nothing more
        chaos = faults.plan_active()
        if chaos:
            from shifu_tpu.resilience import retry

            # `io` fault seam BEFORE the pull, retried under the io
            # budget. Only the injected fault is retryable here: an
            # exception raised inside next(it) CLOSES a generator
            # source, so "retrying" the pull would read as a clean
            # end-of-stream and silently truncate the chunk stream —
            # real read errors must stay loud.
            retry.retry_call(lambda: faults.fault_point("io"), seam="io")
        if timers is not None:
            with timers.timer(stage):
                item = next(it)
        else:
            item = next(it)
        if transform is not None:
            if chaos:
                from shifu_tpu.resilience import retry

                # the per-chunk transform is pure host work (parse/
                # bin-code/pad), so a crashed prefetch worker "restarts"
                # by re-running it under the retry budget
                def _apply(i=item):
                    faults.fault_point("prefetch")
                    return transform(i)

                item = retry.retry_call(_apply, seam="prefetch")
            else:
                item = transform(item)
        from shifu_tpu.obs import registry

        registry().counter("pipeline.chunks").inc()
        return item

    if depth <= 0:
        def _serial() -> Iterator[Any]:
            it = iter(source)
            while True:
                try:
                    yield _produce(it)
                except StopIteration:
                    return

        return _serial()

    q: "queue.Queue" = queue.Queue(maxsize=depth)
    stop = threading.Event()

    def _put(msg) -> bool:
        while not stop.is_set():
            try:
                q.put(msg, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def _work() -> None:
        try:
            it = iter(source)
        except BaseException as e:  # a failing __iter__ must not hang the consumer
            _put(("error", e))
            return
        while not stop.is_set():
            try:
                item = _produce(it)
            except StopIteration:
                _put(("end", None))
                return
            except BaseException as e:  # re-raised consumer-side
                _put(("error", e))
                return
            if not _put(("item", item)):
                return
            # drop the local reference NOW: otherwise the handed-off chunk
            # stays alive in this frame until the next _produce returns,
            # keeping one extra chunk resident for the whole parse
            item = None

    def _consume() -> Iterator[Any]:
        worker = threading.Thread(target=_work, name="shifu-prefetch",
                                  daemon=True)
        worker.start()
        try:
            while True:
                kind, val = q.get()
                if kind == "end":
                    return
                if kind == "error":
                    raise val
                yield val
                # the consumer is done with the chunk once it re-enters the
                # generator; release it before blocking on the queue or one
                # extra chunk stays resident across the whole next wait
                val = None
        finally:
            stop.set()
            try:  # unblock a worker stuck on a full queue
                while True:
                    q.get_nowait()
            except queue.Empty:
                pass
            worker.join(timeout=5.0)

    return _consume()


# ---------------------------------------------------------------------------
# shard planning — the lifecycle map/reduce work division
# ---------------------------------------------------------------------------


class HostPlan:
    """Deterministic chunk -> HOST assignment: the per-process layer of
    the pod-scale data plane, sitting ABOVE ShardPlan's per-device
    round-robin.

    Round-robin on the global chunk index: `host_of(ci) = ci % H`, so
    with H hosts over K chunk files every process prefetches and folds
    at most ceil(K/H) of them — the work-division bound the
    host_affinity bench gates. Like ShardPlan the assignment is a pure
    function of (ci, H): every process derives the identical partition
    with zero coordination, keyed only by its own host index
    (-Dshifu.lifecycle.hostIndex, or jax.process_index() on a real pod;
    the PR-14 lease id names the process, the index orders it).
    `local_index(ci) = ci // H` renumbers a host's own chunks densely so
    the per-device ShardPlan composes underneath and every LOCAL shard
    still folds ~1/S of the host's slice. H=1 is the degenerate
    single-controller plan — same code path, every chunk owned.
    """

    def __init__(self, n_hosts: Optional[int] = None,
                 host_index: Optional[int] = None) -> None:
        from shifu_tpu.parallel.mesh import (
            lifecycle_host_index,
            lifecycle_hosts,
        )

        self.n_hosts = (lifecycle_hosts() if n_hosts is None
                        else max(1, int(n_hosts)))
        self.host_index = (lifecycle_host_index() if host_index is None
                           else int(host_index))
        if not (0 <= self.host_index < self.n_hosts):
            raise ValueError(
                f"host index {self.host_index} outside [0, {self.n_hosts})"
                " — check -Dshifu.lifecycle.hostIndex vs"
                " -Dshifu.lifecycle.hosts")

    @property
    def active(self) -> bool:
        return self.n_hosts > 1

    @property
    def is_merge_host(self) -> bool:
        """Host 0 merges the per-host partials in sorted-host order and
        writes the final artifacts; every other host publishes its part
        and leaves the shared files alone."""
        return self.host_index == 0

    def host_of(self, chunk_index: int) -> int:
        return chunk_index % self.n_hosts

    def owns(self, chunk_index: int) -> bool:
        return chunk_index % self.n_hosts == self.host_index

    def local_index(self, chunk_index: int) -> int:
        """Dense ordinal of an OWNED chunk within this host's slice —
        what the per-device ShardPlan round-robins on, so all S local
        shards stay busy whatever H is."""
        return chunk_index // self.n_hosts

    def record(self, rows: int, stage: str) -> None:
        """Per-host obs: host.chunks / host.rows land in every manifest
        labeled by host and lifecycle stage — the counters the CI
        affinity-division assertion reads (each process only ever
        increments its OWN host label, so two processes' manifests are
        disjoint by construction)."""
        from shifu_tpu.obs import registry

        reg = registry()
        h = str(self.host_index)
        reg.counter("host.chunks", host=h, stage=stage).inc()
        reg.counter("host.rows", host=h, stage=stage).inc(rows)


class ShardPlan:
    """Deterministic chunk -> row-shard assignment for the lifecycle
    folds (streaming stats, norm, eval scoring, init autotype).

    Round-robin on the global chunk index: `shard_of(ci) = ci % S`, so
    with S shards over K chunks every shard folds at most ceil(K/S)
    chunks — the work-division bound the sharded_stats bench gates. The
    assignment is a pure function of (ci, S): every pass, every resume,
    and every host in a real multi-host run derives the identical plan
    with zero coordination, and a shard can prefetch exactly its own
    slice of the chunk stream (`shard_slice`). S=1 is the degenerate
    single-device plan — same code path, every chunk on shard 0.

    With a multi-process HostPlan composed on top (`host=`), ownership
    filters FIRST — this process only ever sees chunks with
    `host_of(ci) == host_index` — and the round-robin runs on the host's
    dense local ordinal (`ci // H`), so all S local shards divide the
    host's slice evenly whatever H is. H=1 reduces every formula to the
    original global one.
    """

    def __init__(self, n_shards: Optional[int] = None,
                 host: Optional[HostPlan] = None) -> None:
        from shifu_tpu.parallel.mesh import lifecycle_shards

        self.n_shards = (lifecycle_shards() if n_shards is None
                         else max(1, int(n_shards)))
        self.host = HostPlan() if host is None else host

    def shard_of(self, chunk_index: int) -> int:
        return self.host.local_index(chunk_index) % self.n_shards

    def group_of(self, chunk_index: int) -> int:
        """Super-step index: group g holds this host's local chunks
        [g*S, (g+1)*S) — one chunk per shard, the unit one sharded fold
        dispatch consumes."""
        return self.host.local_index(chunk_index) // self.n_shards

    def shard_slice(self, numbered: Iterable, shard: int) -> Iterator:
        """Only the owned (ci, item) pairs assigned to `shard` — what a
        multi-host shard prefetches as its own slice."""
        for ci, item in numbered:
            if self.host.owns(ci) and self.shard_of(ci) == shard:
                yield ci, item

    def slices(self, items: Sequence) -> List[List[Tuple[int, Any]]]:
        """Enumerate the chunk list ONCE and hand every shard its index
        view: views[s] is the list of owned (ci, item) pairs shard s
        folds. Replaces S separate `shard_slice` passes, each of which
        re-enumerated (and re-filtered) the full K-chunk list — O(K)
        instead of O(K*S) for per-shard fan-out over a materialized
        list."""
        views: List[List[Tuple[int, Any]]] = \
            [[] for _ in range(self.n_shards)]
        for ci, item in enumerate(items):
            if self.host.owns(ci):
                views[self.shard_of(ci)].append((ci, item))
        return views

    def resume_slice(self, numbered: Iterable,
                     cursors: Sequence[int]) -> Iterator:
        """Per-shard resume over this host's slice: yield owned
        (ci, item) pairs each local shard has NOT folded yet (ci > its
        cursor). Chunks below every cursor are skipped before parse,
        exactly like the single-cursor checkpoint.resume_slice."""
        for pair in numbered:
            ci = pair[0]
            if self.host.owns(ci) and ci > cursors[self.shard_of(ci)]:
                yield pair

    def record(self, shard: int, rows: int, stage: str) -> None:
        """Per-shard obs: shard.chunks / shard.rows land in every
        manifest, labeled by shard and lifecycle stage — the counters the
        work-division acceptance asserts."""
        from shifu_tpu.obs import registry

        reg = registry()
        reg.counter("shard.chunks", shard=str(shard), stage=stage).inc()
        reg.counter("shard.rows", shard=str(shard), stage=stage).inc(rows)


# Device windows fold in f32; a slot's count stays exact below 2^24. The
# psum reduce SUMS the S shard windows in f32, so the bound that matters
# is the TOTAL row count across all shard windows: the window flushes to
# the host float64 fold before that total can reach 2^24 (2^23 leaves a
# whole 65536-row chunk of headroom; a reduced slot count is bounded by
# the window's total rows). Per-shard bounds alone would NOT be enough —
# S exact per-shard counts can sum past 2^24.
WINDOW_FLUSH_ROWS = 1 << 23


class DeviceAccumulator:
    """Sharded device-resident fold of per-chunk BinAggregates, flushed
    to a host float64 fold in bounded windows.

    One f32 window per row shard, stacked [S, ...] and sharded over the
    lifecycle mesh (parallel/mesh.py). The fold is a shard_map program
    (ops/binagg.sharded_window_fold): each shard bin-aggregates its own
    chunk locally and folds it into its own window — one dispatch folds
    up to S chunks with no cross-shard traffic. The windowed flush is ONE
    psum-tree reduction over the mesh's row axes (dcn, data) followed by
    ONE device->host sync — where a per-shard host accumulation would
    cost O(S) pulls per window, the reduce rides ICI/DCN and the host
    sees a single replicated result.

    Exactness invariant (unchanged from the single-device fold, which is
    the S=1 degenerate case of this class): within a window every count
    is exact in f32 — each shard's slot counts are bounded by its own
    window rows, the psum sums them exactly because the flush policy
    bounds the TOTAL window rows across shards below 2^23 < 2^24 — and
    the moment sums are float-summation-order-accurate; across windows
    everything accumulates in float64 — arbitrarily long streams cannot
    saturate, and counts are exact at any stream length and shard count.
    """

    def __init__(self, flush_rows: int = WINDOW_FLUSH_ROWS,
                 n_shards: int = 1) -> None:
        self._acc = None  # stacked [S, ...] device windows
        self._host: Optional[List[np.ndarray]] = None  # f64 fold
        self._flush_rows = flush_rows
        self.n_shards = max(1, int(n_shards))
        self._rows = np.zeros(self.n_shards, dtype=np.int64)
        self._mesh = None

    @property
    def mesh(self):
        if self._mesh is None:
            from shifu_tpu.parallel.mesh import lifecycle_mesh

            self._mesh = lifecycle_mesh(self.n_shards)
        return self._mesh

    @property
    def empty(self) -> bool:
        return self._acc is None and self._host is None

    @property
    def window_rows(self) -> int:
        """Total window rows across shards (the f32-exactness bound the
        flush policy enforces — the psum reduce sums all shards)."""
        return int(self._rows.sum())

    def _flush(self) -> None:
        if self._acc is None:
            return
        import jax

        from shifu_tpu.obs import profile, registry
        from shifu_tpu.ops.binagg import window_reduce

        from shifu_tpu.parallel.mesh import hierarchical_reduce

        reg = registry()
        # the reduce: ONE psum tree over the row axes closes all S shard
        # windows; the single device_get below is the window's ENTIRE d2h
        # budget — was one pull per shard
        reg.counter("reduce.psum_windows").inc()
        reg.counter("device.d2h_syncs").inc()
        if hierarchical_reduce(self.mesh):
            # explicit two-stage lowering: the window crossed DCN as ONE
            # per-slice partial after the ICI psum (ops/binagg)
            reg.counter("reduce.dcn_hops").inc()
        reduced = profile.dispatch(
            "pipeline.psum_reduce", window_reduce(self.mesh), self._acc,
            sync=False)
        part = [np.asarray(x[0], dtype=np.float64)
                for x in jax.device_get(reduced)]
        # -Dshifu.sanitize=divergence: digest every window fold so two
        # runs of the same stream can diff WHERE determinism broke
        from shifu_tpu.analysis import sanitize

        sanitize.record_fold("pipeline.window", part)
        self._acc = None
        self._rows[:] = 0
        if self._host is None:
            self._host = part
        else:
            self._host = [
                np.minimum(h, p) if k == 6 else  # vmin
                np.maximum(h, p) if k == 7 else  # vmax
                h + p
                for k, (h, p) in enumerate(zip(self._host, part))
            ]

    def _ensure_window(self, total_slots: int, n_numeric: int) -> None:
        if self._acc is None:
            from shifu_tpu.ops.binagg import window_init

            self._acc = window_init(self.mesh, total_slots, n_numeric)

    def add(self, agg, rows: int, shard: int = 0) -> None:
        """Fold ONE precomputed chunk aggregate into `shard`'s window;
        `rows` is the chunk's REAL row count (padding rows carry invalid
        tags and count nothing). The streamed stats path uses fold_group
        (the in-program map) instead; this is the entry point for callers
        that already hold a BinAggregates."""
        if self._acc is not None \
                and self.window_rows + rows > self._flush_rows:
            self._flush()
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from shifu_tpu.analysis import sanitize
        from shifu_tpu.obs import profile
        from shifu_tpu.ops.binagg import masked_window_add

        self._ensure_window(int(agg.pos.shape[0]), int(agg.vsum.shape[0]))
        # replication of the aggregate across the mesh is the one
        # sanctioned move — explicit, before the guard arms
        rep = NamedSharding(self.mesh, P())
        agg = jax.device_put(agg, rep)
        sid = jax.device_put(np.int32(shard), rep)
        # sanitizer seam: window + aggregate are now device-resident and
        # correctly placed, so the fold dispatch must not move bytes; the
        # only sanctioned transfer is _flush's explicit device_get.
        # Profiled async (sync would reintroduce the per-chunk RTT wait
        # this accumulator exists to remove).
        with sanitize.transfer_free("pipeline.device_fold"):
            self._acc = profile.dispatch(
                "pipeline.device_fold", masked_window_add(self.mesh),
                self._acc, agg, sid, sync=False)
        self._rows[shard] += rows

    def fold_group(self, codes: np.ndarray, col_offsets: np.ndarray,
                   total_slots: int, tags: np.ndarray,
                   weights: np.ndarray, values: np.ndarray,
                   rows_per_shard: Sequence[int]) -> None:
        """The sharded map: fold one super-step group — stacked [S, n, C]
        codes / [S, n] tags / [S, n] weights / [S, n, Cn] values, one row
        block per shard (empty shards carry invalid-tag padding) — in ONE
        shard_map dispatch. Each shard aggregates its own block locally
        and folds it into its own f32 window."""
        adds = np.asarray(rows_per_shard, dtype=np.int64)
        if self._acc is not None \
                and self.window_rows + int(adds.sum()) > self._flush_rows:
            self._flush()
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from shifu_tpu.analysis import sanitize
        from shifu_tpu.obs import profile
        from shifu_tpu.ops.binagg import sharded_window_fold
        from shifu_tpu.parallel.mesh import row_axes

        self._ensure_window(int(total_slots), int(values.shape[2]))
        axes = row_axes(self.mesh)
        ax = axes if len(axes) > 1 else axes[0]

        def rspec(ndim):
            return NamedSharding(
                self.mesh, P(ax, *([None] * (ndim - 1))))

        # each shard's slice lands on its own devices — the explicit,
        # sanctioned h2d placement ("each host prefetches its own shard")
        codes_d = jax.device_put(codes, rspec(3))
        tags_d = jax.device_put(tags, rspec(2))
        weights_d = jax.device_put(weights, rspec(2))
        values_d = jax.device_put(values, rspec(3))
        offs_d = jax.device_put(col_offsets,
                                NamedSharding(self.mesh, P(None)))
        with sanitize.transfer_free("pipeline.sharded_fold"):
            self._acc = profile.dispatch(
                "pipeline.sharded_fold",
                sharded_window_fold(self.mesh, int(total_slots)),
                self._acc, codes_d, offs_d, tags_d, weights_d, values_d,
                sync=False)
        self._rows += adds

    def fetch(self) -> Optional[List[np.ndarray]]:
        """Final sync: aggregates as float64 numpy arrays in BinAggregates
        field order, or None if no chunk was ever added."""
        self._flush()
        return self._host

    # ---- checkpoint seam (resilience/checkpoint.py) ----
    def snapshot(self) -> dict:
        """Checkpointable state WITHOUT forcing a window flush: the f32
        device windows are pulled as-is (device_get is bit-exact), so a
        resumed fold continues the identical per-shard f32 summation
        order and the result stays bit-identical to an uninterrupted run
        — flushing early here would regroup the f32 sums and break
        parity."""
        out: dict = {"rows": self._rows.copy()}
        if self._host is not None:
            for k, a in enumerate(self._host):
                out[f"host{k}"] = a
        if self._acc is not None:
            import jax

            for k, a in enumerate(jax.device_get(self._acc)):
                out[f"win{k}"] = np.asarray(a)
        return out

    def restore(self, arrays: dict) -> None:
        """Rebuild from `snapshot` arrays (stacked windows re-placed
        sharded over the lifecycle mesh)."""
        host = [arrays[f"host{k}"] for k in range(len(arrays))
                if f"host{k}" in arrays]
        self._host = [np.asarray(a, dtype=np.float64) for a in host] \
            if host else None
        win = [arrays[f"win{k}"] for k in range(len(arrays))
               if f"win{k}" in arrays]
        if win:
            self._acc = self._place_windows(win)
        else:
            self._acc = None
        rows = np.atleast_1d(np.asarray(arrays["rows"], dtype=np.int64))
        assert rows.shape[0] == self.n_shards, (rows.shape, self.n_shards)
        self._rows = rows.copy()

    def _place_windows(self, win: List[np.ndarray]):
        import jax

        from shifu_tpu.ops.binagg import BinAggregates, window_specs
        from jax.sharding import NamedSharding

        sharded, _ = window_specs(self.mesh)
        return BinAggregates(*[
            jax.device_put(np.asarray(a, dtype=np.float32),
                           NamedSharding(self.mesh, s))
            for a, s in zip(win, sharded)])

    # ---- per-shard checkpoint layout (ShardedStreamCheckpoint) ----
    def snapshot_parts(self) -> Tuple[List[dict], dict]:
        """(per_shard, shared): shard s's file gets ITS window slice +
        row count (`local fold state per shard`); the shared reduce file
        gets the post-psum host float64 fold, which no single shard
        owns."""
        snap = self.snapshot()
        per_shard: List[dict] = []
        for s in range(self.n_shards):
            part = {"rows": np.int64(self._rows[s])}
            for k in range(10):
                if f"win{k}" in snap:
                    part[f"win{k}"] = snap[f"win{k}"][s]
            per_shard.append(part)
        shared = {k: v for k, v in snap.items() if k.startswith("host")}
        return per_shard, shared

    def restore_parts(self, per_shard: List[dict], shared: dict) -> None:
        assert len(per_shard) == self.n_shards, \
            (len(per_shard), self.n_shards)
        merged: dict = {
            "rows": np.asarray([int(p["rows"]) for p in per_shard],
                               dtype=np.int64)}
        if any("win0" in p for p in per_shard):
            for k in range(10):
                if f"win{k}" not in per_shard[0]:
                    continue
                merged[f"win{k}"] = np.stack(
                    [p[f"win{k}"] for p in per_shard])
        merged.update(shared)
        self.restore(merged)
