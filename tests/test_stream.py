"""Streaming bounded-memory ingest tests.

The contract (reference MemoryDiskFloatMLDataSet + shifuconfig memory
envelope): the pipeline must complete on datasets far larger than the
configured memory budget, with peak allocation under the budget, and the
streaming results must agree with the in-RAM path.
"""

import json
import os
import tracemalloc

import numpy as np
import pytest

from shifu_tpu.utils import environment
from tests.helpers import make_model_set


def _set_props(**kv):
    for k, v in kv.items():
        environment.set_property(k, str(v))


def _clear_props(*keys):
    for k in keys:
        environment.set_property(k, "")


class TestChunkedReader:
    def test_chunks_concatenate_to_whole_read(self, tmp_path):
        from shifu_tpu.data.reader import read_columnar
        from shifu_tpu.data.stream import iter_columnar_chunks
        from tests.helpers import make_binary_dataset, write_dataset

        names, rows, _ = make_binary_dataset(n_rows=500)
        data_path, _ = write_dataset(str(tmp_path / "d"), names, rows)
        whole = read_columnar(data_path, names)
        chunks = list(iter_columnar_chunks(data_path, names, chunk_rows=128))
        assert len(chunks) == 4
        assert sum(c.n_rows for c in chunks) == whole.n_rows
        got = np.concatenate([c.column("num_0") for c in chunks])
        np.testing.assert_array_equal(got, whole.column("num_0"))

    def test_parquet_chunks(self, tmp_path):
        import pandas as pd

        from shifu_tpu.data.stream import iter_columnar_chunks

        df = pd.DataFrame({
            "a": [str(i) for i in range(300)],
            "b": ["x"] * 300,
        })
        p = str(tmp_path / "part.parquet")
        df.to_parquet(p)
        chunks = list(iter_columnar_chunks(p, ["a", "b"], chunk_rows=100))
        assert sum(c.n_rows for c in chunks) == 300
        assert chunks[0].column("a")[0] == "0"


class TestStreamingStats:
    def test_streaming_matches_exact_within_tolerance(self, tmp_path):
        from shifu_tpu.config import load_column_config_list
        from shifu_tpu.processor.init import InitProcessor
        from shifu_tpu.processor.stats import StatsProcessor

        root = str(tmp_path / "ms")
        make_model_set(root, n_rows=3000)
        assert InitProcessor(root).run() == 0
        assert StatsProcessor(root).run() == 0
        exact = load_column_config_list(os.path.join(root, "ColumnConfig.json"))

        _set_props(**{"shifu.ingest.forceStreaming": "true",
                      "shifu.ingest.chunkRows": "512"})
        try:
            assert StatsProcessor(root).run() == 0
        finally:
            _clear_props("shifu.ingest.forceStreaming",
                         "shifu.ingest.chunkRows")
        stream = load_column_config_list(os.path.join(root, "ColumnConfig.json"))

        for e, s in zip(exact, stream):
            if e.column_stats.ks is None:
                continue
            assert s.column_stats.ks == pytest.approx(e.column_stats.ks,
                                                      abs=2.0), e.column_name
            assert s.column_stats.iv == pytest.approx(e.column_stats.iv,
                                                      rel=0.2, abs=0.05)
            assert s.column_stats.mean == pytest.approx(e.column_stats.mean,
                                                        rel=1e-5, abs=1e-6)
            assert s.column_stats.std_dev == pytest.approx(
                e.column_stats.std_dev, rel=1e-4, abs=1e-6)
            assert s.column_stats.total_count == e.column_stats.total_count
            assert s.column_stats.missing_count == e.column_stats.missing_count
            if e.is_categorical():
                # exact parity for categoricals: counts, not sketches
                assert (s.column_binning.bin_category
                        == e.column_binning.bin_category)
                assert (s.column_binning.bin_count_pos
                        == e.column_binning.bin_count_pos)


class TestStreamingNorm:
    def test_streaming_norm_identical_given_same_bins(self, tmp_path):
        from shifu_tpu.norm.dataset import load_codes, load_normalized
        from shifu_tpu.processor.init import InitProcessor
        from shifu_tpu.processor.norm import NormProcessor
        from shifu_tpu.processor.stats import StatsProcessor

        root = str(tmp_path / "ms")
        make_model_set(root, n_rows=1500)
        assert InitProcessor(root).run() == 0
        assert StatsProcessor(root).run() == 0
        assert NormProcessor(root).run() == 0
        m1, f1, t1, w1 = load_normalized(
            os.path.join(root, "tmp", "norm", "NormalizedData"))
        _, c1, _, _ = load_codes(
            os.path.join(root, "tmp", "norm", "CleanedData"))

        _set_props(**{"shifu.ingest.forceStreaming": "true",
                      "shifu.ingest.chunkRows": "256"})
        try:
            assert NormProcessor(root).run() == 0
        finally:
            _clear_props("shifu.ingest.forceStreaming",
                         "shifu.ingest.chunkRows")
        m2, f2, t2, w2 = load_normalized(
            os.path.join(root, "tmp", "norm", "NormalizedData"))
        _, c2, _, _ = load_codes(
            os.path.join(root, "tmp", "norm", "CleanedData"))

        assert m2.columns == m1.columns
        assert len(m2.shard_rows) >= 5  # one shard per chunk
        np.testing.assert_allclose(np.asarray(f2), np.asarray(f1), atol=1e-6)
        np.testing.assert_array_equal(np.asarray(t2), np.asarray(t1))
        np.testing.assert_allclose(np.asarray(w2), np.asarray(w1), atol=1e-6)
        np.testing.assert_array_equal(np.asarray(c2), np.asarray(c1))
        assert (m2.extra or {}).get("sourceOf")


@pytest.mark.slow
class TestBoundedMemoryPipeline:
    """init -> stats -> norm -> train on a dataset ~8x the memory budget,
    asserting tracked peak allocation stays a small RATIO of a measured
    no-pipeline control (a full in-RAM read of the same file) — absolute
    MB budgets proved env-dependent (allocator/runtime overhead differs
    ~6 MB between runners, which is most of a 10 MB constant), while the
    ratio cancels the per-environment overhead out of the gate."""

    BUDGET_MB = 10
    # streamed ingest must peak at under a quarter of what holding the
    # dataset resident costs IN THIS ENVIRONMENT. The streamed peak is
    # ~(2 + prefetchChunks) in-flight chunks and does NOT scale with
    # rows, while the control scales linearly — the ~80 MB dataset
    # gives the ratio gate 4x its margin at the measured ~16 MB peak.
    CONTROL_RATIO = 4.0

    def _generate_big(self, root: str) -> str:
        """~80 MB CSV written incrementally: 8 informative numerics + one
        fat text column (padding an in-RAM object-array read holds
        resident in full, while the pipeline only ever holds a few
        chunks of it)."""
        from shifu_tpu.config.model_config import Algorithm, new_model_config

        data_dir = os.path.join(root, "data")
        os.makedirs(data_dir, exist_ok=True)
        names = ["target"] + [f"f{i}" for i in range(8)] + ["pad"]
        with open(os.path.join(data_dir, "header.txt"), "w") as fh:
            fh.write("|".join(names))
        rng = np.random.default_rng(0)
        n, block = 140_000, 5_000
        pad = "z" * 500
        with open(os.path.join(data_dir, "data.txt"), "w") as fh:
            for start in range(0, n, block):
                x = rng.normal(size=(block, 8))
                y = (1.5 * x[:, 0] - x[:, 1] > 0).astype(int)
                lines = []
                for i in range(block):
                    fields = [str(y[i])] + [f"{v:.5f}" for v in x[i]] + [pad]
                    lines.append("|".join(fields))
                fh.write("\n".join(lines) + "\n")

        with open(os.path.join(root, "meta.names"), "w") as fh:
            fh.write("pad\n")
        mc = new_model_config("BigModel", Algorithm.NN)
        mc.data_set.data_path = os.path.join(data_dir, "data.txt")
        mc.data_set.header_path = os.path.join(data_dir, "header.txt")
        mc.data_set.data_delimiter = "|"
        mc.data_set.header_delimiter = "|"
        mc.data_set.target_column_name = "target"
        mc.data_set.pos_tags = ["1"]
        mc.data_set.neg_tags = ["0"]
        mc.data_set.meta_column_name_file = os.path.join(root, "meta.names")
        mc.train.num_train_epochs = 3
        mc.save(os.path.join(root, "ModelConfig.json"))
        return os.path.join(data_dir, "data.txt")

    def test_pipeline_under_budget(self, tmp_path):
        from shifu_tpu.data.stream import dataset_size_bytes
        from shifu_tpu.processor.init import InitProcessor
        from shifu_tpu.processor.norm import NormProcessor
        from shifu_tpu.processor.stats import StatsProcessor
        from shifu_tpu.processor.train import TrainProcessor
        from shifu_tpu.varsel.selector import select_by_filter

        root = str(tmp_path / "big")
        os.makedirs(root)
        data_path = self._generate_big(root)
        budget = self.BUDGET_MB * 1024 * 1024
        assert dataset_size_bytes(data_path) >= 3.5 * budget

        _set_props(**{
            "shifu.ingest.memoryBudgetMB": str(self.BUDGET_MB),
            "shifu.ingest.chunkRows": "8192",
        })
        # warm jax/pandas before measuring so one-time import/compile
        # allocations don't count against the ingest budget (pandas and
        # pyarrow alone allocate ~20 MB of module/code objects on first
        # import — ingest cost zero of it is recurring)
        import jax.numpy as jnp
        import pandas  # noqa: F401
        import pyarrow  # noqa: F401

        (jnp.zeros((8, 8)) @ jnp.zeros((8, 8))).block_until_ready()

        # no-pipeline CONTROL, measured in this environment: what the
        # ingest would hold resident without the bounded pipeline (the
        # in-RAM read path the budget knob switches away from)
        from shifu_tpu.data.reader import read_columnar, read_header

        names = read_header(os.path.join(root, "data", "header.txt"), "|")
        tracemalloc.start()
        control = read_columnar(data_path, names, delimiter="|")
        _, peak_control = tracemalloc.get_traced_memory()
        tracemalloc.stop()
        del control
        assert peak_control > 2 * budget, (
            "control read too small to calibrate against "
            f"({peak_control/1e6:.1f} MB)")

        tracemalloc.start()
        try:
            assert InitProcessor(root).run() == 0
            assert StatsProcessor(root).run() == 0
            assert NormProcessor(root).run() == 0
            _, peak_ingest = tracemalloc.get_traced_memory()
            assert TrainProcessor(root).run() == 0
            _, peak_total = tracemalloc.get_traced_memory()
        finally:
            tracemalloc.stop()
            _clear_props("shifu.ingest.memoryBudgetMB",
                         "shifu.ingest.chunkRows")

        assert peak_ingest < peak_control / self.CONTROL_RATIO, (
            f"streamed ingest peak {peak_ingest/1e6:.1f} MB is not "
            f"bounded vs the {peak_control/1e6:.1f} MB no-pipeline "
            f"control (ratio gate {self.CONTROL_RATIO}x)"
        )
        # training adds the dense f32 matrix (HBM-resident design) —
        # still far under holding the raw dataset
        assert peak_total < peak_control / 2
        assert os.path.isfile(os.path.join(root, "models", "model0.nn"))


class TestAdvisorFixes:
    """Regression tests for the round-2 advisor findings (ADVICE.md)."""

    def _columnar(self, arrays: dict):
        from shifu_tpu.data.reader import ColumnarData

        names = list(arrays)
        raw = {k: np.array([f"{v:.6f}" for v in vals])
               for k, vals in arrays.items()}
        n = len(next(iter(arrays.values())))
        return ColumnarData(names=names, raw=raw, n_rows=n)

    def test_streaming_correlation_survives_large_means(self):
        """|mean| >> std used to cancel catastrophically in the f32
        un-centered moments, collapsing r to 0 (ADVICE high)."""
        from shifu_tpu.config import ColumnConfig, ColumnType
        from shifu_tpu.stats.correlation import (
            StreamingCorrelation,
            column_correlation,
        )

        rng = np.random.default_rng(3)
        n = 4000
        a = 1e5 + rng.normal(size=n)
        b = 0.5 * (a - 1e5) + rng.normal(size=n)  # true r ~ 0.447
        cols = [
            ColumnConfig(column_num=i, column_name=nm,
                         column_type=ColumnType.N)
            for i, nm in enumerate(["a", "b"])
        ]
        whole = self._columnar({"a": a, "b": b})
        exact, _ = column_correlation(whole, cols)

        sc = StreamingCorrelation()
        for start in range(0, n, 500):
            sc.update(self._columnar(
                {"a": a[start:start + 500], "b": b[start:start + 500]}), cols)
        corr, names = sc.finalize()
        assert names == ["a", "b"]
        assert abs(corr[0, 1]) > 0.3  # not collapsed to zero
        assert corr[0, 1] == pytest.approx(exact[0, 1], abs=0.01)

    def test_header_filter_full_row_only_and_before_max_rows(self, tmp_path):
        """A data row whose FIRST field equals the first column name must
        survive; a full header row must not consume max_rows budget."""
        from shifu_tpu.data.stream import iter_columnar_chunks

        p = str(tmp_path / "d.csv")
        names = ["a", "b"]
        with open(p, "w") as fh:
            fh.write("a|b\n")        # stray header (dropped, costs no budget)
            fh.write("a|1\n")        # legit row: first field happens to be 'a'
            fh.write("x|2\n")
            fh.write("y|3\n")
        chunks = list(iter_columnar_chunks(p, names, max_rows=3))
        got = np.concatenate([c.column("a") for c in chunks])
        assert list(got) == ["a", "x", "y"]

    def test_categorical_sketch_space_saving_reentry(self):
        """An evicted value that re-enters carries the error floor instead
        of restarting from zero, and evicted mass is tracked."""
        from shifu_tpu.stats.sketch import CategoricalSketch

        sk = CategoricalSketch(working_cap=3)
        no_miss = lambda n: np.zeros(n, dtype=bool)
        sk.update(np.array(["a"] * 10 + ["b"] * 8 + ["c"] * 6 + ["d"] * 2),
                  no_miss(26))
        assert sk.saturated and sk.error_bound >= 2.0
        assert sk.evicted_mass >= 2.0
        # 'd' re-enters: admitted with +error_bound, never undercounted below
        # its new observations
        sk.update(np.array(["d"] * 5), no_miss(5))
        assert sk.counts["d"] >= 5 + 2

    def test_hll_bit_length_exact_at_power_of_two_boundaries(self):
        """frexp-based bit length is exact where floor(log2) rounds up."""
        from shifu_tpu.stats.sketch import DistinctSketch

        sk = DistinctSketch(exact_limit=0)
        sk.exact = None
        # w = 2^40 - 1 has bit_length 40; naive floor(log2(float(w)))+1
        # yields 41 because float64 rounds w up to exactly 2^40
        h = np.array([((2**40 - 1) << 12) | 5], dtype=np.uint64)
        sk.update_hashes(h)
        # rho = (64-12) - 40 + 1 = 13
        assert int(sk.registers[5]) == 13

    def test_shuffle_shard_writer_global_permutation(self, tmp_path):
        """External shuffle: all rows preserved, two lockstep writers stay
        row-aligned, and a sorted input is decorrelated within shards."""
        from shifu_tpu.norm.dataset import ShuffleShardWriter, load_normalized

        n, k = 2000, 4
        vals = np.arange(n, dtype=np.float32)[:, None]
        tags = (np.arange(n) >= n // 2).astype(np.int8)  # label-sorted input
        wts = np.arange(n, dtype=np.float32)
        d1, d2 = str(tmp_path / "w1"), str(tmp_path / "w2")
        w1 = ShuffleShardWriter(d1, "features", np.float32, ["v"], "ZSCALE",
                                n_buckets=k, seed=11)
        w2 = ShuffleShardWriter(d2, "features", np.float32, ["v"], "ZSCALE",
                                n_buckets=k, seed=11)
        for start in range(0, n, 300):
            sl = slice(start, start + 300)
            w1.add(vals[sl], tags[sl], wts[sl])
            w2.add(vals[sl] * 10, tags[sl], wts[sl])
        m1 = w1.close()
        m2 = w2.close()
        _, f1, t1, g1 = load_normalized(d1)
        _, f2, t2, g2 = load_normalized(d2)
        # every row present exactly once
        assert sorted(np.asarray(f1)[:, 0].tolist()) == list(range(n))
        # lockstep writers row-aligned
        np.testing.assert_allclose(np.asarray(f2), np.asarray(f1) * 10)
        np.testing.assert_array_equal(np.asarray(t2), np.asarray(t1))
        # label-sorted input decorrelated: first-half of output not all-0
        half = np.asarray(t1)[: n // 2]
        assert 0.3 < half.mean() < 0.7
        assert m1.shard_rows == m2.shard_rows and len(m1.shard_rows) == k

    def test_streaming_norm_shuffle_is_permutation(self, tmp_path):
        from shifu_tpu.norm.dataset import load_codes, load_normalized
        from shifu_tpu.processor.init import InitProcessor
        from shifu_tpu.processor.norm import NormProcessor
        from shifu_tpu.processor.stats import StatsProcessor

        root = str(tmp_path / "ms")
        make_model_set(root, n_rows=1200)
        assert InitProcessor(root).run() == 0
        assert StatsProcessor(root).run() == 0
        assert NormProcessor(root).run() == 0
        _, f_plain, t_plain, _ = load_normalized(
            os.path.join(root, "tmp", "norm", "NormalizedData"))
        f_plain = np.asarray(f_plain).copy()
        t_plain = np.asarray(t_plain).copy()

        _set_props(**{"shifu.ingest.forceStreaming": "true",
                      "shifu.ingest.chunkRows": "256"})
        try:
            assert NormProcessor(root, shuffle=True).run() == 0
        finally:
            _clear_props("shifu.ingest.forceStreaming",
                         "shifu.ingest.chunkRows")
        _, f_sh, t_sh, _ = load_normalized(
            os.path.join(root, "tmp", "norm", "NormalizedData"))
        _, c_sh, t_codes, _ = load_codes(
            os.path.join(root, "tmp", "norm", "CleanedData"))
        f_sh, t_sh = np.asarray(f_sh), np.asarray(t_sh)

        # same multiset of rows, different order
        key = lambda f, t: sorted(
            map(tuple, np.column_stack([f.round(5), t]).tolist()))
        assert key(f_sh, t_sh) == key(f_plain, t_plain)
        assert not np.array_equal(f_sh, f_plain)
        # features and codes artifacts row-aligned (same tag sequence)
        np.testing.assert_array_equal(t_sh, np.asarray(t_codes))
