"""Observability subsystem: registry, exporters, tracing, run ledger, CLI.

Covers the PR-2 acceptance contract: a deterministic manifest schema check,
Prometheus/JSON exporters round-tripping the same registry state, the
BasicProcessor.run() wrapper (profiler dir under -Dshifu.profile, manifest on
success AND failure, sequence numbering), and the end-to-end
stats -> norm -> train ledger over the synthetic fixture.
"""

import json
import logging
import os
import threading

import numpy as np
import pytest

from tests.helpers import make_model_set


# ---------------------------------------------------------------------------
# metrics registry
# ---------------------------------------------------------------------------


def _populated_registry():
    from shifu_tpu.obs import MetricsRegistry

    reg = MetricsRegistry()
    reg.counter("stats.rows_valid").inc(600)
    reg.counter("eval.records", eval="EvalA").inc(100)
    reg.gauge("eval.auc", eval="EvalA").set(0.97)
    reg.timer("stats.stage", stage="parse").add(1.25, 12)
    reg.timer("stats.stage", stage="device").add(0.5, 12)
    h = reg.histogram("chunk.seconds")
    h.observe(0.3)
    h.observe(2.0)
    s = reg.series("train.train_error", trainer=0)
    s.append(1, 0.25)
    s.append(2, 0.20)
    return reg


class TestMetricsRegistry:
    def test_kinds_and_labels(self):
        reg = _populated_registry()
        assert reg.counter("stats.rows_valid").value == 600
        # same name, different labels = different metric
        assert reg.counter("eval.records", eval="EvalA").value == 100
        assert reg.counter("eval.records", eval="EvalB").value == 0
        assert reg.timer("stats.stage", stage="parse").calls == 12
        assert reg.series("train.train_error", trainer=0).last == 0.20
        snap = reg.snapshot()
        assert snap["counters"]['eval.records{eval="EvalA"}'] == 100
        assert snap["timers"]['stats.stage{stage="parse"}']["seconds"] == 1.25
        assert snap["series"]['train.train_error{trainer="0"}'] == [
            [1.0, 0.25], [2.0, 0.20]]
        assert snap["histograms"]["chunk.seconds"]["count"] == 2

    def test_json_round_trip(self):
        from shifu_tpu.obs import MetricsRegistry

        reg = _populated_registry()
        text = reg.to_json()
        clone = MetricsRegistry.from_json(text)
        assert clone.to_json() == text
        assert clone.snapshot() == reg.snapshot()

    def test_prometheus_round_trip(self):
        """The text exporter's samples parse back to exactly flatten() —
        the same registry state through both exporters."""
        from shifu_tpu.obs import MetricsRegistry, parse_prometheus

        reg = _populated_registry()
        text = reg.to_prometheus()
        assert parse_prometheus(text) == reg.flatten()
        # and the JSON round-tripped clone flattens identically
        clone = MetricsRegistry.from_json(reg.to_json())
        assert clone.flatten() == reg.flatten()
        # spot-check naming conventions
        flat = reg.flatten()
        assert flat["stats_rows_valid_total"] == 600
        assert flat['stats_stage_seconds_total{stage="parse"}'] == 1.25
        assert flat['train_train_error_last{trainer="0"}'] == 0.20

    def test_label_value_escaping_round_trips(self):
        """Label values come from user config (eval-set names) — quotes and
        backslashes must survive both exporters."""
        from shifu_tpu.obs import MetricsRegistry, parse_prometheus

        reg = MetricsRegistry()
        nasty = 'A"B\\C'
        reg.counter("eval.records", eval=nasty).inc(7)
        clone = MetricsRegistry.from_json(reg.to_json())
        assert clone.to_json() == reg.to_json()
        assert clone.counter("eval.records", eval=nasty).value == 7
        prom = reg.to_prometheus()
        assert parse_prometheus(prom) == reg.flatten()
        assert '\\"' in prom and "\\\\" in prom  # escaped on the wire

    def test_thread_safety(self):
        from shifu_tpu.obs import MetricsRegistry

        reg = MetricsRegistry()

        def work():
            for _ in range(1000):
                reg.counter("n").inc()
                reg.timer("t").add(0.001)

        threads = [threading.Thread(target=work) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert reg.counter("n").value == 4000
        assert reg.timer("t").calls == 4000

    def test_stage_timers_compat_and_registry_backing(self):
        from shifu_tpu.obs import MetricsRegistry
        from shifu_tpu.utils.timing import StageTimers

        # bare: self-contained, PR-1 API intact
        st = StageTimers()
        with st.timer("parse"):
            pass
        st.add("device", 0.5, 2)
        assert st.calls("parse") == 1
        assert st.seconds("device") == 0.5
        assert "device 0.50s/2" in st.summary()
        assert st.as_dict()["device"]["calls"] == 2
        # registry-backed: stages are registry timers -> manifest-visible
        reg = MetricsRegistry()
        rt = reg.stage_timers("norm.stage")
        rt.add("write", 0.25)
        assert reg.timer("norm.stage", stage="write").seconds == 0.25
        assert 'norm.stage{stage="write"}' in reg.snapshot()["timers"]


# ---------------------------------------------------------------------------
# span tracing
# ---------------------------------------------------------------------------


class TestTracing:
    def test_nested_spans_chrome_trace(self):
        from shifu_tpu.obs.tracing import Tracer

        tr = Tracer()
        with tr.span("step.stats", seq=1) as attrs:
            with tr.span("stats.pass1"):
                pass
            attrs["rows"] = 300
        events = tr.to_chrome_trace()["traceEvents"]
        assert [e["name"] for e in events] == ["step.stats", "stats.pass1"]
        outer = next(e for e in events if e["name"] == "step.stats")
        inner = next(e for e in events if e["name"] == "stats.pass1")
        for e in events:
            assert e["ph"] == "X" and e["pid"] == os.getpid()
        # containment: inner starts after and ends before outer
        assert outer["ts"] <= inner["ts"]
        assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"] + 1e-3
        assert outer["args"] == {"seq": 1, "rows": 300}
        assert inner["args"]["parent"] == "step.stats"

    def test_save_and_span_seconds(self, tmp_path):
        from shifu_tpu.obs.tracing import Tracer

        tr = Tracer()
        assert tr.save(str(tmp_path / "x" / "t.json")) is None  # no spans
        with tr.span("a"):
            pass
        path = tr.save(str(tmp_path / "x" / "t.json"))
        assert path and os.path.isfile(path)
        doc = json.load(open(path))
        assert doc["traceEvents"][0]["name"] == "a"
        assert tr.span_seconds("a") >= 0.0
        assert tr.span_seconds("missing") == 0.0


# ---------------------------------------------------------------------------
# run wrapper + ledger
# ---------------------------------------------------------------------------


def _dummy_processor(root, step="teststep", fail=False):
    from shifu_tpu.processor.basic import BasicProcessor

    class Dummy(BasicProcessor):
        pass

    Dummy.step = step

    class Ok(Dummy):
        def run_step(self):
            from shifu_tpu.obs import registry

            registry().counter(f"{step}.rows").inc(42)

    class Boom(Dummy):
        def run_step(self):
            raise RuntimeError("step exploded")

    return (Boom if fail else Ok)(root)


class TestRunWrapperAndLedger:
    def test_manifest_on_success_and_sequence_numbering(self, tmp_path):
        root = str(tmp_path)
        assert _dummy_processor(root).run() == 0
        assert _dummy_processor(root).run() == 0
        runs = os.path.join(root, ".shifu", "runs")
        names = sorted(os.listdir(runs))
        assert "teststep-1.json" in names and "teststep-2.json" in names
        m = json.load(open(os.path.join(runs, "teststep-2.json")))
        assert m["schema"] == "shifu.run/1"
        assert m["step"] == "teststep" and m["seq"] == 2
        assert m["status"] == "ok" and m["exitStatus"] == 0
        assert m["error"] is None
        assert isinstance(m["argv"], list)
        assert m["elapsedSeconds"] >= 0
        assert m["metrics"]["counters"]["teststep.rows"] == 42
        # registry reset between runs: seq-2 counter is 42, not 84
        m1 = json.load(open(os.path.join(runs, "teststep-1.json")))
        assert m1["metrics"]["counters"]["teststep.rows"] == 42
        # root span recorded into the chrome trace beside the manifest
        assert m["tracePath"]
        trace = json.load(open(os.path.join(root, m["tracePath"])))
        assert any(e["name"] == "step.teststep"
                   for e in trace["traceEvents"])
        # jax info present (cpu under the test harness)
        assert m["jax"].get("backend") == "cpu"

    def test_manifest_on_failure_reraises(self, tmp_path):
        root = str(tmp_path)
        proc = _dummy_processor(root, fail=True)
        with pytest.raises(RuntimeError, match="step exploded"):
            proc.run()
        m = json.load(open(os.path.join(
            root, ".shifu", "runs", "teststep-1.json")))
        assert m["status"] == "failed" and m["exitStatus"] == 1
        assert m["error"] == "RuntimeError: step exploded"

    def test_profiler_dir_created_under_shifu_profile(self, tmp_path):
        from shifu_tpu.utils import environment

        root = str(tmp_path)
        environment.set_property("shifu.profile", "prof")
        try:
            assert _dummy_processor(root, step="profstep").run() == 0
        finally:
            environment.set_property("shifu.profile", "")
        prof_dir = os.path.join(root, "prof", "profstep")
        assert os.path.isdir(prof_dir)
        m = json.load(open(os.path.join(
            root, ".shifu", "runs", "profstep-1.json")))
        assert m["profileDir"] == prof_dir

    def test_list_and_format_runs(self, tmp_path):
        from shifu_tpu.obs.ledger import format_runs, list_runs

        root = str(tmp_path)
        assert format_runs(list_runs(root)) == \
            "(no runs recorded under .shifu/runs)"
        _dummy_processor(root, step="stats").run()
        _dummy_processor(root, step="norm").run()
        _dummy_processor(root, step="stats").run()
        all_runs = list_runs(root)
        assert len(all_runs) == 3
        # newest first
        assert (all_runs[0]["startedAtUnix"]
                >= all_runs[-1]["startedAtUnix"])
        assert len(list_runs(root, last=2)) == 2
        stats_only = list_runs(root, step="stats")
        assert {m["step"] for m in stats_only} == {"stats"}
        assert sorted(m["seq"] for m in stats_only) == [1, 2]
        table = format_runs(all_runs)
        assert "STEP" in table and "stats" in table and "norm" in table

    def test_runs_cli(self, tmp_path, monkeypatch, capsys):
        from shifu_tpu import cli

        root = str(tmp_path)
        _dummy_processor(root, step="stats").run()
        _dummy_processor(root, step="norm").run()
        monkeypatch.chdir(root)
        assert cli.main(["runs", "--last", "1"]) == 0
        out = capsys.readouterr().out
        assert "norm" in out and "stats" not in out.replace("STEP", "")
        assert cli.main(["runs", "--step", "stats", "--json"]) == 0
        doc = json.loads(capsys.readouterr().out)
        assert len(doc) == 1 and doc[0]["step"] == "stats"


# ---------------------------------------------------------------------------
# end-to-end ledger over the fixture (acceptance criterion)
# ---------------------------------------------------------------------------


class TestLifecycleLedger:
    @pytest.fixture()
    def model_root(self, tmp_path):
        root = make_model_set(str(tmp_path / "ModelSet"), n_rows=300)
        mc_path = os.path.join(root, "ModelConfig.json")
        mc = json.load(open(mc_path))
        mc["train"]["numTrainEpochs"] = 15
        json.dump(mc, open(mc_path, "w"), indent=2)
        return root

    def test_stats_norm_train_manifests(self, model_root):
        from shifu_tpu.processor.init import InitProcessor
        from shifu_tpu.processor.norm import NormProcessor
        from shifu_tpu.processor.stats import StatsProcessor
        from shifu_tpu.processor.train import TrainProcessor

        assert InitProcessor(model_root).run() == 0
        assert StatsProcessor(model_root).run() == 0
        assert NormProcessor(model_root).run() == 0
        assert TrainProcessor(model_root).run() == 0

        runs = os.path.join(model_root, ".shifu", "runs")
        for step in ("init", "stats", "norm", "train"):
            assert os.path.isfile(os.path.join(runs, f"{step}-1.json")), step

        stats = json.load(open(os.path.join(runs, "stats-1.json")))
        counters = stats["metrics"]["counters"]
        assert counters["stats.rows_valid"] == 300
        assert counters["stats.rows_pos"] + counters["stats.rows_neg"] == 300
        # stage timers routed through the registry into the manifest
        timers = stats["metrics"]["timers"]
        assert any(k.startswith("stats.stage{") for k in timers), timers
        assert stats["configHashes"]["ModelConfig.json"]
        # NOTE: no jax.compiles floor here — in a warm process the step can
        # ride the process-global jit cache (zero fresh compiles is the
        # desired steady state); TestJaxProbes pins the counter itself

        norm = json.load(open(os.path.join(runs, "norm-1.json")))
        assert norm["metrics"]["counters"]["norm.rows"] == 300
        assert any(k.startswith("norm.stage{")
                   for k in norm["metrics"]["timers"])

        train = json.load(open(os.path.join(runs, "train-1.json")))
        series = train["metrics"]["series"]
        # per-epoch training series, non-empty
        curve = series.get('train.valid_error{trainer="0"}')
        assert curve and len(curve) >= 1
        assert train["metrics"]["gauges"]["train.valid_error"] < 0.5
        assert train["metrics"]["counters"]["train.iterations"] >= 1

        # `shifu runs --last 3` renders them
        from shifu_tpu.obs.ledger import format_runs, list_runs

        table = format_runs(list_runs(model_root, last=3))
        assert "train" in table and "norm" in table and "stats" in table


# ---------------------------------------------------------------------------
# jax compile probes
# ---------------------------------------------------------------------------


class TestJaxProbes:
    def test_compile_counter_increments_on_fresh_compile(self):
        import jax
        import jax.numpy as jnp

        from shifu_tpu import obs

        assert obs.install_jax_probes()
        obs.reset()

        @jax.jit  # fresh function object -> guaranteed cache miss
        def f(x):
            return x * 3 + 1

        f(jnp.ones(17)).block_until_ready()
        reg = obs.registry()
        assert reg.counter("jax.compiles").value >= 1
        assert reg.timer("jax.compile").seconds > 0
        before = reg.counter("jax.compiles").value
        f(jnp.ones(17)).block_until_ready()  # cache hit: no new compile
        assert reg.counter("jax.compiles").value == before


# ---------------------------------------------------------------------------
# satellite: idempotent logging configure
# ---------------------------------------------------------------------------


class TestConfigureLogging:
    def test_repeated_configure_is_effective(self):
        from shifu_tpu.utils.log import configure

        root = logging.getLogger()
        old_handlers = list(root.handlers)
        old_level = root.level
        old_jax = logging.getLogger("jax").level
        try:
            configure(verbose=False)
            assert root.level == logging.INFO
            assert logging.getLogger("jax").level == logging.WARNING
            # the bug: basicConfig silently no-ops once handlers exist —
            # a later -v must still take effect
            configure(verbose=True)
            assert root.level == logging.DEBUG
            assert logging.getLogger("jax").level == logging.NOTSET
            configure(verbose=False)
            assert root.level == logging.INFO
            # force=True replaces rather than stacks handlers
            assert len(root.handlers) == 1
        finally:
            root.handlers[:] = old_handlers
            root.setLevel(old_level)
            logging.getLogger("jax").setLevel(old_jax)
