"""Multi-class classification (NATIVE + ONEVSALL) end to end.

Parity anchors: ModelTrainConf.MultipleClassification (ModelTrainConf.java:54),
NNWorker one-hot/per-trainer ideals (NNWorker.java:116-131), ONEVSALL bagging
fan-out (TrainModelProcessor.java:685-699), multi-class confusion matrix
(ConfusionMatrix.java:625), MultiClsTagPredictor argmax/threshold semantics.
"""

import os

import numpy as np
import pytest

from tests.helpers import make_multiclass_model_set

CLASSES = ("low", "mid", "high")


def _run_pipeline(root):
    from shifu_tpu.processor.init import InitProcessor
    from shifu_tpu.processor.norm import NormProcessor
    from shifu_tpu.processor.stats import StatsProcessor
    from shifu_tpu.processor.train import TrainProcessor

    assert InitProcessor(root).run() == 0
    assert StatsProcessor(root).run() == 0
    assert NormProcessor(root).run() == 0
    assert TrainProcessor(root).run() == 0


def _run_eval(root):
    from shifu_tpu.processor.evaluate import EvalProcessor

    assert EvalProcessor(root, run_name="Eval1").run() == 0
    cm_path = os.path.join(root, "evals", "Eval1", "EvalConfusionMatrix.csv")
    # pathfinder layout may differ; find it
    if not os.path.isfile(cm_path):
        import glob

        hits = glob.glob(os.path.join(root, "**", "*onfusion*"),
                         recursive=True)
        assert hits, "no confusion matrix artifact written"
        cm_path = hits[0]
    return cm_path


def _accuracy_from_perf(root):
    import glob
    import json

    hits = glob.glob(os.path.join(root, "**", "*erformance*.json"),
                     recursive=True)
    assert hits
    with open(hits[0]) as fh:
        perf = json.load(fh)
    assert "confusionMatrix" in perf
    m = np.asarray(perf["confusionMatrix"])
    assert m.shape == (3, 3)
    return perf["accuracy"], m


# ---------------------------------------------------------------------------
# unit: tag parsing + prediction semantics
# ---------------------------------------------------------------------------


def test_make_class_tags():
    from shifu_tpu.data.reader import make_class_tags

    col = np.array(["low", "high", "mid", "junk", " low "], dtype=object)
    t = make_class_tags(col, list(CLASSES))
    assert t.tolist() == [0, 2, 1, -1, 0]


def test_make_tags_for_dispatch():
    from shifu_tpu.config.model_config import ModelConfig
    from shifu_tpu.data.reader import make_tags_for

    mc = ModelConfig()
    mc.data_set.pos_tags = list(CLASSES)
    mc.data_set.neg_tags = []
    col = np.array(["mid", "low", "nope"], dtype=object)
    assert make_tags_for(mc, col).tolist() == [1, 0, -1]

    mc.data_set.pos_tags = ["M"]
    mc.data_set.neg_tags = ["B"]
    col = np.array(["M", "B", "x"], dtype=object)
    assert make_tags_for(mc, col).tolist() == [1, 0, -1]


def test_predict_one_vs_all_threshold_semantics():
    """ConfusionMatrix.java:708-744: positive iff score > (1-prior)*scale;
    among positives the LARGEST-prior class wins; none positive -> the
    largest-prior class overall."""
    from shifu_tpu.eval.multiclass import predict_one_vs_all

    priors = np.array([0.5, 0.3, 0.2])
    # thresholds: 500, 700, 800
    scores = np.array([
        [600.0, 100.0, 100.0],   # only class 0 positive -> 0
        [100.0, 750.0, 900.0],   # classes 1,2 positive -> class 1 (prior .3)
        [100.0, 100.0, 100.0],   # none positive -> class 0 (max prior)
        [900.0, 900.0, 900.0],   # all positive -> class 0
    ])
    pred = predict_one_vs_all(scores, priors, scale=1000.0)
    assert pred.tolist() == [0, 1, 0, 0]


def test_predict_native_model_major_blocks():
    from shifu_tpu.eval.multiclass import predict_native

    # two models x three classes, model-major: model0 votes class 2,
    # model1 votes class 2 stronger -> average argmax = 2
    scores = np.array([[0.1, 0.2, 0.7, 0.0, 0.3, 0.9]]) * 1000
    assert predict_native(scores, 3).tolist() == [2]
    with pytest.raises(ValueError):
        predict_native(np.zeros((1, 5)), 3)


def test_confusion_matrix_multi_and_text():
    from shifu_tpu.eval.multiclass import (
        confusion_matrix_multi,
        confusion_matrix_text,
        multiclass_accuracy,
    )

    tags = np.array([0, 0, 1, 2, 2, -1])
    pred = np.array([0, 1, 1, 2, 0, 0])
    m = confusion_matrix_multi(tags, pred, 3)
    assert m.tolist() == [[1, 1, 0], [0, 1, 0], [1, 0, 1]]
    text = confusion_matrix_text(m, CLASSES)
    assert text.splitlines()[0] == "\tlow\tmid\thigh"
    assert abs(multiclass_accuracy(m) - 3 / 5) < 1e-12


# ---------------------------------------------------------------------------
# end to end: NATIVE NN
# ---------------------------------------------------------------------------


def test_native_nn_multiclass_end_to_end(tmp_path):
    root = str(tmp_path / "ms")
    make_multiclass_model_set(root, n_rows=700, method="NATIVE")
    from shifu_tpu.config.model_config import ModelConfig

    mc = ModelConfig.load(os.path.join(root, "ModelConfig.json"))
    mc.train.num_train_epochs = 60
    mc.save(os.path.join(root, "ModelConfig.json"))
    _run_pipeline(root)

    from shifu_tpu.models.nn import IndependentNNModel, NNModelSpec

    spec = NNModelSpec.load(os.path.join(root, "models", "model0.nn"))
    assert spec.out_dim == 3  # K sigmoid outputs, NNWorker.java:131
    assert spec.class_tags == list(CLASSES)

    from shifu_tpu.norm.dataset import load_normalized, read_meta

    norm_dir = os.path.join(root, "tmp", "norm", "NormalizedData")
    meta = read_meta(norm_dir)
    assert meta.extra.get("classTags") == list(CLASSES)
    priors = meta.extra.get("classPriors")
    assert priors and abs(sum(priors) - 1.0) < 1e-9

    _, feats, tags, _ = load_normalized(norm_dir)
    out = IndependentNNModel(spec).compute_all(np.asarray(feats))
    assert out.shape[1] == 3
    acc = float((np.argmax(out, axis=1) == np.asarray(tags)).mean())
    assert acc > 0.8, f"NATIVE multi-class accuracy {acc}"

    _run_eval(root)
    eval_acc, m = _accuracy_from_perf(root)
    assert eval_acc > 0.8
    assert m.sum() == 700


# ---------------------------------------------------------------------------
# end to end: ONEVSALL (NN + GBT)
# ---------------------------------------------------------------------------


def test_onevsall_nn_multiclass(tmp_path):
    root = str(tmp_path / "ms")
    make_multiclass_model_set(root, n_rows=700, method="ONEVSALL")
    from shifu_tpu.config.model_config import ModelConfig

    mc = ModelConfig.load(os.path.join(root, "ModelConfig.json"))
    assert mc.train.is_one_vs_all()
    mc.train.num_train_epochs = 60
    mc.save(os.path.join(root, "ModelConfig.json"))
    _run_pipeline(root)

    # one binary model per class (TrainModelProcessor.java:693)
    from shifu_tpu.models.nn import NNModelSpec

    for k in range(3):
        spec = NNModelSpec.load(os.path.join(root, "models", f"model{k}.nn"))
        assert spec.out_dim == 1
        assert spec.class_tags == list(CLASSES)

    _run_eval(root)
    eval_acc, _ = _accuracy_from_perf(root)
    assert eval_acc > 0.75, f"ONEVSALL accuracy {eval_acc}"


def test_onevsall_gbt_multiclass(tmp_path):
    root = str(tmp_path / "ms")
    make_multiclass_model_set(root, n_rows=600, method="ONEVSALL",
                              algorithm="GBT")
    from shifu_tpu.config.model_config import ModelConfig

    mc = ModelConfig.load(os.path.join(root, "ModelConfig.json"))
    mc.train.params["TreeNum"] = 20
    mc.train.params["MaxDepth"] = 4
    mc.save(os.path.join(root, "ModelConfig.json"))
    _run_pipeline(root)

    from shifu_tpu.models.tree import TreeModelSpec

    for k in range(3):
        path = os.path.join(root, "models", f"model{k}.gbt")
        assert os.path.isfile(path)
        spec = TreeModelSpec.load(path)
        assert len(spec.trees) == 20

    _run_eval(root)
    eval_acc, _ = _accuracy_from_perf(root)
    assert eval_acc > 0.7, f"ONEVSALL GBT accuracy {eval_acc}"


def test_native_tree_multiclass_rejected(tmp_path):
    root = str(tmp_path / "ms")
    make_multiclass_model_set(root, n_rows=200, method="NATIVE",
                              algorithm="GBT")
    from shifu_tpu.processor.init import InitProcessor
    from shifu_tpu.processor.norm import NormProcessor
    from shifu_tpu.processor.stats import StatsProcessor
    from shifu_tpu.processor.train import TrainProcessor

    assert InitProcessor(root).run() == 0
    assert StatsProcessor(root).run() == 0
    assert NormProcessor(root).run() == 0
    from shifu_tpu.utils.errors import ShifuError

    with pytest.raises(ShifuError):  # clear error, not a silently-bad model
        TrainProcessor(root).run()


# ---------------------------------------------------------------------------
# NATIVE RF multi-class (per-class histograms, majority-vote leaves)
# ---------------------------------------------------------------------------


def test_rf_native_multiclass_trainer():
    """RF classification: entropy gain over K class-count planes, leaf =
    majority class, model emits per-class vote fractions
    (dt/Impurity.java:368, ConfusionMatrix.java:683)."""
    from shifu_tpu.train.tree_trainer import TreeTrainConfig, train_trees

    rng = np.random.default_rng(7)
    n, F, bins, K = 1500, 6, 8, 3
    codes = rng.integers(0, bins, size=(n, F)).astype(np.int32)
    # class determined by two features with noise
    y = ((codes[:, 0] >= 5).astype(int) + (codes[:, 1] >= 4).astype(int))
    flip = rng.random(n) < 0.05
    y = np.where(flip, rng.integers(0, K, size=n), y).astype(np.float32)
    w = np.ones(n, np.float32)
    cfg = TreeTrainConfig(algorithm="RF", tree_num=10, max_depth=5,
                          impurity="entropy", n_classes=K,
                          feature_subset_strategy="TWOTHIRDS", seed=5,
                          min_instances_per_node=2)
    res = train_trees(codes, y, w, [bins] * F, [False] * F,
                      [f"c{i}" for i in range(F)], cfg)
    assert res.spec.n_classes == K
    # leaf values are class indices
    for t in res.spec.trees:
        vals = t.leaf_value[t.feature == -1]
        assert np.allclose(vals, np.round(vals))
        assert vals.min() >= 0 and vals.max() <= K - 1
    # valid error is a misclassification rate, and the forest learns
    assert 0.0 <= res.valid_error <= 1.0
    assert res.valid_error < 0.2, res.valid_error

    votes = res.spec.independent().compute(codes)
    assert votes.shape == (n, K)
    np.testing.assert_allclose(votes.sum(1), 1.0, atol=1e-5)
    acc = float((np.argmax(votes, 1) == y).mean())
    assert acc > 0.85, acc


def test_rf_native_multiclass_end_to_end(tmp_path):
    root = str(tmp_path / "ms")
    make_multiclass_model_set(root, n_rows=700, method="NATIVE",
                              algorithm="RF")
    from shifu_tpu.config.model_config import ModelConfig

    mc = ModelConfig.load(os.path.join(root, "ModelConfig.json"))
    mc.train.params.update({"TreeNum": 10, "MaxDepth": 5,
                            "Impurity": "entropy",
                            "MinInstancesPerNode": 2})
    mc.save(os.path.join(root, "ModelConfig.json"))
    _run_pipeline(root)

    from shifu_tpu.models.tree import TreeModelSpec

    spec = TreeModelSpec.load(os.path.join(root, "models", "model0.rf"))
    assert spec.n_classes == 3

    _run_eval(root)
    eval_acc, m = _accuracy_from_perf(root)
    assert eval_acc > 0.75, eval_acc
    assert m.sum() == 700


def test_multiclass_confusion_streams_past_budget(tmp_path):
    """The K x K confusion accumulates in score-file chunks past the
    ingest memory budget, matching the in-memory matrix exactly."""
    import glob
    import json

    root = str(tmp_path / "ms")
    make_multiclass_model_set(root, n_rows=500, method="ONEVSALL")
    from shifu_tpu.config.model_config import ModelConfig
    from shifu_tpu.utils import environment

    mc = ModelConfig.load(os.path.join(root, "ModelConfig.json"))
    mc.train.num_train_epochs = 40
    mc.save(os.path.join(root, "ModelConfig.json"))
    _run_pipeline(root)
    _run_eval(root)
    perf_file = glob.glob(os.path.join(root, "**", "EvalPerformance.json"),
                          recursive=True)[0]
    with open(perf_file) as fh:
        in_memory = json.load(fh)

    from shifu_tpu.processor.evaluate import EvalProcessor

    environment.set_property("shifu.ingest.memoryBudgetMB", "0")
    environment.set_property("shifu.ingest.chunkRows", "64")
    try:
        assert EvalProcessor(root, confmat_name="Eval1").run() == 0
    finally:
        environment.set_property("shifu.ingest.memoryBudgetMB", "512")
        environment.set_property("shifu.ingest.chunkRows", str(65536))
    with open(perf_file) as fh:
        streamed = json.load(fh)
    assert streamed["confusionMatrix"] == in_memory["confusionMatrix"]
    assert streamed["accuracy"] == in_memory["accuracy"]


def test_onevsall_grid_search(tmp_path):
    """Grid x ONEVSALL fans out instead of erroring: each trial trains all
    K per-class members as one vmapped program, best params win
    (TrainModelProcessor.java:684-945 runs grid x class Guagua jobs)."""
    import json

    root = str(tmp_path / "ms")
    make_multiclass_model_set(root, n_rows=500, method="ONEVSALL")
    from shifu_tpu.config.model_config import ModelConfig

    mc = ModelConfig.load(os.path.join(root, "ModelConfig.json"))
    mc.train.num_train_epochs = 20
    mc.train.params["LearningRate"] = [0.05, 0.2]
    mc.save(os.path.join(root, "ModelConfig.json"))
    _run_pipeline(root)
    models = [f for f in os.listdir(os.path.join(root, "models"))
              if f.endswith(".nn")]
    assert len(models) == len(CLASSES)  # one binary model per class
